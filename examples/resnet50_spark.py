"""Stretch config: ResNet-50 (Keras Applications) through ``SparkModel``.

BASELINE.md config 5's stretch goal. Uses ``weights=None`` (no download) on
CIFAR-sized synthetic images; the conv stack compiles onto the MXU. On CPU
this compiles slowly — it exists to demonstrate that an arbitrary
Keras-Applications model trains through the mesh engine unchanged.

Size via env: RESNET_SAMPLES (default 256), RESNET_EPOCHS (default 1).
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import keras
import numpy as np

from elephas_tpu import SparkModel
from elephas_tpu.data import SparkContext
from elephas_tpu.utils import to_simple_rdd


def main():
    import jax

    n = int(os.environ.get("RESNET_SAMPLES", 256))
    epochs = int(os.environ.get("RESNET_EPOCHS", 1))
    n_workers = jax.local_device_count()

    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=(n, 32, 32, 3)).astype("float32")
    y = np.eye(10, dtype="float32")[rng.integers(0, 10, size=n)]

    model = keras.applications.ResNet50(
        weights=None, input_shape=(32, 32, 3), classes=10
    )
    model.compile(optimizer="sgd", loss="categorical_crossentropy",
                  metrics=["accuracy"])

    sc = SparkContext(master=f"local[{n_workers}]", appName="resnet50")
    rdd = to_simple_rdd(sc, x, y)
    # remat: recompute activations in the backward pass — ResNet-class
    # activation footprints don't otherwise fit next to replica stacks in HBM.
    spark_model = SparkModel(
        model, mode="synchronous", num_workers=n_workers, remat=True
    )
    spark_model.fit(rdd, epochs=epochs, batch_size=16, verbose=1,
                    validation_split=0.0)
    h = spark_model.training_histories[-1]
    # (that remat actually reaches the compiled program is pinned by
    # tests/models/test_adapters.py::test_remat_flag_reaches_the_compiled_program)
    print(f"ResNet-50 trained {epochs} epoch(s) with remat=True; "
          f"final loss {h['loss'][-1]:.4f}")
    sc.stop()


if __name__ == "__main__":
    main()
