"""Config 5: distributed hyperparameter search with ``HyperParamModel``.

The reference's ``examples/hyperparam_optimization.py`` equivalent: hyperas
``{{choice(...)}}`` template markers in the model source, fanned out over
workers, best model reconstructed on the driver.
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from elephas_tpu import HyperParamModel
from elephas_tpu.data import SparkContext
from elephas_tpu.hyperparam import STATUS_OK, choice, uniform

from _datasets import load_mnist  # noqa: E402


def data():
    (x_train, y_train), (x_test, y_test) = load_mnist(n_train=4096, n_test=1024)
    return x_train, y_train, x_test, y_test


def model(x_train, y_train, x_test, y_test):
    import keras

    m = keras.Sequential(
        [
            keras.layers.Dense({{choice([64, 128, 256])}}, activation="relu"),
            keras.layers.Dropout({{uniform(0.0, 0.5)}}),
            keras.layers.Dense(10, activation="softmax"),
        ]
    )
    m.build((None, 784))
    m.compile(optimizer="adam", loss="categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x_train, y_train, epochs=2, batch_size=128, verbose=0)
    loss, acc = m.evaluate(x_test, y_test, verbose=0)
    return {"loss": -acc, "status": STATUS_OK, "model": m}


def main():
    sc = SparkContext(master="local[4]", appName="hyperparam")
    hp = HyperParamModel(sc, num_workers=4)
    best = hp.minimize(model=model, data=data,
                       max_evals=int(os.environ.get("EX_EPOCHS", 3)))
    x_tr, y_tr, x_te, y_te = data()
    preds = best.predict(x_te, verbose=0)
    acc = float((preds.argmax(1) == y_te.argmax(1)).mean())
    print(f"best model test accuracy: {acc:.4f}")
    sc.stop()


if __name__ == "__main__":
    main()
