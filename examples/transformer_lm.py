"""Long-context transformer LM with dp×sp sequence parallelism.

EXTENSION BEYOND THE REFERENCE (no analog in ``b13n3rd/elephas`` — its
longest-sequence workload is a whole-sequence-per-worker IMDB LSTM). A
GPT-style decoder-only LM trains with the batch sharded over the ``"data"``
mesh axis and the SEQUENCE sharded over a ``"seq"`` axis, attention computed
exactly via ring attention (``ppermute`` KV rotation over ICI) or
DeepSpeed-Ulysses all-to-alls — context length scales linearly with the
seq-axis size.

Task: character-level language modelling of synthetic text with long-range
structure (each line ends by repeating its opening word, so the model must
carry information across the sequence).

Run (TPU): ``KERAS_BACKEND=jax python examples/transformer_lm.py``
Run (CPU mesh): prefix with
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SEQ_LEN = 128
VOCAB = 32


def synthetic_corpus(n_rows: int, seed: int = 0) -> np.ndarray:
    """``[n, SEQ_LEN+1]`` int rows: random prefix, then the prefix repeated —
    forcing attention across half the context window."""
    rng = np.random.default_rng(seed)
    half = SEQ_LEN // 2 + 1
    prefix = rng.integers(2, VOCAB, size=(n_rows, half))
    rows = np.concatenate([prefix, prefix], axis=1)[:, : SEQ_LEN + 1]
    assert rows.shape[1] == SEQ_LEN + 1
    return rows


def main():
    import jax
    import optax

    from elephas_tpu.models import (
        TransformerLM,
        build_lm_train_step,
        build_mesh_sp,
        make_lm_batches,
        shard_lm_batch,
    )

    n_dev = len(jax.devices())
    sp = max(d for d in (1, 2, 4, 8) if n_dev % d == 0 and SEQ_LEN % d == 0)
    dp = n_dev // sp
    mesh = build_mesh_sp(data=dp, seq=sp)
    print(f"devices={n_dev} mesh=data:{dp} x seq:{sp} "
          f"(context/chip = {SEQ_LEN // sp} of {SEQ_LEN} tokens)")

    model = TransformerLM(vocab=VOCAB, d_model=64, n_heads=8, n_layers=2,
                          d_ff=128, max_len=SEQ_LEN)
    step, opt_init = build_lm_train_step(model, mesh, optax.adam(3e-3),
                                         attn="ring")
    params = model.shard_params(mesh, model.init(seed=0))
    state = opt_init(params)

    tokens, positions, targets = make_lm_batches(synthetic_corpus(8 * dp))
    td, pd, gd = shard_lm_batch(mesh, tokens, positions, targets)

    for i in range(60):
        params, state, loss = step(params, state, td, pd, gd)
        if i % 10 == 0 or i == 59:
            print(f"step {i:3d}  loss/token {float(loss):.4f}")

    final = float(loss)
    # random-guess CE is ln(30) ≈ 3.4; the copy structure is learnable far
    # below that
    assert final < 2.0, f"LM failed to learn long-range copy task: {final}"

    # -- inference epilogue: KV-cached greedy generation ------------------
    # Prompt with a training row's prefix + a few repeated tokens; greedy
    # generation (flash-decode kernel path on TPU) must continue the
    # repetition the model learned. (A 60-step d64 model memorizes its 8
    # training rows rather than learning the general copy algorithm —
    # held-out copying needs longer training; this exercises the decode
    # machinery end-to-end on what the model actually knows.)
    import jax.numpy as jnp

    host_params = {k: jnp.asarray(np.asarray(v)) for k, v in params.items()}
    row = synthetic_corpus(8 * dp)[0]  # a training row
    half = SEQ_LEN // 2 + 1
    cut = half + 4
    out = np.asarray(model.generate(
        host_params, row[None, :cut], n_new=SEQ_LEN - cut,
    ))[0]
    acc = float((out[cut:SEQ_LEN] == row[cut:SEQ_LEN]).mean())
    print(f"greedy continuation accuracy on the copy tail: {acc:.2f}")
    assert acc > 0.8, f"decode diverged from the learned sequence: {acc}"
    print("ok")


if __name__ == "__main__":
    main()
