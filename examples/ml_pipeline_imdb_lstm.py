"""Config 3: IMDB LSTM text classification through the ML-Pipeline skin.

The reference's ``ElephasEstimator`` inside a ``pyspark.ml.Pipeline``
(SURVEY.md §3.3), here over the local DataFrame facade. The
Embedding→LSTM→Dense model compiles under Keras-3/JAX; on TPU the LSTM
becomes an XLA ``while``/scan program and the embedding + projection matmuls
land on the MXU.
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import keras
import numpy as np

from elephas_tpu import ElephasEstimator
from elephas_tpu.data import Row, SparkSession
from elephas_tpu.ml import Pipeline
from elephas_tpu.mllib import Vectors

from _datasets import load_imdb  # noqa: E402

MAXLEN = 80
VOCAB = 2000


def make_lstm():
    model = keras.Sequential(
        [
            keras.layers.Embedding(VOCAB, 32),
            keras.layers.LSTM(32),
            keras.layers.Dense(1, activation="sigmoid"),
        ]
    )
    model.build((None, MAXLEN))
    model.compile(optimizer="adam", loss="binary_crossentropy",
                  metrics=["accuracy"])
    return model


def main():
    import jax

    n_workers = jax.local_device_count()
    spark = SparkSession.builder.master(f"local[{n_workers}]").appName(
        "imdb_lstm"
    ).getOrCreate()
    n_train = int(os.environ.get("EX_SAMPLES", 2048))
    (x_train, y_train), (x_test, y_test) = load_imdb(
        n_train=n_train, maxlen=MAXLEN, vocab=VOCAB
    )

    rows = [
        Row(features=Vectors.dense(x.astype("float64")), label=float(y[0]))
        for x, y in zip(x_train, y_train)
    ]
    df = spark.createDataFrame(rows)

    model = make_lstm()
    est = ElephasEstimator()
    est.set_keras_model(model)
    est.set_categorical(False)
    est.set_num_workers(n_workers)
    est.set_epochs(2)
    est.set_batch_size(64)
    est.set_validation_split(0.0)
    est.set_mode("synchronous")
    est.set_parameter_server_mode("jax")

    pipeline = Pipeline(stages=[est])
    fitted = pipeline.fit(df)

    test_rows = [
        Row(features=Vectors.dense(x.astype("float64")), label=float(y[0]))
        for x, y in zip(x_test, y_test)
    ]
    test_df = spark.createDataFrame(test_rows)
    out = fitted.transform(test_df)
    preds = np.array([r.prediction for r in out.collect()])
    labels = np.array([r.label for r in out.collect()])
    acc = float(((preds > 0.5) == (labels > 0.5)).mean())
    print(f"IMDB LSTM pipeline test accuracy: {acc:.4f}")
    spark.stop()


if __name__ == "__main__":
    main()
