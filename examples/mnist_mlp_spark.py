"""Config 1: MNIST MLP through ``SparkModel.fit``, synchronous mode.

The TPU-native equivalent of the reference's flagship example
(``examples/mnist_mlp_spark.py:~1``): same script shape — build data RDD,
build compiled Keras model, hand both to SparkModel — but training runs as one
XLA program over the device mesh.

Run (TPU): ``KERAS_BACKEND=jax python examples/mnist_mlp_spark.py``
Run (CPU mesh): prefix with
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import keras

from elephas_tpu import SparkModel
from elephas_tpu.data import SparkContext
from elephas_tpu.utils import to_simple_rdd

from _datasets import load_mnist  # noqa: E402


def main():
    import jax

    n_workers = jax.local_device_count()
    sc = SparkContext(master=f"local[{n_workers}]", appName="mnist_mlp")
    n_train = int(os.environ.get("EX_SAMPLES", 16384))
    epochs = int(os.environ.get("EX_EPOCHS", 5))
    (x_train, y_train), (x_test, y_test) = load_mnist(n_train=n_train)

    model = keras.Sequential(
        [
            keras.layers.Dense(128, activation="relu"),
            keras.layers.Dropout(0.2),
            keras.layers.Dense(128, activation="relu"),
            keras.layers.Dropout(0.2),
            keras.layers.Dense(10, activation="softmax"),
        ]
    )
    model.build((None, 784))
    model.compile(optimizer="adam", loss="categorical_crossentropy",
                  metrics=["accuracy"])

    rdd = to_simple_rdd(sc, x_train, y_train)
    spark_model = SparkModel(model, mode="synchronous", num_workers=n_workers)
    spark_model.fit(rdd, epochs=epochs, batch_size=128, verbose=1,
                    validation_split=0.1)

    loss, acc = spark_model.evaluate(x_test, y_test)
    print(f"test loss={loss:.4f} acc={acc:.4f}")
    sc.stop()


if __name__ == "__main__":
    main()
