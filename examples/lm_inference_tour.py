"""Inference & fine-tuning tour: LoRA → merge → quantize → generate →
speculative decoding, end to end on one small LM.

EXTENSION BEYOND THE REFERENCE (no analog in ``b13n3rd/elephas`` — its
inference surface is ``model.predict`` and it has no fine-tuning or
quantization machinery). The pipeline here is the modern deployment story,
each stage verified against the previous one:

1. pretrain a small ``TransformerLM`` briefly (dp×sp mesh);
2. LoRA-fine-tune on a shifted task — only the rank-r adapters train, the
   base stays bit-frozen;
3. ``merge_lora`` bakes the adapters in; ``quantize_lm_params`` compresses
   the merged weights to int8 (bit-identical inference vs dequantized);
4. KV-cached ``generate`` (flash-decode kernel on TPU) and
   ``generate_speculative`` (the pretrained model drafts for the
   fine-tuned one) produce the same greedy output;
5. the deployed artifact goes behind a continuous-batching
   ``ServingEngine``: interleaved requests share one slot-batched KV
   cache, each streams out with its own TTFT/throughput, and every greedy
   continuation equals the per-request ``generate``.

Run (TPU): ``KERAS_BACKEND=jax python examples/lm_inference_tour.py``
Run (CPU mesh): prefix with
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SEQ = 32
VOCAB = 24
STEPS = int(os.environ.get("EX_STEPS", 40))


def corpus(n, stride, seed=0):
    """Rows whose second half repeats the first shifted by ``stride`` mod
    vocab — pretraining uses stride 0 (plain copy), fine-tuning stride 3."""
    rng = np.random.default_rng(seed)
    half = SEQ // 2 + 1
    prefix = rng.integers(0, VOCAB, size=(n, half))
    rows = np.concatenate([prefix, (prefix + stride) % VOCAB], axis=1)
    return rows[:, : SEQ + 1]


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from elephas_tpu.models import (
        TransformerLM,
        apply_lora,
        build_lm_train_step,
        build_lora_lm_train_step,
        build_mesh_sp,
        lora_trainable_count,
        make_lm_batches,
        merge_lora,
        quantize_lm_params,
        quantized_nbytes,
        shard_lm_batch,
    )

    n_dev = len(jax.devices())
    sp = max(d for d in (1, 2, 4) if n_dev % d == 0 and SEQ % d == 0)
    dp = n_dev // sp
    mesh = build_mesh_sp(data=dp, seq=sp)
    model = TransformerLM(vocab=VOCAB, d_model=48, n_heads=4, n_layers=2,
                          d_ff=96, max_len=SEQ, pos_encoding="rotary")

    # 1. pretrain on the copy task
    step, opt_init = build_lm_train_step(model, mesh, optax.adam(3e-3),
                                         attn="ring")
    params = model.shard_params(mesh, model.init(seed=0))
    state = opt_init(params)
    batch = shard_lm_batch(mesh, *make_lm_batches(corpus(8 * dp, stride=0)))
    for i in range(STEPS):
        params, state, loss = step(params, state, *batch)
    print(f"pretrain loss {float(loss):.3f}")

    # 2. LoRA fine-tune on the shifted task: base frozen, adapters learn
    host_base = {k: np.asarray(v) for k, v in params.items()}
    # independent buffers: the LoRA step donates its params, so the copy
    # handed to apply_lora must not be the one we keep for the draft
    base = {k: jnp.asarray(v) for k, v in host_base.items()}
    lparams = apply_lora({k: jnp.asarray(v) for k, v in host_base.items()},
                         rank=4)
    trainable, total = lora_trainable_count(lparams)
    lstep, lopt_init = build_lora_lm_train_step(model, mesh,
                                                optax.adam(1e-2), attn="ring")
    lstate = lopt_init(lparams)
    fbatch = shard_lm_batch(mesh,
                            *make_lm_batches(corpus(8 * dp, stride=3, seed=7)))
    first = last = None
    for i in range(2 * STEPS):
        lparams, lstate, loss = lstep(lparams, lstate, *fbatch)
        first = float(loss) if first is None else first
        last = float(loss)
    print(f"lora fine-tune ({trainable:,}/{total:,} trainable): "
          f"loss {first:.3f} -> {last:.3f}")
    assert last < first

    # 3. merge + quantize for deployment
    merged = merge_lora(lparams)
    qparams = quantize_lm_params(merged)
    orig_bytes = sum(np.asarray(v).nbytes for v in merged.values())
    print(f"merged+quantized: {orig_bytes:,} -> {quantized_nbytes(qparams):,} "
          "bytes")

    # 4. generate with the quantized fine-tuned model; then speculative
    # decoding with the PRETRAINED model as draft — same greedy output
    row = corpus(1, stride=3, seed=7)[0]
    cut = SEQ // 2 + 3
    prompt = row[None, :cut]
    plain = np.asarray(model.generate(qparams, prompt, n_new=SEQ - cut))
    spec = np.asarray(model.generate_speculative(
        qparams, prompt, n_new=SEQ - cut, draft=model, draft_params=base,
        spec_k=3,
    ))
    np.testing.assert_array_equal(plain, spec)
    acc = float((plain[0, cut:SEQ] == row[cut:SEQ]).mean())
    print(f"greedy == speculative; fine-tuned continuation accuracy {acc:.2f}")

    # 5. serve the deployed artifact: interleaved requests, one shared
    # slot-batched KV cache, per-request TTFT/throughput from the engine's
    # own metrics
    from elephas_tpu.serving import ServingEngine

    reqs = []
    for i in range(6):
        r = corpus(1, stride=3, seed=20 + i)[0]
        c = SEQ // 2 + 1 + i % 3        # mixed prompt lengths
        reqs.append((r[:c].astype(np.int32), SEQ - c))
    eng = ServingEngine(model, qparams, n_slots=4)
    ids = []
    for p, n_new in reqs:
        ids.append(eng.submit(p, n_new))
        eng.step()                      # interleave submission with decode
    fin = eng.drain(max_steps=1000)
    snap = eng.snapshot()
    print(f"served {snap['counters']['completed']} requests through "
          f"{snap['engine']['n_slots']} slots "
          f"(occupancy {snap['engine']['batch_occupancy']:.2f})")
    print("  request  prompt  new  ttft_ms   tok/s")
    for rid in ids:
        t = fin[rid].timing
        print(f"  {rid:>7}  {t.prompt_tokens:>6}  {t.generated_tokens:>3}"
              f"  {t.ttft * 1e3:7.1f}  {t.decode_tokens_per_sec:6.1f}")
    for rid, (p, n_new) in zip(ids, reqs):
        ref = np.asarray(model.generate(qparams, p[None], n_new))[0, len(p):]
        np.testing.assert_array_equal(fin[rid].tokens, ref)
    print("serving == per-request generate")
    print("ok")


if __name__ == "__main__":
    main()
