"""HF checkpoint import tour: torch GPT-2/Llama → TPU-native LM →
verify → quantize → (sharded) generate.

EXTENSION BEYOND THE REFERENCE (``b13n3rd/elephas`` consumes Keras models
only — SURVEY.md §2.5; it has no foreign-checkpoint interop). This script
demonstrates the migration path from the HuggingFace ecosystem:

1. build a small ``transformers`` GPT-2 and a Llama-style GQA model in
   torch (stand-ins for real checkpoints — pass ``HF_MODEL=<path>`` to
   import a downloaded one instead);
2. ``lm_from_hf`` converts each into the functional ``TransformerLM``
   layout (architecture — gelu/swiglu, rmsnorm, biases, rope_theta, GQA —
   resolved from the HF config);
3. verify logits parity against the torch forward pass;
4. run the framework's own machinery on the imported weights: KV-cached
   greedy generation, int8 quantized generation, and dp×sp sequence-
   sharded generation on the device mesh — all without touching torch
   again.

Run (TPU): ``KERAS_BACKEND=jax python examples/hf_import_tour.py``
Run (CPU mesh): prefix with
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def tiny_hf_models():
    import torch
    import transformers

    torch.manual_seed(0)
    gpt2 = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0))
    llama = transformers.LlamaForCausalLM(transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, attention_dropout=0.0))
    mixtral = transformers.MixtralForCausalLM(transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4,
        num_experts_per_tok=2, attention_dropout=0.0, sliding_window=None,
        attn_implementation="eager"))
    gpt2.eval(), llama.eval(), mixtral.eval()
    return {"gpt2": gpt2, "llama-gqa": llama, "mixtral-moe": mixtral}


def main():
    import jax
    import jax.numpy as jnp
    import torch

    from elephas_tpu.models import build_lm_generate, build_mesh_sp, lm_from_hf
    from elephas_tpu.models.quantize import quantize_lm_params, quantized_nbytes

    if os.environ.get("HF_MODEL"):
        from elephas_tpu.models import load_hf_lm

        model, params = load_hf_lm(os.environ["HF_MODEL"])
        todo = [(os.environ["HF_MODEL"], model, params, None)]
    else:
        todo = []
        for name, hf in tiny_hf_models().items():
            model, params = lm_from_hf(hf)
            todo.append((name, model, params, hf))

    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 120, size=(4, 10)).astype(np.int32)

    for name, model, params, hf in todo:
        print(f"\n=== {name}: {model.n_layers}L d{model.d_model} "
              f"{model.activation}/{model.norm} "
              f"H{model.n_heads}/KV{model.n_kv_heads} ===")
        p = jax.tree.map(jnp.asarray, params)

        if hf is not None:
            pos = np.broadcast_to(np.arange(prompt.shape[1]), prompt.shape)
            with jax.default_matmul_precision("float32"):
                ours = np.asarray(model.apply(p, prompt, pos))
            with torch.no_grad():
                theirs = hf(input_ids=torch.tensor(
                    prompt, dtype=torch.long)).logits.numpy()
            print(f"logits parity vs torch: max|Δ| = "
                  f"{np.abs(ours - theirs).max():.2e}")

        out = np.asarray(model.generate(p, prompt, 12))
        print("greedy generate:", out[0, -12:].tolist())

        qp = quantize_lm_params(p)
        qout = np.asarray(model.generate(qp, prompt, 12))
        agree = float((qout == out).mean())
        print(f"int8 generate ({quantized_nbytes(qp)/2**20:.1f} MiB "
              f"resident): {agree:.0%} token agreement")

        n_dev = len(jax.devices())
        if n_dev >= 2:
            mesh = build_mesh_sp(data=2 if n_dev >= 8 else 1,
                                 seq=4 if n_dev >= 8 else n_dev)
            gen = build_lm_generate(model, mesh)
            sout = np.asarray(gen(model.shard_params(mesh, p), prompt, 12))
            print(f"sharded generate over {dict(mesh.shape)}: "
                  f"{'token-for-token equal' if (sout == out).all() else 'MISMATCH'}")


if __name__ == "__main__":
    main()
