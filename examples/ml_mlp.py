"""``ElephasEstimator`` basics on a DataFrame (reference ``examples/ml_mlp.py``)."""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import keras
import numpy as np

from elephas_tpu import ElephasEstimator
from elephas_tpu.data import Row, SparkSession
from elephas_tpu.mllib import Vectors

from _datasets import load_mnist  # noqa: E402


def main():
    import jax

    n_workers = jax.local_device_count()
    spark = SparkSession.builder.master(f"local[{n_workers}]").appName(
        "ml_mlp"
    ).getOrCreate()
    n_train = int(os.environ.get("EX_SAMPLES", 8192))
    (x_train, y_train), (x_test, y_test) = load_mnist(n_train=n_train, n_test=1024)

    df = spark.createDataFrame(
        [Row(features=Vectors.dense(x.astype("float64")),
             label=float(y.argmax())) for x, y in zip(x_train, y_train)]
    )

    model = keras.Sequential(
        [keras.layers.Dense(128, activation="relu"),
         keras.layers.Dense(10, activation="softmax")]
    )
    model.build((None, 784))
    model.compile(optimizer="adam", loss="categorical_crossentropy",
                  metrics=["accuracy"])

    estimator = ElephasEstimator()
    estimator.set_keras_model(model)
    estimator.set_categorical(True)
    estimator.set_nb_classes(10)
    estimator.set_num_workers(n_workers)
    estimator.set_epochs(int(os.environ.get("EX_EPOCHS", 3)))
    estimator.set_batch_size(64)
    estimator.set_validation_split(0.1)
    estimator.set_mode("synchronous")
    estimator.set_parameter_server_mode("jax")

    transformer = estimator.fit(df)

    test_df = spark.createDataFrame(
        [Row(features=Vectors.dense(x.astype("float64")),
             label=float(y.argmax())) for x, y in zip(x_test, y_test)]
    )
    rows = transformer.transform(test_df).collect()
    preds = np.array([r.prediction for r in rows])
    labels = np.array([r.label for r in rows])
    print(f"test accuracy: {float((preds == labels).mean()):.4f}")
    spark.stop()


if __name__ == "__main__":
    main()
