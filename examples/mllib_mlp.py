"""Config 4: ``SparkMLlibModel`` on LabeledPoint RDDs.

Boston-housing-shaped regression + Iris multiclass, the reference's
``examples/mllib_mlp.py`` equivalents: LabeledPoint in, MLlib Vector/Matrix
out.
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import keras
import numpy as np

from elephas_tpu import SparkMLlibModel
from elephas_tpu.data import SparkContext
from elephas_tpu.mllib import Matrices, Vectors
from elephas_tpu.utils import to_labeled_point

from _datasets import load_boston, load_iris  # noqa: E402


def boston_regression(sc, n_workers):
    x, y = load_boston()
    # standardize for a stable MLP fit
    x = (x - x.mean(0)) / (x.std(0) + 1e-6)
    y_mean, y_std = y.mean(), y.std()
    lp_rdd = to_labeled_point(sc, x, (y - y_mean) / y_std, categorical=False)

    model = keras.Sequential(
        [keras.layers.Dense(32, activation="relu"), keras.layers.Dense(1)]
    )
    model.build((None, 13))
    model.compile(optimizer="adam", loss="mse")
    mllib_model = SparkMLlibModel(model, mode="synchronous",
                                  num_workers=n_workers)
    epochs = int(os.environ.get("EX_EPOCHS", 20))
    mllib_model.fit(lp_rdd, epochs=epochs, batch_size=32, validation_split=0.0,
                    categorical=False)
    pred = mllib_model.predict(Vectors.dense(x[0].astype("float64")))
    print(f"Boston: predicted {float(pred[0]) * y_std + y_mean:.1f}, "
          f"actual {y[0]:.1f}")


def iris_classification(sc, n_workers):
    x, y = load_iris()
    x = (x - x.mean(0)) / (x.std(0) + 1e-6)
    lp_rdd = to_labeled_point(sc, x, y, categorical=True)

    model = keras.Sequential(
        [keras.layers.Dense(16, activation="relu"),
         keras.layers.Dense(3, activation="softmax")]
    )
    model.build((None, 4))
    model.compile(optimizer="adam", loss="categorical_crossentropy",
                  metrics=["accuracy"])
    mllib_model = SparkMLlibModel(model, mode="synchronous",
                                  num_workers=min(n_workers, 4))
    epochs = int(os.environ.get("EX_EPOCHS", 30))
    mllib_model.fit(lp_rdd, epochs=epochs, batch_size=16, validation_split=0.0,
                    categorical=True, nb_classes=3)
    preds = mllib_model.predict(
        Matrices.dense(len(x), 4, x.astype("float64").flatten(order="F"))
    )
    acc = float((preds.toArray().argmax(1) == y).mean())
    print(f"Iris: train accuracy {acc:.4f}")


def main():
    import jax

    n_workers = jax.local_device_count()
    sc = SparkContext(master=f"local[{n_workers}]", appName="mllib_mlp")
    boston_regression(sc, n_workers)
    iris_classification(sc, n_workers)
    sc.stop()


if __name__ == "__main__":
    main()
