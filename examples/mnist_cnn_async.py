"""Config 2: MNIST CNN, asynchronous + hogwild modes.

The reference drives these through its HTTP/Socket parameter server; here both
the literal host PS (``parameter_server_mode='http'|'socket'``) and the
on-device merge path (``'jax'``) are exercised. The CNN (Conv2D stack) runs
on the MXU via XLA.
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import keras

from elephas_tpu import SparkModel
from elephas_tpu.data import SparkContext
from elephas_tpu.utils import to_simple_rdd

from _datasets import load_mnist  # noqa: E402


def make_cnn():
    model = keras.Sequential(
        [
            keras.layers.Reshape((28, 28, 1)),
            keras.layers.Conv2D(16, 3, activation="relu"),
            keras.layers.MaxPooling2D(2),
            keras.layers.Conv2D(32, 3, activation="relu"),
            keras.layers.MaxPooling2D(2),
            keras.layers.Flatten(),
            keras.layers.Dense(64, activation="relu"),
            keras.layers.Dense(10, activation="softmax"),
        ]
    )
    model.build((None, 784))
    model.compile(optimizer="adam", loss="categorical_crossentropy",
                  metrics=["accuracy"])
    return model


def main():
    import jax

    n_workers = jax.local_device_count()
    sc = SparkContext(master=f"local[{n_workers}]", appName="mnist_cnn_async")
    n_train = int(os.environ.get("EX_SAMPLES", 8192))
    epochs = int(os.environ.get("EX_EPOCHS", 3))
    (x_train, y_train), (x_test, y_test) = load_mnist(n_train=n_train, n_test=1024)
    rdd = to_simple_rdd(sc, x_train, y_train)

    for mode, ps in [("asynchronous", "jax"), ("hogwild", "jax"),
                     ("asynchronous", "http")]:
        model = make_cnn()
        spark_model = SparkModel(
            model, mode=mode, frequency="epoch", parameter_server_mode=ps,
            num_workers=n_workers, port=4100, merge="mean",
        )
        spark_model.fit(rdd, epochs=epochs, batch_size=64, verbose=0,
                        validation_split=0.0)
        loss, acc = spark_model.evaluate(x_test, y_test)
        print(f"{mode:12s}/{ps:6s}: test loss={loss:.4f} acc={acc:.4f}")
    sc.stop()


if __name__ == "__main__":
    main()
