"""Example datasets with offline synthetic fallbacks.

``keras.datasets.*`` downloads are unavailable in air-gapped environments, so
every loader falls back to a deterministic synthetic dataset with the same
shapes/dtypes as the real one. The training dynamics differ from the real
datasets, but every example exercises the identical API surface and shapes.
"""

from __future__ import annotations

import numpy as np


def load_mnist(n_train=16384, n_test=2048):
    """(x [n,784] float32 in [0,1], y one-hot [n,10]) — real MNIST if cached."""
    try:
        import keras

        (x_tr, y_tr), (x_te, y_te) = keras.datasets.mnist.load_data()
        x_tr = x_tr.reshape(-1, 784).astype("float32") / 255.0
        x_te = x_te.reshape(-1, 784).astype("float32") / 255.0
        y_tr = np.eye(10, dtype="float32")[y_tr]
        y_te = np.eye(10, dtype="float32")[y_te]
        return (x_tr[:n_train], y_tr[:n_train]), (x_te[:n_test], y_te[:n_test])
    except Exception:
        rng = np.random.default_rng(0)
        # Class-dependent Gaussian blobs in pixel space: learnable, MNIST-shaped.
        protos = rng.uniform(0, 1, size=(10, 784)).astype("float32")

        def make(n):
            labels = rng.integers(0, 10, size=n)
            x = protos[labels] + 0.3 * rng.normal(size=(n, 784)).astype("float32")
            x = np.clip(x, 0, 1).astype("float32")
            y = np.eye(10, dtype="float32")[labels]
            return x, y

        return make(n_train), make(n_test)


def load_imdb(n_train=2048, n_test=512, maxlen=80, vocab=2000):
    """(sequences [n,maxlen] int32, labels [n,1] float32) — IMDB-shaped."""
    try:
        import keras

        (x_tr, y_tr), (x_te, y_te) = keras.datasets.imdb.load_data(num_words=vocab)
        from keras.preprocessing.sequence import pad_sequences

        x_tr = pad_sequences(x_tr, maxlen=maxlen).astype("int32")
        x_te = pad_sequences(x_te, maxlen=maxlen).astype("int32")
        return (
            (x_tr[:n_train], y_tr[:n_train].astype("float32").reshape(-1, 1)),
            (x_te[:n_test], y_te[:n_test].astype("float32").reshape(-1, 1)),
        )
    except Exception:
        rng = np.random.default_rng(1)
        # Sentiment-word model: two token distributions; label = which
        # distribution dominated the sequence.
        pos_words = rng.integers(2, vocab // 2, size=vocab // 8)
        neg_words = rng.integers(vocab // 2, vocab, size=vocab // 8)

        def make(n):
            labels = rng.integers(0, 2, size=n)
            seqs = np.where(
                labels[:, None] == 1,
                rng.choice(pos_words, size=(n, maxlen)),
                rng.choice(neg_words, size=(n, maxlen)),
            )
            noise = rng.integers(2, vocab, size=(n, maxlen))
            mask = rng.random((n, maxlen)) < 0.3
            seqs = np.where(mask, noise, seqs).astype("int32")
            return seqs, labels.astype("float32").reshape(-1, 1)

        return make(n_train), make(n_test)


def load_boston(n=506):
    """Boston-housing-shaped regression: (x [n,13], y [n])."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(n, 13)).astype("float32")
    w = rng.normal(size=(13,))
    y = (x @ w + 0.1 * rng.normal(size=n) + 22.5).astype("float32")
    return x, y


def load_iris():
    """Iris-shaped 3-class problem: (x [150,4], y [150] class ids)."""
    rng = np.random.default_rng(3)
    centers = np.array(
        [[5.0, 3.4, 1.5, 0.2], [5.9, 2.8, 4.3, 1.3], [6.6, 3.0, 5.6, 2.0]],
        dtype="float32",
    )
    labels = np.repeat(np.arange(3), 50)
    x = centers[labels] + 0.25 * rng.normal(size=(150, 4)).astype("float32")
    perm = rng.permutation(150)
    return x[perm].astype("float32"), labels[perm].astype("float64")
