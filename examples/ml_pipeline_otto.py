"""Full ML Pipeline with feature stages (reference ``examples/ml_pipeline_otto.py``).

Otto-product-classification-shaped problem: 93 count features, 9 classes,
string category labels — StringIndexer → StandardScaler → ElephasEstimator in
one Pipeline, the reference's flagship pipeline demo.
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import keras
import numpy as np

from elephas_tpu import ElephasEstimator
from elephas_tpu.data import Row, SparkSession
from elephas_tpu.ml import Pipeline, StandardScaler, StringIndexer
from elephas_tpu.mllib import Vectors


def load_otto(n=4096, d=93, c=9):
    rng = np.random.default_rng(11)
    protos = rng.poisson(3.0, size=(c, d)).astype("float32")
    labels = rng.integers(0, c, size=n)
    x = rng.poisson(protos[labels] + 1.0).astype("float32")
    names = [f"Class_{i + 1}" for i in range(c)]
    return x, [names[i] for i in labels]


def main():
    import jax

    n_workers = jax.local_device_count()
    spark = SparkSession.builder.master(f"local[{n_workers}]").appName(
        "otto"
    ).getOrCreate()
    x, targets = load_otto(n=int(os.environ.get("EX_SAMPLES", 4096)))

    df = spark.createDataFrame(
        [Row(raw_features=Vectors.dense(xi.astype("float64")), target=t)
         for xi, t in zip(x, targets)]
    )

    model = keras.Sequential(
        [
            keras.layers.Dense(128, activation="relu"),
            keras.layers.Dropout(0.2),
            keras.layers.Dense(9, activation="softmax"),
        ]
    )
    model.build((None, 93))
    model.compile(optimizer="adam", loss="categorical_crossentropy",
                  metrics=["accuracy"])

    estimator = ElephasEstimator()
    estimator.set_keras_model(model)
    estimator.set_categorical(True)
    estimator.set_nb_classes(9)
    estimator.set_features_col("scaled_features")
    estimator.set_label_col("label")
    estimator.set_num_workers(n_workers)
    estimator.set_epochs(int(os.environ.get("EX_EPOCHS", 4)))
    estimator.set_batch_size(64)
    estimator.set_validation_split(0.0)
    estimator.set_mode("synchronous")
    estimator.set_parameter_server_mode("jax")

    pipeline = Pipeline(
        stages=[
            StringIndexer(inputCol="target", outputCol="label"),
            StandardScaler(inputCol="raw_features",
                           outputCol="scaled_features"),
            estimator,
        ]
    )
    fitted = pipeline.fit(df)
    rows = fitted.transform(df).collect()
    preds = np.array([r.prediction for r in rows])
    labels = np.array([r.label for r in rows])
    print(f"Otto pipeline train accuracy: {float((preds == labels).mean()):.4f}")
    spark.stop()


if __name__ == "__main__":
    main()
