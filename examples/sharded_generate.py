"""Sequence-sharded generation: decode without gathering to one device.

EXTENSION BEYOND THE REFERENCE (no analog in ``b13n3rd/elephas`` — its
inference surface is driver-local ``model.predict``). A ``TransformerLM``
trained dp×sp keeps training state resident across the mesh; this example
shows the matching inference path: ``build_lm_generate`` compiles
generation as ONE ``shard_map`` program where the batch shards over
``"data"`` and the KV cache shards over ``"seq"`` along time — per-chip
cache memory drops by the seq-axis size, and the decode horizon scales
with the mesh instead of one chip's HBM
(``elephas_tpu/models/sharded_generate.py`` for the logsumexp merge).

The script trains briefly on a copy task, generates with the sharded
program, and checks the rollout token-for-token against the gathered
single-device ``generate`` — the exactness contract the tests pin.

Run (TPU): ``KERAS_BACKEND=jax python examples/sharded_generate.py``
Run (CPU mesh): prefix with
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SEQ = 32
VOCAB = 24
STEPS = int(os.environ.get("EX_STEPS", 30))


def corpus(n, seed=0):
    """Rows whose second half repeats the first — learnable in seconds."""
    rng = np.random.default_rng(seed)
    half = SEQ // 2 + 1
    first = rng.integers(0, VOCAB, size=(n, half))
    return np.concatenate([first, first[:, : SEQ + 1 - half]], axis=1)


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from elephas_tpu.models import (
        TransformerLM,
        build_lm_generate,
        build_lm_train_step,
        build_mesh_sp,
        make_lm_batches,
        shard_lm_batch,
    )

    n_dev = jax.local_device_count()
    seq_axis = 4 if n_dev % 4 == 0 else 1
    data_axis = n_dev // seq_axis
    mesh = build_mesh_sp(data=data_axis, seq=seq_axis)
    print(f"mesh: data={data_axis} x seq={seq_axis}")

    model = TransformerLM(vocab=VOCAB, d_model=32, n_heads=4, n_layers=2,
                          d_ff=64, max_len=SEQ, pos_encoding="rotary")
    step, opt_init = build_lm_train_step(model, mesh, optax.adam(3e-3),
                                         attn="ring")
    params = model.shard_params(mesh, model.init(seed=0))
    state = opt_init(params)
    for i in range(STEPS):
        rows = corpus(4 * data_axis, seed=i)
        batch = shard_lm_batch(mesh, *make_lm_batches(rows))
        params, state, loss = step(params, state, *batch)
    print(f"trained {STEPS} steps, final loss {float(loss):.3f}")

    # generate with the seq-sharded cache; prompt = first half of fresh rows
    prompt = corpus(2 * data_axis, seed=999)[:, : SEQ // 2].astype(np.int32)
    n_new = SEQ - SEQ // 2
    gen = build_lm_generate(model, mesh)
    sharded = np.asarray(gen(params, prompt, n_new))

    gathered_params = {k: jnp.asarray(np.asarray(v)) for k, v in
                       params.items()}
    gathered = np.asarray(model.generate(gathered_params, prompt, n_new))
    assert (sharded == gathered).all(), "sharded rollout diverged"

    # the trained model should mostly copy the prompt forward
    want = corpus(2 * data_axis, seed=999)[:, SEQ // 2: SEQ]
    acc = float((sharded[:, SEQ // 2:] == want).mean())
    print(f"sharded == gathered rollout; copy-task accuracy {acc:.2f}")


if __name__ == "__main__":
    main()
