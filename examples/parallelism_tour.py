"""A runnable tour of every parallelism schedule the framework ships.

EXTENSION SHOWCASE (the reference is data-parallel only — SURVEY.md §2.3).
On whatever devices are visible this script builds each trainer on a small
model, runs a few steps, and prints the loss trajectory: tensor (tp),
pipeline (pp), expert (ep, both routings), ZeRO-3 (fsdp), the dp×sp(×ep)
transformer LMs, and the 3-D dp×pp×tp composite. Every schedule here is
verified against a single-device oracle in `tests/` — this file is the
user-facing "how do I hold it" companion.

Run (CPU mesh): prefix with
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
Run (TPU): ``KERAS_BACKEND=jax python examples/parallelism_tour.py``
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def second_axis(n_devices: int) -> int:
    return max(d for d in (1, 2, 4, 8) if n_devices % d == 0)


def run_steps(step, params, state, batch, n=6):
    losses = []
    for _ in range(n):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))
    return losses


def main():
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import elephas_tpu.parallel as par
    from elephas_tpu.models import (
        MoETransformerLM,
        build_lm_train_step,
        build_mesh_sp,
        make_lm_batches,
        shard_lm_batch,
    )

    rng = np.random.default_rng(0)
    n_dev = len(jax.devices())
    second = second_axis(n_dev)
    dp = n_dev // second
    print(f"{n_dev} device(s); second-axis size {second}")

    def xent(y, yp):
        return -jnp.sum(y * jax.nn.log_softmax(yp, -1), -1)

    x = rng.normal(size=(32 * dp, 16)).astype("float32")
    y = np.eye(4, dtype="float32")[rng.integers(0, 4, size=32 * dp)]

    def data_batch(mesh, spec=P("data")):
        return (jax.device_put(x, NamedSharding(mesh, spec)),
                jax.device_put(y, NamedSharding(mesh, spec)))

    # -- tensor parallelism: Megatron column/row pairs
    mesh = par.build_mesh2d(data=dp, model=second)
    tpm = par.TensorParallelMLP([16, 8 * second, 8 * second, 8 * second, 4],
                                tp=second)
    step, oi = par.build_tp_train_step(tpm, mesh, optax.adam(1e-2), xent)
    p = tpm.shard_params(mesh, tpm.init())
    print("tp   ", run_steps(step, p, oi(p), data_batch(mesh)))

    # -- pipeline parallelism: GPipe microbatching
    mesh = par.build_mesh_pp(data=dp, pipe=second)
    ppm = par.PipelineDenseStack(d_in=16, hidden=16, d_out=4,
                                 n_stages=second)
    step, oi = par.build_pp_train_step(ppm, mesh, optax.adam(1e-2), xent,
                                       n_micro=4)
    p = ppm.shard_params(mesh, ppm.init())
    print("pp   ", run_steps(step, p, oi(p), data_batch(mesh)))

    # -- expert parallelism: token-choice and dropless expert-choice
    for routing in ("token_choice", "expert_choice"):
        mesh = par.build_mesh_ep(data=dp, expert=second)
        moe = par.MoEFeedForward(d_model=16, d_ff=32,
                                 n_experts=2 * second, k=2, routing=routing)
        step, oi = par.build_ep_train_step(
            moe, mesh, optax.adam(1e-2),
            lambda a, b: jnp.sum((a - b) ** 2, -1))
        p = moe.shard_params(mesh, moe.init())
        xt = rng.normal(size=(16 * n_dev, 16)).astype("float32")
        spec = P(("data", "expert"))
        batch = (jax.device_put(xt, NamedSharding(mesh, spec)),) * 2
        print(f"ep({routing[:5]})", run_steps(step, p, oi(p), batch))

    # -- ZeRO-3 / fsdp: params+grads+opt state chunked over the data axis
    mesh = par.build_mesh(n_dev)
    shapes = {"w0": (16, 32), "b0": (32,), "w1": (32, 4), "b1": (4,)}

    def apply_fn(pr, xb):
        h = jax.nn.relu(jnp.dot(xb, pr["w0"]) + pr["b0"])
        return jnp.dot(h, pr["w1"]) + pr["b1"]

    step, oi, fsdp = par.build_fsdp_train_step(
        apply_fn, shapes, mesh, optax.adam(1e-2), xent)
    p = fsdp.shard(mesh, fsdp.chunk_host(
        {k: (rng.normal(size=s) * 0.1).astype("float32")
         for k, s in shapes.items()}))
    xf = rng.normal(size=(8 * n_dev, 16)).astype("float32")
    yf = np.eye(4, dtype="float32")[rng.integers(0, 4, size=8 * n_dev)]
    batch = (jax.device_put(xf, NamedSharding(mesh, P("data"))),
             jax.device_put(yf, NamedSharding(mesh, P("data"))))
    print("fsdp ", run_steps(step, p, oi(p), batch))

    # -- dp×sp×ep: MoE transformer LM, sequence + experts on one axis
    mesh = build_mesh_sp(data=dp, seq=second)
    lm = MoETransformerLM(vocab=13, d_model=16, n_heads=second, n_layers=1,
                          d_ff=32, max_len=16 * second,
                          n_experts=2 * second, k=1, ep_groups=second)
    step, oi = build_lm_train_step(lm, mesh, optax.adam(3e-3), attn="ring")
    rows = rng.integers(0, 13, size=(4 * dp, 16 * second + 1))
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))
    p = lm.shard_params(mesh, lm.init())
    print("lm   ", run_steps(step, p, oi(p), batch))

    # -- 3-D composite: dp × pipeline × tensor (needs >= 4 devices)
    if n_dev >= 4:
        tp3 = 2
        pp3 = second // tp3 if second > tp3 else 2
        dp3 = n_dev // (pp3 * tp3)
        mesh = par.build_mesh_3d(data=dp3, pipe=pp3, model=tp3)
        m3 = par.TensorPipelineStack(d_in=16, hidden=16, d_out=4,
                                     n_stages=pp3)
        step, oi = par.build_3d_train_step(m3, mesh, optax.adam(1e-2), xent,
                                           n_micro=4)
        x3 = rng.normal(size=(16 * dp3, 16)).astype("float32")
        y3 = np.eye(4, dtype="float32")[rng.integers(0, 4, size=16 * dp3)]
        batch = (jax.device_put(x3, NamedSharding(mesh, P("data"))),
                 jax.device_put(y3, NamedSharding(mesh, P("data"))))
        p = m3.shard_params(mesh, m3.init())
        print("3d   ", run_steps(step, p, oi(p), batch))

    print("ok")


if __name__ == "__main__":
    main()
