# Test/bench entry points.
#
# Tests run on a virtual 8-device CPU mesh (the JAX analog of Spark local[8])
# with the axon TPU sitecustomize registration disabled — see
# tests/conftest.py for why the env prefix is required.

TEST_ENV = PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
	XLA_FLAGS=--xla_force_host_platform_device_count=8 KERAS_BACKEND=jax

.PHONY: test test-fast test-chaos test-perf test-spec test-streaming \
	test-fleet test-elastic test-paged test-soak bench bench-serving \
	bench-paged bench-lm bench-spec bench-fleet bench-elastic bench-wire

test:
	$(TEST_ENV) bash scripts/run_tests.sh -x -q

test-fast:
	$(TEST_ENV) bash scripts/run_tests.sh -x -q -m "not slow"

# Pinned deterministic chaos scenarios only (quorum commit under dead
# workers, straggler backup exactly-once, hot-standby PS failover).
test-chaos:
	ELEPHAS_TEST_GROUP=chaos $(TEST_ENV) bash scripts/run_tests.sh -x -q

# Slow loss-trajectory parity sweeps for the train-step hot-path knobs
# (overlap_grads / fused_apply / remat) — kept out of tier-1 by marker.
test-perf:
	ELEPHAS_TEST_GROUP=perf $(TEST_ENV) bash scripts/run_tests.sh -x -q

# Speculative-decoding pins only (draft/verify token identity across
# dense/paged/mesh/adapters + the metrics schema).
test-spec:
	ELEPHAS_TEST_GROUP=spec $(TEST_ENV) bash scripts/run_tests.sh -x -q

# Streaming train-to-serve pins only (hot weight rollover replay identity,
# publication cadence/eval-gate/rollback, version piggyback parity,
# supervised stream crash-resume determinism).
test-streaming:
	ELEPHAS_TEST_GROUP=streaming $(TEST_ENV) bash scripts/run_tests.sh -x -q

# Serving-fleet pins only (trace determinism, DRR fairness, router
# migration identity, autoscaler scale-up/down, the pinned fleet chaos
# scenario with kill + join mid-trace).
test-fleet:
	ELEPHAS_TEST_GROUP=fleet $(TEST_ENV) bash scripts/run_tests.sh -x -q

# Elastic multi-host control-plane pins only (subprocess host emulation:
# epoch fencing, mesh re-formation on SIGKILL/partition/late-join, and
# the pinned 2→4→3-host SparkModel.fit chaos scenario).
test-elastic:
	ELEPHAS_TEST_GROUP=elastic $(TEST_ENV) bash scripts/run_tests.sh -x -q

# Paged-KV pins only (fused paged-attention kernel oracles, dense-vs-paged
# token-identity fuzz over the knob cross-product, page-boundary
# speculative accepts, and the PagesExhausted-mid-window chaos).
test-paged:
	ELEPHAS_TEST_GROUP=paged $(TEST_ENV) bash scripts/run_tests.sh -x -q

# Randomized cross-stack chaos soak, including the slow >=20-schedule
# acceptance run (seeded fault schedules over ALL sites — wire corruption
# + logical drops/kills — applied to sync/async/hogwild fit, fit_stream,
# and a fleet replay, with the global invariant checker after every run).
# The fast smoke + harness pins also carry the marker and run in tier-1.
test-soak:
	ELEPHAS_TEST_GROUP=soak $(TEST_ENV) bash scripts/run_tests.sh -x -q

bench:
	KERAS_BACKEND=jax python bench.py

# Wire bench only: checksummed v2 framing tax vs the legacy ASCII dialect
# on a live socket PS push/pull round-trip with multi-MB payloads
# (acceptance: overhead <= 5%; out-of-band zero-copy framing keeps v2
# ahead of legacy despite the CRC32C pass).
bench-wire:
	JAX_PLATFORMS=cpu KERAS_BACKEND=jax python -c "import json, bench; \
	print(json.dumps({'wire': bench.bench_wire(3)}))"

# Serving benches only: continuous batching vs sequential, then the fast
# path (fused K-step decode vs single-step) at concurrency 1 and 8.
bench-serving:
	KERAS_BACKEND=jax python -c "import json, bench; \
	r = {'serving': bench.bench_serving(3), \
	     'serving_fastpath': bench.bench_serving_fastpath(3)}; \
	print(json.dumps(r))"

# Speculative-decoding bench only: steady-state decode throughput and
# acceptance rate at speculate_k vs the single-step baseline, on a
# high-acceptance (greedy self-draft) and a low-acceptance (n-gram on
# random tokens) workload.
bench-spec:
	KERAS_BACKEND=jax python -c "import json, bench; \
	print(json.dumps({'spec_decode': bench.bench_spec_decode(3)}))"

# Paged-KV bench only: concurrency at a fixed KV HBM budget (dense slots
# vs the paged pool), the prefix-cache hit ratio, and the equal-batch
# per-step decode-time cell with copy_bytes_per_step (fused kernels move
# O(new tokens) per step, not the O(context) gather round trip).
bench-paged:
	KERAS_BACKEND=jax python -c "import json, bench; \
	print(json.dumps({'paged_kv': bench.bench_paged_kv(3)}))"

# Fleet bench only: SLO attainment vs offered load at 2 and 4 partitions
# on the pinned deterministic trace, plus the autoscaler miss-rate
# recovery scenario. JAX_PLATFORMS=cpu: the judged numbers are scheduling
# quality on the SimClock, not accelerator throughput.
bench-fleet:
	JAX_PLATFORMS=cpu KERAS_BACKEND=jax python -c "import json, bench; \
	print(json.dumps({'fleet': bench.bench_fleet(3)}))"

# Elasticity bench only: time-to-recover after a real host SIGKILL (epoch
# bump → first post-re-formation commit) and throughput retained at
# 3-of-4 hosts vs 4-of-4, on the subprocess emulation harness.
# JAX_PLATFORMS=cpu: the judged numbers are control-plane recovery
# latency, not accelerator throughput.
bench-elastic:
	JAX_PLATFORMS=cpu KERAS_BACKEND=jax python -c "import json, bench; \
	print(json.dumps({'elasticity': bench.bench_elasticity(3)}))"

# LM section only, forced on (BENCH_LM=1 runs it even off-TPU): the judged
# geometry with per-phase timing (fwd_ms / bwd_reduce_ms / apply_ms /
# reduce_block_ms) plus the overlap-on/off comparison. Override geometry
# and knobs via BENCH_LM_* (e.g. BENCH_LM_OVERLAP=ring BENCH_LM_REMAT=dots).
bench-lm:
	BENCH_LM=1 KERAS_BACKEND=jax python -c "import json, bench; \
	r = {'lm': bench.bench_lm(3), \
	     'lm_overlap': bench.bench_lm_overlap(3)}; \
	print(json.dumps(r))"
