# Test/bench entry points.
#
# Tests run on a virtual 8-device CPU mesh (the JAX analog of Spark local[8])
# with the axon TPU sitecustomize registration disabled — see
# tests/conftest.py for why the env prefix is required.

TEST_ENV = PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
	XLA_FLAGS=--xla_force_host_platform_device_count=8 KERAS_BACKEND=jax

.PHONY: test test-fast test-chaos bench bench-serving

test:
	$(TEST_ENV) bash scripts/run_tests.sh -x -q

test-fast:
	$(TEST_ENV) bash scripts/run_tests.sh -x -q -m "not slow"

# Pinned deterministic chaos scenarios only (quorum commit under dead
# workers, straggler backup exactly-once, hot-standby PS failover).
test-chaos:
	ELEPHAS_TEST_GROUP=chaos $(TEST_ENV) bash scripts/run_tests.sh -x -q

bench:
	KERAS_BACKEND=jax python bench.py

# Serving benches only: continuous batching vs sequential, then the fast
# path (fused K-step decode vs single-step) at concurrency 1 and 8.
bench-serving:
	KERAS_BACKEND=jax python -c "import json, bench; \
	r = {'serving': bench.bench_serving(3), \
	     'serving_fastpath': bench.bench_serving_fastpath(3)}; \
	print(json.dumps(r))"
