#!/usr/bin/env bash
# Suite runner with hang AND crash recovery.
#
# The suite runs in SHARDS (one pytest process per top-level tests/
# directory). Two reasons:
#
# 1. tests/conftest.py arms a per-test watchdog: a test that exceeds its
#    bound (ELEPHAS_TEST_TIMEOUT) gets every thread's stack dumped, its
#    nodeid written to ELEPHAS_WATCHDOG_FILE, and the process killed with
#    exit 42 — a wedged XLA CPU collective cannot be interrupted from
#    Python, so the process is the unit of recovery. This wrapper turns
#    that into a retry (rerun the shard; a nodeid that hangs twice is
#    deselected and the job marked failed).
# 2. One ~500-test process accumulates an enormous jit cache and compiler
#    state, under which XLA's CPU backend segfaults rarely but
#    reproducibly (observed in backend_compile_and_load during a backward
#    compile; the same test passes in a fresh process). Sharding bounds
#    per-process state; a shard that CRASHES (segfault/abort) retries once
#    in a fresh process before failing the job.
#
# Environment (test env vars, e.g. JAX_PLATFORMS) must be set by the
# caller; `make test` does that.
#
# Marker groups: ELEPHAS_TEST_GROUP=<marker> (e.g. `chaos`, `perf` for
# the slow train-step parity sweeps, `spec`, `streaming` for the
# train-to-serve rollover pins, `fleet` for the serving-fleet
# control plane, `elastic` for the elastic multi-host pins with
# subprocess host emulation, `paged` for the fused paged-attention
# kernel oracles + dense-vs-paged identity fuzz + page-pressure chaos,
# or `soak` for the randomized cross-stack chaos soak including the slow
# >=20-schedule acceptance run — see the matching make targets) restricts
# every shard to that pytest marker. The group's `-m` is appended AFTER the
# caller's args because pytest honors only the LAST -m — so
# `ELEPHAS_TEST_GROUP=chaos make test-fast` runs the chaos group even
# though the Makefile target itself passes `-m "not slow"`.
set -u

WATCHDOG_FILE="${ELEPHAS_WATCHDOG_FILE:-$(mktemp /tmp/elephas_watchdog.XXXXXX)}"
export ELEPHAS_WATCHDOG_FILE="$WATCHDOG_FILE"

group_args=()
if [ -n "${ELEPHAS_TEST_GROUP:-}" ]; then
  group_args=(-m "$ELEPHAS_TEST_GROUP")
fi

# Top-level shards: every directory under tests/ plus tests/ itself
# non-recursively (pytest.ini-style rootdir files). New test trees are
# picked up automatically — tests/serving/ (the continuous-batching
# engine) and tests/resilience/ (fault-injection chaos scenarios) run as
# their own shards like models/ops/parallel.
shards=()
for d in tests/*/; do
  [ -d "$d" ] && [ -n "$(find "$d" -name 'test_*.py' -print -quit)" ] \
    && shards+=("${d%/}")
done
if [ -n "$(find tests -maxdepth 1 -name 'test_*.py' -print -quit)" ]; then
  shards+=("--top")  # sentinel: tests/ non-recursive
fi

overall_rc=0

run_shard() {
  local shard="$1"; shift
  local deselect=()
  local hung_once=""
  local hung_failed=0
  local crashed_once=0
  local target=("$shard")
  if [ "$shard" == "--top" ]; then
    target=()
    for f in tests/test_*.py; do [ -e "$f" ] && target+=("$f"); done
    [ "${#target[@]}" -eq 0 ] && return 0
  fi

  for attempt in 1 2 3 4; do
    rm -f "$WATCHDOG_FILE"
    python -m pytest "${target[@]}" "$@" "${group_args[@]}" "${deselect[@]}"
    rc=$?
    if [ "$rc" -eq 5 ]; then  # no tests collected in this shard
      return 0
    fi
    if [ "$rc" -ge 128 ]; then  # killed by signal (segfault, abort, …)
      if [ "$crashed_once" -eq 0 ]; then
        echo "[run_tests] shard ${target[*]} crashed (rc=$rc) — retrying once in a fresh process"
        crashed_once=1
        continue
      fi
      echo "[run_tests] shard ${target[*]} crashed twice (rc=$rc) — failing"
      return "$rc"
    fi
    if [ "$rc" -ne 42 ]; then
      if [ "$rc" -eq 0 ] && [ "$hung_failed" -ne 0 ]; then
        echo "[run_tests] shard green but a test hung twice and was deselected — failing"
        return 1
      fi
      return "$rc"
    fi
    nodeid="$(head -n1 "$WATCHDOG_FILE" 2>/dev/null || true)"
    if [ -z "$nodeid" ]; then
      echo "[run_tests] watchdog exit without a recorded nodeid — giving up"
      return 42
    fi
    echo "[run_tests] watchdog killed hung test: $nodeid (attempt $attempt)"
    tail -n +2 "$WATCHDOG_FILE"  # the hung process's all-thread stack dump
    if [ "$nodeid" == "$hung_once" ]; then
      echo "[run_tests] $nodeid hung twice — deselecting it and failing the job at the end"
      deselect+=("--deselect=$nodeid")
      hung_failed=1
      hung_once=""
    else
      hung_once="$nodeid"
    fi
  done

  echo "[run_tests] too many watchdog kills in shard ${target[*]} — giving up"
  return 1
}

for shard in "${shards[@]}"; do
  run_shard "$shard" "$@"
  rc=$?
  if [ "$rc" -ne 0 ]; then
    overall_rc="$rc"
    echo "[run_tests] shard $shard FAILED (rc=$rc)"
    # -x style early exit if the caller asked for it
    for a in "$@"; do
      if [ "$a" == "-x" ] || [ "$a" == "--exitfirst" ]; then
        rm -f "$WATCHDOG_FILE"
        exit "$rc"
      fi
    done
  fi
done

rm -f "$WATCHDOG_FILE"
exit "$overall_rc"
