#!/usr/bin/env bash
# Suite runner with hang recovery.
#
# tests/conftest.py arms a per-test watchdog: a test that exceeds its bound
# (ELEPHAS_TEST_TIMEOUT; see conftest for the default and how it was sized)
# gets every thread's stack dumped, its
# nodeid written to ELEPHAS_WATCHDOG_FILE, and the process killed with exit
# 42 — a wedged XLA CPU collective cannot be interrupted from Python, so the
# process is the unit of recovery. This wrapper turns that into a retry:
#
#   exit 42, first time for a nodeid  -> rerun the suite (the hung test gets
#                                        a second chance in a fresh process)
#   exit 42, same nodeid twice        -> deselect it, keep running the rest,
#                                        mark the job failed
#   any other exit                    -> passed through unchanged
#
# Environment (test env vars, e.g. JAX_PLATFORMS) must be set by the caller;
# `make test` does that.
set -u

WATCHDOG_FILE="${ELEPHAS_WATCHDOG_FILE:-$(mktemp /tmp/elephas_watchdog.XXXXXX)}"
export ELEPHAS_WATCHDOG_FILE="$WATCHDOG_FILE"

deselect=()
hung_once=""
hung_failed=0

for attempt in 1 2 3 4; do
  rm -f "$WATCHDOG_FILE"
  python -m pytest tests/ "$@" "${deselect[@]}"
  rc=$?
  if [ "$rc" -ne 42 ]; then
    rm -f "$WATCHDOG_FILE"
    if [ "$rc" -eq 0 ] && [ "$hung_failed" -ne 0 ]; then
      echo "[run_tests] suite green but a test hung twice and was deselected — failing"
      exit 1
    fi
    exit "$rc"
  fi
  nodeid="$(head -n1 "$WATCHDOG_FILE" 2>/dev/null || true)"
  if [ -z "$nodeid" ]; then
    echo "[run_tests] watchdog exit without a recorded nodeid — giving up"
    exit 42
  fi
  echo "[run_tests] watchdog killed hung test: $nodeid (attempt $attempt)"
  tail -n +2 "$WATCHDOG_FILE"  # the hung process's all-thread stack dump
  if [ "$nodeid" == "$hung_once" ]; then
    echo "[run_tests] $nodeid hung twice — deselecting it and failing the job at the end"
    deselect+=("--deselect=$nodeid")
    hung_failed=1
    hung_once=""
  else
    hung_once="$nodeid"
  fi
done

echo "[run_tests] too many watchdog kills — giving up"
exit 1
