"""Shared backend-bootstrap helpers for the judged harness scripts.

``bench.py`` and ``__graft_entry__.py`` both need the same two moves when the
tunnelled TPU backend is absent, hung, or too small for the requested mesh:

1. probe jax backend init in a *subprocess* (a hung ``jax.devices()`` through
   a dead relay would otherwise hang the whole harness), and
2. fall back to a virtual CPU mesh (``JAX_PLATFORMS=cpu`` + XLA's
   ``--xla_force_host_platform_device_count``) so an artifact is always
   produced.

Keeping the recipe here — one importable module, no jax import at module
scope — means the two harness entry points cannot drift apart.
"""

import os
import subprocess
import sys


def cpu_mesh_env(n_devices, base_env=None):
    """Return a copy of ``base_env`` (default ``os.environ``) rewritten to run
    jax on a virtual ``n_devices``-device CPU mesh with axon TPU registration
    disabled."""
    env = dict(os.environ if base_env is None else base_env)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "KERAS_BACKEND": "jax",
            "XLA_FLAGS": env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={int(n_devices)}",
        }
    )
    return env


def probe_backend(timeout_s=420):
    """Initialize jax in a subprocess and report what it sees.

    Returns ``(ok, n_devices, detail)`` where ``detail`` is the platform name
    on success or a truncated error description on failure. Never raises and
    never hangs past ``timeout_s``.
    """
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; d = jax.devices(); "
                "print(len(d), d[0].platform)",
            ],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False, 0, f"backend probe hung >{timeout_s}s"
    if proc.returncode != 0:
        return False, 0, proc.stderr[-500:]
    # Plugins/sitecustomize may print extra lines around ours — scan from the
    # end for the "<int> <platform>" line rather than trusting the last line.
    for line in reversed(proc.stdout.strip().splitlines()):
        parts = line.split()
        if len(parts) == 2 and parts[0].isdigit():
            return True, int(parts[0]), parts[1]
    return False, 0, f"unparseable probe output: {proc.stdout[-200:]!r}"
