// Native prefetching batch loader.
//
// The reference's per-epoch data plane is Python: workers materialize their
// partition, then Keras shuffles and slices batches on the GIL-bound host
// thread (elephas/worker.py:~25 materialization; Keras fit's index
// shuffling). This is the TPU build's native equivalent for the host paths:
// Fisher-Yates shuffle + permuted row gather + batch assembly run on C++
// worker threads into a ring of preallocated slots, so the Python thread
// only memcpy-consumes ready batches (and the GIL is never held during
// gather). The compiled engine path doesn't need this — whole epochs live
// on-device — but the reference-faithful host workers and any custom
// training loop feeding jax.device_put do.
//
// extern "C" API (ctypes-friendly; see elephas_tpu/data/native_loader.py):
//   dl_open(x, y, n, x_row, y_row, batch, n_prefetch, n_threads) -> handle
//   dl_start_epoch(handle, seed)     begin shuffled epoch (drops prior state)
//   dl_next(handle, x_out, y_out)    -> batch rows filled, 0 at epoch end
//   dl_close(handle)
//
// The caller OWNS x/y (numpy buffers) and must keep them alive until
// dl_close; rows are float32, row-major, x_row/y_row floats per row. The
// final partial batch is returned with its true row count.

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Slot {
  std::vector<float> x, y;
  int64_t rows = 0;
  int64_t index = -1;  // batch index this slot holds; -1 = empty
  bool busy = false;   // a worker is gathering into it (survives epoch reset)
};

struct Loader {
  const float *x = nullptr, *y = nullptr;
  int64_t n = 0, x_row = 0, y_row = 0, batch = 0;
  // generation-owned permutation: stale workers keep their epoch's vector
  // alive through the shared_ptr they copied under the lock
  std::shared_ptr<const std::vector<int64_t>> perm;

  std::vector<Slot> slots;
  int64_t n_batches = 0;
  int64_t next_fill = 0;     // next batch index a worker will gather
  int64_t next_serve = 0;    // next batch index dl_next hands out
  int64_t epoch_gen = 0;     // bumped per start_epoch; stale fills discard
  bool closing = false;

  std::mutex mu;
  std::condition_variable cv_fill, cv_serve;
  std::vector<std::thread> workers;
};

void worker_loop(Loader *L) {
  std::unique_lock<std::mutex> lk(L->mu);
  for (;;) {
    int64_t gen = L->epoch_gen;
    // wait for a batch to gather and a free slot to gather into
    int64_t bi = -1;
    Slot *slot = nullptr;
    for (;;) {
      if (L->closing) return;
      if (L->epoch_gen == gen && L->next_fill < L->n_batches) {
        int64_t want = L->next_fill;
        Slot &s = L->slots[want % (int64_t)L->slots.size()];
        // claimable once its previous batch was served AND no (possibly
        // stale) worker is still writing its buffers
        if (!s.busy && s.index < L->next_serve) {
          bi = want;
          slot = &s;
          L->next_fill++;
          slot->index = bi;
          slot->rows = 0;  // consumers must wait until rows > 0
          slot->busy = true;
          break;
        }
      }
      L->cv_fill.wait(lk);
      gen = L->epoch_gen;
    }

    // gather outside the lock; this generation's perm is pinned by the
    // shared_ptr copy, and `busy` keeps the slot ours across epoch resets
    auto perm = L->perm;
    const int64_t start = bi * L->batch;
    const int64_t rows = std::min(L->batch, L->n - start);
    lk.unlock();
    for (int64_t r = 0; r < rows; ++r) {
      const int64_t src = (*perm)[(size_t)(start + r)];
      std::memcpy(slot->x.data() + r * L->x_row, L->x + src * L->x_row,
                  sizeof(float) * L->x_row);
      std::memcpy(slot->y.data() + r * L->y_row, L->y + src * L->y_row,
                  sizeof(float) * L->y_row);
    }
    lk.lock();
    slot->busy = false;
    if (L->epoch_gen == gen) {
      slot->rows = rows;  // publish
      L->cv_serve.notify_all();
    } else {
      // epoch restarted mid-gather: contents are stale, slot is reusable
      L->cv_fill.notify_all();
    }
  }
}

}  // namespace

extern "C" {

void *dl_open(const float *x, const float *y, int64_t n, int64_t x_row,
              int64_t y_row, int64_t batch, int64_t n_prefetch,
              int64_t n_threads) {
  if (n <= 0 || batch <= 0 || x_row <= 0 || y_row <= 0) return nullptr;
  auto *L = new Loader;
  L->x = x;
  L->y = y;
  L->n = n;
  L->x_row = x_row;
  L->y_row = y_row;
  L->batch = batch;
  L->n_batches = 0;  // no epoch yet
  if (n_prefetch < 2) n_prefetch = 2;
  L->slots.resize((size_t)n_prefetch);
  for (auto &s : L->slots) {
    s.x.resize((size_t)(batch * x_row));
    s.y.resize((size_t)(batch * y_row));
  }
  if (n_threads < 1) n_threads = 1;
  for (int64_t i = 0; i < n_threads; ++i)
    L->workers.emplace_back(worker_loop, L);
  return L;
}

void dl_start_epoch(void *h, int64_t seed) {
  auto *L = static_cast<Loader *>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  L->epoch_gen++;
  auto perm = std::make_shared<std::vector<int64_t>>((size_t)L->n);
  for (int64_t i = 0; i < L->n; ++i) (*perm)[(size_t)i] = i;
  std::mt19937_64 rng((uint64_t)seed);
  for (int64_t i = L->n - 1; i > 0; --i) {
    std::uniform_int_distribution<int64_t> d(0, i);
    std::swap((*perm)[(size_t)i], (*perm)[(size_t)d(rng)]);
  }
  L->perm = std::move(perm);
  L->n_batches = (L->n + L->batch - 1) / L->batch;
  L->next_fill = 0;
  L->next_serve = 0;
  for (auto &s : L->slots) {
    s.index = -1;  // busy flags intentionally survive (stale gathers)
    s.rows = 0;
  }
  L->cv_fill.notify_all();
}

int64_t dl_next(void *h, float *x_out, float *y_out) {
  auto *L = static_cast<Loader *>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  if (L->next_serve >= L->n_batches) return 0;  // epoch done
  const int64_t want = L->next_serve;
  Slot &s = L->slots[want % (int64_t)L->slots.size()];
  while (!(s.index == want && s.rows > 0)) {
    if (L->closing) return -1;
    L->cv_serve.wait(lk);
  }
  const int64_t rows = s.rows;
  // copy WITHOUT the lock: workers cannot claim this slot until next_serve
  // advances past it, so the consumer owns it for the duration
  lk.unlock();
  std::memcpy(x_out, s.x.data(), sizeof(float) * (size_t)(rows * L->x_row));
  std::memcpy(y_out, s.y.data(), sizeof(float) * (size_t)(rows * L->y_row));
  lk.lock();
  L->next_serve++;
  L->cv_fill.notify_all();  // the slot just freed
  return rows;
}

void dl_close(void *h) {
  auto *L = static_cast<Loader *>(h);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->closing = true;
  }
  L->cv_fill.notify_all();
  L->cv_serve.notify_all();
  for (auto &t : L->workers) t.join();
  delete L;
}

}  // extern "C"
