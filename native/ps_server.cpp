// Native parameter-server runtime.
//
// The reference's parameter server is pure Python (Flask / socket + pickle,
// elephas/parameter/server.py) — its throughput ceiling is the GIL plus
// pickle. This is the TPU build's native equivalent: a C++ TCP server holding
// the master weights as contiguous float32 buffers, applying pushed deltas
// with lock-free (hogwild) or mutex-serialized (asynchronous) semantics, one
// thread per connection, zero Python in the data path.
//
// Wire protocol (binary, little-endian):
//   'G'                                   -> reply: u32 n_arrays, then per
//                                            array u64 nelem + nelem*f32
//   'U' u32 n_arrays { u64 nelem, f32[] } -> weights[i] -= delta[i]; reply 'A'
//   'R' u32 len, id[], u32 attempt        -> register task attempt; reply 'k'
//   'T' u32 len, id[], <U payload>        -> tagged update (accumulated
//                                            under the task record); reply 'A'
//   'C' u32 len, id[]                     -> commit (drop record); reply 'A'
//   'V' <compressed payload>              -> compressed update; reply 'A'
//   'W' u32 len, id[], <compressed>       -> tagged compressed; reply 'A'
//
// Compressed payload (the python Int8/TopK codecs' wire form — decoded to
// dense f32 here, so compressed and raw clients interoperate):
//   u32 n_arrays, then per array u8 kind:
//     0 raw:  u64 nelem, f32[nelem]
//     1 int8: u64 nelem, f32 scale, i8[nelem]       (delta = q * scale)
//     2 topk: u64 nelem, u64 nnz, i64 idx[nnz], f32 vals[nnz]
//
// The R/T/C opcodes are the exactly-once retry extension, mirroring the
// Python servers (elephas_tpu/parameter/server.py register_attempt /
// commit_attempt): a task's tagged pushes accumulate under its record; when
// a NEWER attempt of the same task registers, the failed attempt's whole
// accumulated contribution is rolled back (weights += acc) before the retry
// pushes anything. Stale/duplicate registers are ignored. Abandoned records
// are bounded (oldest evicted past kMaxAttemptRecords) so dead jobs on a
// long-lived server cannot pin model-sized accumulators forever.
//
// Exposed through a minimal C API consumed via ctypes
// (elephas_tpu/parameter/native.py). Build: native/Makefile (g++ -O3
// -shared -fPIC -pthread).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr size_t kMaxAttemptRecords = 512;

struct AttemptRec {
  uint32_t attempt = 0;
  bool has_delta = false;
  std::vector<std::vector<float>> delta;  // sum of this attempt's pushes
};

struct WeightStore {
  std::vector<std::vector<float>> arrays;
  std::mutex mu;
  bool hogwild = false;
  std::unordered_map<std::string, AttemptRec> attempts;
  std::deque<std::string> attempt_order;  // insertion order, for eviction

  void apply_delta(const std::vector<std::vector<float>>& delta,
                   const std::string* task_id = nullptr) {
    if (hogwild && task_id == nullptr) {
      subtract(delta);  // racy by design: HOGWILD! semantics
    } else {
      // Tagged pushes always lock: the accumulator bookkeeping must not
      // race (hogwild's weight write staying best-effort is about the
      // weights, not the control-plane records).
      std::lock_guard<std::mutex> lock(mu);
      subtract(delta);
      if (task_id != nullptr) {
        auto it = attempts.find(*task_id);
        if (it != attempts.end()) {
          if (!it->second.has_delta) {
            it->second.delta = delta;
            it->second.has_delta = true;
          } else {
            auto& acc = it->second.delta;
            for (size_t i = 0; i < acc.size() && i < delta.size(); ++i) {
              const size_t n = std::min(acc[i].size(), delta[i].size());
              for (size_t j = 0; j < n; ++j) acc[i][j] += delta[i][j];
            }
          }
        }
      }
    }
  }

  // Mirrors the Python server's register_attempt: rollback on a newer
  // attempt, ignore stale registers, bound abandoned records.
  void register_attempt(const std::string& task_id, uint32_t attempt) {
    std::lock_guard<std::mutex> lock(mu);
    auto it = attempts.find(task_id);
    if (it == attempts.end()) {
      while (attempts.size() >= kMaxAttemptRecords && !attempt_order.empty()) {
        attempts.erase(attempt_order.front());
        attempt_order.pop_front();
      }
      attempts[task_id] = AttemptRec{attempt, false, {}};
      attempt_order.push_back(task_id);
    } else if (attempt > it->second.attempt) {
      if (it->second.has_delta) {
        for (size_t i = 0;
             i < arrays.size() && i < it->second.delta.size(); ++i) {
          float* w = arrays[i].data();
          const float* d = it->second.delta[i].data();
          const size_t n = std::min(arrays[i].size(),
                                    it->second.delta[i].size());
          for (size_t j = 0; j < n; ++j) w[j] += d[j];
        }
      }
      it->second = AttemptRec{attempt, false, {}};
    }  // else: stale/duplicate — keep the live attempt record
  }

  void commit_attempt(const std::string& task_id) {
    std::lock_guard<std::mutex> lock(mu);
    attempts.erase(task_id);
    for (auto it = attempt_order.begin(); it != attempt_order.end(); ++it) {
      if (*it == task_id) {
        attempt_order.erase(it);
        break;
      }
    }
  }

  void subtract(const std::vector<std::vector<float>>& delta) {
    for (size_t i = 0; i < arrays.size() && i < delta.size(); ++i) {
      float* w = arrays[i].data();
      const float* d = delta[i].data();
      const size_t n = std::min(arrays[i].size(), delta[i].size());
      for (size_t j = 0; j < n; ++j) w[j] -= d[j];
    }
  }

  // Snapshot under the lock (hogwild reads race by design, matching the
  // reference's lock-free GET).
  std::vector<std::vector<float>> snapshot() {
    if (hogwild) return arrays;
    std::lock_guard<std::mutex> lock(mu);
    return arrays;
  }

  // Per-array element counts, for bounding incoming frame sizes: a pushed
  // delta can never legitimately be larger than the weights it updates.
  std::vector<uint64_t> elem_bounds() {
    std::lock_guard<std::mutex> lock(mu);
    std::vector<uint64_t> out(arrays.size());
    for (size_t i = 0; i < arrays.size(); ++i) out[i] = arrays[i].size();
    return out;
  }
};

struct Server {
  WeightStore store;
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{false};
  std::thread accept_thread;
  std::vector<std::thread> conn_threads;
  std::mutex conn_mu;
};

// recv with a 200ms socket timeout on connection fds: EAGAIN retries while
// the server is running, so eps_stop() can always join connection threads
// instead of hanging on a blocked recv.
bool read_exact(int fd, void* buf, size_t n, const std::atomic<bool>* running) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      if (running != nullptr && !running->load()) return false;
      continue;
    }
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// A delta array can never legitimately exceed the weights it updates, so a
// corrupt or desynced frame claiming a huge nelem is rejected before the
// allocation instead of OOM-ing the connection thread. bounds is empty only
// before eps_set_weights, where the permissive legacy cap applies.
bool nelem_ok(uint32_t i, uint64_t nelem, const std::vector<uint64_t>& bounds) {
  if (bounds.empty()) return nelem <= (1ull << 34);
  return i < bounds.size() && nelem <= bounds[i];
}

bool read_weight_lists(int fd, std::vector<std::vector<float>>* out,
                       const std::atomic<bool>* running,
                       const std::vector<uint64_t>& bounds) {
  uint32_t n_arrays = 0;
  if (!read_exact(fd, &n_arrays, sizeof(n_arrays), running)) return false;
  if (n_arrays > 100000) return false;  // sanity bound
  out->resize(n_arrays);
  for (uint32_t i = 0; i < n_arrays; ++i) {
    uint64_t nelem = 0;
    if (!read_exact(fd, &nelem, sizeof(nelem), running)) return false;
    if (!nelem_ok(i, nelem, bounds)) return false;
    (*out)[i].resize(nelem);
    if (!read_exact(fd, (*out)[i].data(), nelem * sizeof(float), running))
      return false;
  }
  return true;
}

bool write_weight_lists(int fd, const std::vector<std::vector<float>>& arrays) {
  uint32_t n_arrays = static_cast<uint32_t>(arrays.size());
  if (!write_exact(fd, &n_arrays, sizeof(n_arrays))) return false;
  for (const auto& a : arrays) {
    uint64_t nelem = a.size();
    if (!write_exact(fd, &nelem, sizeof(nelem))) return false;
    if (!write_exact(fd, a.data(), nelem * sizeof(float))) return false;
  }
  return true;
}

bool read_compressed_lists(int fd, std::vector<std::vector<float>>* out,
                           const std::atomic<bool>* running,
                           const std::vector<uint64_t>& bounds) {
  uint32_t n_arrays = 0;
  if (!read_exact(fd, &n_arrays, sizeof(n_arrays), running)) return false;
  if (n_arrays > 100000) return false;  // sanity bound
  out->resize(n_arrays);
  for (uint32_t i = 0; i < n_arrays; ++i) {
    uint8_t kind = 0;
    if (!read_exact(fd, &kind, sizeof(kind), running)) return false;
    uint64_t nelem = 0;
    if (!read_exact(fd, &nelem, sizeof(nelem), running)) return false;
    if (!nelem_ok(i, nelem, bounds)) return false;
    auto& dst = (*out)[i];
    dst.assign(nelem, 0.0f);
    if (kind == 0) {
      if (!read_exact(fd, dst.data(), nelem * sizeof(float), running))
        return false;
    } else if (kind == 1) {
      float scale = 0.0f;
      if (!read_exact(fd, &scale, sizeof(scale), running)) return false;
      std::vector<int8_t> q(nelem);
      if (!read_exact(fd, q.data(), nelem, running)) return false;
      for (uint64_t j = 0; j < nelem; ++j)
        dst[j] = static_cast<float>(q[j]) * scale;
    } else if (kind == 2) {
      uint64_t nnz = 0;
      if (!read_exact(fd, &nnz, sizeof(nnz), running)) return false;
      if (nnz > nelem) return false;
      std::vector<int64_t> idx(nnz);
      std::vector<float> vals(nnz);
      if (!read_exact(fd, idx.data(), nnz * sizeof(int64_t), running))
        return false;
      if (!read_exact(fd, vals.data(), nnz * sizeof(float), running))
        return false;
      for (uint64_t j = 0; j < nnz; ++j) {
        if (idx[j] < 0 || static_cast<uint64_t>(idx[j]) >= nelem)
          return false;
        dst[static_cast<uint64_t>(idx[j])] = vals[j];
      }
    } else {
      return false;
    }
  }
  return true;
}

bool read_task_id(int fd, std::string* out, const std::atomic<bool>* running) {
  uint32_t len = 0;
  if (!read_exact(fd, &len, sizeof(len), running)) return false;
  if (len > 4096) return false;  // sanity bound
  out->resize(len);
  return read_exact(fd, out->data(), len, running);
}

void serve_connection_loop(Server* s, int fd) {
  while (s->running.load()) {
    char op = 0;
    if (!read_exact(fd, &op, 1, &s->running)) break;
    // Re-read per op: cheap (a short vector copy under the lock), and stays
    // correct if eps_set_weights resizes the store mid-connection.
    const std::vector<uint64_t> bounds = s->store.elem_bounds();
    if (op == 'G') {
      auto snap = s->store.snapshot();
      if (!write_weight_lists(fd, snap)) break;
    } else if (op == 'U') {
      std::vector<std::vector<float>> delta;
      if (!read_weight_lists(fd, &delta, &s->running, bounds)) break;
      s->store.apply_delta(delta);
      char ack = 'A';
      if (!write_exact(fd, &ack, 1)) break;
    } else if (op == 'R') {
      std::string task_id;
      uint32_t attempt = 0;
      if (!read_task_id(fd, &task_id, &s->running)) break;
      if (!read_exact(fd, &attempt, sizeof(attempt), &s->running)) break;
      s->store.register_attempt(task_id, attempt);
      char ack = 'k';
      if (!write_exact(fd, &ack, 1)) break;
    } else if (op == 'T') {
      std::string task_id;
      if (!read_task_id(fd, &task_id, &s->running)) break;
      std::vector<std::vector<float>> delta;
      if (!read_weight_lists(fd, &delta, &s->running, bounds)) break;
      s->store.apply_delta(delta, &task_id);
      char ack = 'A';
      if (!write_exact(fd, &ack, 1)) break;
    } else if (op == 'C') {
      std::string task_id;
      if (!read_task_id(fd, &task_id, &s->running)) break;
      s->store.commit_attempt(task_id);
      char ack = 'A';
      if (!write_exact(fd, &ack, 1)) break;
    } else if (op == 'V') {
      std::vector<std::vector<float>> delta;
      if (!read_compressed_lists(fd, &delta, &s->running, bounds)) break;
      s->store.apply_delta(delta);
      char ack = 'A';
      if (!write_exact(fd, &ack, 1)) break;
    } else if (op == 'W') {
      std::string task_id;
      if (!read_task_id(fd, &task_id, &s->running)) break;
      std::vector<std::vector<float>> delta;
      if (!read_compressed_lists(fd, &delta, &s->running, bounds)) break;
      s->store.apply_delta(delta, &task_id);
      char ack = 'A';
      if (!write_exact(fd, &ack, 1)) break;
    } else {
      break;
    }
  }
}

void serve_connection(Server* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  timeval tv{0, 200000};  // 200ms — lets threads notice eps_stop()
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  try {
    serve_connection_loop(s, fd);
  } catch (const std::exception&) {
    // A corrupt frame that slipped past the bounds (or genuine allocation
    // pressure) costs this one connection, never the training process.
  }
  ::close(fd);
}

void accept_loop(Server* s) {
  while (s->running.load()) {
    sockaddr_in addr{};
    socklen_t len = sizeof(addr);
    int fd = ::accept(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
    if (fd < 0) {
      if (!s->running.load()) break;
      continue;
    }
    std::lock_guard<std::mutex> lock(s->conn_mu);
    s->conn_threads.emplace_back(serve_connection, s, fd);
  }
}

}  // namespace

extern "C" {

void* eps_create(int hogwild) {
  auto* s = new Server();
  s->store.hogwild = hogwild != 0;
  return s;
}

// Returns the bound port (pass port=0 for an OS-assigned one), or -1.
int eps_start(void* handle, int port) {
  auto* s = static_cast<Server*>(handle);
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) return -1;
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
    return -1;
  if (::listen(s->listen_fd, 64) < 0) return -1;
  socklen_t len = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  s->port = ntohs(addr.sin_port);
  s->running.store(true);
  s->accept_thread = std::thread(accept_loop, s);
  return s->port;
}

void eps_set_weights(void* handle, int n_arrays, const int64_t* sizes,
                     const float* const* data) {
  auto* s = static_cast<Server*>(handle);
  std::lock_guard<std::mutex> lock(s->store.mu);
  s->store.arrays.resize(static_cast<size_t>(n_arrays));
  for (int i = 0; i < n_arrays; ++i) {
    s->store.arrays[i].assign(data[i], data[i] + sizes[i]);
  }
}

// Live attempt-record count (testability: the Python servers expose their
// dict directly; this is the C++ store's equivalent).
int eps_attempt_count(void* handle) {
  auto* s = static_cast<Server*>(handle);
  std::lock_guard<std::mutex> lock(s->store.mu);
  return static_cast<int>(s->store.attempts.size());
}

int eps_num_arrays(void* handle) {
  auto* s = static_cast<Server*>(handle);
  std::lock_guard<std::mutex> lock(s->store.mu);
  return static_cast<int>(s->store.arrays.size());
}

int64_t eps_array_size(void* handle, int idx) {
  auto* s = static_cast<Server*>(handle);
  std::lock_guard<std::mutex> lock(s->store.mu);
  return static_cast<int64_t>(s->store.arrays[static_cast<size_t>(idx)].size());
}

void eps_get_array(void* handle, int idx, float* out) {
  auto* s = static_cast<Server*>(handle);
  std::lock_guard<std::mutex> lock(s->store.mu);
  const auto& a = s->store.arrays[static_cast<size_t>(idx)];
  std::memcpy(out, a.data(), a.size() * sizeof(float));
}

void eps_stop(void* handle) {
  auto* s = static_cast<Server*>(handle);
  s->running.store(false);
  if (s->listen_fd >= 0) {
    ::shutdown(s->listen_fd, SHUT_RDWR);
    ::close(s->listen_fd);
    s->listen_fd = -1;
  }
  if (s->accept_thread.joinable()) s->accept_thread.join();
  std::lock_guard<std::mutex> lock(s->conn_mu);
  for (auto& t : s->conn_threads)
    if (t.joinable()) t.join();
  s->conn_threads.clear();
}

void eps_destroy(void* handle) {
  auto* s = static_cast<Server*>(handle);
  delete s;
}

}  // extern "C"
