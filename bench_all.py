"""BASELINE configs 2-5 measured through the public APIs.

``bench.py`` is the judged harness (config 1 MLP + the MFU-accounted LM);
this script measures the remaining BASELINE.md target configs:

- **2** MNIST-CNN through ``SparkModel`` in synchronous AND async/hogwild
  modes — throughput plus the convergence envelope (same model/data/epochs,
  final test accuracy per mode: async staleness trades accuracy for
  pipeline overlap; the envelope quantifies it).
- **3** IMDB-LSTM through the ``ElephasEstimator`` Spark-ML pipeline.
- **4** ``SparkMLlibModel`` on LabeledPoint RDDs (Boston-shaped regression
  + Iris multiclass).
- **5** ``HyperParamModel`` distributed search wall-clock.

Prints ONE JSON line ``{"configs": {...}}`` (stderr carries progress).
Config 2 reports steady-state throughput (a warmup fit absorbs compile);
configs 3-5 are one-shot API flows, so their wall-clock INCLUDES compile —
stated in the output rather than hidden.

Datasets are the examples' offline synthetic fallbacks (``examples/_datasets``)
— identical shapes/dtypes to the real ones, no network. Knobs:
``BENCH_ALL_SAMPLES``, ``BENCH_ALL_EPOCHS``, ``BENCH_ALL_EVALS``.
"""

import json
import os
import sys
import time

os.environ.setdefault("KERAS_BACKEND", "jax")
_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_REPO, "examples"))


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def _accuracy(model_like, x, y):
    import numpy as np

    preds = np.asarray(model_like.predict(x))
    return float((preds.argmax(1) == y.argmax(1)).mean())


def config2_mnist_cnn():
    """Sync vs async vs hogwild CNN: samples/sec/chip + accuracy envelope.

    Async/hogwild each measure BOTH schedules: ``compiled``
    (``parameter_server_mode='jax'`` — the TPU-first path, whole run in one
    XLA program with documented one-period staleness) and ``host`` (live
    parameter server through HTTP, the reference's semantics). The envelope
    is only meaningful off the accuracy ceiling, so the default geometry is
    ONE epoch (BENCH_ALL_C2_EPOCHS to override) — at 3 epochs every mode
    used to hit test accuracy 1.000 and the measured envelope was vacuously
    0.000.
    """
    import jax
    import numpy as np

    from elephas_tpu import SparkModel
    from elephas_tpu.data import SparkContext
    from elephas_tpu.utils import to_simple_rdd

    from _datasets import load_mnist
    from mnist_cnn_async import make_cnn

    n = int(os.environ.get("BENCH_ALL_SAMPLES", 8192))
    epochs = int(os.environ.get(
        "BENCH_ALL_C2_EPOCHS", os.environ.get("BENCH_ALL_EPOCHS", 1)))
    n_dev = jax.local_device_count()
    n_workers = max(n_dev, 2)

    (x_tr, y_tr), (x_te, y_te) = load_mnist(n_train=n, n_test=1024)
    sc = SparkContext(master=f"local[{n_workers}]", appName="bench_all_c2")
    rdd = to_simple_rdd(sc, x_tr, y_tr, num_slices=n_workers)

    cells = (
        ("sync", "synchronous", "jax"),
        ("async_compiled", "asynchronous", "jax"),
        ("async_host", "asynchronous", "http"),
        ("hogwild_compiled", "hogwild", "jax"),
        ("hogwild_host", "hogwild", "http"),
    )
    out = {}
    for name, mode, ps_mode in cells:
        sm = SparkModel(make_cnn(), mode=mode, frequency="epoch",
                        num_workers=n_workers, merge="mean",
                        parameter_server_mode=ps_mode)
        sm.fit(rdd, epochs=epochs, batch_size=64, verbose=0,
               validation_split=0.0)  # warmup: compile at this geometry
        acc = _accuracy(sm, x_te, y_te)  # accuracy after the FIRST fit:
        # the envelope compares one pass from identical fresh weights
        t0 = time.perf_counter()
        sm.fit(rdd, epochs=epochs, batch_size=64, verbose=0,
               validation_split=0.0)
        dt = time.perf_counter() - t0
        sps_chip = n * epochs / dt / n_dev
        out[name] = {
            "samples_per_sec_per_chip": round(sps_chip, 1),
            "test_accuracy": round(acc, 4),
        }
        log(f"config2 {name} ({mode}/{ps_mode}): {sps_chip:,.0f} "
            f"samples/sec/chip steady-state, first-fit acc {acc:.4f}")
    sc.stop()
    # convergence envelope: each cell's first-fit accuracy relative to sync
    sync_acc = out["sync"]["test_accuracy"]
    for name in out:
        if name != "sync":
            out[name]["accuracy_vs_sync"] = round(
                out[name]["test_accuracy"] - sync_acc, 4
            )
    return out


def config3_imdb_lstm():
    """ElephasEstimator pipeline on IMDB-shaped data.

    Two figures since round 5 (the config-2/6 marginal discipline applied
    to the L5 skins): the one-shot wall-clock incl. compile (the honest
    DataFrame-API first-use number), and the MARGINAL steady-state rate
    from differencing fits at two epoch counts after per-geometry warmups
    — per-fit fixed cost (compile, DataFrame conversion, weight
    round-trips) cancels, leaving the compiled program's per-step rate.
    """
    import jax
    import numpy as np

    from elephas_tpu import ElephasEstimator
    from elephas_tpu.data import Row, SparkSession
    from elephas_tpu.ml import Pipeline
    from elephas_tpu.mllib import Vectors

    from _datasets import load_imdb
    from ml_pipeline_imdb_lstm import MAXLEN, VOCAB, make_lstm

    n = int(os.environ.get("BENCH_ALL_SAMPLES", 8192)) // 4
    epochs = int(os.environ.get("BENCH_ALL_EPOCHS", 3))
    n_dev = jax.local_device_count()

    spark = SparkSession.builder.master(f"local[{n_dev}]").appName(
        "bench_all_c3").getOrCreate()
    (x_tr, y_tr), (x_te, y_te) = load_imdb(n_train=n, n_test=512,
                                           maxlen=MAXLEN, vocab=VOCAB)
    df = spark.createDataFrame([
        Row(features=Vectors.dense(x.astype("float64")), label=float(y[0]))
        for x, y in zip(x_tr, y_tr)
    ])
    est = ElephasEstimator()
    est.set_keras_model(make_lstm())
    est.set_categorical(False)
    est.set_num_workers(n_dev)
    est.set_epochs(epochs)
    est.set_batch_size(32)  # partitions must exceed the batch (skip quirk)
    est.set_validation_split(0.0)
    est.set_mode("synchronous")
    est.set_parameter_server_mode("jax")

    t0 = time.perf_counter()
    fitted = Pipeline(stages=[est]).fit(df)
    dt = time.perf_counter() - t0

    test_df = spark.createDataFrame([
        Row(features=Vectors.dense(x.astype("float64")), label=float(y[0]))
        for x, y in zip(x_te, y_te)
    ])
    rows = fitted.transform(test_df).collect()
    preds = np.array([r.prediction for r in rows])
    labels = np.array([r.label for r in rows])
    acc = float(((preds > 0.5) == (labels > 0.5)).mean())

    # marginal steady-state: difference estimator fits at two epoch
    # counts (each epoch count is its own compiled program — warm up
    # both geometries first, then best-of-2)
    e_lo, e_hi = 1, 1 + 2 * epochs

    def best_est_fit(n_epochs, reps=2):
        est.set_epochs(n_epochs)
        Pipeline(stages=[est]).fit(df)  # warmup/compile this geometry
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            Pipeline(stages=[est]).fit(df)
            best = min(best, time.perf_counter() - t0)
        return best

    t_lo = best_est_fit(e_lo)
    t_hi = best_est_fit(e_hi)
    # same timer-noise floor _marginal_fit_sps enforces: a differenced
    # wall below resolution must report None, not a fantasy rate
    sps_marginal = (
        n * (e_hi - e_lo) / (t_hi - t_lo)
        if t_hi - t_lo >= _MARGINAL_FLOOR_S else None)
    log(f"config3 imdb-lstm pipeline: {n * epochs / dt:,.0f} samples/sec "
        f"(incl. compile); marginal steady-state "
        + (f"{sps_marginal:,.0f} samples/sec" if sps_marginal
           else "below timer floor")
        + f"; acc {acc:.4f}")
    return {
        "samples_per_sec_incl_compile": round(n * epochs / dt, 1),
        "samples_per_sec_marginal":
            round(sps_marginal, 1) if sps_marginal else None,
        "test_accuracy": round(acc, 4),
    }


_MARGINAL_FLOOR_S = 0.05  # differenced wall below this is timer noise


def _marginal_fit_sps(m, fit_kwargs, n_samples, e_lo, e_hi, reps=2):
    """Round-5 shared helper: marginal steady-state samples/sec from
    differencing fits at two epoch counts (per-geometry warmups; per-fit
    fixed cost cancels). Returns ``None`` when the differenced wall is
    below the timer-noise floor — tiny-dataset fits can complete their
    extra epochs faster than the measurement resolves, and a clamped
    division would report a fantasy number."""
    def best(n_epochs):
        m.fit(epochs=n_epochs, **fit_kwargs)  # warmup/compile
        b = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            m.fit(epochs=n_epochs, **fit_kwargs)
            b = min(b, time.perf_counter() - t0)
        return b

    t_lo, t_hi = best(e_lo), best(e_hi)
    if t_hi - t_lo < _MARGINAL_FLOOR_S:
        return None
    return n_samples * (e_hi - e_lo) / (t_hi - t_lo)


def config4_mllib():
    """SparkMLlibModel: Boston-shaped regression MSE + Iris accuracy —
    one-shot wall incl. compile AND (round 5) the marginal steady-state
    rate via the config-2/6 differencing discipline."""
    import jax
    import keras
    import numpy as np

    from elephas_tpu import SparkMLlibModel
    from elephas_tpu.data import SparkContext
    from elephas_tpu.utils import to_labeled_point

    from _datasets import load_boston, load_iris

    n_dev = jax.local_device_count()
    epochs = int(os.environ.get("BENCH_ALL_EPOCHS", 3)) * 7
    sc = SparkContext(master=f"local[{n_dev}]", appName="bench_all_c4")

    # regression
    x, y = load_boston()
    x = (x - x.mean(0)) / (x.std(0) + 1e-6)
    y_n = (y - y.mean()) / y.std()
    lp = to_labeled_point(sc, x, y_n, categorical=False)
    reg = keras.Sequential(
        [keras.layers.Dense(32, activation="relu"), keras.layers.Dense(1)]
    )
    reg.build((None, x.shape[1]))
    reg.compile(optimizer="adam", loss="mse")
    m = SparkMLlibModel(reg, mode="synchronous", num_workers=n_dev)
    t0 = time.perf_counter()
    m.fit(lp, epochs=epochs, batch_size=32, validation_split=0.0,
          categorical=False)
    dt_reg = time.perf_counter() - t0
    mse = float(np.mean(
        (np.asarray(m.predict(x)).ravel() - y_n) ** 2
    ))

    # multiclass (load_iris yields class ids)
    xi, yi = load_iris()
    xi = (xi - xi.mean(0)) / (xi.std(0) + 1e-6)
    lpi = to_labeled_point(sc, xi, yi, categorical=True)
    clf = keras.Sequential([
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(3, activation="softmax"),
    ])
    clf.build((None, xi.shape[1]))
    clf.compile(optimizer="adam", loss="categorical_crossentropy",
                metrics=["accuracy"])
    mc = SparkMLlibModel(clf, mode="synchronous", num_workers=n_dev)
    t0 = time.perf_counter()
    mc.fit(lpi, epochs=epochs, batch_size=16, validation_split=0.0,
           categorical=True, nb_classes=3)
    dt_cls = time.perf_counter() - t0
    acc = float(
        (np.asarray(mc.predict(xi)).argmax(1) == yi.astype(int)).mean()
    )
    # marginal steady-state for both skins (fixed per-fit cost cancels)
    sps_reg = _marginal_fit_sps(
        m, dict(labeled_points=lp, batch_size=32, validation_split=0.0,
                categorical=False), len(x), 1, 1 + 20 * epochs)
    sps_cls = _marginal_fit_sps(
        mc, dict(labeled_points=lpi, batch_size=16, validation_split=0.0,
                 categorical=True, nb_classes=3), len(xi), 1,
        1 + 20 * epochs)
    sc.stop()
    fmt = lambda v: f"{v:,.0f} sps" if v else "below timer floor"
    log(f"config4 boston mse {mse:.4f} ({dt_reg:.1f}s incl. compile; "
        f"marginal {fmt(sps_reg)}), iris acc {acc:.4f} "
        f"({dt_cls:.1f}s; marginal {fmt(sps_cls)})")
    return {
        "boston_mse_normalized": round(mse, 4),
        "boston_fit_seconds_incl_compile": round(dt_reg, 2),
        "boston_samples_per_sec_marginal":
            round(sps_reg, 1) if sps_reg else None,
        "iris_accuracy": round(acc, 4),
        "iris_fit_seconds_incl_compile": round(dt_cls, 2),
        "iris_samples_per_sec_marginal":
            round(sps_cls, 1) if sps_cls else None,
    }


def config5_hyperparam():
    """Distributed TPE search wall-clock (device-slice fan-out).

    Round 5 adds the marginal seconds/trial: differencing searches at two
    ``max_evals`` budgets cancels the fixed setup (context, first-model
    compile). Per-trial recompiles remain — the search space varies layer
    sizes, so each trial IS a new geometry; the marginal figure prices a
    trial's true cost, not the harness's."""
    from elephas_tpu import HyperParamModel
    from elephas_tpu.data import SparkContext

    from hyperparam_optimization import data, model

    evals = int(os.environ.get("BENCH_ALL_EVALS", 2))
    workers = 4
    sc = SparkContext(master=f"local[{workers}]", appName="bench_all_c5")
    hp = HyperParamModel(sc, num_workers=workers)
    t0 = time.perf_counter()
    trials = hp.compute_trials(model=model, data=data, max_evals=evals)
    dt = time.perf_counter() - t0
    e_hi = 3 * evals
    t0 = time.perf_counter()
    trials_hi = hp.compute_trials(model=model, data=data, max_evals=e_hi)
    dt_hi = time.perf_counter() - t0
    n_lo = len(trials)
    n_hi = len(trials_hi)
    marg_trial = (dt_hi - dt) / max(n_hi - n_lo, 1)
    sc.stop()
    ok = [t for t in trials if t["status"] == "ok"]
    best = min(t["loss"] for t in ok)
    devices = sorted({t["device"] for t in trials})
    log(f"config5 search: {n_lo} trials / {workers} workers in "
        f"{dt:.1f}s (incl. compile); marginal {marg_trial:.2f} s/trial "
        f"({n_hi - n_lo} extra trials in {dt_hi - dt:.1f}s); best loss "
        f"{best:.4f}, devices {devices}")
    return {
        "trials": n_lo,
        "workers": workers,
        "wall_seconds_incl_compile": round(dt, 2),
        "marginal_seconds_per_trial": round(marg_trial, 2),
        "best_loss": round(best, 4),
        "distinct_devices": len(devices),
    }


def conv_train_flops_per_sample(model) -> float:
    """Analytic training FLOPs per sample for a Keras conv net — matmul/conv
    FLOPs only (the MFU convention, same rigor as ``bench.py``'s
    ``lm_train_flops_per_token``): a Conv2D costs ``2·kh·kw·cin·cout·Ho·Wo``
    forward (each output pixel is a ``kh·kw·cin``-deep dot), a Dense
    ``2·cin·cout``; training ≈ 3x forward (backward is two conv-sized
    contractions). BN/ReLU/pool are bandwidth, not FLOPs, and are excluded.
    """
    import keras

    fwd = 0.0
    for layer in model.layers:
        if isinstance(layer, keras.layers.Conv2D):
            kh, kw = layer.kernel_size
            cin = int(layer.input.shape[-1])
            _, ho, wo, cout = layer.output.shape
            fwd += 2.0 * kh * kw * cin * cout * ho * wo
        elif isinstance(layer, keras.layers.Dense):
            fwd += 2.0 * int(layer.input.shape[-1]) * int(layer.units)
    return 3.0 * fwd


def config6_conv_mfu():
    """FLOPs-accounted ResNet-50 training throughput + MFU, remat on/off.

    The LM benchmark carries the chip's efficiency story; this config gives
    conv workloads the same rigor: analytic conv FLOPs (above), steady-state
    samples/sec through the compiled engine, MFU against the spec-sheet
    peak, and the cost of rematerialization (recompute-in-backward) on the
    identical geometry. Gated to TPU by default (BENCH_ALL_CONV=1 forces —
    an MFU against a CPU has no meaning). Input size via
    BENCH_ALL_CONV_IMAGE (default 64: CIFAR-class images keep the relay
    compile tractable; the per-sample FLOPs accounting makes the number
    comparable across sizes).
    """
    import jax
    import keras
    import numpy as np

    from elephas_tpu import SparkModel
    from elephas_tpu.data import SparkContext
    from elephas_tpu.utils import to_simple_rdd

    gate = os.environ.get("BENCH_ALL_CONV", "auto")
    on_tpu = jax.devices()[0].platform == "tpu"
    if gate == "0" or (gate == "auto" and not on_tpu):
        log("config6 conv: skipped (not on TPU; BENCH_ALL_CONV=1 forces)")
        return {"skipped": "not on TPU"}

    from bench import peak_bf16_flops

    img = int(os.environ.get("BENCH_ALL_CONV_IMAGE", 64))
    n = int(os.environ.get("BENCH_ALL_CONV_SAMPLES", 2048))
    batch = int(os.environ.get("BENCH_ALL_CONV_BATCH", 64))
    n_dev = jax.local_device_count()

    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=(n, img, img, 3)).astype("float32")
    y = np.eye(10, dtype="float32")[rng.integers(0, 10, size=n)]
    sc = SparkContext(master=f"local[{n_dev}]", appName="bench_all_c6")
    rdd = to_simple_rdd(sc, x, y, num_slices=n_dev)

    def make_resnet():
        model = keras.applications.ResNet50(
            weights=None, input_shape=(img, img, 3), classes=10)
        model.compile(optimizer="sgd", loss="categorical_crossentropy")
        return model

    flops_sample = conv_train_flops_per_sample(make_resnet())
    peak = peak_bf16_flops(jax.devices()[0])
    out = {"flops_per_sample": round(flops_sample),
           "image": img, "batch": batch}

    # A fit's wall-clock on a relay-attached chip is dominated by the
    # per-fit weight round-trip (the ~100 MB ResNet-50 state moves at
    # ~4 MB/s through this tunnel — measured; a directly-attached host
    # moves it in tens of ms). So two figures are reported: raw
    # steady-state samples/sec (environment-honest), and the MARGINAL
    # per-step cost from differencing a 1-epoch and a 3-epoch fit — the
    # fixed per-fit transfer cancels, leaving the compiled program's
    # actual per-step time, which is what MFU is computed from.
    e_lo, e_hi = 1, 3

    def best_fit_time(sm, epochs, reps=2):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            sm.fit(rdd, epochs=epochs, batch_size=batch, verbose=0,
                   validation_split=0.0)
            best = min(best, time.perf_counter() - t0)
        return best

    # Match the engine's actual schedule: S = ceil(per-worker samples / B)
    # (engine.py pads the last batch), and never 0 — a huge BENCH_ALL_CONV
    # batch must not zero-divide the marginal-step math.
    steps_per_epoch = max(1, -(-(n // n_dev) // batch))
    for name, remat in (("remat_off", False), ("remat_on", True)):
        sm = SparkModel(make_resnet(), mode="synchronous", num_workers=n_dev,
                        remat=remat)
        sm.fit(rdd, epochs=e_lo, batch_size=batch, verbose=0,
               validation_split=0.0)  # warmup/compile @ e_lo
        t_lo = best_fit_time(sm, e_lo)
        sm.fit(rdd, epochs=e_hi, batch_size=batch, verbose=0,
               validation_split=0.0)  # warmup/compile @ e_hi
        t_hi = best_fit_time(sm, e_hi)
        sps_raw = n * e_lo / t_lo / n_dev
        step_ms = max(t_hi - t_lo, 1e-9) / ((e_hi - e_lo) * steps_per_epoch)
        sps_marginal = batch / step_ms
        cell = {
            "samples_per_sec_per_chip_raw": round(sps_raw, 1),
            "marginal_step_ms": round(step_ms * 1e3, 1),
            "samples_per_sec_per_chip_marginal": round(sps_marginal, 1),
        }
        if peak:
            cell["mfu_marginal"] = round(
                flops_sample * sps_marginal / peak, 4)
        out[name] = cell
        log(f"config6 resnet50@{img} {name}: raw {sps_raw:,.0f} sps/chip; "
            f"marginal {step_ms * 1e3:.0f} ms/step = {sps_marginal:,.0f} "
            f"sps/chip, {flops_sample * sps_marginal / 1e12:.1f} TFLOP/s"
            + (f", MFU {cell['mfu_marginal'] * 100:.1f}%" if peak else ""))
    sc.stop()
    return out


def config7_speculative():
    """Speculative decoding measured on a trained draft/target pair.

    Random-weight models never agree, so acceptance is meaningless there;
    this config trains BOTH models on the same synthetic Markov language
    (next token = deterministic map of the current with prob q, else
    uniform noise — learnable in a few hundred steps) and then measures,
    for greedy decoding of held-out prompts:

    - ``acceptance_rate``: accepted draft proposals / proposed;
    - ``seq_pass_reduction``: n_new / verify rounds — the ALGORITHMIC win
      (sequential target passes saved), dispatch-environment-independent;
    - measured wall tokens/sec for plain cached decode vs speculative.

    Since round 4 the greedy round loop is ONE compiled while_loop
    (``_spec_rollout_device``): dispatches per emitted token < 1, so wall
    clock measures the on-chip trade directly. Two wall cells: the small
    trained pair (d512 target — launch-bound decode, where speculation
    buys little by construction) and a SERVING-SCALE pair (d2048/L8
    target, the judged-LM geometry, whose decode step is weight-bandwidth
    bound — the regime speculative decoding exists for). TPU-gated
    (BENCH_ALL_SPEC=1 forces).
    """
    import jax
    import numpy as np
    import optax

    gate = os.environ.get("BENCH_ALL_SPEC", "auto")
    on_tpu = jax.devices()[0].platform == "tpu"
    if gate == "0" or (gate == "auto" and not on_tpu):
        log("config7 speculative: skipped (not on TPU; BENCH_ALL_SPEC=1 "
            "forces)")
        return {"skipped": "not on TPU"}

    from elephas_tpu.models import (
        TransformerLM, build_lm_train_step, build_mesh_sp, make_lm_batches,
        shard_lm_batch,
    )

    V, T, q = 256, 128, 0.9
    steps = int(os.environ.get("BENCH_ALL_SPEC_STEPS", 150))
    n_new = int(os.environ.get("BENCH_ALL_SPEC_NEW", 128))
    spec_k = int(os.environ.get("BENCH_ALL_SPEC_K", 4))
    rng = np.random.default_rng(0)

    def chain(b, t, seed):
        r = np.random.default_rng(seed)
        rows = np.empty((b, t), np.int64)
        rows[:, 0] = r.integers(0, V, size=b)
        nxt = (np.arange(V) * 7 + 13) % V  # the deterministic successor map
        for j in range(1, t):
            noise = r.integers(0, V, size=b)
            take = r.random(b) < q
            rows[:, j] = np.where(take, nxt[rows[:, j - 1]], noise)
        return rows

    mesh = build_mesh_sp(data=1, seq=1)

    def train(model, seed, n_steps, lr=3e-3):
        step, opt_init = build_lm_train_step(
            model, mesh, optax.adam(lr), attn="flash")
        params = model.shard_params(mesh, model.init(seed=seed))
        state = opt_init(params)
        loss = None
        for i in range(n_steps):
            rows = chain(16, T + 1, seed=1000 + i)
            batch = shard_lm_batch(mesh, *make_lm_batches(rows))
            params, state, loss = step(params, state, *batch)
        log(f"config7: trained {n_steps} steps "
            f"(final loss {float(loss):.3f})")
        return params

    horizon = 32 + n_new + spec_k + 2
    target = TransformerLM(vocab=V, d_model=512, n_heads=4, n_layers=4,
                           d_ff=2048, max_len=max(T, horizon),
                           compute_dtype="bfloat16", pos_encoding="rotary")
    draftm = TransformerLM(vocab=V, d_model=128, n_heads=1, n_layers=2,
                           d_ff=512, max_len=max(T, horizon),
                           compute_dtype="bfloat16", pos_encoding="rotary")
    # The draft trains on a THIRD of the steps: a fully-converged draft on
    # this near-deterministic language accepts ~100% (both models argmax
    # the successor map), which demonstrates the mechanism but never
    # exercises rejection — an undertrained draft gives an acceptance rate
    # that actually discriminates.
    t_params = train(target, 0, steps)
    d_params = train(draftm, 1, max(steps // 3, 1))

    prompt = chain(1, 32, seed=99).astype(np.int32)

    # plain cached decode (one compiled scan) — warmup then best-of-2
    plain = None
    t_plain = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        plain = np.asarray(target.generate(t_params, prompt, n_new))
        dt = time.perf_counter() - t0
        t_plain = min(t_plain, dt)  # first rep absorbs compile
    # speculative — same schedule
    stats = None
    t_spec = float("inf")
    spec = None
    for _ in range(3):
        t0 = time.perf_counter()
        spec, stats = target.generate_speculative(
            t_params, prompt, n_new, draftm, d_params, spec_k=spec_k,
            with_stats=True)
        dt = time.perf_counter() - t0
        t_spec = min(t_spec, dt)
    spec = np.asarray(spec)
    agree = bool((spec == plain).all())  # greedy: must match the target

    # Sampled cell: greedy acceptance is STRUCTURALLY ~1.0 on this language
    # (both models argmax the same learned successor map), so the rejection
    # rule never fires; at temperature the acceptance rate is the measured
    # draft/target distribution overlap — the discriminating number.
    _, s_stats = target.generate_speculative(
        t_params, prompt, n_new, draftm, d_params, spec_k=spec_k,
        temperature=0.8, with_stats=True)

    out = {
        "acceptance_rate_greedy": round(stats["acceptance_rate"], 4),
        "acceptance_rate_sampled_t0.8": round(
            s_stats["acceptance_rate"], 4),
        "rounds": stats["rounds"],
        "n_new": n_new,
        "seq_pass_reduction": round(n_new / stats["rounds"], 2),
        "seq_pass_reduction_sampled": round(
            n_new / s_stats["rounds"], 2),
        "spec_k": spec_k,
        "plain_tokens_per_sec": round(n_new / t_plain, 1),
        "spec_tokens_per_sec": round(n_new / t_spec, 1),
        "wall_speedup": round(t_plain / t_spec, 3),
        "greedy_output_matches_target": agree,
    }
    log(f"config7: acceptance {out['acceptance_rate_greedy']:.2%} greedy / "
        f"{out['acceptance_rate_sampled_t0.8']:.2%} sampled, "
        f"{stats['rounds']} verify rounds for {n_new} tokens "
        f"({out['seq_pass_reduction']}x fewer sequential target passes; "
        f"{out['seq_pass_reduction_sampled']}x sampled), "
        f"wall {out['plain_tokens_per_sec']:.0f} -> "
        f"{out['spec_tokens_per_sec']:.0f} tok/s "
        f"(x{out['wall_speedup']}), match={agree}")

    # -- serving-scale cell: big (weight-bandwidth-bound) target ----------
    # d2048/L8 needs ~300 adam(1e-3) steps to learn the Markov language
    # (loss ~0.9; an undertrained target disagrees with ANY draft and
    # acceptance collapses). Wall clock is measured two ways: raw at
    # n_big tokens, and MARGINAL (differencing 64- and n_big-token
    # rollouts) so the ~100 ms per-call relay overhead cancels — the same
    # honest-metric discipline as the judged MNIST figure.
    big_steps = int(os.environ.get("BENCH_ALL_SPEC_BIG_STEPS", 300))
    n_big = int(os.environ.get("BENCH_ALL_SPEC_BIG_NEW", 512))
    bh = 64 + n_big + spec_k + 2
    big = TransformerLM(vocab=V, d_model=2048, n_heads=8, n_layers=8,
                        d_ff=8192, max_len=max(T, bh),
                        compute_dtype="bfloat16", pos_encoding="rotary",
                        tie_embeddings=True)
    bdraft = TransformerLM(vocab=V, d_model=256, n_heads=2, n_layers=2,
                           d_ff=1024, max_len=max(T, bh),
                           compute_dtype="bfloat16", pos_encoding="rotary")
    b_params = train(big, 2, big_steps, lr=1e-3)
    bd_params = train(bdraft, 3, max(big_steps // 3, 1))

    def best_wall(fn):
        best, result = float("inf"), None
        for _ in range(3):
            t0 = time.perf_counter()
            result = np.asarray(fn())
            best = min(best, time.perf_counter() - t0)
        return best, result

    t_plain_64, _ = best_wall(lambda: big.generate(b_params, prompt, 64))
    tb_plain, bplain = best_wall(
        lambda: big.generate(b_params, prompt, n_big))
    t_spec_64, _ = best_wall(lambda: big.generate_speculative(
        b_params, prompt, 64, bdraft, bd_params, spec_k=spec_k))
    tb_spec, bspec = best_wall(lambda: big.generate_speculative(
        b_params, prompt, n_big, bdraft, bd_params, spec_k=spec_k))
    _, bstats = big.generate_speculative(
        b_params, prompt, n_big, bdraft, bd_params, spec_k=spec_k,
        with_stats=True)
    # Sampled cell (round 5): the f32 rejection rule now runs in the SAME
    # compiled round loop — measured with the same marginal differencing.
    # Tokens/sec also reflects the LOWER sampled acceptance (more verify
    # rounds — semantics, not dispatch), so the per-ROUND time is the
    # apples-to-apples device-loop comparison.
    t_sspec_64, _ = best_wall(lambda: big.generate_speculative(
        b_params, prompt, 64, bdraft, bd_params, spec_k=spec_k,
        temperature=0.8, seed=1))
    tb_sspec, _ = best_wall(lambda: big.generate_speculative(
        b_params, prompt, n_big, bdraft, bd_params, spec_k=spec_k,
        temperature=0.8, seed=1))
    _, sstats_64 = big.generate_speculative(
        b_params, prompt, 64, bdraft, bd_params, spec_k=spec_k,
        temperature=0.8, seed=1, with_stats=True)
    _, sbstats = big.generate_speculative(
        b_params, prompt, n_big, bdraft, bd_params, spec_k=spec_k,
        temperature=0.8, seed=1, with_stats=True)
    bagree = bool((np.asarray(bspec) == bplain).all())
    marg = n_big - 64
    m_plain = (tb_plain - t_plain_64) / marg * 1e3  # ms/token
    m_spec = (tb_spec - t_spec_64) / marg * 1e3
    m_sspec = (tb_sspec - t_sspec_64) / marg * 1e3
    _, bstats_64 = big.generate_speculative(
        b_params, prompt, 64, bdraft, bd_params, spec_k=spec_k,
        with_stats=True)
    g_round_ms = (tb_spec - t_spec_64) / max(
        bstats["rounds"] - bstats_64["rounds"], 1) * 1e3
    s_round_ms = (tb_sspec - t_sspec_64) / max(
        sbstats["rounds"] - sstats_64["rounds"], 1) * 1e3
    out["serving_scale"] = {
        "target": "d2048xL8xF8192-bf16",
        "draft": "d256xL2xF1024-bf16",
        "n_new": n_big,
        "acceptance_rate_greedy": round(bstats["acceptance_rate"], 4),
        "rounds": bstats["rounds"],
        "plain_tokens_per_sec": round(n_big / tb_plain, 1),
        "spec_tokens_per_sec": round(n_big / tb_spec, 1),
        "wall_speedup": round(tb_plain / tb_spec, 3),
        "marginal_ms_per_token_plain": round(m_plain, 3),
        "marginal_ms_per_token_spec": round(m_spec, 3),
        "marginal_wall_speedup": (
            round(m_plain / m_spec, 2) if m_spec > 0 else None),
        "greedy_output_matches_target": bagree,
        "sampled_t0.8": {
            "acceptance_rate": round(sbstats["acceptance_rate"], 4),
            "rounds": sbstats["rounds"],
            "marginal_ms_per_token": round(m_sspec, 3),
            "marginal_wall_speedup_vs_plain": (
                round(m_plain / m_sspec, 2) if m_sspec > 0 else None),
            "round_ms_greedy": round(g_round_ms, 2),
            "round_ms_sampled": round(s_round_ms, 2),
            "round_time_ratio_sampled_over_greedy": (
                round(s_round_ms / g_round_ms, 3) if g_round_ms > 0
                else None),
        },
    }
    s = out["serving_scale"]
    ss = s["sampled_t0.8"]
    log(f"config7 serving-scale: acceptance "
        f"{s['acceptance_rate_greedy']:.2%}, wall "
        f"{s['plain_tokens_per_sec']:.0f} -> "
        f"{s['spec_tokens_per_sec']:.0f} tok/s (x{s['wall_speedup']}); "
        f"marginal {m_plain:.2f} -> {m_spec:.2f} ms/tok "
        f"(x{s['marginal_wall_speedup']}), match={bagree}; sampled t0.8 "
        f"{m_sspec:.2f} ms/tok (x{ss['marginal_wall_speedup_vs_plain']} "
        f"vs plain), round {s_round_ms:.1f} vs greedy {g_round_ms:.1f} ms "
        f"(x{ss['round_time_ratio_sampled_over_greedy']})")
    return out


def config8_moe_lm():
    """Mixtral-shaped MoE LM training throughput + model-FLOPs MFU.

    One chip holds ALL experts (the expert axis has size 1 here; multi-chip
    shards them — ``dryrun_multichip``), so this measures the routing
    machinery's single-chip cost: tokens/sec and an MFU whose denominator
    counts MODEL FLOPs only (attention + router + the k ACTIVE experts per
    token, swiglu-aware) — dispatch (index-form slot gather since round 4;
    see docs/PERFORMANCE.md config 8) is counted as OVERHEAD, not useful
    FLOPs, so the gap between this MFU and the dense LM's at equal active
    FLOPs IS the price of routing. TPU-gated (BENCH_ALL_MOE=1 forces).
    """
    import jax
    import numpy as np
    import optax

    gate = os.environ.get("BENCH_ALL_MOE", "auto")
    on_tpu = jax.devices()[0].platform == "tpu"
    if gate == "0" or (gate == "auto" and not on_tpu):
        log("config8 moe: skipped (not on TPU; BENCH_ALL_MOE=1 forces)")
        return {"skipped": "not on TPU"}

    from elephas_tpu.models import (
        MoETransformerLM, adam_compact, build_lm_train_step, build_mesh_sp,
        make_lm_batches, shard_lm_batch,
    )

    D, L, H, F = 1024, 4, 8, 4096
    E, K = 8, 2
    V, T, B = 8192, 1024, 4
    steps, reps = 10, 3
    # param_dtype="bfloat16": expert stacks STORED bf16 (router/attention
    # stay f32; adam math stays f32 via adam_compact upcasts). Kills the
    # dominant per-step f32→bf16 convert traffic — measured −10.1 ms/step
    # at this geometry with the loss trajectory matching f32 storage to
    # 5 decimals at step 2 (docs/PERFORMANCE.md config 8).
    model = MoETransformerLM(
        vocab=V, d_model=D, n_heads=H, n_layers=L, d_ff=F, max_len=T,
        n_experts=E, k=K, capacity_factor=1.25, compute_dtype="bfloat16",
        pos_encoding="rotary", tie_embeddings=True, activation="swiglu",
        norm="rmsnorm", ffn_bias=False, param_dtype="bfloat16",
    )
    mesh = build_mesh_sp(data=1, seq=1)
    step, opt_init = build_lm_train_step(model, mesh, adam_compact(1e-3),
                                         attn="flash")
    params = model.shard_params(mesh, model.init(seed=0))
    state = opt_init(params)
    rows = np.random.default_rng(0).integers(0, V, size=(B, T + 1))
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))

    log(f"config8 moe: d{D} L{L} E{E} k{K} F{F} T{T} B{B} bf16 swiglu "
        "(compiling...)")
    for _ in range(2):
        params, state, loss = step(params, state, *batch)
    float(loss)

    best = float("inf")
    for rep in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            params, state, loss = step(params, state, *batch)
        last = float(loss)
        dt = time.perf_counter() - t0
        log(f"config8 rep {rep}: {dt / steps * 1e3:.1f} ms/step")
        best = min(best, dt)
    assert np.isfinite(last), last

    # model FLOPs/token (fwd, x3 train): attention qkvo + causal dots,
    # router D*E, k active swiglu experts (3 matmuls each), tied head
    attn = L * (2 * (2 * D * D + 2 * D * D) + 4 * D * (T + 1) / 2)
    ffn = L * (2 * D * E + K * 3 * 2 * D * F)
    flops_tok = 3.0 * (attn + ffn + 2 * D * V)
    tok_s = B * T * steps / best
    import bench as _bench
    peak = _bench.peak_bf16_flops(jax.devices()[0])
    mfu = flops_tok * tok_s / peak if peak else None
    log(f"config8 moe: {tok_s:,.0f} tok/s, "
        f"{flops_tok * tok_s / 1e12:.1f} TF/s model flops"
        + (f", MFU {mfu * 100:.1f}%" if mfu else ""))
    return {
        "tokens_per_sec": round(tok_s, 1),
        "model_flops_mfu": round(mfu, 4) if mfu else None,
        "step_ms": round(best / steps * 1e3, 2),
        "flops_per_token_model_only": round(flops_tok),
        "active_params_per_token_frac": round(K / E, 3),
        "config": f"d{D}xL{L}xE{E}k{K}xF{F}xT{T}xB{B}-swiglu-bf16-bf16params",
    }


def config9_large_vocab_lm():
    """V=32k LM: the vocab-chunked loss head vs the dense head.

    The imported-checkpoint vocabs (32k–152k) make the ``[B, T, V]``
    logits + cotangent the peak-memory term of a fine-tuning step.
    ``vocab_block`` streams the head (online-lse forward, per-block
    recompute backward; ``chunked_summed_xent``) — this config measures
    BOTH step time and XLA's compiled temp-memory budget for the two
    paths at d1024/L4/V32768/T2048/B4 bf16. TPU-gated
    (BENCH_ALL_VOCAB=1 forces).
    """
    import jax
    import numpy as np

    gate = os.environ.get("BENCH_ALL_VOCAB", "auto")
    on_tpu = jax.devices()[0].platform == "tpu"
    if gate == "0" or (gate == "auto" and not on_tpu):
        log("config9 vocab: skipped (not on TPU; BENCH_ALL_VOCAB=1 forces)")
        return {"skipped": "not on TPU"}

    from elephas_tpu.models import (
        TransformerLM, adam_compact, build_lm_train_step, build_mesh_sp,
        make_lm_batches, shard_lm_batch,
    )

    D, L, H, F, V, T, B = 1024, 4, 8, 4096, 32768, 2048, 4
    steps = 8
    out = {}
    for label, vocab_block in (("dense_head", None), ("chunked_head", 8192)):
        model = TransformerLM(
            vocab=V, d_model=D, n_heads=H, n_layers=L, d_ff=F, max_len=T,
            compute_dtype="bfloat16", pos_encoding="rotary",
            tie_embeddings=True, activation="swiglu", norm="rmsnorm",
            ffn_bias=False,
        )
        mesh = build_mesh_sp(data=1, seq=1)
        step, opt_init = build_lm_train_step(
            model, mesh, adam_compact(1e-3), attn="flash",
            vocab_block=vocab_block)
        params = model.shard_params(mesh, model.init(seed=0))
        state = opt_init(params)
        rows = np.random.default_rng(0).integers(0, V, size=(B, T + 1))
        batch = shard_lm_batch(mesh, *make_lm_batches(rows))
        temp_gb = None
        try:  # compiled temp budget — the memory claim, measured by XLA
            target = next(v for c in (step.__closure__ or [])
                          for v in [c.cell_contents] if hasattr(v, "lower"))
            compiled = target.lower(params, state, *batch).compile()
            temp_gb = compiled.memory_analysis().temp_size_in_bytes / 1e9
        except Exception as e:
            log(f"config9: memory_analysis unavailable ({e})")
        for _ in range(2):
            params, state, loss = step(params, state, *batch)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, state, loss = step(params, state, *batch)
        last = float(loss)
        dt = (time.perf_counter() - t0) / steps
        assert np.isfinite(last), last
        out[label] = {
            "tokens_per_sec": round(B * T / dt, 1),
            "step_ms": round(dt * 1e3, 2),
            "xla_temp_gb": round(temp_gb, 2) if temp_gb else None,
        }
        log(f"config9 {label}: {B * T / dt:,.0f} tok/s, "
            f"{dt * 1e3:.1f} ms/step, temp {temp_gb and round(temp_gb, 2)} GB")
    d, c = out["dense_head"], out["chunked_head"]
    if d["xla_temp_gb"] and c["xla_temp_gb"]:
        out["temp_memory_saved_gb"] = round(
            d["xla_temp_gb"] - c["xla_temp_gb"], 2)
    out["config"] = f"d{D}xL{L}xV{V}xT{T}xB{B}-swiglu-bf16"
    return out


def main():
    from harness_env import cpu_mesh_env, probe_backend

    if not os.environ.get("BENCH_FELL_BACK"):
        ok, n_visible, detail = probe_backend()
        if not ok:
            log(f"backend probe failed ({detail}); falling back to CPU")
            env = cpu_mesh_env(8)
            env["BENCH_FELL_BACK"] = "1"
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        log(f"backend: {n_visible} x {detail}")

    results = {}
    for name, fn in (
        ("mnist_cnn_modes", config2_mnist_cnn),
        ("imdb_lstm_pipeline", config3_imdb_lstm),
        ("mllib", config4_mllib),
        ("hyperparam_search", config5_hyperparam),
        ("conv_mfu", config6_conv_mfu),
        ("speculative", config7_speculative),
        ("moe_lm", config8_moe_lm),
        ("large_vocab_lm", config9_large_vocab_lm),
    ):
        try:
            results[name] = fn()
        except Exception as e:  # each config stands alone
            log(f"{name} FAILED: {type(e).__name__}: {e}")
            results[name] = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps({"configs": results}))


if __name__ == "__main__":
    main()
