"""BASELINE configs 2-5 measured through the public APIs.

``bench.py`` is the judged harness (config 1 MLP + the MFU-accounted LM);
this script measures the remaining BASELINE.md target configs:

- **2** MNIST-CNN through ``SparkModel`` in synchronous AND async/hogwild
  modes — throughput plus the convergence envelope (same model/data/epochs,
  final test accuracy per mode: async staleness trades accuracy for
  pipeline overlap; the envelope quantifies it).
- **3** IMDB-LSTM through the ``ElephasEstimator`` Spark-ML pipeline.
- **4** ``SparkMLlibModel`` on LabeledPoint RDDs (Boston-shaped regression
  + Iris multiclass).
- **5** ``HyperParamModel`` distributed search wall-clock.

Prints ONE JSON line ``{"configs": {...}}`` (stderr carries progress).
Config 2 reports steady-state throughput (a warmup fit absorbs compile);
configs 3-5 are one-shot API flows, so their wall-clock INCLUDES compile —
stated in the output rather than hidden.

Datasets are the examples' offline synthetic fallbacks (``examples/_datasets``)
— identical shapes/dtypes to the real ones, no network. Knobs:
``BENCH_ALL_SAMPLES``, ``BENCH_ALL_EPOCHS``, ``BENCH_ALL_EVALS``.
"""

import json
import os
import sys
import time

os.environ.setdefault("KERAS_BACKEND", "jax")
_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_REPO, "examples"))


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def _accuracy(model_like, x, y):
    import numpy as np

    preds = np.asarray(model_like.predict(x))
    return float((preds.argmax(1) == y.argmax(1)).mean())


def config2_mnist_cnn():
    """Sync vs async vs hogwild CNN: samples/sec/chip + accuracy envelope."""
    import jax
    import numpy as np

    from elephas_tpu import SparkModel
    from elephas_tpu.data import SparkContext
    from elephas_tpu.utils import to_simple_rdd

    from _datasets import load_mnist
    from mnist_cnn_async import make_cnn

    n = int(os.environ.get("BENCH_ALL_SAMPLES", 8192))
    epochs = int(os.environ.get("BENCH_ALL_EPOCHS", 3))
    n_dev = jax.local_device_count()
    n_workers = max(n_dev, 2)

    (x_tr, y_tr), (x_te, y_te) = load_mnist(n_train=n, n_test=1024)
    sc = SparkContext(master=f"local[{n_workers}]", appName="bench_all_c2")
    rdd = to_simple_rdd(sc, x_tr, y_tr, num_slices=n_workers)

    out = {}
    for mode in ("synchronous", "asynchronous", "hogwild"):
        sm = SparkModel(make_cnn(), mode=mode, frequency="epoch",
                        num_workers=n_workers, merge="mean")
        sm.fit(rdd, epochs=epochs, batch_size=64, verbose=0,
               validation_split=0.0)  # warmup: compile at this geometry
        t0 = time.perf_counter()
        sm.fit(rdd, epochs=epochs, batch_size=64, verbose=0,
               validation_split=0.0)
        dt = time.perf_counter() - t0
        sps_chip = n * epochs / dt / n_dev
        acc = _accuracy(sm, x_te, y_te)
        out[mode] = {
            "samples_per_sec_per_chip": round(sps_chip, 1),
            "test_accuracy": round(acc, 4),
        }
        log(f"config2 {mode}: {sps_chip:,.0f} samples/sec/chip, "
            f"acc {acc:.4f}")
    sc.stop()
    # convergence envelope: async/hogwild accuracy relative to sync
    sync_acc = out["synchronous"]["test_accuracy"]
    for m in ("asynchronous", "hogwild"):
        out[m]["accuracy_vs_sync"] = round(
            out[m]["test_accuracy"] - sync_acc, 4
        )
    return out


def config3_imdb_lstm():
    """ElephasEstimator pipeline on IMDB-shaped data (wall-clock incl.
    compile — the one-shot DataFrame API flow)."""
    import jax
    import numpy as np

    from elephas_tpu import ElephasEstimator
    from elephas_tpu.data import Row, SparkSession
    from elephas_tpu.ml import Pipeline
    from elephas_tpu.mllib import Vectors

    from _datasets import load_imdb
    from ml_pipeline_imdb_lstm import MAXLEN, VOCAB, make_lstm

    n = int(os.environ.get("BENCH_ALL_SAMPLES", 8192)) // 4
    epochs = int(os.environ.get("BENCH_ALL_EPOCHS", 3))
    n_dev = jax.local_device_count()

    spark = SparkSession.builder.master(f"local[{n_dev}]").appName(
        "bench_all_c3").getOrCreate()
    (x_tr, y_tr), (x_te, y_te) = load_imdb(n_train=n, n_test=512,
                                           maxlen=MAXLEN, vocab=VOCAB)
    df = spark.createDataFrame([
        Row(features=Vectors.dense(x.astype("float64")), label=float(y[0]))
        for x, y in zip(x_tr, y_tr)
    ])
    est = ElephasEstimator()
    est.set_keras_model(make_lstm())
    est.set_categorical(False)
    est.set_num_workers(n_dev)
    est.set_epochs(epochs)
    est.set_batch_size(32)  # partitions must exceed the batch (skip quirk)
    est.set_validation_split(0.0)
    est.set_mode("synchronous")
    est.set_parameter_server_mode("jax")

    t0 = time.perf_counter()
    fitted = Pipeline(stages=[est]).fit(df)
    dt = time.perf_counter() - t0

    test_df = spark.createDataFrame([
        Row(features=Vectors.dense(x.astype("float64")), label=float(y[0]))
        for x, y in zip(x_te, y_te)
    ])
    rows = fitted.transform(test_df).collect()
    preds = np.array([r.prediction for r in rows])
    labels = np.array([r.label for r in rows])
    acc = float(((preds > 0.5) == (labels > 0.5)).mean())
    log(f"config3 imdb-lstm pipeline: {n * epochs / dt:,.0f} samples/sec "
        f"(incl. compile), acc {acc:.4f}")
    return {
        "samples_per_sec_incl_compile": round(n * epochs / dt, 1),
        "test_accuracy": round(acc, 4),
    }


def config4_mllib():
    """SparkMLlibModel: Boston-shaped regression MSE + Iris accuracy."""
    import jax
    import keras
    import numpy as np

    from elephas_tpu import SparkMLlibModel
    from elephas_tpu.data import SparkContext
    from elephas_tpu.utils import to_labeled_point

    from _datasets import load_boston, load_iris

    n_dev = jax.local_device_count()
    epochs = int(os.environ.get("BENCH_ALL_EPOCHS", 3)) * 7
    sc = SparkContext(master=f"local[{n_dev}]", appName="bench_all_c4")

    # regression
    x, y = load_boston()
    x = (x - x.mean(0)) / (x.std(0) + 1e-6)
    y_n = (y - y.mean()) / y.std()
    lp = to_labeled_point(sc, x, y_n, categorical=False)
    reg = keras.Sequential(
        [keras.layers.Dense(32, activation="relu"), keras.layers.Dense(1)]
    )
    reg.build((None, x.shape[1]))
    reg.compile(optimizer="adam", loss="mse")
    m = SparkMLlibModel(reg, mode="synchronous", num_workers=n_dev)
    t0 = time.perf_counter()
    m.fit(lp, epochs=epochs, batch_size=32, validation_split=0.0,
          categorical=False)
    dt_reg = time.perf_counter() - t0
    mse = float(np.mean(
        (np.asarray(m.predict(x)).ravel() - y_n) ** 2
    ))

    # multiclass (load_iris yields class ids)
    xi, yi = load_iris()
    xi = (xi - xi.mean(0)) / (xi.std(0) + 1e-6)
    lpi = to_labeled_point(sc, xi, yi, categorical=True)
    clf = keras.Sequential([
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(3, activation="softmax"),
    ])
    clf.build((None, xi.shape[1]))
    clf.compile(optimizer="adam", loss="categorical_crossentropy",
                metrics=["accuracy"])
    mc = SparkMLlibModel(clf, mode="synchronous", num_workers=n_dev)
    t0 = time.perf_counter()
    mc.fit(lpi, epochs=epochs, batch_size=16, validation_split=0.0,
           categorical=True, nb_classes=3)
    dt_cls = time.perf_counter() - t0
    acc = float(
        (np.asarray(mc.predict(xi)).argmax(1) == yi.astype(int)).mean()
    )
    sc.stop()
    log(f"config4 boston mse {mse:.4f} ({dt_reg:.1f}s), "
        f"iris acc {acc:.4f} ({dt_cls:.1f}s), incl. compile")
    return {
        "boston_mse_normalized": round(mse, 4),
        "boston_fit_seconds_incl_compile": round(dt_reg, 2),
        "iris_accuracy": round(acc, 4),
        "iris_fit_seconds_incl_compile": round(dt_cls, 2),
    }


def config5_hyperparam():
    """Distributed TPE search wall-clock (device-slice fan-out)."""
    from elephas_tpu import HyperParamModel
    from elephas_tpu.data import SparkContext

    from hyperparam_optimization import data, model

    evals = int(os.environ.get("BENCH_ALL_EVALS", 2))
    workers = 4
    sc = SparkContext(master=f"local[{workers}]", appName="bench_all_c5")
    hp = HyperParamModel(sc, num_workers=workers)
    t0 = time.perf_counter()
    trials = hp.compute_trials(model=model, data=data, max_evals=evals)
    dt = time.perf_counter() - t0
    sc.stop()
    ok = [t for t in trials if t["status"] == "ok"]
    best = min(t["loss"] for t in ok)
    devices = sorted({t["device"] for t in trials})
    log(f"config5 search: {len(trials)} trials / {workers} workers in "
        f"{dt:.1f}s (incl. compile), best loss {best:.4f}, "
        f"devices {devices}")
    return {
        "trials": len(trials),
        "workers": workers,
        "wall_seconds_incl_compile": round(dt, 2),
        "best_loss": round(best, 4),
        "distinct_devices": len(devices),
    }


def main():
    from harness_env import cpu_mesh_env, probe_backend

    if not os.environ.get("BENCH_FELL_BACK"):
        ok, n_visible, detail = probe_backend()
        if not ok:
            log(f"backend probe failed ({detail}); falling back to CPU")
            env = cpu_mesh_env(8)
            env["BENCH_FELL_BACK"] = "1"
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        log(f"backend: {n_visible} x {detail}")

    results = {}
    for name, fn in (
        ("mnist_cnn_modes", config2_mnist_cnn),
        ("imdb_lstm_pipeline", config3_imdb_lstm),
        ("mllib", config4_mllib),
        ("hyperparam_search", config5_hyperparam),
    ):
        try:
            results[name] = fn()
        except Exception as e:  # each config stands alone
            log(f"{name} FAILED: {type(e).__name__}: {e}")
            results[name] = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps({"configs": results}))


if __name__ == "__main__":
    main()
