"""Ulysses all-to-all attention vs full attention on the 8-device CPU mesh."""

import numpy as np
import pytest

from elephas_tpu.ops.ring_attention import attention_reference
from elephas_tpu.ops.ulysses import ulysses_attention
from elephas_tpu.parallel import build_mesh


def _qkv(b=2, t=64, h=8, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.normal(size=(b, t, h, d)).astype("float32")
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(causal):
    q, k, v = _qkv()
    mesh = build_mesh(8)
    out = np.asarray(ulysses_attention(q, k, v, mesh=mesh, causal=causal))
    ref = np.asarray(attention_reference(q, k, v, causal=causal))
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_group_size_one_is_plain_attention():
    q, k, v = _qkv(t=32, h=2)
    out = np.asarray(ulysses_attention(q, k, v, mesh=build_mesh(1)))
    ref = np.asarray(attention_reference(q, k, v))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_indivisible_heads_rejected():
    q, k, v = _qkv(h=4)  # 4 heads % 8 devices != 0
    with pytest.raises(ValueError, match="head count"):
        ulysses_attention(q, k, v, mesh=build_mesh(8))


def test_indivisible_sequence_rejected():
    q, k, v = _qkv(t=60)
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q, k, v, mesh=build_mesh(8))


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_flow(causal):
    """Differentiable end-to-end through both all-to-alls."""
    import jax
    import jax.numpy as jnp

    q, k, v = _qkv(b=1, t=16, h=8, d=8)
    mesh = build_mesh(8)

    def loss_uly(q):
        return jnp.sum(ulysses_attention(q, k, v, mesh=mesh, causal=causal) ** 2)

    def loss_ref(q):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    g_uly = np.asarray(jax.grad(loss_uly)(jnp.asarray(q)))
    g_ref = np.asarray(jax.grad(loss_ref)(jnp.asarray(q)))
    np.testing.assert_allclose(g_uly, g_ref, atol=2e-4, rtol=2e-4)


def test_agrees_with_ring():
    """The two sequence-parallel schedules are interchangeable."""
    from elephas_tpu.ops.ring_attention import ring_attention

    q, k, v = _qkv(t=32)
    mesh = build_mesh(8)
    a = np.asarray(ulysses_attention(q, k, v, mesh=mesh, causal=True))
    b = np.asarray(ring_attention(q, k, v, mesh=mesh, causal=True))
    np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("hkv", [2, 8, 3])
def test_gqa_kv_heads(hkv):
    """Ulysses accepts divisor KV heads (Hkv % P == 0 re-shards the small
    blocks; otherwise they broadcast before the all_to_all); Hkv=3 does
    not divide H=8 and must be rejected."""
    rng = np.random.default_rng(5)
    q = rng.normal(size=(2, 64, 8, 8)).astype("float32")
    k = rng.normal(size=(2, 64, hkv, 8)).astype("float32")
    v = rng.normal(size=(2, 64, hkv, 8)).astype("float32")
    mesh = build_mesh(8)
    if 8 % hkv:
        with pytest.raises(Exception):
            np.asarray(ulysses_attention(q, k, v, mesh=mesh, causal=True))
        return
    got = np.asarray(ulysses_attention(q, k, v, mesh=mesh, causal=True))
    want = np.asarray(attention_reference(
        q, np.repeat(k, 8 // hkv, axis=2), np.repeat(v, 8 // hkv, axis=2),
        causal=True,
    ))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [3, 13, 24])
def test_windowed_matches_oracle(window):
    """The post-all-to-all sequence is global, so the flash window mask
    must reproduce the dense windowed oracle exactly."""
    q, k, v = _qkv()
    mesh = build_mesh(8)
    ref = np.asarray(attention_reference(q, k, v, causal=True,
                                         window=window))
    out = np.asarray(ulysses_attention(q, k, v, mesh=mesh, causal=True,
                                       window=window))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
