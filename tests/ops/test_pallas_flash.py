"""Pallas flash-attention training kernels vs the dense oracle (interpret
mode on CPU), forward and backward, across MHA/GQA, causal/full, padded and
uneven tile shapes. The jnp scan implementation (``flash_attention.py``) is
itself oracle-tested in ``test_flash_attention.py``; here the hand-written
TPU kernels must match the same dense reference, gradients included."""

import numpy as np
import pytest

import jax

from elephas_tpu.compat import shard_map as compat_shard_map
import jax.numpy as jnp

from elephas_tpu.ops import attention_reference
from elephas_tpu.ops.pallas_flash import flash_attention_tpu


def _rand(rng, *shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


CASES = [
    # B, T, H, Hkv, Dh, causal, bq, bk
    (2, 256, 4, 4, 64, True, 128, 128),
    (2, 256, 4, 2, 64, True, 128, 128),     # grouped-query
    (1, 200, 4, 4, 64, True, 128, 128),     # T padded up to the tile
    (2, 256, 4, 4, 64, False, 128, 128),    # non-causal
    (1, 384, 8, 2, 32, True, 256, 128),     # uneven q/k tiles + GQA
    (1, 160, 2, 1, 16, False, 128, 128),    # padded + non-causal + MQA
]


@pytest.mark.parametrize("b,t,h,hkv,dh,causal,bq,bk", CASES)
def test_forward_and_grads_match_dense(b, t, h, hkv, dh, causal, bq, bk):
    rng = np.random.default_rng(0)
    q = _rand(rng, b, t, h, dh)
    k = _rand(rng, b, t, hkv, dh)
    v = _rand(rng, b, t, hkv, dh)
    g = _rand(rng, b, t, h, dh)

    def ref(q, k, v):
        return attention_reference(q, k, v, causal=causal)

    def ker(q, k, v):
        return flash_attention_tpu(q, k, v, causal, bq, bk, True)

    np.testing.assert_allclose(
        np.asarray(ker(q, k, v)), np.asarray(ref(q, k, v)),
        atol=2e-5, rtol=2e-5,
    )
    want = jax.vjp(ref, q, k, v)[1](g)
    got = jax.vjp(ker, q, k, v)[1](g)
    for name, a, b_ in zip(("dq", "dk", "dv"), got, want):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=2e-5, rtol=2e-5,
            err_msg=name,
        )


def test_bf16_inputs_roundtrip():
    """bf16 in → bf16 out, f32 accumulation inside (tolerance is bf16's)."""
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 128, 2, 64)), jnp.bfloat16)
    out = flash_attention_tpu(q, q, q, True, 128, 128, True)
    assert out.dtype == jnp.bfloat16
    want = attention_reference(q, q, q, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(want, np.float32),
        atol=2e-2, rtol=2e-2,
    )


def test_kernel_under_shard_map_matches_oracle():
    """On real multi-chip hardware the ulysses/LM paths invoke the Pallas
    kernels INSIDE shard_map (per-shard local attention after the
    all_to_all). Pin that composition: kernel under shard_map over a
    dp mesh == dense oracle, forward and backward."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from elephas_tpu.parallel import build_mesh

    rng = np.random.default_rng(3)
    B, T, H, Dh = 8, 128, 2, 32
    q = _rand(rng, B, T, H, Dh)
    g = _rand(rng, B, T, H, Dh)
    mesh = build_mesh(4)

    def local(q):
        return flash_attention_tpu(q, q, q, True, 128, 128, True)

    fwd = jax.jit(compat_shard_map(
        local, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False,
    ))
    qd = jax.device_put(q, NamedSharding(mesh, P("data")))
    want = attention_reference(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(fwd(qd)), np.asarray(want),
                               atol=2e-5, rtol=2e-5)

    def loss(q):
        return jnp.sum(fwd(q) * g)

    def oracle_loss(q):
        return jnp.sum(attention_reference(q, q, q, causal=True) * g)

    got = jax.grad(loss)(qd)
    ref = jax.grad(oracle_loss)(q)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_with_pallas_kernel_matches_oracle(monkeypatch):
    """The REAL multi-chip long-context composition: ulysses all_to_alls
    around the Pallas flash kernel, under shard_map, gradients included.
    On CPU the dispatcher picks the jnp scan, so force the kernel (interpret
    mode) through the same ``flash_attention`` seam the TPU path uses."""
    import sys

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from elephas_tpu.ops import attention_reference
    from elephas_tpu.ops.ulysses import ulysses_attention_local
    from elephas_tpu.parallel import build_mesh

    ul = sys.modules["elephas_tpu.ops.ulysses"]
    monkeypatch.setattr(
        ul, "flash_attention",
        lambda q, k, v, causal=False, window=None: flash_attention_tpu(
            q, k, v, causal, 128, 128, True, window=window),
    )

    rng = np.random.default_rng(5)
    B, T, H, Dh = 2, 256, 4, 32
    q = _rand(rng, B, T, H, Dh)
    g = _rand(rng, B, T, H, Dh)
    mesh = build_mesh(4)

    fwd = jax.jit(compat_shard_map(
        lambda q: ulysses_attention_local(q, q, q, True, "data"),
        mesh=mesh, in_specs=P(None, "data"), out_specs=P(None, "data"),
        check_vma=False,
    ))
    qd = jax.device_put(q, NamedSharding(mesh, P(None, "data")))
    want = attention_reference(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(fwd(qd)), np.asarray(want),
                               atol=2e-5, rtol=2e-5)

    got = jax.grad(lambda q: jnp.sum(fwd(q) * g))(qd)
    ref = jax.grad(
        lambda q: jnp.sum(attention_reference(q, q, q, causal=True) * g)
    )(q)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal,hkv", [(True, 4), (True, 2), (False, 4)])
def test_ring_with_pallas_kernel_matches_oracle(causal, hkv):
    """The TPU ring body (_ring_flash_local): per-visit Pallas flash merged
    by logsumexp, KV blocks rotating via ppermute — vs the dense oracle,
    gradients included (kernel VJP + lse cotangent + jnp merge)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from elephas_tpu.ops import attention_reference
    from elephas_tpu.ops.ring_attention import _ring_flash_local
    from elephas_tpu.parallel import build_mesh

    rng = np.random.default_rng(7)
    B, T, H, Dh = 2, 256, 4, 32
    q = _rand(rng, B, T, H, Dh)
    k = _rand(rng, B, T, hkv, Dh)
    v = _rand(rng, B, T, hkv, Dh)
    g = _rand(rng, B, T, H, Dh)
    mesh = build_mesh(4)

    fwd = jax.jit(compat_shard_map(
        lambda q, k, v: _ring_flash_local(q, k, v, causal, "data",
                                          interpret=True),
        mesh=mesh, in_specs=P(None, "data"), out_specs=P(None, "data"),
        check_vma=False,
    ))
    spec = NamedSharding(mesh, P(None, "data"))
    qd, kd, vd = (jax.device_put(a, spec) for a in (q, k, v))
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(fwd(qd, kd, vd)),
                               np.asarray(want), atol=2e-5, rtol=2e-5)

    def loss(q, k, v):
        return jnp.sum(fwd(q, k, v) * g)

    def oracle_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) * g)

    got = jax.grad(loss, argnums=(0, 1, 2))(qd, kd, vd)
    ref = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5, err_msg=name)


@pytest.mark.parametrize("hkv,dh,t", [(4, 32, 256), (2, 64, 256), (2, 32, 200)])
def test_rope_fused_matches_prerotated_oracle(hkv, dh, t):
    """flash_attention_rope (in-kernel rotation, derotated gradients) must
    equal rotate-then-attend exactly — forward and all three gradients."""
    import jax

    from elephas_tpu.models.transformer import _rope_angles, _rope_rotate
    from elephas_tpu.ops import attention_reference
    from elephas_tpu.ops.pallas_flash import (flash_attention_rope,
                                              make_rope_tables)

    rng = np.random.default_rng(11)
    B, H = 2, 4
    q = _rand(rng, B, t, H, dh)
    k = _rand(rng, B, t, hkv, dh)
    v = _rand(rng, B, t, hkv, dh)
    g = _rand(rng, B, t, H, dh)
    positions = jnp.broadcast_to(jnp.arange(t), (B, t))
    cos, sin = _rope_angles(positions, dh)
    cos4, sin4 = cos[:, :, None, :], sin[:, :, None, :]
    c2, s2 = make_rope_tables(cos, sin)

    def ref(q, k, v):
        return attention_reference(_rope_rotate(q, cos4, sin4),
                                   _rope_rotate(k, cos4, sin4), v,
                                   causal=True)

    def ker(q, k, v):
        return flash_attention_rope(q, k, v, c2, s2, True, 128, 128, True)

    np.testing.assert_allclose(np.asarray(ker(q, k, v)),
                               np.asarray(ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)
    want = jax.vjp(ref, q, k, v)[1](g)
    got = jax.vjp(ker, q, k, v)[1](g)
    for name, a, b in zip(("dq", "dk", "dv"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5, err_msg=name)


@pytest.mark.parametrize("window", [24, 64, 130])
def test_windowed_ring_with_pallas_kernel_matches_oracle(window):
    """Round 5: the TPU ring body's 4-way windowed switch (skip/diag/full/
    banded-partial) in interpret mode vs the dense windowed oracle,
    gradients included — windows below / at / past the 64-token shard
    exercise every branch, including the banded partial fold's autodiff."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from elephas_tpu.ops import attention_reference
    from elephas_tpu.ops.ring_attention import _ring_flash_local
    from elephas_tpu.parallel import build_mesh

    rng = np.random.default_rng(8)
    B, T, H, Dh = 1, 256, 2, 32
    q = _rand(rng, B, T, H, Dh)
    k = _rand(rng, B, T, H, Dh)
    v = _rand(rng, B, T, H, Dh)
    g = _rand(rng, B, T, H, Dh)
    mesh = build_mesh(4)

    fwd = jax.jit(compat_shard_map(
        lambda q, k, v: _ring_flash_local(q, k, v, True, "data",
                                          interpret=True, window=window),
        mesh=mesh, in_specs=P(None, "data"), out_specs=P(None, "data"),
        check_vma=False,
    ))
    spec = NamedSharding(mesh, P(None, "data"))
    qd, kd, vd = (jax.device_put(a, spec) for a in (q, k, v))
    want = attention_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(fwd(qd, kd, vd)),
                               np.asarray(want), atol=2e-5, rtol=2e-5)

    def loss(q, k, v):
        return jnp.sum(fwd(q, k, v) * g)

    def oracle_loss(q, k, v):
        return jnp.sum(
            attention_reference(q, k, v, causal=True, window=window) * g)

    got = jax.grad(loss, argnums=(0, 1, 2))(qd, kd, vd)
    ref = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5, err_msg=name)
