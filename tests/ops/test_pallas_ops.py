"""Pallas fused cross-entropy kernel vs the jnp reference oracle.

The kernel itself runs under ``interpret=True`` on CPU (the real lowering is
TPU-only); values AND gradients must match the reference implementation, which
in turn matches Keras.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.ops.pallas_ops import (
    fused_xent_from_logits,
    xent_from_logits_reference,
)


def _case(B, C, seed=0):
    rng = np.random.default_rng(seed)
    logits = rng.normal(size=(B, C)).astype("float32") * 3
    labels = np.eye(C, dtype="float32")[rng.integers(0, C, size=B)]
    return jnp.asarray(logits), jnp.asarray(labels)


@pytest.mark.parametrize("B,C", [(8, 128), (32, 512), (5, 10), (13, 300)])
def test_forward_matches_reference(B, C):
    logits, labels = _case(B, C)
    ours = fused_xent_from_logits(logits, labels, True)
    ref = xent_from_logits_reference(logits, labels)
    assert ours.shape == (B,)
    assert np.allclose(np.asarray(ours), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("B,C", [(8, 128), (5, 10)])
def test_gradient_matches_reference(B, C):
    logits, labels = _case(B, C, seed=1)
    sw = jnp.asarray(np.random.default_rng(2).uniform(0, 1, B).astype("float32"))

    def loss_ours(x):
        return jnp.sum(fused_xent_from_logits(x, labels, True) * sw)

    def loss_ref(x):
        return jnp.sum(xent_from_logits_reference(x, labels) * sw)

    g_ours = jax.grad(loss_ours)(logits)
    g_ref = jax.grad(loss_ref)(logits)
    assert np.allclose(np.asarray(g_ours), np.asarray(g_ref), atol=1e-5)


def test_matches_keras_loss():
    import keras

    logits, labels = _case(16, 64, seed=3)
    ours = fused_xent_from_logits(logits, labels, True)
    theirs = keras.losses.categorical_crossentropy(
        labels, logits, from_logits=True
    )
    assert np.allclose(np.asarray(ours), np.asarray(theirs), atol=1e-5)


def test_loss_resolver_logits_path():
    from elephas_tpu.models.losses import resolve_per_sample_loss

    import keras

    fn = resolve_per_sample_loss(
        keras.losses.CategoricalCrossentropy(from_logits=True)
    )
    logits, labels = _case(8, 32, seed=4)
    per = fn(labels, logits)
    ref = xent_from_logits_reference(logits, labels)
    assert np.allclose(np.asarray(per), np.asarray(ref), atol=1e-5)
