"""Pallas grouped matmul (``ops.grouped_matmul``) vs its jnp oracles.

Kernels run under ``interpret=True`` on CPU (the real lowering is
TPU-only). Routing-level guarantees — the tile-aligned layout reproducing
the slot/one-hot executors' decisions bit-for-bit — are covered by the
``apply_gmm`` executor tests at the bottom; here the kernels themselves
are checked for values and gradients, including the K-chunked dispatch,
the transposed-weights twin, and empty groups (min-one-tile contract).

Tolerances are loose-ish (atol 5e-2 on O(10) magnitudes): XLA:CPU's
oneDNN matmuls use bf16-fastmath paths, so even two jnp lowerings of the
same contraction differ by ~1e-2 relative.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.ops import grouped_matmul as G
from elephas_tpu.parallel.expert import MoEFeedForward

ATOL = 5e-2


def _case(M, K, N, E, gmap, seed=0):
    rng = np.random.default_rng(seed)
    lhs = jnp.asarray(rng.standard_normal((M, K)), jnp.float32)
    rhs = jnp.asarray(rng.standard_normal((E, K, N)), jnp.float32)
    return lhs, rhs, jnp.asarray(gmap, jnp.int32)


def test_gmm_forward_matches_reference():
    lhs, rhs, gmap = _case(768, 256, 128, 4, [0, 1, 1, 2, 3, 3])
    out = G.gmm(lhs, rhs, gmap, True)
    ref = G.gmm_reference(lhs, rhs, gmap)
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=ATOL)


def test_gmm_t_forward_matches_reference():
    lhs, _, gmap = _case(768, 256, 128, 4, [0, 1, 1, 2, 3, 3])
    rng = np.random.default_rng(1)
    rhs_t = jnp.asarray(rng.standard_normal((4, 128, 256)), jnp.float32)
    out = G.gmm_t(lhs, rhs_t, gmap, True)
    ref = G.gmm_reference(lhs, rhs_t, gmap, transpose_rhs=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=ATOL)


def test_gmm_gradients_match_reference():
    lhs, rhs, gmap = _case(768, 256, 128, 4, [0, 1, 1, 2, 3, 3])

    def f(l, r):
        return jnp.sum(jnp.sin(G.gmm(l, r, gmap, True)))

    def fr(l, r):
        return jnp.sum(jnp.sin(G.gmm_reference(l, r, gmap)))

    gl, gr = jax.jit(jax.grad(f, (0, 1)))(lhs, rhs)
    gl_r, gr_r = jax.jit(jax.grad(fr, (0, 1)))(lhs, rhs)
    np.testing.assert_allclose(np.asarray(gl), np.asarray(gl_r), atol=ATOL)
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gr_r), atol=ATOL)


def test_tgmm_matches_f64_oracle_and_zeroes_empty_groups():
    # group 2 is EMPTY but still owns one (all-sentinel) tile — the
    # min-one-tile contract the executor's layout guarantees; its weight
    # grad must come out exactly zero, not garbage.
    M, K, N, E, tm = 768, 256, 128, 4, 128
    rng = np.random.default_rng(2)
    lhs = np.zeros((M, K), np.float32)
    g = np.zeros((M, N), np.float32)
    # rows: e0 gets 192 (1.5 tiles -> pad), e1 gets 256, e3 gets 128
    fill = rng.standard_normal
    lhs[:192], g[:192] = fill((192, K)), fill((192, N))
    lhs[256:512], g[256:512] = fill((256, K)), fill((256, N))
    lhs[640:768], g[640:768] = fill((128, K)), fill((128, N))
    gmap = jnp.asarray([0, 0, 1, 1, 2, 3], jnp.int32)
    out = np.asarray(G.tgmm(jnp.asarray(lhs), jnp.asarray(g), gmap, E,
                            jnp.float32, True))
    seg = {0: (0, 256), 1: (256, 512), 3: (640, 768)}
    for e in range(E):
        if e in seg:
            a, b = seg[e]
            want = lhs[a:b].astype(np.float64).T @ g[a:b].astype(np.float64)
        else:
            want = np.zeros((K, N))
        np.testing.assert_allclose(out[e], want, atol=ATOL)


def test_k_chunked_paths_match(monkeypatch):
    monkeypatch.setattr(G, "_K_CHUNK", 128)  # force chunking at K=512
    lhs, rhs, gmap = _case(512, 512, 128, 4, [0, 1, 2, 3], seed=3)

    def f(l, r):
        return jnp.sum(jnp.sin(G.gmm(l, r, gmap, True)))

    def fr(l, r):
        return jnp.sum(jnp.sin(G.gmm_reference(l, r, gmap)))

    gl, gr = jax.jit(jax.grad(f, (0, 1)))(lhs, rhs)
    gl_r, gr_r = jax.jit(jax.grad(fr, (0, 1)))(lhs, rhs)
    np.testing.assert_allclose(np.asarray(gl), np.asarray(gl_r), atol=ATOL)
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gr_r), atol=ATOL)


def test_tileable_gates():
    assert G.tileable(1024, 256, 128, 128)
    assert not G.tileable(1000, 256, 128, 128)   # rows not tile-aligned
    assert not G.tileable(1024, 192, 128, 128)   # K not lane-tileable
    assert not G.tileable(1024, 256, 100, 128)   # N not lane-tileable
    assert not G.tileable(1024, 2304, 128, 128)  # K > 2 chunks, not chunkable


# -- the MoE executor built on these kernels ---------------------------------


def _moe(act="swiglu", bias=False, cf=1.25, E=4):
    moe = MoEFeedForward(128, 128, E, k=2, capacity_factor=cf,
                         activation=act, bias=bias)
    params = {k: jnp.asarray(v) for k, v in moe.init(0).items()}
    return moe, params


@pytest.mark.parametrize("act,bias,cf", [
    ("swiglu", False, 1.25),   # Mixtral expert shape
    ("relu", True, 0.5),       # heavy drops: capacity keeps must agree
    ("gelu", False, 2.0),
])
def test_apply_gmm_matches_oracle(act, bias, cf):
    moe, params = _moe(act, bias, cf)
    x = jnp.asarray(np.random.default_rng(4).standard_normal((256, 128)),
                    jnp.float32)
    y, aux = jax.jit(moe.apply_gmm)(params, x)
    yr, auxr = jax.jit(moe.apply_reference)(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr), atol=1e-4)
    assert abs(float(aux) - float(auxr)) < 1e-5


def test_apply_gmm_gradients_match_oracle():
    moe, params = _moe()
    x = jnp.asarray(np.random.default_rng(5).standard_normal((256, 128)),
                    jnp.float32)

    def loss(p, fn):
        yy, aa = fn(p, x)
        return jnp.sum(yy ** 2) + aa

    g1 = jax.jit(jax.grad(lambda p: loss(p, moe.apply_gmm)))(params)
    g2 = jax.jit(jax.grad(lambda p: loss(p, moe.apply_reference)))(params)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   atol=1e-2)


def test_apply_gmm_ep_groups_match_oracle():
    moe, params = _moe(cf=1.0)
    x = jnp.asarray(np.random.default_rng(6).standard_normal((256, 128)),
                    jnp.float32)
    y, aux = jax.jit(lambda p, c: moe.apply_gmm(p, c, ep=4))(params, x)
    yr, auxr = jax.jit(lambda p, c: moe.apply_reference(p, c, ep=4))(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr), atol=1e-4)
    assert abs(float(aux) - float(auxr)) < 1e-5


def test_apply_gmm_kernel_path_interpret():
    # force the Pallas kernels (interpret mode) end to end, with a router
    # biased so one expert goes hungry (empty-group tiles exercised)
    moe, params = _moe()
    params = dict(params)
    wg = np.zeros((128, 4), np.float32)
    wg[:, 3] = -10.0  # expert 3 never chosen
    params["wg"] = jnp.asarray(wg)
    x = jnp.asarray(np.random.default_rng(7).standard_normal((256, 128)),
                    jnp.float32)
    y, aux = jax.jit(lambda p, c: moe.apply_gmm(p, c, interpret=True))(
        params, x)
    yr, auxr = jax.jit(moe.apply_reference)(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr), atol=ATOL)
    g = jax.jit(jax.grad(
        lambda p: jnp.sum(moe.apply_gmm(p, x, interpret=True)[0] ** 2)
    ))(params)
    for k, v in g.items():
        assert np.isfinite(np.asarray(v)).all(), k


def test_apply_gmm_rejects_expert_choice():
    moe = MoEFeedForward(128, 128, 4, k=2, routing="expert_choice")
    params = {k: jnp.asarray(v) for k, v in moe.init(0).items()}
    x = jnp.zeros((64, 128), jnp.float32)
    with pytest.raises(ValueError, match="token_choice"):
        moe.apply_gmm(params, x)


@pytest.mark.parametrize("n,E", [(100, 4), (100, 8), (96, 3)])
def test_apply_gmm_unaligned_token_counts(n, E):
    """k·N not a multiple of the row tile: the layout buffer must round
    up to tile alignment or the tile→expert geometry shears (regression:
    reshape crash at E=4, silently wrong output at E=8)."""
    moe = MoEFeedForward(128, 128, E, k=2, capacity_factor=1.25,
                         activation="swiglu", bias=False)
    params = {k: jnp.asarray(v) for k, v in moe.init(0).items()}
    x = jnp.asarray(np.random.default_rng(8).standard_normal((n, 128)),
                    jnp.float32)
    y, aux = jax.jit(moe.apply_gmm)(params, x)
    yr, auxr = jax.jit(moe.apply_reference)(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr), atol=1e-4)
    assert abs(float(aux) - float(auxr)) < 1e-5
