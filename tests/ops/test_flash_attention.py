"""Blockwise flash attention vs the dense oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elephas_tpu.ops.flash_attention import flash_attention
from elephas_tpu.ops.ring_attention import attention_reference


def _qkv(b=2, t=64, h=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.normal(size=(b, t, h, d)).astype("float32")
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [8, 16, 64])
def test_matches_dense(causal, block):
    q, k, v = _qkv()
    got = np.asarray(flash_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        causal=causal, block_size=block,
    ))
    want = np.asarray(attention_reference(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_odd_length_falls_back_to_divisor_block():
    q, k, v = _qkv(t=48)  # 48 % 128 != 0 → blk becomes 48
    got = np.asarray(flash_attention(*map(jnp.asarray, (q, k, v)),
                                     causal=True, block_size=128))
    want = np.asarray(attention_reference(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match(causal):
    q, k, v = _qkv(b=1, t=32, h=2, d=8)

    def loss_flash(q):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_size=8) ** 2)

    def loss_ref(q):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    g1 = np.asarray(jax.grad(loss_flash)(jnp.asarray(q)))
    g2 = np.asarray(jax.grad(loss_ref)(jnp.asarray(q)))
    np.testing.assert_allclose(g1, g2, atol=2e-4, rtol=2e-4)


def test_bf16_accumulates_f32():
    q, k, v = _qkv()
    ref = np.asarray(attention_reference(q, k, v, causal=True))
    qb = jnp.asarray(q, jnp.bfloat16)
    kb = jnp.asarray(k, jnp.bfloat16)
    vb = jnp.asarray(v, jnp.bfloat16)
    out = flash_attention(qb, kb, vb, causal=True, block_size=16)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), ref, atol=5e-2, rtol=5e-2
    )


def test_gqa_kv_heads_match_repeated_oracle():
    """K/V with fewer (divisor) heads equal explicit repetition."""
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(2, 32, 8, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 32, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 32, 2, 8)), jnp.float32)
    kf = jnp.repeat(k, 4, axis=2)
    vf = jnp.repeat(v, 4, axis=2)
    got = np.asarray(flash_attention(q, k, v, causal=True, block_size=8))
    want = np.asarray(attention_reference(q, kf, vf, causal=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k[:, :, :1].repeat(3, axis=2), v, causal=True)
