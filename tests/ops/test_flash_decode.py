"""Flash-decode kernel vs the einsum oracle (interpret mode on CPU).

Covers GQA group sizes (G=1 multi-query up to G=H), padding-sensitive head
dims and cache lengths, positions in every T-block (incl. block boundaries),
traced positions under scan (the generate() usage), and bf16 caches.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.ops import decode_attention_reference, flash_decode


def rand(rng, *shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


def fused(q, k, v, pos):
    return flash_decode(q, k, v, pos, interpret=True)


@pytest.mark.parametrize("hkv,g", [(1, 4), (2, 2), (4, 1), (2, 5)])
def test_gqa_group_shapes(hkv, g):
    rng = np.random.default_rng(0)
    B, T, Dh = 3, 40, 16
    q = rand(rng, B, hkv, g, Dh)
    k = rand(rng, B, hkv, T, Dh)
    v = rand(rng, B, hkv, T, Dh)
    for pos in (0, 17, T - 1):
        got = fused(q, k, v, pos)
        want = decode_attention_reference(q, k, v, pos)
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5,
                                   err_msg=f"pos={pos}")


def test_multi_block_cache_and_boundaries():
    """Cache longer than one T-block: online softmax must merge blocks, and
    positions at/around block edges must mask exactly."""
    rng = np.random.default_rng(1)
    B, Hkv, G, Dh, T = 2, 2, 3, 8, 700  # > 2 blocks of 256
    q = rand(rng, B, Hkv, G, Dh)
    k = rand(rng, B, Hkv, T, Dh) * 3
    v = rand(rng, B, Hkv, T, Dh)
    for pos in (0, 255, 256, 511, 512, 699):
        got = fused(q, k, v, pos)
        want = decode_attention_reference(q, k, v, pos)
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4,
                                   err_msg=f"pos={pos}")


def test_traced_position_under_scan():
    """pos advances inside lax.scan in generate(): the kernel must accept a
    traced scalar (scalar prefetch) and stay exact at every step."""
    rng = np.random.default_rng(2)
    B, Hkv, G, Dh, T = 2, 1, 2, 8, 20
    q = rand(rng, B, Hkv, G, Dh)
    k = rand(rng, B, Hkv, T, Dh)
    v = rand(rng, B, Hkv, T, Dh)

    def step(_, pos):
        return None, fused(q, k, v, pos)

    _, outs = jax.lax.scan(step, None, jnp.arange(T))
    for pos in range(T):
        want = decode_attention_reference(q, k, v, pos)
        np.testing.assert_allclose(outs[pos], want, atol=1e-5, rtol=1e-5,
                                   err_msg=f"pos={pos}")


def test_bf16_cache_f32_softmax():
    rng = np.random.default_rng(3)
    B, Hkv, G, Dh, T = 2, 2, 2, 16, 33
    q32 = rand(rng, B, Hkv, G, Dh)
    k32 = rand(rng, B, Hkv, T, Dh)
    v32 = rand(rng, B, Hkv, T, Dh)
    got = fused(q32.astype(jnp.bfloat16), k32.astype(jnp.bfloat16),
                v32.astype(jnp.bfloat16), 20)
    assert got.dtype == jnp.float32
    want = decode_attention_reference(q32, k32, v32, 20)
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2)


def test_large_scores_stable():
    """Online softmax must not overflow with large logits."""
    rng = np.random.default_rng(4)
    B, Hkv, G, Dh, T = 1, 1, 1, 8, 300
    q = rand(rng, B, Hkv, G, Dh) * 30
    k = rand(rng, B, Hkv, T, Dh) * 30
    v = rand(rng, B, Hkv, T, Dh)
    got = fused(q, k, v, T - 1)
    assert np.isfinite(np.asarray(got)).all()
    want = decode_attention_reference(q, k, v, T - 1)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


# -- lse-exposing variant (sequence-parallel decode merge) -------------------


def test_lse_matches_reference_lse():
    """flash_decode_lse's (out, lse) vs the reference pair, across GQA
    shapes, multi-block caches, and block-boundary positions."""
    from elephas_tpu.ops.flash_decode import (
        decode_attention_reference_lse,
        flash_decode_lse,
    )

    rng = np.random.default_rng(5)
    for (hkv, g, dh, t) in [(2, 2, 16, 40), (1, 4, 32, 300), (2, 5, 16, 257)]:
        q = rand(rng, 2, hkv, g, dh)
        k = rand(rng, 2, hkv, t, dh)
        v = rand(rng, 2, hkv, t, dh)
        for pos in (0, t // 2, t - 1):
            got_o, got_lse = flash_decode_lse(q, k, v, pos, interpret=True)
            want_o, want_lse = decode_attention_reference_lse(q, k, v, pos)
            np.testing.assert_allclose(got_o, want_o, atol=1e-5, rtol=1e-5,
                                       err_msg=f"out pos={pos}")
            np.testing.assert_allclose(got_lse, want_lse, atol=1e-5,
                                       rtol=1e-5, err_msg=f"lse pos={pos}")


def test_lse_merge_reconstructs_full_attention():
    """The logsumexp partial merge (the sharded-decode contract): splitting
    the cache into R slices, attending each with its own lse, and merging
    must equal attention over the whole cache."""
    from elephas_tpu.ops.flash_decode import (
        decode_attention_reference,
        flash_decode_lse,
    )

    rng = np.random.default_rng(6)
    B, Hkv, G, Dh, T, R = 2, 2, 2, 16, 64, 4
    Tl = T // R
    q = rand(rng, B, Hkv, G, Dh)
    k = rand(rng, B, Hkv, T, Dh)
    v = rand(rng, B, Hkv, T, Dh)
    for pos in (0, 13, Tl - 1, Tl, T - 1):
        outs, lses = [], []
        for r in range(R):
            pos_local = pos - r * Tl
            o_r, lse_r = flash_decode_lse(
                q, k[:, :, r * Tl:(r + 1) * Tl], v[:, :, r * Tl:(r + 1) * Tl],
                max(0, min(pos_local, Tl - 1)), interpret=True)
            lses.append(np.where(pos_local >= 0, np.asarray(lse_r), -np.inf))
            outs.append(np.asarray(o_r))
        m = np.max(lses, axis=0)
        w = np.exp(np.asarray(lses) - m)                      # [R, B, Hkv, G]
        merged = (w[..., None] * np.asarray(outs)).sum(0) / w.sum(0)[..., None]
        want = decode_attention_reference(q, k, v, pos)
        np.testing.assert_allclose(merged, want, atol=1e-5, rtol=1e-5,
                                   err_msg=f"pos={pos}")


def test_per_row_positions():
    """pos as a [B] vector: each row's visibility bound is independent
    (the batched-speculative-decoding contract) and equals per-row scalar
    calls."""
    rng = np.random.default_rng(7)
    B, Hkv, G, Dh, T = 4, 2, 2, 16, 64
    q = rand(rng, B, Hkv, G, Dh)
    k = rand(rng, B, Hkv, T, Dh)
    v = rand(rng, B, Hkv, T, Dh)
    pos = np.array([0, 13, 31, 63], np.int32)
    got = fused(q, k, v, jnp.asarray(pos))
    for b in range(B):
        want_b = decode_attention_reference(q[b:b + 1], k[b:b + 1],
                                            v[b:b + 1], int(pos[b]))
        np.testing.assert_allclose(got[b:b + 1], want_b, atol=1e-5,
                                   rtol=1e-5, err_msg=f"row {b}")


def test_lse_windowed_and_past_end_positions():
    """Round 5: the windowed kernel must accept positions PAST the cache
    end (a sequence-sharded rank whose slice the window partially left
    keeps global arithmetic that way) — alignment-padding rows masked,
    kv block index clipped, exact vs the reference at every pos in and
    beyond the cache."""
    from elephas_tpu.ops.flash_decode import (
        decode_attention_reference_lse,
        flash_decode_lse,
    )

    rng = np.random.default_rng(6)
    hkv, g, dh, t, w = 2, 2, 16, 40, 12
    q = rand(rng, 2, hkv, g, dh)
    k = rand(rng, 2, hkv, t, dh)
    v = rand(rng, 2, hkv, t, dh)
    for pos in (0, 5, t - 1, t, t + w // 2, t + w - 2):
        got_o, got_lse = flash_decode_lse(q, k, v, pos, interpret=True,
                                          window=w)
        want_o, want_lse = decode_attention_reference_lse(q, k, v, pos,
                                                          window=w)
        np.testing.assert_allclose(got_o, want_o, atol=1e-5, rtol=1e-5,
                                   err_msg=f"out pos={pos}")
        np.testing.assert_allclose(got_lse, want_lse, atol=1e-5,
                                   rtol=1e-5, err_msg=f"lse pos={pos}")
