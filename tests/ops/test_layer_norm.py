"""Fused LayerNorm Pallas kernel vs the jnp oracle (interpret mode on CPU).

Covers padding-sensitive shapes (N not a multiple of 8, D not a multiple of
128), leading batch dims, bf16 inputs, and full gradients (dx, dscale, dbias)
through the custom VJP.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.ops import fused_layer_norm, layer_norm_reference

SHAPES = [
    (8, 128),     # exact tiles
    (5, 96),      # both dims padded
    (13, 384),    # rows padded
    (16, 200),    # lanes padded
]


def fused(x, s, b, eps=1e-5):
    return fused_layer_norm(x, s, b, eps, True)  # interpret=True on CPU


@pytest.mark.parametrize("shape", SHAPES)
def test_forward_matches_reference(shape):
    rng = np.random.default_rng(0)
    n, d = shape
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32) * 3 + 1
    s = jnp.asarray(rng.normal(size=(d,)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(d,)), jnp.float32)
    np.testing.assert_allclose(
        fused(x, s, b), layer_norm_reference(x, s, b), atol=1e-5, rtol=1e-5
    )


def test_forward_leading_batch_dims():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 7, 96)), jnp.float32)
    s = jnp.asarray(rng.normal(size=(96,)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(96,)), jnp.float32)
    out = fused(x, s, b)
    assert out.shape == x.shape
    np.testing.assert_allclose(
        out, layer_norm_reference(x, s, b), atol=1e-5, rtol=1e-5
    )


@pytest.mark.parametrize("shape", SHAPES)
def test_gradients_match_reference(shape):
    rng = np.random.default_rng(2)
    n, d = shape
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    s = jnp.asarray(1 + 0.1 * rng.normal(size=(d,)), jnp.float32)
    b = jnp.asarray(0.1 * rng.normal(size=(d,)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)  # non-uniform cotangent

    def loss(f):
        return lambda x, s, b: jnp.sum(w * f(x, s, b))

    got = jax.grad(loss(fused), argnums=(0, 1, 2))(x, s, b)
    want = jax.grad(loss(layer_norm_reference), argnums=(0, 1, 2))(x, s, b)
    for g, r, name in zip(got, want, ("dx", "dscale", "dbias")):
        np.testing.assert_allclose(g, r, atol=1e-4, rtol=1e-4, err_msg=name)


@pytest.mark.parametrize("shape", [(8, 128), (5, 96)])
def test_large_mean_small_spread_is_stable(shape):
    """E[x²]−μ² would catastrophically cancel (or go NaN) here; the centered
    masked variance must stay accurate with |μ| ≫ σ."""
    rng = np.random.default_rng(6)
    n, d = shape
    x = jnp.asarray(1e4 + rng.normal(size=(n, d)), jnp.float32)
    s = jnp.asarray(1 + 0.1 * rng.normal(size=(d,)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(d,)), jnp.float32)
    out = fused(x, s, b)
    assert np.isfinite(np.asarray(out)).all()
    # oracle in float64 (the float32 jnp reference also cancels here)
    x64 = np.asarray(x, np.float64)
    mu = x64.mean(-1, keepdims=True)
    var = x64.var(-1, keepdims=True)
    want = (x64 - mu) / np.sqrt(var + 1e-5) * np.asarray(s) + np.asarray(b)
    np.testing.assert_allclose(out, want.astype(np.float32), atol=5e-2, rtol=5e-2)
    dx = jax.grad(lambda x: jnp.sum(fused(x, s, b)))(x)
    assert np.isfinite(np.asarray(dx)).all()


def test_bfloat16_input_f32_statistics():
    rng = np.random.default_rng(3)
    x32 = jnp.asarray(rng.normal(size=(9, 160)), jnp.float32)
    s = jnp.ones((160,), jnp.float32)
    b = jnp.zeros((160,), jnp.float32)
    out = fused(x32.astype(jnp.bfloat16), s, b)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(
        out, layer_norm_reference(x32, s, b), atol=2e-2, rtol=2e-2
    )


def test_grad_dtype_follows_primals():
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(8, 128)), jnp.bfloat16)
    s = jnp.ones((128,), jnp.float32)
    b = jnp.zeros((128,), jnp.bfloat16)
    dx, ds, db = jax.grad(
        lambda x, s, b: jnp.sum(fused(x, s, b)), argnums=(0, 1, 2)
    )(x, s, b)
    assert dx.dtype == jnp.bfloat16
    assert ds.dtype == jnp.float32
    assert db.dtype == jnp.bfloat16


def test_jit_and_vmap_compose():
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(3, 10, 96)), jnp.float32)
    s = jnp.ones((96,), jnp.float32)
    b = jnp.zeros((96,), jnp.float32)
    jitted = jax.jit(functools.partial(fused_layer_norm, eps=1e-5, interpret=True))
    np.testing.assert_allclose(
        jitted(x, s, b), layer_norm_reference(x, s, b), atol=1e-5, rtol=1e-5
    )
