"""Ring attention vs full attention on the 8-device CPU mesh."""

import numpy as np
import pytest

from elephas_tpu.ops.ring_attention import attention_reference, ring_attention
from elephas_tpu.parallel import build_mesh


def _qkv(b=2, t=64, h=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.normal(size=(b, t, h, d)).astype("float32")
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(causal):
    q, k, v = _qkv()
    mesh = build_mesh(8)
    out = np.asarray(ring_attention(q, k, v, mesh=mesh, causal=causal))
    ref = np.asarray(attention_reference(q, k, v, causal=causal))
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ring_size_one_is_plain_attention():
    q, k, v = _qkv(t=32)
    out = np.asarray(ring_attention(q, k, v, mesh=build_mesh(1)))
    ref = np.asarray(attention_reference(q, k, v))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_indivisible_sequence_rejected():
    q, k, v = _qkv(t=60)  # 60 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh=build_mesh(8))


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_flow(causal):
    """Differentiable end-to-end (training usage), incl. the causal backward
    path through the -inf masking and isneginf guards."""
    import jax
    import jax.numpy as jnp

    q, k, v = _qkv(b=1, t=16, h=2, d=8)
    mesh = build_mesh(8)

    def loss_ring(q):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=causal) ** 2)

    def loss_ref(q):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    g_ring = np.asarray(jax.grad(loss_ring)(jnp.asarray(q)))
    g_ref = np.asarray(jax.grad(loss_ref)(jnp.asarray(q)))
    np.testing.assert_allclose(g_ring, g_ref, atol=2e-4, rtol=2e-4)


def test_gqa_small_kv_rides_the_ring():
    """Ring attention accepts divisor KV heads (the ppermute hops then move
    only the small blocks) and equals the repeated-KV oracle."""
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    q = rng.normal(size=(2, 64, 8, 8)).astype("float32")
    k = rng.normal(size=(2, 64, 2, 8)).astype("float32")
    v = rng.normal(size=(2, 64, 2, 8)).astype("float32")
    mesh = build_mesh(8)
    got = np.asarray(ring_attention(q, k, v, mesh=mesh, causal=True))
    want = np.asarray(attention_reference(
        q, np.repeat(k, 4, axis=2), np.repeat(v, 4, axis=2), causal=True
    ))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [3, 8, 13, 24])
def test_windowed_matches_oracle(window):
    """Sliding windows below / at / spanning / beyond the 8-token shard:
    the ring's absolute-position masks must equal the dense windowed
    oracle, and gradients must flow through the banded partial visits."""
    q, k, v = _qkv()
    mesh = build_mesh(8)
    ref = np.asarray(attention_reference(q, k, v, causal=True,
                                         window=window))
    out = np.asarray(ring_attention(q, k, v, mesh=mesh, causal=True,
                                    window=window))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_windowed_gradients_flow():
    import jax
    import jax.numpy as jnp

    q, k, v = (jnp.asarray(a) for a in _qkv())
    mesh = build_mesh(8)

    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True,
                                      window=13) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True,
                                           window=13) ** 2)

    g = jax.grad(loss, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_window_requires_causal():
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, k, v, mesh=build_mesh(8), causal=False, window=4)
