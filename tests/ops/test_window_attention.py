"""Sliding-window attention: every op path vs a naive masked oracle.

Window convention (Mistral): query ``t`` sees keys ``(t-window, t]``. The
same ``window`` knob must mean the same thing in the dense oracle, the jnp
blockwise flash, the Pallas training kernels (plain + rope-fused + GQA,
forward and gradients), and the flash-decode cache kernel (scalar and
per-row positions) — each is pinned here against an independently written
mask.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elephas_tpu.ops.flash_attention import _flash
from elephas_tpu.ops.flash_decode import (
    decode_attention_reference_lse,
    flash_decode_lse,
)
from elephas_tpu.ops.pallas_flash import (
    flash_attention_rope,
    flash_attention_tpu,
    make_rope_tables,
)
from elephas_tpu.ops.ring_attention import attention_reference

B, T, H, Dh = 2, 40, 4, 16


def _qkv(hkv=H, t=T, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, t, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, t, hkv, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, t, hkv, Dh)), jnp.float32)
    return q, k, v


def _naive(q, k, v, window):
    from elephas_tpu.ops.flash_attention import repeat_kv_heads

    k = repeat_kv_heads(k, q.shape[2])
    v = repeat_kv_heads(v, q.shape[2])
    t = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   precision=jax.lax.Precision.HIGHEST) * (Dh ** -0.5)
    i = jnp.arange(t)
    m = (i[None, :] <= i[:, None]) & (i[None, :] > i[:, None] - window)
    p = jax.nn.softmax(jnp.where(m, s, -jnp.inf), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v,
                      precision=jax.lax.Precision.HIGHEST)


@pytest.mark.parametrize("window", [1, 9, 40, 200])
def test_oracle_matches_naive(window):
    q, k, v = _qkv()
    want = _naive(q, k, v, window)
    got = attention_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_window_requires_causal():
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="causal"):
        attention_reference(q, k, v, causal=False, window=4)


@pytest.mark.parametrize("window", [3, 9])
def test_jnp_flash_forward_and_grads(window):
    q, k, v = _qkv()
    want = _naive(q, k, v, window)
    got = _flash(q, k, v, True, 16, window)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    f_ref = lambda *a: (_naive(*a, window) ** 2).sum()
    f_fl = lambda *a: (_flash(*a, True, 16, window) ** 2).sum()
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(f_fl, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("window", [5, 17])
def test_pallas_kernels_forward_and_grads(window):
    q, k, v = _qkv()
    want = _naive(q, k, v, window)
    got = flash_attention_tpu(q, k, v, True, 16, 16, True, window)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    f_ref = lambda *a: (_naive(*a, window) ** 2).sum()
    f_pl = lambda *a: (
        flash_attention_tpu(*a, True, 16, 16, True, window) ** 2).sum()
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_pl = jax.grad(f_pl, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_pl):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_pallas_rope_gqa_window():
    from elephas_tpu.models.transformer import _rope_angles, _rope_rotate

    window = 9
    q, k, v = _qkv(hkv=2)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))
    cos, sin = _rope_angles(pos, Dh)
    c2, s2 = make_rope_tables(cos, sin)
    qr = _rope_rotate(q, cos[:, :, None, :], sin[:, :, None, :])
    kr = _rope_rotate(k, cos[:, :, None, :], sin[:, :, None, :])
    want = _naive(qr, kr, v, window)
    got = flash_attention_rope(q, k, v, c2, s2, True, 16, 16, True, window)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    # gradients: rotate-then-attend == fused rotated attention
    f_ref = lambda q, k, v: (_naive(
        _rope_rotate(q, cos[:, :, None, :], sin[:, :, None, :]),
        _rope_rotate(k, cos[:, :, None, :], sin[:, :, None, :]),
        v, window) ** 2).sum()
    f_pl = lambda q, k, v: (flash_attention_rope(
        q, k, v, c2, s2, True, 16, 16, True, window) ** 2).sum()
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_pl = jax.grad(f_pl, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_pl):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("pos", [3, 17, np.array([5, 30])])
def test_flash_decode_window(pos):
    rng = np.random.default_rng(1)
    kc = jnp.asarray(rng.normal(size=(B, 2, 48, Dh)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(B, 2, 48, Dh)), jnp.float32)
    qd = jnp.asarray(rng.normal(size=(B, 2, 2, Dh)), jnp.float32)
    window = 7
    want, want_lse = decode_attention_reference_lse(qd, kc, vc, pos, window)
    got, got_lse = flash_decode_lse(qd, kc, vc, pos, interpret=True,
                                    window=window)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(got_lse, want_lse, rtol=1e-6, atol=1e-6)


def test_decode_window_equals_full_when_not_binding():
    rng = np.random.default_rng(2)
    kc = jnp.asarray(rng.normal(size=(B, 2, 32, Dh)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(B, 2, 32, Dh)), jnp.float32)
    qd = jnp.asarray(rng.normal(size=(B, 2, 2, Dh)), jnp.float32)
    full, _ = decode_attention_reference_lse(qd, kc, vc, 5)
    win, _ = decode_attention_reference_lse(qd, kc, vc, 5, window=100)
    np.testing.assert_allclose(win, full, rtol=1e-7, atol=1e-7)


@pytest.mark.parametrize("pos", [3, 11, 40, np.array([5, 57])])
def test_flash_decode_ring_matches_reference(pos):
    # rolling buffer: 16 slots, window 11 — positions far beyond the
    # buffer wrap; kernel must agree with the age-masked reference
    rng = np.random.default_rng(3)
    kc = jnp.asarray(rng.normal(size=(B, 2, 16, Dh)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(B, 2, 16, Dh)), jnp.float32)
    qd = jnp.asarray(rng.normal(size=(B, 2, 2, Dh)), jnp.float32)
    window = 11
    want, want_lse = decode_attention_reference_lse(qd, kc, vc, pos, window,
                                                    ring=True)
    got, got_lse = flash_decode_lse(qd, kc, vc, pos, interpret=True,
                                    window=window, ring=True)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(got_lse, want_lse, rtol=1e-6, atol=1e-6)


def test_ring_requires_window():
    rng = np.random.default_rng(4)
    kc = jnp.asarray(rng.normal(size=(B, 2, 16, Dh)), jnp.float32)
    qd = jnp.asarray(rng.normal(size=(B, 2, 2, Dh)), jnp.float32)
    with pytest.raises(ValueError, match="window"):
        decode_attention_reference_lse(qd, kc, kc, 3, ring=True)
    with pytest.raises(ValueError, match="window"):
        flash_decode_lse(qd, kc, kc, 3, interpret=True, ring=True)
