"""Fused paged attention: the kernel family that decodes DIRECTLY over
the page pool through the block table (``ops/paged_attention.py``).

Three layers of pins:

* the jnp reference oracle (gather-through-table + the exact dense
  reference math) must be BITWISE the dense kernels on the equivalent
  dense cache — this is what makes the CPU paged path bit-identical to
  the dense serving engine;
* the Pallas kernels under ``interpret=True`` must match the oracle to
  float32 accumulation tolerance, and must NEVER read pages wholly past
  ``pos`` (NaN-poison proof — the pages are simply not DMA'd);
* ``TransformerLM.decode_step_paged``/``decode_chunk_paged`` must emit
  logits bitwise equal to dense ``decode_step``/``decode_chunk`` while
  writing only the newly produced rows into their owning pages.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models.transformer import TransformerLM
from elephas_tpu.ops.flash_decode import (
    decode_attention_reference,
    decode_attention_reference_lse,
)
from elephas_tpu.ops.paged_attention import (
    paged_chunk_reference,
    paged_decode_reference,
    paged_decode_reference_lse,
    paged_flash_chunk,
    paged_flash_decode_lse,
    paged_view_rows,
)

pytestmark = pytest.mark.paged

S, Hkv, G, Dh = 3, 2, 2, 16
PAGE, M = 8, 5
P = S * M + 1          # distinct page per (slot, logical index) + trash
T = M * PAGE


def _setup(seed=0, trash=7.25):
    """Pool + table + the equivalent dense cache. ``trash`` poisons the
    trash page with finite garbage (the masking contract: trash content
    is arbitrary but FINITE, and masked contributions are exactly 0)."""
    rng = np.random.default_rng(seed)
    kp = rng.standard_normal((P, Hkv, PAGE, Dh)).astype(np.float32)
    vp = rng.standard_normal((P, Hkv, PAGE, Dh)).astype(np.float32)
    kp[0] = vp[0] = trash
    table = 1 + np.arange(S * M, dtype=np.int32).reshape(S, M)
    # dense cache = the gathered view (gather is pure indexing)
    tbl = jnp.asarray(table)
    kd = paged_view_rows(jnp.asarray(kp), tbl, PAGE)
    vd = paged_view_rows(jnp.asarray(vp), tbl, PAGE)
    q = rng.standard_normal((S, Hkv, G, Dh)).astype(np.float32)
    return (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp), tbl,
            kd, vd)


@pytest.mark.parametrize("window", [None, 11])
def test_decode_oracle_bitwise_vs_dense(window):
    q, kp, vp, table, kd, vd = _setup()
    pos = jnp.asarray([5, 17, T - 1], jnp.int32)   # mid-page, page edge
    o_ref, lse_ref = decode_attention_reference_lse(q, kd, vd, pos,
                                                    window=window)
    o_pag, lse_pag = paged_decode_reference_lse(q, kp, vp, table, pos,
                                                PAGE, window=window)
    assert (np.asarray(o_ref) == np.asarray(o_pag)).all()
    assert (np.asarray(lse_ref) == np.asarray(lse_pag)).all()
    o2 = paged_decode_reference(q, kp, vp, table, pos, PAGE, window=window)
    assert (np.asarray(o_ref) == np.asarray(o2)).all()


@pytest.mark.parametrize("window", [None, 11])
def test_chunk_oracle_bitwise_vs_dense_chunk_math(window):
    """The chunk oracle must reproduce ``decode_chunk``'s exact einsum/
    softmax block (that block is re-derived here verbatim)."""
    import jax
    rng = np.random.default_rng(1)
    C = 4
    q = jnp.asarray(
        rng.standard_normal((S, Hkv, G, C, Dh)).astype(np.float32))
    _, kp, vp, table, kd, vd = _setup(seed=1)
    pos0 = jnp.asarray([3, 14, 26], jnp.int32)
    pos_b = pos0[:, None] + jnp.arange(C)[None, :]
    slots = jnp.arange(T)[None, None, :]
    m = slots <= pos_b[:, :, None]
    if window is not None:
        m &= slots > pos_b[:, :, None] - window
    scores = jnp.einsum(
        "bkgsd,bktd->bkgst", q, kd,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST) * (Dh ** -0.5)
    scores = jnp.where(m[:, None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    want = jnp.einsum(
        "bkgst,bktd->bkgsd", probs, vd,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)
    got = paged_chunk_reference(q, kp, vp, table, pos0, PAGE,
                                window=window)
    assert (np.asarray(want) == np.asarray(got)).all()


@pytest.mark.parametrize("window", [None, 11])
def test_pallas_decode_interpret_matches_oracle(window):
    q, kp, vp, table, _, _ = _setup(seed=2)
    pos = jnp.asarray([7, 12, 31], jnp.int32)
    o_ref, lse_ref = paged_decode_reference_lse(q, kp, vp, table, pos,
                                                PAGE, window=window)
    o_ker, lse_ker = paged_flash_decode_lse(q, kp, vp, table, pos, PAGE,
                                            window=window, interpret=True)
    np.testing.assert_allclose(np.asarray(o_ker), np.asarray(o_ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(lse_ker), np.asarray(lse_ref),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("window", [None, 9])
def test_pallas_chunk_interpret_matches_oracle(window):
    rng = np.random.default_rng(3)
    C = 4
    q = jnp.asarray(
        rng.standard_normal((S, Hkv, G, C, Dh)).astype(np.float32))
    _, kp, vp, table, _, _ = _setup(seed=3)
    pos0 = jnp.asarray([6, 13, 22], jnp.int32)   # 6+3 straddles page 1
    want = paged_chunk_reference(q, kp, vp, table, pos0, PAGE,
                                 window=window)
    got = paged_flash_chunk(q, kp, vp, table, pos0, PAGE, window=window,
                            interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_pallas_never_reads_pages_past_pos():
    """Pages wholly past ``pos`` are never DMA'd: poisoning them with NaN
    must not perturb the output (the oracle masks them; the kernel's
    block index map never touches them)."""
    q, kp, vp, table, _, _ = _setup(seed=4)
    pos = jnp.asarray([5, 9, 12], jnp.int32)     # pages >= 2 dead for all
    clean_o, clean_l = paged_flash_decode_lse(q, kp, vp, table, pos, PAGE,
                                              interpret=True)
    kp_n, vp_n = np.asarray(kp).copy(), np.asarray(vp).copy()
    for s in range(S):
        for mcell in range(2, M):                # wholly past every pos
            kp_n[int(table[s, mcell])] = np.nan
            vp_n[int(table[s, mcell])] = np.nan
    pois_o, pois_l = paged_flash_decode_lse(
        q, jnp.asarray(kp_n), jnp.asarray(vp_n), table, pos, PAGE,
        interpret=True)
    assert np.isfinite(np.asarray(pois_o)).all()
    assert (np.asarray(clean_o) == np.asarray(pois_o)).all()
    assert (np.asarray(clean_l) == np.asarray(pois_l)).all()


def test_trash_page_masked_exactly():
    """Unmapped table cells (trash, id 0) within the visible range must
    contribute exactly zero: finite trash garbage × exp(-inf) = 0."""
    q, kp, vp, table, kd, vd = _setup(seed=5, trash=1e4)
    pos = jnp.asarray([4, 4, 4], jnp.int32)
    want = decode_attention_reference(q, kd, vd, pos)
    got = paged_decode_reference(q, kp, vp, table, pos, PAGE)
    assert (np.asarray(want) == np.asarray(got)).all()


class TestTransformerPagedMethods:
    """decode_step_paged / decode_chunk_paged vs their dense siblings on
    a real model: logits AND written-KV bitwise identity."""

    def _mk(self, **kw):
        cfg = dict(vocab=17, d_model=16, n_heads=4, n_layers=2, d_ff=32,
                   max_len=64)
        cfg.update(kw)
        model = TransformerLM(**cfg)
        params = model.init(0)
        return model, params

    def _pools(self, model, B, M_):
        L = model.n_layers
        hkv = model.n_kv_heads
        dh = model.d_model // model.n_heads
        T_ = M_ * PAGE
        cache = {k: jnp.zeros((L, B, hkv, T_, dh), jnp.float32)
                 for k in ("k", "v")}
        pool = {k: jnp.full((L, B * M_ + 1, hkv, PAGE, dh), 7.25,
                            jnp.float32) for k in ("k", "v")}
        table = jnp.asarray(1 + np.arange(B * M_).reshape(B, M_),
                            jnp.int32)
        return cache, pool, table

    @pytest.mark.parametrize("windows", [None, (None, 8)])
    def test_bitwise_identity_chunk_then_steps(self, windows):
        kw = {} if windows is None else {"attn_window": windows}
        model, params = self._mk(**kw)
        rng = np.random.default_rng(0)
        B, M_ = 3, 6
        cache, pool, table = self._pools(model, B, M_)
        toks = jnp.asarray(rng.integers(0, 17, (B, 11)), jnp.int32)
        lg_d, cache = model.decode_chunk(params, toks, 0, cache)
        lg_p, pool = model.decode_chunk_paged(params, toks, 0, pool,
                                              table, PAGE)
        assert (np.asarray(lg_d) == np.asarray(lg_p)).all()
        # written KV bytes identical through the gathered view
        for key in ("k", "v"):
            for l in range(model.n_layers):
                view = paged_view_rows(pool[key][l], table, PAGE)
                assert (np.asarray(cache[key][l][:, :, :11])
                        == np.asarray(view[:, :, :11])).all()
        pos = jnp.full((B,), 11, jnp.int32)
        for step in range(8):                    # crosses page boundary
            tok = jnp.asarray(rng.integers(0, 17, (B,)), jnp.int32)
            lg_d, cache = model.decode_step(params, tok, pos, cache)
            lg_p, pool = model.decode_step_paged(params, tok, pos, pool,
                                                 table, PAGE)
            assert (np.asarray(lg_d) == np.asarray(lg_p)).all(), step
            pos = pos + 1

    def test_per_row_verify_chunk_bitwise(self):
        model, params = self._mk()
        rng = np.random.default_rng(1)
        B, M_ = 3, 6
        cache, pool, table = self._pools(model, B, M_)
        toks = jnp.asarray(rng.integers(0, 17, (B, 9)), jnp.int32)
        _, cache = model.decode_chunk(params, toks, 0, cache)
        _, pool = model.decode_chunk_paged(params, toks, 0, pool, table,
                                           PAGE)
        pos0 = jnp.asarray([9, 7, 8], jnp.int32)  # uneven (spec verify)
        ch = jnp.asarray(rng.integers(0, 17, (B, 5)), jnp.int32)
        lg_d, _ = model.decode_chunk(params, ch, pos0, cache)
        lg_p, _ = model.decode_chunk_paged(params, ch, pos0, pool, table,
                                           PAGE)
        assert (np.asarray(lg_d) == np.asarray(lg_p)).all()

    def test_unmapped_write_lands_in_trash(self):
        """Positions past the table's capacity write to the trash page
        and never corrupt mapped pages."""
        model, params = self._mk()
        rng = np.random.default_rng(2)
        B, M_ = 3, 6
        _, pool, table = self._pools(model, B, M_)
        toks = jnp.asarray(rng.integers(0, 17, (B, 9)), jnp.int32)
        _, pool = model.decode_chunk_paged(params, toks, 0, pool, table,
                                           PAGE)
        tbl2 = table[:, :2]                      # capacity 16
        tok = jnp.asarray(rng.integers(0, 17, (B,)), jnp.int32)
        over = jnp.full((B,), 20, jnp.int32)
        _, pool2 = model.decode_step_paged(params, tok, over, pool, tbl2,
                                           PAGE)
        for key in ("k", "v"):
            assert (np.asarray(pool2[key][:, 1:])
                    == np.asarray(pool[key][:, 1:])).all()

    def test_ring_cache_refused(self):
        model, params = self._mk(attn_window=8)  # all-windowed → rolling
        _, pool, table = self._pools(model, 2, 4)
        tok = jnp.zeros((2,), jnp.int32)
        with pytest.raises(ValueError, match="linear-horizon"):
            model.decode_step_paged(params, tok, 0, pool, table, PAGE)
        with pytest.raises(ValueError, match="linear-horizon"):
            model.decode_chunk_paged(params, tok[:, None], 0, pool,
                                     table, PAGE)
