"""ElephasEstimator/Transformer pipeline tests (reference: tests/test_ml_model.py)."""

import numpy as np
import pytest

from elephas_tpu import (
    ElephasEstimator,
    ElephasTransformer,
    load_ml_estimator,
    load_ml_transformer,
)
from elephas_tpu.data import Row
from elephas_tpu.ml import Pipeline, StandardScaler, StringIndexer, df_to_simple_rdd
from elephas_tpu.mllib import Vectors

from ..conftest import make_classifier


@pytest.fixture
def df(spark_session, toy_classification):
    x, y = toy_classification
    rows = [
        Row(features=Vectors.dense(xi.astype("float64")), label=float(yi.argmax()))
        for xi, yi in zip(x, y)
    ]
    return spark_session.createDataFrame(rows)


def make_estimator(num_workers=4, epochs=3):
    import keras

    model = make_classifier()
    est = ElephasEstimator()
    est.set_keras_model_config(model.to_json())
    est.set_optimizer_config(keras.optimizers.serialize(keras.optimizers.Adam()))
    est.set_loss("categorical_crossentropy")
    est.set_metrics(["accuracy"])
    est.set_categorical(True)
    est.set_nb_classes(3)
    est.set_num_workers(num_workers)
    est.set_epochs(epochs)
    est.set_batch_size(16)
    est.set_validation_split(0.0)
    est.set_mode("synchronous")
    est.set_parameter_server_mode("jax")
    return est


def test_df_to_simple_rdd(df):
    rdd = df_to_simple_rdd(df, categorical=True, nb_classes=3)
    x0, y0 = rdd.first()
    assert x0.shape == (10,)
    assert y0.shape == (3,)
    assert y0.sum() == 1.0


def test_estimator_fit_transform(df, toy_classification):
    x, y = toy_classification
    est = make_estimator()
    transformer = est.fit(df)
    assert isinstance(transformer, ElephasTransformer)
    out = transformer.transform(df)
    assert "prediction" in out.columns
    preds = np.array([r.prediction for r in out.collect()])
    labels = np.array([r.label for r in out.collect()])
    acc = float((preds == labels).mean())
    assert acc > 0.34, f"pipeline accuracy too low: {acc}"
    assert preds.dtype == np.float64


def test_pipeline_with_feature_stages(spark_session, toy_classification):
    x, y = toy_classification
    rows = [
        Row(raw=Vectors.dense(xi.astype("float64")),
            category=["a", "b", "c"][int(yi.argmax())])
        for xi, yi in zip(x, y)
    ]
    df = spark_session.createDataFrame(rows)
    est = make_estimator(epochs=3)
    est.set_features_col("scaled")
    est.set_label_col("label")
    pipeline = Pipeline(
        stages=[
            StringIndexer(inputCol="category", outputCol="label"),
            StandardScaler(inputCol="raw", outputCol="scaled"),
            est,
        ]
    )
    fitted = pipeline.fit(df)
    out = fitted.transform(df)
    assert "prediction" in out.columns
    assert out.count() == len(rows)


def test_estimator_save_load(tmp_path):
    est = make_estimator()
    path = str(tmp_path / "estimator.h5")
    est.save(path)
    loaded = load_ml_estimator(path)
    assert loaded.get_mode() == "synchronous"
    assert loaded.get_nb_classes() == 3
    assert loaded.get_keras_model_config() == est.get_keras_model_config()


def test_transformer_save_load(tmp_path, df, toy_classification):
    x, _ = toy_classification
    transformer = make_estimator(epochs=1).fit(df)
    path = str(tmp_path / "transformer.h5")
    transformer.save(path)
    loaded = load_ml_transformer(path)
    preds1 = loaded.get_model().predict(x[:4].astype("float32"), verbose=0)
    preds2 = transformer.get_model().predict(x[:4].astype("float32"), verbose=0)
    assert np.allclose(preds1, preds2, atol=1e-6)


def test_explain_params():
    est = make_estimator()
    text = est.explainParams()
    assert "keras_model_config" in text
    assert "num_workers" in text


def test_unknown_param_rejected():
    with pytest.raises(ValueError, match="Unknown param"):
        ElephasEstimator(not_a_param=1)
