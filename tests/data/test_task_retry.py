"""Task retry + TaskContext on the RDD facade.

The reference inherits task retry from Spark L0 (``spark.task.maxFailures``,
SURVEY.md §5.3); the facade reproduces it: each ``mapPartitions`` partition
call is a task that runs under a ``TaskContext`` and is retried on exception.
"""

import threading

import pytest

from elephas_tpu.data import SparkConf, SparkContext, TaskContext, TaskFailedError


def test_task_context_none_on_driver():
    assert TaskContext.get() is None


def test_task_context_inside_partition(spark_context):
    rdd = spark_context.parallelize(range(8), 4)

    def f(it):
        ctx = TaskContext.get()
        assert ctx is not None
        yield (ctx.partitionId(), ctx.attemptNumber(), ctx.stageId())
        # consume so the partition isn't empty-looking
        list(it)

    out = rdd.mapPartitions(f).collect()
    pids = sorted(p for p, _, _ in out)
    assert pids == [0, 1, 2, 3]
    assert all(a == 0 for _, a, _ in out)
    # all tasks of one mapPartitions call share a stage id
    assert len({s for _, _, s in out}) == 1


def test_flaky_partition_retried_until_success(spark_context):
    rdd = spark_context.parallelize(range(8), 4)
    failures = {"n": 0}
    lock = threading.Lock()

    def f(it):
        ctx = TaskContext.get()
        if ctx.partitionId() == 2 and ctx.attemptNumber() < 2:
            with lock:
                failures["n"] += 1
            raise RuntimeError("injected fault")
        yield sum(it) + ctx.attemptNumber()

    out = rdd.mapPartitions(f).collect()
    assert failures["n"] == 2
    # partition 2 holds [4, 5] and succeeded on attempt 2
    assert sorted(out) == [1, 5, 11, 13]


def test_max_failures_exhausted_aborts_job(spark_context):
    rdd = spark_context.parallelize(range(4), 2)

    def always_fails(it):
        raise RuntimeError("permanent fault")
        yield

    with pytest.raises(TaskFailedError) as e:
        rdd.mapPartitions(always_fails).collect()
    assert e.value.attempts == 4  # Spark's spark.task.maxFailures default
    assert isinstance(e.value.cause, RuntimeError)


def test_max_failures_configurable():
    conf = (
        SparkConf().setMaster("local[2]").setAppName("t")
        .set("spark.task.maxFailures", 1)
    )
    sc = SparkContext(conf=conf)
    assert sc.getConf().get("spark.task.maxFailures") == 1
    attempts = {"n": 0}

    def f(it):
        attempts["n"] += 1
        raise RuntimeError("boom")
        yield

    with pytest.raises(TaskFailedError):
        sc.parallelize([1, 2], 1).mapPartitions(f).collect()
    assert attempts["n"] == 1
    sc.stop()


def test_nested_map_partitions_restores_outer_context(spark_context):
    """A partition function running its own local mapPartitions must get its
    outer TaskContext back afterwards (restore, not clear)."""

    def outer(it):
        before = TaskContext.get()
        # nested 1-partition job runs sequentially on this same thread
        inner = spark_context.parallelize([1, 2, 3], 1)
        inner_out = inner.mapPartitions(lambda i: [sum(i)]).collect()
        after = TaskContext.get()
        assert after is not None
        yield (before.partitionId(), after.partitionId(), inner_out[0],
               sum(it))

    out = spark_context.parallelize(range(4), 2).mapPartitions(outer).collect()
    for before_pid, after_pid, inner_sum, _ in out:
        assert before_pid == after_pid
        assert inner_sum == 6


def test_context_cleared_after_tasks(spark_context):
    rdd = spark_context.parallelize(range(4), 2)
    rdd.mapPartitions(lambda it: [sum(it)]).collect()
    assert TaskContext.get() is None
