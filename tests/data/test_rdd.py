"""Spark-core facade semantics (SURVEY.md §7.0: the Spark surface elephas uses)."""

import threading
import time

from elephas_tpu.data import SparkConf, SparkContext


def test_parallelize_slicing(spark_context):
    rdd = spark_context.parallelize(list(range(10)), 3)
    parts = rdd.partitions()
    assert len(parts) == 3
    assert sum(len(p) for p in parts) == 10
    # Spark-style contiguous slicing
    assert parts[0] + parts[1] + parts[2] == list(range(10))


def test_repartition_balance(spark_context):
    rdd = spark_context.parallelize(list(range(100)), 2).repartition(8)
    sizes = [len(p) for p in rdd.partitions()]
    assert len(sizes) == 8
    assert max(sizes) - min(sizes) <= 1
    assert sorted(rdd.collect()) == list(range(100))


def test_map_filter_collect_count(spark_context):
    rdd = spark_context.parallelize(list(range(10)), 4)
    out = rdd.map(lambda v: v * 2).filter(lambda v: v % 4 == 0)
    assert sorted(out.collect()) == [0, 4, 8, 12, 16]
    assert out.count() == 5


def test_map_partitions_generator(spark_context):
    rdd = spark_context.parallelize(list(range(12)), 4)

    def gen(it):
        yield sum(it)

    sums = rdd.mapPartitions(gen).collect()
    assert len(sums) == 4
    assert sum(sums) == sum(range(12))


def test_map_partitions_concurrency():
    """Partitions must run concurrently (async-mode interleaving depends on it)."""
    sc = SparkContext(master="local[4]")
    barrier = threading.Barrier(4, timeout=10)

    def wait_all(it):
        barrier.wait()  # deadlocks unless all 4 partitions run concurrently
        yield len(list(it))

    rdd = sc.parallelize(list(range(8)), 4)
    out = rdd.mapPartitions(wait_all).collect()
    assert sum(out) == 8


def test_broadcast(spark_context):
    b = spark_context.broadcast({"w": [1, 2, 3]})
    rdd = spark_context.parallelize([0, 1], 2)
    out = rdd.mapPartitions(lambda it: iter([b.value["w"][0]])).collect()
    assert out == [1, 1]


def test_zip_and_take(spark_context):
    a = spark_context.parallelize([1, 2, 3], 2)
    b = spark_context.parallelize(["a", "b", "c"], 3)
    assert a.zip(b).collect() == [(1, "a"), (2, "b"), (3, "c")]
    assert a.take(2) == [1, 2]
    assert a.first() == 1


def test_spark_conf_construction():
    conf = SparkConf().setMaster("local[2]").setAppName("x")
    sc = SparkContext(conf=conf)
    assert sc.defaultParallelism == 2
    assert sc.appName == "x"
    sc.stop()
