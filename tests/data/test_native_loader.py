"""Native prefetching batch loader: completeness, determinism, concurrency."""

import numpy as np
import pytest

from elephas_tpu.data.native_loader import NativeBatchLoader


def _data(n=257, d=5, c=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, c)).astype(np.float32)
    return x, y


def test_epoch_is_complete_shuffled_permutation():
    x, y = _data()
    with NativeBatchLoader(x, y, batch_size=32) as dl:
        got_x, got_y = [], []
        sizes = []
        for xb, yb in dl.epoch(seed=7):
            assert xb.shape[1:] == x.shape[1:]
            got_x.append(xb)
            got_y.append(yb)
            sizes.append(xb.shape[0])
        gx = np.concatenate(got_x)
        gy = np.concatenate(got_y)
    assert gx.shape == x.shape
    assert sizes[-1] == 257 % 32  # true short final batch
    # every source row appears exactly once (match rows by sorting)
    order = np.lexsort(gx.T)
    base = np.lexsort(x.T)
    np.testing.assert_array_equal(gx[order], x[base])
    # x/y pairing preserved through the shuffle
    np.testing.assert_array_equal(gy[order], y[base])
    # and it actually shuffled
    assert not np.array_equal(gx, x)


def test_deterministic_per_seed_and_varies_across_seeds():
    x, y = _data(n=96)
    with NativeBatchLoader(x, y, batch_size=16) as dl:
        a = [xb.copy() for xb, _ in dl.epoch(seed=3)]
        b = [xb.copy() for xb, _ in dl.epoch(seed=3)]
        c = [xb.copy() for xb, _ in dl.epoch(seed=4)]
    for xa, xb_ in zip(a, b):
        np.testing.assert_array_equal(xa, xb_)
    assert any(not np.array_equal(xa, xc) for xa, xc in zip(a, c))


def test_many_epochs_stress():
    """Epoch restarts (including abandoned mid-epoch iterators) must not
    deadlock or corrupt batches."""
    x, y = _data(n=128, d=3, c=2)
    x[:, 0] = np.arange(128)  # row id channel
    with NativeBatchLoader(x, y, batch_size=16, n_prefetch=3,
                           n_threads=3) as dl:
        for e in range(30):
            it = dl.epoch(seed=e)
            if e % 3 == 1:
                next(it)  # abandon mid-epoch → restart races exercised
                continue
            ids = np.concatenate([xb[:, 0] for xb, _ in it])
            np.testing.assert_array_equal(np.sort(ids), np.arange(128))


def test_nd_features_roundtrip():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(40, 4, 3)).astype(np.float32)  # image-like
    y = rng.normal(size=(40, 2)).astype(np.float32)
    with NativeBatchLoader(x, y, batch_size=8) as dl:
        for xb, yb in dl.epoch(seed=0):
            assert xb.shape[1:] == (4, 3)
            for row_x, row_y in zip(xb, yb):
                src = np.where((y == row_y).all(axis=1))[0]
                assert len(src) == 1
                np.testing.assert_array_equal(row_x, x[src[0]])


def test_validation():
    x, y = _data(n=8)
    with pytest.raises(ValueError, match="row counts"):
        NativeBatchLoader(x, y[:4], batch_size=2)
    with pytest.raises(ValueError, match="empty"):
        NativeBatchLoader(x[:0], y[:0], batch_size=2)
    dl = NativeBatchLoader(x, y, batch_size=2)
    dl.close()
    with pytest.raises(RuntimeError, match="closed"):
        next(dl.epoch(0))
