"""Spark-SQL facade semantics (the DataFrame surface the ML skin uses)."""

import numpy as np
import pytest

from elephas_tpu.data import Row, SparkSession
from elephas_tpu.mllib import Vectors


def test_row_access():
    r = Row(features=[1, 2], label=1.0)
    assert r.label == 1.0
    assert r["features"] == [1, 2]
    assert r.asDict() == {"features": [1, 2], "label": 1.0}
    with pytest.raises(AttributeError):
        _ = r.missing


def test_create_dataframe_and_select(spark_session):
    df = spark_session.createDataFrame(
        [(1.0, 2.0), (3.0, 4.0)], schema=["a", "b"]
    )
    assert df.columns == ["a", "b"]
    assert df.count() == 2
    sel = df.select("b")
    assert sel.columns == ["b"]
    assert [r.b for r in sel.collect()] == [2.0, 4.0]


def test_with_column_and_rdd(spark_session):
    df = spark_session.createDataFrame(
        [Row(features=Vectors.dense([1.0, 0.0]), label=0.0),
         Row(features=Vectors.dense([0.0, 1.0]), label=1.0)]
    )
    df2 = df.withColumn("prediction", lambda r: r.label + 1)
    assert [r.prediction for r in df2.collect()] == [1.0, 2.0]
    feats = df.rdd.map(lambda r: r.features.toArray()).collect()
    assert np.allclose(feats[1], [0.0, 1.0])


def test_random_split(spark_session):
    df = spark_session.createDataFrame([(float(i),) for i in range(100)], ["v"])
    a, b = df.randomSplit([0.8, 0.2], seed=1)
    assert a.count() + b.count() == 100
    assert 60 <= a.count() <= 95
