"""The pinned fleet chaos scenario (ISSUE 17 acceptance): a bursty
multi-tenant trace with a partition KILLED mid-run and a replacement
joining — deterministically, on the SimClock, asserting:

- every request reaches a terminal state (nothing lost to the crash),
- p99 deadline misses stay bounded (asserted threshold),
- surviving AND migrated streams emit zero divergent tokens vs the
  undisturbed baseline run,
- on paged partitions, page accounting is exact (``kv.check()``) at
  every fleet step throughout the kill/join churn.

Everything here is ``chaos``-marked alongside the resilience suite's
pinned scenarios, and ``fleet``-marked for `make test-fleet`.
"""

import pytest

import jax.numpy as jnp

from elephas_tpu.fleet import (FleetPolicy, FleetRouter, SimClock,
                               TrafficModel, run_trace)
from elephas_tpu.models.transformer import TransformerLM
from elephas_tpu.serving import ServingEngine

pytestmark = [pytest.mark.fleet, pytest.mark.chaos]

KILL_AT = 2.0     # mid-burst: partition 0 dies with requests in flight
JOIN_AT = 2.5     # replacement joins before the backlog drains
STEP_DT = 0.05
MISS_BOUND = 0.1  # ≤10% of deadline-carrying requests may miss p99-style


def _model():
    return TransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=2,
                         d_ff=32, max_len=48)


def _trace():
    # bursty + multi-tenant + a sampled fraction, exactly the harness's
    # point: interactive tenants carry deadlines, batch tenants don't
    return TrafficModel(seed=3, base_rps=4.0, duration_s=12.0,
                        n_tenants=4, sampled_frac=0.5,
                        burst_amp=2.0).generate()


def _run(trace, *, paged, chaos, check_every_step=False):
    clock = SimClock()

    def factory(pid):
        return ServingEngine(_model.model, _model.params, n_slots=4,
                             max_queue=8, paged=paged, page_size=4,
                             clock=clock, perf_clock=clock)

    router = FleetRouter(factory, 2, policy=FleetPolicy(), clock=clock,
                         lease_s=0.5)
    if not check_every_step:
        snap = run_trace(router, trace, clock=clock, step_dt=STEP_DT,
                         chaos=chaos)
        return router, snap
    # hand-rolled replay loop so kv.check() runs after EVERY fleet step
    pending = sorted(trace.requests, key=lambda r: r.arrival_s)
    events = sorted(chaos or [], key=lambda e: e["t"])
    i = e = steps = 0
    while True:
        now = clock()
        while e < len(events) and events[e]["t"] <= now:
            ev = events[e]
            e += 1
            (router.kill_partition(ev["pid"]) if ev["op"] == "kill"
             else router.join_partition())
        while i < len(pending) and pending[i].arrival_s <= now:
            router.submit(pending[i])
            i += 1
        router.step()
        for pid in router.partition_ids():
            router._engines[pid].kv.check()  # exact page accounting
        if i >= len(pending) and e >= len(events) and router.active == 0:
            break
        clock.advance(STEP_DT)
        steps += 1
        assert steps < 20000
    return router, router.snapshot()


def setup_module():
    _model.model = _model()
    _model.params = {k: jnp.asarray(v)
                     for k, v in _model.model.init(seed=1).items()}


CHAOS = [{"t": KILL_AT, "op": "kill", "pid": 0},
         {"t": JOIN_AT, "op": "join"}]


def test_pinned_chaos_dense_zero_divergence_and_bounded_misses():
    trace = _trace()
    base_router, base = _run(trace, paged=False, chaos=None)
    router, snap = _run(trace, paged=False, chaos=CHAOS)

    # nothing lost: every request terminal, the fleet drained
    f = snap["fleet"]
    assert f["done"] == len(trace) and f["queued"] == 0
    assert f["epoch_changes"] >= 2      # the kill's expiry + the join
    assert router.migrations >= 1       # in-flight work moved

    # bounded deadline misses under the kill/join churn
    slo = snap["slo"]
    assert slo["deadline_done"] == slo["with_deadline"]
    miss_frac = slo["deadline_missed"] / slo["deadline_done"]
    assert miss_frac <= MISS_BOUND, (
        f"{slo['deadline_missed']}/{slo['deadline_done']} deadline misses")

    # zero token divergence: surviving AND migrated streams
    base_res = base_router.results()
    chaos_res = router.results()
    migrated = [rid for rid, st in chaos_res.items() if st.migrations > 0]
    assert migrated, "the kill must actually migrate at least one stream"
    for rid, st in base_res.items():
        assert chaos_res[rid].tokens == st.tokens, f"{rid} diverged"
    # deterministic replay: the same chaos run pins the same snapshot
    _, snap2 = _run(trace, paged=False, chaos=CHAOS)
    assert snap2["fleet"] == snap["fleet"]
    assert snap2["slo"] == snap["slo"]


@pytest.mark.slow
def test_pinned_chaos_paged_exact_page_accounting_throughout():
    """Same scenario on PAGED partitions, ``kv.check()`` after every
    fleet step: the kill drops a whole partition's pages with it, the
    join brings a fresh pool, and migration re-prefills — page refcounts
    must stay exact through all of it."""
    trace = _trace()
    router, snap = _run(trace, paged=True, chaos=CHAOS,
                        check_every_step=True)
    f = snap["fleet"]
    assert f["done"] == len(trace) and f["queued"] == 0
    assert router.migrations >= 1
    slo = snap["slo"]
    assert (slo["deadline_missed"] / max(slo["deadline_done"], 1)
            <= MISS_BOUND)
    # paged vs dense identity: the same trace's streams match the dense
    # chaos run (the engine pins paged==dense; the fleet must preserve it)
    dense_router, _ = _run(trace, paged=False, chaos=CHAOS)
    dense = dense_router.results()
    for rid, st in router.results().items():
        assert st.tokens == dense[rid].tokens, f"{rid} diverged paged/dense"
