"""FleetPolicy: strict priority tiers, DRR fairness under Zipf skew,
token-bucket rate limits, deadline shedding, overload backpressure."""

import pytest

from elephas_tpu.fleet import FleetPolicy
from elephas_tpu.fleet.traffic import TraceRequest

pytestmark = pytest.mark.fleet


def _req(rid, tenant=0, max_new=4, priority=0, deadline_s=None,
         arrival_s=0.0):
    return TraceRequest(request_id=rid, arrival_s=arrival_s, tenant=tenant,
                        prompt=[1, 2], max_new=max_new, priority=priority,
                        deadline_s=deadline_s)


def _drain(policy, now):
    out = []
    while True:
        d = policy.poll(now)
        if d is None:
            return out
        out.append(d)


def test_higher_tier_dispatches_first():
    p = FleetPolicy()
    p.submit(_req("b0", tenant=0, priority=0), 0.0)
    p.submit(_req("b1", tenant=1, priority=0), 0.0)
    p.submit(_req("i0", tenant=2, priority=1), 0.0)
    order = [r.request_id for kind, r in _drain(p, 0.0)]
    assert order[0] == "i0"
    assert set(order) == {"i0", "b0", "b1"}


def test_drr_interleaves_heavy_and_light_tenant():
    """Tenant 0 floods 10 requests, tenant 1 submits 2: DRR must serve
    tenant 1 long before tenant 0's backlog drains (no FIFO starvation),
    and equal-cost tenants alternate."""
    p = FleetPolicy(quantum=4.0)
    for i in range(10):
        p.submit(_req(f"h{i}", tenant=0), 0.0)
    p.submit(_req("l0", tenant=1), 0.0)
    p.submit(_req("l1", tenant=1), 0.0)
    order = [r.request_id for kind, r in _drain(p, 0.0)]
    assert len(order) == 12
    # both light requests land within the first four dispatches
    assert {"l0", "l1"} <= set(order[:4])


def test_drr_token_cost_throttles_expensive_tenant():
    """Tenant 0's requests cost 8 tokens, tenant 1's cost 2: with a
    quantum of 4, tenant 1 gets ~4x the REQUEST rate (equal token
    share), so its queue drains much earlier."""
    p = FleetPolicy(quantum=4.0)
    for i in range(4):
        p.submit(_req(f"e{i}", tenant=0, max_new=8), 0.0)
        p.submit(_req(f"c{i}", tenant=1, max_new=2), 0.0)
    order = [r.request_id for kind, r in _drain(p, 0.0)]
    cheap_done = max(order.index(f"c{i}") for i in range(4))
    exp_done = max(order.index(f"e{i}") for i in range(4))
    assert cheap_done < exp_done
    # all four cheap requests dispatch before the LAST two expensive ones
    assert cheap_done < order.index("e2")


def test_rate_limit_skips_until_refill():
    """Tenant 0 limited to 2 tokens/s with burst 4: its first request
    (4 tokens) drains the bucket; the second must wait ~2s of refill
    while unlimited tenant 1 keeps dispatching."""
    p = FleetPolicy(rate_limits={0: (2.0, 4.0)})
    p.submit(_req("a0", tenant=0, max_new=4), 0.0)
    p.submit(_req("a1", tenant=0, max_new=4), 0.0)
    p.submit(_req("b0", tenant=1, max_new=4), 0.0)
    got = [r.request_id for kind, r in _drain(p, 0.0)]
    assert "a0" in got and "b0" in got and "a1" not in got
    assert p.queue_depth == 1
    assert _drain(p, 1.0) == []          # bucket at 2 of 4 needed
    late = [r.request_id for kind, r in _drain(p, 2.0)]
    assert late == ["a1"]


def test_expired_deadline_shed_not_dispatched():
    p = FleetPolicy()
    p.submit(_req("d0", deadline_s=1.0, arrival_s=0.0), 0.0)
    p.submit(_req("ok", tenant=1), 0.0)
    out = _drain(p, 2.0)  # now past d0's absolute deadline
    kinds = {r.request_id: kind for kind, r in out}
    assert kinds == {"d0": "shed", "ok": "dispatch"}


def test_unmeetable_budget_shed_with_itl_floor():
    """Deadline not yet expired, but budget * itl floor overruns it —
    provably hopeless, shed now; same deadline with a small budget
    dispatches."""
    p = FleetPolicy(itl_estimate_s=1.0)
    p.submit(_req("hopeless", max_new=10, deadline_s=5.0), 0.0)
    p.submit(_req("fine", tenant=1, max_new=3, deadline_s=5.0), 0.0)
    kinds = {r.request_id: kind for kind, r in _drain(p, 0.0)}
    assert kinds == {"hopeless": "shed", "fine": "dispatch"}


def test_overload_sheds_at_submit():
    p = FleetPolicy(max_queue_per_tenant=2)
    assert p.submit(_req("q0"), 0.0) is None
    assert p.submit(_req("q1"), 0.0) is None
    assert p.submit(_req("q2"), 0.0) == "overload"
    assert p.queue_depth == 2


def test_push_front_beats_fifo_order():
    p = FleetPolicy()
    p.submit(_req("first"), 0.0)
    p.submit(_req("second"), 0.0)
    kind, r = p.poll(0.0)
    assert r.request_id == "first"
    p.push_front(r)  # dispatch failed: back to the front of the line
    order = [x.request_id for kind, x in _drain(p, 0.0)]
    assert order == ["first", "second"]


def test_idle_tenant_banks_no_credit():
    """A tenant whose queue drained starts from zero deficit when it
    returns — idle time is not a savings account."""
    p = FleetPolicy(quantum=4.0)
    p.submit(_req("x0", tenant=0), 0.0)
    _drain(p, 0.0)
    for _ in range(3):
        assert p.poll(0.0) is None  # idle sweeps reset, never accrue
    snap = p.snapshot()
    assert snap["tenants"]["0"]["deficit"] == 0.0


def test_snapshot_schema_and_counts():
    p = FleetPolicy(rate_limits={1: (5.0, 10.0)})
    p.submit(_req("a", tenant=0), 0.0)
    p.submit(_req("b", tenant=1, priority=1), 0.0)
    p.submit(_req("c", tenant=0, deadline_s=0.5), 0.0)
    out = _drain(p, 1.0)  # c sheds (expired), a and b dispatch
    assert len(out) == 3
    snap = p.snapshot()
    assert snap["queued"] == 0
    t0, t1 = snap["tenants"]["0"], snap["tenants"]["1"]
    assert t0["enqueued"] == 2 and t0["dispatched"] == 1 and t0["shed"] == 1
    assert t1["tier"] == 1 and t1["dispatched"] == 1
    assert t1["rate_tokens"] is not None and t0["rate_tokens"] is None
    for row in (t0, t1):
        assert set(row) == {"tier", "queued", "deficit", "rate_tokens",
                            "enqueued", "dispatched", "shed"}


def test_bad_config_rejected():
    with pytest.raises(ValueError):
        FleetPolicy(quantum=0.0)
    with pytest.raises(ValueError):
        FleetPolicy(max_queue_per_tenant=0)
    with pytest.raises(ValueError):
        FleetPolicy(itl_estimate_s=-1.0)
