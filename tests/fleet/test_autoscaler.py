"""Autoscaler: deterministic scale-up on queue depth / miss rate,
graceful scale-down when drained, cooldown and bounds honored."""

import pytest

import jax.numpy as jnp

from elephas_tpu.fleet import (Autoscaler, FleetRouter, SimClock,
                               TrafficModel, run_trace)
from elephas_tpu.models.transformer import TransformerLM
from elephas_tpu.serving import ServingEngine

pytestmark = pytest.mark.fleet


def _model():
    return TransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=2,
                         d_ff=32, max_len=48)


def _router(model, params, clock, n=1, n_slots=2):
    def factory(pid):
        return ServingEngine(model, params, n_slots=n_slots, max_queue=16,
                             clock=clock, perf_clock=clock)
    return FleetRouter(factory, n, clock=clock, lease_s=2.0)


def test_scales_up_under_burst_and_back_down_when_idle():
    model = _model()
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    clock = SimClock()
    router = _router(model, params, clock, n=1)
    scaler = Autoscaler(router, min_partitions=1, max_partitions=4,
                        cooldown_s=1.0, queue_high=3.0)
    trace = TrafficModel(seed=5, base_rps=6.0, duration_s=10.0,
                         n_tenants=4).generate()
    run_trace(router, trace, clock=clock, step_dt=0.05, autoscaler=scaler)
    ups = [e for e in scaler.events if e["action"] == "up"]
    downs = [e for e in scaler.events if e["action"] == "down"]
    assert ups, "burst load must trigger scale-up"
    assert downs, "drained fleet must shrink back"
    assert router.n_live == 1  # idles back to the floor
    # scale events are membership changes; no work may be lost to them
    snap = router.snapshot()
    assert snap["fleet"]["done"] == len(trace)
    assert snap["fleet"]["ok"] == len(trace)


def test_determinism_same_trace_same_events():
    model = _model()
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    trace = TrafficModel(seed=5, base_rps=6.0, duration_s=8.0).generate()

    def run_once():
        clock = SimClock()
        router = _router(model, params, clock, n=1)
        scaler = Autoscaler(router, min_partitions=1, max_partitions=4,
                            cooldown_s=1.0, queue_high=3.0)
        run_trace(router, trace, clock=clock, step_dt=0.05,
                  autoscaler=scaler)
        return scaler.events

    assert run_once() == run_once()


def test_cooldown_separates_decisions():
    model = _model()
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    clock = SimClock()
    router = _router(model, params, clock, n=1)
    scaler = Autoscaler(router, max_partitions=8, cooldown_s=5.0,
                        queue_high=0.5)
    from elephas_tpu.fleet.traffic import TraceRequest
    for i in range(12):  # deep queue, far past queue_high
        router.submit(TraceRequest(request_id=f"r{i}", arrival_s=0.0,
                                   tenant=0, prompt=[1, 2], max_new=4))
    assert scaler.maybe_scale(0.0) == "up"
    assert scaler.maybe_scale(1.0) is None      # inside cooldown
    assert scaler.maybe_scale(5.0) == "up"      # cooldown elapsed
    assert router.n_live == 3


def test_bounds_are_hard():
    model = _model()
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    clock = SimClock()
    router = _router(model, params, clock, n=1)
    scaler = Autoscaler(router, min_partitions=1, max_partitions=1,
                        cooldown_s=0.0, queue_high=0.5, queue_low=10.0)
    from elephas_tpu.fleet.traffic import TraceRequest
    for i in range(8):
        router.submit(TraceRequest(request_id=f"r{i}", arrival_s=0.0,
                                   tenant=0, prompt=[1, 2], max_new=4))
    assert scaler.maybe_scale(0.0) is None      # at max: never grows
    while router.active:
        router.step()
        clock.advance(0.05)
    assert scaler.maybe_scale(10.0) is None     # at min: never shrinks
    assert router.n_live == 1
    with pytest.raises(ValueError):
        Autoscaler(router, min_partitions=2, max_partitions=1)


def test_miss_rate_signal_triggers_scale_up():
    """Queue shallow but the window's deadline completions mostly
    missed: the miss-rate confirmation signal alone must scale up."""
    model = _model()
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    clock = SimClock()
    router = _router(model, params, clock, n=1)
    scaler = Autoscaler(router, max_partitions=4, cooldown_s=0.0,
                        queue_high=1e9, miss_rate_high=0.5)
    from elephas_tpu.fleet.traffic import TraceRequest
    # an impossible deadline: sheds, counting as a window miss
    router.submit(TraceRequest(request_id="m0", arrival_s=0.0, tenant=0,
                               prompt=[1, 2], max_new=4, deadline_s=0.01))
    clock.advance(1.0)
    router.step()  # policy sheds m0
    assert router.results()["m0"].finish_reason == "shed"
    assert scaler.window_miss_rate() == 1.0
    assert scaler.maybe_scale(clock()) == "up"
