"""FleetRouter: placement, token identity through the fleet, migration
on death and retirement, weight fan-out, and the snapshot surface."""

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.fleet import (FleetPolicy, FleetRouter, SimClock,
                               TrafficModel, router_sink, run_trace)
from elephas_tpu.fleet.traffic import TraceRequest
from elephas_tpu.models.transformer import TransformerLM
from elephas_tpu.serving import ServingEngine
from elephas_tpu.streaming.bridge import params_to_list

pytestmark = pytest.mark.fleet

V = 17


def _model(**kw):
    cfg = dict(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=48)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=1):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


def _fleet(model, params, clock, n=2, *, n_slots=4, paged=False, **rkw):
    def factory(pid):
        return ServingEngine(model, params, n_slots=n_slots, max_queue=8,
                             paged=paged, page_size=4, clock=clock,
                             perf_clock=clock)
    return FleetRouter(factory, n, clock=clock, lease_s=1.0, **rkw)


def _req(rid, prompt, max_new, **kw):
    d = dict(request_id=rid, arrival_s=0.0, tenant=0,
             prompt=[int(x) for x in prompt], max_new=max_new)
    d.update(kw)
    return TraceRequest(**d)


def _run(router, clock, reqs, step_dt=0.05, max_steps=5000):
    for r in reqs:
        router.submit(r)
    steps = 0
    while router.active:
        router.step()
        clock.advance(step_dt)
        steps += 1
        assert steps < max_steps, "fleet failed to drain"
    return router.results()


def test_greedy_identity_through_the_fleet():
    """Tokens produced through the 2-partition fleet equal the model's
    own per-request greedy ``generate`` — routing adds placement, never
    different math."""
    model, clock = _model(), SimClock()
    params = _params(model)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, V, size=n).astype(np.int32)
               for n in (3, 5, 7, 4, 6, 8)]
    router = _fleet(model, params, clock)
    reqs = [_req(f"r{i}", p, 6, tenant=i % 3) for i, p in enumerate(prompts)]
    results = _run(router, clock, reqs)
    assert len(results) == len(reqs)
    for i, p in enumerate(prompts):
        st = results[f"r{i}"]
        assert st.finish_reason in ("eos", "length")
        ref = model.generate(params, p[None], 6)[0, len(p):]
        assert st.tokens == [int(t) for t in ref]


def test_load_spreads_across_partitions():
    model, clock = _model(), SimClock()
    router = _fleet(model, _params(model), clock, n=2, n_slots=2)
    reqs = [_req(f"r{i}", [1, 2, 3], 4) for i in range(8)]
    _run(router, clock, reqs)
    snap = router.snapshot()
    per_part = [p["counters"]["submitted"]
                for p in snap["partitions"].values()]
    assert len(per_part) == 2 and min(per_part) >= 2


def test_kill_partition_migrates_and_streams_stay_identical():
    """Kill a partition with requests in flight: after the lease
    expires, stranded requests resume elsewhere from prompt ++ generated
    with the original seed — the final streams are bitwise identical to
    an undisturbed run, sampled requests included."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, V, size=4).astype(np.int32)
               for _ in range(6)]

    def build(clock):
        return _fleet(model, params, clock, n=2)

    def reqs():
        return [_req(f"r{i}", p, 8, seed=100 + i,
                     temperature=0.7 if i % 2 else 0.0)
                for i, p in enumerate(prompts)]

    clock_a = SimClock()
    base = _run(build(clock_a), clock_a, reqs())

    clock_b = SimClock()
    router = build(clock_b)
    for r in reqs():
        router.submit(r)
    steps = 0
    killed = False
    while router.active:
        router.step()
        if not killed and steps == 2:
            router.kill_partition(0)
            killed = True
        clock_b.advance(0.05)
        steps += 1
        assert steps < 5000
    chaos = router.results()
    assert router.migrations > 0, "kill must strand in-flight work"
    assert router.epoch_changes >= 1
    for rid, st in base.items():
        assert chaos[rid].tokens == st.tokens, f"{rid} diverged"
        assert chaos[rid].finish_reason == st.finish_reason


def test_retire_partition_migrates_without_lease_wait():
    model, clock = _model(), SimClock()
    router = _fleet(model, _params(model), clock, n=2)
    reqs = [_req(f"r{i}", [1, 2, 3, 4], 8) for i in range(4)]
    for r in reqs:
        router.submit(r)
    router.step()  # place some work
    router.retire_partition(0)
    assert router.n_live == 1
    assert router.migrations > 0  # requeued immediately, no sweep needed
    steps = 0
    while router.active:
        router.step()
        clock.advance(0.05)
        steps += 1
        assert steps < 5000
    ref = model.generate(_params(model), np.asarray([[1, 2, 3, 4]]), 8)[0, 4:]
    for rid, st in router.results().items():
        assert st.tokens == [int(t) for t in ref]


def test_swap_params_fans_out_and_covers_late_joiners():
    model, clock = _model(), SimClock()
    p1, p2 = _params(model, seed=1), _params(model, seed=2)
    router = _fleet(model, p1, clock, n=2)
    v = router.swap_params(p2, 7)
    assert v == 7
    for pid in router.partition_ids():
        assert router._engines[pid].weights_version == 7
    late = router.join_partition()
    assert router._engines[late].weights_version == 7

    # the publisher-sink adapter drives the same fan-out in wire order
    sink = router_sink(router, p1)
    sink(params_to_list({k: np.asarray(v) for k, v in p1.items()}), 9)
    for pid in router.partition_ids():
        assert router._engines[pid].weights_version == 9


def test_snapshot_schema_latency_slo_tenants():
    model, clock = _model(), SimClock()
    router = _fleet(model, _params(model), clock,
                    policy=FleetPolicy(itl_estimate_s=0.05))
    trace = TrafficModel(seed=2, base_rps=3.0, duration_s=6.0,
                         n_tenants=3).generate()
    snap = run_trace(router, trace, clock=clock, step_dt=0.05)
    assert set(snap) >= {"fleet", "latency", "slo", "tenants",
                         "partitions", "replay"}
    f = snap["fleet"]
    assert f["done"] == len(trace) and f["queued"] == 0
    lat = snap["latency"]
    assert lat["n_ttft"] > 0 and lat["ttft_p99"] >= lat["ttft_p50"] >= 0
    assert lat["itl_p99"] >= lat["itl_p50"] > 0
    slo = snap["slo"]
    assert slo["offered"] == len(trace)
    assert slo["deadline_met"] + slo["deadline_missed"] == slo["deadline_done"]
    assert 0.0 <= slo["attainment"] <= 1.0
    # every tenant that submitted appears, with DRR credit observable
    for tid, n in trace.tenants().items():
        row = snap["tenants"][str(tid)]
        assert row["submitted"] == n
        assert row["done"] == row["submitted"]
        assert "deficit" in row and "tier" in row
    total_tokens = sum(len(s.tokens) for s in router.results().values())
    assert sum(r["tokens"] for r in snap["tenants"].values()) == total_tokens


def test_duplicate_request_id_rejected():
    from elephas_tpu.serving import AdmissionError
    model, clock = _model(), SimClock()
    router = _fleet(model, _params(model), clock)
    router.submit(_req("dup", [1, 2], 2))
    with pytest.raises(AdmissionError):
        router.submit(_req("dup", [3, 4], 2))


def test_tenant_maps_to_adapter_only_when_served():
    """Dense partitions serve every tenant on the base weights (engine
    adapter 0); the tenant id still drives fleet accounting."""
    model, clock = _model(), SimClock()
    router = _fleet(model, _params(model), clock, n=1)
    eng = router._engines[0]
    assert router._engine_adapter(eng, 5) == 0
    results = _run(router, clock, [_req("x", [1, 2, 3], 3, tenant=5)])
    assert results["x"].finish_reason in ("eos", "length")
    assert router.snapshot()["tenants"]["5"]["submitted"] == 1
