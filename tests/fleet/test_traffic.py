"""Traffic harness: seeded determinism, JSON replayability, and the
three load properties the fleet is exercised against — bursty arrivals,
heavy-tailed lengths, Zipf tenant skew."""

import numpy as np
import pytest

from elephas_tpu.fleet import SimClock, Trace, TrafficModel
from elephas_tpu.fleet.traffic import zipf_weights

pytestmark = pytest.mark.fleet


def _model(**kw):
    cfg = dict(seed=0, base_rps=4.0, duration_s=20.0, n_tenants=6)
    cfg.update(kw)
    return TrafficModel(**cfg)


def test_same_seed_bit_identical_trace():
    a = _model().generate()
    b = _model().generate()
    assert a.to_json() == b.to_json()
    assert len(a) > 10


def test_different_seed_different_trace():
    a = _model(seed=1).generate()
    b = _model(seed=2).generate()
    assert a.to_json() != b.to_json()


def test_json_round_trip_lossless():
    t = _model().generate()
    t2 = Trace.from_json(t.to_json())
    assert t2.to_json() == t.to_json()
    assert t2.config == t.config
    r, r2 = t.requests[0], t2.requests[0]
    assert r2 == r  # dataclass equality: every field survives


def test_arrivals_sorted_and_within_duration():
    t = _model().generate()
    arr = [r.arrival_s for r in t.requests]
    assert arr == sorted(arr)
    assert all(0 <= a < 20.0 for a in arr)


def test_zipf_tenant_skew():
    """Rank-0 tenant dominates; the head outweighs the tail (the skew
    the DRR fairness layer exists to contain)."""
    t = _model(duration_s=60.0, zipf_a=1.2).generate()
    counts = t.tenants()
    assert max(counts, key=counts.get) == 0
    head = counts.get(0, 0) + counts.get(1, 0)
    tail = sum(v for k, v in counts.items() if k >= 2)
    assert head > tail


def test_heavy_tailed_lengths():
    """Lognormal sigma produces a genuine tail: max well above median,
    everything within the configured clip."""
    t = _model(duration_s=120.0, prompt_len_sigma=1.0,
               prompt_len_max=64).generate()
    lens = np.array([len(r.prompt) for r in t.requests])
    assert lens.max() <= 64 and lens.min() >= 1
    assert lens.max() >= 3 * np.median(lens)


def test_interactive_tenants_carry_deadlines_and_priority():
    t = _model(interactive_tenants=2, batch_deadline_s=None).generate()
    for r in t.requests:
        if r.tenant < 2:
            assert r.priority == 1 and r.deadline_s is not None
            assert r.deadline_s >= 4.0  # base + per-token margin
        else:
            assert r.priority == 0 and r.deadline_s is None


def test_scaled_compresses_arrivals_only():
    t = _model().generate()
    s = t.scaled(2.0)
    assert len(s) == len(t)
    for a, b in zip(t.requests, s.requests):
        assert b.arrival_s == pytest.approx(a.arrival_s / 2.0)
        assert b.prompt == a.prompt and b.max_new == a.max_new
    assert s.offered_rps == pytest.approx(2.0 * t.offered_rps)
    assert s.config["load_scale"] == 2.0


def test_burst_windows_raise_local_rate():
    """With a huge burst amplitude the burst windows must be visibly
    denser than the off-burst background."""
    m = _model(seed=11, duration_s=60.0, burst_amp=9.0, diurnal_amp=0.0,
               burst_every_s=20.0, burst_width_s=5.0)
    rng = np.random.default_rng(m.cfg["seed"])
    windows = m._burst_windows(rng)
    t = m.generate()
    assert windows, "seed must produce at least one burst window"
    in_w = sum(1 for r in t.requests
               if any(lo <= r.arrival_s < hi for lo, hi in windows))
    out_w = len(t) - in_w
    w_span = sum(hi - lo for lo, hi in windows)
    o_span = 60.0 - w_span
    assert in_w / max(w_span, 1e-9) > 3.0 * (out_w / max(o_span, 1e-9))


def test_zipf_weights_normalized_and_monotone():
    w = zipf_weights(8, 1.1)
    assert w.sum() == pytest.approx(1.0)
    assert all(w[i] > w[i + 1] for i in range(7))


def test_sim_clock_explicit_advance_only():
    c = SimClock(5.0)
    assert c() == 5.0 and c() == 5.0  # reading never advances
    assert c.advance(1.5) == 6.5 and c() == 6.5
    with pytest.raises(ValueError):
        c.advance(-1.0)


def test_bad_config_rejected():
    with pytest.raises(ValueError):
        TrafficModel(base_rps=0.0)
    with pytest.raises(ValueError):
        TrafficModel(diurnal_amp=1.0)
    with pytest.raises(ValueError):
        _model().generate().scaled(0.0)
