"""Parameter server/client round-trips (reference: tests/parameter/...).

Exercises both wire backends (HTTP, raw socket), the update semantics
(``weights -= delta``), and concurrent pushes (lock vs hogwild).
"""

import threading
import time

import numpy as np
import pytest

from elephas_tpu.parameter import BaseParameterClient, HttpServer, SocketServer

PORTS = iter(range(41000, 41100))


def _weights():
    return [np.ones((4, 3), "float32"), np.zeros((3,), "float32")]


@pytest.mark.parametrize("backend", ["http", "socket"])
def test_pull_push_round_trip(backend):
    port = next(PORTS)
    server_cls = HttpServer if backend == "http" else SocketServer
    server = server_cls(_weights(), mode="asynchronous", port=port)
    server.start()
    try:
        client = BaseParameterClient.get_client(backend, port, host="127.0.0.1")
        w = client.get_parameters()
        assert np.allclose(w[0], 1.0)
        delta = [np.full((4, 3), 0.25, "float32"), np.full((3,), -1.0, "float32")]
        client.update_parameters(delta)
        w2 = client.get_parameters()
        assert np.allclose(w2[0], 0.75)  # weights -= delta
        assert np.allclose(w2[1], 1.0)
        client.close()
    finally:
        server.stop()


@pytest.mark.parametrize("backend", ["http", "socket"])
def test_concurrent_updates_locked(backend):
    port = next(PORTS)
    server_cls = HttpServer if backend == "http" else SocketServer
    server = server_cls([np.zeros((10,), "float64")], mode="asynchronous", port=port)
    server.start()
    try:
        n_threads, n_pushes = 4, 10

        def push():
            client = BaseParameterClient.get_client(backend, port, host="127.0.0.1")
            for _ in range(n_pushes):
                client.update_parameters([np.full((10,), -1.0)])
            client.close()

        threads = [threading.Thread(target=push) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # 'u' is fire-and-forget (reference protocol has no ack): poll until
        # the server has drained its connection buffers.
        deadline = time.time() + 10
        while time.time() < deadline:
            final = server.get_weights()[0]
            if np.allclose(final, n_threads * n_pushes):
                break
            time.sleep(0.05)
        # With the lock, every update lands exactly once.
        assert np.allclose(final, n_threads * n_pushes)
    finally:
        server.stop()


def test_hogwild_skips_lock():
    port = next(PORTS)
    server = HttpServer([np.zeros((2,), "float32")], mode="hogwild", port=port)
    server.start()
    try:
        client = BaseParameterClient.get_client("http", port, host="127.0.0.1")
        client.update_parameters([np.ones((2,), "float32")])
        assert np.allclose(client.get_parameters()[0], -1.0)
        client.close()
    finally:
        server.stop()


def test_socket_client_reconnects_after_peer_reset():
    """A persistent socket goes stale when the peer resets (server restart,
    idle LB reap). Every op must retry once on a fresh connection instead of
    failing the worker task on the first post-reset call."""
    port = next(PORTS)
    server = SocketServer(_weights(), mode="asynchronous", port=port)
    server.start()
    try:
        client = BaseParameterClient.get_client("socket", port, host="127.0.0.1")
        assert np.allclose(client.get_parameters()[0], 1.0)
        # simulate the peer reset underneath the live client: the next send
        # on this socket raises ConnectionError/OSError
        import socket as socket_mod

        client._sock.shutdown(socket_mod.SHUT_RDWR)
        client._sock.close()
        # pulls, pushes, and version reads all recover on a fresh connection
        assert np.allclose(client.get_parameters()[0], 1.0)
        client._sock.close()
        client.update_parameters(
            [np.full((4, 3), 0.5, "float32"), np.zeros((3,), "float32")]
        )
        # 'u' is fire-and-forget and the reconnect put it on a NEW
        # connection: poll until the server has drained it.
        deadline = time.time() + 10
        while time.time() < deadline:
            if np.allclose(client.get_parameters()[0], 0.5):
                break
            time.sleep(0.05)
        assert np.allclose(client.get_parameters()[0], 0.5)
        client._sock.close()
        assert client.get_version() >= 1
        client.close()
    finally:
        server.stop()


def test_socket_client_raises_when_server_genuinely_gone():
    """The one-shot reconnect must not loop forever on a dead server: the
    second failure propagates (the policy layer owns further retries)."""
    port = next(PORTS)
    server = SocketServer(_weights(), mode="asynchronous", port=port)
    server.start()
    client = BaseParameterClient.get_client("socket", port, host="127.0.0.1")
    assert np.allclose(client.get_parameters()[0], 1.0)
    server.stop()
    # the established connection may outlive the listener; drop it so the
    # reconnect path has to dial the (now closed) listener and fail honestly
    client._sock.close()
    client._sock = None
    with pytest.raises(OSError):
        client.get_parameters()
    client.close()
