"""Native (C++) parameter server: build, round-trip, concurrency, training."""

import threading

import numpy as np
import pytest

from elephas_tpu.parameter.native import (
    NativeClient,
    NativeServer,
    native_available,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library failed to build"
)


def _weights():
    return [np.ones((4, 3), "float32"), np.zeros((3,), "float32")]


def _client_for(server, weights):
    return NativeClient(
        [w.shape for w in weights], [w.dtype for w in weights], server.port
    )


def test_round_trip():
    server = NativeServer(_weights(), mode="asynchronous", port=0)
    server.start()
    try:
        client = _client_for(server, _weights())
        w = client.get_parameters()
        assert np.allclose(w[0], 1.0)
        assert w[0].shape == (4, 3)
        delta = [np.full((4, 3), 0.25, "float32"), np.full((3,), -1.0, "float32")]
        client.update_parameters(delta)
        w2 = client.get_parameters()
        assert np.allclose(w2[0], 0.75)
        assert np.allclose(w2[1], 1.0)
        client.close()
    finally:
        server.stop()


def test_concurrent_updates_exact():
    server = NativeServer([np.zeros((1000,), "float32")], mode="asynchronous",
                          port=0)
    server.start()
    try:
        n_threads, n_pushes = 8, 25

        def push():
            client = NativeClient([(1000,)], ["float32"], server.port)
            for _ in range(n_pushes):
                client.update_parameters([np.full((1000,), -1.0, "float32")])
            client.close()

        threads = [threading.Thread(target=push) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # Updates are acked on the native protocol: no settle race — every
        # push has landed by the time the client returns.
        final = server.get_weights()[0]
        assert np.allclose(final, n_threads * n_pushes)
    finally:
        server.stop()


def test_spark_model_native_ps(spark_context, toy_classification):
    from elephas_tpu import SparkModel
    from elephas_tpu.utils import to_simple_rdd

    from ..conftest import make_classifier

    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    model = make_classifier()
    base = float(
        (model.predict(x, verbose=0).argmax(1) == y.argmax(1)).mean()
    )
    sm = SparkModel(
        model, mode="asynchronous", frequency="epoch",
        parameter_server_mode="native", num_workers=4, port=0,
    )
    sm.fit(rdd, epochs=4, batch_size=16, validation_split=0.0)
    acc = float(
        (sm.master_network.predict(x, verbose=0).argmax(1) == y.argmax(1)).mean()
    )
    assert acc > max(base, 0.34)
