"""Native (C++) parameter server: build, round-trip, concurrency, training."""

import threading

import numpy as np
import pytest

from elephas_tpu.parameter.native import (
    NativeClient,
    NativeServer,
    native_available,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library failed to build"
)


def _weights():
    return [np.ones((4, 3), "float32"), np.zeros((3,), "float32")]


def _client_for(server, weights):
    return NativeClient(
        [w.shape for w in weights], [w.dtype for w in weights], server.port
    )


def test_round_trip():
    server = NativeServer(_weights(), mode="asynchronous", port=0)
    server.start()
    try:
        client = _client_for(server, _weights())
        w = client.get_parameters()
        assert np.allclose(w[0], 1.0)
        assert w[0].shape == (4, 3)
        delta = [np.full((4, 3), 0.25, "float32"), np.full((3,), -1.0, "float32")]
        client.update_parameters(delta)
        w2 = client.get_parameters()
        assert np.allclose(w2[0], 0.75)
        assert np.allclose(w2[1], 1.0)
        client.close()
    finally:
        server.stop()


def test_concurrent_updates_exact():
    server = NativeServer([np.zeros((1000,), "float32")], mode="asynchronous",
                          port=0)
    server.start()
    try:
        n_threads, n_pushes = 8, 25

        def push():
            client = NativeClient([(1000,)], ["float32"], server.port)
            for _ in range(n_pushes):
                client.update_parameters([np.full((1000,), -1.0, "float32")])
            client.close()

        threads = [threading.Thread(target=push) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # Updates are acked on the native protocol: no settle race — every
        # push has landed by the time the client returns.
        final = server.get_weights()[0]
        assert np.allclose(final, n_threads * n_pushes)
    finally:
        server.stop()


def test_spark_model_native_ps(spark_context, toy_classification):
    from elephas_tpu import SparkModel
    from elephas_tpu.utils import to_simple_rdd

    from ..conftest import make_classifier

    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    model = make_classifier()
    base = float(
        (model.predict(x, verbose=0).argmax(1) == y.argmax(1)).mean()
    )
    sm = SparkModel(
        model, mode="asynchronous", frequency="epoch",
        parameter_server_mode="native", num_workers=4, port=0,
    )
    sm.fit(rdd, epochs=4, batch_size=16, validation_split=0.0)
    acc = float(
        (sm.master_network.predict(x, verbose=0).argmax(1) == y.argmax(1)).mean()
    )
    assert acc > max(base, 0.34)


def test_compressed_pushes_int8_and_topk():
    """V/W opcodes: codec frames decode to dense f32 server-side; int8 is
    exact within quantization error and top-k error feedback converges."""
    import pytest

    from elephas_tpu.parameter.compression import make_codec
    from elephas_tpu.parameter.native import (NativeClient, NativeServer,
                                              native_available)

    if not native_available():
        pytest.skip("native toolchain unavailable")

    w0 = [np.zeros((64,), "float32"), np.full((4, 4), 5.0, "float32")]
    server = NativeServer([w.copy() for w in w0], port=0)
    server.start()
    try:
        shapes = [w.shape for w in w0]
        dts = ["float32"] * 2

        # int8: weights -= decode(encode(delta)); error bounded by scale/2
        c8 = NativeClient(shapes, dts, server.port,
                          codec=make_codec("int8"))
        delta = [np.linspace(-1, 1, 64).astype("float32"),
                 np.full((4, 4), 0.25, "float32")]
        c8.update_parameters(delta)
        got = c8.get_parameters()
        for g, w, d in zip(got, w0, delta):
            scale = np.abs(d).max() / 127.0
            np.testing.assert_allclose(g, w - d, atol=scale / 2 + 1e-7)
        c8.close()

        # topk with error feedback: repeated pushes of the same delta
        # deliver (approximately) the full mass over time
        ck = NativeClient(shapes, dts, server.port,
                          codec=make_codec("topk:0.25"))
        before = ck.get_parameters()
        d = [np.arange(64, dtype="float32") / 64.0,
             np.zeros((4, 4), "float32")]
        for _ in range(8):
            ck.update_parameters(d)
        after = ck.get_parameters()
        applied = before[0] - after[0]
        # ≥ the mass of ~6 full pushes must have landed (feedback catches up)
        assert float(applied.sum()) > 6 * float(d[0].sum()), applied.sum()
        ck.close()

        # tagged compressed pushes roll back exactly-once on retry
        # (baseline re-read AFTER ck.close() — close flushes its residual)
        ct = NativeClient(shapes, dts, server.port,
                          codec=make_codec("int8"))
        base = ct.get_parameters()
        assert ct.register_attempt("t-0", 0)
        ct.update_parameters_tagged("t-0", [np.full((64,), 100.0, "float32"),
                                            np.zeros((4, 4), "float32")])
        snap_poisoned = ct.get_parameters()
        assert not np.allclose(snap_poisoned[0], base[0])
        assert ct.register_attempt("t-0", 1)  # retry → poison rolled back
        clean = ct.get_parameters()
        np.testing.assert_allclose(clean[0], base[0], atol=1e-5)
        ct.close()
    finally:
        server.stop()


def test_native_topk_residual_flush_on_close_and_commit():
    """Residual flush parity with CompressingClient: one push + close (or
    commit) delivers the FULL delta through the native wire."""
    import pytest

    from elephas_tpu.parameter.compression import make_codec
    from elephas_tpu.parameter.native import (NativeClient, NativeServer,
                                              native_available)

    if not native_available():
        pytest.skip("native toolchain unavailable")

    w0 = [np.zeros((100,), "float32")]
    delta = [np.arange(1.0, 101.0, dtype="float32")]

    for tagged in (False, True):
        server = NativeServer([w.copy() for w in w0], port=0)
        server.start()
        try:
            c = NativeClient([(100,)], ["float32"], server.port,
                             codec=make_codec("topk:0.1"))
            if tagged:
                assert c.register_attempt("t-0", 0)
                c.update_parameters_tagged("t-0", delta)
                c.commit_attempt("t-0")  # flush rides the attempt record
            else:
                c.update_parameters(delta)
                c.close()                # best-effort flush
            np.testing.assert_allclose(server.get_weights()[0], -delta[0],
                                       atol=1e-5)
            if tagged:
                assert server.attempt_count() == 0
        finally:
            server.stop()


@pytest.mark.parametrize("dtype", ["bfloat16", "float16"])
def test_low_precision_weights_round_trip(dtype):
    """bf16/f16 weights ride the f32 store losslessly: get_parameters
    restores the original dtype, and pushed deltas (cast through f32 on the
    wire) apply exactly — the dtype-parity contract for the native stack
    (values are exactly representable, so no tolerance is needed)."""
    import ml_dtypes  # registers bfloat16 with numpy

    dt = np.dtype("float16") if dtype == "float16" else ml_dtypes.bfloat16
    weights = [np.ones((8, 4), dt), (np.arange(6) / 4).astype(dt)]
    server = NativeServer(weights, mode="asynchronous", port=0)
    server.start()
    try:
        client = NativeClient([w.shape for w in weights],
                              [w.dtype for w in weights], server.port)
        got = client.get_parameters()
        assert got[0].dtype == weights[0].dtype
        np.testing.assert_array_equal(
            got[1].astype("float32"), weights[1].astype("float32"))
        delta = [np.full((8, 4), 0.5, dt), np.full((6,), 0.25, dt)]
        client.update_parameters(delta)
        got2 = client.get_parameters()
        assert got2[0].dtype == weights[0].dtype
        np.testing.assert_array_equal(got2[0].astype("float32"),
                                      np.full((8, 4), 0.5, "float32"))
        client.close()
    finally:
        server.stop()


def test_f64_rejected_loudly():
    with pytest.raises(ValueError, match="truncated"):
        NativeServer([np.zeros((3,), "float64")], mode="asynchronous",
                     port=0)
