"""Parameter-server torture tests: concurrency, conservation, linearizability.

The reference has no race detection (SURVEY.md §5.2 — races are a *feature*
in hogwild). These tests give the rebuild an explicit concurrency contract:

- locked ``asynchronous`` mode is linearizable for updates — under heavy
  multi-client hammering the final weights equal start − Σdeltas exactly
  (update application is read-modify-write under the lock, so no update can
  be lost);
- attempt registration/rollback composes with that contract under
  concurrency (rolled-back attempts subtract out exactly);
- ``hogwild`` mode must stay *available* under the same hammering (no
  deadlock, finite weights) — lost updates are its documented contract, so
  only liveness is asserted.
"""

import threading

import numpy as np
import pytest

from elephas_tpu.parameter.client import BaseParameterClient
from elephas_tpu.parameter.server import HttpServer, SocketServer

N_CLIENTS = 8
N_UPDATES = 25


def hammer(kind, port, client_fn):
    errs = []

    def worker(i):
        try:
            client = BaseParameterClient.get_client(kind, port=port, host="127.0.0.1")
            client_fn(client, i)
            client.close()
        except Exception as e:  # noqa: BLE001 — collected for the assertion
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(N_CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    assert not any(t.is_alive() for t in threads), "deadlocked client threads"


@pytest.mark.parametrize("server_cls,kind", [(HttpServer, "http"),
                                             (SocketServer, "socket")])
def test_locked_async_conserves_every_update(server_cls, kind):
    w0 = [np.zeros((4, 4)), np.zeros((7,))]
    server = server_cls([w.copy() for w in w0], mode="asynchronous", port=0)
    server.start()
    try:
        def client_fn(client, i):
            for u in range(N_UPDATES):
                delta = [np.full((4, 4), 1.0), np.full((7,), float(u % 3))]
                client.update_parameters(delta)
                if u % 5 == 0:
                    client.get_parameters()  # interleave reads
            # socket pushes are fire-and-forget; a trailing pull on the same
            # connection orders after them, draining this client's stream
            client.get_parameters()

        hammer(kind, server.port, client_fn)
        got = server.get_weights()
        total0 = N_CLIENTS * N_UPDATES * 1.0
        total1 = N_CLIENTS * sum(float(u % 3) for u in range(N_UPDATES))
        np.testing.assert_allclose(got[0], -np.full((4, 4), total0))
        np.testing.assert_allclose(got[1], -np.full((7,), total1))
    finally:
        server.stop()


@pytest.mark.parametrize("server_cls,kind", [(HttpServer, "http"),
                                             (SocketServer, "socket")])
def test_rollback_composes_under_concurrency(server_cls, kind):
    """Half the clients run a failed attempt (rolled back) then a clean one;
    the other half push untagged. Final = start − (untagged + clean tagged)."""
    w0 = [np.zeros((5,))]
    server = server_cls([w.copy() for w in w0], mode="asynchronous", port=0)
    server.start()
    try:
        def client_fn(client, i):
            if i % 2 == 0:
                tid = f"task-{i}"
                assert client.register_attempt(tid, 0)
                for _ in range(N_UPDATES):
                    client.update_parameters_tagged(tid, [np.full((5,), 7.0)])
                # "crash": a new attempt registers, undoing all of the above
                assert client.register_attempt(tid, 1)
                client.update_parameters_tagged(tid, [np.full((5,), 2.0)])
                client.commit_attempt(tid)
            else:
                for _ in range(N_UPDATES):
                    client.update_parameters([np.full((5,), 1.0)])
            client.get_parameters()  # drain this connection's stream

        hammer(kind, server.port, client_fn)
        got = server.get_weights()
        tagged = (N_CLIENTS // 2) * 2.0
        untagged = (N_CLIENTS // 2) * N_UPDATES * 1.0
        np.testing.assert_allclose(got[0], -np.full((5,), tagged + untagged))
        assert server._attempts == {}  # all committed → memory released
    finally:
        server.stop()


@pytest.mark.parametrize("server_cls,kind", [(HttpServer, "http"),
                                             (SocketServer, "socket")])
def test_hogwild_stays_live_under_hammering(server_cls, kind):
    """Hogwild's contract is availability, not conservation: the server must
    survive concurrent lock-free updates without deadlock or corruption
    beyond lost updates (weights finite, correct shapes)."""
    w0 = [np.zeros((16,))]
    server = server_cls([w.copy() for w in w0], mode="hogwild", port=0)
    server.start()
    try:
        def client_fn(client, i):
            for _ in range(N_UPDATES):
                client.update_parameters([np.full((16,), 1.0)])

        hammer(kind, server.port, client_fn)
        got = server.get_weights()
        assert got[0].shape == (16,)
        assert np.isfinite(got[0]).all()
        # every element saw at least one and at most all updates
        assert (-got[0] >= 1.0).all()
        assert (-got[0] <= N_CLIENTS * N_UPDATES).all()
    finally:
        server.stop()
