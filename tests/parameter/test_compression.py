"""Delta compression codecs + compressed pushes over the real wire.

Extension (the reference pushes full f32 pickles, SURVEY.md §2.4): int8
linear quantization and top-k sparsification with client-side error
feedback. Tests cover codec accuracy/size, residual bookkeeping, wire
interop with plain clients against one server, and an end-to-end compressed
async fit that still learns.
"""

import pickle

import numpy as np
import pytest

from elephas_tpu.parameter.client import BaseParameterClient
from elephas_tpu.parameter.compression import (
    CompressingClient,
    Int8Codec,
    TopKCodec,
    make_codec,
    maybe_decode,
)
from elephas_tpu.parameter.server import HttpServer


def deltas(rng, scale=1.0):
    return [rng.normal(size=(32, 16)).astype(np.float32) * scale,
            rng.normal(size=(7,)).astype(np.float32) * scale]


def test_int8_roundtrip_accuracy_and_size():
    rng = np.random.default_rng(0)
    d = deltas(rng)
    payload = Int8Codec().encode(d)
    back = maybe_decode(payload)
    for a, b in zip(d, back):
        # quantization error bounded by half a step (scale = max|x|/127)
        assert np.abs(a - b).max() <= np.abs(a).max() / 127.0 / 2 + 1e-7
    assert len(pickle.dumps(payload)) < 0.5 * len(pickle.dumps(d))


def test_topk_keeps_largest_and_tracks_residual():
    codec = TopKCodec(0.1)
    d = [np.arange(1.0, 101.0, dtype=np.float32).reshape(10, 10)]
    back = maybe_decode(codec.encode(d))
    # top 10% of 100 entries = the 10 largest (91..100)
    kept = back[0].ravel()
    assert (kept[-10:] == np.arange(91.0, 101.0, dtype=np.float32)).all()
    assert (kept[:-10] == 0).all()
    # residual holds exactly what was dropped
    np.testing.assert_allclose(codec.residual[0] + back[0], d[0])


def test_topk_error_feedback_transmits_everything_eventually():
    """Σ(decoded pushes) → Σ(true deltas): nothing is lost, only delayed."""
    rng = np.random.default_rng(1)
    codec = TopKCodec(0.25)
    true_sum = None
    sent_sum = None
    for _ in range(40):
        d = deltas(rng)
        true_sum = d if true_sum is None else [a + b for a, b in zip(true_sum, d)]
        back = maybe_decode(codec.encode(d))
        sent_sum = back if sent_sum is None else [a + b for a, b in zip(sent_sum, back)]
    # remaining gap = current residual, bounded; relative error small
    for t, s, r in zip(true_sum, sent_sum, codec.residual):
        np.testing.assert_allclose(s + r, t, rtol=1e-5, atol=1e-5)
        assert np.abs(t - s).max() <= np.abs(r).max() + 1e-6


def test_make_codec_specs():
    assert make_codec(None) is None
    assert make_codec("none") is None
    assert isinstance(make_codec("int8"), Int8Codec)
    tk = make_codec("topk:0.01")
    assert isinstance(tk, TopKCodec) and tk.fraction == 0.01
    with pytest.raises(ValueError):
        make_codec("gzip")
    with pytest.raises(ValueError):
        make_codec("topk:0")


def test_compressed_and_plain_clients_share_a_server():
    w0 = [np.zeros((8, 8)), np.zeros((3,))]
    server = HttpServer([w.copy() for w in w0], mode="asynchronous", port=0)
    server.start()
    try:
        plain = BaseParameterClient.get_client("http", port=server.port,
                                               host="127.0.0.1")
        comp = CompressingClient(
            BaseParameterClient.get_client("http", port=server.port,
                                           host="127.0.0.1"),
            make_codec("int8"),
        )
        plain.update_parameters([np.full((8, 8), 2.0), np.full((3,), 2.0)])
        comp.update_parameters([np.full((8, 8), 1.0), np.full((3,), 1.0)])
        got = comp.get_parameters()  # pulls stay exact/full precision
        np.testing.assert_allclose(got[0], -np.full((8, 8), 3.0), atol=0.02)
        np.testing.assert_allclose(got[1], -np.full((3,), 3.0), atol=0.02)
    finally:
        server.stop()


def test_compression_accepted_for_native_protocol(classifier_factory):
    """The native binary protocol carries compressed deltas too (V/W
    opcodes) — the construction must accept it like http/socket."""
    from elephas_tpu import SparkModel

    sm = SparkModel(classifier_factory(), mode="asynchronous",
                    parameter_server_mode="native", compression="int8")
    assert sm.compression == "int8"


def test_bad_compression_spec_rejected_eagerly(classifier_factory):
    from elephas_tpu import SparkModel

    with pytest.raises(ValueError, match="compression"):
        SparkModel(classifier_factory(), mode="asynchronous",
                   compression="gzip")


def test_close_flushes_topk_residual():
    """One push + close must deliver the FULL delta (residual flushed as a
    final exact push) — nothing dies with the client."""
    w0 = [np.zeros((10, 10))]
    server = HttpServer([w.copy() for w in w0], mode="asynchronous", port=0)
    server.start()
    try:
        comp = CompressingClient(
            BaseParameterClient.get_client("http", port=server.port,
                                           host="127.0.0.1"),
            make_codec("topk:0.1"),
        )
        delta = [np.arange(1.0, 101.0, dtype=np.float32).reshape(10, 10)]
        comp.update_parameters(delta)
        comp.close()
        np.testing.assert_allclose(server.get_weights()[0], -delta[0])
    finally:
        server.stop()


def test_commit_flushes_residual_tagged():
    """The residual flush rides the attempt record (flush BEFORE commit,
    tagged): the server sees the full delta, and a post-commit retry
    cannot double-apply it."""
    w0 = [np.zeros((10, 10))]
    server = HttpServer([w.copy() for w in w0], mode="asynchronous", port=0)
    server.start()
    try:
        comp = CompressingClient(
            BaseParameterClient.get_client("http", port=server.port,
                                           host="127.0.0.1"),
            make_codec("topk:0.1"),
        )
        assert comp.register_attempt("task-0", 0)
        delta = [np.arange(1.0, 101.0, dtype=np.float32).reshape(10, 10)]
        comp.update_parameters_tagged("task-0", delta)
        comp.commit_attempt("task-0")
        np.testing.assert_allclose(server.get_weights()[0], -delta[0])
        assert server._attempts == {}
        comp.close()
    finally:
        server.stop()


def test_topk_handles_empty_and_full_fractions():
    codec = TopKCodec(0.5)
    d = [np.zeros((0,), np.float32), np.ones((3,), np.float32)]
    back = maybe_decode(codec.encode(d))
    assert back[0].shape == (0,)
    # keep-everything edge: fraction 1.0 transmits the delta exactly
    full = TopKCodec(1.0)
    back = maybe_decode(full.encode([np.arange(5.0, dtype=np.float32)]))
    np.testing.assert_allclose(back[0], np.arange(5.0))


def test_compression_rejected_on_non_host_paths(classifier_factory):
    from elephas_tpu import SparkModel

    with pytest.raises(ValueError, match="no PS traffic"):
        SparkModel(classifier_factory(), mode="synchronous",
                   compression="int8")
    with pytest.raises(ValueError, match="no PS traffic"):
        SparkModel(classifier_factory(), mode="asynchronous",
                   parameter_server_mode="jax", compression="int8")
    with pytest.raises(ValueError, match="no PS traffic"):
        # sync host path collects deltas via mapPartitions, not a PS client
        SparkModel(classifier_factory(), mode="synchronous", comm="host",
                   compression="int8")


def test_save_load_roundtrips_compression(classifier_factory, tmp_path):
    from elephas_tpu import SparkModel, load_spark_model

    sm = SparkModel(classifier_factory(), mode="asynchronous",
                    parameter_server_mode="http", compression="topk:0.05")
    path = str(tmp_path / "m.keras")
    sm.save(path)
    loaded = load_spark_model(path)
    assert loaded.compression == "topk:0.05"


@pytest.mark.slow
@pytest.mark.parametrize("spec", ["int8", "topk:0.25"])
def test_compressed_async_fit_still_learns(
    spark_context, toy_classification, classifier_factory, spec
):
    from elephas_tpu import SparkModel
    from elephas_tpu.utils import to_simple_rdd

    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y, num_slices=2)
    sm = SparkModel(classifier_factory(), mode="asynchronous",
                    frequency="epoch", parameter_server_mode="http",
                    num_workers=2, port=0, compression=spec)
    assert sm.get_config()["compression"] == spec
    sm.fit(rdd, epochs=4, batch_size=32, verbose=0, validation_split=0.0)
    acc = (sm.predict(x).argmax(1) == y.argmax(1)).mean()
    assert acc > 0.5, (spec, acc)


def test_tagged_client_close_does_not_flush_residual():
    """A tagged client's nonzero residual at close() means the attempt
    FAILED (commit flushes on success) — close must NOT push it untagged,
    or the stray mass escapes the retry's rollback and double-applies."""
    w0 = [np.zeros((10, 10))]
    server = HttpServer([w.copy() for w in w0], mode="asynchronous", port=0)
    server.start()
    try:
        comp = CompressingClient(
            BaseParameterClient.get_client("http", port=server.port,
                                           host="127.0.0.1"),
            make_codec("topk:0.1"),
        )
        assert comp.register_attempt("task-x", 0)
        delta = [np.arange(1.0, 101.0, dtype=np.float32).reshape(10, 10)]
        comp.update_parameters_tagged("task-x", delta)  # leaves a residual
        comp.close()  # simulated failure path: NO commit happened
        # retry rolls the whole attempt back → weights must be pristine
        retry = BaseParameterClient.get_client("http", port=server.port,
                                               host="127.0.0.1")
        assert retry.register_attempt("task-x", 1)
        np.testing.assert_allclose(server.get_weights()[0], 0.0, atol=1e-7)
        retry.close()
    finally:
        server.stop()
