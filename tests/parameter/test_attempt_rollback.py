"""Exactly-once retry semantics on the parameter servers.

The reference's async path is not idempotent under Spark task retry — a
retried task re-pushes deltas on top of the failed attempt's (SURVEY.md §5.3
documents the hole). The rebuild fixes it: tagged updates are accumulated per
task and a re-registered attempt rolls the previous attempt's contribution
back. These tests drive the full client↔server wire path for both backends.
"""

import numpy as np
import pytest

from elephas_tpu.parameter.client import BaseParameterClient
from elephas_tpu.parameter.native import native_available
from elephas_tpu.parameter.server import HttpServer, SocketServer

W0 = [np.zeros((3,), dtype="float64"), np.full((2, 2), 10.0)]

BACKENDS = [
    "http",
    "socket",
    pytest.param("native", marks=pytest.mark.skipif(
        not native_available(), reason="native toolchain unavailable")),
]


def start(kind, mode="asynchronous"):
    if kind == "native":
        # the native store is f32-only by contract (it rejects f64 loudly)
        from elephas_tpu.parameter.native import NativeClient, NativeServer

        w0 = [w.astype("float32") for w in W0]
        server = NativeServer([w.copy() for w in w0], mode=mode, port=0)
        server.start()
        client = NativeClient([w.shape for w in w0],
                              ["float32"] * len(w0), port=server.port)
        return server, client
    server_cls = {"http": HttpServer, "socket": SocketServer}[kind]
    server = server_cls([w.copy() for w in W0], mode=mode, port=0)
    server.start()
    client = BaseParameterClient.get_client(kind, port=server.port, host="127.0.0.1")
    return server, client


def attempt_count(server) -> int:
    return (server.attempt_count() if hasattr(server, "attempt_count")
            else len(server._attempts))


def delta(v):
    return [np.full((3,), v), np.full((2, 2), v)]


@pytest.mark.parametrize("server_cls", BACKENDS)
def test_retry_rolls_back_failed_attempt(server_cls):
    server, client = start(server_cls)
    try:
        assert client.register_attempt("partition-0", 0) is True
        client.update_parameters_tagged("partition-0", delta(1.0))
        client.update_parameters_tagged("partition-0", delta(2.0))
        # ...task dies here, having already pushed 3.0 of delta; retry:
        assert client.register_attempt("partition-0", 1) is True
        client.update_parameters_tagged("partition-0", delta(5.0))
        got = client.get_parameters()
        # exactly-once: only the successful attempt's 5.0 survives
        np.testing.assert_allclose(got[0], W0[0] - 5.0)
        np.testing.assert_allclose(got[1], W0[1] - 5.0)
    finally:
        client.close()
        server.stop()


@pytest.mark.parametrize("server_cls", BACKENDS)
def test_untagged_updates_keep_reference_behavior(server_cls):
    """Plain reference-shaped pushes are untouched by the attempt machinery."""
    server, client = start(server_cls)
    try:
        client.update_parameters(delta(1.0))
        client.update_parameters(delta(2.0))
        got = client.get_parameters()
        np.testing.assert_allclose(got[0], W0[0] - 3.0)
    finally:
        client.close()
        server.stop()


@pytest.mark.parametrize("server_cls", BACKENDS)
def test_independent_tasks_do_not_roll_back_each_other(server_cls):
    server, client = start(server_cls)
    try:
        client.register_attempt("partition-0", 0)
        client.register_attempt("partition-1", 0)
        client.update_parameters_tagged("partition-0", delta(1.0))
        client.update_parameters_tagged("partition-1", delta(2.0))
        # partition-1 retries; partition-0's contribution must survive
        client.register_attempt("partition-1", 1)
        client.update_parameters_tagged("partition-1", delta(4.0))
        got = client.get_parameters()
        np.testing.assert_allclose(got[0], W0[0] - 5.0)
    finally:
        client.close()
        server.stop()


@pytest.mark.parametrize("server_cls", BACKENDS)
def test_stale_register_cannot_roll_back_live_attempt(server_cls):
    """A zombie executor replaying an OLD attempt's register must not undo the
    live attempt's committed training (guard: only newer attempts roll back)."""
    server, client = start(server_cls)
    try:
        client.register_attempt("partition-0", 1)
        client.update_parameters_tagged("partition-0", delta(5.0))
        # zombie replays attempt 0's registration — must be ignored
        client.register_attempt("partition-0", 0)
        got = client.get_parameters()
        np.testing.assert_allclose(got[0], W0[0] - 5.0)
        # and the live attempt can still retry correctly afterwards
        client.register_attempt("partition-0", 2)
        client.update_parameters_tagged("partition-0", delta(7.0))
        got = client.get_parameters()
        np.testing.assert_allclose(got[0], W0[0] - 7.0)
    finally:
        client.close()
        server.stop()


@pytest.mark.parametrize("server_cls", BACKENDS)
def test_commit_frees_accumulator_and_keeps_weights(server_cls):
    server, client = start(server_cls)
    try:
        client.register_attempt("partition-0", 0)
        client.update_parameters_tagged("partition-0", delta(3.0))
        client.commit_attempt("partition-0")
        # a pull on the same connection orders after the commit opcode
        got = client.get_parameters()
        np.testing.assert_allclose(got[0], W0[0] - 3.0)
        assert attempt_count(server) == 0  # bounded by in-flight tasks
        # a later register for the same partition starts a fresh history and
        # cannot roll back the committed work
        client.register_attempt("partition-0", 0)
        got = client.get_parameters()
        np.testing.assert_allclose(got[0], W0[0] - 3.0)
    finally:
        client.close()
        server.stop()


@pytest.mark.parametrize("server_cls", ["http", "socket"])
def test_attempt_record_eviction_rolls_back_and_keeps_exactly_once(server_cls):
    """_MAX_ATTEMPT_RECORDS bounds server memory on long-lived servers by
    evicting the oldest attempt record. The eviction must roll the evicted
    task's uncommitted contribution back (it is presumed dead), so that a
    task that nonetheless retries later re-pushes from scratch and nothing
    double-applies — exactly-once survives the eviction."""
    server, client = start(server_cls)
    server._MAX_ATTEMPT_RECORDS = 4   # instance override: tiny cap
    try:
        client.register_attempt("victim", 0)
        client.update_parameters_tagged("victim", delta(1.0))
        for i in range(3):
            client.register_attempt(f"filler-{i}", 0)
        assert attempt_count(server) == 4
        # one past the cap: the oldest ("victim") is evicted and its
        # uncommitted 1.0 rolled back
        client.register_attempt("overflow", 0)
        assert attempt_count(server) == 4
        got = client.get_parameters()
        np.testing.assert_allclose(got[0], W0[0])
        np.testing.assert_allclose(got[1], W0[1])
        # the evicted task retries: it re-registers from scratch and its
        # new pushes apply exactly once (no ghost of the rolled-back 1.0)
        assert client.register_attempt("victim", 1) is True
        client.update_parameters_tagged("victim", delta(5.0))
        got = client.get_parameters()
        np.testing.assert_allclose(got[0], W0[0] - 5.0)
        np.testing.assert_allclose(got[1], W0[1] - 5.0)
    finally:
        client.close()
        server.stop()


@pytest.mark.parametrize("server_cls", ["http", "socket"])
def test_eviction_of_committed_free_records_rolls_back_nothing(server_cls):
    """Records with no uncommitted pushes evict without touching weights."""
    server, client = start(server_cls)
    server._MAX_ATTEMPT_RECORDS = 2
    try:
        client.register_attempt("a", 0)       # never pushes
        client.register_attempt("b", 0)
        client.update_parameters_tagged("b", delta(2.0))
        client.register_attempt("c", 0)       # evicts "a": no rollback
        got = client.get_parameters()
        np.testing.assert_allclose(got[0], W0[0] - 2.0)
        assert attempt_count(server) == 2
    finally:
        client.close()
        server.stop()


def test_http_register_transient_error_raises_not_degrades():
    """A 503 from /register is a transient fault on an attempt-API-capable
    server — the client must surface it (task retry handles it), NOT silently
    fall back to untagged pushes (which would reopen the double-apply hole)."""
    import http.server
    import threading
    import urllib.error

    class FlakyHandler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            self.send_error(503)

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FlakyHandler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        client = BaseParameterClient.get_client(
            "http", port=httpd.server_address[1], host="127.0.0.1"
        )
        with pytest.raises(urllib.error.HTTPError):
            client.register_attempt("partition-0", 0)
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_socket_register_against_reference_server_degrades():
    """A reference-shaped socket server (only 'g'/'u' opcodes) closes the
    connection on the unknown 'r' opcode; the client must return False AND
    recover its connection for plain pulls/pushes."""
    import socket as socket_mod
    import threading

    from elephas_tpu.utils import sockets as socket_utils

    weights = [np.zeros(2)]
    srv = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    stop = threading.Event()

    def serve():
        while not stop.is_set():
            try:
                srv.settimeout(0.2)
                conn, _ = srv.accept()
            except OSError:
                continue
            while True:
                op = conn.recv(1)
                if op == b"g":
                    socket_utils.send(conn, weights)
                elif op == b"u":
                    socket_utils.receive(conn)
                else:  # reference behavior: unknown opcode -> drop connection
                    conn.close()
                    break

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    try:
        client = BaseParameterClient.get_client(
            "socket", port=srv.getsockname()[1], host="127.0.0.1"
        )
        assert client.register_attempt("partition-0", 0) is False
        # degraded path must still work on a fresh connection
        client.update_parameters([np.ones(2)])
        np.testing.assert_allclose(client.get_parameters()[0], weights[0])
        client.close()
    finally:
        stop.set()
        srv.close()


def test_http_register_against_reference_server_degrades():
    """A server without /register (the reference's Flask routes) → False."""
    import http.server
    import pickle
    import threading

    class RefHandler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            payload = pickle.dumps([np.zeros(2)])
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_POST(self):
            if self.path.rstrip("/") == "/update":
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()
            else:
                self.send_error(404)

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), RefHandler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        client = BaseParameterClient.get_client(
            "http", port=httpd.server_address[1], host="127.0.0.1"
        )
        assert client.register_attempt("partition-0", 0) is False
        client.update_parameters([np.ones(2)])  # plain push still works
    finally:
        httpd.shutdown()
        httpd.server_close()
