"""The train-to-serve pipeline: StreamTrainer -> PS -> WeightPublisher.

Runs against REAL parameter servers (http and socket, port=0) with a
pure-numpy ``train_fn`` — the stream contract (ordered exactly-once
commits, monotone version stamps), the publisher's cadence legs, the eval
gate with poisoned-update auto-rollback, the bounded ring, and the
``SparkModel.fit_stream`` entry point wiring it all to a live engine.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.parameter.client import BaseParameterClient
from elephas_tpu.parameter.server import HttpServer, SocketServer
from elephas_tpu.streaming import (
    StreamTrainer,
    WeightPublisher,
    engine_sink,
    list_to_params,
    params_to_list,
)

pytestmark = pytest.mark.streaming

SERVERS = {"http": HttpServer, "socket": SocketServer}


def _weights():
    return [np.zeros((3,), np.float32), np.ones((2, 2), np.float32)]


def _server_client(kind):
    server = SERVERS[kind](_weights(), port=0)
    server.start()
    client = BaseParameterClient.get_client(kind, port=server.port,
                                            host="127.0.0.1", timeout=10.0)
    return server, client


def _train_fn(weights, batch):
    """Deterministic toy step: add the batch scalar everywhere; loss is
    the scalar (lets tests poison specific commits)."""
    return [w + np.float32(batch) for w in weights], float(batch)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


# -- trainer --------------------------------------------------------------

@pytest.mark.parametrize("kind", list(SERVERS))
def test_trainer_commits_are_ordered_and_version_stamped(kind):
    server, client = _server_client(kind)
    try:
        trainer = StreamTrainer(client, _train_fn)
        commits = trainer.run([0.5, 1.0, 0.25, 2.0])
        assert [c.index for c in commits] == [0, 1, 2, 3]
        # one applied delta per commit: stamps are exactly 1..4
        assert [c.version for c in commits] == [1, 2, 3, 4]
        assert [c.loss for c in commits] == [0.5, 1.0, 0.25, 2.0]
        # the PS master integrated every micro-batch exactly once
        np.testing.assert_allclose(server.get_weights()[0],
                                   np.full((3,), 3.75, np.float32))
        assert trainer._tagged      # rode the exactly-once fence
    finally:
        client.close()
        server.stop()


def test_trainer_resume_cursor_skips_committed_batches():
    server, client = _server_client("socket")
    try:
        trainer = StreamTrainer(client, _train_fn)
        trainer.run([1.0, 1.0, 1.0], publisher=None)
        # resume from ordinal 3 of the same logical stream: 0..2 skipped
        more = trainer.run([1.0, 1.0, 1.0, 1.0, 1.0], start_index=3)
        assert [c.index for c in more] == [3, 4]
        assert server.version == 5  # 3 + 2, nothing double-applied
    finally:
        client.close()
        server.stop()


# -- publisher cadence ----------------------------------------------------

def test_publish_every_n_commits():
    server, client = _server_client("http")
    try:
        seen = []
        pub = WeightPublisher(client, lambda w, v: seen.append(v),
                              publish_every=3)
        StreamTrainer(client, _train_fn).run([1.0] * 7, publisher=pub)
        assert seen == [3, 6]
        assert pub.state_dict()["commits_since"] == 1
    finally:
        client.close()
        server.stop()


def test_publish_time_leg_fires_between_count_boundaries():
    server, client = _server_client("http")
    try:
        clock = FakeClock()
        seen = []
        pub = WeightPublisher(client, lambda w, v: seen.append(v),
                              publish_every=100, max_interval_s=5.0,
                              clock=clock)
        trainer = StreamTrainer(client, _train_fn)
        for i in range(4):
            pub.offer(trainer.step(1.0, index=i))
            clock.advance(2.0)
        # the 4th offer (t=6s) crossed the 5s bound despite count << 100
        assert seen == [4]
    finally:
        client.close()
        server.stop()


# -- eval gate + rollback -------------------------------------------------

def _eval_fn(weights, batch):
    # "loss" = mean weight magnitude: grows when a poisoned (huge) delta
    # lands, shrinks/stays flat for the benign stream of negative batches
    return float(np.mean([np.abs(w).mean() for w in weights]))


def test_poisoned_update_auto_rolls_back():
    """A poisoned commit regresses the eval gate: the sink is rolled back
    to the last good version (original stamp), the candidate is refused,
    and once training recovers the publisher resumes publishing."""
    server, client = _server_client("socket")
    try:
        seen = []
        pub = WeightPublisher(client, lambda w, v: seen.append((v, w[0][0])),
                              publish_every=1, eval_fn=_eval_fn,
                              regression_margin=1e-6)
        trainer = StreamTrainer(client, _train_fn)
        pub.offer(trainer.step(-0.25, index=0))     # good: publishes v1
        pub.offer(trainer.step(100.0, index=1))     # poisoned: refused
        pub.offer(trainer.step(-100.0, index=2))    # recovery: publishes v3
        events = [r.event for r in pub.history]
        assert events == ["publish", "rollback", "publish"]
        rb = pub.history[1]
        assert rb.version == 1 and rb.rejected_version == 2
        # the poison NEVER reached the sink: it kept serving v1 (already
        # the last good — no redundant republish), then took v3
        assert [v for v, _ in seen] == [1, 3]
        assert pub.rollbacks == 1 and pub.published == 2
        assert pub.serving_version == 3

        # a freshly restarted sink (resume: publisher state says v3 but
        # the engine came back cold) DOES get last-good actively re-fed
        # when the next candidate regresses
        pub.serving_version = -1
        pub.offer(trainer.step(100.0, index=3))     # poisoned again
        assert [v for v, _ in seen] == [1, 3, 3]
        np.testing.assert_allclose(seen[2][1], seen[1][1])
        assert pub.rollbacks == 2 and pub.serving_version == 3
    finally:
        client.close()
        server.stop()


def test_first_publish_has_no_gate_baseline():
    server, client = _server_client("http")
    try:
        seen = []
        pub = WeightPublisher(client, lambda w, v: seen.append(v),
                              publish_every=1, eval_fn=_eval_fn)
        pub.offer(StreamTrainer(client, _train_fn).step(50.0))
        assert seen == [1]          # nothing to regress against yet
        assert pub.last_good_loss is not None
    finally:
        client.close()
        server.stop()


def test_ring_is_bounded_and_newest_wins():
    server, client = _server_client("http")
    try:
        pub = WeightPublisher(client, lambda w, v: None, publish_every=1,
                              ring_size=3)
        trainer = StreamTrainer(client, _train_fn)
        for i in range(6):
            pub.offer(trainer.step(1.0, index=i))
        assert pub.ring_versions() == [4, 5, 6]   # oldest fell off
        # ring holds detached copies, not the live master
        v, w, _ = pub.ring[-1]
        trainer.step(99.0)
        np.testing.assert_allclose(w[0], np.full((3,), 6.0, np.float32))
    finally:
        client.close()
        server.stop()


def test_publisher_state_roundtrips_through_json():
    import json

    server, client = _server_client("http")
    try:
        pub = WeightPublisher(client, lambda w, v: None, publish_every=2,
                              eval_fn=_eval_fn)
        trainer = StreamTrainer(client, _train_fn)
        for i in range(5):
            pub.offer(trainer.step(-0.1, index=i))
        state = json.loads(json.dumps(pub.state_dict()))  # JSON-able
        clone = WeightPublisher(client, lambda w, v: None, publish_every=2,
                                eval_fn=_eval_fn)
        clone.load_state_dict(state, weights=server.get_weights())
        assert clone.state_dict()["history"] == pub.state_dict()["history"]
        assert clone.commits_since == pub.commits_since
        assert clone.last_good_version == pub.last_good_version
    finally:
        client.close()
        server.stop()


# -- end-to-end: fit_stream wiring to a live engine ------------------------

def test_fit_stream_publishes_into_live_engine():
    """SparkModel.fit_stream drives its own PS + the publisher into a
    live ServingEngine sink: the engine's version gauge advances, tokens
    get attributed, and the master network ends on the final PS weights."""
    from elephas_tpu import SparkModel
    from elephas_tpu.models.transformer import TransformerLM
    from elephas_tpu.serving import ServingEngine

    model = TransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=2,
                          d_ff=32, max_len=48)
    p0 = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    eng = ServingEngine(model, p0, n_slots=2)
    rng = np.random.default_rng(0)
    rid = eng.submit(rng.integers(0, 17, size=(5,)).astype(np.int32), 4,
                     seed=0)
    eng.step()      # prefill + first token under the initial version 0

    class _LMShim:
        """Keras-shaped facade over the LM params for SparkModel's
        start_server/set_weights plumbing (PS wire order = sorted keys)."""
        def __init__(self, params):
            self.params = dict(params)

        def get_weights(self):
            return params_to_list(self.params)

        def set_weights(self, weights):
            self.params = list_to_params(weights, self.params)

    shim = _LMShim(model.init(seed=1))
    sm = SparkModel(shim, mode="asynchronous",
                    parameter_server_mode="socket", port=0)

    def train_fn(weights, batch):
        return [w + np.float32(batch) * 1e-3 for w in weights], float(batch)

    def sink(weights, version):
        engine_sink(eng, p0)(weights, version)
        eng.step()          # decode a round under each published version

    summary = sm.fit_stream([1.0, 2.0, 3.0, 4.0], train_fn, sink=sink,
                            publish_every=2)
    eng.drain(max_steps=200)
    assert summary["commits"] == 4
    assert summary["publisher"]["published"] == 2
    assert summary["last_version"] == 4
    assert eng.weights_version == 4              # last published stamp
    rec = eng.result(rid)
    assert rec.version_first == 0 and rec.version_last == 4
    assert all(v in (0, 2, 4) for v in rec.token_versions)
    # the master network integrated all four micro-batches
    np.testing.assert_allclose(
        shim.params["tok"],
        np.asarray(model.init(seed=1)["tok"]) + np.float32(10.0) * 1e-3,
        rtol=1e-5, atol=1e-6)


def test_fit_stream_rejects_modes_without_live_ps(classifier_factory):
    from elephas_tpu import SparkModel

    sm = SparkModel(classifier_factory(), mode="synchronous")
    with pytest.raises(ValueError, match="fit_stream"):
        sm.fit_stream([1.0], _train_fn)
