"""Version piggyback parity across transports + monotonicity on failover.

The HTTP transport has stamped pulls with ``X-Elephas-Version`` since the
failover PR; the socket transport only had the explicit ``b"v"`` probe —
so a socket pull could not bound its own staleness. The ``b"G"`` opcode
closes that gap: one atomic ``(version, weights)`` pair per pull, with a
probe-and-degrade dance against legacy servers (which close the
connection on the unknown opcode).

The capstone is the replication-lag scenario: a client that committed
through the primary must NEVER observe a post-failover pull older than
its last acknowledged commit — the FailoverClient holds the pull until
the standby's version catches up, which only works because pulls now
carry versions on BOTH transports.
"""

import socket as socket_mod
import threading

import numpy as np
import pytest

from elephas_tpu.parameter.client import BaseParameterClient, SocketClient
from elephas_tpu.parameter.server import HttpServer, SocketServer
from elephas_tpu.resilience.policy import FailoverClient
from elephas_tpu.utils import sockets as socket_utils

pytestmark = pytest.mark.streaming


def _weights():
    return [np.zeros((3,), np.float32)]


def _delta(v):
    return [np.full((3,), v, np.float32)]


# -- cross-transport parity -----------------------------------------------

def test_pull_version_piggyback_parity_http_vs_socket():
    """Same update sequence, both transports: every pull leaves the
    client holding the exact server version those weights correspond to,
    and the weights agree bit-for-bit."""
    servers = {k: cls(_weights(), port=0)
               for k, cls in (("http", HttpServer), ("socket", SocketServer))}
    clients = {}
    try:
        for kind, server in servers.items():
            server.start()
            clients[kind] = BaseParameterClient.get_client(
                kind, port=server.port, host="127.0.0.1", timeout=10.0)
        for step in range(1, 4):
            pulled = {}
            for kind in servers:
                servers[kind].apply_delta(_delta(1.0))
                pulled[kind] = clients[kind].get_parameters()
                assert clients[kind].last_seen_version == step, kind
            np.testing.assert_array_equal(pulled["http"][0],
                                          pulled["socket"][0])
    finally:
        for c in clients.values():
            c.close()
        for s in servers.values():
            s.stop()


def test_versioned_weights_pair_is_consistent():
    server = SocketServer(_weights(), port=0)
    server.start()
    try:
        for i in range(3):
            server.apply_delta(_delta(1.0))
            version, weights = server.get_versioned_weights()
            assert version == i + 1
            np.testing.assert_allclose(
                weights[0], np.full((3,), -(i + 1.0), np.float32))
    finally:
        server.stop()


# -- legacy degrade -------------------------------------------------------

class _LegacyServer:
    """A pre-versioned-pull socket server: knows ``b"g"``/``b"v"`` only
    and CLOSES the connection on any other opcode (the real legacy
    listener's ``else: break``)."""

    def __init__(self, weights):
        self.weights = weights
        self.version = 0
        self._sock = socket_mod.socket()
        self._sock.setsockopt(socket_mod.SOL_SOCKET,
                              socket_mod.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(4)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket_mod.timeout:
                continue
            except OSError:
                return
            try:
                while True:
                    op = conn.recv(1)
                    if op == b"g":
                        socket_utils.send(conn, self.weights)
                    elif op == b"v":
                        socket_utils.send(conn, self.version)
                    else:
                        break
            except OSError:
                pass
            finally:
                conn.close()

    def stop(self):
        self._stop.set()
        self._sock.close()
        self._thread.join(timeout=5)


def test_socket_client_degrades_against_legacy_server():
    """First pull probes ``b"G"``, eats the legacy close, retries with
    ``b"g"`` on a fresh connection, and stays degraded — later pulls go
    straight to the legacy opcode. No version piggyback, exactly like a
    pre-header HTTP server."""
    legacy = _LegacyServer(_weights())
    client = SocketClient(port=legacy.port, host="127.0.0.1", timeout=10.0)
    try:
        assert client._versioned_pull
        for _ in range(2):
            weights = client.get_parameters()
            np.testing.assert_array_equal(weights[0], _weights()[0])
        assert not client._versioned_pull
        assert client.last_seen_version == -1    # staleness unbounded
    finally:
        client.close()
        legacy.stop()


def test_socket_client_restores_probe_after_outage():
    """A DEAD server also fails the ``b"G"`` probe — but the legacy
    fallback fails too, which distinguishes outage from old code: the
    probe is restored so a recovered modern server isn't permanently
    downgraded."""
    server = SocketServer(_weights(), port=0)
    server.start()
    port = server.port
    server.stop()
    client = SocketClient(port=port, host="127.0.0.1", timeout=2.0)
    with pytest.raises((ConnectionError, OSError)):
        client.get_parameters()
    assert client._versioned_pull       # outage != legacy

    revived = SocketServer(_weights(), port=port)
    revived.start()
    try:
        revived.apply_delta(_delta(1.0))
        client.get_parameters()
        assert client.last_seen_version == 1   # piggyback back in force
    finally:
        client.close()
        revived.stop()


# -- monotonicity under replication lag -----------------------------------

class GatedStandby(SocketServer):
    """Standby whose replicated applies block on a gate: deterministic
    replication LAG, released mid-test."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.gate = threading.Event()

    def apply_delta(self, delta, task_id=None, attempt=None):
        assert self.gate.wait(timeout=30), "test gate never released"
        super().apply_delta(delta, task_id=task_id, attempt=attempt)


def test_post_failover_pull_never_older_than_acknowledged_commits():
    """The pinned scenario: 3 commits acknowledged through the primary
    (version 3 observed), standby stuck at 0 behind a replication gate,
    primary dies. The next pull MUST NOT serve version-0 weights — the
    failover holds it until the standby drains to >= 3."""
    primary = SocketServer(_weights(), port=0, name="primary")
    standby = GatedStandby(_weights(), port=0, name="standby")
    primary.start()
    standby.start()
    primary.attach_standby(standby)
    cp = SocketClient(port=primary.port, host="127.0.0.1", timeout=5.0)
    cs = SocketClient(port=standby.port, host="127.0.0.1", timeout=5.0)
    client = FailoverClient([cp, cs], staleness_wait_s=10.0, poll_s=0.01)
    try:
        for _ in range(3):
            client.update_parameters(_delta(1.0))
        assert client.get_version() == 3     # commits acknowledged
        assert standby.version == 0          # replication is gated

        primary._dead = True                 # fail-stop: new traffic dies
        # release the lag only AFTER the failed-over pull is already
        # waiting on the standby's catch-up poll
        threading.Timer(0.3, standby.gate.set).start()
        weights = client.get_parameters()

        assert client.failovers == 1
        # the pull reflects every acknowledged commit — not the stale
        # version-0 standby state the gate was holding
        np.testing.assert_allclose(weights[0],
                                   np.full((3,), -3.0, np.float32))
        assert cs.last_seen_version >= 3     # socket pull carried the stamp
        assert standby.version >= 3
    finally:
        standby.gate.set()
        cp.close()
        cs.close()
        primary.stop()
        standby.stop()
