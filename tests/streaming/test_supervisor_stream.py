"""Streaming under the TrainingSupervisor: crash mid-stream, resume from
the checkpointed cursor + publisher state, and replay the version history
DETERMINISTICALLY — the crashed-and-resumed run produces the identical
commit stream, publish/rollback history, and final weights as an
uninterrupted run at the same seed."""

import numpy as np
import pytest

from elephas_tpu.parameter.client import BaseParameterClient
from elephas_tpu.parameter.server import SocketServer
from elephas_tpu.resilience import SupervisorAborted, TrainingSupervisor
from elephas_tpu.streaming import StreamTrainer, WeightPublisher
from elephas_tpu.utils.checkpoint import load_checkpoint

pytestmark = pytest.mark.streaming


def _weights():
    return [np.zeros((3,), np.float32)]


def _batches(seed, n=8):
    rng = np.random.default_rng(seed)
    return [float(x) for x in rng.normal(size=n)]


def _train_fn(weights, batch):
    return [w + np.float32(batch) for w in weights], float(batch)


class CrashingTrainFn:
    """Deterministic train step that dies ONCE at batch ordinal
    ``crash_at`` (batch boundaries are the only crash sites the stream
    contract needs to survive: a mid-push crash is the PS attempt
    machinery's job, pinned in the chaos suite)."""

    def __init__(self, crash_at):
        self.crash_at = crash_at
        self.calls = 0

    def __call__(self, weights, batch):
        self.calls += 1
        if self.calls == self.crash_at:
            self.crash_at = None        # crash once
            raise RuntimeError("injected stream crash")
        return _train_fn(weights, batch)


def _run_stream(tmpdir, batches, train_fn, *, crash=False,
                publish_every=2, eval_gate=True):
    """One full supervised stream against a fresh socket PS; returns
    (publish history, final PS weights, supervisor events)."""
    server = SocketServer(_weights(), port=0)
    server.start()
    client = BaseParameterClient.get_client("socket", port=server.port,
                                            host="127.0.0.1", timeout=10.0)
    try:
        published = []
        eval_fn = ((lambda w, b: float(np.abs(w[0]).mean()))
                   if eval_gate else None)
        pub = WeightPublisher(client,
                              lambda w, v: published.append((v, w[0][0])),
                              publish_every=publish_every, eval_fn=eval_fn,
                              regression_margin=0.5)
        trainer = StreamTrainer(client, train_fn)
        sup = TrainingSupervisor(None, str(tmpdir),
                                 checkpoint_frequency=1,
                                 max_restarts=2 if crash else 0)
        sup.fit_stream(batches, trainer, publisher=pub)
        history = [dict(e.__dict__) for e in pub.history]
        return history, [w.copy() for w in server.get_weights()], sup.events
    finally:
        client.close()
        server.stop()


def test_crash_resume_replays_version_history_exactly(tmp_path):
    """The pinned determinism scenario: same seed, crash at batch 5 vs no
    crash — identical publish/rollback history (versions, losses, commit
    indices), identical final weights, and the server never applied a
    batch twice."""
    batches = _batches(seed=42)

    clean_hist, clean_w, clean_events = _run_stream(
        tmp_path / "clean", batches, _train_fn)

    crashed_hist, crashed_w, events = _run_stream(
        tmp_path / "crashed", batches, CrashingTrainFn(crash_at=5),
        crash=True)

    assert [e.kind for e in events] == ["start", "crash", "resume",
                                        "complete"]
    assert crashed_hist == clean_hist       # version history replays
    np.testing.assert_allclose(crashed_w[0], clean_w[0], rtol=1e-6)
    # exactly-once: final version == number of batches, both runs
    assert clean_hist[-1]["version"] <= len(batches)


def test_checkpoint_carries_cursor_and_publisher_state(tmp_path):
    batches = _batches(seed=7, n=5)
    _run_stream(tmp_path, batches, _train_fn, publish_every=2)
    weights, meta, _ = load_checkpoint(str(tmp_path))
    assert meta["mode"] == "stream"
    stream = meta["stream"]
    assert stream["batches_done"] == 5
    assert stream["commits"] == 5
    pub_state = stream["publisher"]
    assert pub_state["published"] >= 1
    assert [r["event"] for r in pub_state["history"]]
    # checkpointed weights are the PS master at the cursor
    np.testing.assert_allclose(
        weights[0], np.full((3,), sum(batches), np.float32), rtol=1e-5)


def test_restart_budget_still_enforced_for_streams(tmp_path):
    server = SocketServer(_weights(), port=0)
    server.start()
    client = BaseParameterClient.get_client("socket", port=server.port,
                                            host="127.0.0.1", timeout=10.0)
    try:
        class AlwaysCrash:
            def __call__(self, weights, batch):
                raise RuntimeError("always dies")

        trainer = StreamTrainer(client, AlwaysCrash())
        sup = TrainingSupervisor(None, str(tmp_path / "cp"),
                                 checkpoint_frequency=1, max_restarts=1)
        with pytest.raises(SupervisorAborted, match="budget"):
            sup.fit_stream(_batches(seed=1, n=3), trainer)
        assert sup.restarts == 1
    finally:
        client.close()
        server.stop()


def test_resume_skips_committed_batches_on_live_server(tmp_path):
    """The PS outlives the driver crash: resume must NOT re-apply
    committed batches to the still-warm server (the version counter would
    jump and the weights would double-integrate)."""
    server = SocketServer(_weights(), port=0)
    server.start()
    client = BaseParameterClient.get_client("socket", port=server.port,
                                            host="127.0.0.1", timeout=10.0)
    try:
        batches = [1.0, 1.0, 1.0, 1.0]
        trainer = StreamTrainer(client, CrashingTrainFn(crash_at=3))
        sup = TrainingSupervisor(None, str(tmp_path),
                                 checkpoint_frequency=1, max_restarts=1)
        sup.fit_stream(batches, trainer)
        assert server.version == 4      # one applied delta per batch
        np.testing.assert_allclose(server.get_weights()[0],
                                   np.full((3,), 4.0, np.float32))
    finally:
        client.close()
        server.stop()
