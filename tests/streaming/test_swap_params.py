"""Hot weight rollover on the serving engine: ``swap_params``.

The pinned contract (ROADMAP "streaming" milestone):

- **Zero token corruption** — swapping between decode rounds never
  produces a token that neither version would have produced: the stream
  is token-identical to a REPLAY that applies the same version schedule
  at the same step indices. Pinned across dense/paged × greedy/sampled ×
  speculation on/off.
- **Exact attribution** — every emitted token carries exactly one
  weights version (``token_versions``), and boundaries fall only between
  decode rounds.
- **No drain** — in-flight requests keep decoding through the swap (KV
  computed under the old version stays; only future work uses the new
  weights). Decode throughput under continuous publication stays within
  10% of the static engine.
- **Speculation** — an NgramDrafter keeps speculating (the verify rule is
  exact under any proposer); a ModelDrafter stands down until its own
  params are refreshed.
- **Paged** — the radix prefix cache is flushed at the swap (its pages
  hold old-version KV) and prompts whose chunked prefill spanned the swap
  never register prefix pages.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models.transformer import TransformerLM
from elephas_tpu.serving import ServingEngine
from elephas_tpu.serving.engine import ModelDrafter

pytestmark = pytest.mark.streaming

V = 17


def _model(**kw):
    cfg = dict(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=48)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=1):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


def _prompts(rng, lens):
    return [rng.integers(0, V, size=(n,)).astype(np.int32) for n in lens]


def _run_with_schedule(eng, reqs, schedule, max_steps=5000, **submit_kw):
    """Submit every request up front, then step to completion applying
    ``schedule`` = {step_index: (params, version, drafter_params)} BETWEEN
    steps. Returns (tokens per request, token_versions per request)."""
    ids = [eng.submit(p, n, seed=i, **submit_kw)
           for i, (p, n) in enumerate(reqs)]
    step = 0
    while step < max_steps:
        if step in schedule:
            params, version, dp = schedule[step]
            eng.swap_params(params, version=version, drafter_params=dp)
        if eng.step() == "idle" and not eng._requests:
            break
        step += 1
    out = [eng.result(rid) for rid in ids]
    return [r.tokens for r in out], [r.token_versions for r in out]


def _engines(model):
    """The knob matrix the corruption pin runs over."""
    return {
        "dense": dict(n_slots=2),
        "dense-chunked-fused": dict(n_slots=2, prefill_chunk=8, fuse_k=4),
        "paged": dict(n_slots=2, paged=True, page_size=8),
        "spec-ngram": dict(n_slots=2, speculate_k=3),
    }


# -- replay identity (the zero-corruption pin) ----------------------------

@pytest.mark.parametrize("knobs", list(_engines(None).values()),
                         ids=list(_engines(None).keys()))
@pytest.mark.parametrize("temperature", [0.0, 0.8],
                         ids=["greedy", "sampled"])
def test_swap_stream_replays_identically(knobs, temperature):
    """Same prompts + same version schedule at the same step indices =>
    the same tokens, the same attribution — across every engine knob,
    greedy and seeded-sampled. This is the zero-corruption property: a
    divergent replay would mean some token depended on state the swap
    corrupted."""
    model = _model()
    p1, p2, p3 = _params(model, 1), _params(model, 2), _params(model, 3)
    rng = np.random.default_rng(0)
    reqs = [(p, 7) for p in _prompts(rng, [5, 11, 3, 8])]
    schedule = {2: (p2, 1, None), 5: (p3, 2, None)}

    runs = []
    for _ in range(2):
        eng = ServingEngine(model, p1, **knobs)
        runs.append(_run_with_schedule(eng, reqs, dict(schedule),
                                       temperature=temperature))
    assert runs[0] == runs[1]
    toks, vers = runs[0]
    for t, v in zip(toks, vers):
        assert len(t) == len(v) == 7          # exactly one version per token
        assert all(x in (0, 1, 2) for x in v)
        assert v == sorted(v)                 # monotone: forward swaps only


def test_prefix_versions_pinned_against_static_engines():
    """Sanity anchor for the replay pin: the tokens emitted BEFORE the
    first swap match the static old-version engine exactly, so the replay
    identity above is not vacuously comparing two broken streams."""
    model = _model()
    p1, p2 = _params(model, 1), _params(model, 2)
    rng = np.random.default_rng(1)
    reqs = [(p, 6) for p in _prompts(rng, [4, 9])]

    eng = ServingEngine(model, p1, n_slots=2)
    toks, vers = _run_with_schedule(eng, reqs, {3: (p2, 1, None)})

    static = ServingEngine(model, p1, n_slots=2)
    stoks, _ = _run_with_schedule(static, reqs, {})
    for t, v, s in zip(toks, vers, stoks):
        n_old = sum(1 for x in v if x == 0)
        assert t[:n_old] == s[:n_old]
    assert any(0 in v and 1 in v for v in vers)  # a swap actually landed


def test_finished_request_version_summary():
    model = _model()
    p1, p2 = _params(model, 1), _params(model, 2)
    rng = np.random.default_rng(2)
    (prompt,) = _prompts(rng, [6])

    eng = ServingEngine(model, p1, n_slots=1)
    rid = eng.submit(prompt, 6, seed=0)
    eng.step(); eng.step(); eng.step()
    eng.swap_params(p2)              # version defaults to +1
    eng.drain(max_steps=200)
    rec = eng.result(rid)
    assert rec.version_first == rec.token_versions[0] == 0
    assert rec.version_last == rec.token_versions[-1] == 1
    snap = eng.snapshot()["engine"]
    assert snap["weights_version"] == 1
    assert snap["weight_swaps"] == 1


def test_cancelled_before_first_token_has_empty_attribution():
    model = _model()
    eng = ServingEngine(model, _params(model), n_slots=1)
    rng = np.random.default_rng(3)
    (prompt,) = _prompts(rng, [4])
    rid = eng.submit(prompt, 6, seed=0)
    eng.cancel(rid)
    eng.drain(max_steps=50)
    rec = eng.result(rid)
    assert rec.token_versions == []
    assert rec.version_first == rec.version_last == -1


def test_rollback_republishes_older_stamp():
    """A rollback publishes an OLDER version with its original stamp: the
    gauge reports what is serving, and attribution follows the schedule,
    monotone or not."""
    model = _model()
    p1, p2 = _params(model, 1), _params(model, 2)
    eng = ServingEngine(model, p1, n_slots=1)
    eng.swap_params(p2, version=7)
    eng.swap_params(p1, version=3)   # rollback: older stamp, gauge follows
    assert eng.weights_version == 3
    assert eng.snapshot()["engine"]["weights_version"] == 3
    assert eng.snapshot()["engine"]["weight_swaps"] == 2


# -- speculation ----------------------------------------------------------

def test_model_drafter_stands_down_until_refreshed():
    """A swap without drafter params stalls speculation (window 0, exact
    single-token decode continues); handing fresh drafter params in the
    swap re-arms it atomically."""
    model = _model()
    p1, p2 = _params(model, 1), _params(model, 2)
    eng = ServingEngine(model, p1, n_slots=2, speculate_k=3,
                        drafter=ModelDrafter(model, p1))
    rng = np.random.default_rng(4)
    ids = [eng.submit(p, 10, seed=i)
           for i, p in enumerate(_prompts(rng, [5, 7]))]
    for _ in range(3):
        eng.step()
    eng.swap_params(p2)
    assert eng._drafter_stale and eng._spec_window() == 0
    eng.drain(max_steps=500)           # completes WITHOUT speculation
    assert all(len(eng.result(r).tokens) == 10 for r in ids)

    eng2 = ServingEngine(model, p1, n_slots=2, speculate_k=3,
                         drafter=ModelDrafter(model, p1))
    eng2.swap_params(p2, drafter_params=p2)
    assert not eng2._drafter_stale     # atomic pair swap: no stand-down


def test_drafter_params_without_model_drafter_rejected():
    model = _model()
    p1, p2 = _params(model, 1), _params(model, 2)
    eng = ServingEngine(model, p1, n_slots=2, speculate_k=3)  # ngram
    with pytest.raises(ValueError, match="ModelDrafter"):
        eng.swap_params(p2, drafter_params=p2)


def test_ngram_drafter_keeps_speculating_through_swap():
    model = _model()
    p1, p2 = _params(model, 1), _params(model, 2)
    eng = ServingEngine(model, p1, n_slots=2, speculate_k=3)
    rng = np.random.default_rng(5)
    ids = [eng.submit(p, 12, seed=i)
           for i, p in enumerate(_prompts(rng, [6, 6]))]
    for _ in range(4):
        eng.step()
    before = eng.snapshot()["fastpath"]["spec_rounds"]
    eng.swap_params(p2)
    eng.drain(max_steps=500)
    assert eng.snapshot()["fastpath"]["spec_rounds"] > before
    assert all(len(eng.result(r).tokens) == 12 for r in ids)


# -- paged prefix cache ---------------------------------------------------

def test_swap_flushes_prefix_cache_and_refcounts_survive():
    """Old-version prefix pages are dropped at the swap; live slots hold
    their own increfs so in-flight requests finish; the allocator's
    refcount invariant holds through flush + new-version reuse."""
    model = _model()
    p1, p2 = _params(model, 1), _params(model, 2)
    rng = np.random.default_rng(6)
    (long_p, short_p) = _prompts(rng, [16, 4])

    eng = ServingEngine(model, p1, n_slots=2, paged=True, page_size=8)
    eng.submit(long_p, 3, seed=0)
    eng.drain(max_steps=200)           # finished => its prefix registered
    assert eng.kv.memory_stats()["prefix"]["nodes"] > 0

    mid = eng.submit(long_p, 6, seed=1)  # adopts the cached prefix
    eng.step()
    eng.swap_params(p2)
    assert eng.kv.memory_stats()["prefix"]["nodes"] == 0  # flushed
    eng.submit(short_p, 4, seed=2)
    eng.drain(max_steps=300)
    assert len(eng.result(mid).tokens) == 6  # in-flight request unharmed
    eng.kv.check()                     # refcount invariant intact


def test_chunked_prefill_spanning_swap_never_registers_prefix():
    """A prompt whose chunked prefill straddles the swap holds
    mixed-version KV — it must finish fine but NOT seed the prefix cache
    (a later adopter would silently attend two weight versions)."""
    model = _model()
    p1, p2 = _params(model, 1), _params(model, 2)
    rng = np.random.default_rng(7)
    (long_p,) = _prompts(rng, [24])

    eng = ServingEngine(model, p1, n_slots=2, paged=True, page_size=8,
                        prefill_chunk=8)
    rid = eng.submit(long_p, 3, seed=0)
    eng.step()                         # first chunk under version 0
    eng.swap_params(p2)                # remaining chunks under version 1
    eng.drain(max_steps=300)
    assert len(eng.result(rid).tokens) == 3
    assert eng.kv.memory_stats()["prefix"]["nodes"] == 0
    eng.kv.check()


# -- throughput under continuous publication ------------------------------

def _decode_rate(swap_every, model, p1, p2, reqs):
    eng = ServingEngine(model, p1, n_slots=4)
    ids = [eng.submit(p, n, seed=i) for i, (p, n) in enumerate(reqs)]
    params_cycle = [p2, p1]
    step = 0
    t0 = time.perf_counter()
    while any(eng.result(r, pop=False) is None for r in ids):
        if swap_every and step and step % swap_every == 0:
            eng.swap_params(params_cycle[(step // swap_every) % 2])
        eng.step()
        step += 1
        if step > 5000:
            raise AssertionError("drain did not converge")
    dt = time.perf_counter() - t0
    emitted = sum(len(eng.result(r, pop=False).tokens) for r in ids)
    return emitted / dt


def test_decode_throughput_within_10pct_under_publication():
    """Continuous publication (a swap every 4 decode rounds — far hotter
    than any sane cadence) costs < 10% decode throughput vs the static
    engine: the swap is a host pointer flip, no retrace, no drain.
    Median of 3 to beat CPU timer noise."""
    model = _model()
    p1, p2 = _params(model, 1), _params(model, 2)
    rng = np.random.default_rng(8)
    reqs = [(p, 24) for p in _prompts(rng, [6, 6, 6, 6])]

    _decode_rate(0, model, p1, p2, reqs)        # warmup: compile both
    _decode_rate(4, model, p1, p2, reqs)
    static = sorted(_decode_rate(0, model, p1, p2, reqs) for _ in range(3))[1]
    rolling = sorted(_decode_rate(4, model, p1, p2, reqs) for _ in range(3))[1]
    assert rolling >= 0.9 * static, (
        f"continuous publication cost too much: {rolling:.1f} vs "
        f"{static:.1f} tok/s")
