"""Unit tests for the elastic-membership layer: HeartbeatRegistry lease /
epoch / fence semantics under a fake clock, and QuorumRunner's K-of-N round
mechanics (retries, partial commit, straggler backups, late-result fencing)
with plain-python tasks — no Keras, no parameter server."""

import threading
import time

import pytest

from elephas_tpu.data.rdd import TaskContext
from elephas_tpu.resilience import (
    HeartbeatRegistry, QuorumLostError, QuorumRunner, member_id_for,
)

pytestmark = pytest.mark.resilience


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


# -- HeartbeatRegistry -------------------------------------------------------


def test_join_heartbeat_and_epoch_monotonicity():
    clock = FakeClock()
    reg = HeartbeatRegistry(lease_s=5.0, clock=clock)
    assert reg.epoch == 0
    e1 = reg.join("a")
    e2 = reg.join("b")
    assert (e1, e2) == (1, 2)
    # heartbeat of a known member renews the lease without an epoch bump
    clock.advance(1.0)
    reg.heartbeat("a")
    assert reg.epoch == 2
    # heartbeat of an UNKNOWN member is an implicit join (epoch bump)
    reg.heartbeat("c")
    assert reg.epoch == 3
    assert reg.live() == ["a", "b", "c"]


def test_sweep_expires_lapsed_leases_and_fences_them():
    clock = FakeClock()
    reg = HeartbeatRegistry(lease_s=5.0, clock=clock)
    reg.join("a")
    reg.join("b")
    clock.advance(3.0)
    reg.heartbeat("b")
    clock.advance(2.5)          # a: 5.5s silent (expired); b: 2.5s (live)
    assert reg.sweep() == ["a"]
    assert reg.live() == ["b"]
    assert not reg.is_live("a")
    # the expiry fenced a's results at the bumped epoch
    assert reg.fence("a") == reg.epoch == 3
    # rejoin admits the member again but keeps results launched before the
    # death fenced: fence moves UP to the rejoin epoch, never down
    reg.join("a")
    assert reg.is_live("a")
    assert reg.fence("a") == reg.epoch == 4


def test_is_live_default_answers_for_unknown_members_only():
    reg = HeartbeatRegistry(lease_s=5.0, clock=FakeClock())
    assert not reg.is_live("ghost")
    assert reg.is_live("ghost", default=True)      # never seen: caller's call
    reg.join("ghost")
    reg.leave("ghost")
    # seen-and-departed is NOT unknown: default must not resurrect it
    assert not reg.is_live("ghost", default=True)


def test_straggler_window_between_threshold_and_lease():
    clock = FakeClock()
    reg = HeartbeatRegistry(lease_s=10.0, straggler_after_s=2.0, clock=clock)
    reg.join("a")
    reg.join("b")
    clock.advance(3.0)
    reg.heartbeat("b")
    assert reg.stragglers() == ["a"]    # 3s silent: past threshold, in lease
    clock.advance(8.0)                  # a now 11s silent: lease lapsed
    reg.heartbeat("b")
    assert reg.stragglers() == []       # a past its lease, b just beat
    assert reg.sweep() == ["a"]


def test_snapshot_shape_and_event_bounds():
    clock = FakeClock()
    reg = HeartbeatRegistry(lease_s=5.0, straggler_after_s=1.0, clock=clock,
                            max_events=4)
    for i in range(10):
        reg.join(f"m{i}")
    reg.observe_backup("m1", 1)
    reg.observe_failover(endpoint=1, version=7)
    reg.observe_round(expected=10, received=8, quorum=8, backups=1,
                      deadline_hit=True)
    snap = reg.snapshot()
    assert snap["membership"]["epoch"] == 10
    assert len(snap["membership"]["live"]) == 10
    assert snap["counters"]["join"] == 10
    assert snap["counters"]["failovers"] == 1
    assert snap["rounds"][-1]["shortfall"] == 2
    assert snap["rounds"][-1]["deadline_hit"] is True
    assert len(snap["events"]) == 4     # bounded deque, newest kept
    assert snap["events"][-1]["kind"] == "round"
    # snapshot must be JSON-able (serving/metrics.py contract)
    import json

    json.dumps(snap)


def test_registry_event_callback_fires():
    seen = []
    reg = HeartbeatRegistry(lease_s=5.0, clock=FakeClock(),
                            on_event=seen.append)
    reg.join("a")
    reg.leave("a")
    assert [e.kind for e in seen] == ["join", "leave"]


# -- QuorumRunner ------------------------------------------------------------


def _registry(**kw):
    kw.setdefault("lease_s", 30.0)
    return HeartbeatRegistry(**kw)


def test_run_commits_every_partition_and_sets_task_context():
    reg = _registry()
    seen = {}

    def task(it):
        ctx = TaskContext.get()
        seen[ctx.partitionId()] = (ctx.attemptNumber(), ctx.stageId())
        yield sum(it)

    runner = QuorumRunner(reg)
    out = runner.run([[1, 2], [3, 4], [5, 6]], task, stage_id=9)
    assert out == {0: [3], 1: [7], 2: [11]}
    assert seen == {0: (0, 9), 1: (0, 9), 2: (0, 9)}
    assert runner.backups_launched == 0 and runner.abandoned == []
    assert reg.snapshot()["rounds"][-1]["shortfall"] == 0


def test_transient_crash_is_retried_with_next_attempt_number():
    reg = _registry()

    def task(it):
        ctx = TaskContext.get()
        if ctx.partitionId() == 1 and ctx.attemptNumber() == 0:
            raise RuntimeError("injected")
        yield ctx.attemptNumber()

    out = QuorumRunner(reg).run([[0], [0], [0]], task)
    assert out == {0: [0], 1: [1], 2: [0]}


def test_permanent_failure_expires_member_but_quorum_commits():
    reg = _registry()

    def task(it):
        ctx = TaskContext.get()
        if ctx.partitionId() == 2:
            raise RuntimeError("always down")
        yield "ok"

    runner = QuorumRunner(reg, quorum=2, max_failures=3)
    out = runner.run([[0], [0], [0]], task)
    assert sorted(out) == [0, 1]
    assert not reg.is_live(member_id_for(2))    # declared dead, fenced
    assert reg.fence(member_id_for(2)) > 0
    assert reg.snapshot()["rounds"][-1]["received"] == 2


def test_quorum_lost_raises_once_too_few_can_report():
    reg = _registry()

    def task(it):
        if TaskContext.get().partitionId() >= 1:
            raise RuntimeError("down")
        yield "ok"

    with pytest.raises(QuorumLostError):
        QuorumRunner(reg, quorum=3, max_failures=2).run([[0], [0], [0]], task)


def test_round_deadline_commits_partial_and_abandons_the_rest():
    reg = _registry()
    release = threading.Event()

    def task(it):
        if TaskContext.get().partitionId() == 2:
            release.wait(5.0)       # never finishes inside the deadline
        yield "ok"

    runner = QuorumRunner(reg, quorum=2, round_deadline_s=0.3)
    try:
        out = runner.run([[0], [0], [0]], task)
    finally:
        release.set()               # unblock the zombie thread
    assert sorted(out) == [0, 1]
    assert runner.abandoned == [2]
    # the abandoned member was expired: its late result is stale by epoch
    assert not reg.is_live(member_id_for(2))
    assert reg.snapshot()["rounds"][-1]["deadline_hit"] is True


def test_straggler_backup_first_finish_wins():
    reg = _registry(straggler_after_s=0.15)
    stalled = threading.Event()

    def task(it):
        ctx = TaskContext.get()
        if ctx.partitionId() == 0 and ctx.attemptNumber() == 0:
            stalled.wait(5.0)       # injected slow node, attempt 0 only
        yield f"attempt-{ctx.attemptNumber()}"

    runner = QuorumRunner(reg)
    try:
        out = runner.run([[0], [0]], task)
    finally:
        stalled.set()
    # the backup clone (attempt 1) won the race; only ITS result committed
    assert out[0] == ["attempt-1"]
    assert out[1] == ["attempt-0"]
    assert runner.backups_launched == 1
    counters = reg.snapshot()["counters"]
    assert counters["backup"] == 1


def test_late_result_after_deadline_commit_is_epoch_fenced():
    """A task abandoned at the deadline eventually finishes: its queued
    result must be rejected by the membership fence, never committed."""
    reg = _registry()
    release = threading.Event()
    finished = threading.Event()

    def task(it):
        if TaskContext.get().partitionId() == 1:
            release.wait(5.0)
            finished.set()
        yield "late"

    runner = QuorumRunner(reg, quorum=1, round_deadline_s=0.2)
    out = runner.run([[0], [0]], task)
    assert sorted(out) == [0]
    release.set()
    assert finished.wait(5.0)
    # launch epoch predates the expiry fence — exactly the stale-by-epoch
    # condition the runner (and the async path's server fence) rejects
    launched_at_most = 2            # both joins happened, nothing later
    assert reg.fence(member_id_for(1)) > launched_at_most


def test_unknown_member_result_paths_never_block_driver():
    """Whole-round wall clock stays bounded by the slowest COMMITTED chain,
    not by zombies: run() must return while the abandoned thread sleeps."""
    reg = _registry()
    release = threading.Event()

    def task(it):
        if TaskContext.get().partitionId() == 1:
            release.wait(5.0)
        yield "ok"

    t0 = time.monotonic()
    try:
        QuorumRunner(reg, quorum=1, round_deadline_s=0.2).run([[0], [0]], task)
    finally:
        elapsed = time.monotonic() - t0
        release.set()
    assert elapsed < 3.0
