"""TrainingSupervisor lifecycle: crash → resume-from-latest-valid-
checkpoint → complete, bounded by the restart budget. The fit itself is a
recording stub here (the real-training end-to-end runs live in
test_chaos.py); what these tests pin is the supervisor's own contract —
what it resumes from, when it gives up, and what it reports."""

import json

import numpy as np
import pytest

from elephas_tpu.resilience import (
    RetryPolicy,
    SupervisorAborted,
    TrainingSupervisor,
)
from elephas_tpu.utils.checkpoint import load_checkpoint

pytestmark = pytest.mark.resilience


class FakeNet:
    """One weight that counts trained epochs — resume math is exact."""

    def __init__(self):
        self._w = [np.zeros((1,), np.float32)]

    def get_weights(self):
        return [w.copy() for w in self._w]

    def set_weights(self, ws):
        self._w = [np.asarray(w, np.float32).copy() for w in ws]


class FakeHostModel:
    """SparkModel stand-in on the host path: fit(epochs=k) adds k to the
    weight; optionally crashes on its Nth fit call."""

    comm = "host"
    mode = "synchronous"

    def __init__(self, crash_on_call=None):
        self.master_network = FakeNet()
        self.fit_calls = 0
        self.crash_on_call = crash_on_call

    def fit(self, rdd, epochs=1, **kwargs):
        self.fit_calls += 1
        if self.fit_calls == self.crash_on_call:
            raise RuntimeError("injected fit crash")
        self.master_network._w = [
            w + epochs for w in self.master_network._w
        ]


class AlwaysCrashModel:
    comm = "jax"

    def fit(self, rdd, **kwargs):
        raise RuntimeError("always dies")


def _events(sup):
    return [e.kind for e in sup.events]


def test_clean_run_checkpoints_and_completes(tmp_path):
    model = FakeHostModel()
    sup = TrainingSupervisor(model, str(tmp_path / "ck"),
                             checkpoint_frequency=2)
    sup.fit(rdd=None, epochs=4)
    assert sup.restarts == 0
    assert _events(sup) == ["start", "complete"]
    assert model.master_network._w[0][0] == 4.0
    weights, meta, _ = load_checkpoint(str(tmp_path / "ck"))
    assert meta["epoch"] == 4 and weights[0][0] == 4.0


def test_crash_resumes_from_latest_checkpoint(tmp_path):
    # freq=1, epochs=4, crash on the 3rd fit call: epochs 1 and 2 are
    # checkpointed, the crash loses nothing durable, and the resumed run
    # must do EXACTLY epochs 3 and 4 — total trained epochs stays 4.
    model = FakeHostModel(crash_on_call=3)
    sup = TrainingSupervisor(model, str(tmp_path / "ck"),
                             checkpoint_frequency=1, max_restarts=2)
    sup.fit(rdd=None, epochs=4)
    assert sup.restarts == 1
    assert _events(sup) == ["start", "crash", "resume", "complete"]
    assert model.master_network._w[0][0] == 4.0      # not 5, not 3
    assert model.fit_calls == 5                      # 4 productive + 1 crash
    _, meta, _ = load_checkpoint(str(tmp_path / "ck"))
    assert meta["epoch"] == 4


def test_budget_exhausted_aborts_with_cause(tmp_path):
    sup = TrainingSupervisor(AlwaysCrashModel(), str(tmp_path / "ck"),
                             max_restarts=2)
    with pytest.raises(SupervisorAborted) as exc:
        sup.fit(rdd=None, epochs=1)
    assert sup.restarts == 2
    assert isinstance(exc.value.__cause__, RuntimeError)
    assert _events(sup).count("crash") == 2          # budget, then abort


def test_should_restart_filter_aborts_immediately(tmp_path):
    sup = TrainingSupervisor(
        AlwaysCrashModel(), str(tmp_path / "ck"), max_restarts=5,
        should_restart=lambda e: not isinstance(e, RuntimeError))
    with pytest.raises(SupervisorAborted):
        sup.fit(rdd=None, epochs=1)
    assert sup.restarts == 0                         # never retried


def test_restart_backoff_uses_policy(tmp_path):
    slept = []
    sup = TrainingSupervisor(
        FakeHostModel(crash_on_call=1), str(tmp_path / "ck"),
        max_restarts=1,
        restart_policy=RetryPolicy(base_delay_s=0.25, jitter=0.0,
                                   sleep=slept.append))
    sup.fit(rdd=None, epochs=1)
    assert slept == [0.25]


def test_partial_checkpoint_is_not_resumed(tmp_path):
    # A torn checkpoint (weights.npz missing) must read as "no checkpoint":
    # the supervisor starts fresh instead of dying in load_checkpoint.
    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / "meta.json").write_text(json.dumps({"epoch": 99}))
    model = FakeHostModel()
    sup = TrainingSupervisor(model, str(ck), checkpoint_frequency=1)
    sup.fit(rdd=None, epochs=2)
    assert _events(sup)[0] == "start"                # not "resume"
    assert model.master_network._w[0][0] == 2.0


def test_events_reach_callback(tmp_path):
    seen = []
    sup = TrainingSupervisor(FakeHostModel(crash_on_call=2),
                             str(tmp_path / "ck"), checkpoint_frequency=1,
                             max_restarts=1, on_event=seen.append)
    sup.fit(rdd=None, epochs=2)
    assert [e.kind for e in seen] == ["start", "crash", "resume", "complete"]
    assert "injected fit crash" in [e for e in seen if e.kind == "crash"][0].detail
