"""The randomized cross-stack chaos soak (``elephas_tpu.resilience.soak``).

The smoke test keeps two seeded schedules in tier-1 so the soak harness
itself can never rot; the full ≥20-schedule acceptance run is marked
``slow`` and rides the ``soak`` marker group (``make test-soak``).
"""

import numpy as np
import pytest

from elephas_tpu.resilience.faults import FaultPlan
from elephas_tpu.resilience.soak import (
    SCENARIOS,
    SoakInvariantViolation,
    _wire_ledger_check,
    draw_fault_kwargs,
    run_schedule,
    run_soak,
)

pytestmark = pytest.mark.soak

_WIRE_DESTRUCTIVE = ("wire_flip_bits", "wire_garbage", "wire_truncate")


def _fired_destructive(run):
    return sum(count for site, count in run.get("fired", {}).items()
               if site.split(":", 1)[0] in _WIRE_DESTRUCTIVE)


def test_draw_fault_kwargs_is_pinned_and_bounded():
    a = draw_fault_kwargs(3, "asynchronous")
    b = draw_fault_kwargs(3, "asynchronous")
    assert a == b                       # the schedule itself is seeded
    for name, value in a.items():
        if name.startswith(("drop", "dup", "push", "pull", "wire")):
            assert 0.0 <= float(value) <= 0.2, (name, value)
    # and it actually varies across seeds (one differing draw suffices)
    assert any(draw_fault_kwargs(s, "asynchronous") != a for s in range(4, 9))


def test_wire_ledger_check_catches_silent_application():
    """The soak's core claim: destructive wire fires with ZERO typed
    catches means corruption may have been applied silently — that must
    be an invariant violation, never a quiet pass."""
    plan = FaultPlan(seed=0, wire_garbage=0.5)
    plan.fired["wire_garbage:client"] = 3      # fired ...
    with pytest.raises(SoakInvariantViolation, match="silently applied"):
        _wire_ledger_check(plan)               # ... but nothing caught
    plan.wire_caught["server:CorruptFrameError"] = 1
    _wire_ledger_check(plan)                   # any typed catch clears it


def test_run_schedule_reports_typed_failures_and_raises_the_rest(monkeypatch):
    def dies_typed(seed):
        raise ConnectionError("server never came back")

    def dies_untyped(seed):
        raise ValueError("this is a real bug")

    monkeypatch.setitem(SCENARIOS, "dies-typed", dies_typed)
    monkeypatch.setitem(SCENARIOS, "dies-untyped", dies_untyped)

    report = run_schedule("dies-typed", 0)
    assert report["outcome"] == "typed:ConnectionError"
    assert "never came back" in report["error"]

    with pytest.raises(ValueError, match="real bug"):
        run_schedule("dies-untyped", 0)

    # run_soak collects instead of dying, so one red seed hides nothing
    soak = run_soak(n_schedules=2, scenarios=["dies-untyped", "dies-typed"])
    assert soak["typed_failures"] == 1
    assert len(soak["failures"]) == 1
    assert "ValueError" in soak["failures"][0]["error"]


@pytest.mark.timeout(300)
def test_soak_smoke_two_schedules():
    """Tier-1 canary: two full stream-stack schedules through the real
    harness (each runs its stack twice for the replay bit-identity
    check)."""
    report = run_soak(n_schedules=2, scenarios=["fit-stream"])
    assert report["failures"] == []
    assert report["completed"] + report["typed_failures"] == 2


@pytest.mark.slow
@pytest.mark.timeout(1800)
def test_soak_twenty_five_schedules_across_all_stacks():
    """The acceptance run: ≥20 seeded schedules round-robined over every
    stack. Every schedule either completes with invariants green or dies
    with a named typed error; destructive wire faults must actually have
    fired somewhere (the storm is real, not a no-op)."""
    report = run_soak(n_schedules=25, verbose=True)
    assert report["failures"] == [], report["failures"]
    assert report["completed"] + report["typed_failures"] == 25
    assert report["completed"] >= 15     # the rate band keeps most green
    assert any(_fired_destructive(r) > 0 for r in report["runs"])
