"""Pinned elastic-quorum scenarios (ISSUE acceptance criteria, scenario b):
with 2 of 8 workers crashed by a seeded FaultPlan and quorum K=6, synchronous
training must complete on the surviving subset with a finite, decreasing
loss. Plus the fused-program analog: an expired member is masked out of the
merge via ``worker_valid`` without recompiling the executable."""

import numpy as np
import pytest

from elephas_tpu import SparkModel
from elephas_tpu.resilience import (
    FaultPlan, HeartbeatRegistry, QuorumLostError,
)
from elephas_tpu.utils import to_simple_rdd

from ..conftest import make_classifier

pytestmark = pytest.mark.resilience


@pytest.fixture(scope="module")
def quorum_data():
    rng = np.random.default_rng(11)
    n, d, c = 400, 10, 3            # 8 partitions x 50 samples (> batch 16)
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d, c))
    y = np.eye(c, dtype="float32")[(x @ w).argmax(axis=1)]
    return x, y


@pytest.mark.chaos
def test_sync_quorum_commits_despite_two_dead_workers(spark_context,
                                                      quorum_data):
    """Scenario b pinned: partitions 2 and 5 crash on EVERY attempt (node
    death, not a transient) — with quorum 6-of-8 the round must commit on
    the received deltas, and training must still reduce the loss."""
    x, y = quorum_data
    model = make_classifier(hidden=8, optimizer="sgd")
    loss_before = float(model.evaluate(x, y, verbose=0)[0])

    plan = FaultPlan(seed=3, dead_partitions=[2, 5])
    registry = HeartbeatRegistry(lease_s=120.0, clock=lambda: 0.0)
    sm = SparkModel(model, mode="synchronous", num_workers=8, comm="host",
                    fault_plan=plan, membership=registry, quorum=6)
    sm.fit(to_simple_rdd(spark_context, x, y), epochs=1, batch_size=16,
           verbose=0, validation_split=0.0, shuffle=False)

    assert any(k.startswith("dead-partition-") for k in plan.fired), \
        "the injected node deaths never fired"
    final = model.get_weights()
    for w in final:
        assert np.all(np.isfinite(np.asarray(w)))
    loss_after = float(model.evaluate(x, y, verbose=0)[0])
    assert loss_after < loss_before

    snap = sm.membership_snapshot()
    round_ = snap["rounds"][-1]
    assert round_["expected"] == 8
    assert round_["received"] == 6
    assert round_["quorum"] == 6
    # the dead members were expired and fenced
    assert "partition-2" not in snap["membership"]["live"]
    assert "partition-5" not in snap["membership"]["live"]
    assert snap["membership"]["fences"]["partition-2"] > 0


@pytest.mark.chaos
def test_sync_quorum_lost_raises(spark_context, quorum_data):
    """With quorum == N, a permanently dead partition makes the round
    impossible: the fit must fail loudly, not hang or silently commit."""
    x, y = quorum_data
    model = make_classifier(hidden=4, optimizer="sgd")
    sm = SparkModel(model, mode="synchronous", num_workers=4, comm="host",
                    fault_plan=FaultPlan(seed=0, dead_partitions=[1]),
                    membership=HeartbeatRegistry(lease_s=120.0,
                                                 clock=lambda: 0.0),
                    quorum=4)
    with pytest.raises(QuorumLostError):
        sm.fit(to_simple_rdd(spark_context, x[:200], y[:200]), epochs=1,
               batch_size=16, verbose=0, validation_split=0.0, shuffle=False)


def test_jax_membership_mask_excludes_expired_worker(spark_context,
                                                     quorum_data):
    """Fused-program path: a member the registry saw die is masked out of
    every merge denominator (engine ``worker_valid``), geometry unchanged."""
    x, y = quorum_data
    # frozen clock: lease expiry can NEVER fire from wall time, so the only
    # expired member is the one the test expires explicitly — this pins the
    # mask deterministically on loaded/slow CI hosts (the historical flake:
    # a straggling executor's heartbeat aged past the lease mid-fit and the
    # mask grew a second zero)
    registry = HeartbeatRegistry(lease_s=120.0, clock=lambda: 0.0)
    model = make_classifier(hidden=8, optimizer="sgd")
    loss_before = float(model.evaluate(x, y, verbose=0)[0])
    sm = SparkModel(model, mode="synchronous", num_workers=4, comm="jax",
                    membership=registry, quorum=2)

    # all members unknown-or-live: the mask collapses to None so the common
    # case stays on the cached no-mask executable
    assert sm._membership_mask(4) is None
    registry.join("partition-3")
    registry.expire("partition-3")
    assert sm._membership_mask(4) == [1.0, 1.0, 1.0, 0.0]

    sm.fit(to_simple_rdd(spark_context, x[:200], y[:200]), epochs=2,
           batch_size=16, verbose=0, validation_split=0.0, shuffle=False)
    for w in model.get_weights():
        assert np.all(np.isfinite(np.asarray(w)))
    loss_after = float(model.evaluate(x[:200], y[:200], verbose=0)[0])
    assert loss_after < loss_before


def test_jax_membership_mask_quorum_lost():
    registry = HeartbeatRegistry(lease_s=120.0, clock=lambda: 0.0)
    model = make_classifier(hidden=4, optimizer="sgd")
    sm = SparkModel(model, mode="synchronous", num_workers=4, comm="jax",
                    membership=registry, quorum=3)
    for pid in (1, 2):
        registry.join(f"partition-{pid}")
        registry.expire(f"partition-{pid}")
    with pytest.raises(QuorumLostError):
        sm._membership_mask(4)
