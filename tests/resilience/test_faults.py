"""FaultPlan: every decision must be a deterministic function of
(seed, site, opportunity index) — same plan seed, same faults, any thread
interleaving — and every crash site must fire at most once."""

import pytest

from elephas_tpu.resilience import (
    FaultPlan,
    FaultyClient,
    InjectedFault,
    InjectedWorkerCrash,
    TransientFault,
)

pytestmark = pytest.mark.resilience


class FakeCtx:
    """Stand-in for elephas_tpu.data.TaskContext."""

    def __init__(self, partition=0, attempt=0, stage=1):
        self._p, self._a, self._s = partition, attempt, stage

    def partitionId(self):
        return self._p

    def attemptNumber(self):
        return self._a

    def stageId(self):
        return self._s


class RecordingClient:
    """Inner parameter client that just records traffic."""

    def __init__(self):
        self.pulls = 0
        self.pushes = []
        self.closed = False

    def get_parameters(self):
        self.pulls += 1
        return ["weights"]

    def update_parameters(self, delta):
        self.pushes.append(("plain", delta))

    def update_parameters_tagged(self, task_id, delta):
        self.pushes.append((task_id, delta))

    def register_attempt(self, task_id, attempt):
        return True

    def commit_attempt(self, task_id):
        pass

    def close(self):
        self.closed = True


def test_same_seed_same_decisions():
    a = FaultPlan(seed=7, drop_push=0.3, dup_push=0.1)
    b = FaultPlan(seed=7, drop_push=0.3, dup_push=0.1)
    assert [a.push_fault() for _ in range(64)] == \
        [b.push_fault() for _ in range(64)]


def test_different_seeds_differ():
    a = FaultPlan(seed=0, drop_push=0.5)
    b = FaultPlan(seed=1, drop_push=0.5)
    assert [a.push_fault() for _ in range(64)] != \
        [b.push_fault() for _ in range(64)]


def test_sites_are_independent_streams():
    """Traffic at one site must not shift another site's decisions —
    that's what makes concurrent-worker chaos runs reproducible."""
    quiet = FaultPlan(seed=3, drop_push=0.4)
    quiet_seq = [quiet.decide("drop_push", 0.4) for _ in range(32)]

    noisy = FaultPlan(seed=3, drop_push=0.4)
    for _ in range(100):
        noisy.decide("other_site", 0.5)     # unrelated traffic first
    assert quiet_seq == [noisy.decide("drop_push", 0.4) for _ in range(32)]


def test_rate_bounds():
    plan = FaultPlan(seed=5)
    assert not any(plan.decide("never", 0.0) for _ in range(50))
    assert all(plan.decide("always", 1.0) for _ in range(50))


def test_drop_rate_roughly_honored():
    plan = FaultPlan(seed=11, drop_push=0.2)
    drops = sum(plan.push_fault() == "drop" for _ in range(500))
    assert 60 <= drops <= 140                # 0.2 ± generous slack


def test_faulty_client_drop_and_dup():
    drop_all = FaultyClient(RecordingClient(), FaultPlan(seed=0, drop_push=1.0))
    drop_all.update_parameters([1.0])
    assert drop_all.inner.pushes == []       # lost in flight, no error

    dup_all = FaultyClient(RecordingClient(), FaultPlan(seed=0, dup_push=1.0))
    dup_all.update_parameters_tagged("t", [1.0])
    assert dup_all.inner.pushes == [("t", [1.0]), ("t", [1.0])]


def test_faulty_client_transient_errors():
    plan = FaultPlan(seed=0, push_error_rate=1.0, pull_error_rate=1.0)
    client = FaultyClient(RecordingClient(), plan)
    with pytest.raises(TransientFault):
        client.update_parameters([1.0])
    with pytest.raises(TransientFault):
        client.get_parameters()
    assert client.inner.pushes == [] and client.inner.pulls == 0
    # a TransientFault must look like a real network error to handlers
    assert issubclass(TransientFault, ConnectionError)
    assert issubclass(TransientFault, InjectedFault)


def test_pull_delay_uses_injected_sleep():
    slept = []
    plan = FaultPlan(seed=0, pull_delay_s=2.5, pull_delay_prob=1.0,
                     sleep=slept.append)
    client = FaultyClient(RecordingClient(), plan)
    client.get_parameters()
    assert slept == [2.5]
    assert client.inner.pulls == 1


def test_crash_after_pushes_fires_once_attempt0_only():
    plan = FaultPlan(seed=0, crash_partition=1, crash_after_pushes=2)
    client = FaultyClient(RecordingClient(), plan)
    ctx = FakeCtx(partition=1, attempt=0)
    client._task_ctx = lambda: ctx           # bypass thread-local lookup
    client.update_parameters([1])
    client.update_parameters([2])
    with pytest.raises(InjectedWorkerCrash):
        client.update_parameters([3])
    assert len(client.inner.pushes) == 2     # the third never went out
    # the retry (attempt 1) sails through — fault fired once
    client._task_ctx = lambda: FakeCtx(partition=1, attempt=1)
    for i in range(5):
        client.update_parameters([i])
    assert len(client.inner.pushes) == 7


def test_crash_ignores_other_partitions():
    plan = FaultPlan(seed=0, crash_partition=1, crash_after_pushes=0)
    client = FaultyClient(RecordingClient(), plan)
    client._task_ctx = lambda: FakeCtx(partition=0, attempt=0)
    for i in range(5):
        client.update_parameters([i])
    assert len(client.inner.pushes) == 5


def test_maybe_crash_partition_once():
    plan = FaultPlan(seed=0, crash_partition=2)
    with pytest.raises(InjectedWorkerCrash):
        plan.maybe_crash_partition(FakeCtx(partition=2, attempt=0))
    # retry attempt AND a hypothetical second attempt-0 call both survive
    plan.maybe_crash_partition(FakeCtx(partition=2, attempt=1))
    plan.maybe_crash_partition(FakeCtx(partition=2, attempt=0))
    plan.maybe_crash_partition(None)         # driver-side: no ctx, no crash


def test_tick_fires_at_exact_index_once():
    plan = FaultPlan(seed=0, crash_sites={"fit_chunk": 2})
    plan.tick("fit_chunk")
    plan.tick("fit_chunk")
    with pytest.raises(InjectedWorkerCrash):
        plan.tick("fit_chunk")               # 0-based call index 2
    plan.tick("fit_chunk")                   # fired once; restarts proceed
    plan.tick("other_site")                  # unconfigured sites never fire
    assert plan.fired == {"fit_chunk": 2}


def test_server_hooks_and_serving_stalls():
    slept = []
    plan = FaultPlan(seed=0, server_drop_push=1.0, server_pull_delay_s=0.5,
                     serving_stalls={3: 40.0}, sleep=slept.append)
    assert plan.drop_server_push()
    plan.delay_server_pull()
    assert slept == [0.5]
    assert plan.serving_stall(3) == 40.0
    assert plan.serving_stall(2) == 0.0


def test_faulty_client_delegates_lifecycle():
    client = FaultyClient(RecordingClient(), FaultPlan(seed=0))
    assert client.register_attempt("t", 0)
    client.commit_attempt("t")
    client.close()
    assert client.inner.closed
