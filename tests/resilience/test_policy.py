"""RetryPolicy / CircuitBreaker / ResilientClient: schedules are
deterministic and injectable (no real sleeping in any of these tests),
transient-vs-fatal classification is exact, and the breaker's state
machine walks closed → open → half-open → closed."""

import urllib.error

import pytest

from elephas_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    ResilientClient,
    RetryExhausted,
    RetryPolicy,
    TransientFault,
    default_is_transient,
)

pytestmark = pytest.mark.resilience


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class FlakyClient:
    """Inner client whose pull fails ``fail_pulls`` times, then succeeds."""

    def __init__(self, fail_pulls=0, fail_pushes=0):
        self.fail_pulls = fail_pulls
        self.fail_pushes = fail_pushes
        self.pulls = 0
        self.pushes = 0

    def get_parameters(self):
        self.pulls += 1
        if self.pulls <= self.fail_pulls:
            raise ConnectionResetError("flaky pull")
        return ["weights"]

    def update_parameters(self, delta):
        self.pushes += 1
        if self.pushes <= self.fail_pushes:
            raise ConnectionResetError("flaky push")

    def update_parameters_tagged(self, task_id, delta):
        self.update_parameters(delta)

    def register_attempt(self, task_id, attempt):
        return True

    def commit_attempt(self, task_id):
        pass

    def close(self):
        pass


def _policy(**kw):
    kw.setdefault("sleep", lambda s: None)
    return RetryPolicy(**kw)


def test_transient_classification():
    assert default_is_transient(ConnectionResetError())
    assert default_is_transient(TimeoutError())
    assert default_is_transient(urllib.error.URLError("down"))
    assert default_is_transient(OSError("pipe"))
    assert default_is_transient(TransientFault("injected"))
    assert default_is_transient(CircuitOpenError("open"))
    assert not default_is_transient(ValueError("bug"))
    assert not default_is_transient(RuntimeError("crash"))


def test_retry_recovers_after_transients():
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("not yet")
        return "ok"

    assert _policy(max_attempts=5).call(fn) == "ok"
    assert len(calls) == 3


def test_retry_exhausted_keeps_cause():
    policy = _policy(max_attempts=3)
    with pytest.raises(RetryExhausted) as exc:
        policy.call(lambda: (_ for _ in ()).throw(TimeoutError("slow")))
    assert isinstance(exc.value.__cause__, TimeoutError)


def test_non_transient_propagates_immediately():
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("a real bug")

    with pytest.raises(ValueError):
        _policy(max_attempts=5).call(fn)
    assert len(calls) == 1                   # no retry on program errors


def test_backoff_schedule_deterministic_and_capped():
    a = RetryPolicy(seed=9, base_delay_s=0.1, multiplier=2.0,
                    max_delay_s=0.5, jitter=0.5)
    b = RetryPolicy(seed=9, base_delay_s=0.1, multiplier=2.0,
                    max_delay_s=0.5, jitter=0.5)
    delays = [a.delay(i) for i in range(8)]
    assert delays == [b.delay(i) for i in range(8)]   # reproducible
    assert all(0.0 < d <= 0.5 for d in delays)        # capped, jitter < 100%
    assert RetryPolicy(seed=9, jitter=0.0).delay(1) == 0.1  # pure exponential
    assert RetryPolicy(seed=1).delay(0) != RetryPolicy(seed=2).delay(0)


def test_retry_sleeps_the_scheduled_delays():
    slept = []
    policy = RetryPolicy(max_attempts=3, jitter=0.0, base_delay_s=0.05,
                         sleep=slept.append)
    with pytest.raises(RetryExhausted):
        policy.call(lambda: (_ for _ in ()).throw(ConnectionError()))
    assert slept == [policy.delay(0), policy.delay(1)]  # no sleep after last


def test_deadline_cuts_retries_short():
    clock = FakeClock()

    def fn():
        clock.t += 10.0                      # each attempt burns 10s
        raise ConnectionError("down")

    policy = _policy(max_attempts=100, deadline_s=25.0, clock=clock,
                     base_delay_s=0.0, jitter=0.0)
    with pytest.raises(RetryExhausted) as exc:
        policy.call(fn, describe="pull")
    assert "deadline" in str(exc.value)
    assert clock.t <= 40.0                   # gave up instead of spinning


def test_breaker_opens_after_threshold_and_fails_fast():
    clock = FakeClock()
    cb = CircuitBreaker(failure_threshold=3, reset_timeout_s=5.0,
                        clock=clock)
    for _ in range(3):
        with pytest.raises(ConnectionError):
            cb.call(lambda: (_ for _ in ()).throw(ConnectionError()))
    assert cb.state == CircuitBreaker.OPEN
    calls = []
    with pytest.raises(CircuitOpenError):
        cb.call(lambda: calls.append(1))     # rejected without calling
    assert calls == []


def test_breaker_half_open_probe_closes_or_reopens():
    clock = FakeClock()
    cb = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                        clock=clock)
    with pytest.raises(ConnectionError):
        cb.call(lambda: (_ for _ in ()).throw(ConnectionError()))
    assert cb.state == CircuitBreaker.OPEN
    clock.t = 6.0
    assert cb.state == CircuitBreaker.HALF_OPEN
    # failed probe → straight back to open
    with pytest.raises(ConnectionError):
        cb.call(lambda: (_ for _ in ()).throw(ConnectionError()))
    assert cb.state == CircuitBreaker.OPEN
    clock.t = 12.0
    assert cb.call(lambda: "ok") == "ok"     # good probe closes it
    assert cb.state == CircuitBreaker.CLOSED


def test_breaker_half_open_admits_single_probe():
    clock = FakeClock()
    cb = CircuitBreaker(failure_threshold=1, reset_timeout_s=1.0,
                        clock=clock)
    cb.record_failure()
    clock.t = 2.0
    assert cb.allow()                        # the probe slot
    assert not cb.allow()                    # concurrent caller fails fast
    cb.record_success()
    assert cb.allow()


def test_breaker_half_open_concurrent_arbitration():
    """N threads hit a just-half-opened breaker simultaneously: exactly ONE
    must win the probe slot — a thundering herd of probes against a barely
    recovered server is what half-open exists to prevent. Repeated across
    rounds (with the probe failing in between) to shake out lost-update
    races on the ``_probing`` flag."""
    import threading

    clock = FakeClock()
    cb = CircuitBreaker(failure_threshold=1, reset_timeout_s=1.0,
                        clock=clock)
    n_threads = 16
    for round_ in range(5):
        cb.record_failure()                  # (re)open the breaker
        clock.t += 2.0                       # past the reset window
        barrier = threading.Barrier(n_threads)
        admitted = []
        lock = threading.Lock()

        def contend():
            barrier.wait()
            ok = cb.allow()
            with lock:
                admitted.append(ok)

        threads = [threading.Thread(target=contend)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert admitted.count(True) == 1, \
            f"round {round_}: {admitted.count(True)} probes admitted"
        cb.record_failure()                  # the probe failed: back to open
        assert cb.state == CircuitBreaker.OPEN


def test_resilient_client_rides_through_flaky_wire():
    inner = FlakyClient(fail_pulls=2, fail_pushes=1)
    client = ResilientClient(inner, policy=_policy(max_attempts=5))
    assert client.get_parameters() == ["weights"]
    client.update_parameters([1.0])
    assert inner.pulls == 3 and inner.pushes == 2


def test_resilient_client_breaker_outage_and_recovery():
    """A dead server trips the breaker (fail-fast), and the retry policy
    backs off across the reset window to the half-open probe — the worker
    resumes without ever seeing the outage."""
    clock = FakeClock()
    inner = FlakyClient(fail_pulls=2)
    breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=5.0,
                             clock=clock)

    def sleep(s):
        clock.t += max(s, 6.0)               # each backoff outlives the reset

    client = ResilientClient(
        inner,
        policy=RetryPolicy(max_attempts=6, sleep=sleep, clock=clock),
        breaker=breaker)
    assert client.get_parameters() == ["weights"]
    assert breaker.state == CircuitBreaker.CLOSED


def test_resilient_client_gives_up_cleanly():
    inner = FlakyClient(fail_pulls=100)
    client = ResilientClient(inner, policy=_policy(max_attempts=3))
    with pytest.raises(RetryExhausted):
        client.get_parameters()
    assert inner.pulls == 3
