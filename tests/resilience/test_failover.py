"""Pinned failover scenarios (ISSUE acceptance criteria, scenarios a + c):

- hot-standby parameter server: the primary is killed mid-epoch by a seeded
  FaultPlan; training must complete against the standby, no committed update
  may be lost (standby version >= primary version after replication drains),
  and the weight version counter stays monotone across the failover;
- injected straggler: the backup clone wins, and the server applies exactly
  the winner's deltas for that task id — the zombie's late pushes are fenced.

Plus deterministic server-level tests of the attempt fence and the version
counter (no threads, no timing)."""

import threading

import numpy as np
import pytest

from elephas_tpu import SparkModel
from elephas_tpu.parameter.client import HttpClient
from elephas_tpu.parameter.server import HttpServer
from elephas_tpu.resilience import FaultPlan, HeartbeatRegistry, RetryPolicy
from elephas_tpu.utils import to_simple_rdd

from ..conftest import make_classifier

pytestmark = pytest.mark.resilience


@pytest.fixture(scope="module")
def failover_data():
    rng = np.random.default_rng(23)
    n, d, c = 200, 10, 3            # 4 partitions x 50 samples (> batch 16)
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d, c))
    y = np.eye(c, dtype="float32")[(x @ w).argmax(axis=1)]
    return x, y


@pytest.mark.chaos
def test_training_survives_primary_ps_kill(spark_context, failover_data):
    """Scenario a pinned: the primary dies at its 13th request (mid-epoch,
    after real updates have been applied). Clients must transparently
    re-target the standby, training must complete with a lower loss, and
    the standby must hold every update the primary committed."""
    x, y = failover_data
    model = make_classifier(hidden=8, optimizer="sgd")
    loss_before = float(model.evaluate(x, y, verbose=0)[0])

    plan = FaultPlan(seed=5, crash_sites={"kill-primary": 12})
    registry = HeartbeatRegistry(lease_s=120.0)
    sm = SparkModel(
        model, mode="asynchronous", num_workers=4, comm="host",
        parameter_server_mode="http", port=0, fault_plan=plan,
        membership=registry, hot_standby=True,
        retry_policy=RetryPolicy(max_attempts=4, base_delay_s=0.01,
                                 max_delay_s=0.05),
    )
    sm.fit(to_simple_rdd(spark_context, x, y), epochs=2, batch_size=16,
           verbose=0, validation_split=0.0, shuffle=False)

    assert "kill-primary" in plan.fired, "the injected PS kill never fired"
    snap = sm.membership_snapshot()
    assert snap["counters"]["failovers"] >= 1
    ps = snap["parameter_servers"]
    # updates were committed on the primary BEFORE it died, and none were
    # lost: after replication drains the standby has them all, plus the
    # post-failover ones — the version counter is monotone across servers
    assert ps["primary"]["version"] > 0
    assert ps["standby"]["version"] >= ps["primary"]["version"]
    assert ps["primary"]["replication_errors"] == 0
    # total applied pushes (4 workers x 2 epochs) all landed somewhere
    assert ps["standby"]["version"] == 8

    final = model.get_weights()
    for w in final:
        assert np.all(np.isfinite(np.asarray(w)))
    loss_after = float(model.evaluate(x, y, verbose=0)[0])
    assert loss_after < loss_before


@pytest.mark.chaos
def test_straggler_backup_wins_and_server_applies_winner_only(
        spark_context, failover_data):
    """Scenario c pinned: partition 1 stalls 9s before registering; the
    registry flags the silence after 3s and a backup clone (attempt 1)
    races ahead. The server must end up with exactly the WINNER's pushes
    for that task id — one per batch — no matter when the zombie wakes."""
    x, y = failover_data
    model = make_classifier(hidden=8, optimizer="sgd")
    loss_before = float(model.evaluate(x, y, verbose=0)[0])

    release = threading.Event()
    plan = FaultPlan(seed=7, straggler_stalls={1: 9.0},
                     sleep=lambda s: release.wait(s))
    registry = HeartbeatRegistry(lease_s=120.0, straggler_after_s=3.0)
    sm = SparkModel(
        model, mode="asynchronous", frequency="batch", num_workers=4,
        comm="host", parameter_server_mode="http", port=0, fault_plan=plan,
        membership=registry,
        retry_policy=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                                 max_delay_s=0.02),
    )
    try:
        sm.fit(to_simple_rdd(spark_context, x, y), epochs=1, batch_size=16,
               verbose=0, validation_split=0.0, shuffle=False)
    finally:
        release.set()               # wake the zombie; the server is gone

    assert "straggle-partition-1" in plan.fired
    snap = sm.membership_snapshot()
    assert snap["counters"].get("backup", 0) >= 1
    assert any(e["kind"] == "backup" and e["member"] == "partition-1"
               for e in snap["events"])
    # exactly-once for the straggler's task: 50 samples / 16 per batch = 3
    # batches, so exactly 3 applied deltas — the backup's, not 6 (backup +
    # zombie) and not 0
    applied = snap["parameter_servers"]["primary"]["applied_tagged"]
    straggler_tasks = {k: v for k, v in applied.items()
                       if k.endswith("partition-1")}
    assert list(straggler_tasks.values()) == [3]

    for w in model.get_weights():
        assert np.all(np.isfinite(np.asarray(w)))
    loss_after = float(model.evaluate(x, y, verbose=0)[0])
    assert loss_after < loss_before


# -- deterministic server-level fence / version tests ------------------------


def _weights():
    return [np.zeros((3,), np.float32)]


def _delta(v=1.0):
    return [np.full((3,), v, np.float32)]


def test_attempt_fence_rejects_zombie_pushes_even_after_commit():
    """The fence outlives the accumulator: a zombie that wakes up AFTER the
    winner committed (record popped) must still be refused."""
    server = HttpServer(_weights(), mode="asynchronous", port=0)
    server.start()
    try:
        client = HttpClient(port=server.port)
        assert client.register_attempt("task", 1)   # the backup registers
        client.update_parameters_tagged("task", _delta(), attempt=1)
        client.commit_attempt("task")
        applied = np.array(server.weights[0])

        # zombie attempt 0: stale register is ignored, pushes are fenced
        client.register_attempt("task", 0)
        client.update_parameters_tagged("task", _delta(5.0), attempt=0)
        np.testing.assert_array_equal(server.weights[0], applied)
        assert server.rejected_stale == 1
        assert server.applied_tagged["task"] == 1
        client.close()
    finally:
        server.stop()


def test_version_counter_is_monotone_and_exposed_to_clients():
    server = HttpServer(_weights(), mode="asynchronous", port=0)
    server.start()
    try:
        client = HttpClient(port=server.port)
        assert client.get_version() == 0
        client.update_parameters(_delta())
        assert client.get_version() == 1
        client.update_parameters(_delta())
        assert client.get_version() == 2
        # pulls report the version too (header), for staleness bounding
        client.get_parameters()
        assert client.last_seen_version == 2
        client.close()
    finally:
        server.stop()


def test_replication_streams_every_committed_update_to_standby():
    primary = HttpServer(_weights(), mode="asynchronous", port=0,
                         name="primary")
    standby = HttpServer(_weights(), mode="asynchronous", port=0,
                         name="standby")
    primary.start()
    standby.start()
    primary.attach_standby(standby)
    try:
        client = HttpClient(port=primary.port)
        client.register_attempt("t", 1)
        for _ in range(3):
            client.update_parameters_tagged("t", _delta(), attempt=1)
        client.commit_attempt("t")
        client.close()
        primary.flush_replication()
        assert standby.version == primary.version == 3
        np.testing.assert_array_equal(standby.weights[0], primary.weights[0])
        # the attempt table replicated too: a zombie fenced on the primary
        # is equally fenced on the standby after failover
        assert standby._fence.get("t") == primary._fence.get("t") == 1
        assert "t" not in standby._attempts
    finally:
        primary.stop()
        standby.stop()
