"""The pinned chaos scenarios (ISSUE acceptance criteria): with a seeded
FaultPlan killing one worker mid-partition and dropping 20% of pushes —

- synchronous training: BIT-IDENTICAL final weights after the task retry;
- asynchronous / hogwild: still converges within tolerance;
- serving: a request exceeding its deadline frees its slot while the
  remaining greedy streams stay token-identical to the unfaulted run.

All fault decisions are functions of the plan seed, so these are pinned
regressions, not flaky probabilistic checks."""

import numpy as np
import pytest

from elephas_tpu import SparkModel
from elephas_tpu.resilience import FaultPlan, RetryPolicy
from elephas_tpu.utils import to_simple_rdd

from ..conftest import make_classifier

pytestmark = pytest.mark.resilience


@pytest.fixture(scope="module")
def chaos_data():
    rng = np.random.default_rng(42)
    n, d, c = 200, 10, 3
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d, c))
    y = np.eye(c, dtype="float32")[(x @ w).argmax(axis=1)]
    return x, y


@pytest.fixture(scope="module")
def init_weights():
    return make_classifier(hidden=8, optimizer="sgd").get_weights()


def _sync_fit_weights(init_weights, x, y, sc, fault_plan=None):
    """One deterministic host-path synchronous fit → final weights.
    shuffle=False + validation_split=0 makes each worker's Keras fit a
    pure function of (weights, partition data), so runs are comparable
    bit-for-bit."""
    model = make_classifier(hidden=8, optimizer="sgd")
    model.set_weights(init_weights)
    sm = SparkModel(model, mode="synchronous", num_workers=4, comm="host",
                    fault_plan=fault_plan)
    sm.fit(to_simple_rdd(sc, x, y), epochs=1, batch_size=16, verbose=0,
           validation_split=0.0, shuffle=False)
    return model.get_weights()


def test_sync_bit_identical_after_worker_crash(spark_context, chaos_data,
                                               init_weights):
    """Kill worker partition 1 mid-partition (after its local fit, before
    its delta is returned): the facade's Spark-parity task retry must
    recompute the SAME delta, and the merged result must equal the
    unfaulted run exactly — not approximately."""
    x, y = chaos_data
    clean = _sync_fit_weights(init_weights, x, y, spark_context)

    plan = FaultPlan(seed=0, crash_partition=1)
    faulted = _sync_fit_weights(init_weights, x, y, spark_context,
                                fault_plan=plan)
    assert plan.fired, "the injected crash never fired"
    for w_clean, w_faulted in zip(clean, faulted):
        np.testing.assert_array_equal(np.asarray(w_clean),
                                      np.asarray(w_faulted))


@pytest.mark.parametrize("mode", ["asynchronous", "hogwild"])
def test_async_converges_under_chaos(spark_context, chaos_data,
                                     init_weights, mode):
    """The full storm on the live parameter server: 20% of pushes dropped
    in flight, one worker killed mid-partition after its first push
    (exercising the server's attempt rollback on retry), transient wire
    errors absorbed by the retry policy. Training must still move the
    weights toward lower loss and keep them sane."""
    x, y = chaos_data
    model = make_classifier(hidden=8, optimizer="sgd")
    model.set_weights(init_weights)
    loss_before = float(model.evaluate(x, y, verbose=0)[0])

    plan = FaultPlan(seed=2, drop_push=0.2, push_error_rate=0.1,
                     crash_partition=1, crash_after_pushes=1)
    sm = SparkModel(
        model, mode=mode, num_workers=4, comm="host",
        parameter_server_mode="http", port=0, fault_plan=plan,
        retry_policy=RetryPolicy(max_attempts=4, base_delay_s=0.01,
                                 max_delay_s=0.05))
    sm.fit(to_simple_rdd(spark_context, x, y), epochs=2,
           batch_size=16, verbose=0, validation_split=0.0, shuffle=False)

    final = model.get_weights()
    assert any(k.startswith("crash-partition") for k in plan.fired), \
        "the injected worker crash never fired"
    for w in final:
        w = np.asarray(w)
        assert np.all(np.isfinite(w))
        assert np.abs(w).max() < 1e3          # no runaway double-applies
    loss_after = float(model.evaluate(x, y, verbose=0)[0])
    assert loss_after < loss_before           # converged despite the chaos


def test_serving_deadline_frees_slot_streams_unperturbed():
    """One request exceeds its deadline under an injected stall: it must
    be reaped with its slot reclaimed (the queued request takes the slot
    over), and every OTHER greedy stream must be token-identical to the
    unfaulted engine's output."""
    jnp = pytest.importorskip("jax.numpy")
    from elephas_tpu.models.transformer import TransformerLM
    from elephas_tpu.serving import ServingEngine

    V = 17
    model = TransformerLM(vocab=V, d_model=16, n_heads=4, n_layers=2,
                          d_ff=32, max_len=48)
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, V, size=(t,)).astype(np.int32)
               for t in (4, 6, 5)]

    class FakeClock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            self.t += 1.0
            return self.t

    # unfaulted reference run: all three finish by length
    ref = ServingEngine(model, params, n_slots=2, clock=FakeClock())
    ref_ids = [ref.submit(p, 8) for p in prompts]
    ref_fin = ref.drain(max_steps=500)
    assert all(ref_fin[r].finish_reason == "length" for r in ref_ids)

    # faulted run: request 0 carries a deadline, and an injected stall at
    # step 4 ages the engine clock 1000s past it mid-generation
    plan = FaultPlan(seed=0, serving_stalls={4: 1000.0})
    eng = ServingEngine(model, params, n_slots=2, clock=FakeClock(),
                        fault_plan=plan)
    victim = eng.submit(prompts[0], 8, deadline_s=100.0)
    survivor = eng.submit(prompts[1], 8)
    queued = eng.submit(prompts[2], 8)
    fin = eng.drain(max_steps=500)

    dead = fin[victim]
    assert dead.finish_reason == "deadline"
    assert len(dead.tokens) < 8               # cut off mid-generation
    # its slot was reclaimed and reused: the queued request both ran and
    # finished normally
    assert fin[queued].finish_reason == "length"
    # the surviving greedy streams are token-identical to the unfaulted run
    assert fin[survivor].tokens == ref_fin[ref_ids[1]].tokens
    assert fin[queued].tokens == ref_fin[ref_ids[2]].tokens
    assert eng.snapshot()["counters"]["cancelled"] == {"deadline": 1}
    assert eng.kv.active_slots == 0           # nothing leaked
