"""Shared test helpers (one home for the per-sample losses the parallel
trainer tests all use)."""

import jax
import jax.numpy as jnp


def softmax_xent(y, y_pred):
    """Per-sample categorical cross-entropy from one-hot labels."""
    logp = jax.nn.log_softmax(y_pred, axis=-1)
    return -jnp.sum(y * logp, axis=-1)
