"""Socket framing + master discovery (reference: elephas/utils/sockets.py)."""

import os
import socket
import threading

import numpy as np

from elephas_tpu.utils.sockets import determine_master, receive, send


def test_determine_master_env(monkeypatch):
    monkeypatch.setenv("SPARK_LOCAL_IP", "10.1.2.3")
    monkeypatch.delenv("ELEPHAS_MASTER", raising=False)
    assert determine_master(4000) == "10.1.2.3:4000"
    monkeypatch.setenv("ELEPHAS_MASTER", "tpu-host")
    assert determine_master(4001) == "tpu-host:4001"
    monkeypatch.setenv("ELEPHAS_MASTER", "tpu-host:9999")
    assert determine_master(4001) == "tpu-host:9999"


def test_send_receive_round_trip():
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    port = server.getsockname()[1]
    payload = {"weights": [np.arange(5), np.ones((2, 2))], "tag": "x"}
    received = {}

    def serve():
        conn, _ = server.accept()
        received["msg"] = receive(conn)
        send(conn, "ack")
        conn.close()

    t = threading.Thread(target=serve)
    t.start()
    client = socket.create_connection(("127.0.0.1", port))
    send(client, payload)
    assert receive(client) == "ack"
    t.join()
    client.close()
    server.close()
    assert received["msg"]["tag"] == "x"
    assert np.allclose(received["msg"]["weights"][0], np.arange(5))
