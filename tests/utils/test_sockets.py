"""Socket framing + master discovery (reference: elephas/utils/sockets.py).

The v2 checksummed-frame format, the bilingual receive path, and the typed
decode errors (corrupt / oversize / truncated / stalled) are pinned here;
the adversarial end-to-end scenarios live in ``test_wire_fuzz.py``."""

import os
import pickle
import socket
import struct
import threading
import time

import numpy as np
import pytest

from elephas_tpu.utils.sockets import (
    FLAG_OOB,
    HEADER_WIDTH,
    MAGIC,
    OOB_MIN_BYTES,
    V2_HEADER_BYTES,
    WIRE_V1,
    WIRE_V2,
    CorruptFrameError,
    FrameStalledError,
    FrameTooLargeError,
    TruncatedFrameError,
    determine_master,
    frame_checksum,
    receive,
    receive_frame,
    send,
)


def test_determine_master_env(monkeypatch):
    monkeypatch.setenv("SPARK_LOCAL_IP", "10.1.2.3")
    monkeypatch.delenv("ELEPHAS_MASTER", raising=False)
    assert determine_master(4000) == "10.1.2.3:4000"
    monkeypatch.setenv("ELEPHAS_MASTER", "tpu-host")
    assert determine_master(4001) == "tpu-host:4001"
    monkeypatch.setenv("ELEPHAS_MASTER", "tpu-host:9999")
    assert determine_master(4001) == "tpu-host:9999"


def test_send_receive_round_trip():
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    port = server.getsockname()[1]
    payload = {"weights": [np.arange(5), np.ones((2, 2))], "tag": "x"}
    received = {}

    def serve():
        conn, _ = server.accept()
        received["msg"] = receive(conn)
        send(conn, "ack")
        conn.close()

    t = threading.Thread(target=serve)
    t.start()
    client = socket.create_connection(("127.0.0.1", port))
    send(client, payload)
    assert receive(client) == "ack"
    t.join()
    client.close()
    server.close()
    assert received["msg"]["tag"] == "x"
    assert np.allclose(received["msg"]["weights"][0], np.arange(5))


# -- v2 framing ------------------------------------------------------------

def _pair():
    a, b = socket.socketpair()
    return a, b


def _v2_frame(obj, *, flip_payload_bit=None, crc_delta=0, flags=0,
              length_override=None):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    body = bytearray(payload)
    if flip_payload_bit is not None:
        body[flip_payload_bit // 8] ^= 1 << (flip_payload_bit % 8)
    length = len(payload) if length_override is None else length_override
    header = struct.pack(">4sBBQI", MAGIC, WIRE_V2, flags, length,
                         (frame_checksum(payload) + crc_delta) & 0xFFFFFFFF)
    return header + bytes(body)


def test_v2_round_trip_and_dialect_detection():
    a, b = _pair()
    try:
        send(a, {"k": np.arange(3)}, version=WIRE_V2)
        obj, ver = receive_frame(b)
        assert ver == WIRE_V2 and np.allclose(obj["k"], np.arange(3))
        # the SAME receive path accepts a legacy frame next on the wire
        send(a, "old-style", version=WIRE_V1)
        obj, ver = receive_frame(b)
        assert (obj, ver) == ("old-style", WIRE_V1)
    finally:
        a.close()
        b.close()


def test_flipped_payload_bit_is_a_typed_checksum_error():
    a, b = _pair()
    try:
        a.sendall(_v2_frame([1, 2, 3], flip_payload_bit=11))
        with pytest.raises(CorruptFrameError, match="checksum mismatch"):
            receive(b)
    finally:
        a.close()
        b.close()


def test_flipped_crc_is_a_typed_checksum_error():
    a, b = _pair()
    try:
        a.sendall(_v2_frame([1, 2, 3], crc_delta=1))
        with pytest.raises(CorruptFrameError, match="checksum"):
            receive(b)
    finally:
        a.close()
        b.close()


def test_reserved_flags_refused():
    a, b = _pair()
    try:
        a.sendall(_v2_frame("x", flags=0x40))
        with pytest.raises(CorruptFrameError, match="flags"):
            receive(b)
    finally:
        a.close()
        b.close()


def test_hostile_length_refused_before_allocation_both_dialects():
    # v2: declared length way past the bound — typed error, no allocation
    a, b = _pair()
    try:
        a.sendall(_v2_frame("x", length_override=1 << 50))
        with pytest.raises(FrameTooLargeError, match="declared"):
            receive(b, max_frame_bytes=1 << 20)
    finally:
        a.close()
        b.close()
    # legacy: a hostile ASCII header makes the same typed promise
    a, b = _pair()
    try:
        a.sendall(str(1 << 50).zfill(HEADER_WIDTH).encode("ascii"))
        with pytest.raises(FrameTooLargeError, match="legacy"):
            receive(b, max_frame_bytes=1 << 20)
    finally:
        a.close()
        b.close()


def test_garbage_lead_byte_and_garbage_legacy_header_are_typed():
    a, b = _pair()
    try:
        a.sendall(b"\xff" + b"junk" * 8)
        with pytest.raises(CorruptFrameError, match="unrecognized"):
            receive(b)
    finally:
        a.close()
        b.close()
    a, b = _pair()
    try:
        a.sendall(b"1" + b"not-digits-after!!!" + b"x" * 32)
        with pytest.raises(CorruptFrameError, match="legacy header"):
            receive(b)
    finally:
        a.close()
        b.close()


def test_peer_close_mid_frame_is_truncated_error_naming_shortfall():
    a, b = _pair()
    try:
        frame = _v2_frame(list(range(100)))
        a.sendall(frame[: V2_HEADER_BYTES + 5])  # header + 5 payload bytes
        a.close()
        with pytest.raises(TruncatedFrameError, match="closed mid-frame"):
            receive(b)
    finally:
        b.close()


def test_stall_mid_frame_raises_idle_between_frames_does_not():
    # idle BEFORE a frame starts: the stall deadline must NOT apply —
    # a worker parked at a round boundary is healthy
    a, b = _pair()
    try:
        result = {}

        def reader():
            result["obj"] = receive(b, stall_timeout_s=0.2)

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.5)             # well past the stall deadline, idle
        send(a, "late but fine")
        t.join(timeout=5)
        assert result["obj"] == "late but fine"
    finally:
        a.close()
        b.close()
    # stalling INSIDE a frame: typed error at the deadline
    a, b = _pair()
    try:
        frame = _v2_frame(list(range(1000)))
        a.sendall(frame[:30])       # header + a few payload bytes, then hang
        start = time.monotonic()
        with pytest.raises(FrameStalledError, match="stalled mid-frame"):
            receive(b, stall_timeout_s=0.2)
        assert time.monotonic() - start < 5.0
    finally:
        a.close()
        b.close()


# -- out-of-band (FLAG_OOB) frames -----------------------------------------

class _Tap:
    """Capture the raw bytes send() writes, to tamper with them."""

    def __init__(self):
        self.raw = bytearray()

    def sendall(self, data):
        self.raw += bytes(data)


def _oob_weights():
    return [np.arange(1 << 16, dtype=np.float32),
            np.full((257, 129), 3.25, np.float32)]


def _captured_oob_frame(obj):
    tap = _Tap()
    send(tap, obj)
    assert tap.raw[5] & FLAG_OOB, "payload large enough must go out-of-band"
    return tap.raw


def _feed(frame_bytes):
    a, b = _pair()

    def feeder():
        try:
            a.sendall(bytes(frame_bytes))
        except OSError:
            pass              # receiver aborted mid-frame: expected
        finally:
            a.close()

    t = threading.Thread(target=feeder)
    t.start()
    return b, t


def test_oob_round_trip_yields_equal_writable_arrays():
    a, b = _pair()
    try:
        weights = _oob_weights()
        t = threading.Thread(target=lambda: send(a, {"w": weights}))
        t.start()
        obj, ver = receive_frame(b)
        t.join()
        assert ver == WIRE_V2
        for got, want in zip(obj["w"], weights):
            np.testing.assert_array_equal(got, want)
            assert got.dtype == want.dtype and got.shape == want.shape
            got[...] = 0      # consumers may mutate pulled weights in place
    finally:
        a.close()
        b.close()


def test_small_v2_payload_stays_single_frame():
    tap = _Tap()
    send(tap, {"w": [np.arange(8, dtype=np.float32)]})
    assert tap.raw[5] == 0    # flags clear: contiguous payload, crc in header
    assert len(tap.raw) < OOB_MIN_BYTES


def test_oob_flipped_buffer_bit_is_a_typed_checksum_error():
    frame = bytearray(_captured_oob_frame({"w": _oob_weights()}))
    frame[-17] ^= 0x20        # deep inside the last out-of-band buffer
    b, t = _feed(frame)
    try:
        with pytest.raises(CorruptFrameError, match="checksum mismatch"):
            receive(b)
    finally:
        b.close()            # unblocks the feeder if we aborted early
        t.join()


def test_oob_hostile_buffer_table_is_typed_not_an_overallocation():
    frame = bytearray(_captured_oob_frame({"w": _oob_weights()}))
    body_len = struct.unpack(">I", frame[V2_HEADER_BYTES:V2_HEADER_BYTES + 4])[0]
    table_at = V2_HEADER_BYTES + 4 + body_len + 4
    struct.pack_into(">Q", frame, table_at, 1 << 50)  # lie about buffer 0
    b, t = _feed(frame)
    try:
        with pytest.raises(CorruptFrameError, match="table/length"):
            receive(b)
    finally:
        b.close()            # unblocks the feeder if we aborted early
        t.join()


def test_oob_peer_close_mid_buffer_is_truncated_error():
    frame = _captured_oob_frame({"w": _oob_weights()})
    b, t = _feed(frame[:-1000])   # die 1000 bytes short of the last buffer
    try:
        with pytest.raises(TruncatedFrameError, match="closed mid-frame"):
            receive(b)
    finally:
        b.close()            # unblocks the feeder if we aborted early
        t.join()


def test_stall_restores_socket_timeout():
    a, b = _pair()
    try:
        b.settimeout(7.5)
        send(a, "hi")
        assert receive(b, stall_timeout_s=0.5, mid_message=True) == "hi"
        assert b.gettimeout() == 7.5
    finally:
        a.close()
        b.close()
