"""Model serialization round-trip (reference: tests/utils/test_serialization.py)."""

import numpy as np

from elephas_tpu.utils import dict_to_model, model_to_dict
from elephas_tpu.utils.serialization import load_weights_npz, save_weights_npz


def test_model_to_dict_round_trip(classifier_factory):
    model = classifier_factory()
    d = model_to_dict(model)
    assert set(d.keys()) == {"model", "weights"}
    model2 = dict_to_model(d)
    for w1, w2 in zip(model.get_weights(), model2.get_weights()):
        assert np.allclose(w1, w2)
    x = np.random.default_rng(0).normal(size=(4, 10)).astype("float32")
    assert np.allclose(model.predict(x, verbose=0), model2.predict(x, verbose=0))


def test_weights_npz_round_trip(tmp_path, classifier_factory):
    model = classifier_factory()
    path = str(tmp_path / "weights.npz")
    save_weights_npz(path, model.get_weights())
    loaded = load_weights_npz(path)
    for w1, w2 in zip(model.get_weights(), loaded):
        assert np.allclose(w1, w2)


def test_old_style_yaml_config_loads(classifier_factory):
    """Reference-era artifacts stored model.to_yaml(); dict_to_model must
    accept them (YAML → JSON config conversion on the fly)."""
    import json

    import yaml

    from elephas_tpu.utils.serialization import dict_to_model, model_to_dict

    model = classifier_factory()
    d = model_to_dict(model)
    legacy = {
        "model": yaml.safe_dump(json.loads(d["model"])),  # to_yaml analog
        "weights": d["weights"],
    }
    loaded = dict_to_model(legacy)
    for a, b in zip(model.get_weights(), loaded.get_weights()):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
