"""Sharded (orbax/tensorstore) pytree checkpoints.

`save_sharded_pytree` writes each shard from its owning process with no
host gather; `load_sharded_pytree` restores straight into the target
shardings (resharding allowed). The npz `save_pytree` path is covered in
tests/integration/test_checkpoint.py — these are the scale-out variants.
"""

import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint")




class TestShardedPytree:
    """Orbax-backed sharded checkpoints: no-gather save, direct-to-device
    restore, and resharding on restore."""

    def _mesh_tree(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()).reshape(4, 2),
                    ("data", "model"))
        x = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                           NamedSharding(mesh, P("data", "model")))
        r = jax.device_put(jnp.ones((3,)), NamedSharding(mesh, P()))
        return mesh, {"w": x, "nest": {"r": r}}

    def test_round_trip_with_shardings(self, tmp_path):
        import jax

        from elephas_tpu.utils import load_sharded_pytree, \
            save_sharded_pytree

        _, tree = self._mesh_tree()
        save_sharded_pytree(str(tmp_path / "ck"), tree)
        restored = load_sharded_pytree(str(tmp_path / "ck"), template=tree)
        assert restored["w"].sharding == tree["w"].sharding
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(tree["w"]))
        np.testing.assert_array_equal(np.asarray(restored["nest"]["r"]),
                                      np.asarray(tree["nest"]["r"]))

    def test_host_restore_without_template(self, tmp_path):
        from elephas_tpu.utils import load_sharded_pytree, \
            save_sharded_pytree

        _, tree = self._mesh_tree()
        save_sharded_pytree(str(tmp_path / "ck"), tree)
        host = load_sharded_pytree(str(tmp_path / "ck"))
        np.testing.assert_array_equal(np.asarray(host["w"]),
                                      np.asarray(tree["w"]))

    def test_restore_onto_different_mesh_shape(self, tmp_path):
        """Resume onto a DIFFERENT mesh geometry: saved from a (4,2) mesh,
        restored into shardings of a (2,4) mesh over the same 8 devices —
        the elastic-restart case (job relaunched with a different
        data/model split). Tensorstore serves whatever slices the new
        sharding asks for; values must be exact."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from elephas_tpu.utils import load_sharded_pytree, \
            save_sharded_pytree

        _, tree = self._mesh_tree()          # saved over a (4, 2) mesh
        save_sharded_pytree(str(tmp_path / "ck"), tree)
        remesh = Mesh(np.array(jax.devices()).reshape(2, 4),
                      ("data", "model"))
        tmpl = {"w": jax.device_put(jnp.zeros((8, 8)),
                                    NamedSharding(remesh,
                                                  P("data", "model"))),
                "nest": {"r": jax.device_put(jnp.zeros((3,)),
                                             NamedSharding(remesh, P()))}}
        restored = load_sharded_pytree(str(tmp_path / "ck"), template=tmpl)
        assert restored["w"].sharding == tmpl["w"].sharding
        assert restored["w"].sharding.mesh.devices.shape == (2, 4)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(tree["w"]))
        np.testing.assert_array_equal(np.asarray(restored["nest"]["r"]),
                                      np.asarray(tree["nest"]["r"]))

    def test_restore_into_different_sharding(self, tmp_path):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from elephas_tpu.utils import load_sharded_pytree, \
            save_sharded_pytree

        mesh, tree = self._mesh_tree()
        save_sharded_pytree(str(tmp_path / "ck"), tree)
        # resharding restore: saved over ("data","model"), restored
        # replicated — tensorstore serves whatever slices are asked
        tmpl = {"w": jax.device_put(jnp.zeros((8, 8)),
                                    NamedSharding(mesh, P())),
                "nest": {"r": jax.device_put(jnp.zeros((3,)),
                                             NamedSharding(mesh, P()))}}
        restored = load_sharded_pytree(str(tmp_path / "ck"), template=tmpl)
        assert restored["w"].sharding == tmpl["w"].sharding
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(tree["w"]))

    def test_has_checkpoint_rejects_partial_directory(self, tmp_path):
        """A crash can die between the weights write and the meta commit
        point, or mid-``json.dump``; an auto-resume probe must classify
        every such partial directory as 'no checkpoint'."""
        import json

        from elephas_tpu.utils.checkpoint import (has_checkpoint,
                                                  save_checkpoint)

        assert not has_checkpoint(str(tmp_path / "missing"))

        # weights landed, crash before meta.json (the commit point)
        weights_only = tmp_path / "weights_only"
        weights_only.mkdir()
        from elephas_tpu.utils.serialization import save_weights_npz

        save_weights_npz(str(weights_only / "weights.npz"),
                         [np.ones((2, 2), np.float32)])
        assert not has_checkpoint(str(weights_only))

        # meta.json landed but truncated mid-json.dump
        truncated = tmp_path / "truncated"
        truncated.mkdir()
        save_weights_npz(str(truncated / "weights.npz"),
                         [np.ones((2, 2), np.float32)])
        (truncated / "meta.json").write_text('{"epoch": ')
        assert not has_checkpoint(str(truncated))

        # meta.json parses but weights.npz is gone (partial delete /
        # out-of-order writer)
        meta_only = tmp_path / "meta_only"
        meta_only.mkdir()
        (meta_only / "meta.json").write_text(json.dumps({"epoch": 1}))
        assert not has_checkpoint(str(meta_only))

        # the real thing still passes
        good = tmp_path / "good"
        save_checkpoint(str(good), [np.ones((2, 2), np.float32)],
                        {"epoch": 1})
        assert has_checkpoint(str(good))

    def test_resumes_lm_trainer_bit_identically(self, tmp_path):
        import jax
        import optax

        from elephas_tpu.models import (TransformerLM, build_lm_train_step,
                                        build_mesh_sp, make_lm_batches,
                                        shard_lm_batch)
        from elephas_tpu.utils import load_sharded_pytree, \
            save_sharded_pytree

        model = TransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=1,
                              d_ff=32, max_len=16)
        mesh = build_mesh_sp(data=4, seq=2)
        step, opt_init = build_lm_train_step(model, mesh, optax.adam(1e-2),
                                             attn="ring")
        params = model.shard_params(mesh, model.init(0))
        opt = opt_init(params)
        rows = np.arange(17 * 4).reshape(4, 17) % 17
        batch = shard_lm_batch(mesh, *make_lm_batches(rows))
        params, opt, _ = step(params, opt, *batch)

        save_sharded_pytree(str(tmp_path / "state"),
                            {"params": params, "opt": opt})
        # continue directly
        p2, o2, l2 = step(params, opt, *batch)
        # resume from checkpoint into fresh sharded templates
        tmpl = {"params": model.shard_params(mesh, model.init(0)),
                "opt": opt_init(model.shard_params(mesh, model.init(0)))}
        st = load_sharded_pytree(str(tmp_path / "state"), template=tmpl)
        p3, o3, l3 = step(st["params"], st["opt"], *batch)
        assert float(l2) == float(l3)
        for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(p3)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
