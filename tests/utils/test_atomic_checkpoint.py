"""Atomic checkpoint writes: a crash at ANY instant never tears a file.

``atomic_write`` (temp sibling + fsync + ``os.replace``) and the meta-last
commit ordering in ``save_checkpoint`` promise that a reader always sees
each file either absent, the previous complete version, or the new complete
version. These tests crash saves at chosen points (injected exceptions) and
at arbitrary points (SIGKILL loop) and hold the promise to it.

(The orbax-gated sharded variants live in ``test_checkpoint.py``.)
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from elephas_tpu.utils.checkpoint import (
    atomic_write,
    has_checkpoint,
    load_checkpoint,
    save_checkpoint,
)
from elephas_tpu.utils.serialization import load_weights_npz, save_weights_npz


def _tmp_residue(directory):
    return [n for n in os.listdir(directory) if ".tmp." in n]


def _weights(value):
    return [np.full((3, 2), value, np.float32), np.arange(4, dtype=np.float32)]


def test_atomic_write_success_and_no_residue(tmp_path):
    path = tmp_path / "blob.bin"
    with atomic_write(str(path)) as f:
        f.write(b"v1-complete")
    assert path.read_bytes() == b"v1-complete"
    assert _tmp_residue(tmp_path) == []


def test_atomic_write_crash_keeps_previous_version(tmp_path):
    path = tmp_path / "blob.bin"
    path.write_bytes(b"v1-complete")
    with pytest.raises(RuntimeError, match="crash mid-write"):
        with atomic_write(str(path)) as f:
            f.write(b"v2-partia")          # torn write, then the crash
            raise RuntimeError("crash mid-write")
    assert path.read_bytes() == b"v1-complete"
    assert _tmp_residue(tmp_path) == []


def test_save_weights_crash_keeps_previous_version(tmp_path, monkeypatch):
    path = str(tmp_path / "weights.npz")
    save_weights_npz(path, _weights(1.0))

    real_savez = np.savez

    def torn_savez(f, **arrays):
        real_savez(f, **arrays)
        raise OSError("disk gone mid-save")

    monkeypatch.setattr(np, "savez", torn_savez)
    with pytest.raises(OSError, match="disk gone"):
        save_weights_npz(path, _weights(2.0))
    monkeypatch.undo()

    np.testing.assert_array_equal(load_weights_npz(path)[0],
                                  _weights(1.0)[0])
    assert _tmp_residue(tmp_path) == []


def test_checkpoint_crash_during_weights_keeps_old_checkpoint(
        tmp_path, monkeypatch):
    ckpt = str(tmp_path / "ck")
    save_checkpoint(ckpt, _weights(1.0), {"epoch": 1})

    real_savez = np.savez
    monkeypatch.setattr(
        np, "savez",
        lambda f, **arrays: (_ for _ in ()).throw(OSError("killed")))
    with pytest.raises(OSError, match="killed"):
        save_checkpoint(ckpt, _weights(2.0), {"epoch": 2})
    monkeypatch.setattr(np, "savez", real_savez)

    assert has_checkpoint(ckpt)
    weights, meta, _ = load_checkpoint(ckpt)
    np.testing.assert_array_equal(weights[0], _weights(1.0)[0])
    assert meta == {"epoch": 1}
    assert _tmp_residue(ckpt) == []


def test_checkpoint_crash_before_meta_is_allowed_skew(tmp_path, monkeypatch):
    """Dying between the weights rename and the meta rename is the ONE
    documented skew: new weights under the previous save's meta. The
    checkpoint must stay fully loadable — resume replays finished work,
    it never reads a torn file."""
    ckpt = str(tmp_path / "ck")
    save_checkpoint(ckpt, _weights(1.0), {"epoch": 1})

    monkeypatch.setattr(
        json, "dumps",
        lambda obj: (_ for _ in ()).throw(RuntimeError("died pre-meta")))
    with pytest.raises(RuntimeError, match="died pre-meta"):
        save_checkpoint(ckpt, _weights(2.0), {"epoch": 2})
    monkeypatch.undo()

    assert has_checkpoint(ckpt)
    weights, meta, _ = load_checkpoint(ckpt)
    np.testing.assert_array_equal(weights[0], _weights(2.0)[0])  # new
    assert meta == {"epoch": 1}                                  # old meta
    assert _tmp_residue(ckpt) == []


_KILL_LOOP = """
import sys
import numpy as np
from elephas_tpu.utils.serialization import save_weights_npz

path = sys.argv[1]
version = 0
print("ready", flush=True)
while True:
    version += 1
    save_weights_npz(path, [np.full((64, 64), float(version), np.float32)])
"""


def test_sigkill_mid_save_loop_never_tears_the_file(tmp_path):
    """Real, unhandleable death: SIGKILL a process that is overwriting the
    same npz in a tight loop, at arbitrary instants. The surviving file
    must ALWAYS parse and hold exactly one complete version's data."""
    path = str(tmp_path / "weights.npz")
    for round_no in range(3):
        proc = subprocess.Popen(
            [sys.executable, "-c", _KILL_LOOP, path],
            stdout=subprocess.PIPE, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        try:
            assert proc.stdout.readline().strip() == b"ready"
            time.sleep(0.05 + 0.07 * round_no)   # vary the kill instant
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        weights = load_weights_npz(path)         # parses, never torn
        arr = weights[0]
        assert arr.shape == (64, 64)
        assert float(arr.min()) == float(arr.max())  # one version, whole
        # SIGKILL can strand at most the CURRENT temp sibling (unlink-on-
        # error never ran — nothing can run); it never replaces the target
        leftover = _tmp_residue(tmp_path)
        assert len(leftover) <= 1
        for name in leftover:
            os.unlink(os.path.join(tmp_path, name))
