"""RDD conversion tests (reference: tests/utils/test_rdd_utils.py)."""

import numpy as np
import pytest

from elephas_tpu.mllib import LabeledPoint
from elephas_tpu.utils import (
    encode_label,
    from_labeled_point,
    lp_to_simple_rdd,
    to_labeled_point,
    to_simple_rdd,
)


def test_to_simple_rdd(spark_context):
    x = np.arange(20).reshape(10, 2).astype("float32")
    y = np.arange(10).astype("float32")
    rdd = to_simple_rdd(spark_context, x, y)
    pairs = rdd.collect()
    assert len(pairs) == 10
    assert np.allclose(pairs[3][0], x[3])
    assert pairs[3][1] == y[3]


def test_to_simple_rdd_length_mismatch(spark_context):
    with pytest.raises(ValueError):
        to_simple_rdd(spark_context, np.zeros((5, 2)), np.zeros((4,)))


def test_encode_label():
    enc = encode_label(2, 4)
    assert enc.tolist() == [0, 0, 1, 0]


def test_labeled_point_round_trip(spark_context):
    x = np.random.default_rng(0).normal(size=(12, 3)).astype("float64")
    y = np.array([0, 1, 2, 0, 1, 2, 0, 1, 2, 0, 1, 2], dtype="float64")
    lp_rdd = to_labeled_point(spark_context, x, y, categorical=False)
    points = lp_rdd.collect()
    assert all(isinstance(p, LabeledPoint) for p in points)
    x2, y2 = from_labeled_point(lp_rdd, categorical=False)
    assert np.allclose(x2, x)
    assert np.allclose(y2, y)


def test_labeled_point_categorical(spark_context):
    x = np.zeros((6, 2))
    y_onehot = np.eye(3)[[0, 1, 2, 0, 1, 2]]
    lp_rdd = to_labeled_point(spark_context, x, y_onehot, categorical=True)
    labels = [p.label for p in lp_rdd.collect()]
    assert labels == [0, 1, 2, 0, 1, 2]
    _, y2 = from_labeled_point(lp_rdd, categorical=True, nb_classes=3)
    assert np.allclose(y2, y_onehot)


def test_lp_to_simple_rdd(spark_context):
    x = np.ones((4, 2))
    y = np.array([0, 1, 1, 0], dtype="float64")
    lp_rdd = to_labeled_point(spark_context, x, y, categorical=False)
    simple = lp_to_simple_rdd(lp_rdd, categorical=True, nb_classes=2)
    pairs = simple.collect()
    assert np.allclose(pairs[1][1], [0, 1])
    assert np.allclose(pairs[0][0], x[0])
