"""Wire fuzzing: hostile and corrupted bytes against the LIVE socket stack.

The framing unit tests (``test_sockets.py``) pin the decoder; this suite
pins the system behavior around it — a parameter server, a SocketClient,
and an emulation worker fed bit-flipped / truncated / oversize / garbage /
duplicated frames must

- survive (the process and every other connection keep working),
- quarantine exactly the bad connection,
- never apply a corrupted payload (weights unchanged, fires == catches),
- and interoperate across the legacy↔v2 negotiation matrix.
"""

import pickle
import socket
import struct
import threading
import time

import numpy as np
import pytest

from elephas_tpu.parameter.client import BaseParameterClient, SocketClient
from elephas_tpu.parameter.server import SocketServer
from elephas_tpu.resilience.faults import FaultPlan
from elephas_tpu.utils.sockets import (
    HEADER_WIDTH,
    MAGIC,
    NEGOTIATE_OP,
    WIRE_V1,
    WIRE_V2,
    CorruptFrameError,
    frame_checksum,
    receive,
    send,
)


def _weights():
    return [np.zeros((4,), np.float32), np.ones((2, 3), np.float32)]


def _start_server(**kwargs):
    server = SocketServer(_weights(), port=0, **kwargs)
    server.start()
    return server


def _raw_conn(port):
    return socket.create_connection(("127.0.0.1", port), timeout=5.0)


def _v2_push_frame(delta):
    payload = pickle.dumps(delta, protocol=pickle.HIGHEST_PROTOCOL)
    header = struct.pack(">4sBBQI", MAGIC, WIRE_V2, 0, len(payload),
                         frame_checksum(payload))
    return header + payload


def _closed_by_peer(sock):
    """True iff the peer closes (EOF/reset) within the socket timeout."""
    try:
        return sock.recv(1) == b""
    except (ConnectionError, OSError):
        return True


def _wait_for(cond, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while not cond():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.02)
    return True


def _settle(getter, settle_s=0.3, timeout_s=5.0):
    """Poll ``getter()`` until its value holds still for ``settle_s``."""
    last = getter()
    deadline = time.monotonic() + timeout_s
    stable_since = time.monotonic()
    while time.monotonic() < deadline:
        time.sleep(0.05)
        cur = getter()
        if cur != last:
            last, stable_since = cur, time.monotonic()
        elif time.monotonic() - stable_since >= settle_s:
            break
    return last


# -- server under attack ---------------------------------------------------

def test_server_quarantines_garbage_connection_others_unaffected():
    server = _start_server()
    good = BaseParameterClient.get_client("socket", port=server.port,
                                          host="127.0.0.1", timeout=5.0)
    try:
        assert np.allclose(good.get_parameters()[0], 0.0)
        bad = _raw_conn(server.port)
        bad.sendall(b"\xff\x00garbage-bytes" * 4)
        assert _closed_by_peer(bad)          # quarantined: just this conn
        bad.close()
        # the well-behaved client's connection still works, and pushes apply
        good.update_parameters([np.full((4,), -1.0, np.float32),
                                np.zeros((2, 3), np.float32)])
        assert np.allclose(good.get_parameters()[0], 1.0)
        assert server.wire_errors >= 1
    finally:
        good.close()
        server.stop()


def test_server_bit_flip_caught_never_applied():
    server = _start_server()
    try:
        before = [np.array(w) for w in server.get_weights()]
        frame = bytearray(_v2_push_frame(
            [np.full((4,), 123.0, np.float32),
             np.full((2, 3), 123.0, np.float32)]))
        frame[25] ^= 0x10                    # one bit, inside the payload
        bad = _raw_conn(server.port)
        bad.sendall(b"u" + bytes(frame))
        assert _closed_by_peer(bad)
        bad.close()
        assert server.wire_errors == 1
        assert server.version == 0           # nothing applied
        for w_before, w_now in zip(before, server.get_weights()):
            np.testing.assert_array_equal(w_before, w_now)
    finally:
        server.stop()


def test_server_oversize_declared_length_refused_both_dialects():
    server = _start_server(max_frame_bytes=1 << 16)
    try:
        # legacy dialect: hostile ASCII header declaring a petabyte
        bad = _raw_conn(server.port)
        bad.sendall(b"u" + str(1 << 50).zfill(HEADER_WIDTH).encode())
        assert _closed_by_peer(bad)
        bad.close()
        # v2 dialect: hostile binary length field
        frame = bytearray(_v2_push_frame([np.zeros((2,), np.float32)]))
        struct.pack_into(">Q", frame, 6, 1 << 50)
        bad = _raw_conn(server.port)
        bad.sendall(b"u" + bytes(frame))
        assert _closed_by_peer(bad)
        bad.close()
        assert server.wire_errors == 2 and server.version == 0
    finally:
        server.stop()


def test_server_truncated_push_caught():
    server = _start_server()
    try:
        frame = _v2_push_frame([np.full((4,), 9.0, np.float32),
                                np.zeros((2, 3), np.float32)])
        bad = _raw_conn(server.port)
        bad.sendall(b"u" + frame[: len(frame) // 2])
        bad.close()                          # EOF mid-frame
        assert _wait_for(lambda: server.wire_errors == 1)
        assert server.version == 0
    finally:
        server.stop()


def test_server_slow_loris_disconnected_idle_client_kept():
    server = _start_server(stall_timeout_s=0.3)
    idle = BaseParameterClient.get_client("socket", port=server.port,
                                          host="127.0.0.1", timeout=5.0)
    try:
        idle.get_parameters()                # open + prove the connection
        loris = _raw_conn(server.port)
        frame = _v2_push_frame([np.zeros((4,), np.float32),
                                np.zeros((2, 3), np.float32)])
        loris.sendall(b"u" + frame[:10])     # start a frame, then stall
        assert _closed_by_peer(loris)        # reaped at the stall deadline
        loris.close()
        assert server.wire_errors == 1
        # the IDLE (between frames) client was not reaped
        assert np.allclose(idle.get_parameters()[0], 0.0)
    finally:
        idle.close()
        server.stop()


# -- client under attack ---------------------------------------------------

def _lying_server(reply_builder):
    """Accept one v2-negotiated connection, answer the first opcode with
    ``reply_builder()`` raw bytes, then close. Returns (port, thread)."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(4)
    port = lsock.getsockname()[1]

    def serve():
        while True:
            try:
                conn, _ = lsock.accept()
            except OSError:
                return
            with conn:
                try:
                    op = conn.recv(1)
                    if op == NEGOTIATE_OP:
                        conn.recv(4)
                        conn.sendall(MAGIC)
                        op = conn.recv(1)
                    if op:
                        conn.sendall(reply_builder())
                except OSError:
                    pass

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return lsock, port


def test_client_corrupt_reply_is_typed_and_counted():
    def corrupt_reply():
        frame = bytearray()
        payload = pickle.dumps(([np.arange(4)]), protocol=2)
        frame += struct.pack(">4sBBQI", MAGIC, WIRE_V2, 0, len(payload),
                             frame_checksum(payload) ^ 0xDEAD)
        frame += payload
        return bytes(frame)

    lsock, port = _lying_server(corrupt_reply)
    plan = FaultPlan(seed=0, wire_flip_bits=1e-12)  # ledger only, no fires
    client = SocketClient(port=port, host="127.0.0.1", timeout=5.0,
                          fault_plan=plan)
    try:
        with pytest.raises(CorruptFrameError):
            client.get_parameters()
        assert client.wire_errors >= 1
        assert sum(plan.wire_caught.values()) >= 1
        assert any(k.startswith("client:CorruptFrameError")
                   for k in plan.wire_caught)
    finally:
        client.close()
        lsock.close()


def test_client_wrong_shape_reply_is_typed_not_a_crash():
    lsock, port = _lying_server(
        lambda: _v2_push_frame("not a weight list at all"))
    client = SocketClient(port=port, host="127.0.0.1", timeout=5.0)
    try:
        with pytest.raises(CorruptFrameError, match="desynchronized|expected"):
            client.get_parameters()
        assert client.wire_errors >= 1
    finally:
        client.close()
        lsock.close()


def test_faultysocket_client_corruption_fired_equals_caught():
    """Every destructive fire on the client's outbound frames is caught by
    the server, 1:1, and nothing lands in the weights."""
    # the catch ledger lives on whatever plan the SERVER holds (in the soak
    # one plan is shared end to end); a faultless plan records catches
    # without wrapping the server's replies
    ledger = FaultPlan(seed=0)
    server = _start_server(fault_plan=ledger)
    plan = FaultPlan(seed=7, wire_garbage=1.0)   # every frame garbage
    try:
        before = [np.array(w) for w in server.get_weights()]
        for _ in range(3):
            # fresh connection per push so every fired frame actually
            # REACHES the server (a stale quarantined socket would eat the
            # retry's bytes and break the 1:1 fired==caught accounting,
            # which is exactly why the soak only pins fired>0 ⇒ caught>0)
            client = SocketClient(port=server.port, host="127.0.0.1",
                                  timeout=5.0, fault_plan=plan)
            client.update_parameters([np.full((4,), 5.0, np.float32),
                                      np.full((2, 3), 5.0, np.float32)])
            client.close()
        fired = plan.fired.get("wire_garbage:client", 0)
        assert fired == 3                        # opcode/hello are control
        assert _wait_for(
            lambda: ledger.wire_caught.get("server:CorruptFrameError", 0)
            >= fired)
        assert ledger.wire_caught.get("server:CorruptFrameError", 0) == fired
        assert server.wire_errors == fired
        assert server.version == 0               # nothing ever applied
        for w_before, w_now in zip(before, server.get_weights()):
            np.testing.assert_array_equal(w_before, w_now)
        clean = SocketClient(port=server.port, host="127.0.0.1", timeout=5.0)
        np.testing.assert_array_equal(clean.get_parameters()[0], before[0])
        clean.close()
    finally:
        server.stop()


def test_faultysocket_duplicate_frames_absorbed():
    """A duplicated outbound frame lands where an opcode is expected: the
    server types it, quarantines, and at-most-once push semantics hold."""
    server = _start_server()
    plan = FaultPlan(seed=1, wire_duplicate=1.0)
    client = SocketClient(port=server.port, host="127.0.0.1", timeout=5.0,
                          fault_plan=plan)
    try:
        for _ in range(3):
            client.update_parameters([np.full((4,), 1.0, np.float32),
                                      np.zeros((2, 3), np.float32)])
        applied = _settle(lambda: server.version)
        assert 1 <= applied <= 3             # at-most-once: never MORE
        # pulls still work (reconnect absorbs each quarantine close), and
        # the weights agree with the version — no double-apply slipped in
        weights = client.get_parameters()
        assert round(float(-weights[0][0])) == applied
        assert plan.fired.get("wire_duplicate:client", 0) >= 3
    finally:
        client.close()
        server.stop()


# -- negotiation matrix ----------------------------------------------------

def test_negotiation_v2_client_v2_server():
    server = _start_server()
    client = SocketClient(port=server.port, host="127.0.0.1", timeout=5.0)
    try:
        client.get_parameters()
        assert client.negotiated_wire_version == WIRE_V2
        client.update_parameters([np.full((4,), 1.0, np.float32),
                                  np.zeros((2, 3), np.float32)])
        assert _wait_for(lambda: server.version == 1)
    finally:
        client.close()
        server.stop()


def test_negotiation_forced_legacy_client_v2_server():
    server = _start_server()
    client = SocketClient(port=server.port, host="127.0.0.1", timeout=5.0,
                          wire_version=WIRE_V1)
    try:
        assert np.allclose(client.get_parameters()[0], 0.0)
        assert client.negotiated_wire_version == WIRE_V1
        client.update_parameters([np.full((4,), 2.0, np.float32),
                                  np.zeros((2, 3), np.float32)])
        assert _wait_for(lambda: server.version == 1)
        np.testing.assert_allclose(server.get_weights()[0],
                                   np.full((4,), -2.0, np.float32))
    finally:
        client.close()
        server.stop()


def _legacy_reference_server():
    """A minimal reference-shaped peer: ASCII-header frames only, closes on
    any unknown opcode (which is what a pre-negotiation server does when it
    sees the b"W" hello)."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(4)
    port = lsock.getsockname()[1]
    state = {"weights": _weights(), "pushes": 0}

    def serve():
        while True:
            try:
                conn, _ = lsock.accept()
            except OSError:
                return
            try:
                while True:
                    op = conn.recv(1)
                    if op == b"g":
                        send(conn, state["weights"], version=WIRE_V1)
                    elif op == b"u":
                        delta = receive(conn)
                        state["weights"] = [w - d for w, d in
                                            zip(state["weights"], delta)]
                        state["pushes"] += 1
                    else:
                        break                # unknown opcode: silent close
            except (ConnectionError, OSError):
                pass
            finally:
                conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return lsock, port, state


def test_negotiation_v2_client_degrades_to_legacy_server():
    lsock, port, state = _legacy_reference_server()
    client = SocketClient(port=port, host="127.0.0.1", timeout=5.0)
    try:
        assert np.allclose(client.get_parameters()[0], 0.0)
        assert client.negotiated_wire_version == WIRE_V1
        client.update_parameters([np.full((4,), 3.0, np.float32),
                                  np.zeros((2, 3), np.float32)])
        client.get_parameters()              # same connection still healthy
        assert _wait_for(lambda: state["pushes"] == 1)
        np.testing.assert_allclose(state["weights"][0],
                                   np.full((4,), -3.0, np.float32))
    finally:
        client.close()
        lsock.close()


def test_negotiation_forced_v2_client_refuses_legacy_server():
    lsock, port, _state = _legacy_reference_server()
    client = SocketClient(port=port, host="127.0.0.1", timeout=5.0,
                          wire_version=WIRE_V2)
    try:
        with pytest.raises(CorruptFrameError, match="did not acknowledge"):
            client.get_parameters()
    finally:
        client.close()
        lsock.close()


# -- emulation worker under attack -----------------------------------------

def test_emulation_worker_survives_garbage_driver():
    from elephas_tpu.parallel.emulation import worker_main

    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]
    served = {}

    def evil_driver():
        conn, _ = lsock.accept()
        with conn:
            served["hello"] = receive(conn)      # the worker's join hello
            conn.sendall(b"\xfe" + b"\x00" * 64)  # then pure garbage
    t = threading.Thread(target=evil_driver, daemon=True)
    t.start()

    rc = worker_main(f"127.0.0.1:{port}", host_id=3, devices=1,
                     connect_timeout_s=5.0)
    t.join(timeout=5)
    lsock.close()
    assert rc == 1                               # typed exit, no hang/crash
    assert served["hello"]["host"] == 3
