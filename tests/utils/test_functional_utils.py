"""Pure-function weight-math tests (reference: tests/utils/test_functional_utils.py)."""

import numpy as np

from elephas_tpu.utils import (
    add_params,
    divide_by,
    get_neutral,
    mean_params,
    scale_params,
    subtract_params,
)


def _params():
    return [np.array([[1.0, 2.0], [3.0, 4.0]]), np.array([0.5, -0.5])]


def test_add_params():
    p = _params()
    out = add_params(p, p)
    assert np.allclose(out[0], 2 * p[0])
    assert np.allclose(out[1], 2 * p[1])


def test_subtract_params_zero():
    p = _params()
    out = subtract_params(p, p)
    for leaf in out:
        assert np.allclose(leaf, 0)


def test_delta_semantics():
    """delta = before - after; applying via subtract recovers `after`."""
    before = _params()
    after = [leaf + 1.0 for leaf in before]
    delta = subtract_params(before, after)
    recovered = subtract_params(before, delta)
    for r, a in zip(recovered, after):
        assert np.allclose(r, a)


def test_get_neutral():
    p = _params()
    z = get_neutral(p)
    for zl, pl in zip(z, p):
        assert zl.shape == pl.shape
        assert np.allclose(zl, 0)


def test_divide_by():
    p = _params()
    out = divide_by(p, 4)
    assert np.allclose(out[0], p[0] / 4)


def test_scale_and_mean():
    p = _params()
    assert np.allclose(scale_params(p, 2.0)[0], 2 * p[0])
    q = [leaf * 3 for leaf in p]
    m = mean_params([p, q])
    assert np.allclose(m[0], 2 * p[0])
