"""Example-script smoke tests.

The reference's examples double as its integration surface (SURVEY.md §4 —
CI runs them nowhere, and they rot). Here each example runs as a subprocess
on the CPU mesh with tiny ``EX_SAMPLES``/``EX_EPOCHS`` overrides, asserting
it exits cleanly — the same scripts scale back up to real sizes unchanged.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXAMPLES = [
    "mnist_mlp_spark.py",
    "mnist_cnn_async.py",
    "mllib_mlp.py",
    "ml_mlp.py",
    "ml_pipeline_otto.py",
    "ml_pipeline_imdb_lstm.py",
    "hyperparam_optimization.py",
    "transformer_lm.py",
    "parallelism_tour.py",
    "lm_inference_tour.py",
    "hf_import_tour.py",
    "sharded_generate.py",
    "resnet50_spark.py",
    "ml_pipeline_notebook.ipynb",  # executed via nbconvert
]


@pytest.mark.slow
@pytest.mark.timeout(900)  # resnet50 measures ~134s locally; 900 covers CI
@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs(script):
    if script == "hf_import_tour.py":
        # torch/transformers are the import tour's conversion oracle, not
        # project dependencies (test_hf_import.py importorskips the same way)
        pytest.importorskip("torch")
        pytest.importorskip("transformers")
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "KERAS_BACKEND": "jax",
        # > batch_size(128) per each of the 8 workers, or the reference's
        # skip-small-partitions quirk empties the fit
        "EX_SAMPLES": "2048",
        "EX_EPOCHS": "1",
        "EX_STEPS": "12",
        # resnet50: 8 workers x 20 samples > batch_size(16); one epoch of
        # the conv stack compiles+runs in ~100s on the CPU mesh
        "RESNET_SAMPLES": "160",
        "RESNET_EPOCHS": "1",
    })
    if script.endswith(".ipynb"):
        cmd = [sys.executable, "-m", "nbconvert", "--to", "notebook",
               "--execute", "--stdout", script]
    else:
        cmd = [sys.executable, os.path.join(_REPO, "examples", script)]
    proc = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.join(_REPO, "examples"),
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    # (that resnet50's remat flag actually changes the compiled program is
    # pinned by test_adapters.py::test_remat_flag_reaches_the_compiled_program)
