"""Model-family coverage through the compiled engine.

The reference trains arbitrary Keras models; the engine must handle every
variable kind they bring: BatchNorm (non-trainable moving statistics — the
mergeable-ntv merge path), Dropout (seed-generator state), Conv (MXU path),
Embedding+LSTM (recurrent scan-in-scan) — the last two are covered by
examples and the LSTM pipeline test; here BN and regression heads get
first-class tests.
"""

import numpy as np
import pytest

from elephas_tpu import SparkModel
from elephas_tpu.models import KerasModelAdapter
from elephas_tpu.parallel import CompiledTrainer, build_mesh
from elephas_tpu.utils import to_simple_rdd


def _bn_model(d=10, c=3):
    import keras

    m = keras.Sequential(
        [
            keras.layers.Dense(16),
            keras.layers.BatchNormalization(),
            keras.layers.Activation("relu"),
            keras.layers.Dense(c, activation="softmax"),
        ]
    )
    m.build((None, d))
    m.compile(optimizer="adam", loss="categorical_crossentropy",
              metrics=["accuracy"])
    return m


def test_batchnorm_trains_and_merges_stats(toy_classification):
    x, y = toy_classification
    m = _bn_model()
    adapter = KerasModelAdapter(m)
    # BN moving mean/var live in non-trainable weights → mergeable slots
    assert any(s is not None for s in adapter._ntv_slots)
    stats_before = [np.array(v) for v in m.non_trainable_variables[:2]]
    trainer = CompiledTrainer(adapter, build_mesh(4), mode="synchronous")
    res = trainer.fit([(x[i::4], y[i::4]) for i in range(4)], epochs=4,
                      batch_size=16, validation_split=0.0)
    assert res.history["loss"][-1] < res.history["loss"][0]
    # moving statistics must have moved and been merged (finite, changed)
    stats_after = [np.array(v) for v in m.non_trainable_variables[:2]]
    changed = any(
        not np.allclose(a, b) for a, b in zip(stats_before, stats_after)
    )
    assert changed, "BatchNorm moving statistics did not update"
    for s in stats_after:
        assert np.all(np.isfinite(s))


def test_batchnorm_async_mode(spark_context, toy_classification):
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    sm = SparkModel(_bn_model(), mode="asynchronous", frequency="epoch",
                    parameter_server_mode="jax", num_workers=4, merge="mean")
    sm.fit(rdd, epochs=3, batch_size=16, validation_split=0.0)
    h = sm.training_histories[-1]
    assert h["loss"][-1] < h["loss"][0]
    preds = sm.predict(x[:8])
    assert np.all(np.isfinite(preds))


def test_regression_model(toy_regression):
    import keras

    x, y = toy_regression
    m = keras.Sequential(
        [keras.layers.Dense(16, activation="relu"), keras.layers.Dense(1)]
    )
    m.build((None, 8))
    m.compile(optimizer="adam", loss="mse")
    trainer = CompiledTrainer(KerasModelAdapter(m), build_mesh(8),
                              mode="synchronous")
    res = trainer.fit([(x[i::8], y[i::8].reshape(-1, 1)) for i in range(8)],
                      epochs=10, batch_size=16, validation_split=0.0)
    assert res.history["loss"][-1] < res.history["loss"][0] * 0.9
