"""Multi-process (multi-"host") training smoke test.

The reference's multi-worker story is Spark executors on a cluster; ours is
one JAX process per host joined via ``initialize_cluster``
(``jax.distributed`` — SURVEY.md §2.4's DCN bootstrap). This test launches
TWO separate processes, each owning 2 virtual CPU devices, and runs the SAME
``SparkModel.fit`` in both over the resulting 4-device global mesh — the
actual cross-process code path (Gloo collectives between processes), not a
single-process simulation.
"""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import sys
import numpy as np

from elephas_tpu.parallel import initialize_cluster
initialize_cluster(coordinator_address="127.0.0.1:%(port)d",
                   num_processes=2, process_id=int(sys.argv[1]))

import jax
assert jax.device_count() == 4, jax.device_count()
assert jax.local_device_count() == 2

import keras
from elephas_tpu import SparkModel
from elephas_tpu.data import SparkContext
from elephas_tpu.utils import to_simple_rdd

rng = np.random.default_rng(0)
x = rng.normal(size=(256, 10)).astype("float32")
w = rng.normal(size=(10, 3))
y = np.eye(3, dtype="float32")[(x @ w).argmax(1)]

keras.utils.set_random_seed(7)
model = keras.Sequential([
    keras.layers.Dense(16, activation="relu"),
    keras.layers.Dense(3, activation="softmax"),
])
model.build((None, 10))
model.compile(optimizer="adam", loss="categorical_crossentropy",
              metrics=["accuracy"])

sc = SparkContext("local[4]")
rdd = to_simple_rdd(sc, x, y)
sm = SparkModel(model, mode="synchronous", num_workers=4)
sm.fit(rdd, epochs=2, batch_size=16, validation_split=0.0)
h = sm.training_histories[-1]["loss"]
assert h[-1] < h[0], h
print("LOSSES", [round(v, 6) for v in h], flush=True)
"""


def _reserved_port():
    """A bound-and-held listener socket plus its port.

    The old ``_free_port`` bound, read the port, and CLOSED the socket
    before the workers launched — a TOCTOU window in which any other suite
    process could steal the port (the deflake target). Holding the bound
    socket with ``SO_REUSEADDR`` keeps the port reserved until the
    coordinator worker is actually ready to bind it; ``SO_REUSEADDR`` lets
    that bind succeed while our listener is still in the kernel's tables.
    """
    import socket

    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    return s, s.getsockname()[1]


@pytest.mark.multihost
@pytest.mark.xfail(
    os.environ.get("JAX_PLATFORMS", "cpu") == "cpu",
    strict=False,
    reason="Multiprocess computations aren't implemented on the CPU backend",
)
def test_two_process_fit(tmp_path):
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "KERAS_BACKEND": "jax",
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
    })

    def _launch():
        holder, port = _reserved_port()
        script = tmp_path / "worker.py"
        script.write_text(_WORKER % {"port": port})
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(pid)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
            for pid in (0, 1)
        ]
        holder.close()  # released only once the fleet is launching
        return procs

    procs = _launch()
    outs = None
    try:
        outs = [p.communicate(timeout=420)[0] for p in procs]
        # One retry for residual bind races (the reservation shrinks the
        # window to the holder-close → coordinator-bind gap; it cannot
        # close it entirely from outside the coordinator process).
        if any(p.returncode != 0 for p in procs) and any(
            "Address already in use" in out for out in outs
        ):
            procs = _launch()
            outs = [p.communicate(timeout=420)[0] for p in procs]
    finally:
        # Reap unconditionally: kill() alone leaves a zombie Popen on the
        # timeout path; wait() collects it.
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    # SPMD: both processes must observe identical merged training histories
    lines = [
        next(l for l in out.splitlines() if l.startswith("LOSSES"))
        for out in outs
    ]
    assert lines[0] == lines[1], lines
