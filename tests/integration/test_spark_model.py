"""End-to-end SparkModel training matrix.

The reference's distributed test suite IS this matrix (SURVEY.md §4):
mode × parameter-server backend × frequency, trained on a small dataset,
asserting training ran and improved the model. Here the matrix additionally
covers the TPU fast path (``parameter_server_mode='jax'``, on-device psum
merges) next to the reference-shaped host paths (collect / HTTP PS / socket
PS with real thread interleaving).
"""

import numpy as np
import pytest

from elephas_tpu import SparkModel, load_spark_model
from elephas_tpu.utils import to_simple_rdd

from ..conftest import make_classifier

PORTS = iter(range(42000, 42100))


def _accuracy(model, x, y):
    preds = model.predict(x, verbose=0)
    return float((preds.argmax(1) == y.argmax(1)).mean())


@pytest.fixture
def rdd(spark_context, toy_classification):
    x, y = toy_classification
    return to_simple_rdd(spark_context, x, y)


# -- the matrix --------------------------------------------------------------

MATRIX = [
    # (mode, ps_mode, frequency)
    ("synchronous", "jax", "epoch"),
    ("synchronous", "jax", "batch"),  # gradient-sync DP-SGD (TPU extension)
    ("asynchronous", "jax", "epoch"),
    ("asynchronous", "jax", "batch"),
    ("hogwild", "jax", "epoch"),
    ("asynchronous", "http", "epoch"),
    ("asynchronous", "http", "batch"),
    ("asynchronous", "socket", "epoch"),
    ("hogwild", "http", "epoch"),
    ("hogwild", "socket", "batch"),
]


@pytest.mark.parametrize("mode,ps_mode,frequency", MATRIX)
def test_training_matrix(mode, ps_mode, frequency, rdd, toy_classification):
    x, y = toy_classification
    model = make_classifier()
    base_acc = _accuracy(model, x, y)
    spark_model = SparkModel(
        model,
        mode=mode,
        frequency=frequency,
        parameter_server_mode=ps_mode,
        num_workers=4,
        port=next(PORTS),
        merge="mean",
    )
    spark_model.fit(rdd, epochs=4, batch_size=16, verbose=0, validation_split=0.0)
    acc = _accuracy(spark_model.master_network, x, y)
    assert acc > max(base_acc, 0.34), f"no improvement: {base_acc} -> {acc}"


def test_sync_host_path_matches_reference_shape(rdd, toy_classification):
    """Synchronous over the host collect path (the reference's literal merge)."""
    x, y = toy_classification
    model = make_classifier()
    base_acc = _accuracy(model, x, y)
    spark_model = SparkModel(model, mode="synchronous", num_workers=4, comm="host")
    spark_model.fit(rdd, epochs=4, batch_size=16, validation_split=0.0)
    assert _accuracy(spark_model.master_network, x, y) > base_acc
    assert spark_model.training_histories  # per-worker Keras histories collected


def test_sync_jax_records_history(rdd):
    model = make_classifier()
    spark_model = SparkModel(model, mode="synchronous", num_workers=4)
    spark_model.fit(rdd, epochs=3, batch_size=16, validation_split=0.2)
    h = spark_model.training_histories[-1]
    assert len(h["loss"]) == 3
    assert "val_loss" in h and "accuracy" in h
    assert h["loss"][-1] < h["loss"][0]


def test_small_partitions_skipped(spark_context):
    """Partitions with <= batch_size samples are skipped (reference quirk)."""
    x = np.random.default_rng(0).normal(size=(40, 10)).astype("float32")
    y = np.eye(3, dtype="float32")[np.random.default_rng(1).integers(0, 3, 40)]
    rdd = to_simple_rdd(spark_context, x, y)
    model = make_classifier()
    # 40 samples over 4 workers = 10 each <= batch_size 16 → everything skipped
    spark_model = SparkModel(model, mode="synchronous", num_workers=4)
    with pytest.raises(ValueError, match="skipped"):
        spark_model.fit(rdd, epochs=1, batch_size=16, validation_split=0.0)


def test_predict_array_and_rdd(rdd, toy_classification, spark_context):
    x, y = toy_classification
    model = make_classifier()
    spark_model = SparkModel(model, mode="synchronous", num_workers=4)
    spark_model.fit(rdd, epochs=1, batch_size=16, validation_split=0.0)
    preds = spark_model.predict(x[:10])
    assert preds.shape == (10, 3)
    feature_rdd = spark_context.parallelize([row for row in x[:10]], 2)
    dist_preds = np.stack(spark_model.predict(feature_rdd).collect())
    assert np.allclose(dist_preds, preds, atol=1e-5)
    # host path: the reference-shaped mapPartitions replica predict
    host_model = SparkModel(
        spark_model.master_network, mode="synchronous", num_workers=4,
        comm="host",
    )
    host_preds = np.stack(host_model.predict(feature_rdd).collect())
    assert np.allclose(host_preds, preds, atol=1e-5)


def test_compiled_predict_matches_keras(toy_classification):
    """Mesh-sharded compiled predict ≡ driver-local Keras predict."""
    x, _ = toy_classification
    model = make_classifier()
    spark_model = SparkModel(model, mode="synchronous", num_workers=4)
    ref = model.predict(x, verbose=0)
    fast = spark_model.predict(x)  # comm='jax' → compiled sharded path
    assert fast.shape == ref.shape
    assert np.allclose(fast, ref, atol=1e-5)
    # odd-sized inputs exercise padding/bucketing
    assert np.allclose(spark_model.predict(x[:37]), ref[:37], atol=1e-5)


def test_compiled_evaluate_matches_keras(toy_classification):
    x, y = toy_classification
    model = make_classifier()
    spark_model = SparkModel(model, mode="synchronous", num_workers=4)
    ref_loss, ref_acc = model.evaluate(x, y, verbose=0)
    loss, acc = spark_model.evaluate(x, y)
    assert abs(loss - ref_loss) < 1e-3
    assert abs(acc - ref_acc) < 1e-6


def test_evaluate_non_accuracy_metrics_fall_back(toy_classification):
    """A model compiled with non-accuracy metrics must keep the Keras
    return shape from evaluate (the compiled path only knows accuracy)."""
    import keras

    x, y = toy_classification
    model = keras.Sequential(
        [keras.layers.Dense(16, activation="relu"), keras.layers.Dense(3)]
    )
    model.build((None, 10))
    model.compile(optimizer="adam", loss="mse", metrics=["mae"])
    spark_model = SparkModel(model, mode="synchronous", num_workers=4)
    ref = model.evaluate(x, y, verbose=0)
    got = spark_model.evaluate(x, y)
    assert isinstance(got, list) and len(got) == len(ref)
    assert np.allclose(got, ref, atol=1e-5)


def test_evaluate_weighted_metrics_fall_back(toy_classification):
    """weighted_metrics live outside the compiled path's reach → Keras."""
    import keras

    x, y = toy_classification
    model = keras.Sequential(
        [keras.layers.Dense(16, activation="relu"), keras.layers.Dense(3)]
    )
    model.build((None, 10))
    model.compile(optimizer="adam", loss="mse", weighted_metrics=["mae"])
    spark_model = SparkModel(model, mode="synchronous", num_workers=4)
    ref = model.evaluate(x, y, verbose=0)
    got = spark_model.evaluate(x, y)
    assert isinstance(got, list) and len(got) == len(ref)
    assert np.allclose(got, ref, atol=1e-5)


def test_evaluate_master_metrics_override_falls_back(toy_classification):
    """master_metrics=['mae'] on an accuracy-compiled model → gate/adapter
    disagree → must fail over to Keras, keeping the Keras return shape."""
    x, y = toy_classification
    model = make_classifier()
    spark_model = SparkModel(
        model, mode="synchronous", num_workers=4, master_metrics=["mae"]
    )
    ref = model.evaluate(x, y, verbose=0)
    got = spark_model.evaluate(x, y)
    assert isinstance(got, list) and len(got) == len(ref)
    assert np.allclose(got, ref, atol=1e-5)


def test_predict_uncompiled_model(toy_classification):
    """predict needs no loss: an unfitted, uncompiled (built) model predicts
    on the fast path just like driver-local Keras predict did."""
    import keras

    x, _ = toy_classification
    model = keras.Sequential(
        [keras.layers.Dense(16, activation="relu"),
         keras.layers.Dense(3, activation="softmax")]
    )
    model.build((None, 10))
    spark_model = SparkModel(model, mode="synchronous", num_workers=4)
    preds = spark_model.predict(x[:10])
    assert np.allclose(preds, model.predict(x[:10], verbose=0), atol=1e-5)
    # ...but fitting without a loss still raises the clean error
    with pytest.raises(ValueError, match="No loss available"):
        spark_model._get_trainer().adapter.build_train_step(
            spark_model._get_trainer().optimizer
        )


def test_remat_trains_equivalently(rdd, toy_classification):
    """``remat=True`` (jax.checkpoint in the backward pass) must not change
    the math — same seed/geometry trains to the same weights."""
    x, y = toy_classification
    import keras

    results = []
    for remat in (False, True):
        keras.utils.set_random_seed(123)
        model = make_classifier()
        spark_model = SparkModel(
            model, mode="synchronous", num_workers=4, remat=remat
        )
        spark_model.fit(rdd, epochs=2, batch_size=16, validation_split=0.0)
        results.append(spark_model.master_network.get_weights())
    for a, b in zip(*results):
        assert np.allclose(a, b, atol=1e-5)


def test_save_and_load(tmp_path, rdd, toy_classification):
    x, y = toy_classification
    model = make_classifier()
    spark_model = SparkModel(model, mode="synchronous", num_workers=4)
    spark_model.fit(rdd, epochs=1, batch_size=16, validation_split=0.0)
    path = str(tmp_path / "model.keras")
    spark_model.save(path)
    loaded = load_spark_model(path)
    assert loaded.mode == "synchronous"
    for a, b in zip(
        spark_model.master_network.get_weights(), loaded.master_network.get_weights()
    ):
        assert np.allclose(a, b)
    assert np.allclose(
        loaded.master_network.predict(x[:4], verbose=0),
        spark_model.predict(x[:4]),
        atol=1e-5,
    )
