"""Checkpoint/resume for the parallelism-extension trainers.

A run interrupted after k steps and resumed from a pytree checkpoint must
continue bit-identically to an uninterrupted run — including sharded (tp)
and chunked (fsdp) parameter layouts and their optimizer states.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from elephas_tpu.parallel import build_mesh
from elephas_tpu.parallel.fsdp import build_fsdp_train_step
from elephas_tpu.parallel.tensor import (
    TensorParallelMLP,
    build_mesh2d,
    build_tp_train_step,
)
from elephas_tpu.utils.checkpoint import load_pytree, place_like, save_pytree


from tests._helpers import softmax_xent as _softmax_xent  # noqa: E402


def _task(seed=3, n=32, d=10, c=4):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.eye(c, dtype=np.float32)[rng.integers(0, c, size=n)]
    return x, y


def test_fsdp_resume_is_bit_identical(tmp_path):
    mesh = build_mesh(8)
    shapes = {"w0": (10, 17), "b0": (17,), "w1": (17, 4), "b1": (4,)}

    def apply_fn(p, xb):
        h = jax.nn.relu(jnp.dot(xb, p["w0"]) + p["b0"])
        return jnp.dot(h, p["w1"]) + p["b1"]

    step, opt_init, fsdp = build_fsdp_train_step(
        apply_fn, shapes, mesh, optax.adam(1e-2), _softmax_xent
    )
    rng = np.random.default_rng(0)
    host = {k: (rng.normal(size=s) * 0.1).astype(np.float32)
            for k, s in shapes.items()}
    x, y = _task()
    xd = jax.device_put(x, NamedSharding(mesh, P("data")))
    yd = jax.device_put(y, NamedSharding(mesh, P("data")))

    # uninterrupted run: 4 steps
    chunks = fsdp.shard(mesh, fsdp.chunk_host(host))
    state = opt_init(chunks)
    for _ in range(4):
        chunks, state, _ = step(chunks, state, xd, yd)
    want = fsdp.unchunk_host({k: np.asarray(v) for k, v in chunks.items()})

    # interrupted run: 2 steps, checkpoint, reload, 2 more
    chunks = fsdp.shard(mesh, fsdp.chunk_host(host))
    state = opt_init(chunks)
    for _ in range(2):
        chunks, state, _ = step(chunks, state, xd, yd)
    save_pytree(str(tmp_path / "params"), chunks)
    save_pytree(str(tmp_path / "opt"), state)

    fresh_chunks = fsdp.shard(mesh, fsdp.chunk_host(host))
    chunks2 = place_like(fresh_chunks, load_pytree(str(tmp_path / "params")))
    state2 = place_like(opt_init(fresh_chunks),
                        load_pytree(str(tmp_path / "opt")))
    for _ in range(2):
        chunks2, state2, _ = step(chunks2, state2, xd, yd)
    got = fsdp.unchunk_host({k: np.asarray(v) for k, v in chunks2.items()})

    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)


def _roundtrip(tmp_path, make_fresh, step, params, state, batch, n_pre=2,
               n_post=2):
    """Run n_pre steps, checkpoint, restore onto fresh buffers, run n_post
    more; returns the final (params, state)."""
    for _ in range(n_pre):
        params, state, _ = step(params, state, *batch)
    save_pytree(str(tmp_path / "p"), params)
    save_pytree(str(tmp_path / "s"), state)
    fresh_params, fresh_state = make_fresh()
    params = place_like(fresh_params, load_pytree(str(tmp_path / "p")))
    state = place_like(fresh_state, load_pytree(str(tmp_path / "s")))
    for _ in range(n_post):
        params, state, _ = step(params, state, *batch)
    return params, state


@pytest.mark.parametrize("kind", ["pp", "ep", "lm"])
def test_other_trainers_resume_bit_identical(kind, tmp_path):
    """pp, ep, and the MoE LM trainers must also resume exactly."""
    if kind == "pp":
        from elephas_tpu.parallel.pipeline import (
            PipelineDenseStack, build_mesh_pp, build_pp_train_step)

        mesh = build_mesh_pp(data=2, pipe=4)
        model = PipelineDenseStack(d_in=10, hidden=16, d_out=4, n_stages=4)
        step, opt_init = build_pp_train_step(
            model, mesh, optax.adam(1e-2), _softmax_xent, n_micro=4)
        x, y = _task(seed=11)
        batch = tuple(jax.device_put(a, NamedSharding(mesh, P("data")))
                      for a in (x, y))
        make = lambda: (model.shard_params(mesh, model.init(seed=1)),)
    elif kind == "ep":
        from elephas_tpu.parallel.expert import (
            MoEFeedForward, build_mesh_ep, build_ep_train_step)

        mesh = build_mesh_ep(data=2, expert=4)
        model = MoEFeedForward(d_model=8, d_ff=16, n_experts=8, k=2)
        step, opt_init = build_ep_train_step(
            model, mesh, optax.adam(1e-2),
            lambda a, b: jnp.sum((a - b) ** 2, -1))
        rng = np.random.default_rng(12)
        xt = rng.normal(size=(64, 8)).astype(np.float32)
        spec = P(("data", "expert"))
        batch = tuple(jax.device_put(a, NamedSharding(mesh, spec))
                      for a in (xt, xt))
        make = lambda: (model.shard_params(mesh, model.init(seed=1)),)
    else:
        from elephas_tpu.models.transformer import (
            MoETransformerLM, build_lm_train_step, build_mesh_sp,
            make_lm_batches, shard_lm_batch)

        mesh = build_mesh_sp(data=2, seq=4)
        model = MoETransformerLM(vocab=11, d_model=8, n_heads=4, n_layers=1,
                                 d_ff=16, max_len=16, n_experts=4, k=1,
                                 ep_groups=4)
        step, opt_init = build_lm_train_step(model, mesh, optax.adam(1e-2),
                                             attn="ring")
        rows = np.random.default_rng(13).integers(0, 11, size=(4, 17))
        batch = shard_lm_batch(mesh, *make_lm_batches(rows))
        make = lambda: (model.shard_params(mesh, model.init(seed=1)),)

    def make_fresh():
        (p,) = make()
        return p, opt_init(p)

    # uninterrupted
    params, state = make_fresh()
    for _ in range(4):
        params, state, _ = step(params, state, *batch)
    want = {k: np.asarray(jax.device_get(v)) for k, v in params.items()}

    # interrupted + resumed
    params, state = make_fresh()
    params, state = _roundtrip(tmp_path, make_fresh, step, params, state,
                               batch)
    for k, v in want.items():
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(params[k])), v, err_msg=k)


def test_non_numeric_leaf_rejected(tmp_path):
    with pytest.raises(TypeError, match="non-numeric"):
        save_pytree(str(tmp_path / "bad"), {"a": np.ones(3), "b": "label"})


def test_tp_resume_is_bit_identical(tmp_path):
    mesh = build_mesh2d(data=2, model=4)
    model = TensorParallelMLP([10, 16, 8, 16, 4], tp=4)
    step, opt_init = build_tp_train_step(
        model, mesh, optax.adam(1e-2), _softmax_xent
    )
    x, y = _task(seed=5)
    xd = jax.device_put(x, NamedSharding(mesh, P("data")))
    yd = jax.device_put(y, NamedSharding(mesh, P("data")))
    params0 = model.init(seed=1)

    params = model.shard_params(mesh, params0)
    state = opt_init(params)
    for _ in range(4):
        params, state, _ = step(params, state, xd, yd)
    want = model.gather_params(params)

    params = model.shard_params(mesh, params0)
    state = opt_init(params)
    for _ in range(2):
        params, state, _ = step(params, state, xd, yd)
    save_pytree(str(tmp_path / "p"), params)
    save_pytree(str(tmp_path / "s"), state)

    fresh = model.shard_params(mesh, model.init(seed=1))
    params2 = place_like(fresh, load_pytree(str(tmp_path / "p")))
    state2 = place_like(opt_init(fresh), load_pytree(str(tmp_path / "s")))
    # restored leaves keep the sharded layout (model dim split over "model")
    assert params2["w0"].sharding.spec == P(None, "model")
    for _ in range(2):
        params2, state2, _ = step(params2, state2, xd, yd)
    got = model.gather_params(params2)

    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)
