"""TPE sampler: concentration, determinism, and mixed-space handling."""

import math
import random

import pytest

from elephas_tpu.hyperparam import (
    STATUS_OK,
    TPESampler,
    _Choice,
    _LogUniform,
    _QUniform,
    _Uniform,
)


def _trials(spaces, losses_for, n, seed=0):
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        params = [s.sample(rng) for s in spaces]
        out.append({"loss": losses_for(params), "status": STATUS_OK,
                    "params": params})
    return out


def test_startup_is_random_prior():
    spaces = [_Uniform(0, 1)]
    sampler = TPESampler(spaces, n_startup=5)
    rng = random.Random(0)
    got = sampler.suggest([], rng)
    assert 0.0 <= got[0] <= 1.0


def test_concentrates_on_good_region():
    """With a quadratic loss around x=2, proposals must shift toward 2."""
    spaces = [_Uniform(0.0, 10.0)]
    trials = _trials(spaces, lambda p: (p[0] - 2.0) ** 2, n=40)
    sampler = TPESampler(spaces)
    rng = random.Random(1)
    proposals = [sampler.suggest(trials, rng)[0] for _ in range(50)]
    mean = sum(proposals) / len(proposals)
    # prior mean is 5.0; the TPE posterior must sit far closer to 2.0
    assert abs(mean - 2.0) < 1.5, mean
    assert all(0.0 <= p <= 10.0 for p in proposals)


def test_loguniform_concentrates_in_log_space():
    spaces = [_LogUniform(1e-5, 1.0)]
    # best losses near 1e-3
    trials = _trials(
        spaces, lambda p: abs(math.log10(p[0]) - (-3.0)), n=40
    )
    sampler = TPESampler(spaces)
    rng = random.Random(2)
    proposals = [sampler.suggest(trials, rng)[0] for _ in range(50)]
    logs = [math.log10(p) for p in proposals]
    mean = sum(logs) / len(logs)
    assert abs(mean - (-3.0)) < 1.2, mean


def test_choice_prefers_winning_option():
    spaces = [_Choice([16, 32, 64, 128])]
    trials = _trials(
        spaces, lambda p: 0.0 if p[0] == 64 else 1.0, n=40
    )
    sampler = TPESampler(spaces)
    rng = random.Random(3)
    proposals = [sampler.suggest(trials, rng)[0] for _ in range(60)]
    frac = sum(1 for p in proposals if p == 64) / len(proposals)
    assert frac > 0.5, frac


def test_mixed_space_and_determinism():
    spaces = [_Uniform(0, 1), _Choice(["a", "b"]), _QUniform(0, 100, 10),
              _LogUniform(1e-4, 1e-1)]
    trials = _trials(
        spaces,
        lambda p: p[0] + (0.0 if p[1] == "b" else 1.0) + abs(p[2] - 50) / 100,
        n=30,
    )
    sampler = TPESampler(spaces)
    a = sampler.suggest(trials, random.Random(7))
    b = sampler.suggest(trials, random.Random(7))
    assert a == b  # same rng state → same proposal
    assert a[1] in ("a", "b")
    assert a[2] % 10 == 0
