"""Keras mixed_bfloat16 policy through the compiled engine.

On TPU, bfloat16 compute is the MXU-native path; the engine must train
mixed-precision models (bf16 compute, f32 variables) unchanged.
"""

import numpy as np
import pytest


def test_mixed_bfloat16_policy(toy_classification):
    import keras

    from elephas_tpu.models import KerasModelAdapter
    from elephas_tpu.parallel import CompiledTrainer, build_mesh

    x, y = toy_classification
    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    try:
        m = keras.Sequential(
            [keras.layers.Dense(32, activation="relu"),
             keras.layers.Dense(3, activation="softmax")]
        )
        m.build((None, 10))
        m.compile("adam", "categorical_crossentropy", metrics=["accuracy"])
        assert m.layers[0].compute_dtype == "bfloat16"
        assert m.layers[0].variable_dtype == "float32"
        trainer = CompiledTrainer(
            KerasModelAdapter(m), build_mesh(4), mode="synchronous"
        )
        res = trainer.fit(
            [(x[i::4], y[i::4]) for i in range(4)], epochs=4, batch_size=16,
            validation_split=0.0,
        )
        assert res.history["loss"][-1] < res.history["loss"][0]
        assert all(np.isfinite(v) for v in res.history["loss"])
    finally:
        keras.mixed_precision.set_global_policy("float32")
