"""Distributed hyperparameter search tests (reference: tests/test_hyperparam.py)."""

import numpy as np

from elephas_tpu import HyperParamModel
from elephas_tpu.hyperparam import STATUS_OK, VotingModel, choice, uniform


def data():
    rng = np.random.default_rng(5)
    x = rng.normal(size=(256, 8)).astype("float32")
    w = rng.normal(size=(8, 2))
    y = np.eye(2, dtype="float32")[(x @ w).argmax(1)]
    return x[:192], y[:192], x[192:], y[192:]


def model(x_train, y_train, x_test, y_test):
    import keras

    m = keras.Sequential(
        [
            keras.layers.Dense({{choice([8, 16, 32])}}, activation="relu"),
            keras.layers.Dropout({{uniform(0.0, 0.3)}}),
            keras.layers.Dense(2, activation="softmax"),
        ]
    )
    m.build((None, 8))
    m.compile(optimizer="adam", loss="categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x_train, y_train, epochs=3, batch_size=32, verbose=0)
    loss, acc = m.evaluate(x_test, y_test, verbose=0)
    return {"loss": -acc, "status": STATUS_OK, "model": m}


def test_minimize_returns_trained_model(spark_context):
    hp = HyperParamModel(spark_context, num_workers=2)
    best = hp.minimize(model=model, data=data, max_evals=2)
    x_tr, y_tr, x_te, y_te = data()
    preds = best.predict(x_te, verbose=0)
    acc = float((preds.argmax(1) == y_te.argmax(1)).mean())
    assert acc > 0.5, f"best model accuracy too low: {acc}"


def test_compute_trials_counts(spark_context):
    hp = HyperParamModel(spark_context, num_workers=2)
    trials = hp.compute_trials(model=model, data=data, max_evals=2)
    assert len(trials) == 4  # num_workers * max_evals
    assert all(t["status"] == STATUS_OK for t in trials)
    # sampled hyperparameters recorded, within their spaces
    for t in trials:
        assert t["params"][0] in (8, 16, 32)
        assert 0.0 <= t["params"][1] <= 0.3


def test_workers_pinned_to_disjoint_devices(spark_context):
    """Mesh-slice fan-out (SURVEY §7.1.5): with 4 workers on the 8-device
    mesh, each worker's trials must land on its OWN device — not all on
    device 0 (the pre-fix behavior, which serialized every concurrent
    trial on one chip). Wall-clock speedup itself is not measurable on
    this single-core CI box (8 virtual devices share one core); on real
    multi-chip hardware the pinned devices compute concurrently."""
    hp = HyperParamModel(spark_context, num_workers=4)
    trials = hp.compute_trials(model=model, data=data, max_evals=1)
    assert len(trials) == 4
    devices = {t["device"] for t in trials}
    assert len(devices) == 4, f"workers shared devices: {sorted(devices)}"


def test_voting_model(spark_context):
    hp = HyperParamModel(spark_context, num_workers=2)
    ensemble = hp.best_models(nb_models=2, model=model, data=data, max_evals=2)
    assert isinstance(ensemble, VotingModel)
    x_tr, y_tr, x_te, y_te = data()
    preds = ensemble.predict(x_te)
    assert preds.shape == (64, 2)
    classes = ensemble.predict_classes(x_te)
    assert set(np.unique(classes)).issubset({0, 1})
