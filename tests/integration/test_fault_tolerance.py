"""End-to-end fault tolerance: injected executor crashes during ``fit``.

The reference has no failure handling of its own — it inherits Spark task
retry, under which its async path double-applies deltas (SURVEY.md §5.3).
These tests inject crashes into the host-path workers and assert (a) the job
survives via task retry, and (b) a crashed async attempt's partial pushes are
rolled back server-side, so even a *poison* delta pushed right before the
crash cannot corrupt the final weights.
"""

import numpy as np
import pytest

from elephas_tpu import SparkModel
from elephas_tpu.data import TaskContext
from elephas_tpu.utils import to_simple_rdd
from elephas_tpu.worker import AsynchronousSparkWorker, SparkWorker

pytestmark = pytest.mark.slow


def _ps_backends():
    from elephas_tpu.parameter.native import native_available

    return [
        "http", "socket",
        pytest.param("native", marks=pytest.mark.skipif(
            not native_available(), reason="native toolchain unavailable")),
    ]


@pytest.mark.parametrize("ps_mode", _ps_backends())
def test_async_retry_rolls_back_partial_pushes(
    spark_context, toy_classification, classifier_factory, monkeypatch,
    ps_mode,
):
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y, num_slices=2)
    model = classifier_factory()
    init_weights = [np.array(w) for w in model.get_weights()]

    orig_train = AsynchronousSparkWorker.train
    crashes = {"n": 0}

    def flaky_train(self, iterator):
        ctx = TaskContext.get()
        if ctx is not None and ctx.partitionId() == 0 and ctx.attemptNumber() == 0:
            # Simulate an executor that registers, pushes a *poison* partial
            # update, then dies. Rollback must erase the poison entirely.
            # the same stage-scoped id the real worker registers under —
            # rollback only fires when the retry re-registers THIS id
            from elephas_tpu.worker import task_id_for

            tid = task_id_for(ctx)
            assert self.client.register_attempt(tid, ctx.attemptNumber())
            poison = [np.full_like(w, 1e6) for w in self.client.get_parameters()]
            self.client.update_parameters_tagged(tid, poison)
            crashes["n"] += 1
            raise RuntimeError("injected executor crash after partial push")
        yield from orig_train(self, iterator)

    monkeypatch.setattr(AsynchronousSparkWorker, "train", flaky_train)

    spark_model = SparkModel(
        model, mode="asynchronous", frequency="epoch",
        parameter_server_mode=ps_mode, num_workers=2, port=0,
    )
    spark_model.fit(rdd, epochs=2, batch_size=32, verbose=0, validation_split=0.0)

    assert crashes["n"] == 1
    final = spark_model.master_network.get_weights()
    # Poison delta was 1e6 per element; any surviving trace would dominate.
    assert max(float(np.abs(w).max()) for w in final) < 1e3
    # And training actually happened (weights moved off the broadcast start).
    moved = sum(
        float(np.abs(a - b).sum()) for a, b in zip(final, init_weights)
    )
    assert moved > 0


def test_async_retry_without_attempt_api_fails_fast(
    spark_context, toy_classification, classifier_factory, monkeypatch
):
    """Clients without the attempt API (a pre-extension remote server) must
    not silently double-apply under retry — the retried attempt aborts."""
    from elephas_tpu.data import TaskFailedError
    from elephas_tpu.parameter.client import HttpClient

    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y, num_slices=2)

    monkeypatch.setattr(
        HttpClient, "register_attempt", lambda self, t, a: False
    )
    orig_train = AsynchronousSparkWorker.train
    crashes = {"n": 0}

    def flaky_train(self, iterator):
        ctx = TaskContext.get()
        if ctx is not None and ctx.partitionId() == 0 and ctx.attemptNumber() == 0:
            crashes["n"] += 1
            raise RuntimeError("injected executor crash")
        yield from orig_train(self, iterator)

    monkeypatch.setattr(AsynchronousSparkWorker, "train", flaky_train)

    spark_model = SparkModel(
        classifier_factory(), mode="asynchronous", frequency="epoch",
        parameter_server_mode="http", num_workers=2, port=0,
    )
    with pytest.raises(TaskFailedError) as e:
        spark_model.fit(rdd, epochs=1, batch_size=32, verbose=0,
                        validation_split=0.0)
    assert "not safe without the parameter server attempt API" in str(e.value.cause)
    assert crashes["n"] == 1


def test_sync_retry_is_naturally_idempotent(
    spark_context, toy_classification, classifier_factory, monkeypatch
):
    """Sync deltas travel via collect(); a retried task re-yields, nothing
    server-side to undo. Crash attempt 0 of one partition, expect clean fit."""
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y, num_slices=2)
    model = classifier_factory()

    orig_train = SparkWorker.train
    crashes = {"n": 0}

    def flaky_train(self, iterator):
        ctx = TaskContext.get()
        if ctx is not None and ctx.partitionId() == 1 and ctx.attemptNumber() == 0:
            crashes["n"] += 1
            raise RuntimeError("injected executor crash")
        yield from orig_train(self, iterator)

    monkeypatch.setattr(SparkWorker, "train", flaky_train)

    spark_model = SparkModel(
        model, mode="synchronous", num_workers=2, comm="host",
    )
    spark_model.fit(rdd, epochs=1, batch_size=32, verbose=0, validation_split=0.0)

    assert crashes["n"] == 1
    history = spark_model.training_histories[-1]
    assert np.isfinite(history["loss"][-1])
