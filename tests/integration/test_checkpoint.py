"""Mid-training checkpoint/resume (TPU-build extension over the reference)."""

import numpy as np

from elephas_tpu import SparkModel
from elephas_tpu.utils import to_simple_rdd
from elephas_tpu.utils.checkpoint import has_checkpoint, load_checkpoint

from ..conftest import make_classifier


def test_checkpoint_and_resume(tmp_path, spark_context, toy_classification):
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    ckpt = str(tmp_path / "ckpt")

    model = make_classifier()
    sm = SparkModel(model, mode="synchronous", num_workers=4)
    sm.fit(rdd, epochs=4, batch_size=16, validation_split=0.0,
           checkpoint_dir=ckpt, checkpoint_frequency=2)
    assert has_checkpoint(ckpt)
    weights, meta, opt_state = load_checkpoint(ckpt)
    assert meta["epoch"] == 4
    assert opt_state is not None
    for a, b in zip(weights, sm.master_network.get_weights()):
        assert np.allclose(a, b)
    # history covers all 4 epochs across the 2 chunks
    assert len(sm.training_histories[-1]["loss"]) == 4

    # Resume continues from epoch 4 toward 6 (2 more epochs only)
    sm2 = SparkModel(make_classifier(), mode="synchronous", num_workers=4)
    sm2.fit(rdd, epochs=6, batch_size=16, validation_split=0.0,
            checkpoint_dir=ckpt, checkpoint_frequency=2, resume=True)
    assert len(sm2.training_histories[-1]["loss"]) == 2
    _, meta2, _ = load_checkpoint(ckpt)
    assert meta2["epoch"] == 6
    # resumed training continued improving from the checkpoint
    assert sm2.training_histories[-1]["loss"][-1] < sm.training_histories[-1]["loss"][0]


def test_checkpointed_sync_fit_is_merge_faithful(
    tmp_path, spark_context, toy_classification
):
    """Turning on checkpoint_dir must NOT change synchronous-mode semantics:
    the chunked fit carries per-worker weight stacks across chunks and
    merges once, so its final weights equal the uninterrupted fit's."""
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)

    m_plain = make_classifier()
    init = [np.array(w) for w in m_plain.get_weights()]
    plain = SparkModel(m_plain, mode="synchronous", num_workers=4)
    plain.fit(rdd, epochs=4, batch_size=16, validation_split=0.0)

    m_chunk = make_classifier()
    m_chunk.set_weights(init)
    chunked = SparkModel(m_chunk, mode="synchronous", num_workers=4)
    chunked.fit(rdd, epochs=4, batch_size=16, validation_split=0.0,
                checkpoint_dir=str(tmp_path / "ckpt_eq"),
                checkpoint_frequency=1)

    for a, b in zip(plain.master_network.get_weights(),
                    chunked.master_network.get_weights()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # per-epoch (pre-merge) histories line up too
    np.testing.assert_allclose(
        plain.training_histories[-1]["loss"],
        chunked.training_histories[-1]["loss"], rtol=1e-5, atol=1e-6,
    )


def test_sync_resume_reproduces_uninterrupted_fit(
    tmp_path, spark_context, toy_classification
):
    """Kill-and-resume across processes: a sync fit resumed from disk (worker
    stacks reloaded) ends at the same weights as one that never stopped."""
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    ckpt = str(tmp_path / "ckpt_resume")

    m_plain = make_classifier()
    init = [np.array(w) for w in m_plain.get_weights()]
    plain = SparkModel(m_plain, mode="synchronous", num_workers=4)
    plain.fit(rdd, epochs=4, batch_size=16, validation_split=0.0)

    m_first = make_classifier()
    m_first.set_weights(init)
    first = SparkModel(m_first, mode="synchronous", num_workers=4)
    first.fit(rdd, epochs=2, batch_size=16, validation_split=0.0,
              checkpoint_dir=ckpt, checkpoint_frequency=2)
    # "crash": a NEW SparkModel resumes epochs 2..4 from the checkpoint
    second = SparkModel(make_classifier(), mode="synchronous", num_workers=4)
    second.fit(rdd, epochs=4, batch_size=16, validation_split=0.0,
               checkpoint_dir=ckpt, checkpoint_frequency=2, resume=True)

    for a, b in zip(plain.master_network.get_weights(),
                    second.master_network.get_weights()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_resume_with_stale_worker_state_warns_and_restarts_stacks(
    tmp_path, spark_context, toy_classification
):
    """A crash between the worker_state and meta writes leaves mismatched
    epoch stamps; resume must warn and fall back to fresh stacks, not
    silently continue from the wrong per-worker state."""
    import warnings

    from elephas_tpu.utils.checkpoint import load_pytree, save_pytree

    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    ckpt = str(tmp_path / "ckpt_stale")

    sm = SparkModel(make_classifier(), mode="synchronous", num_workers=4)
    sm.fit(rdd, epochs=2, batch_size=16, validation_split=0.0,
           checkpoint_dir=ckpt, checkpoint_frequency=2)
    # corrupt the stamp to simulate the torn write
    ws_path = str(tmp_path / "ckpt_stale" / "worker_state")
    ws = load_pytree(ws_path)
    ws["epoch"] = np.int64(999)
    save_pytree(ws_path, ws)

    sm2 = SparkModel(make_classifier(), mode="synchronous", num_workers=4)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        sm2.fit(rdd, epochs=4, batch_size=16, validation_split=0.0,
                checkpoint_dir=ckpt, checkpoint_frequency=2, resume=True)
    assert any("worker_state" in str(w.message) for w in caught)
    # and training still completed the remaining epochs
    assert len(sm2.training_histories[-1]["loss"]) == 2


def test_timings_recorded(spark_context, toy_classification):
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    sm = SparkModel(make_classifier(), mode="synchronous", num_workers=4)
    sm.fit(rdd, epochs=1, batch_size=16, validation_split=0.0)
    assert sm.timings and sm.timings[-1]["samples_per_sec"] > 0


def test_trainer_reused_across_fits(spark_context, toy_classification):
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    sm = SparkModel(make_classifier(), mode="synchronous", num_workers=4)
    sm.fit(rdd, epochs=1, batch_size=16, validation_split=0.0)
    t1 = sm._jax_trainer
    sm.fit(rdd, epochs=1, batch_size=16, validation_split=0.0)
    assert sm._jax_trainer is t1  # compile cache survives across fits
