"""Mid-training checkpoint/resume (TPU-build extension over the reference)."""

import numpy as np

from elephas_tpu import SparkModel
from elephas_tpu.utils import to_simple_rdd
from elephas_tpu.utils.checkpoint import has_checkpoint, load_checkpoint

from ..conftest import make_classifier


def test_checkpoint_and_resume(tmp_path, spark_context, toy_classification):
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    ckpt = str(tmp_path / "ckpt")

    model = make_classifier()
    sm = SparkModel(model, mode="synchronous", num_workers=4)
    sm.fit(rdd, epochs=4, batch_size=16, validation_split=0.0,
           checkpoint_dir=ckpt, checkpoint_frequency=2)
    assert has_checkpoint(ckpt)
    weights, meta, opt_state = load_checkpoint(ckpt)
    assert meta["epoch"] == 4
    assert opt_state is not None
    for a, b in zip(weights, sm.master_network.get_weights()):
        assert np.allclose(a, b)
    # history covers all 4 epochs across the 2 chunks
    assert len(sm.training_histories[-1]["loss"]) == 4

    # Resume continues from epoch 4 toward 6 (2 more epochs only)
    sm2 = SparkModel(make_classifier(), mode="synchronous", num_workers=4)
    sm2.fit(rdd, epochs=6, batch_size=16, validation_split=0.0,
            checkpoint_dir=ckpt, checkpoint_frequency=2, resume=True)
    assert len(sm2.training_histories[-1]["loss"]) == 2
    _, meta2, _ = load_checkpoint(ckpt)
    assert meta2["epoch"] == 6
    # resumed training continued improving from the checkpoint
    assert sm2.training_histories[-1]["loss"][-1] < sm.training_histories[-1]["loss"][0]


def test_timings_recorded(spark_context, toy_classification):
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    sm = SparkModel(make_classifier(), mode="synchronous", num_workers=4)
    sm.fit(rdd, epochs=1, batch_size=16, validation_split=0.0)
    assert sm.timings and sm.timings[-1]["samples_per_sec"] > 0


def test_trainer_reused_across_fits(spark_context, toy_classification):
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    sm = SparkModel(make_classifier(), mode="synchronous", num_workers=4)
    sm.fit(rdd, epochs=1, batch_size=16, validation_split=0.0)
    t1 = sm._jax_trainer
    sm.fit(rdd, epochs=1, batch_size=16, validation_split=0.0)
    assert sm._jax_trainer is t1  # compile cache survives across fits
