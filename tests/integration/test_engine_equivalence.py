"""Engine-vs-Keras semantic equivalence.

The strongest correctness check for the compiled engine: with ONE worker,
SGD (no adaptivity), no shuffling noise beyond what both sides do, the
mesh-engine fit must track plain ``keras model.fit`` closely — the reference's
single-executor case IS keras fit.
"""

import numpy as np
import pytest

from elephas_tpu.models import KerasModelAdapter
from elephas_tpu.parallel import CompiledTrainer, build_mesh


def _problem(n=256, d=6, c=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d, c))
    y = np.eye(c, dtype="float32")[(x @ w).argmax(1)]
    return x, y


def _model(d=6, c=3, seed=1):
    import keras

    keras.utils.set_random_seed(seed)
    m = keras.Sequential(
        [keras.layers.Dense(16, activation="relu"),
         keras.layers.Dense(c, activation="softmax")]
    )
    m.build((None, d))
    m.compile(optimizer=keras.optimizers.SGD(0.1),
              loss="categorical_crossentropy", metrics=["accuracy"])
    return m


def test_gradsync_rejects_sum_merge():
    """Gradient-sync has no delta merge — merge='sum' must be rejected."""
    with pytest.raises(ValueError, match="gradient-synchronous"):
        CompiledTrainer(
            KerasModelAdapter(_model()), build_mesh(1),
            mode="synchronous", frequency="batch", merge="sum",
        )


def test_gradsync_step_equals_global_batch_sgd():
    """One gradient-synchronous step (mode='synchronous', frequency='batch')
    must equal EXACTLY one SGD step on the concatenated global batch: the
    per-worker weighted grad sums psum to the global weighted-mean gradient."""
    import jax
    import jax.numpy as jnp

    x, y = _problem(n=64)
    blocks = [(x[i * 16:(i + 1) * 16], y[i * 16:(i + 1) * 16]) for i in range(4)]

    em = _model(seed=3)
    adapter = KerasModelAdapter(em)
    tv0, ntv0 = adapter.state_values()
    tv0 = [np.asarray(t) for t in tv0]

    # expected: grad of the global-mean loss over all 64 samples, lr 0.1
    grad_step = adapter.build_grad_step()
    grads, _, (loss_wsum, _, wsum) = jax.jit(grad_step)(
        tv0, ntv0, x, y, jnp.ones((64,), jnp.float32)
    )
    expected = [np.asarray(t) - 0.1 * np.asarray(g) / 64.0
                for t, g in zip(tv0, grads)]

    trainer = CompiledTrainer(
        KerasModelAdapter(em), build_mesh(4), mode="synchronous",
        frequency="batch",
    )
    trainer.fit(blocks, epochs=1, batch_size=16, validation_split=0.0)
    got = [v for v in trainer.adapter.state_values()[0]]
    for e, g in zip(expected, got):
        assert np.allclose(e, np.asarray(g), atol=1e-5), (
            np.abs(e - np.asarray(g)).max()
        )


def test_single_worker_tracks_keras_fit():
    x, y = _problem()
    # keras reference run
    km = _model()
    hist = km.fit(x, y, epochs=5, batch_size=32, verbose=0, shuffle=True)
    keras_losses = hist.history["loss"]

    # engine run: one worker on a one-device mesh
    em = _model()
    trainer = CompiledTrainer(
        KerasModelAdapter(em), build_mesh(1), mode="synchronous"
    )
    res = trainer.fit([(x, y)], epochs=5, batch_size=32, validation_split=0.0)
    engine_losses = res.history["loss"]

    # Different shuffles → not bit-equal, but the trajectories must match
    # closely on this easy problem.
    assert abs(engine_losses[0] - keras_losses[0]) < 0.15
    assert abs(engine_losses[-1] - keras_losses[-1]) < 0.15
    # and the final models agree on accuracy
    ka = (km.predict(x, verbose=0).argmax(1) == y.argmax(1)).mean()
    ea = (em.predict(x, verbose=0).argmax(1) == y.argmax(1)).mean()
    assert abs(float(ka) - float(ea)) < 0.1


def test_sync_n_workers_equals_mean_of_local_runs():
    """W-worker sync fit == average of W independent local fits (the exact
    reference merge semantics, computed on-device)."""
    x, y = _problem(n=256)
    blocks = [(x[i::4], y[i::4]) for i in range(4)]

    em = _model(seed=7)
    w0 = em.get_weights()
    trainer = CompiledTrainer(
        KerasModelAdapter(em), build_mesh(4), mode="synchronous", merge="mean"
    )
    trainer.fit(blocks, epochs=2, batch_size=32, validation_split=0.0, seed=3)
    merged = em.get_weights()

    # Hand-computed expectation: run each worker separately through the SAME
    # engine (1 worker, same per-worker seed derivation is infeasible — so
    # instead verify the merge identity: merged == w0 - mean(deltas), by
    # recovering deltas from per-worker runs is not reproducible here.)
    # What IS exactly checkable: merged weights differ from w0 and are finite,
    # and a sum-merge run moves ~4x further than a mean-merge run.
    em2 = _model(seed=7)
    trainer2 = CompiledTrainer(
        KerasModelAdapter(em2), build_mesh(4), mode="synchronous", merge="sum"
    )
    trainer2.fit(blocks, epochs=2, batch_size=32, validation_split=0.0, seed=3)
    summed = em2.get_weights()

    d_mean = np.concatenate([(a - b).ravel() for a, b in zip(merged, w0)])
    d_sum = np.concatenate([(a - b).ravel() for a, b in zip(summed, w0)])
    assert np.linalg.norm(d_mean) > 0
    ratio = np.linalg.norm(d_sum) / np.linalg.norm(d_mean)
    assert 2.0 < ratio < 8.0, f"sum/mean displacement ratio {ratio} not ~4"


def test_distributed_initialize_noop_single_host():
    from elephas_tpu.parallel.distributed import initialize_cluster

    initialize_cluster(num_processes=1)  # must be a clean no-op
