"""Test fixtures.

Mirrors the reference's test strategy (SURVEY.md §4): a real local "cluster"
fixture (here: an 8-device CPU mesh via ``--xla_force_host_platform_device_count``,
the JAX analog of Spark ``local[8]``), small Keras model factories, and tiny
synthetic datasets.

IMPORTANT environment note: run tests with the axon TPU registration disabled
and CPU forced, or the sitecustomize TPU claim serializes every python
process::

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 KERAS_BACKEND=jax \
    python -m pytest tests/ -x -q

(`make test` does exactly this.) The settings below are a best-effort fallback
for when jax has not yet initialized a backend.
"""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import re
import sys

import numpy as np
import pytest

# The 8-device CPU mesh has one known flake: XLA's CPU collective rendezvous
# can starve in long tight loops (CollectivePermute timeout / rendezvous
# deadlock — see docs/DISTRIBUTED.md). Tests keep step counts small to avoid
# it, but the harness must not rely on that convention alone: a failure whose
# output matches the signature is retried ONCE. Anything else fails normally
# — this must never mask a real bug, so the pattern is deliberately narrow.
_COLLECTIVE_FLAKE = re.compile(
    r"CollectivePermute"
    r"|[Rr]endezvous.{0,120}(tim(e|ed)[ -]?out|abort|deadlock|starv)"
    r"|(tim(e|ed)[ -]?out|deadlock|starv\w*).{0,120}[Rr]endezvous",
    re.DOTALL,
)


def pytest_runtest_protocol(item, nextitem):
    from _pytest.runner import runtestprotocol

    hook = item.ihook
    hook.pytest_runtest_logstart(nodeid=item.nodeid, location=item.location)
    reports = runtestprotocol(item, nextitem=nextitem, log=False)
    if any(
        r.when == "call" and r.failed
        and _COLLECTIVE_FLAKE.search(str(r.longrepr))
        for r in reports
    ):
        sys.stderr.write(
            f"\n[conftest] known CPU-collective rendezvous flake in "
            f"{item.nodeid}; retrying once\n"
        )
        reports = runtestprotocol(item, nextitem=nextitem, log=False)
    for report in reports:
        hook.pytest_runtest_logreport(report=report)
    hook.pytest_runtest_logfinish(nodeid=item.nodeid, location=item.location)
    return True


@pytest.fixture(scope="session")
def spark_context():
    from elephas_tpu.data import SparkContext

    sc = SparkContext(master="local[8]", appName="elephas-tpu-tests")
    yield sc
    sc.stop()


@pytest.fixture(scope="session")
def spark_session():
    from elephas_tpu.data import SparkSession

    session = SparkSession.builder.master("local[8]").appName("tests").getOrCreate()
    yield session


@pytest.fixture(scope="session")
def toy_classification():
    """Linearly-separable-ish 3-class problem: (X [640,10], Y one-hot [640,3])."""
    rng = np.random.default_rng(42)
    n, d, c = 640, 10, 3
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d, c))
    y = np.eye(c, dtype="float32")[(x @ w).argmax(axis=1)]
    return x, y


@pytest.fixture(scope="session")
def toy_regression():
    rng = np.random.default_rng(7)
    n, d = 512, 8
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d,))
    y = (x @ w + 0.05 * rng.normal(size=(n,))).astype("float32")
    return x, y


def make_classifier(input_dim=10, nb_classes=3, hidden=32, optimizer="adam"):
    import keras

    model = keras.Sequential(
        [
            keras.layers.Dense(hidden, activation="relu"),
            keras.layers.Dense(nb_classes, activation="softmax"),
        ]
    )
    model.build((None, input_dim))
    model.compile(
        optimizer=optimizer, loss="categorical_crossentropy", metrics=["accuracy"]
    )
    return model


def make_regressor(input_dim=8, hidden=16):
    import keras

    model = keras.Sequential(
        [keras.layers.Dense(hidden, activation="relu"), keras.layers.Dense(1)]
    )
    model.build((None, input_dim))
    model.compile(optimizer="adam", loss="mse")
    return model


@pytest.fixture
def classifier_factory():
    return make_classifier


@pytest.fixture
def regressor_factory():
    return make_regressor
