"""Test fixtures.

Mirrors the reference's test strategy (SURVEY.md §4): a real local "cluster"
fixture (here: an 8-device CPU mesh via ``--xla_force_host_platform_device_count``,
the JAX analog of Spark ``local[8]``), small Keras model factories, and tiny
synthetic datasets.

IMPORTANT environment note: run tests with the axon TPU registration disabled
and CPU forced, or the sitecustomize TPU claim serializes every python
process::

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 KERAS_BACKEND=jax \
    python -m pytest tests/ -x -q

(`make test` does exactly this.) The settings below are a best-effort fallback
for when jax has not yet initialized a backend.
"""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def spark_context():
    from elephas_tpu.data import SparkContext

    sc = SparkContext(master="local[8]", appName="elephas-tpu-tests")
    yield sc
    sc.stop()


@pytest.fixture(scope="session")
def spark_session():
    from elephas_tpu.data import SparkSession

    session = SparkSession.builder.master("local[8]").appName("tests").getOrCreate()
    yield session


@pytest.fixture(scope="session")
def toy_classification():
    """Linearly-separable-ish 3-class problem: (X [640,10], Y one-hot [640,3])."""
    rng = np.random.default_rng(42)
    n, d, c = 640, 10, 3
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d, c))
    y = np.eye(c, dtype="float32")[(x @ w).argmax(axis=1)]
    return x, y


@pytest.fixture(scope="session")
def toy_regression():
    rng = np.random.default_rng(7)
    n, d = 512, 8
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d,))
    y = (x @ w + 0.05 * rng.normal(size=(n,))).astype("float32")
    return x, y


def make_classifier(input_dim=10, nb_classes=3, hidden=32, optimizer="adam"):
    import keras

    model = keras.Sequential(
        [
            keras.layers.Dense(hidden, activation="relu"),
            keras.layers.Dense(nb_classes, activation="softmax"),
        ]
    )
    model.build((None, input_dim))
    model.compile(
        optimizer=optimizer, loss="categorical_crossentropy", metrics=["accuracy"]
    )
    return model


def make_regressor(input_dim=8, hidden=16):
    import keras

    model = keras.Sequential(
        [keras.layers.Dense(hidden, activation="relu"), keras.layers.Dense(1)]
    )
    model.build((None, input_dim))
    model.compile(optimizer="adam", loss="mse")
    return model


@pytest.fixture
def classifier_factory():
    return make_classifier


@pytest.fixture
def regressor_factory():
    return make_regressor
