"""Test fixtures.

Mirrors the reference's test strategy (SURVEY.md §4): a real local "cluster"
fixture (here: an 8-device CPU mesh via ``--xla_force_host_platform_device_count``,
the JAX analog of Spark ``local[8]``), small Keras model factories, and tiny
synthetic datasets.

IMPORTANT environment note: run tests with the axon TPU registration disabled
and CPU forced, or the sitecustomize TPU claim serializes every python
process::

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 KERAS_BACKEND=jax \
    python -m pytest tests/ -x -q

(`make test` does exactly this.) The settings below are a best-effort fallback
for when jax has not yet initialized a backend.
"""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import faulthandler
import re
import sys
import threading

import numpy as np
import pytest

# The 8-device CPU mesh has one known flake: XLA's CPU collective rendezvous
# can starve in long tight loops (CollectivePermute timeout / rendezvous
# deadlock — see docs/DISTRIBUTED.md). Tests keep step counts small to avoid
# it, but the harness must not rely on that convention alone: a failure whose
# output matches the signature is retried ONCE. Anything else fails normally
# — this must never mask a real bug, so the pattern is deliberately narrow.
_COLLECTIVE_FLAKE = re.compile(
    r"CollectivePermute"
    r"|[Rr]endezvous.{0,120}(tim(e|ed)[ -]?out|abort|deadlock|starv)"
    r"|(tim(e|ed)[ -]?out|deadlock|starv\w*).{0,120}[Rr]endezvous",
    re.DOTALL,
)

# Flake-retry accounting: the retry must never silently mask a RISING flake
# rate (a newly introduced intermittent deadlock pattern-matches the flake
# signature). Every retry is counted and reported in the terminal summary;
# past ELEPHAS_MAX_FLAKE_RETRIES (default 5) the run FAILS even if every
# retried test eventually passed.
_flake_retries: list = []  # nodeids that hit the retry path

# Per-test hang watchdog. A starved CPU-collective rendezvous does not
# always error out — it can wedge the process, and pytest (single-process,
# no pytest-timeout in this image) would sit until the CI job bound.
# A timer thread converts the hang into a fast, attributable failure: dump
# every thread's stack, record the culprit nodeid in ELEPHAS_WATCHDOG_FILE,
# and hard-exit with code 42 (scripts/run_tests.sh reruns the suite once and
# deselects the test if it hangs twice). A blocked XLA collective cannot be
# interrupted from Python, so killing the process is the only honest option.
# Override per test with @pytest.mark.timeout(seconds) for legitimately slow
# tests, or globally with ELEPHAS_TEST_TIMEOUT (0 disables). The default is
# sized from the measured suite profile (slowest non-example test ≈ 70s
# locally) with ~4x headroom for slower CI runners — a real hang still
# surfaces in minutes, not the job bound.
_WATCHDOG_DEFAULT = float(os.environ.get("ELEPHAS_TEST_TIMEOUT", "300"))
_WATCHDOG_EXIT_CODE = 42


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "timeout(seconds): per-test hang-watchdog bound (conftest watchdog, "
        "not pytest-timeout)",
    )


def _watchdog_abort(nodeid: str, seconds: float) -> None:
    # pytest's capture machinery owns stderr and os._exit skips its flush, so
    # anything written there is lost. The watchdog file (read and echoed by
    # scripts/run_tests.sh) is the one channel guaranteed to survive: nodeid
    # on line 1, full all-thread stack dump after it.
    msg = (
        f"[conftest] WATCHDOG: {nodeid} still running after {seconds:.0f}s "
        f"— dumping stacks and aborting the process (exit "
        f"{_WATCHDOG_EXIT_CODE})\n"
    )
    path = os.environ.get("ELEPHAS_WATCHDOG_FILE")
    if path:
        try:
            with open(path, "w") as f:
                f.write(nodeid + "\n" + msg)
                faulthandler.dump_traceback(file=f)
        except OSError:
            pass
    try:
        os.write(2, ("\n" + msg).encode())  # best effort if fd 2 is a tty
    except OSError:
        pass
    os._exit(_WATCHDOG_EXIT_CODE)


def pytest_runtest_protocol(item, nextitem):
    from _pytest.runner import runtestprotocol

    hook = item.ihook
    hook.pytest_runtest_logstart(nodeid=item.nodeid, location=item.location)

    marker = item.get_closest_marker("timeout")
    if marker:  # positional or pytest-timeout-style seconds= keyword
        seconds = float(
            marker.args[0] if marker.args
            else marker.kwargs.get("seconds", _WATCHDOG_DEFAULT)
        )
    else:
        seconds = _WATCHDOG_DEFAULT

    def run_once():
        if seconds > 0:
            timer = threading.Timer(
                seconds, _watchdog_abort, args=(item.nodeid, seconds))
            timer.daemon = True
            timer.start()
            try:
                return runtestprotocol(item, nextitem=nextitem, log=False)
            finally:
                timer.cancel()
        return runtestprotocol(item, nextitem=nextitem, log=False)

    reports = run_once()
    if any(
        r.when == "call" and r.failed
        and _COLLECTIVE_FLAKE.search(str(r.longrepr))
        for r in reports
    ):
        _flake_retries.append(item.nodeid)
        sys.stderr.write(
            f"\n[conftest] known CPU-collective rendezvous flake in "
            f"{item.nodeid}; retrying once "
            f"(retry #{len(_flake_retries)} this run)\n"
        )
        reports = run_once()
    for report in reports:
        hook.pytest_runtest_logreport(report=report)
    hook.pytest_runtest_logfinish(nodeid=item.nodeid, location=item.location)
    return True


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    if _flake_retries:
        terminalreporter.write_sep(
            "=", f"collective-flake retries: {len(_flake_retries)}")
        for nodeid in _flake_retries:
            terminalreporter.write_line(f"  retried: {nodeid}")


def pytest_sessionfinish(session, exitstatus):
    max_retries = int(os.environ.get("ELEPHAS_MAX_FLAKE_RETRIES", "5"))
    if len(_flake_retries) > max_retries and session.exitstatus == 0:
        sys.stderr.write(
            f"\n[conftest] {len(_flake_retries)} flake retries fired this "
            f"run (> ELEPHAS_MAX_FLAKE_RETRIES={max_retries}) — the flake "
            f"rate is rising; failing the run so it gets looked at\n"
        )
        session.exitstatus = 1


@pytest.fixture(scope="session")
def spark_context():
    from elephas_tpu.data import SparkContext

    sc = SparkContext(master="local[8]", appName="elephas-tpu-tests")
    yield sc
    sc.stop()


@pytest.fixture(scope="session")
def spark_session():
    from elephas_tpu.data import SparkSession

    session = SparkSession.builder.master("local[8]").appName("tests").getOrCreate()
    yield session


@pytest.fixture(scope="session")
def toy_classification():
    """Linearly-separable-ish 3-class problem: (X [640,10], Y one-hot [640,3])."""
    rng = np.random.default_rng(42)
    n, d, c = 640, 10, 3
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d, c))
    y = np.eye(c, dtype="float32")[(x @ w).argmax(axis=1)]
    return x, y


@pytest.fixture(scope="session")
def toy_regression():
    rng = np.random.default_rng(7)
    n, d = 512, 8
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d,))
    y = (x @ w + 0.05 * rng.normal(size=(n,))).astype("float32")
    return x, y


def make_classifier(input_dim=10, nb_classes=3, hidden=32, optimizer="adam"):
    import keras

    model = keras.Sequential(
        [
            keras.layers.Dense(hidden, activation="relu"),
            keras.layers.Dense(nb_classes, activation="softmax"),
        ]
    )
    model.build((None, input_dim))
    model.compile(
        optimizer=optimizer, loss="categorical_crossentropy", metrics=["accuracy"]
    )
    return model


def make_regressor(input_dim=8, hidden=16):
    import keras

    model = keras.Sequential(
        [keras.layers.Dense(hidden, activation="relu"), keras.layers.Dense(1)]
    )
    model.build((None, input_dim))
    model.compile(optimizer="adam", loss="mse")
    return model


@pytest.fixture
def classifier_factory():
    return make_classifier


@pytest.fixture
def regressor_factory():
    return make_regressor
