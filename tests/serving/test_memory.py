"""Paged serving memory, host side: block allocator invariants under
random churn, radix prefix-cache semantics, and PagedKVCache page
bookkeeping (the device programs are pinned in test_paged.py).

The fuzz test is the subsystem's safety net: after EVERY operation of a
random alloc/incref/decref/adopt/register/release/evict schedule, the
allocator's ``check()`` must hold — no negative refcounts, no leaked
pages, no page both free and owned — and refcounts must equal exactly
the references the test itself holds."""

from collections import Counter

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models.transformer import TransformerLM
from elephas_tpu.serving import (BlockAllocator, PagedKVCache,
                                 PagesExhausted, RadixPrefixCache)

pytestmark = pytest.mark.serving

V = 17


def _model(**kw):
    cfg = dict(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=48)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=1):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


# -- block allocator ------------------------------------------------------

def test_allocator_basic_lifecycle():
    alloc = BlockAllocator(n_partitions=2, pages_per_partition=4)
    assert alloc.free_count(0) == 3          # page 0 is the pinned trash
    lid = alloc.alloc(0)
    assert lid != 0 and alloc.refcount(0, lid) == 1
    alloc.incref(0, lid)
    assert alloc.refcount(0, lid) == 2
    alloc.decref(0, lid)
    alloc.decref(0, lid)
    assert alloc.free_count(0) == 3          # back on the free list
    alloc.check()


def test_allocator_exhaustion_and_misuse():
    alloc = BlockAllocator(n_partitions=1, pages_per_partition=3)
    a, b = alloc.alloc(0), alloc.alloc(0)
    with pytest.raises(PagesExhausted) as ei:
        alloc.alloc(0)
    assert ei.value.partition == 0 and ei.value.shortfall == 1
    with pytest.raises(ValueError):
        alloc.incref(0, 0)                   # trash page is untouchable
    with pytest.raises(ValueError):
        alloc.decref(0, 0)
    alloc.decref(0, a)
    with pytest.raises(ValueError):
        alloc.decref(0, a)                   # double free
    alloc.decref(0, b)
    alloc.check()
    with pytest.raises(ValueError):
        BlockAllocator(n_partitions=0, pages_per_partition=2)
    with pytest.raises(ValueError):
        BlockAllocator(n_partitions=1, pages_per_partition=1)


def test_allocator_fuzz_invariants_after_every_op():
    """Random alloc/incref/decref churn, ``check()`` + exact refcount
    accounting after EVERY operation."""
    rng = np.random.default_rng(0)
    parts, pages = 3, 9
    alloc = BlockAllocator(n_partitions=parts, pages_per_partition=pages)
    held = []                                # one entry per reference we own
    for _ in range(2500):
        op = int(rng.integers(0, 3))
        if op == 0:
            part = int(rng.integers(0, parts))
            try:
                held.append((part, alloc.alloc(part)))
            except PagesExhausted as e:
                assert e.partition == part
                assert alloc.free_count(part) == 0
        elif op == 1 and held:
            part, lid = held[int(rng.integers(len(held)))]
            alloc.incref(part, lid)
            held.append((part, lid))
        elif op == 2 and held:
            part, lid = held.pop(int(rng.integers(len(held))))
            alloc.decref(part, lid)
        alloc.check()
        counts = Counter(held)
        for p in range(parts):
            live = 0
            for lid in range(1, pages):
                ref = alloc.refcount(p, lid)
                assert ref == counts.get((p, lid), 0)
                live += ref > 0
            assert alloc.free_count(p) == pages - 1 - live  # nothing leaked
    for part, lid in held:
        alloc.decref(part, lid)
    alloc.check()
    assert all(alloc.free_count(p) == pages - 1 for p in range(parts))


# -- radix prefix cache ---------------------------------------------------

def test_radix_match_register_evict():
    alloc = BlockAllocator(1, 10)
    cache = RadixPrefixCache(page=4)
    toks = np.arange(12, dtype=np.int32)
    pages = [(0, alloc.alloc(0)) for _ in range(3)]
    assert cache.register(0, 0, toks, pages, alloc) == 3
    assert cache.n_nodes == 3
    for p, lid in pages:                     # registration holds one ref
        assert alloc.refcount(p, lid) == 2
    chain = cache.match(0, 0, toks, 3)
    assert [(n.partition, n.lid) for n in chain] == pages
    assert len(cache.match(0, 0, toks[:8], 2)) == 2
    diverged = toks.copy()
    diverged[5] = 99                          # page 1 differs -> chain stops
    assert len(cache.match(0, 0, diverged, 3)) == 1
    # rank and adapter id key separate trees: no cross-tenant sharing
    assert cache.match(0, 1, toks, 3) == []
    assert cache.match(1, 0, toks, 3) == []
    # re-registering identical content creates nothing and keeps the
    # ORIGINAL pages (the second copy's pages stay the caller's)
    dup = [(0, alloc.alloc(0)) for _ in range(3)]
    assert cache.register(0, 0, toks, dup, alloc) == 0
    for p, lid in dup:
        assert alloc.refcount(p, lid) == 1
        alloc.decref(p, lid)


def test_radix_evict_lru_leaves_only():
    alloc = BlockAllocator(1, 10)
    cache = RadixPrefixCache(page=4)
    old = np.arange(8, dtype=np.int32)
    new = np.arange(100, 108, dtype=np.int32)
    p_old = [(0, alloc.alloc(0)) for _ in range(2)]
    p_new = [(0, alloc.alloc(0)) for _ in range(2)]
    cache.register(0, 0, old, p_old, alloc)
    cache.register(0, 0, new, p_new, alloc)
    for p, lid in p_old + p_new:             # owner drops its refs: clean
        alloc.decref(p, lid)
    cache.match(0, 0, old, 2)                # touch: `old` is now RECENT
    assert cache.evict(alloc, 0, 1) == 1     # LRU leaf = new's tail page
    assert len(cache.match(0, 0, new, 2, touch=False)) == 1
    assert len(cache.match(0, 0, old, 2, touch=False)) == 2
    # a page still referenced by a slot (refcount > 1) is not evictable,
    # and it shields its ancestors too (only LEAVES are eviction targets)
    hot = cache.match(0, 0, old, 2, touch=False)[-1]
    alloc.incref(hot.partition, hot.lid)
    assert cache.evict(alloc, 0, 10) == 1    # only new's root is clean+leaf
    assert cache.n_nodes == 2                # held leaf + its parent survive
    alloc.decref(hot.partition, hot.lid)
    assert cache.evict(alloc, 0, 10) == 2    # leaf first, then its parent
    assert cache.n_nodes == 0
    alloc.check()
    assert alloc.free_count(0) == 9


def test_radix_evict_respects_protect():
    alloc = BlockAllocator(1, 6)
    cache = RadixPrefixCache(page=4)
    toks = np.arange(8, dtype=np.int32)
    pages = [(0, alloc.alloc(0)) for _ in range(2)]
    cache.register(0, 0, toks, pages, alloc)
    for p, lid in pages:
        alloc.decref(p, lid)
    protected = frozenset(cache.match(0, 0, toks, 2, touch=False))
    assert cache.evict(alloc, 0, 10, protect=protected) == 0
    assert cache.evict(alloc, 0, 10) == 2


# -- PagedKVCache host bookkeeping ---------------------------------------

def test_paged_cache_fits_and_validation():
    model = _model()
    kv = PagedKVCache(model, _params(model), n_slots=2, page_size=8,
                      pages_per_partition=4)
    assert kv.fits(24)                       # 3 pages <= 3 usable
    assert not kv.fits(25)                   # 4 pages > 3 usable
    with pytest.raises(ValueError):          # page must divide the shard
        PagedKVCache(model, _params(model), n_slots=2, page_size=7)


def test_paged_cache_host_churn_fuzz():
    """Random slot lifecycle (allocate, adopt, span-allocate, register,
    decode growth, release, evict) against the full cross-check
    ``PagedKVCache.check()`` after every operation. Host-only: pages move
    without any device program running."""
    model = _model()
    kv = PagedKVCache(model, _params(model), n_slots=4, page_size=8,
                      pages_per_partition=10)
    rng = np.random.default_rng(1)
    live = {}
    for _ in range(300):
        op = int(rng.integers(0, 4))
        if op == 0 and kv.free_slots:
            slot = kv.allocate()
            n = int(rng.integers(1, 41))
            prompt = rng.integers(0, V, size=(n,)).astype(np.int32)
            kv.set_adapter(slot, 0)
            adopted = kv.adopt_prefix(slot, prompt)
            assert adopted <= n - 1          # >=1 real token left to insert
            try:
                kv._ensure_span(slot, adopted, n)
            except PagesExhausted:
                kv.release(slot)             # mid-way failure: clean undo
                kv.check()
                continue
            kv.pos[slot] = n
            kv.register_prefix(slot, prompt)
            live[slot] = n
        elif op == 1 and live:
            slot = list(live)[int(rng.integers(len(live)))]
            kv.release(slot)
            del live[slot]
        elif op == 2 and live:
            slot = list(live)[int(rng.integers(len(live)))]
            steps = int(rng.integers(1, 4))
            if live[slot] + steps <= kv.max_len:
                try:
                    kv.ensure_decode([slot], steps)
                except PagesExhausted:
                    kv.check()
                    continue
                for _ in range(steps):
                    kv.advance(slot)
                live[slot] += steps
        else:
            kv.evict_pages(int(rng.integers(kv.n_partitions)), 2)
        kv.check()
    for slot in list(live):
        kv.release(slot)
    kv.check()
    stats = kv.memory_stats()
    kv.evict_pages(0, stats["pages_total"])
    assert kv.memory_stats()["pages_used"] == 0
    kv.check()


def test_memory_stats_shape():
    model = _model()
    kv = PagedKVCache(model, _params(model), n_slots=2, page_size=8)
    s = kv.memory_stats()
    assert s["pages_used"] == 0 and 0.0 <= s["page_utilization"] <= 1.0
    assert s["kv_hbm_bytes"] > 0
    assert set(s["prefix"]) == {"nodes", "hits_pages", "lookups_pages",
                                "hit_ratio"}
    slot = kv.allocate()
    kv._ensure_span(slot, 0, 17)             # 3 pages of 8
    assert kv.memory_stats()["pages_used"] == 3
