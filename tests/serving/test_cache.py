"""SlotKVCache: insert correctness against the batched prefill oracle,
slot isolation, free-list accounting, bucketed compile reuse."""

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models.transformer import TransformerLM
from elephas_tpu.serving.cache import SlotKVCache, bucket_length

pytestmark = pytest.mark.serving

V = 17


def _model(**kw):
    cfg = dict(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=48)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=1):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


def test_bucket_length():
    assert bucket_length(1) == 8            # floor
    assert bucket_length(8) == 8
    assert bucket_length(9) == 16
    assert bucket_length(13) == 16
    assert bucket_length(33) == 64


def test_free_list_accounting():
    model = _model()
    kv = SlotKVCache(model, _params(model), n_slots=3)
    assert (kv.free_slots, kv.active_slots) == (3, 0)
    a = kv.allocate()
    b = kv.allocate()
    assert a != b and kv.free_slots == 1
    kv.release(a)
    assert kv.free_slots == 2
    with pytest.raises(ValueError):
        kv.release(a)                       # double release
    kv.allocate()
    kv.allocate()
    with pytest.raises(RuntimeError):
        kv.allocate()                       # exhausted


@pytest.mark.parametrize("kw", [{}, {"pos_encoding": "rotary",
                                     "n_kv_heads": 2}])
def test_insert_matches_prefill_logits(kw):
    """The bucket-padded slot insert must reproduce the batched prefill's
    last-real-position logits (pad rows are garbage by contract — only the
    returned row is meaningful)."""
    model = _model(**kw)
    params = _params(model)
    rng = np.random.default_rng(0)
    kv = SlotKVCache(model, params, n_slots=2)
    for slot, T0 in ((kv.allocate(), 5), (kv.allocate(), 11)):
        prompt = rng.integers(0, V, size=(T0,)).astype(np.int32)
        last = np.asarray(kv.insert(slot, prompt))
        cache = model.init_cache(1, length=kv.capacity)
        ref, _ = model.prefill(params, jnp.asarray(prompt)[None], cache)
        np.testing.assert_allclose(last, np.asarray(ref)[0, -1],
                                   atol=2e-4, rtol=2e-4)
        assert kv.pos[slot] == T0


def test_insert_leaves_other_slots_untouched():
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(1)
    kv = SlotKVCache(model, params, n_slots=3)
    s0 = kv.allocate()
    kv.insert(s0, rng.integers(0, V, size=(7,)).astype(np.int32))
    before = np.asarray(kv.cache["k"])[:, s0].copy()
    s1 = kv.allocate()
    kv.insert(s1, rng.integers(0, V, size=(4,)).astype(np.int32))
    after = np.asarray(kv.cache["k"])[:, s0]
    np.testing.assert_array_equal(before, after)


def test_insert_reuses_one_program_per_bucket():
    """Prompts of length 5 and 7 share the 8-bucket: the compiled insert
    must not retrace (same program, different t_last)."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(2)
    kv = SlotKVCache(model, params, n_slots=2)
    from elephas_tpu.serving.cache import _insert_kernel
    before = _insert_kernel._cache_size()
    kv.insert(kv.allocate(), rng.integers(0, V, size=(5,)).astype(np.int32))
    kv.insert(kv.allocate(), rng.integers(0, V, size=(7,)).astype(np.int32))
    assert _insert_kernel._cache_size() - before == 1


def test_prompt_length_validation():
    model = _model()
    kv = SlotKVCache(model, _params(model), n_slots=1)
    slot = kv.allocate()
    with pytest.raises(ValueError):
        kv.insert(slot, np.zeros(0, np.int32))
    with pytest.raises(ValueError):
        kv.insert(slot, np.zeros(model.max_len + 1, np.int32))


def test_ring_cache_refused():
    model = _model(attn_window=8)           # all-windowed → rolling buffer
    with pytest.raises(NotImplementedError):
        SlotKVCache(model, _params(model), n_slots=2)
