"""Serving fast path: chunked prefill, fused multi-token decode, and the
device-resident step state behind them.

The acceptance property for EVERY knob here is token identity: turning a
fast-path feature on must not change a single emitted token — greedy or
seeded-sampled, local or sharded — relative to the single-step
whole-prefill driver (which is itself pinned against
``TransformerLM.generate`` in test_engine.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models.transformer import TransformerLM, build_mesh_sp
from elephas_tpu.serving import ServingEngine
from elephas_tpu.serving.scheduler import Scheduler

pytestmark = pytest.mark.serving

V = 17


def _model(**kw):
    cfg = dict(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=48)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=1):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


def _run(eng, reqs, **submit_kw):
    """Submit ``(prompt, max_new)`` pairs interleaved with steps; drain;
    return the token list per request in submission order."""
    ids = []
    for i, (prompt, max_new) in enumerate(reqs):
        ids.append(eng.submit(prompt, max_new, seed=i, **submit_kw))
        eng.step()
    eng.drain(max_steps=5000)
    return [eng.result(rid).tokens for rid in ids]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


def _prompts(rng, lens):
    return [rng.integers(0, V, size=(n,)).astype(np.int32) for n in lens]


# -- chunked prefill ------------------------------------------------------

def test_chunked_prefill_greedy_identity():
    """Long prompts inserted as chunks (interleaved with live decodes)
    emit the same greedy continuation as whole-prompt prefill AND as
    per-request ``generate``."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(0)
    prompts = _prompts(rng, [20, 3, 17, 9, 26])
    reqs = [(p, 6) for p in prompts]

    chunked = ServingEngine(model, params, n_slots=2, prefill_chunk=8)
    got = _run(chunked, reqs)
    assert chunked.snapshot()["fastpath"]["prefill_chunks"] >= 6

    whole = ServingEngine(model, params, n_slots=2)
    assert got == _run(whole, reqs)
    for prompt, toks in zip(prompts, got):
        ref = np.asarray(model.generate(params, prompt[None], 6))
        assert toks == ref[0, len(prompt):].tolist()


def test_chunked_prefill_sampled_identity():
    """Seeded-sampled streams are (seed, position)-keyed, so chunk
    boundaries cannot change them either."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(1)
    reqs = [(p, 5) for p in _prompts(rng, [19, 11, 25])]
    a = _run(ServingEngine(model, params, n_slots=2, prefill_chunk=8),
             reqs, temperature=0.8)
    b = _run(ServingEngine(model, params, n_slots=2), reqs, temperature=0.8)
    assert a == b


def test_chunked_prefill_sharded_identity():
    """The dp×sp engine's chunk-insert program (existing-row logsumexp
    merge) matches the local chunked engine and ``generate``."""
    mesh = build_mesh_sp(data=2, seq=2)
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(2)
    prompts = _prompts(rng, [21, 4, 18])
    reqs = [(p, 5) for p in prompts]
    eng = ServingEngine(model, params, n_slots=4, mesh=mesh,
                        prefill_chunk=8)
    got = _run(eng, reqs)
    assert eng.snapshot()["fastpath"]["prefill_chunks"] >= 4
    for prompt, toks in zip(prompts, got):
        ref = np.asarray(model.generate(params, prompt[None], 5))
        assert toks == ref[0, len(prompt):].tolist()


def test_chunked_prefill_cancel_mid_train_frees_slot():
    """Cancelling a request mid-chunk-train closes the train, frees the
    slot, and leaves co-batched streams untouched."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(3)
    short, long = _prompts(rng, [3, 26])

    eng = ServingEngine(model, params, n_slots=2, prefill_chunk=8)
    rid_s = eng.submit(short, 8)
    eng.step()                          # admit short → live
    rid_l = eng.submit(long, 4)
    eng.step()                          # admit long → first chunk only
    assert eng._partial is not None
    assert eng.cancel(rid_l)
    assert eng._partial is None
    assert eng.kv.free_slots == 1       # slot reclaimed immediately
    eng.drain(max_steps=500)
    assert eng.result(rid_l).finish_reason == "cancelled"
    ref = np.asarray(model.generate(params, short[None], 8))
    assert eng.result(rid_s).tokens == ref[0, len(short):].tolist()


def test_scheduler_interleaves_chunks_with_decode():
    """With a live decode row, an open chunk train alternates
    prefill_chunk/decode; with none, chunks run back-to-back."""
    s = Scheduler()
    assert s.decide(1, 1, has_partial=True, last_action=None) \
        == "prefill_chunk"
    assert s.decide(1, 1, has_partial=True, last_action="prefill_chunk") \
        == "decode"
    assert s.decide(1, 1, has_partial=True, last_action="decode") \
        == "prefill_chunk"
    assert s.decide(1, 0, has_partial=True, last_action="prefill_chunk") \
        == "prefill_chunk"
    # and the legacy positional form still drives the non-chunked loop
    assert s.decide(1, 1) == "decode"
    assert s.decide(0, 0) == "idle"


# -- fused multi-token decode ---------------------------------------------

def test_fused_decode_greedy_identity():
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(4)
    prompts = _prompts(rng, [5, 9, 3, 12, 7])
    reqs = [(p, 9) for p in prompts]

    fused = ServingEngine(model, params, n_slots=4, fuse_k=4)
    got = _run(fused, reqs)
    assert fused.snapshot()["fastpath"]["fused_blocks"] > 0

    assert got == _run(ServingEngine(model, params, n_slots=4), reqs)
    for prompt, toks in zip(prompts, got):
        ref = np.asarray(model.generate(params, prompt[None], 9))
        assert toks == ref[0, len(prompt):].tolist()


def test_fused_decode_sampled_identity():
    """Fused blocks replay the exact per-(seed, position) sample stream
    of K single steps."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(5)
    reqs = [(p, 7) for p in _prompts(rng, [6, 10, 4])]
    a = _run(ServingEngine(model, params, n_slots=2, fuse_k=3), reqs,
             temperature=0.7)
    b = _run(ServingEngine(model, params, n_slots=2), reqs,
             temperature=0.7)
    assert a == b


def test_fused_decode_sharded_identity():
    mesh = build_mesh_sp(data=2, seq=2)
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(6)
    prompts = _prompts(rng, [5, 11, 8, 4])
    reqs = [(p, 6) for p in prompts]
    fused = ServingEngine(model, params, n_slots=4, mesh=mesh, fuse_k=3)
    got = _run(fused, reqs)
    assert fused.snapshot()["fastpath"]["fused_blocks"] > 0
    for prompt, toks in zip(prompts, got):
        ref = np.asarray(model.generate(params, prompt[None], 6))
        assert toks == ref[0, len(prompt):].tolist()


def test_fused_eos_truncation_exact():
    """EOS inside a fused block: the host truncates the row's stream at
    the EOS token — identical records to the single-step driver, which
    stops the row the step it fires."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(7)
    reqs = [(p, 12) for p in _prompts(rng, [4, 8, 6])]

    fused_eng = ServingEngine(model, params, n_slots=4, fuse_k=4)
    got = _run(fused_eng, reqs, eos_id=3)
    ref = _run(ServingEngine(model, params, n_slots=4), reqs, eos_id=3)
    assert got == ref
    for toks in got:
        assert 3 not in toks[:-1]       # EOS never mid-stream


def test_fused_bypass_under_deadline_and_queue_pressure():
    """Fusion must stand down whenever it could perturb observable
    behavior: live deadlines (per-step reap exactness) and queued work
    behind EOS-able actives (admission latency)."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(8)
    p1, p2 = _prompts(rng, [4, 5])

    eng = ServingEngine(model, params, n_slots=1, fuse_k=4,
                        clock=FakeClock())
    eng.submit(p1, 6, deadline_s=1e9)
    eng.step()
    eng.drain(max_steps=200)
    assert eng.snapshot()["fastpath"]["fused_blocks"] == 0

    eng2 = ServingEngine(model, params, n_slots=1, fuse_k=4)
    eng2.submit(p1, 10, eos_id=3)       # EOS-able active...
    eng2.step()
    eng2.submit(p2, 2)                  # ...with work queued behind it
    while eng2.scheduler.queue_depth:
        eng2.step()
        assert eng2.metrics.fused_blocks == 0
    eng2.drain(max_steps=200)


def test_deadline_reap_exact_with_fusion_enabled():
    """A deadlined request under ``fuse_k>1`` produces the identical
    terminal record the single-step driver does (fusion bypasses)."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(9)
    (prompt,) = _prompts(rng, [4])

    def run(**kw):
        eng = ServingEngine(model, params, n_slots=1, clock=FakeClock(),
                            **kw)
        rid = eng.submit(prompt, 20, deadline_s=9.0)
        eng.drain(max_steps=100)
        fin = eng.result(rid)
        return fin.finish_reason, fin.tokens

    assert run(fuse_k=4) == run()


def test_cancel_between_fused_blocks_exact():
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(10)
    pa, pb = _prompts(rng, [5, 7])

    def run(**kw):
        eng = ServingEngine(model, params, n_slots=2, **kw)
        ra = eng.submit(pa, 20)
        rb = eng.submit(pb, 20)
        for _ in range(4):
            eng.step()
        eng.cancel(ra)
        eng.drain(max_steps=200)
        return eng.result(ra).tokens, eng.result(rb).tokens

    a4, b4 = run(fuse_k=4)
    a1, b1 = run()
    assert b4 == b1                     # survivor stream untouched
    # cancel timing is counted in STEPS, and a fused step yields up to K
    # tokens, so the streams may differ in length — but never in content:
    # one must be a prefix of the other
    n = min(len(a4), len(a1))
    assert n > 0 and a4[:n] == a1[:n]


def test_fused_smoke_and_metrics():
    """CI tripwire (fast, CPU): the fused path must actually EXECUTE —
    a regression that silently falls back to the single-step driver
    fails here — and the fast-path histograms must populate and stay
    JSON-able."""
    import json

    model = _model()
    params = _params(model)
    rng = np.random.default_rng(11)
    eng = ServingEngine(model, params, n_slots=2, fuse_k=4,
                        prefill_chunk=8)
    reqs = [(p, 8) for p in _prompts(rng, [3, 20])]
    _run(eng, reqs)
    snap = json.loads(json.dumps(eng.snapshot()))
    fp = snap["fastpath"]
    assert fp["fused_blocks"] >= 1
    assert fp["fused_steps"] >= 4
    assert fp["prefill_chunks"] >= 2
    assert fp["inter_token_latency_s"]["count"] > 0
    assert fp["dispatch_overhead_s"]["count"] > 0
    # decode_steps counts LOGICAL steps: fused blocks contribute K each
    assert snap["engine"]["decode_steps"] >= fp["fused_steps"]
