"""Seeded dense-vs-paged identity fuzz and page-pressure chaos.

The paged engine's ONE contract is bitwise token identity with the
dense engine under every knob combination.  This file sweeps the knob
cross-product — ``(page_size, fuse_k, speculate_k, prefill_chunk)``,
plus multi-tenant adapter routing — with ``kv.check()`` asserted after
EVERY engine step, not just at drain.  It also pins the two paged-only
hazards the sweep alone can't force:

* speculative accept runs that STRADDLE a page boundary (``page_size=8``
  with ``speculate_k=5`` — a fully-accepted verify chunk commits 5
  tokens, so some round necessarily crosses ``pos % 8 == 0``), and
* ``PagesExhausted`` raised while a FUSED multi-token window wants
  pages: clean-leaf eviction, then newest-admitted preemption, then a
  token-transparent resume of the preempted request.

Every assertion here is exact equality — no tolerances anywhere.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.models.lora import MultiTenantLM
from elephas_tpu.models.transformer import TransformerLM
from elephas_tpu.serving.engine import ServingEngine

pytestmark = [pytest.mark.serving, pytest.mark.paged]

V = 17


def _model(**kw):
    cfg = dict(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=64)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=1):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


def _prompts(rng, lens):
    return [rng.integers(0, V, size=(n,)).astype(np.int32) for n in lens]


def _run_checked(eng, reqs, *, check_every_step=True, **submit_kw):
    """Submit ``reqs``, drive the engine ONE step at a time, and assert
    the allocator invariants (``kv.check()``) after every single step —
    the fuzz contract is that no intermediate state is ever broken, not
    merely the final one."""
    ids = []
    for i, (prompt, max_new) in enumerate(reqs):
        ids.append(eng.submit(prompt, max_new, seed=i, **submit_kw))
        eng.step()
        if check_every_step and eng.kv is not None:
            eng.kv.check()
    for _ in range(5000):
        if not (eng.scheduler.queue_depth or eng.kv.active_slots):
            break
        eng.step()
        if check_every_step and eng.kv is not None:
            eng.kv.check()
    else:  # pragma: no cover - hang guard
        raise AssertionError("engine did not drain in 5000 steps")
    return [eng.result(rid).tokens for rid in ids]


def _run_dense(model, params, reqs, **submit_kw):
    return _run_checked(ServingEngine(model, params, n_slots=4), reqs,
                        check_every_step=False, **submit_kw)


# -- knob-sweep fuzz ------------------------------------------------------

# (page_size, fuse_k, speculate_k, prefill_chunk) — each row turns a
# different subset of the fast-path machinery loose on the page pool.
# Tier-1 keeps the two ends of the spectrum (plain, and everything at
# once); the interior rows are `slow` and run via `make test-paged`
# (the group's `-m paged` is appended after `-m "not slow"`).
_slow = pytest.mark.slow
KNOBS = [
    (8, 1, 1, None),                          # plain single-step decode
    pytest.param(16, 1, 1, None, marks=_slow),  # bigger pages
    pytest.param(8, 4, 1, None, marks=_slow),   # fused windows only
    pytest.param(8, 1, 4, None, marks=_slow),   # speculation only
    pytest.param(8, 1, 1, 8, marks=_slow),      # chunked prefill only
    pytest.param(16, 4, 1, 16, marks=_slow),    # fused + chunked, p16
    (8, 2, 5, 8),        # everything at once; 5-token verify chunks
]


@pytest.mark.parametrize("page,fuse_k,spec_k,chunk", KNOBS)
@pytest.mark.parametrize("temp", [0.0, 0.8])
def test_fuzz_knob_sweep_bitwise_identity(page, fuse_k, spec_k, chunk,
                                          temp):
    """Every knob combination streams EXACTLY the dense engine's tokens,
    greedy and sampled, with allocator invariants intact at every step."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(1000 + page * 31 + fuse_k * 7
                                + spec_k * 3 + (chunk or 0))
    # prompt lengths land on, just before, and well past page boundaries
    reqs = [(p, 10) for p in _prompts(rng, [5, 16, 23, 7, 31, 9])]
    want = _run_dense(model, params, reqs, temperature=temp)
    eng = ServingEngine(model, params, n_slots=4, paged=True,
                        page_size=page, fuse_k=fuse_k,
                        speculate_k=spec_k, prefill_chunk=chunk)
    got = _run_checked(eng, reqs, temperature=temp)
    assert got == want
    stats = eng.kv.memory_stats()
    # all request refs released; at most clean prefix-cache pages remain
    assert stats["pages_used"] == stats["prefix"]["nodes"]
    eng.kv.evict_pages(0, stats["pages_total"])
    assert eng.kv.memory_stats()["pages_used"] == 0
    eng.kv.check()


def test_fuzz_multi_tenant_knob_sweep():
    """Multi-tenant LoRA routing stays exact under the fast-path knobs:
    co-batched tenants with different adapters + speculation + chunked
    prefill each match a dedicated dense engine running that tenant's
    MERGED weights."""
    mt = MultiTenantLM(vocab=V, d_model=16, n_heads=4, n_layers=2,
                       d_ff=32, max_len=64, n_adapters=3, lora_rank=4)
    mtp = mt.init(seed=1)
    mtp = mt.randomize_adapter(mtp, 1, seed=7)
    mtp = mt.randomize_adapter(mtp, 2, seed=8)
    mtp = {k: jnp.asarray(v) for k, v in mtp.items()}
    base = mt.base_model()
    rng = np.random.default_rng(21)
    prompts = _prompts(rng, [15, 19, 24, 9])
    eng = ServingEngine(mt, mtp, n_slots=4, paged=True, page_size=8,
                        speculate_k=4, prefill_chunk=8)
    ids = [eng.submit(p, 10, seed=0, request_id=f"r{i}", adapter_id=i % 3)
           for i, p in enumerate(prompts)]
    for _ in range(5000):
        if not (eng.scheduler.queue_depth or eng.kv.active_slots):
            break
        eng.step()
        eng.kv.check()
    for i, (p, rid) in enumerate(zip(prompts, ids)):
        merged = mt.merged_params(mtp, i % 3)
        ref = ServingEngine(base, merged, n_slots=1)
        ref.submit(p, 10, seed=0, request_id="x")
        ref.drain(max_steps=5000)
        assert eng.result(rid).tokens == ref.result("x").tokens, i
    eng.kv.check()


# -- page-boundary-straddling speculative accepts ------------------------

def test_spec_accepts_straddle_page_boundaries():
    """Greedy self-speculation accepts every draft, so each verify round
    commits ``speculate_k`` tokens at once; with ``speculate_k=5`` and
    ``page_size=8`` those 5-token runs MUST repeatedly straddle page
    boundaries (gcd(5, 8) = 1 walks every residue).  The committed
    stream still equals per-request ``generate`` bitwise, and the new
    page acquired mid-chunk is accounted exactly at every step."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(33)
    # pos starts at len(prompt); 6 and 7 put the first verify chunk
    # across the first page edge immediately
    prompts = _prompts(rng, [6, 7, 14, 23])
    eng = ServingEngine(model, params, n_slots=4, paged=True, page_size=8,
                        speculate_k=5)
    got = _run_checked(eng, [(p, 20) for p in prompts])
    for i, p in enumerate(prompts):
        ref = np.asarray(model.generate(params, p[None], 20))
        assert got[i] == ref[0, len(p):].tolist()
    # speculation actually ran (the point of the test)
    fp = eng.snapshot()["fastpath"]
    assert fp["spec_rounds"] > 0 and fp["spec_accepted"] > 0


# -- chaos: PagesExhausted mid-fused-window ------------------------------

def test_chaos_pages_exhausted_mid_fused_window():
    """A fused K-token window pre-allocates every page it may write; with
    a pool sized so that allocation FAILS mid-flight, the engine must
    evict clean leaves, then preempt the newest-admitted request, launch
    the window for the survivors, and later resume the victim with NO
    token-level trace — the final streams are bitwise the dense engine's
    and the pool drains to zero."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(6)
    prompts = _prompts(rng, [21, 19, 23, 17])
    reqs = [(p, 12) for p in prompts]
    want = _run_dense(model, params, reqs)
    eng = ServingEngine(model, params, n_slots=4, paged=True, page_size=8,
                        fuse_k=4, pages_per_partition=12,
                        prefix_cache=False)
    got = _run_checked(eng, reqs)
    assert got == want
    assert eng.kv.preemptions > 0            # pressure actually bit
    fp = eng.snapshot()["fastpath"]
    assert fp["fused_blocks"] > 0            # and fusion actually ran
    assert eng.kv.memory_stats()["pages_used"] == 0
    eng.kv.check()


def test_chaos_pages_exhausted_mid_spec_window():
    """Same pressure story for the SPECULATIVE window: every position a
    verify chunk may write gets its page before launch, so exhaustion
    surfaces as eviction/preemption BEFORE the program runs and the
    committed streams stay bitwise-dense."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(14)
    prompts = _prompts(rng, [21, 19, 23, 17])
    reqs = [(p, 12) for p in prompts]
    want = _run_dense(model, params, reqs)
    eng = ServingEngine(model, params, n_slots=4, paged=True, page_size=8,
                        speculate_k=4, pages_per_partition=12,
                        prefix_cache=False)
    got = _run_checked(eng, reqs)
    assert got == want
    assert eng.kv.preemptions > 0
    assert eng.kv.memory_stats()["pages_used"] == 0
    eng.kv.check()
