"""Speculative decoding on the serving fast path: draft K, verify once.

The acceptance property is the same one every fast-path knob here pins:
turning speculation on (``speculate_k > 1``) must not change a single
emitted token — greedy or seeded-sampled, dense or paged, local or
sharded, any drafter. Speculation is allowed to change ONLY how many
device programs the stream costs, never the stream. The verify rule is
exact-match against the engine's own per-slot selection
(:func:`~elephas_tpu.models.transformer.spec_verify_select`), which makes
the identity bitwise rather than distributional — so these tests compare
token lists directly instead of statistics.
"""

from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models.transformer import (TransformerLM, build_mesh_sp,
                                            spec_verify_select)
from elephas_tpu.models.lora import MultiTenantLM
from elephas_tpu.serving import (AdmissionError, ModelDrafter, NgramDrafter,
                                 ServingEngine)
from elephas_tpu.serving.scheduler import Scheduler

pytestmark = [pytest.mark.serving, pytest.mark.spec]

V = 17


def _model(**kw):
    cfg = dict(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=48)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=1):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


def _run(eng, reqs, **submit_kw):
    ids = []
    for i, (prompt, max_new) in enumerate(reqs):
        ids.append(eng.submit(prompt, max_new, seed=i, **submit_kw))
        eng.step()
    eng.drain(max_steps=5000)
    return [eng.result(rid).tokens for rid in ids]


def _prompts(rng, lens):
    return [rng.integers(0, V, size=(n,)).astype(np.int32) for n in lens]


@pytest.fixture(scope="module")
def base_case():
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(0)
    prompts = _prompts(rng, [5, 9, 3, 7])
    return model, params, [(p, 12) for p in prompts]


# -- the selection rule itself --------------------------------------------

def test_verify_select_equals_sequential_selection():
    """``spec_verify_select`` applied to a [S, K+1, V] chunk of logits
    must pick, at every chunk offset, EXACTLY the token the one-at-a-time
    engine rule (``select_slot_tokens`` keyed on absolute position) picks —
    greedy rows and sampled rows alike. This is the lemma the whole
    bitwise-identity claim rests on: given it, induction over accepted
    prefixes makes the emitted stream the sequential stream."""
    import jax
    from elephas_tpu.models.transformer import select_slot_tokens
    rng = np.random.default_rng(3)
    S, K = 4, 3
    logits = jnp.asarray(rng.normal(size=(S, K + 1, V)).astype(np.float32))
    drafts = jnp.asarray(rng.integers(0, V, size=(S, K)).astype(np.int32))
    pos = jnp.asarray(np.array([2, 7, 0, 5], np.int32))
    temps = jnp.asarray(np.array([0.0, 0.9, 0.0, 1.3], np.float32))
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(S, dtype=jnp.uint32))
    sel, n = spec_verify_select(logits, drafts, pos, temps, keys)
    sel = np.asarray(sel)
    for j in range(K + 1):
        step = np.asarray(
            select_slot_tokens(logits[:, j], pos + 1 + j, temps, keys))
        assert (sel[:, j] == step).all(), j
    # the acceptance count is the longest drafts-match-selection prefix
    want_n = np.zeros(S, np.int64)
    for s in range(S):
        while (want_n[s] < K
               and sel[s, want_n[s]] == int(drafts[s, want_n[s]])):
            want_n[s] += 1
    assert (np.asarray(n) == want_n).all()


def test_ngram_drafter_proposes_repeats():
    """The self-drafting n-gram drafter finds the most recent prior
    occurrence of the longest context suffix and proposes its historical
    continuation; with no history it repeats the last token. Pure host
    numpy — deterministic by construction."""
    d = NgramDrafter(n_max=3)
    ctx = np.asarray([4, 5, 6, 9, 4, 5, 6], np.int32)
    # suffix (5, 6) last occurred at index 1 → continuation 9, then 4, 5
    assert d.propose(ctx, 3).tolist() == [9, 4, 5]
    # continuation shorter than k pads with its own last token
    assert d.propose(np.asarray([7, 8, 7], np.int32), 4).tolist() == \
        [8, 7, 7, 7]
    # no repeated suffix anywhere: repeat the tail
    assert d.propose(np.asarray([1], np.int32), 2).tolist() == [1, 1]


# -- token identity, every engine configuration ---------------------------

def test_spec_greedy_identity_and_engagement(base_case):
    """Greedy speculative decoding (default n-gram drafter) is token-
    identical to the non-speculative engine AND to per-request
    ``generate`` — and the rounds actually ran (an accidentally dead
    feature would pass identity trivially)."""
    model, params, reqs = base_case
    want = _run(ServingEngine(model, params, n_slots=4), reqs)
    eng = ServingEngine(model, params, n_slots=4, speculate_k=4)
    assert _run(eng, reqs) == want
    fp = eng.snapshot()["fastpath"]
    assert fp["spec_rounds"] > 0
    for (prompt, n), toks in zip(reqs, want):
        ref = np.asarray(model.generate(params, prompt[None], n))
        assert toks == ref[0, len(prompt):].tolist()


def test_spec_sampled_identity(base_case):
    """Seeded sampling: the (seed, absolute-position) keying of the
    verify selection makes the sampled stream bitwise the sequential
    one — acceptance never rewinds or replays a random draw."""
    model, params, reqs = base_case
    want = _run(ServingEngine(model, params, n_slots=4), reqs,
                temperature=0.9)
    eng = ServingEngine(model, params, n_slots=4, speculate_k=4)
    assert _run(eng, reqs, temperature=0.9) == want
    assert eng.snapshot()["fastpath"]["spec_rounds"] > 0


def test_spec_model_drafter_identity_high_acceptance(base_case):
    """A greedy self-draft (the target model as its own drafter) under a
    greedy target mostly accepts, and the stream is still the pinned one.
    Acceptance is HIGH but deliberately not pinned at 100%: the drafter
    argmaxes ``decode_step`` logits while verify scores a ``decode_chunk``,
    and the two programs may reassociate float ops differently — at a
    near-tie the argmax flips, the exact-match rule rejects, and the
    emitted stream is STILL exactly the sequential one (which is the
    property that matters). Also pins drafter-independence: n-gram and
    model drafters produce the SAME tokens."""
    model, params, reqs = base_case
    want = _run(ServingEngine(model, params, n_slots=4), reqs)
    eng = ServingEngine(model, params, n_slots=4, speculate_k=4,
                        drafter=ModelDrafter(model, params))
    assert _run(eng, reqs) == want
    fp = eng.snapshot()["fastpath"]
    assert fp["spec_rounds"] > 0
    assert fp["spec_accepted"] >= 0.7 * fp["spec_drafted"]


def test_spec_paged_bitwise_dense(base_case):
    """Paged speculation (accepted-run scatter, rejected tail into the
    trash page) is token-identical to dense speculation and to the
    non-speculative stream; the pool passes its integrity check after."""
    model, params, reqs = base_case
    want = _run(ServingEngine(model, params, n_slots=4), reqs)
    eng = ServingEngine(model, params, n_slots=4, speculate_k=4,
                        paged=True, page_size=8)
    assert _run(eng, reqs) == want
    assert eng.snapshot()["fastpath"]["spec_rounds"] > 0
    eng.kv.check()


def test_spec_mesh_identity(base_case):
    """The sharded verify program (seq-sharded cache, merged logits
    replicated across ranks) emits the same greedy and sampled streams as
    the local engine, dense and paged."""
    model, params, reqs = base_case
    mesh = build_mesh_sp(data=2, seq=2)
    want = _run(ServingEngine(model, params, n_slots=4), reqs)
    eng = ServingEngine(model, params, n_slots=4, mesh=mesh, speculate_k=4)
    assert _run(eng, reqs) == want
    assert eng.snapshot()["fastpath"]["spec_rounds"] > 0
    paged = ServingEngine(model, params, n_slots=4, mesh=mesh,
                          speculate_k=4, paged=True, page_size=8)
    assert _run(paged, reqs) == want
    want_s = _run(ServingEngine(model, params, n_slots=4), reqs,
                  temperature=0.8)
    eng_s = ServingEngine(model, params, n_slots=4, mesh=mesh,
                          speculate_k=4)
    assert _run(eng_s, reqs, temperature=0.8) == want_s


def test_spec_multi_tenant_adapters(base_case):
    """Per-adapter speculation on the paged multi-tenant engine: each
    co-batched tenant's speculative stream equals a dedicated dense
    NON-speculative engine running that tenant's merged weights."""
    mt = MultiTenantLM(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
                       max_len=48, n_adapters=3, lora_rank=4)
    mtp = mt.init(seed=1)
    mtp = mt.randomize_adapter(mtp, 1, seed=7)
    mtp = mt.randomize_adapter(mtp, 2, seed=8)
    mtp = {k: jnp.asarray(v) for k, v in mtp.items()}
    base = mt.base_model()
    rng = np.random.default_rng(10)
    prompts = _prompts(rng, [21, 19, 23, 17])
    eng = ServingEngine(mt, mtp, n_slots=4, paged=True, page_size=8,
                        speculate_k=4)
    ids = [eng.submit(p, 10, seed=0, request_id=f"r{i}", adapter_id=i % 3)
           for i, p in enumerate(prompts)]
    eng.drain(max_steps=5000)
    assert eng.snapshot()["fastpath"]["spec_rounds"] > 0
    for i, (p, rid) in enumerate(zip(prompts, ids)):
        merged = mt.merged_params(mtp, i % 3)
        ref = ServingEngine(base, merged, n_slots=1)
        ref.submit(p, 10, seed=0, request_id="x")
        ref.drain(max_steps=5000)
        assert eng.result(rid).tokens == ref.result("x").tokens, i
    eng.kv.check()


def test_spec_eos_truncates_mid_round(base_case):
    """A row that hits EOS inside an accepted run stops emitting there —
    finish reason and token list match the sequential engine exactly (the
    device keeps committing the rest of the round; only host emission
    truncates, same contract as the fused path)."""
    model, params, reqs = base_case
    base = ServingEngine(model, params, n_slots=4)
    want_ids = [base.submit(p, n, seed=i, eos_id=2)
                for i, (p, n) in enumerate(reqs)]
    base.drain(max_steps=5000)
    want = [base.result(r) for r in want_ids]
    assert any(f.finish_reason == "eos" for f in want), \
        "fixture no longer exercises EOS; pick a different eos_id"
    eng = ServingEngine(model, params, n_slots=4, speculate_k=4)
    got_ids = [eng.submit(p, n, seed=i, eos_id=2)
               for i, (p, n) in enumerate(reqs)]
    eng.drain(max_steps=5000)
    for rid, ref in zip(got_ids, want):
        got = eng.result(rid)
        assert got.tokens == ref.tokens
        assert got.finish_reason == ref.finish_reason


def test_spec_stands_down_for_deadlines(base_case):
    """Any live deadline forces the engine back to single-step decode
    (the same contract as fusion: a deadline must be observable every
    logical step) — zero speculative rounds, stream unchanged."""
    model, params, reqs = base_case
    want = _run(ServingEngine(model, params, n_slots=4), reqs)
    eng = ServingEngine(model, params, n_slots=4, speculate_k=4)
    ids = [eng.submit(p, n, seed=i, deadline_s=1e9)
           for i, (p, n) in enumerate(reqs)]
    eng.drain(max_steps=5000)
    assert [eng.result(r).tokens for r in ids] == want
    assert eng.snapshot()["fastpath"]["spec_rounds"] == 0


# -- construction validation ----------------------------------------------

def test_spec_validation(base_case):
    model, params, _ = base_case
    with pytest.raises(ValueError):
        ServingEngine(model, params, n_slots=2, speculate_k=0)
    mesh = build_mesh_sp(data=2, seq=2)
    with pytest.raises(NotImplementedError):
        ServingEngine(model, params, n_slots=2, mesh=mesh, speculate_k=4,
                      drafter=ModelDrafter(model, params))
    from elephas_tpu.models.transformer import MoETransformerLM
    moe = MoETransformerLM(vocab=V, d_model=16, n_heads=4, n_layers=1,
                           d_ff=32, max_len=48, n_experts=4, k=2)
    moep = _params(moe)
    with pytest.raises(ValueError):
        ServingEngine(moe, moep, n_slots=2, speculate_k=4)
    # speculate_k=1 on an MoE model is fine: the feature is off
    ServingEngine(moe, moep, n_slots=2, speculate_k=1)


# -- scheduler page reservation (satellite) --------------------------------

def test_scheduler_reserves_speculative_lookahead_pages():
    """``decide`` must hold back the live slots' accept-burst page
    exposure: the head admits only when its pages AND the reservation
    both fit. The pre-reservation behavior (admit on head need alone) is
    the bug this pins out."""
    from elephas_tpu.serving.scheduler import ServingRequest
    s = Scheduler()
    s.push(ServingRequest(request_id="q", prompt=np.zeros(4, np.int32),
                          max_new=4))
    common = dict(free_slots=1, active_slots=3, free_pages=5, need_pages=4)
    assert s.decide(**common) == "prefill"                      # no reserve
    assert s.decide(**common, reserve_pages=1) == "prefill"     # 4+1 <= 5
    assert s.decide(**common, reserve_pages=2) == "decode"      # 4+2 > 5
    # negative reservations are clamped, not credited
    assert s.decide(**common, reserve_pages=-3) == "prefill"
    # with no paged accounting at all, reserve_pages is inert
    assert s.decide(free_slots=1, active_slots=0,
                    reserve_pages=99) == "prefill"


# -- metrics schema (satellite) --------------------------------------------

def test_spec_metrics_schema_and_consistency(base_case):
    """The ``fastpath`` spec section is present IFF ``speculate_k > 1``,
    and its counters obey the pinned accounting identities."""
    model, params, reqs = base_case
    off = ServingEngine(model, params, n_slots=4)
    _run(off, reqs)
    fp_off = off.snapshot()["fastpath"]
    for key in ("spec_rounds", "spec_drafted", "spec_accepted",
                "spec_emitted", "spec_rows", "acceptance_rate",
                "emitted_per_row_per_round"):
        assert key not in fp_off, key

    eng = ServingEngine(model, params, n_slots=4, speculate_k=4)
    _run(eng, reqs)
    fp = eng.snapshot()["fastpath"]
    assert fp["spec_rounds"] > 0
    # every verify round commits each row's accepted run + one correction
    assert fp["spec_emitted"] == fp["spec_accepted"] + fp["spec_rows"]
    # drafts per round per row never exceed the lookahead window
    assert fp["spec_accepted"] <= fp["spec_drafted"]
    assert fp["spec_drafted"] <= fp["spec_rows"] * (eng.speculate_k - 1)
    # the histograms are dist dicts like every other fastpath histogram
    for key in ("acceptance_rate", "emitted_per_row_per_round"):
        assert set(fp[key]) == {"count", "p50", "p95", "mean"}
    assert fp["acceptance_rate"]["count"] == fp["spec_rounds"]
    # a spec round is ONE logical decode step: fused counters untouched
    assert fp["fused_blocks"] == 0
    import json
    json.dumps(eng.snapshot())  # the whole snapshot stays JSON-able


def test_no_wall_clock_reads_outside_perf_counter():
    """The engine and metrics modules must never read ``time.time`` —
    latency histograms use ``time.perf_counter`` and request lifecycle
    stamps use the injectable engine clock. A ``time.time`` crept in once
    and broke fake-clock latency pins; this keeps it out."""
    from elephas_tpu.serving import engine as engine_mod
    from elephas_tpu.serving import metrics as metrics_mod
    for mod in (engine_mod, metrics_mod):
        src = Path(mod.__file__).read_text()
        assert "time.time(" not in src, mod.__name__
