"""ServingMetrics: pinned numbers under a fake clock, JSON-able snapshot."""

import json

import pytest

from elephas_tpu.serving.metrics import (
    RequestTiming,
    ServingMetrics,
    _percentile,
)

pytestmark = pytest.mark.serving


def _timing(rid="r", prompt=4, sub=0.0, adm=1.0, first=2.0, fin=6.0, gen=8,
            reason="length"):
    return RequestTiming(request_id=rid, prompt_tokens=prompt,
                         submitted_at=sub, admitted_at=adm,
                         first_token_at=first, finished_at=fin,
                         generated_tokens=gen, finish_reason=reason)


def test_request_timing_derived_quantities():
    t = _timing()
    assert t.queue_wait == 1.0
    assert t.ttft == 2.0                  # from SUBMIT, queue wait included
    assert t.decode_tokens_per_sec == 8 / 5.0   # admitted → finished

    # unfinished stages stay None instead of crashing
    partial = RequestTiming(request_id="p", prompt_tokens=1, submitted_at=0.0)
    assert partial.queue_wait is None
    assert partial.ttft is None
    assert partial.decode_tokens_per_sec is None


def test_percentile_nearest_rank():
    vals = [1.0, 2.0, 3.0, 4.0, 5.0]
    assert _percentile(vals, 0.50) == 3.0
    assert _percentile(vals, 0.0) == 1.0
    assert _percentile(vals, 1.0) == 5.0
    assert _percentile([], 0.5) == 0.0


def test_batch_occupancy_is_mean_active_fraction():
    m = ServingMetrics(n_slots=4)
    assert m.batch_occupancy == 0.0
    m.observe_decode_step(4)
    m.observe_decode_step(2)
    assert m.batch_occupancy == pytest.approx((1.0 + 0.5) / 2)


def test_snapshot_is_json_able_and_complete():
    m = ServingMetrics(n_slots=2)
    m.observe_submit()
    m.observe_submit()
    m.observe_reject("queue_full")
    m.observe_prefill()
    m.observe_decode_step(2)
    m.observe_swap(3)
    m.observe_finish(_timing(rid="a", fin=5.0, gen=4))
    m.observe_finish(_timing(rid="b", sub=1.0, adm=1.5, first=3.5, fin=9.5,
                             gen=16))
    snap = m.snapshot(active_slots=1, queue_depth=3)
    roundtrip = json.loads(json.dumps(snap))    # must survive json

    eng = roundtrip["engine"]
    assert eng == {"n_slots": 2, "active_slots": 1, "queue_depth": 3,
                   "batch_occupancy": 1.0, "prefills": 1, "decode_steps": 1,
                   "weights_version": 3, "weight_swaps": 1}
    ctr = roundtrip["counters"]
    assert ctr["submitted"] == 2
    assert ctr["rejected"] == {"queue_full": 1}
    assert ctr["completed"] == 2
    assert ctr["tokens_generated"] == 20
    ttft = roundtrip["requests"]["ttft_s"]
    assert ttft["count"] == 2
    assert ttft["p50"] == 2.0 and ttft["p95"] == 2.5


def test_finished_window_is_bounded():
    m = ServingMetrics(n_slots=1, window=3)
    for i in range(10):
        m.observe_finish(_timing(rid=f"r{i}", gen=1))
    assert m.completed == 10                   # counter keeps the total
    assert m.snapshot()["requests"]["ttft_s"]["count"] == 3


def test_per_tenant_accounting_in_snapshot():
    """The tenants section attributes submits, admissions, tokens, and
    finish reasons (including sheds) to each adapter_id — the fleet's
    fairness observability rides on these counters."""
    m = ServingMetrics(n_slots=2)
    m.observe_submit(adapter_id=1)
    m.observe_submit(adapter_id=1)
    m.observe_submit(adapter_id=2)
    m.observe_prefill(adapter_id=1)
    m.observe_finish(_timing(rid="a", gen=4), adapter_id=1)
    m.observe_cancel("shed", adapter_id=2, tokens=0)
    m.observe_cancel("deadline", adapter_id=1, tokens=3)
    snap = json.loads(json.dumps(m.snapshot()))
    t1, t2 = snap["tenants"]["1"], snap["tenants"]["2"]
    assert t1 == {"submitted": 2, "admitted": 1, "tokens": 7,
                  "finished": {"length": 1, "deadline": 1}}
    assert t2 == {"submitted": 1, "admitted": 0, "tokens": 0,
                  "finished": {"shed": 1}}
    # tenant keys sort numerically-as-strings for stable JSON diffs
    assert list(snap["tenants"]) == ["1", "2"]
