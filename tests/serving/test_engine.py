"""ServingEngine: the acceptance properties of the continuous-batching
loop — greedy token-identity to ``TransformerLM.generate`` under
interleaved mixed-length load, slot reclaim past the slot budget,
backpressure, streaming, sampled determinism, and the sharded ops."""

import itertools

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models.transformer import TransformerLM, build_mesh_sp
from elephas_tpu.serving import AdmissionError, ServingEngine

pytestmark = pytest.mark.serving

V = 17


def _model(**kw):
    cfg = dict(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=48)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=1):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


def _mixed_requests(rng, n, lens=(2, 3, 5, 7, 9, 11), news=(3, 5, 7, 9)):
    """n (prompt, max_new) pairs cycling through mixed geometries."""
    li, ni = itertools.cycle(lens), itertools.cycle(news)
    return [(rng.integers(0, V, size=(next(li),)).astype(np.int32), next(ni))
            for _ in range(n)]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


def test_greedy_identity_interleaved_mixed_lengths():
    """≥8 concurrent mixed-length requests, submissions interleaved with
    steps: every greedy continuation must equal the per-request
    ``generate`` EXACTLY, and 12 requests must flow through 8 slots (slot
    reclaim under load)."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(0)
    reqs = _mixed_requests(rng, 12)
    eng = ServingEngine(model, params, n_slots=8, max_queue=16)

    ids = []
    for i, (prompt, max_new) in enumerate(reqs):
        ids.append(eng.submit(prompt, max_new))
        if i >= 4:
            eng.step()          # interleave: decode while submitting
    assert eng.kv.active_slots > 0      # genuinely concurrent mid-stream
    fin = eng.drain(max_steps=2000)
    assert len(fin) == 12

    for rid, (prompt, max_new) in zip(ids, reqs):
        ref = np.asarray(model.generate(params, prompt[None],
                                        max_new))[0, len(prompt):]
        got = np.asarray(fin[rid].tokens)
        np.testing.assert_array_equal(got, ref, err_msg=rid)
        assert fin[rid].finish_reason == "length"


def test_serves_more_requests_than_slots():
    """A 2-slot engine must serve 7 requests — slots are reclaimed and
    reused, and occupancy/queue gauges stay consistent throughout."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(3)
    reqs = _mixed_requests(rng, 7)
    eng = ServingEngine(model, params, n_slots=2, max_queue=16)
    ids = [eng.submit(p, m) for p, m in reqs]
    fin = eng.drain(max_steps=2000)
    assert sorted(fin) == sorted(ids)
    snap = eng.snapshot()
    assert snap["counters"]["completed"] == 7
    assert snap["engine"]["active_slots"] == 0
    assert snap["engine"]["queue_depth"] == 0
    assert snap["engine"]["prefills"] == 7


def test_backpressure_rejects_when_queue_full():
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(4)
    eng = ServingEngine(model, params, n_slots=1, max_queue=2)
    p = rng.integers(0, V, size=(3,)).astype(np.int32)
    eng.submit(p, 2)
    eng.submit(p, 2)
    with pytest.raises(AdmissionError) as ei:
        eng.submit(p, 2)
    assert ei.value.reason == "queue_full"
    assert eng.snapshot()["counters"]["rejected"] == {"queue_full": 1}
    # the engine still drains the admitted work afterwards
    assert len(eng.drain(max_steps=500)) == 2


def test_admission_validation_reasons():
    model = _model()
    eng = ServingEngine(model, _params(model), n_slots=1)
    long_prompt = np.zeros(model.max_len + 1, np.int32)
    with pytest.raises(AdmissionError) as ei:
        eng.submit(long_prompt, 1)
    assert ei.value.reason == "prompt_too_long"
    with pytest.raises(AdmissionError) as ei:
        eng.submit(np.zeros(40, np.int32), 20)
    assert ei.value.reason == "length_exceeds_cache"
    with pytest.raises(AdmissionError) as ei:
        eng.submit(np.zeros(4, np.int32), 0)
    assert ei.value.reason == "bad_request"
    rid = eng.submit(np.zeros(4, np.int32), 2, request_id="dup")
    with pytest.raises(AdmissionError) as ei:
        eng.submit(np.zeros(4, np.int32), 2, request_id="dup")
    assert ei.value.reason == "bad_request"
    assert rid == "dup"


def test_streaming_callbacks_in_order_with_done_flag():
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, V, size=(6,)).astype(np.int32)
    seen = []
    eng = ServingEngine(model, params, n_slots=2)
    rid = eng.submit(prompt, 5,
                     on_token=lambda r, t, d: seen.append((r, t, d)))
    fin = eng.drain(max_steps=200)
    assert [t for _, t, _ in seen] == fin[rid].tokens
    assert [d for _, _, d in seen] == [False] * 4 + [True]
    assert all(r == rid for r, _, _ in seen)


def test_eos_finishes_early_and_frees_slot():
    """Pick the greedy rollout's 3rd generated token as EOS: the engine
    must stop there (EOS included), report reason 'eos', and reuse the
    slot for the next request."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(6)
    prompt = rng.integers(0, V, size=(5,)).astype(np.int32)
    ref = np.asarray(model.generate(params, prompt[None], 8))[0, 5:]
    eos = int(ref[2])
    stop = int(np.argmax(ref == eos))       # first occurrence (could be <2)

    eng = ServingEngine(model, params, n_slots=1)
    rid = eng.submit(prompt, 8, eos_id=eos)
    rid2 = eng.submit(prompt, 3)            # queued behind the 1 slot
    fin = eng.drain(max_steps=200)
    np.testing.assert_array_equal(fin[rid].tokens, ref[:stop + 1])
    assert fin[rid].finish_reason == "eos"
    assert len(fin[rid2].tokens) == 3       # slot was reclaimed and reused


def test_sampled_stream_independent_of_cobatching():
    """A sampled request's tokens are a function of (seed, position) only:
    the same submission must produce identical tokens whether it runs
    alone in a 2-slot engine or co-batched with 3 others in a 4-slot
    one — and two different seeds must (here) differ."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, V, size=(6,)).astype(np.int32)
    others = _mixed_requests(rng, 3)

    solo = ServingEngine(model, params, n_slots=2)
    r1 = solo.submit(prompt, 10, temperature=0.8, seed=42)
    solo.drain(max_steps=200)

    solo_tokens = solo.result(r1).tokens  # result() pops: read once

    busy = ServingEngine(model, params, n_slots=4)
    for p, m in others:
        busy.submit(p, m, temperature=1.3, seed=9)
    r2 = busy.submit(prompt, 10, temperature=0.8, seed=42)
    fin = busy.drain(max_steps=500)
    assert solo_tokens == fin[r2].tokens

    reseed = ServingEngine(model, params, n_slots=2)
    r3 = reseed.submit(prompt, 10, temperature=0.8, seed=43)
    reseed.drain(max_steps=200)
    assert reseed.result(r3).tokens != solo_tokens


def test_timing_with_fake_clock():
    """Injected clock pins the metrics exactly: TTFT counts queue wait,
    and a queued request's wait exceeds an immediately-admitted one's."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(8)
    p = rng.integers(0, V, size=(4,)).astype(np.int32)
    eng = ServingEngine(model, params, n_slots=1, clock=FakeClock())
    r1 = eng.submit(p, 2)
    r2 = eng.submit(p, 2)
    fin = eng.drain(max_steps=100)
    t1, t2 = fin[r1].timing, fin[r2].timing
    assert t1.queue_wait is not None and t2.queue_wait is not None
    assert t2.queue_wait > t1.queue_wait
    assert t1.ttft == t1.first_token_at - t1.submitted_at
    assert t1.generated_tokens == 2 and t2.generated_tokens == 2


def test_sharded_engine_matches_local_greedy():
    """The dp×sp serving ops (slots over "data", cache time over "seq")
    must be a drop-in: identical greedy tokens to the single-device
    engine and to per-request generate."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(9)
    reqs = _mixed_requests(rng, 6)
    mesh = build_mesh_sp(data=2, seq=2)
    eng = ServingEngine(model, params, n_slots=4, mesh=mesh)
    ids = [eng.submit(p, m) for p, m in reqs]
    fin = eng.drain(max_steps=1000)
    assert len(fin) == 6
    for rid, (prompt, max_new) in zip(ids, reqs):
        ref = np.asarray(model.generate(params, prompt[None],
                                        max_new))[0, len(prompt):]
        np.testing.assert_array_equal(np.asarray(fin[rid].tokens), ref,
                                      err_msg=rid)


def test_result_pop_on_read_and_peek():
    """result() is pop-on-read — the retention contract — with pop=False
    as the explicit peek."""
    model = _model()
    eng = ServingEngine(model, _params(model), n_slots=1)
    rid = eng.submit(np.zeros(3, np.int32), 2)
    eng.drain(max_steps=100)
    assert eng.result(rid, pop=False).finish_reason == "length"  # peek
    assert eng.result(rid, pop=False) is not None                # still there
    assert eng.result(rid).finish_reason == "length"             # pop
    assert eng.result(rid) is None                               # gone
    # a popped id is reusable, like a finished-and-evicted one
    assert eng.submit(np.zeros(3, np.int32), 2, request_id=rid) == rid


def test_finished_retention_is_bounded():
    """Unread results must not accumulate forever: past max_finished the
    OLDEST records are evicted (and counted), the newest retained."""
    model = _model()
    eng = ServingEngine(model, _params(model), n_slots=1, max_finished=2)
    rids = [eng.submit(np.zeros(3, np.int32), 2) for _ in range(5)]
    eng.drain(max_steps=500)
    assert [eng.result(r, pop=False) is not None for r in rids] == \
        [False, False, False, True, True]
    assert eng.snapshot()["counters"]["results_evicted"] == 3
    with pytest.raises(ValueError):
        ServingEngine(model, _params(model), n_slots=1, max_finished=0)


def test_cancel_active_frees_slot_and_keeps_partial_tokens():
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(12)
    p = rng.integers(0, V, size=(4,)).astype(np.int32)
    eng = ServingEngine(model, params, n_slots=1)
    rid = eng.submit(p, 10)
    eng.step()                               # prefill: 1 token out
    eng.step()                               # decode: 2nd token
    assert eng.kv.active_slots == 1
    assert eng.cancel(rid)
    assert eng.kv.active_slots == 0          # O(1) slot reclaim
    fin = eng.result(rid)
    assert fin.finish_reason == "cancelled"
    assert len(fin.tokens) == 2              # partials preserved
    assert eng.cancel(rid) is False          # not live any more
    assert eng.cancel("never-existed") is False
    assert eng.snapshot()["counters"]["cancelled"] == {"cancelled": 1}
    # the freed slot is immediately reusable
    rid2 = eng.submit(p, 3)
    eng.drain(max_steps=100)
    assert eng.result(rid2).finish_reason == "length"


def test_cancel_queued_never_occupies_slot():
    """Cancelling a queued request tombstones it in O(1): it never
    prefills, the queue gauge drops, and the rest drain normally."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(13)
    p = rng.integers(0, V, size=(4,)).astype(np.int32)
    eng = ServingEngine(model, params, n_slots=1, max_queue=8)
    busy = eng.submit(p, 4)
    eng.step()                               # busy takes the only slot
    assert eng.kv.free_slots == 0
    doomed = eng.submit(p, 4)
    assert eng.scheduler.queue_depth == 1
    assert eng.cancel(doomed)
    assert eng.scheduler.queue_depth == 0
    fin = eng.drain(max_steps=200)
    assert eng.result(doomed).tokens == []   # never ran
    assert fin[busy].finish_reason == "length"
    assert eng.snapshot()["engine"]["prefills"] == 1
    # no slot leak: the cancel never touched the slot budget, and the
    # drain returned busy's slot
    assert eng.kv.free_slots == 1 and eng.kv.active_slots == 0


def test_deadline_expired_in_queue_is_shed_not_reaped():
    """A request that times out while still QUEUED is SHED with zero
    tokens and a distinct ``"shed"`` finish reason — it never cost a
    slot, which is different from a ``"deadline"`` reap of admitted
    work (callers can retry a shed against another replica). The slot
    goes to work that can still meet its deadline."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(14)
    p = rng.integers(0, V, size=(4,)).astype(np.int32)
    eng = ServingEngine(model, params, n_slots=1, clock=FakeClock())
    busy = eng.submit(p, 6)
    doomed = eng.submit(p, 6, deadline_s=2.0)   # FakeClock: +1s per call
    fin = eng.drain(max_steps=200)
    assert fin[doomed].finish_reason == "shed"
    assert fin[doomed].tokens == []
    assert fin[busy].finish_reason == "length"
    assert eng.snapshot()["counters"]["cancelled"] == {"shed": 1}
    with pytest.raises(AdmissionError) as ei:
        eng.submit(p, 2, deadline_s=0.0)
    assert ei.value.reason == "bad_request"


def test_shed_at_admission_when_budget_provably_overruns():
    """With an ``itl_estimate_s`` latency floor, a queued request whose
    remaining budget times the floor overruns its deadline is shed at
    decide() BEFORE it wastes a prefill — even though the deadline has
    not expired yet. A meetable request with the same deadline admits
    and finishes."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(15)
    p = rng.integers(0, V, size=(4,)).astype(np.int32)
    eng = ServingEngine(model, params, n_slots=2, clock=FakeClock(),
                        itl_estimate_s=10.0)
    # deadline 60 fake-seconds out: 8 tokens * 10 s/token = 80 > 60
    hopeless = eng.submit(p, 8, deadline_s=60.0)
    fine = eng.submit(p, 3, deadline_s=60.0)    # 30 < 60: provably fine
    fin = eng.drain(max_steps=200)
    assert fin[hopeless].finish_reason == "shed"
    assert fin[hopeless].tokens == []
    assert fin[fine].finish_reason == "length"
    assert eng.snapshot()["engine"]["prefills"] == 1  # hopeless never ran
    with pytest.raises(ValueError):
        ServingEngine(model, params, itl_estimate_s=0.0)


def test_injectable_perf_clock_makes_histograms_deterministic():
    """The latency histograms (dispatch overhead etc.) read the engine's
    ``perf_clock``, not a hard-coded ``perf_counter``: injecting a
    deterministic clock makes two identical runs produce bit-identical
    histogram sections — the property fleet trace replay relies on."""

    class CountingClock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            self.t += 0.25
            return self.t

    def run_once():
        model = _model()
        params = _params(model)
        rng = np.random.default_rng(21)
        eng = ServingEngine(model, params, n_slots=2, clock=FakeClock(),
                            perf_clock=CountingClock())
        for i, (p, n) in enumerate(_mixed_requests(rng, 3)):
            eng.submit(p, n, request_id=f"r{i}")
        eng.drain(max_steps=300)
        return eng.snapshot()

    a, b = run_once(), run_once()
    assert a == b                            # the WHOLE snapshot pins
    d = a["fastpath"]["dispatch_overhead_s"]
    assert d["count"] > 0
    # every sample derives from the injected clock's 0.25 grid, so the
    # percentiles are exact multiples of it — impossible with perf_counter
    assert d["p50"] % 0.25 == 0
