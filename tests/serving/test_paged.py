"""Paged serving engine: token identity vs the dense ``SlotKVCache``
engine, prefix-cache reuse, preemption transparency, page-returning
cancellation/reaping, page-gated admission, and multi-tenant LoRA.

The acceptance bar mirrors the fast path's: ``paged=True`` must not
change a single emitted token — greedy or seeded-sampled, local or
dp×sp-sharded, with or without ``prefill_chunk``/``fuse_k`` — while KV
HBM scales with live tokens instead of ``slots × max_len``."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models.lora import MultiTenantLM
from elephas_tpu.models.transformer import TransformerLM, build_mesh_sp
from elephas_tpu.resilience import FaultPlan
from elephas_tpu.serving import AdmissionError, ServingEngine

pytestmark = [pytest.mark.serving, pytest.mark.paged]

V = 17


def _model(**kw):
    cfg = dict(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=48)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=1):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


def _prompts(rng, lens):
    return [rng.integers(0, V, size=(n,)).astype(np.int32) for n in lens]


def _run(eng, reqs, **submit_kw):
    ids = []
    for i, (prompt, max_new) in enumerate(reqs):
        ids.append(eng.submit(prompt, max_new, seed=i, **submit_kw))
        eng.step()
    eng.drain(max_steps=5000)
    return [eng.result(rid).tokens for rid in ids]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


# -- token identity vs the dense engine ----------------------------------

@pytest.mark.parametrize("page", [8, 16])
def test_paged_local_identity_greedy_and_sampled(page):
    """Mixed greedy/sampled batch: the paged engine's streams equal the
    dense engine's AND per-request ``generate`` (greedy rows)."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(0)
    prompts = _prompts(rng, [5, 11, 23, 3, 17, 9])
    reqs = [(p, 8) for p in prompts]

    def both(temp):
        dense = _run(ServingEngine(model, params, n_slots=4), reqs,
                     temperature=temp)
        eng = ServingEngine(model, params, n_slots=4, paged=True,
                            page_size=page)
        paged = _run(eng, reqs, temperature=temp)
        eng.kv.check()
        return dense, paged

    dense, paged = both(0.0)
    assert dense == paged
    for prompt, toks in zip(prompts, paged):
        ref = np.asarray(model.generate(params, prompt[None], 8))
        assert toks == ref[0, len(prompt):].tolist()
    dense, paged = both(0.9)
    assert dense == paged


def test_paged_local_identity_chunked_and_fused():
    """``paged=True`` composes with ``prefill_chunk`` and ``fuse_k``
    token-identically (the chunk grid may even SHIFT when a prefix hit
    skips leading pages — the capacity-length reduction makes any chunk
    decomposition bitwise-equal)."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(1)
    reqs = [(p, 6) for p in _prompts(rng, [20, 3, 26, 17, 9])]
    dense = _run(ServingEngine(model, params, n_slots=2, prefill_chunk=8,
                               fuse_k=3), reqs, temperature=0.7)
    eng = ServingEngine(model, params, n_slots=2, prefill_chunk=8,
                        fuse_k=3, paged=True, page_size=8)
    assert dense == _run(eng, reqs, temperature=0.7)
    assert eng.snapshot()["fastpath"]["prefill_chunks"] >= 4
    assert eng.snapshot()["fastpath"]["fused_blocks"] >= 1
    eng.kv.check()


def test_paged_sharded_identity():
    """The dp×sp paged programs (gathered block-table views over the
    pool sharded ``(data, seq)``) are token-identical to the LOCAL dense
    engine, plain and with chunked prefill + fused decode."""
    mesh = build_mesh_sp(data=2, seq=2)
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(2)
    reqs = [(p, 6) for p in _prompts(rng, [21, 4, 18, 11])]

    local = _run(ServingEngine(model, params, n_slots=4), reqs,
                 temperature=0.8)
    eng = ServingEngine(model, params, n_slots=4, mesh=mesh, paged=True,
                        page_size=8)
    assert local == _run(eng, reqs, temperature=0.8)
    eng.kv.check()
    fast = ServingEngine(model, params, n_slots=4, mesh=mesh, paged=True,
                         page_size=8, prefill_chunk=8, fuse_k=3)
    assert local == _run(fast, reqs, temperature=0.8)
    fast.kv.check()


# -- prefix cache ---------------------------------------------------------

def test_prefix_cache_reuse_identity_and_hit_ratio():
    """Requests sharing a token prefix adopt its pages (skipping their
    prefill) and STILL emit identical tokens; the snapshot reports the
    hit ratio."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(3)
    system = rng.integers(0, V, size=(16,)).astype(np.int32)
    prompts = [np.concatenate([system,
                               rng.integers(0, V, size=(6,)).astype(np.int32)])
               for _ in range(6)]
    reqs = [(p, 6) for p in prompts]

    dense = _run(ServingEngine(model, params, n_slots=2), reqs)
    eng = ServingEngine(model, params, n_slots=2, paged=True, page_size=8)
    assert dense == _run(eng, reqs)
    mem = eng.snapshot()["memory"]
    # first request is cold; the other five adopt the 2 system pages
    assert mem["prefix"]["hits_pages"] >= 10
    assert mem["prefix"]["hit_ratio"] > 0.5
    # identical RESUBMISSION hits end-to-end and repeats the stream
    again = _run(eng, reqs, request_id=None)
    assert again == dense
    eng.kv.check()


def test_prefix_cache_off_still_identical():
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(4)
    reqs = [(p, 5) for p in _prompts(rng, [9, 21, 13])]
    dense = _run(ServingEngine(model, params, n_slots=2), reqs)
    eng = ServingEngine(model, params, n_slots=2, paged=True, page_size=8,
                        prefix_cache=False)
    assert dense == _run(eng, reqs)
    assert eng.snapshot()["memory"]["prefix"]["nodes"] == 0


# -- preemption -----------------------------------------------------------

def test_preemption_is_token_transparent():
    """A pool too small for the co-batch forces preemption (newest
    victim, requeued at the front); every stream still matches the dense
    engine exactly — recompute-preemption is invisible in the output."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(5)
    prompts = _prompts(rng, [21, 19, 23, 17])
    reqs = [(p, 12) for p in prompts]
    dense = _run(ServingEngine(model, params, n_slots=4), reqs,
                 temperature=0.8)
    # each request peaks at ceil((23+12)/8)=5 pages; 11 usable pages
    # cannot hold 4x5, so page pressure must preempt
    eng = ServingEngine(model, params, n_slots=4, paged=True, page_size=8,
                        pages_per_partition=12, prefix_cache=False)
    assert dense == _run(eng, reqs, temperature=0.8)
    assert eng.kv.preemptions > 0
    assert eng.snapshot()["memory"]["preemptions"] > 0
    eng.kv.check()
    assert eng.kv.memory_stats()["pages_used"] == 0   # all returned


def test_submit_rejects_request_that_never_fits():
    model = _model()
    params = _params(model)
    eng = ServingEngine(model, params, n_slots=2, paged=True, page_size=8,
                        pages_per_partition=4)      # 3 usable pages = 24 tok
    with pytest.raises(AdmissionError) as ei:
        eng.submit(np.zeros(20, np.int32), max_new=8)
    assert ei.value.reason == "length_exceeds_cache"
    eng.submit(np.zeros(16, np.int32), max_new=8)   # exactly 3 pages: fine


# -- cancellation / deadline chaos ---------------------------------------

def test_cancel_mid_decode_returns_pages():
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(6)
    prompts = _prompts(rng, [17, 9, 21])
    eng = ServingEngine(model, params, n_slots=4, paged=True, page_size=8)
    ids = [eng.submit(p, 12, seed=i, request_id=f"r{i}")
           for i, p in enumerate(prompts)]
    for _ in range(8):
        eng.step()
    used_before = eng.kv.memory_stats()["pages_used"]
    assert eng.cancel(ids[1])
    eng.kv.check()
    assert eng.kv.memory_stats()["pages_used"] < used_before
    assert eng.result(ids[1]).finish_reason == "cancelled"
    eng.drain(max_steps=5000)
    # survivors are unperturbed: same tokens as per-request generate
    for i in (0, 2):
        ref = np.asarray(model.generate(params, prompts[i][None], 12))
        assert (eng.result(ids[i]).tokens
                == ref[0, len(prompts[i]):].tolist())
    eng.kv.check()
    assert eng.kv.memory_stats()["pages_used"] == \
        eng.kv.memory_stats()["prefix"]["nodes"]    # only clean cache pages
    eng.kv.evict_pages(0, 100)
    assert eng.kv.memory_stats()["pages_used"] == 0


def test_cancel_queued_request_holds_no_pages():
    """Cancel a still-QUEUED (never admitted) request on the paged
    engine: it holds no slot and no page refs yet, so the cancel must
    change NOTHING in the allocator — ``pages_used`` identical before
    and after, ``kv.check()`` exact — and survivors sharing its would-be
    prefix stream unperturbed, leaving only clean prefix-cache pages."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(16)
    system = rng.integers(0, V, size=(16,)).astype(np.int32)
    prompts = [np.concatenate([system,
                               rng.integers(0, V, size=(4,)).astype(np.int32)])
               for _ in range(4)]
    eng = ServingEngine(model, params, n_slots=2, paged=True, page_size=8)
    admitted = [eng.submit(prompts[i], 10, request_id=f"a{i}")
                for i in range(2)]
    for _ in range(4):
        eng.step()                       # both admitted, slots full
    assert eng.kv.free_slots == 0
    queued = [eng.submit(prompts[i], 10, request_id=f"q{i}")
              for i in (2, 3)]
    assert eng.scheduler.queue_depth == 2
    used_before = eng.kv.memory_stats()["pages_used"]
    assert eng.cancel(queued[0])
    eng.kv.check()                       # refcounts exact after cancel
    assert eng.kv.memory_stats()["pages_used"] == used_before
    assert eng.scheduler.queue_depth == 1
    rec = eng.result(queued[0])
    assert rec.finish_reason == "cancelled" and rec.tokens == []
    eng.drain(max_steps=5000)
    eng.kv.check()
    # survivors and the still-queued sibling: bitwise per-request identity
    for i, rid in ((0, admitted[0]), (1, admitted[1]), (3, queued[1])):
        ref = np.asarray(model.generate(params, prompts[i][None], 10))
        assert eng.result(rid).tokens == ref[0, len(prompts[i]):].tolist()
    # every request-held ref released; only clean prefix pages remain
    stats = eng.kv.memory_stats()
    assert stats["pages_used"] == stats["prefix"]["nodes"]
    eng.kv.evict_pages(0, stats["pages_total"])
    assert eng.kv.memory_stats()["pages_used"] == 0
    eng.kv.check()


def test_chaos_deadline_reaps_decref_shared_prefix():
    """A ``FaultPlan`` stall kills requests mid-decode via their
    deadlines; the reaps must return every page INCLUDING decrefs of
    prefix pages shared with survivors, and the allocator cross-check
    must hold after each step."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(7)
    system = rng.integers(0, V, size=(16,)).astype(np.int32)
    prompts = [np.concatenate([system,
                               rng.integers(0, V, size=(4,)).astype(np.int32)])
               for _ in range(4)]
    plan = FaultPlan(serving_stalls={6: 50.0})     # step 6 "takes" 50s
    eng = ServingEngine(model, params, n_slots=4, paged=True, page_size=8,
                        clock=FakeClock(), fault_plan=plan)
    doomed = [eng.submit(prompts[i], 20, request_id=f"d{i}",
                         deadline_s=30.0) for i in range(2)]
    safe = [eng.submit(prompts[i], 20, request_id=f"s{i}")
            for i in (2, 3)]
    while eng.scheduler.queue_depth or eng.kv.active_slots:
        eng.step()
        eng.kv.check()                              # invariants EVERY step
    for rid in doomed:
        fin = eng.result(rid)
        assert fin.finish_reason == "deadline"
        assert len(fin.tokens) < 20
    for i, rid in zip((2, 3), safe):
        ref = np.asarray(model.generate(params, prompts[i][None], 20))
        assert eng.result(rid).tokens == ref[0, len(prompts[i]):].tolist()
    # all request-held refs are gone: only clean prefix pages remain
    stats = eng.kv.memory_stats()
    assert stats["pages_used"] == stats["prefix"]["nodes"]
    eng.kv.evict_pages(0, stats["pages_total"])
    assert eng.kv.memory_stats()["pages_used"] == 0
    eng.kv.check()


# -- page-gated admission (no starvation) --------------------------------

def test_admission_by_free_pages_long_prompt_not_starved():
    """PINNED no-starvation contract: a long-prompt request at the queue
    head is never overtaken by cheaper requests behind it — admission
    gates on the HEAD's page need, so short requests wait until the head
    admits, even while a slot sits free."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(8)
    eng = ServingEngine(model, params, n_slots=2, paged=True, page_size=8,
                        pages_per_partition=8, clock=FakeClock())
    a = eng.submit(rng.integers(0, V, size=(30,)).astype(np.int32), 8,
                   request_id="a")
    assert eng.step() == "prefill"                  # a admitted, 4-5 pages
    long = eng.submit(rng.integers(0, V, size=(33,)).astype(np.int32), 6,
                      request_id="long")
    short = eng.submit(rng.integers(0, V, size=(4,)).astype(np.int32), 2,
                       request_id="short")
    # the starvation bait: a slot is free and `short` would fit its
    # pages, but the HEAD (`long`) does not -> the engine must decode,
    # not admit `short` past it
    assert eng.kv.free_slots == 1
    assert eng.step() == "decode"
    assert eng._requests["long"].slot is None
    assert eng._requests["short"].slot is None
    eng.drain(max_steps=5000)
    fins = {rid: eng.result(rid) for rid in (a, "long", "short")}
    assert all(f.finish_reason == "length" for f in fins.values())
    # pinned order: `long` was admitted strictly before `short`
    assert (fins["long"].timing.admitted_at
            < fins["short"].timing.admitted_at)
    for rid, n in (("a", 8), ("long", 6), ("short", 2)):
        prompt = fins[rid].prompt
        ref = np.asarray(model.generate(params, prompt[None], n))
        assert fins[rid].tokens == ref[0, len(prompt):].tolist()
    eng.kv.check()


# -- multi-tenant LoRA ----------------------------------------------------

def test_multi_tenant_adapter0_identity_and_validation():
    """Adapter 0 (zero-initialized B) equals the plain base model;
    adapter ids are validated at submit on both engines."""
    mt = MultiTenantLM(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
                       max_len=48, n_adapters=3, lora_rank=4)
    mtp = {k: jnp.asarray(v) for k, v in mt.init(seed=1).items()}
    base = mt.base_model()
    basep = {k: v for k, v in mtp.items() if not k.startswith("lora_")}
    rng = np.random.default_rng(9)
    reqs = [(p, 8) for p in _prompts(rng, [5, 17, 11, 23])]
    want = _run(ServingEngine(base, basep, n_slots=4), reqs,
                temperature=0.8)
    eng = ServingEngine(mt, mtp, n_slots=4, paged=True, page_size=8)
    assert want == _run(eng, reqs, temperature=0.8, adapter_id=0)
    with pytest.raises(AdmissionError) as ei:
        eng.submit(reqs[0][0], 2, adapter_id=3)
    assert ei.value.reason == "bad_request"
    dense = ServingEngine(mt, mtp, n_slots=2)
    with pytest.raises(AdmissionError):
        dense.submit(reqs[0][0], 2, adapter_id=1)   # dense is single-tenant


def test_multi_tenant_cobatch_matches_merged_dense():
    """Co-batched tenants with DIFFERENT adapters each match a dedicated
    dense engine running that tenant's merged weights — per-slot adapter
    selection inside the one decode program is exact, and tenants do not
    bleed into each other."""
    mt = MultiTenantLM(vocab=V, d_model=16, n_heads=4, n_layers=2, d_ff=32,
                       max_len=48, n_adapters=3, lora_rank=4)
    mtp = mt.init(seed=1)
    mtp = mt.randomize_adapter(mtp, 1, seed=7)
    mtp = mt.randomize_adapter(mtp, 2, seed=8)
    mtp = {k: jnp.asarray(v) for k, v in mtp.items()}
    base = mt.base_model()
    rng = np.random.default_rng(10)
    prompts = _prompts(rng, [21, 19, 23, 17])
    eng = ServingEngine(mt, mtp, n_slots=4, paged=True, page_size=8)
    ids = [eng.submit(p, 10, seed=0, request_id=f"r{i}", adapter_id=i % 3)
           for i, p in enumerate(prompts)]
    eng.drain(max_steps=5000)
    for i, (p, rid) in enumerate(zip(prompts, ids)):
        merged = mt.merged_params(mtp, i % 3)
        ref = ServingEngine(base, merged, n_slots=1)
        ref.submit(p, 10, seed=0, request_id="x")
        ref.drain(max_steps=5000)
        assert eng.result(rid).tokens == ref.result("x").tokens, i
    eng.kv.check()


# -- observability --------------------------------------------------------

def test_snapshot_memory_section_json_roundtrip():
    model = _model()
    params = _params(model)
    eng = ServingEngine(model, params, n_slots=2, paged=True, page_size=8)
    rng = np.random.default_rng(11)
    _run(eng, [(p, 4) for p in _prompts(rng, [9, 13])])
    snap = json.loads(json.dumps(eng.snapshot()))
    mem = snap["memory"]
    assert mem["page_size"] == 8
    assert 0.0 <= mem["page_utilization"] <= 1.0
    assert mem["kv_hbm_bytes"] > 0
    assert mem["pages_used"] + mem["pages_free"] == mem["pages_total"]
    assert 0.0 <= mem["prefix"]["hit_ratio"] <= 1.0
    # the dense engine has no memory section (stable schema)
    dense = ServingEngine(model, params, n_slots=2)
    assert "memory" not in dense.snapshot()
