"""Scheduler: priority-then-FIFO order, bounded-queue backpressure, the
prefill-vs-decode decision."""

import numpy as np
import pytest

from elephas_tpu.serving.scheduler import (
    AdmissionError,
    Scheduler,
    ServingRequest,
)

pytestmark = pytest.mark.serving


def _req(rid, priority=0):
    return ServingRequest(request_id=rid, prompt=np.zeros(2, np.int32),
                          max_new=4, priority=priority)


def test_fifo_within_priority():
    s = Scheduler(max_queue=8)
    for rid in ["a", "b", "c"]:
        s.push(_req(rid))
    assert [s.pop().request_id for _ in range(3)] == ["a", "b", "c"]
    assert s.pop() is None


def test_priority_wins_fifo_breaks_ties():
    s = Scheduler(max_queue=8)
    s.push(_req("low-1", priority=0))
    s.push(_req("hi-1", priority=5))
    s.push(_req("low-2", priority=0))
    s.push(_req("hi-2", priority=5))
    order = [s.pop().request_id for _ in range(4)]
    assert order == ["hi-1", "hi-2", "low-1", "low-2"]


def test_bounded_queue_rejects_with_reason():
    s = Scheduler(max_queue=2)
    s.push(_req("a"))
    s.push(_req("b"))
    with pytest.raises(AdmissionError) as ei:
        s.push(_req("c"))
    assert ei.value.reason == "queue_full"
    # rejection is non-destructive: both queued requests still come out
    assert s.queue_depth == 2
    s.pop()
    s.push(_req("c"))                       # capacity freed → admitted
    assert s.queue_depth == 2


def test_decide_is_prefill_first():
    s = Scheduler(max_queue=4)
    assert s.decide(free_slots=2, active_slots=0) == "idle"
    assert s.decide(free_slots=0, active_slots=3) == "decode"
    s.push(_req("a"))
    # waiting work + a free slot → admit before decoding
    assert s.decide(free_slots=1, active_slots=3) == "prefill"
    # no free slot → the queue waits, decode proceeds
    assert s.decide(free_slots=0, active_slots=3) == "decode"
    s.pop()
    assert s.decide(free_slots=1, active_slots=0) == "idle"


def test_max_queue_validation():
    with pytest.raises(ValueError):
        Scheduler(max_queue=0)
