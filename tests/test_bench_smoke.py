"""bench.py smoke test: the driver-run benchmark must always produce its
one JSON line, whatever happens to the internals it exercises."""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_emits_json_line():
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "KERAS_BACKEND": "jax",
        "BENCH_NO_PROBE": "1",
        "BENCH_SAMPLES": "4096",
        "BENCH_EPOCHS": "1",
        "BENCH_REPS": "1",
        # tiny serving geometry: the phase must still land in the JSON
        "BENCH_SERVE_DMODEL": "64",
        "BENCH_SERVE_LAYERS": "2",
        "BENCH_SERVE_VOCAB": "128",
        "BENCH_SERVE_SLOTS": "4",
        "BENCH_SERVE_PROMPT": "8",
        "BENCH_SERVE_NEW": "8",
        # tiny recovery geometry: checkpoint + crash-resume must land too
        "BENCH_REC_SAMPLES": "1024",
        "BENCH_REC_EPOCHS": "2",
        "BENCH_REC_WORKERS": "2",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    line = proc.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["metric"] == "mnist_mlp_sync_samples_per_sec_per_chip"
    assert result["unit"] == "samples/sec/chip"
    assert result["value"] > 0
    assert result["vs_baseline"] > 0
    # the serving phase is CPU-runnable, so its entry must be present
    serving = result["serving"]
    assert serving["agg_tokens_per_sec"] > 0
    assert serving["sequential_tokens_per_sec"] > 0
    assert serving["vs_sequential"] > 0
    assert serving["ttft_p95_ms"] >= serving["ttft_p50_ms"] >= 0
    assert 0 < serving["batch_occupancy"] <= 1
    assert serving["concurrency"] == 4
    # so is the recovery phase: checkpointing tax + one crash-resume cycle
    recovery = result["recovery"]
    assert recovery["plain_fit_s"] > 0
    assert recovery["checkpointed_fit_s"] > 0
    assert recovery["crash_resume_fit_s"] > 0
    assert recovery["epochs"] == 2
    assert recovery["checkpoint_frequency"] == 1
