"""MLlib linalg adapter round-trips (reference: tests/mllib/test_adapter.py)."""

import numpy as np
import pytest

from elephas_tpu.mllib import (
    DenseMatrix,
    DenseVector,
    from_matrix,
    from_vector,
    to_matrix,
    to_vector,
)


def test_vector_round_trip():
    v = np.array([1.0, -2.0, 3.5])
    mv = to_vector(v)
    assert isinstance(mv, DenseVector)
    assert np.allclose(from_vector(mv), v)


def test_matrix_round_trip():
    m = np.arange(6, dtype="float64").reshape(2, 3)
    mm = to_matrix(m)
    assert isinstance(mm, DenseMatrix)
    assert mm.numRows == 2 and mm.numCols == 3
    assert np.allclose(from_matrix(mm), m)


def test_matrix_column_major_storage():
    m = np.array([[1.0, 2.0], [3.0, 4.0]])
    mm = to_matrix(m)
    # MLlib stores column-major
    assert mm.values.tolist() == [1.0, 3.0, 2.0, 4.0]


def test_shape_validation():
    with pytest.raises(ValueError):
        to_vector(np.zeros((2, 2)))
    with pytest.raises(ValueError):
        to_matrix(np.zeros(4))
