"""SparkMLlibModel end-to-end on LabeledPoint RDDs.

The regression cell pins a real bug: non-categorical LabeledPoint labels are
per-sample SCALARS (stacked ``[B]``), and against a ``Dense(1)`` output the
elementwise losses used to broadcast ``[B,1] - [B]`` to ``[B,B]`` — the loss
fell toward the target variance while the gradients were garbage, so the fit
silently predicted the mean. ``resolve_per_sample_loss`` now rank-aligns
(as Keras does); this test fails without it.
"""

import numpy as np

from elephas_tpu import SparkMLlibModel
from elephas_tpu.utils import to_labeled_point


def test_regression_with_scalar_labels_learns(spark_context, toy_regression):
    import keras

    x, y = toy_regression
    y_n = (y - y.mean()) / y.std()
    lp = to_labeled_point(spark_context, x, y_n, categorical=False)

    model = keras.Sequential(
        [keras.layers.Dense(32, activation="relu"), keras.layers.Dense(1)]
    )
    model.build((None, x.shape[1]))
    model.compile(optimizer=keras.optimizers.Adam(1e-2), loss="mse")
    m = SparkMLlibModel(model, mode="synchronous", frequency="batch",
                        num_workers=4)
    m.fit(lp, epochs=25, batch_size=32, validation_split=0.0,
          categorical=False)
    mse = float(np.mean((np.asarray(m.predict(x)).ravel() - y_n) ** 2))
    # broadcast-bug behavior plateaus at ~1.0 (the target variance)
    assert mse < 0.15, f"regression did not learn: mse={mse}"


def test_multiclass_labeled_points_learn(spark_context):
    import keras

    rng = np.random.default_rng(0)
    centers = rng.normal(size=(3, 6)) * 3.0
    labels = rng.integers(0, 3, size=480)
    x = (centers[labels] + rng.normal(size=(480, 6))).astype("float32")

    lp = to_labeled_point(spark_context, x, labels.astype("float64"),
                          categorical=True)
    model = keras.Sequential([
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(3, activation="softmax"),
    ])
    model.build((None, 6))
    model.compile(optimizer=keras.optimizers.Adam(1e-2),
                  loss="categorical_crossentropy", metrics=["accuracy"])
    m = SparkMLlibModel(model, mode="synchronous", frequency="batch",
                        num_workers=4)
    m.fit(lp, epochs=10, batch_size=32, validation_split=0.0,
          categorical=True, nb_classes=3)
    acc = float((np.asarray(m.predict(x)).argmax(1) == labels).mean())
    assert acc > 0.9, acc
