"""dp×tp(×ep) MoE LM vs the replicated / single-device oracles.

Head-sharded attention composed with expert-sharded FFN over one
``("data", "model")`` axis: training trajectories must equal the
replicated dp×sp×ep trainer's (same ep-group semantics: the oracle runs
on a mesh whose seq axis carries the experts), greedy generation must
equal the single-device rollout token-for-token, and per-device expert
shards must actually hold E/tp experts.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from elephas_tpu.models.moe_tp import (
    build_mesh_tp,
    build_moe_lm_tp_generate,
    build_moe_lm_tp_train_step,
    moe_tp_specs,
    shard_moe_tp_params,
)
from elephas_tpu.models.transformer import (
    MoETransformerLM,
    TransformerLM,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)


def _model(tp, **kw):
    cfg = dict(vocab=67, d_model=32, n_heads=4, n_layers=2, d_ff=48,
               max_len=16, n_experts=8, k=2, capacity_factor=2.0,
               aux_weight=1e-2, ep_groups=tp, pos_encoding="rotary",
               norm="rmsnorm", activation="swiglu", ffn_bias=False)
    cfg.update(kw)
    return MoETransformerLM(**cfg)


def _rows(b=8, t=16, seed=0):
    return np.random.default_rng(seed).integers(0, 67, size=(b, t + 1))


@pytest.mark.parametrize("dp,tp", [(1, 4), (2, 2), (2, 4)])
def test_trajectory_matches_sp_ep_oracle(dp, tp):
    """The dp×sp×ep trainer (experts over "seq") is the trusted oracle —
    same ep-group capacity semantics when its seq axis size == tp."""
    model = _model(tp)
    rows = _rows()

    # oracle: replicated attention, experts over "seq" (= ep size tp)
    omesh = build_mesh_sp(data=dp, seq=tp)
    ostep, ooi = build_lm_train_step(model, omesh, optax.adam(1e-2),
                                     attn="ring")
    oparams = model.shard_params(omesh, model.init(seed=0))
    ostate = ooi(oparams)
    obatch = shard_lm_batch(omesh, *make_lm_batches(rows))
    o_losses = []
    for _ in range(3):
        oparams, ostate, ol = ostep(oparams, ostate, *obatch)
        o_losses.append(float(ol))
    from elephas_tpu.parallel.param_utils import gather_host

    want = gather_host(oparams)

    mesh = build_mesh_tp(data=dp, model=tp)
    step, oi = build_moe_lm_tp_train_step(model, mesh, optax.adam(1e-2),
                                          attn="dense")
    params = shard_moe_tp_params(mesh, model, model.init(seed=0))
    state = oi(params)
    from jax.sharding import NamedSharding, PartitionSpec as P

    tokens, positions, targets = make_lm_batches(rows)
    sh = NamedSharding(mesh, P("data", None))
    batch = tuple(jax.device_put(a, sh)
                  for a in (tokens, positions, targets))
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=5e-4, atol=5e-5)
    got = gather_host(params)
    for k, v in want.items():
        np.testing.assert_allclose(got[k], v, rtol=2e-3, atol=2e-4,
                                   err_msg=k)


def test_generation_matches_single_device():
    tp = 4
    # capacity that never binds (E/k) — generation parity needs routing
    # identical to the oracle's dropless semantics at every group size
    # (prefill groups by token slices; the oracle's prefill uses one
    # group — exactly the Mixtral-import serving convention)
    model = _model(tp, capacity_factor=4.0)
    mesh = build_mesh_tp(data=2, model=tp)
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    prompt = _rows(b=4, t=7, seed=5)[:, :8].astype(np.int32)

    want = np.asarray(model.generate(params, prompt, 6))
    gen = build_moe_lm_tp_generate(model, mesh, attn="dense")
    got = np.asarray(gen(shard_moe_tp_params(mesh, model, params),
                         prompt, 6))
    np.testing.assert_array_equal(got, want)


def test_per_device_expert_shards():
    tp = 4
    model = _model(tp)
    mesh = build_mesh_tp(data=2, model=tp)
    params = shard_moe_tp_params(mesh, model, model.init(seed=0))
    w1 = params["w1"]  # [L, E, D, F]
    assert w1.shape[1] == 8
    for shard in w1.addressable_shards:
        assert shard.data.shape[1] == 8 // tp
    wq = params["wq"]  # heads column-sharded
    for shard in wq.addressable_shards:
        assert shard.data.shape[-1] == 32 // tp


def test_guards():
    dense = TransformerLM(vocab=32, d_model=16, n_heads=2, n_layers=1,
                          d_ff=32, max_len=8)
    mesh = build_mesh_tp(data=2, model=4)
    with pytest.raises(NotImplementedError, match="MoE"):
        build_moe_lm_tp_train_step(dense, mesh, optax.sgd(0.1))
    bad = _model(4, n_experts=6)  # 6 % 4 != 0
    with pytest.raises(ValueError, match="n_experts"):
        build_moe_lm_tp_train_step(bad, mesh, optax.sgd(0.1))
