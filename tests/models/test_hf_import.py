"""HF checkpoint import: logits + greedy-generation parity vs torch.

CPU torch is the independent oracle — tiny randomly initialized HF models
(GPT-2-, Llama-, GQA-, and bias-variant configs) are converted through
``models/hf_import.py`` and must reproduce the torch forward pass's logits
and ``model.generate``'s greedy tokens exactly (float tolerance). This
doubles as an independent cross-implementation check of the whole
TransformerLM stack (norms, rope, GQA grouping, gelu/swiglu, caches).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from elephas_tpu.models.hf_import import lm_from_hf


def _hf_logits(hf_model, tokens):
    with torch.no_grad():
        out = hf_model(input_ids=torch.tensor(tokens, dtype=torch.long))
    return out.logits.float().numpy()


def _our_logits(model, params, tokens):
    p = jax.tree.map(jnp.asarray, params)
    pos = np.broadcast_to(np.arange(tokens.shape[1]), tokens.shape)
    # Parity is judged at true-f32 matmul precision — JAX's *default*
    # f32 matmul on CPU/TPU may use reduced-precision passes (a runtime
    # speed knob, not a property of the imported weights).
    with jax.default_matmul_precision("float32"):
        return np.asarray(model.apply(p, tokens, pos))


def _assert_logits_close(model, params, hf_model, tokens):
    ours = _our_logits(model, params, tokens)
    theirs = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def _assert_greedy_parity(model, params, hf_model, tokens, n_new=6):
    with torch.no_grad():
        # explicit all-ones mask: HF otherwise infers padding from
        # pad_token_id and would mask real tokens that happen to equal it
        hf_out = hf_model.generate(
            torch.tensor(tokens, dtype=torch.long), max_new_tokens=n_new,
            attention_mask=torch.ones(tokens.shape, dtype=torch.long),
            do_sample=False, eos_token_id=None, pad_token_id=0,
        ).numpy()
    p = jax.tree.map(jnp.asarray, params)
    with jax.default_matmul_precision("float32"):
        ours = np.asarray(model.generate(p, tokens, n_new))
    np.testing.assert_array_equal(ours, hf_out)


def _tiny_gpt2():
    torch.manual_seed(7)
    cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    m = transformers.GPT2LMHeadModel(cfg)
    m.eval()
    return m


def _tiny_llama(**over):
    torch.manual_seed(7)
    kw = dict(
        vocab_size=97, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, rope_theta=10000.0,
        attention_dropout=0.0, tie_word_embeddings=False,
    )
    kw.update(over)
    m = transformers.LlamaForCausalLM(transformers.LlamaConfig(**kw))
    m.eval()
    return m


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(0)
    return rng.integers(0, 97, size=(2, 12)).astype(np.int32)


def test_gpt2_logits_parity(tokens):
    hf = _tiny_gpt2()
    model, params = lm_from_hf(hf)
    assert model.activation == "gelu" and model.attn_bias
    assert model.tie_embeddings and model.pos_encoding == "learned"
    _assert_logits_close(model, params, hf, tokens)


def test_gpt2_greedy_generation_parity(tokens):
    hf = _tiny_gpt2()
    model, params = lm_from_hf(hf)
    _assert_greedy_parity(model, params, hf, tokens)


def test_llama_logits_parity(tokens):
    hf = _tiny_llama()
    model, params = lm_from_hf(hf)
    assert model.activation == "swiglu" and model.norm == "rmsnorm"
    assert not model.ffn_bias and model.pos_encoding == "rotary"
    _assert_logits_close(model, params, hf, tokens)


def test_llama_gqa_logits_parity(tokens):
    hf = _tiny_llama(num_key_value_heads=2)
    model, params = lm_from_hf(hf)
    assert model.n_kv_heads == 2
    _assert_logits_close(model, params, hf, tokens)


def test_llama_attention_bias_variant(tokens):
    # qwen2-style q/k/v biases via the llama config flag
    hf = _tiny_llama(attention_bias=True)
    model, params = lm_from_hf(hf)
    assert model.attn_bias
    _assert_logits_close(model, params, hf, tokens)


def test_llama_tied_and_theta_variant(tokens):
    hf = _tiny_llama(tie_word_embeddings=True, rope_theta=500000.0)
    model, params = lm_from_hf(hf)
    assert model.tie_embeddings and model.rope_theta == 500000.0
    _assert_logits_close(model, params, hf, tokens)


def test_llama_greedy_generation_parity(tokens):
    hf = _tiny_llama(num_key_value_heads=2)
    model, params = lm_from_hf(hf)
    _assert_greedy_parity(model, params, hf, tokens)


def test_imported_model_int8_quantize_still_generates(tokens):
    # the point of the import: downstream machinery applies unchanged
    from elephas_tpu.models.quantize import quantize_lm_params

    hf = _tiny_llama()
    model, params = lm_from_hf(hf)
    qp = quantize_lm_params(jax.tree.map(jnp.asarray, params))
    out = np.asarray(model.generate(qp, tokens, 4))
    assert out.shape == (tokens.shape[0], tokens.shape[1] + 4)


def test_unsupported_model_type_raises():
    hf = _tiny_gpt2()
    hf.config.model_type = "bloom"
    with pytest.raises(NotImplementedError, match="model_type"):
        lm_from_hf(hf)


def test_rope_scaling_rejected(tokens):
    hf = _tiny_llama()
    hf.config.rope_scaling = {"rope_type": "linear", "factor": 2.0}
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        lm_from_hf(hf)


def _tiny_mistral(**over):
    torch.manual_seed(7)
    kw = dict(
        vocab_size=97, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=8, attention_dropout=0.0,
        attn_implementation="eager",
    )
    kw.update(over)
    m = transformers.MistralForCausalLM(transformers.MistralConfig(**kw))
    m.eval()
    return m


def test_mistral_sliding_window_logits_parity():
    # the window BINDS here (T=24 > window=8): parity vs torch's own
    # sliding-window mask validates the whole SWA stack independently
    hf = _tiny_mistral()
    model, params = lm_from_hf(hf)
    assert model.attn_window == 8 and model.max_len == 64
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 97, size=(2, 24)).astype(np.int32)
    _assert_logits_close(model, params, hf, toks)


def test_mistral_non_binding_window_drops_knob():
    hf = _tiny_mistral(sliding_window=64)  # >= max_len: never binds
    model, _ = lm_from_hf(hf)
    assert model.attn_window is None


def test_mistral_greedy_generation_parity(tokens):
    hf = _tiny_mistral()
    model, params = lm_from_hf(hf)
    _assert_greedy_parity(model, params, hf, tokens)


def test_qwen2_mixed_sliding_layers_import_parity():
    """Qwen2 with max_window_layers windows only SOME layers — imported
    as a PER-LAYER attn_window list; logits parity with the window
    BINDING (T=24 > window=8) validates the mixed-window stack against
    torch's own per-layer masks."""
    torch.manual_seed(7)
    cfg = transformers.Qwen2Config(
        vocab_size=97, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, use_sliding_window=True,
        sliding_window=8, max_window_layers=1, attention_dropout=0.0,
        attn_implementation="eager",
    )
    hf = transformers.Qwen2ForCausalLM(cfg)
    hf.eval()
    model, params = lm_from_hf(hf)
    assert model.mixed_window
    assert model.attn_windows == (None, 8)  # layer 0 full, layer 1 slides
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 97, size=(2, 24)).astype(np.int32)
    _assert_logits_close(model, params, hf, toks)


def test_qwen2_mixed_sliding_greedy_generation_parity():
    """Mixed-window decode (linear cache, per-layer masks) must match
    HF generate token-for-token past the window boundary."""
    torch.manual_seed(7)
    cfg = transformers.Qwen2Config(
        vocab_size=97, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, use_sliding_window=True,
        sliding_window=6, max_window_layers=1, attention_dropout=0.0,
        attn_implementation="eager",
    )
    hf = transformers.Qwen2ForCausalLM(cfg)
    hf.eval()
    model, params = lm_from_hf(hf)
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 97, size=(2, 9)).astype(np.int32)
    _assert_greedy_parity(model, params, hf, toks, n_new=8)


def test_qwen2_default_no_sliding_imports_full_attention(tokens):
    torch.manual_seed(7)
    cfg = transformers.Qwen2Config(
        vocab_size=97, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, attention_dropout=0.0,
        attn_implementation="eager",
    )
    hf = transformers.Qwen2ForCausalLM(cfg)
    hf.eval()
    model, params = lm_from_hf(hf)
    assert model.attn_window is None and model.attn_bias  # q/k/v biases
    _assert_logits_close(model, params, hf, tokens)


def _tiny_mixtral(**over):
    torch.manual_seed(7)
    kw = dict(
        vocab_size=97, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4,
        num_experts_per_tok=2, attention_dropout=0.0, sliding_window=None,
        attn_implementation="eager",
    )
    kw.update(over)
    m = transformers.MixtralForCausalLM(transformers.MixtralConfig(**kw))
    m.eval()
    return m


def test_mixtral_logits_parity(tokens):
    # validates the whole MoE routing stack (softmax top-k renormalized
    # combine, per-token dispatch) against HF's independent implementation
    hf = _tiny_mixtral()
    model, params = lm_from_hf(hf)
    assert type(model).__name__ == "MoETransformerLM"
    assert model.moe.activation == "swiglu" and not model.moe.bias
    assert model.moe.capacity_factor * model.moe.k == model.n_experts
    _assert_logits_close(model, params, hf, tokens)


def test_mixtral_greedy_generation_parity(tokens):
    hf = _tiny_mixtral()
    model, params = lm_from_hf(hf)
    _assert_greedy_parity(model, params, hf, tokens)


def test_mixtral_single_expert_per_token(tokens):
    hf = _tiny_mixtral(num_experts_per_tok=1)  # switch-style
    model, params = lm_from_hf(hf)
    _assert_logits_close(model, params, hf, tokens)


def test_imported_mixtral_generates_ep_sharded(tokens):
    # the import's point: the framework's EP machinery applies unchanged —
    # experts sharded over the mesh, token-for-token equal to gathered
    from elephas_tpu.models import build_lm_generate, build_mesh_sp

    hf = _tiny_mixtral()
    model, params = lm_from_hf(hf)
    p = jax.tree.map(jnp.asarray, params)
    with jax.default_matmul_precision("float32"):
        want = np.asarray(model.generate(p, tokens, 6))
        mesh = build_mesh_sp(data=2, seq=4)
        gen = build_lm_generate(model, mesh)
        got = np.asarray(gen(model.shard_params(mesh, p), tokens, 6))
    np.testing.assert_array_equal(got, want)


def test_imported_llama_generates_tensor_parallel(tokens):
    # Megatron head-sharded serving of an imported checkpoint: KV cache
    # memory drops by tp, rollout equals the gathered one
    from elephas_tpu.models import build_lm_tp_generate, build_mesh_tp, \
        shard_tp_params

    hf = _tiny_llama(num_key_value_heads=2)
    model, params = lm_from_hf(hf)
    p = jax.tree.map(jnp.asarray, params)
    mesh = build_mesh_tp(data=2, model=2)
    with jax.default_matmul_precision("float32"):
        want = np.asarray(model.generate(p, tokens, 6))
        gen = build_lm_tp_generate(model, mesh, attn="dense")
        got = np.asarray(gen(shard_tp_params(mesh, model, p), tokens, 6))
    np.testing.assert_array_equal(got, want)
