"""Optimizer mapping + compact-adam convergence.

The compact adam (bf16 moments, f32 math — ``models/optimizers.py``) claims
to be loss-neutral. That claim is pinned here two ways: the update rule
matches optax.adam exactly when the compact dtype is float32 (pure
refactoring check), and with bfloat16 moments a small-LM training run lands
at the same loss as f32 adam within a tight relative band.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elephas_tpu.models import adam_compact
from elephas_tpu.models.optimizers import to_optax


def _rollout(opt, params, grads_seq):
    state = opt.init(params)
    out = []
    for g in grads_seq:
        updates, state = opt.update(g, state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        out.append(params)
    return out


def test_f32_compact_matches_optax_adam_exactly():
    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(4,)), jnp.float32),
    }
    grads_seq = [
        {
            "w": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(4,)), jnp.float32),
        }
        for _ in range(5)
    ]
    ours = _rollout(
        adam_compact(3e-3, eps=1e-8, moment_dtype=jnp.float32),
        params, grads_seq,
    )
    ref = _rollout(optax.adam(3e-3, eps=1e-8), params, grads_seq)
    for a, b in zip(ours, ref):
        for k in params:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-6, atol=1e-7)


def test_bf16_moments_converge_like_f32():
    """Train the same tiny MLP regression with f32 vs bf16-moment adam."""

    rng = np.random.default_rng(1)
    w_true = rng.normal(size=(16, 1)).astype(np.float32)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    y = x @ w_true + 0.01 * rng.normal(size=(256, 1)).astype(np.float32)
    x, y = jnp.asarray(x), jnp.asarray(y)

    def init_params():
        r = np.random.default_rng(2)
        return {
            "w1": jnp.asarray(r.normal(size=(16, 32)) * 0.1, jnp.float32),
            "w2": jnp.asarray(r.normal(size=(32, 1)) * 0.1, jnp.float32),
        }

    def loss_fn(p):
        h = jnp.tanh(x @ p["w1"])
        return jnp.mean((h @ p["w2"] - y) ** 2)

    def train(opt, steps=120):
        params = init_params()
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            loss, g = jax.value_and_grad(loss_fn)(params)
            updates, state = opt.update(g, state, params)
            return jax.tree_util.tree_map(jnp.add, params, updates), state, loss

        for _ in range(steps):
            params, state, loss = step(params, state)
        return float(loss)

    f32_loss = train(optax.adam(1e-2, eps=1e-8))
    bf16_loss = train(adam_compact(1e-2, eps=1e-8))
    # Both must actually train (start ≈ var(y) ≈ 16) and land together.
    assert f32_loss < 0.05
    assert bf16_loss < 0.05
    assert abs(bf16_loss - f32_loss) <= 0.2 * max(f32_loss, 1e-3) + 5e-3


def test_bf16_moment_state_is_half_sized():
    params = {"w": jnp.zeros((64, 64), jnp.float32)}
    state = adam_compact(1e-3).init(params)
    inner = state[0]  # chain: (ScaleByAdamState, scale)
    assert inner.mu["w"].dtype == jnp.bfloat16
    assert inner.nu["w"].dtype == jnp.bfloat16


def test_to_optax_moment_dtype_config():
    opt = to_optax({"name": "adam", "learning_rate": 0.01,
                    "moment_dtype": "bfloat16"})
    state = opt.init({"w": jnp.zeros((4,), jnp.float32)})
    assert state[0].mu["w"].dtype == jnp.bfloat16


def test_compact_state_shards_like_adam():
    """opt_state_specs infers the same sharding tree for the compact state."""
    from jax.sharding import PartitionSpec as P

    from elephas_tpu.parallel.param_utils import opt_state_specs

    params = {"w": jnp.zeros((8, 4), jnp.float32)}
    specs = {"w": P("data", None)}
    s_adam = opt_state_specs(optax.adam(1e-3), params, specs)
    s_comp = opt_state_specs(adam_compact(1e-3), params, specs)
    assert jax.tree_util.tree_structure(s_adam) == \
        jax.tree_util.tree_structure(s_comp)
    assert s_comp[0].mu["w"] == P("data", None)
    assert s_comp[0].nu["w"] == P("data", None)
