"""KV-cached autoregressive generation vs the non-cached forward.

Two oracles, no training needed (long lockstep training loops are also
fragile on this 1-core CI box — XLA CPU's collective rendezvous aborts if
its 8 device threads starve >20s): (1) stepping the cache over a sequence
must reproduce the full forward's logits position-by-position; (2) greedy
``generate`` must equal growing the sequence one token at a time through
the full (uncached) forward.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models.transformer import MoETransformerLM, TransformerLM


def _model(**kw):
    cfg = dict(vocab=17, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.mark.parametrize("pos_encoding", ["learned", "rotary"])
def test_decode_matches_teacher_forced_logits(pos_encoding):
    """Stepping the KV cache over a sequence must reproduce the full
    forward's logits at every position (both positional schemes)."""
    model = _model(pos_encoding=pos_encoding)
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 17, size=(2, 12)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(12), (2, 12))

    full = np.asarray(model.apply(params, tokens, positions, attn="dense"))

    cache = model.init_cache(batch=2)
    step_logits = []
    for t in range(12):
        logits, cache = model.decode_step(params, tokens[:, t], t, cache)
        step_logits.append(np.asarray(logits))
    got = np.stack(step_logits, axis=1)
    np.testing.assert_allclose(got, full, atol=3e-5, rtol=3e-5)


@pytest.mark.parametrize("seed,pos_encoding", [(1, "learned"),
                                               (2, "learned"),
                                               (1, "rotary")])
def test_generate_matches_uncached_rollout(seed, pos_encoding):
    """Greedy cached generation == growing the sequence via the full
    forward one argmax at a time (prompt preserved, continuation equal)."""
    model = _model(pos_encoding=pos_encoding)
    params = {k: jnp.asarray(v) for k, v in model.init(seed=5).items()}
    rng = np.random.default_rng(seed)
    prompt = rng.integers(0, 17, size=(2, 4)).astype(np.int32)

    out = np.asarray(model.generate(params, prompt, n_new=6))

    seq = prompt.copy()
    for _ in range(6):
        pos = np.broadcast_to(np.arange(seq.shape[1]), seq.shape)
        logits = model.apply(params, jnp.asarray(seq), jnp.asarray(pos),
                             attn="dense")
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)

    np.testing.assert_array_equal(out[:, :4], prompt)  # prompt untouched
    np.testing.assert_array_equal(out, seq)


def test_bf16_generate_matches_its_own_rollout():
    model = _model(compute_dtype="bfloat16")
    params = {k: jnp.asarray(v) for k, v in model.init(seed=3).items()}
    prompt = np.array([[1, 2, 3]], np.int32)
    out = np.asarray(model.generate(params, prompt, n_new=4))
    assert out.shape == (1, 7)

    seq = prompt.copy()
    for _ in range(4):
        pos = np.broadcast_to(np.arange(seq.shape[1]), seq.shape)
        logits = model.apply(params, jnp.asarray(seq), jnp.asarray(pos),
                             attn="dense")
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_sampling_modes():
    """temperature=0 is greedy; sampling is seed-deterministic, in-vocab,
    and top_k=1 collapses back to greedy."""
    model = _model()
    params = {k: jnp.asarray(v) for k, v in model.init(seed=4).items()}
    prompt = np.array([[5, 6, 7]], np.int32)

    greedy = np.asarray(model.generate(params, prompt, n_new=6))
    a = np.asarray(model.generate(params, prompt, n_new=6,
                                  temperature=1.5, seed=7))
    b = np.asarray(model.generate(params, prompt, n_new=6,
                                  temperature=1.5, seed=7))
    c = np.asarray(model.generate(params, prompt, n_new=6,
                                  temperature=1.5, seed=8))
    np.testing.assert_array_equal(a, b)  # same seed → same draw
    assert not np.array_equal(a, c) or not np.array_equal(b, greedy)
    assert np.all((a >= 0) & (a < 17))
    np.testing.assert_array_equal(a[:, :3], prompt)

    topk1 = np.asarray(model.generate(params, prompt, n_new=6,
                                      temperature=1.5, top_k=1, seed=9))
    np.testing.assert_array_equal(topk1, greedy)


def test_nucleus_sampling():
    """top_p→0 collapses to greedy (the argmax token always survives the
    nucleus); top_p=1.0 is a no-op vs plain temperature sampling; draws
    stay seed-deterministic and in-vocab."""
    model = _model()
    params = {k: jnp.asarray(v) for k, v in model.init(seed=4).items()}
    prompt = np.array([[5, 6, 7], [1, 2, 3]], np.int32)

    greedy = np.asarray(model.generate(params, prompt, n_new=6))
    tiny_p = np.asarray(model.generate(params, prompt, n_new=6,
                                       temperature=1.5, top_p=1e-6, seed=7))
    np.testing.assert_array_equal(tiny_p, greedy)

    plain = np.asarray(model.generate(params, prompt, n_new=6,
                                      temperature=1.5, seed=7))
    full_p = np.asarray(model.generate(params, prompt, n_new=6,
                                       temperature=1.5, top_p=1.0, seed=7))
    np.testing.assert_array_equal(full_p, plain)

    a = np.asarray(model.generate(params, prompt, n_new=6,
                                  temperature=1.5, top_p=0.8, seed=7))
    b = np.asarray(model.generate(params, prompt, n_new=6,
                                  temperature=1.5, top_p=0.8, seed=7))
    np.testing.assert_array_equal(a, b)
    assert np.all((a >= 0) & (a < 17))
    # composes with top_k (top_k truncates first, nucleus inside it)
    ck = np.asarray(model.generate(params, prompt, n_new=6, temperature=1.5,
                                   top_k=5, top_p=0.9, seed=7))
    assert np.all((ck >= 0) & (ck < 17))


def test_generate_validates_length_and_top_k():
    model = _model(max_len=8)
    params = {k: jnp.asarray(v) for k, v in model.init().items()}
    with pytest.raises(ValueError, match="exceeds max_len"):
        model.generate(params, np.zeros((1, 6), np.int32), n_new=4)
    for bad in (0, 100):
        with pytest.raises(ValueError, match="top_k"):
            model.generate(params, np.zeros((1, 2), np.int32), n_new=2,
                           temperature=1.0, top_k=bad)
    for bad_p in (0.0, 1.5, -0.1):
        with pytest.raises(ValueError, match="top_p"):
            model.generate(params, np.zeros((1, 2), np.int32), n_new=2,
                           temperature=1.0, top_p=bad_p)


@pytest.mark.parametrize("ep_groups", [1, 4])
def test_moe_variant_generates(ep_groups):
    """The MoE LM decodes regardless of its training-time ep_groups —
    decode forces single-group routing per position."""
    model = MoETransformerLM(vocab=11, d_model=16, n_heads=4, n_layers=1,
                             d_ff=32, max_len=16, n_experts=4, k=2,
                             ep_groups=ep_groups)
    params = {k: jnp.asarray(v) for k, v in model.init(seed=0).items()}
    out = model.generate(params, np.zeros((2, 3), np.int32), n_new=5)
    assert out.shape == (2, 8)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < 11))


def test_nucleus_mask_cuts_tied_boundary_logits_by_rank():
    """A value threshold would admit every duplicate of the boundary logit;
    the mask must keep exactly the sorted prefix (argmax always survives)."""
    from elephas_tpu.models.transformer import nucleus_mask

    # row 0: probs ~ [0.5, 0.25, 0.25-eps...] with the two 0.25s TIED.
    # top_p=0.7: prefix is {argmax, first 0.25}; the tied second 0.25 (and
    # everything after) must be cut even though its logit equals the kept one.
    logits = jnp.log(jnp.asarray([[0.5, 0.25, 0.25, 1e-9],
                                  [0.97, 0.01, 0.01, 0.01]]))
    keep = np.asarray(nucleus_mask(logits, 0.7))
    # argmax kept, the tail cut, and EXACTLY ONE of the tied 0.25s kept
    # (which one is sort-permutation detail; a value threshold would keep
    # both and fail the xor)
    assert keep[0, 0] and not keep[0, 3]
    assert bool(keep[0, 1]) ^ bool(keep[0, 2])
    # row 1: argmax alone already reaches 0.7 — nucleus is exactly {argmax}
    assert keep[1].tolist() == [True, False, False, False]
    # widening top_p widens the prefix (but the ~zero-mass tail token's
    # cumulative-before is ~1.0, so it stays cut for any top_p < 1)
    wide = np.asarray(nucleus_mask(logits, 0.99))
    assert wide[0].tolist() == [True, True, True, False]
    # (row 1's cumsum lands exactly ON 0.99 — an f32-rounding coin flip —
    # so only the structurally unambiguous row is pinned here)
