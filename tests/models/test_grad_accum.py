"""Gradient accumulation in the LM train step.

For the dense model, accumulating microbatch gradients and applying ONE
optimizer step must be mathematically identical to the full-batch step —
parameters, optimizer state trajectory, and reported loss.
"""

import numpy as np
import pytest

import jax
import optax

from elephas_tpu.models import (
    TransformerLM,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)


def _setup(accum_steps, sp=2):
    mesh = build_mesh_sp(data=2, seq=sp)
    model = TransformerLM(vocab=13, d_model=8, n_heads=sp, n_layers=1,
                          d_ff=16, max_len=8 * sp)
    step, opt_init = build_lm_train_step(
        model, mesh, optax.adam(1e-2), attn="ring", accum_steps=accum_steps,
    )
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 13, size=(8, 8 * sp + 1))
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))
    params = model.shard_params(mesh, model.init(seed=0))
    return step, params, opt_init(params), batch


@pytest.mark.parametrize("accum_steps", [2, 4])
def test_accumulated_equals_full_batch_step(accum_steps):
    step1, params1, state1, batch = _setup(1)
    stepk, paramsk, statek, _ = _setup(accum_steps)
    for _ in range(3):
        params1, state1, loss1 = step1(params1, state1, *batch)
        paramsk, statek, lossk = stepk(paramsk, statek, *batch)
        np.testing.assert_allclose(float(lossk), float(loss1),
                                   rtol=1e-5, atol=1e-6)
    for k in params1:
        np.testing.assert_allclose(
            np.asarray(paramsk[k]), np.asarray(params1[k]),
            rtol=1e-5, atol=1e-6, err_msg=k,
        )


def test_accum_validation():
    mesh = build_mesh_sp(data=2, seq=2)
    model = TransformerLM(vocab=13, d_model=8, n_heads=2, n_layers=1,
                          d_ff=16, max_len=16)
    with pytest.raises(ValueError, match="accum_steps"):
        build_lm_train_step(model, mesh, optax.adam(1e-2), accum_steps=0)
    # non-divisible local batch surfaces at trace time
    step, opt_init = build_lm_train_step(
        model, mesh, optax.adam(1e-2), accum_steps=3,
    )
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 13, size=(8, 17))  # local batch 4, not /3
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))
    params = model.shard_params(mesh, model.init())
    with pytest.raises(ValueError, match="divisible"):
        step(params, opt_init(params), *batch)
