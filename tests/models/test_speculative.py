"""Speculative decoding: decode_chunk oracle + greedy-equality guarantee.

The load-bearing property: with ``temperature=0``, speculative output must
EQUAL the target's own greedy ``generate`` exactly — regardless of the
draft model's quality or ``spec_k`` — because acceptance is "target argmax
agrees" and every correction IS the target argmax. A bad draft only costs
speed, never output.
"""

import itertools

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models.transformer import TransformerLM


def _model(**kw):
    cfg = dict(vocab=17, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=48)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


@pytest.mark.parametrize("kw", [
    {},
    {"pos_encoding": "rotary", "n_kv_heads": 2},
    {"tie_embeddings": True},
])
def test_decode_chunk_matches_teacher_forced(kw):
    """A chunked cached forward must reproduce the full forward's logits
    at every chunk position (after a prefill prefix)."""
    model = _model(**kw)
    params = _params(model, 1)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 17, size=(2, 12)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(12), (2, 12))
    full = np.asarray(model.apply(params, tokens, positions, attn="dense"))

    cache = model.init_cache(batch=2, length=12)
    _, cache = model.prefill(params, tokens[:, :5], cache)
    chunk_logits, cache = model.decode_chunk(params, tokens[:, 5:9], 5, cache)
    np.testing.assert_allclose(np.asarray(chunk_logits), full[:, 5:9],
                               atol=2e-4, rtol=2e-4)
    # and the cache it wrote supports further chunks
    chunk2, _ = model.decode_chunk(params, tokens[:, 9:12], 9, cache)
    np.testing.assert_allclose(np.asarray(chunk2), full[:, 9:12],
                               atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("spec_k", [1, 3, 5])
@pytest.mark.parametrize("draft_seed", [2, 99])
def test_greedy_speculative_equals_target_greedy(spec_k, draft_seed):
    """Any draft (draft_seed=2 is a DIFFERENT random model → frequent
    rejections; the target itself → all accepted) and any spec_k must
    reproduce the target's greedy rollout exactly."""
    target = _model(pos_encoding="rotary", n_kv_heads=2)
    t_params = _params(target, 1)
    draft = _model(d_model=8, n_heads=2, n_layers=1, d_ff=16,
                   pos_encoding="rotary")
    d_params = _params(draft, draft_seed)
    prompt = np.array([[5, 6, 7]], np.int32)

    want = np.asarray(target.generate(t_params, prompt, n_new=12))
    got = np.asarray(target.generate_speculative(
        t_params, prompt, n_new=12, draft=draft, draft_params=d_params,
        spec_k=spec_k,
    ))
    np.testing.assert_array_equal(got, want)


def test_greedy_speculative_with_self_draft():
    """draft == target: every proposal accepted, still exactly greedy."""
    target = _model()
    t_params = _params(target, 3)
    prompt = np.array([[1, 2]], np.int32)
    want = np.asarray(target.generate(t_params, prompt, n_new=10))
    got = np.asarray(target.generate_speculative(
        t_params, prompt, n_new=10, draft=target, draft_params=t_params,
        spec_k=4,
    ))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("batch", [1, 3])
def test_device_loop_equals_host_oracle(batch):
    """The compiled while_loop rollout must reproduce the host-driver
    oracle token-for-token AND stat-for-stat (rounds/proposed/accepted),
    batch-1 and batched, rejecting draft included."""
    target = _model(pos_encoding="rotary", n_kv_heads=2)
    t_params = _params(target, 1)
    draft = _model(d_model=8, n_heads=2, n_layers=1, d_ff=16,
                   pos_encoding="rotary")
    d_params = _params(draft, 99)
    prompt = np.tile(np.array([[5, 6, 7]], np.int32), (batch, 1))
    prompt[:, 0] = np.arange(batch) + 3  # distinct rows
    want, w_stats = target.generate_speculative(
        t_params, prompt, n_new=12, draft=draft, draft_params=d_params,
        spec_k=3, with_stats=True, host_loop=True)
    got, g_stats = target.generate_speculative(
        t_params, prompt, n_new=12, draft=draft, draft_params=d_params,
        spec_k=3, with_stats=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    for key in ("proposed", "accepted", "tokens_emitted"):
        assert g_stats[key] == w_stats[key], (key, g_stats, w_stats)


def test_sampled_speculative_valid_and_deterministic():
    target = _model()
    t_params = _params(target, 3)
    draft = _model(d_model=8, n_heads=2, n_layers=1, d_ff=16)
    d_params = _params(draft, 4)
    prompt = np.array([[1, 2, 3]], np.int32)

    a = np.asarray(target.generate_speculative(
        t_params, prompt, n_new=10, draft=draft, draft_params=d_params,
        spec_k=3, temperature=1.2, seed=7,
    ))
    b = np.asarray(target.generate_speculative(
        t_params, prompt, n_new=10, draft=draft, draft_params=d_params,
        spec_k=3, temperature=1.2, seed=7,
    ))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (1, 13)
    np.testing.assert_array_equal(a[:, :3], prompt)
    assert np.all((a >= 0) & (a < 17))


def test_self_draft_leaves_no_cache_holes():
    """With draft == target every round fully accepts (bonus path); after
    the fix the draft cache must keep ingesting the last proposal, so the
    acceptance rate stays perfect for the WHOLE rollout — any hole would
    corrupt later proposals and show up as rejections, which for a
    self-draft would mean got != want only if verification logic broke,
    so instead we count the target verify calls: full acceptance advances
    spec_k+1 per round."""
    import jax as jax_mod

    target = _model()
    t_params = _params(target, 3)
    prompt = np.array([[1, 2]], np.int32)
    calls = {"n": 0}
    orig_chunk = TransformerLM.decode_chunk
    orig_jit = jax_mod.jit

    def counting(self, *a, **kw):
        calls["n"] += 1
        return orig_chunk(self, *a, **kw)

    TransformerLM.decode_chunk = counting
    jax_mod.jit = lambda f, **kw: f  # count every call, not every trace
    try:
        spec_k, n_new = 4, 15
        got = np.asarray(target.generate_speculative(
            t_params, prompt, n_new=n_new, draft=target,
            draft_params=t_params, spec_k=spec_k, host_loop=True,
        ))
    finally:
        TransformerLM.decode_chunk = orig_chunk
        jax_mod.jit = orig_jit
    want = np.asarray(target.generate(t_params, prompt, n_new=n_new))
    np.testing.assert_array_equal(got, want)
    # ceil(n_new-1 tokens after the first carry / (spec_k+1)) rounds
    assert calls["n"] == -(-(n_new - 1) // (spec_k + 1))
    # the compiled device loop must show the same perfect-acceptance
    # round count through its stats (its decode_chunk traces once, so
    # the call counter above cannot see its rounds)
    _, stats = target.generate_speculative(
        t_params, prompt, n_new=n_new, draft=target,
        draft_params=t_params, spec_k=spec_k, with_stats=True,
    )
    assert stats["rounds"] == -(-(n_new - 1) // (spec_k + 1)), stats


def test_moe_capacity_bound_rejected():
    """Capacity-BOUND MoE configs still refuse (chunk routing could
    diverge from per-position routing); the message says how to fix."""
    from elephas_tpu.models.transformer import MoETransformerLM

    moe = MoETransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=1,
                           d_ff=32, max_len=32, n_experts=4, k=1)  # cf 1.25
    dense = _model()
    with pytest.raises(NotImplementedError, match="capacity_factor"):
        moe.generate_speculative(
            {k: jnp.asarray(v) for k, v in moe.init().items()},
            np.zeros((1, 2), np.int32), n_new=2, draft=dense,
            draft_params=_params(dense, 0),
        )
    with pytest.raises(NotImplementedError, match="draft"):
        dense.generate_speculative(
            _params(dense, 0), np.zeros((1, 2), np.int32), n_new=2,
            draft=moe,
            draft_params={k: jnp.asarray(v) for k, v in moe.init().items()},
        )


def _moe_unbounded(**kw):
    from elephas_tpu.models.transformer import MoETransformerLM

    cfg = dict(vocab=17, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=48, n_experts=4, k=2, capacity_factor=4.0,
               pos_encoding="rotary", norm="rmsnorm", activation="swiglu",
               ffn_bias=False)
    cfg.update(kw)
    return MoETransformerLM(**cfg)


@pytest.mark.parametrize("spec_k", [1, 3])
def test_moe_greedy_speculative_equals_target_greedy(spec_k):
    """Round 5: capacity-unbounded MoE targets speculate — chunk routing
    == per-position routing by construction, so greedy output must equal
    the MoE target's own rollout (dense draft)."""
    target = _moe_unbounded()
    t_params = {k: jnp.asarray(v) for k, v in target.init(seed=3).items()}
    draft = _model(d_model=8, n_heads=2, n_layers=1, d_ff=16)
    d_params = _params(draft, 4)
    prompt = np.array([[1, 2, 3]], np.int32)
    want = np.asarray(target.generate(t_params, prompt, 10))
    got = np.asarray(target.generate_speculative(
        t_params, prompt, 10, draft, d_params, spec_k=spec_k))
    np.testing.assert_array_equal(got, want)


def test_moe_draft_for_dense_target():
    """An unbounded MoE DRAFT proposes for a dense target."""
    target = _model()
    t_params = _params(target, 3)
    draft = _moe_unbounded(d_model=16, n_layers=1)
    d_params = {k: jnp.asarray(v) for k, v in draft.init(seed=5).items()}
    prompt = np.array([[4, 5]], np.int32)
    want = np.asarray(target.generate(t_params, prompt, 8))
    got = np.asarray(target.generate_speculative(
        t_params, prompt, 8, draft, d_params, spec_k=2))
    np.testing.assert_array_equal(got, want)


def test_moe_self_draft_full_acceptance():
    """MoE target drafting for itself: every round fully accepts and the
    caches stay hole-free through the bonus path."""
    target = _moe_unbounded()
    t_params = {k: jnp.asarray(v) for k, v in target.init(seed=6).items()}
    prompt = np.array([[1, 2], [3, 4]], np.int32)
    want = np.asarray(target.generate(t_params, prompt, 9))
    got, stats = target.generate_speculative(
        t_params, prompt, 9, target, t_params, spec_k=3, with_stats=True)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["accepted"] == stats["proposed"]


def test_moe_sampled_speculative_contract():
    target = _moe_unbounded()
    t_params = {k: jnp.asarray(v) for k, v in target.init(seed=7).items()}
    draft = _model(d_model=8, n_heads=2, n_layers=1, d_ff=16)
    d_params = _params(draft, 8)
    prompt = np.array([[1, 2, 3]], np.int32)
    out = np.asarray(target.generate_speculative(
        t_params, prompt, 8, draft, d_params, spec_k=2, temperature=0.9,
        seed=4))
    assert out.shape == (1, 11)
    np.testing.assert_array_equal(out[:, :3], prompt)
    assert np.all((out >= 0) & (out < 17))


def test_speculative_validation():
    target = _model(max_len=8)
    t_params = _params(target, 0)
    draft = _model(max_len=8)
    d_params = _params(draft, 1)
    # B>1 is now supported (batched per-row positions) — no batch error.
    bad_draft = _model(vocab=19, max_len=8)
    with pytest.raises(ValueError, match="vocab"):
        target.generate_speculative(t_params, np.zeros((1, 2), np.int32),
                                    n_new=2, draft=bad_draft,
                                    draft_params=_params(bad_draft, 0))
    with pytest.raises(ValueError, match="spec_k"):
        target.generate_speculative(t_params, np.zeros((1, 2), np.int32),
                                    n_new=2, draft=draft,
                                    draft_params=d_params, spec_k=0)
    with pytest.raises(ValueError, match="exceeds max_len"):
        target.generate_speculative(t_params, np.zeros((1, 6), np.int32),
                                    n_new=4, draft=draft,
                                    draft_params=d_params)


def test_with_stats_contract():
    """with_stats returns the same tokens plus internally consistent
    accounting: accepted <= proposed = rounds*spec_k, and every round
    emits between 1 and spec_k+1 tokens."""
    target, draft = _model(), _model(d_model=8, n_heads=2, d_ff=16)
    tp, dp = _params(target, 3), _params(draft, 4)
    prompt = np.asarray([[1, 2, 3, 4]], np.int32)
    plain = np.asarray(target.generate_speculative(
        tp, prompt, 14, draft, dp, spec_k=3))
    toks, stats = target.generate_speculative(
        tp, prompt, 14, draft, dp, spec_k=3, with_stats=True)
    np.testing.assert_array_equal(np.asarray(toks), plain)
    assert stats["tokens_emitted"] == 14
    assert stats["proposed"] == stats["rounds"] * 3
    assert 0 <= stats["accepted"] <= stats["proposed"]
    assert stats["acceptance_rate"] == stats["accepted"] / stats["proposed"]
    # every round emits >= 1 token (first token comes from the prefill)
    assert stats["rounds"] >= (14 - 1) // (3 + 1)
    assert stats["rounds"] <= 14


def test_batched_greedy_equals_per_row_rollout():
    """B>1 speculative greedy: every row equals the target's own greedy
    generate — per-row positions, frozen finished rows, and the
    always-ingest draft-cache policy must not leak across rows."""
    target = _model(pos_encoding="rotary")
    draft = _model(d_model=8, n_heads=2, d_ff=16, pos_encoding="rotary")
    tp, dp = _params(target, 5), _params(draft, 6)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 17, size=(3, 5)).astype(np.int32)
    n_new = 13

    got = np.asarray(target.generate_speculative(
        tp, prompt, n_new, draft, dp, spec_k=3))
    want = np.asarray(target.generate(tp, prompt, n_new))
    np.testing.assert_array_equal(got, want)


def test_batched_equals_batch1_rows():
    """Each batched row reproduces its own batch-1 speculative run
    (greedy)."""
    target = _model()
    draft = _model(d_model=8, n_heads=2, d_ff=16)
    tp, dp = _params(target, 7), _params(draft, 8)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 17, size=(2, 4)).astype(np.int32)

    batched = np.asarray(target.generate_speculative(
        tp, prompt, 11, draft, dp, spec_k=4))
    for b in range(2):
        solo = np.asarray(target.generate_speculative(
            tp, prompt[b:b + 1], 11, draft, dp, spec_k=4))
        np.testing.assert_array_equal(batched[b:b + 1], solo,
                                      err_msg=f"row {b}")


def test_batched_sampled_contract():
    """Sampled batched decoding: deterministic per seed, in-vocab, right
    shape, consistent stats."""
    target = _model()
    draft = _model(d_model=8, n_heads=2, d_ff=16)
    tp, dp = _params(target, 9), _params(draft, 10)
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 17, size=(3, 4)).astype(np.int32)

    a, stats = target.generate_speculative(
        tp, prompt, 9, draft, dp, spec_k=3, temperature=0.9, seed=4,
        with_stats=True)
    b = target.generate_speculative(
        tp, prompt, 9, draft, dp, spec_k=3, temperature=0.9, seed=4)
    a, b = np.asarray(a), np.asarray(b)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (3, 13)
    assert (0 <= a).all() and (a < 17).all()
    np.testing.assert_array_equal(a[:, :4], prompt)
    assert 0 <= stats["accepted"] <= stats["proposed"]
    assert stats["tokens_emitted"] == 3 * 9


def test_sampled_device_rollout_contract():
    """Round 5: sampled rounds run on-device (f32 rejection rule). Same
    structural contract as greedy: prompt preserved, vocab range, rows
    freeze at total, deterministic per seed, stats coherent."""
    target = _model()
    t_params = _params(target, 3)
    draft = _model(d_model=8, n_heads=2, n_layers=1, d_ff=16)
    d_params = _params(draft, 4)
    prompt = np.array([[1, 2, 3], [4, 5, 6]], np.int32)

    out, stats = target.generate_speculative(
        t_params, prompt, n_new=9, draft=draft, draft_params=d_params,
        spec_k=3, temperature=0.9, seed=5, with_stats=True)
    out = np.asarray(out)
    assert out.shape == (2, 12)
    np.testing.assert_array_equal(out[:, :3], prompt)
    assert np.all((out >= 0) & (out < 17))
    again = np.asarray(target.generate_speculative(
        t_params, prompt, n_new=9, draft=draft, draft_params=d_params,
        spec_k=3, temperature=0.9, seed=5))
    np.testing.assert_array_equal(again, out)
    assert stats["proposed"] >= stats["accepted"] >= 0
    assert stats["rounds"] >= 1
    assert stats["tokens_emitted"] == 2 * 9


def test_sampled_device_preserves_target_distribution():
    """THE speculative guarantee, for the on-device f32 rejection rule:
    the rollout's marginal token distribution equals the target's own
    temperature sampling. Empirical marginals at the first generated
    positions over many seeded rollouts (B rows × N seeds), compared by
    total-variation distance — the draft is a DIFFERENT model, so any
    bias in accept/residual/bonus math would show up here."""
    target = _model(vocab=7, d_model=16, n_layers=1, max_len=16)
    t_params = _params(target, 8)
    draft = _model(vocab=7, d_model=8, n_heads=2, n_layers=1, d_ff=16,
                   max_len=16)
    d_params = _params(draft, 9)
    prompt = np.tile(np.array([[1, 2]], np.int32), (8, 1))
    temp, n_new, n_seeds = 1.1, 3, 60

    spec, plain = [], []
    for s in range(n_seeds):
        spec.append(np.asarray(target.generate_speculative(
            t_params, prompt, n_new=n_new, draft=draft,
            draft_params=d_params, spec_k=2, temperature=temp, seed=s)))
        plain.append(np.asarray(target.generate(
            t_params, prompt, n_new, temperature=temp, seed=10_000 + s)))
    spec = np.concatenate(spec)    # [8*n_seeds, 2+n_new]
    plain = np.concatenate(plain)
    for j in range(2, 2 + n_new):
        fs = np.bincount(spec[:, j], minlength=7) / len(spec)
        fp = np.bincount(plain[:, j], minlength=7) / len(plain)
        tv = 0.5 * np.abs(fs - fp).sum()
        assert tv < 0.12, (j, tv, fs, fp)


def test_sampled_host_oracle_path_still_works():
    """host_loop=True forces the f64 host driver (the distributional
    oracle the device rule is checked against) — keep it alive."""
    target = _model()
    t_params = _params(target, 3)
    draft = _model(d_model=8, n_heads=2, n_layers=1, d_ff=16)
    d_params = _params(draft, 4)
    prompt = np.array([[1, 2, 3]], np.int32)
    out = np.asarray(target.generate_speculative(
        t_params, prompt, n_new=6, draft=draft, draft_params=d_params,
        spec_k=2, temperature=1.0, seed=3, host_loop=True))
    assert out.shape == (1, 9)
    np.testing.assert_array_equal(out[:, :3], prompt)
    assert np.all((out >= 0) & (out < 17))


def test_moe_capacity_pin_is_exactly_the_boundary():
    """The hf_import pin (cf = E/k, 'a slot for every token') is the
    never-binds boundary: an imported Mixtral (E=8, k=2, cf=4) MUST
    speculate; anything below refuses."""
    from elephas_tpu.models.transformer import MoETransformerLM

    kw = dict(vocab=17, d_model=16, n_heads=4, n_layers=1, d_ff=32,
              max_len=32, n_experts=8, k=2, activation="swiglu",
              norm="rmsnorm", ffn_bias=False)
    assert MoETransformerLM(capacity_factor=4.0, **kw)._supports_speculative
    assert not MoETransformerLM(capacity_factor=3.9,
                                **kw)._supports_speculative


def _all_hists(V, max_len):
    """Every token history of length 0..max_len over a V-token vocab."""
    out = [()]
    for j in range(1, max_len + 1):
        out.extend(itertools.product(range(V), repeat=j))
    return out


def test_sampled_rejection_rule_exact_distribution():
    """CLOSED-FORM exactness of the sampled rejection rule, model-free.

    ``spec_round_accept`` is the acceptance math the compiled rollout
    runs. On a 4-token vocab with spec_k=2 this test enumerates every
    draft proposal combo, marginalizes the acceptance uniforms
    analytically (accept prob ``a_i = min(1, p_t(d_i)/p_d(d_i))`` in
    f64), reads each stop-slot's residual distribution FROM the function
    (forcing each acceptance pattern with constructed uniforms —
    ``u = a/2`` accepts, ``u = (1+a)/2`` rejects), and assembles the
    exact joint distribution over the round's emitted token sequences.

    The speculative guarantee is then checked per POSITION: conditioned
    on any emitted prefix and on the round reaching position ``j``, the
    j-th emitted token is distributed exactly as the target's conditional
    ``T(. | prefix)``. Position ``k+1`` (a fully-accepted round) isolates
    the bonus-slot zero-padding of ``p_d`` (its residual must be ``p_t``
    itself); every rejection branch isolates the clamped normalized
    residual ``(p_t − p_d)+``. Perturbing either — dropping the clamp,
    padding with anything but zeros, reading the wrong stop slot — shifts
    a conditional by O(1), far beyond the 5e-5 f32 tolerance; the TV test
    above stays as an end-to-end smoke over the full rollout.
    """
    from collections import defaultdict

    from elephas_tpu.models.transformer import spec_round_accept

    V, K = 4, 2
    rng = np.random.default_rng(0)

    def _dist():
        p = rng.uniform(0.05, 1.0, V)
        return p / p.sum()

    T = {h: _dist() for h in _all_hists(V, K)}       # target conditionals
    D = {h: _dist() for h in _all_hists(V, K - 1)}   # draft conditionals

    joint = defaultdict(float)
    for d in itertools.product(range(V), repeat=K):
        q = np.prod([D[d[:i]][d[i]] for i in range(K)])
        pt = np.stack([T[d[:i]] for i in range(K + 1)])   # [K+1, V]
        pd = np.stack([D[d[:i]] for i in range(K)])       # [K, V]
        a = np.minimum(1.0, pt[np.arange(K), list(d)]
                       / pd[np.arange(K), list(d)])       # accept probs, f64
        for n in range(K + 1):
            stop = 1.0 - a[n] if n < K else 1.0
            p_n = np.prod(a[:n]) * stop
            if p_n <= 0.0:
                continue
            u = np.array([a[i] / 2 if i < n else (1 + a[i]) / 2
                          for i in range(K)], np.float32)
            n_dev, resid = spec_round_accept(
                jnp.asarray(pt, jnp.float32)[None],
                jnp.asarray(pd, jnp.float32)[None],
                jnp.asarray(np.array(d), jnp.int32)[None],
                jnp.asarray(u)[None])
            assert int(n_dev[0]) == n        # the forced pattern held
            resid = np.asarray(resid[0], np.float64)
            for c in range(V):
                joint[d[:n] + (c,)] += q * p_n * resid[c]

    assert abs(sum(joint.values()) - 1.0) < 1e-5

    for j in range(1, K + 2):
        for h in itertools.product(range(V), repeat=j - 1):
            emitted = np.zeros(V)
            for seq, p in joint.items():
                if len(seq) >= j and seq[:j - 1] == h:
                    emitted[seq[j - 1]] += p
            reach = emitted.sum()            # P(round reaches position j
            if reach < 1e-12:                #   along this prefix)
                continue
            np.testing.assert_allclose(
                emitted / reach, T[h], atol=5e-5,
                err_msg=f"conditional at position {j} after prefix {h}")
