"""Sharded generate == gathered single-device generate, token-for-token.

The claim under test (models/sharded_generate.py): generation over a
("data", "seq") mesh — batch sharded over data, KV cache sharded over seq
with the logsumexp partial merge — reproduces
``TransformerLM.generate``'s single-device rollout exactly. The horizon is
chosen so decode writes cross several seq-rank cache boundaries and the
prompt covers rank 0 only partially.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.models import (
    MoETransformerLM,
    TransformerLM,
    build_lm_generate,
    build_mesh_sp,
)


def _model(**kw):
    cfg = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
               max_len=64, pos_encoding="rotary")
    cfg.update(kw)
    return TransformerLM(**cfg)


def _jp(params):
    return {k: jnp.asarray(v) for k, v in params.items()}


def _prompt(b, t0, vocab=64, seed=3):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, size=(b, t0)).astype(np.int32)


@pytest.mark.parametrize("data,seq", [(2, 4), (1, 8), (4, 2)])
def test_greedy_matches_single_device(data, seq):
    model = _model()
    params = _jp(model.init(seed=0))
    mesh = build_mesh_sp(data=data, seq=seq)
    prompt = _prompt(4, 5)
    n_new = 19  # decode positions 5..23 cross several 8-slot cache slices

    want = np.asarray(model.generate(params, prompt, n_new))
    gen = build_lm_generate(model, mesh)
    got = np.asarray(gen(model.shard_params(mesh, params), prompt, n_new))
    np.testing.assert_array_equal(got, want)


def test_gqa_greedy_matches_single_device():
    model = _model(n_heads=4, n_kv_heads=2)
    params = _jp(model.init(seed=1))
    mesh = build_mesh_sp(data=2, seq=4)
    prompt = _prompt(2, 7)

    want = np.asarray(model.generate(params, prompt, 13))
    gen = build_lm_generate(model, mesh)
    got = np.asarray(gen(model.shard_params(mesh, params), prompt, 13))
    np.testing.assert_array_equal(got, want)


def test_sampled_matches_single_device():
    """Same seed → same split pattern → identical sampled rollout."""
    model = _model()
    params = _jp(model.init(seed=2))
    mesh = build_mesh_sp(data=2, seq=4)
    prompt = _prompt(2, 4)

    want = np.asarray(model.generate(
        params, prompt, 12, temperature=0.8, top_k=20, top_p=0.9, seed=11))
    gen = build_lm_generate(model, mesh, temperature=0.8, top_k=20,
                            top_p=0.9)
    got = np.asarray(gen(model.shard_params(mesh, params), prompt, 12,
                         seed=11))
    np.testing.assert_array_equal(got, want)


def test_long_prompt_spanning_ranks():
    """A prompt longer than one rank's cache slice prefills several slices."""
    model = _model()
    params = _jp(model.init(seed=4))
    mesh = build_mesh_sp(data=1, seq=4)
    prompt = _prompt(2, 21)  # Tl = 8 → prompt spans slices 0, 1, 2
    n_new = 9

    want = np.asarray(model.generate(params, prompt, n_new))
    gen = build_lm_generate(model, mesh)
    got = np.asarray(gen(model.shard_params(mesh, params), prompt, n_new))
    np.testing.assert_array_equal(got, want)


def test_geometry_cache_reuse():
    model = _model()
    params = _jp(model.init(seed=0))
    mesh = build_mesh_sp(data=2, seq=4)
    gen = build_lm_generate(model, mesh)
    p = _prompt(2, 5)
    a = np.asarray(gen(params, p, 6))
    b = np.asarray(gen(params, p, 6))  # second call hits the cached program
    np.testing.assert_array_equal(a, b)


def _moe(seq, **kw):
    # capacity_factor = E/k: no token can overflow an expert, so per-rank
    # dispatch groups keep/drop identically to the gathered rollout and
    # the comparison is meaningful.
    cfg = dict(vocab=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
               max_len=32, n_experts=2 * seq, k=1,
               capacity_factor=2.0 * seq, ep_groups=seq,
               pos_encoding="rotary")
    cfg.update(kw)
    return MoETransformerLM(**cfg)


def test_moe_greedy_matches_single_device():
    """MoE sharded generate: experts stay sharded over "seq" (all_to_all
    dispatch per decoded position), output equals the gathered rollout."""
    seq = 4
    model = _moe(seq)
    params = _jp(model.init(seed=3))
    mesh = build_mesh_sp(data=2, seq=seq)
    prompt = _prompt(2, 5, vocab=32)
    n_new = 11

    want = np.asarray(model.generate(params, prompt, n_new))
    gen = build_lm_generate(model, mesh)
    got = np.asarray(gen(model.shard_params(mesh, params), prompt, n_new))
    np.testing.assert_array_equal(got, want)


def test_moe_expert_shards_stay_local():
    """The compiled program's expert stacks are 1/seq per device — nothing
    gathers."""
    seq = 4
    model = _moe(seq)
    mesh = build_mesh_sp(data=2, seq=seq)
    params = model.shard_params(mesh, _jp(model.init(seed=0)))
    w1 = params["w1"]
    assert w1.addressable_shards[0].data.nbytes * seq == w1.nbytes


def test_bad_batch_rejected():
    model = _model()
    mesh = build_mesh_sp(data=4, seq=2)
    gen = build_lm_generate(model, mesh)
    with pytest.raises(ValueError, match="divisible"):
        gen(_jp(model.init(seed=0)), _prompt(3, 4), 4)


def test_moe_bad_expert_count_rejected():
    model = _moe(4, n_experts=6)
    mesh = build_mesh_sp(data=2, seq=4)
    with pytest.raises(ValueError, match="n_experts"):
        build_lm_generate(model, mesh)


@pytest.mark.parametrize("window", [6, 20])
def test_windowed_greedy_matches_single_device(window):
    """Round 5: sliding-window models generate sharded. Window 6 < the
    8-slot cache slice (ranks expire mid-rollout); window 20 spans
    several slices (partial-expiry arithmetic past a rank's slice end)."""
    model = _model(attn_window=window)
    params = _jp(model.init(seed=4))
    mesh = build_mesh_sp(data=2, seq=4)
    prompt = _prompt(2, 5)
    n_new = 19

    want = np.asarray(model.generate(params, prompt, n_new))
    gen = build_lm_generate(model, mesh)
    got = np.asarray(gen(model.shard_params(mesh, params), prompt, n_new))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("windows", [(None, 6), (4, 10)])
def test_mixed_window_greedy_matches_single_device(windows):
    """Per-layer windows (Gemma-2-style alternation) through the sharded
    decode's period scan — each layer masks its own window globally."""
    model = _model(attn_window=list(windows))
    params = _jp(model.init(seed=5))
    mesh = build_mesh_sp(data=2, seq=4)
    prompt = _prompt(2, 5)
    n_new = 19

    want = np.asarray(model.generate(params, prompt, n_new))
    gen = build_lm_generate(model, mesh)
    got = np.asarray(gen(model.shard_params(mesh, params), prompt, n_new))
    np.testing.assert_array_equal(got, want)


def test_mixed_window_moe_greedy_matches_single_device():
    """The Mixtral/Qwen2 composition: MoE experts sharded over "seq" AND
    per-layer windows in the same sharded rollout."""
    moe = MoETransformerLM(
        vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=64,
        n_experts=8, k=2, capacity_factor=8.0, pos_encoding="rotary",
        norm="rmsnorm", activation="swiglu", ffn_bias=False,
        attn_window=[None, 6])
    params = _jp(moe.init(seed=6))
    mesh = build_mesh_sp(data=1, seq=4)
    prompt = _prompt(2, 5)

    want = np.asarray(moe.generate(params, prompt, 13))
    gen = build_lm_generate(moe, mesh)
    got = np.asarray(gen(moe.shard_params(mesh, params), prompt, 13))
    np.testing.assert_array_equal(got, want)
