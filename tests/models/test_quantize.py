"""Weight-only int8 quantized inference vs the float model.

The load-bearing property: every use site dequantizes to IDENTICAL float
values, so running the model on quantized params must equal running it on
the eagerly-dequantized params bit-for-bit — quantization error is then
purely the (bounded, per-channel) weight rounding vs the ORIGINAL floats.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from elephas_tpu.models import (
    QuantizedTensor,
    TransformerLM,
    dequantize_params,
    quantize_lm_params,
    quantized_nbytes,
)


def _model(**kw):
    cfg = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
               max_len=32)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=0):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


def test_roundtrip_error_bounded_and_size_shrinks():
    model = _model()
    params = _params(model)
    qparams = quantize_lm_params(params)
    for name in ("wq", "wo", "w1", "tok"):
        orig = np.asarray(params[name])
        deq = np.asarray(qparams[name].dequantize())
        reduce_axis = -2 if name != "tok" else -1
        scale = np.max(np.abs(orig), axis=reduce_axis, keepdims=True) / 127.0
        assert np.all(np.abs(orig - deq) <= scale / 2 + 1e-7), name
    # layernorm/bias params pass through untouched
    assert not isinstance(qparams["ln1_s"], QuantizedTensor)
    np.testing.assert_array_equal(qparams["ln1_s"], params["ln1_s"])
    # weights dominate this model: int8 storage must be well under half
    orig_bytes = sum(np.asarray(v).nbytes for v in params.values())
    assert quantized_nbytes(qparams) < 0.45 * orig_bytes


@pytest.mark.parametrize("kw", [
    {},
    {"pos_encoding": "rotary", "n_kv_heads": 2},
    {"tie_embeddings": True},
])
def test_quantized_equals_dequantized_exactly(kw):
    """apply / generate on QuantizedTensor params == on materialized
    dequantized params, bit-for-bit (lazy dequant produces the same
    floats at every use site, including through the layer scan)."""
    model = _model(**kw)
    params = _params(model, seed=1)
    qparams = quantize_lm_params(params)
    dparams = dequantize_params(qparams)

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, 10)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(10), (2, 10))
    lq = np.asarray(model.apply(qparams, tokens, positions, attn="dense"))
    ld = np.asarray(model.apply(dparams, tokens, positions, attn="dense"))
    np.testing.assert_array_equal(lq, ld)

    gq = np.asarray(model.generate(qparams, tokens[:, :4], n_new=8))
    gd = np.asarray(model.generate(dparams, tokens[:, :4], n_new=8))
    np.testing.assert_array_equal(gq, gd)


def test_quantized_logits_close_to_float():
    model = _model()
    params = _params(model, seed=2)
    qparams = quantize_lm_params(params)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, 12)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(12), (2, 12))
    lf = np.asarray(model.apply(params, tokens, positions, attn="dense"))
    lq = np.asarray(model.apply(qparams, tokens, positions, attn="dense"))
    # int8 per-channel keeps logits close; agreement is the real criterion
    assert np.abs(lf - lq).max() < 0.15 * np.abs(lf).max()
    agree = (lf.argmax(-1) == lq.argmax(-1)).mean()
    assert agree > 0.9, agree


def test_quantize_is_idempotent():
    model = _model()
    q1 = quantize_lm_params(_params(model))
    q2 = quantize_lm_params(q1)
    for k, v in q1.items():
        assert q2[k] is v, k


def test_unmerged_lora_rejected_with_clear_error():
    """A LoRATensor adapter node must raise 'merge_lora first', not an
    opaque numpy TypeError from the 0-d object-array path."""
    import pytest

    from elephas_tpu.models.lora import LoRATensor

    model = _model()
    params = _params(model)
    w = np.asarray(params["wq"], np.float32)
    params["wq"] = LoRATensor(
        w,
        np.zeros((w.shape[0], w.shape[1], 2), np.float32),
        np.zeros((w.shape[0], 2, w.shape[2]), np.float32),
        alpha=4.0,
    )
    with pytest.raises(ValueError, match="merge_lora"):
        quantize_lm_params(params)


def test_moe_expert_stacks_quantize_and_stay_exact():
    """MoE w1/w2 are [L, E, in, out]: quantized per (layer, expert,
    channel); apply on quantized params == on dequantized params."""
    from elephas_tpu.models.transformer import MoETransformerLM

    moe = MoETransformerLM(vocab=32, d_model=16, n_heads=4, n_layers=1,
                           d_ff=32, max_len=16, n_experts=4, k=1)
    params = {k: jnp.asarray(v) for k, v in moe.init(seed=5).items()}
    qparams = quantize_lm_params(params)
    assert isinstance(qparams["w1"], QuantizedTensor)
    assert qparams["w1"].s.shape == (1, 4, 1, 32)  # per (L, E, 1, out)
    dparams = dequantize_params(qparams)
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, 32, size=(2, 8)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(8), (2, 8))
    lq = np.asarray(moe.apply(qparams, tokens, positions, attn="dense"))
    ld = np.asarray(moe.apply(dparams, tokens, positions, attn="dense"))
    np.testing.assert_array_equal(lq, ld)


def test_quantized_speculative_decoding_runs():
    """Quantized target + quantized draft through the speculative path:
    still exactly equal to the quantized target's own greedy rollout."""
    target = _model()
    t_q = quantize_lm_params(_params(target, seed=3))
    draft = _model(d_model=16, n_heads=2, n_layers=1, d_ff=32)
    d_q = quantize_lm_params(_params(draft, seed=4))
    prompt = np.array([[1, 2, 3]], np.int32)
    want = np.asarray(target.generate(t_q, prompt, n_new=8))
    got = np.asarray(target.generate_speculative(
        t_q, prompt, n_new=8, draft=draft, draft_params=d_q, spec_k=3,
    ))
    np.testing.assert_array_equal(got, want)
