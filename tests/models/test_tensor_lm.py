"""Tensor-parallel LM == replicated single-device LM, exactly.

The contracts under test (models/tensor_lm.py): the Megatron-sharded
forward produces the same logits, the dp×tp training step takes the same
trajectory (the _enter_tp backward psum makes replicated-param gradients
correct on every rank), and head-sharded generation reproduces
``TransformerLM.generate`` token-for-token.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from elephas_tpu.models import (
    MoETransformerLM,
    TransformerLM,
    build_lm_train_step,
    build_lm_tp_generate,
    build_lm_tp_train_step,
    build_mesh_sp,
    build_mesh_tp,
    make_lm_batches,
    shard_lm_batch,
    shard_tp_params,
    tp_specs,
)


def _model(**kw):
    cfg = dict(vocab=48, d_model=32, n_heads=4, n_layers=2, d_ff=64,
               max_len=48, pos_encoding="rotary")
    cfg.update(kw)
    return TransformerLM(**cfg)


def _jp(params):
    return {k: jnp.asarray(v) for k, v in params.items()}


def _rows(b, t, vocab=48, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, size=(b, t + 1))


def _gather(params):
    return {k: np.asarray(v) for k, v in params.items()}


@pytest.mark.parametrize("data,tp", [(2, 4), (1, 8), (4, 2)])
def test_tp_train_step_matches_replicated(data, tp):
    """N dp×tp steps == N replicated (dp-only) steps: same loss
    trajectory, same final params (gathered)."""
    model = _model(n_heads=8)  # 8 heads / d_ff 64: divisible by every tp
    init = model.init(seed=0)
    rows = _rows(4, 16, seed=3)

    # oracle: the replicated dp×sp trainer on a dp-only mesh
    mesh_o = build_mesh_sp(data=1, seq=1)
    step_o, oi_o = build_lm_train_step(model, mesh_o, optax.adam(1e-2),
                                       attn="dense")
    p_o = model.shard_params(mesh_o, _jp(init))
    s_o = oi_o(p_o)
    batch_o = shard_lm_batch(mesh_o, *make_lm_batches(rows))

    mesh = build_mesh_tp(data=data, model=tp)
    step_t, oi_t = build_lm_tp_train_step(model, mesh, optax.adam(1e-2),
                                          attn="dense")
    p_t = shard_tp_params(mesh, model, _jp(init))
    s_t = oi_t(p_t)
    tokens, positions, targets = make_lm_batches(rows)

    losses_o, losses_t = [], []
    for _ in range(3):
        p_o, s_o, l_o = step_o(p_o, s_o, *batch_o)
        p_t, s_t, l_t = step_t(p_t, s_t, jnp.asarray(tokens),
                               jnp.asarray(positions), jnp.asarray(targets))
        losses_o.append(float(l_o))
        losses_t.append(float(l_t))
    np.testing.assert_allclose(losses_t, losses_o, rtol=2e-4, atol=2e-5)
    g_o, g_t = _gather(p_o), _gather(p_t)
    for k in g_o:
        np.testing.assert_allclose(g_t[k], g_o[k], rtol=2e-3, atol=2e-4,
                                   err_msg=k)


def test_tp_generate_matches_replicated():
    model = _model()
    params = _jp(model.init(seed=1))
    mesh = build_mesh_tp(data=2, model=4)
    prompt = _rows(2, 5, seed=7)[:, :6].astype(np.int32)

    want = np.asarray(model.generate(params, prompt, 12))
    gen = build_lm_tp_generate(model, mesh, attn="dense")
    got = np.asarray(gen(shard_tp_params(mesh, model, params), prompt, 12))
    np.testing.assert_array_equal(got, want)


def test_tp_generate_gqa_and_sampled():
    model = _model(n_heads=8, n_kv_heads=4, d_model=64)
    params = _jp(model.init(seed=2))
    mesh = build_mesh_tp(data=2, model=4)
    prompt = _rows(2, 4, seed=9)[:, :5].astype(np.int32)

    want = np.asarray(model.generate(params, prompt, 9, temperature=0.7,
                                     top_k=16, seed=5))
    gen = build_lm_tp_generate(model, mesh, temperature=0.7, top_k=16,
                               attn="dense")
    got = np.asarray(gen(shard_tp_params(mesh, model, params), prompt, 9,
                         seed=5))
    np.testing.assert_array_equal(got, want)


def test_tp_specs_shard_the_big_stacks():
    model = _model()
    specs = tp_specs(model)
    assert specs["wq"] == P(None, None, "model")
    assert specs["wo"] == P(None, "model", None)
    assert specs["w1"] == P(None, None, "model")
    assert specs["w2"] == P(None, "model", None)
    assert specs["tok"] == P()
    assert specs["lnf_s"] == P()


def test_tp_memory_actually_drops():
    """Per-device bytes for the sharded stacks are 1/tp of the total."""
    model = _model(d_model=64, n_heads=8, d_ff=256)
    mesh = build_mesh_tp(data=1, model=8)
    params = shard_tp_params(mesh, model, _jp(model.init(seed=0)))
    w1 = params["w1"]
    shard_bytes = w1.addressable_shards[0].data.nbytes
    assert shard_bytes * 8 == w1.nbytes


def test_validation_errors():
    mesh = build_mesh_tp(data=1, model=8)
    with pytest.raises(ValueError, match="n_heads"):
        build_lm_tp_train_step(_model(), mesh, optax.sgd(0.1))  # 4 % 8
    moe = MoETransformerLM(vocab=16, d_model=16, n_heads=4, n_layers=1,
                           d_ff=32, max_len=16, n_experts=4)
    with pytest.raises(NotImplementedError):
        build_lm_tp_train_step(moe, mesh, optax.sgd(0.1))
