"""Per-layer sliding windows (Gemma-2-style alternation, Qwen2
max_window_layers) through the core single-device LM stack.

The contract: a length-L ``attn_window`` list gives each layer its own
window; the layer scans decompose over the pattern's minimal period;
decode uses a rolling cache only when every layer is windowed; all decode
paths (step/chunk/generate/beam/speculative) agree with the teacher-forced
forward; builders that assume one model-wide window refuse loudly.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from elephas_tpu.models.transformer import (
    MoETransformerLM,
    TransformerLM,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)


def _model(windows, **kw):
    cfg = dict(vocab=61, d_model=32, n_heads=4, n_layers=len(windows),
               d_ff=64, max_len=64, pos_encoding="rotary", norm="rmsnorm",
               activation="swiglu", ffn_bias=False, attn_window=windows)
    cfg.update(kw)
    return TransformerLM(**cfg)


def test_window_normalization_and_period():
    m = _model([None, 8, None, 8])
    assert m.mixed_window and m.attn_window is None
    assert m.attn_windows == (None, 8, None, 8)
    assert m._window_period() == 2
    assert not m._ring_cache  # a full-attention layer forces horizon cache

    m2 = _model([4, 8, 4, 8])
    assert m2._ring_cache and m2._max_window == 8
    assert m2._window_period() == 2

    m3 = _model([8, 8])  # collapses to the uniform scalar view
    assert not m3.mixed_window and m3.attn_window == 8

    m4 = _model([None, None, 8])  # aperiodic in 3 → full unroll
    assert m4._window_period() == 3

    with pytest.raises(ValueError, match="entries"):
        TransformerLM(vocab=61, d_model=32, n_heads=4, n_layers=2,
                      d_ff=64, max_len=64, attn_window=[8])
    with pytest.raises(ValueError, match=">= 1"):
        _model([0, 8])


def _windowed_oracle(model, params, tokens):
    """Teacher-forced logits with each layer's mask built naively —
    independent of the scan/period machinery (dense attention path is the
    production code; this re-derives it per-layer)."""
    B, T = tokens.shape
    positions = np.broadcast_to(np.arange(T), (B, T))
    return np.asarray(model.apply(
        {k: jnp.asarray(v) for k, v in params.items()},
        jnp.asarray(tokens), jnp.asarray(positions), attn="dense"))


@pytest.mark.parametrize("windows", [
    (None, 6, None, 6),   # Gemma-2-style alternation, horizon cache
    (4, 8, 4, 8),         # all-windowed → shared ring cache
    (None, None, 6),      # aperiodic → unrolled scan
])
def test_flash_path_matches_dense(windows):
    """attn='flash' (blockwise jnp on CPU) and attn='dense' build their
    per-layer masks independently — they must agree past every window."""
    model = _model(list(windows))
    params = model.init(seed=1)
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 61, size=(2, 24)).astype(np.int32)
    positions = np.broadcast_to(np.arange(24), (2, 24))
    p = {k: jnp.asarray(v) for k, v in params.items()}
    dense = np.asarray(model.apply(p, jnp.asarray(tokens),
                                   jnp.asarray(positions), attn="dense"))
    flash = np.asarray(model.apply(p, jnp.asarray(tokens),
                                   jnp.asarray(positions), attn="flash"))
    np.testing.assert_allclose(flash, dense, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("windows", [(None, 6, None, 6), (4, 8, 4, 8)])
def test_generate_consistent_with_teacher_forced(windows):
    """Cached greedy decode must re-derive exactly from the teacher-forced
    argmax at every position (past warm-up, expiry, and — for the
    all-windowed case — ring wrap)."""
    model = _model(list(windows))
    p = {k: jnp.asarray(v) for k, v in model.init(seed=2).items()}
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, 61, size=(2, 4)).astype(np.int32)
    out = np.asarray(model.generate(p, prompt, 20))
    for j in (7, 15, 23):
        lg = _windowed_oracle(model, p, out[:, :j])[:, -1]
        np.testing.assert_array_equal(out[:, j], lg.argmax(-1))


def test_all_windowed_mixed_ring_cache_is_window_sized():
    model = _model([4, 8, 4, 8])
    cache = model.init_cache(1, length=48)
    # ring sized to max window (+1 chunk margin, alignment) — not horizon
    assert cache["k"].shape[3] < 48
    mixed_full = _model([None, 8, None, 8])
    assert mixed_full.init_cache(1, length=48)["k"].shape[3] >= 48


def test_speculative_mixed_window_equals_greedy():
    target = _model([None, 6, None, 6])
    draft = _model([None, 6], d_model=16, n_heads=2, d_ff=32)
    tp = {k: jnp.asarray(v) for k, v in target.init(seed=3).items()}
    dp = {k: jnp.asarray(v) for k, v in draft.init(seed=9).items()}
    prompt = np.random.default_rng(7).integers(
        0, 61, size=(1, 4)).astype(np.int32)
    want = np.asarray(target.generate(tp, prompt, 12))
    got = np.asarray(target.generate_speculative(
        tp, prompt, 12, draft, dp, spec_k=3))
    np.testing.assert_array_equal(got, want)


def test_train_step_runs_and_learns():
    model = _model([None, 6, None, 6], max_len=16)
    mesh = build_mesh_sp(data=2, seq=1)
    step, opt_init = build_lm_train_step(model, mesh, optax.adam(1e-2),
                                         attn="flash")
    params = model.shard_params(mesh, model.init(seed=0))
    state = opt_init(params)
    rows = np.random.default_rng(0).integers(0, 61, size=(4, 17))
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))
    losses = []
    for _ in range(4):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_mixed_window_trains_dp_sp(attn):
    """Round 5: per-layer windows ride the sequence-parallel paths. A
    dp=2×sp=2 run must reproduce the single-device flash trajectory —
    windows span shard boundaries (T_local 8 < window 6+full mix)."""
    model = _model([None, 6, None, 6], max_len=16)
    rows = np.random.default_rng(0).integers(0, 61, size=(4, 17))
    losses = {}
    finals = {}
    for tag, (dp, sp, mode) in {
        "oracle": (1, 1, "flash"), "sp": (2, 2, attn),
    }.items():
        mesh = build_mesh_sp(data=dp, seq=sp)
        step, opt_init = build_lm_train_step(model, mesh, optax.adam(1e-2),
                                             attn=mode)
        params = model.shard_params(mesh, model.init(seed=0))
        state = opt_init(params)
        batch = shard_lm_batch(mesh, *make_lm_batches(rows))
        ls = []
        for _ in range(3):
            params, state, loss = step(params, state, *batch)
            ls.append(float(loss))
        losses[tag] = ls
        finals[tag] = {k: np.asarray(v) for k, v in params.items()}
    np.testing.assert_allclose(losses["sp"], losses["oracle"],
                               rtol=5e-5, atol=5e-6)
    # adam's rsqrt amplifies float-order noise on near-zero second
    # moments, so params get a looser bound than the pinned losses
    for k, v in finals["oracle"].items():
        np.testing.assert_allclose(finals["sp"][k], v, rtol=1e-3,
                                   atol=1e-4, err_msg=k)


def test_unsupported_builders_refuse_loudly():
    model = _model([None, 6, None, 6], max_len=16)

    from elephas_tpu.models.tensor_lm import build_lm_tp_train_step
    from elephas_tpu.models.tensor_lm import build_mesh_tp

    with pytest.raises(NotImplementedError, match="mixed"):
        build_lm_tp_train_step(model, build_mesh_tp(data=2, model=4),
                               optax.sgd(0.1))


def test_lora_on_mixed_window_model():
    """LoRA fine-tuning must compose with per-layer windows (the lazy
    LoRATensor survives the period scan's leading-dim reshape)."""
    from elephas_tpu.models import apply_lora, build_lora_lm_train_step

    model = _model([None, 6, None, 6], max_len=16)
    mesh = build_mesh_sp(data=2, seq=1)
    step, opt_init = build_lora_lm_train_step(model, mesh, optax.adam(1e-2),
                                              attn="dense")
    params = apply_lora(
        {k: jnp.asarray(v) for k, v in model.init(seed=0).items()}, rank=2)
    state = opt_init(params)
    rows = np.random.default_rng(0).integers(0, 61, size=(4, 17))
    tokens, positions, targets = make_lm_batches(rows)
    for _ in range(2):
        params, state, loss = step(params, state, jnp.asarray(tokens),
                                   jnp.asarray(positions),
                                   jnp.asarray(targets))
    assert np.isfinite(float(loss))


def test_quantized_mixed_window_generate():
    """int8 weight-only inference must compose with per-layer windows
    (QuantizedTensor's leading-dim reshape keeps the int8 stacks lazy),
    bit-identical to the dequantized rollout."""
    from elephas_tpu.models import dequantize_params, quantize_lm_params

    model = _model([None, 6, None, 6])
    params = {k: jnp.asarray(v) for k, v in model.init(seed=4).items()}
    qp = quantize_lm_params(params)
    prompt = np.random.default_rng(9).integers(
        0, 61, size=(2, 4)).astype(np.int32)
    want = np.asarray(model.generate(dequantize_params(qp), prompt, 10))
    got = np.asarray(model.generate(qp, prompt, 10))
    np.testing.assert_array_equal(got, want)


def test_moe_variant_accepts_per_layer_windows():
    moe = MoETransformerLM(
        vocab=61, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=32,
        n_experts=4, k=1, pos_encoding="rotary", norm="rmsnorm",
        activation="swiglu", ffn_bias=False, attn_window=[None, 6])
    p = {k: jnp.asarray(v) for k, v in moe.init(seed=0).items()}
    prompt = np.random.default_rng(2).integers(
        0, 61, size=(1, 3)).astype(np.int32)
    out = np.asarray(moe.generate(p, prompt, 6))
    assert out.shape == (1, 9)
