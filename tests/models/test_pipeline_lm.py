"""dp×pp TransformerLM vs the unpipelined single-device oracle.

GPipe over batch rows is exact for the dense LM (rows are independent
through attention, the loss is a token sum), so trajectories must match
the replicated ``build_lm_train_step`` oracle to float tolerance —
including with RoPE (shared-table contract), flash attention, different
microbatch counts, and the chunked loss head.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from elephas_tpu.models.pipeline_lm import (
    build_lm_pp_train_step,
    build_mesh_pp,
    lm_pp_specs,
)
from elephas_tpu.models.transformer import (
    MoETransformerLM,
    TransformerLM,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)
from elephas_tpu.parallel.param_utils import shard_by_specs


def _model(n_layers=4, **kw):
    cfg = dict(vocab=89, d_model=32, n_heads=4, n_layers=n_layers, d_ff=64,
               max_len=16)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _rows(b=8, t=16, seed=0, vocab=89):
    return np.random.default_rng(seed).integers(0, vocab, size=(b, t + 1))


def _oracle(model, optimizer, rows, steps=3):
    mesh = build_mesh_sp(data=1, seq=1)
    step, opt_init = build_lm_train_step(model, mesh, optimizer,
                                         attn="dense")
    params = model.shard_params(mesh, model.init(seed=0))
    state = opt_init(params)
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))
    losses = []
    for _ in range(steps):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))
    return {k: np.asarray(v) for k, v in params.items()}, losses


def _pp_batch(mesh, rows):
    tokens, positions, targets = make_lm_batches(rows)
    sh = NamedSharding(mesh, P("data"))
    return (jax.device_put(tokens, sh), jax.device_put(positions, sh),
            jax.device_put(targets, sh))


@pytest.mark.parametrize("dp,pp,n_micro,kw", [
    (1, 4, 4, {}),
    (2, 2, 2, {}),
    (1, 4, 8, dict(pos_encoding="rotary", norm="rmsnorm",
                   activation="swiglu", ffn_bias=False,
                   tie_embeddings=True)),
])
def test_trajectory_matches_oracle(dp, pp, n_micro, kw):
    model = _model(**kw)
    rows = _rows()
    want, o_losses = _oracle(model, optax.adam(1e-2), rows)

    mesh = build_mesh_pp(data=dp, pipe=pp)
    step, opt_init = build_lm_pp_train_step(
        model, mesh, optax.adam(1e-2), n_micro=n_micro, attn="dense")
    params = shard_by_specs(mesh, lm_pp_specs(model), model.init(seed=0))
    state = opt_init(params)
    batch = _pp_batch(mesh, rows)
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=2e-4, atol=2e-5)
    got = {k: np.asarray(v) for k, v in params.items()}
    for k, v in want.items():
        np.testing.assert_allclose(got[k], v, rtol=5e-4, atol=5e-5,
                                   err_msg=k)


def test_flash_attention_path():
    """attn='flash' (the TPU training path; jnp blockwise on CPU) must
    match the dense-attention pipeline exactly."""
    model = _model(pos_encoding="rotary")
    rows = _rows()
    mesh = build_mesh_pp(data=2, pipe=4)

    def run(attn):
        step, opt_init = build_lm_pp_train_step(
            model, mesh, optax.adam(1e-2), n_micro=4, attn=attn)
        params = shard_by_specs(mesh, lm_pp_specs(model),
                                model.init(seed=0))
        state = opt_init(params)
        batch = _pp_batch(mesh, rows)
        for _ in range(2):
            params, state, loss = step(params, state, *batch)
        return float(loss)

    np.testing.assert_allclose(run("flash"), run("dense"), rtol=1e-5)


def test_vocab_block_trajectory_unchanged():
    model = _model(tie_embeddings=True)
    rows = _rows()
    mesh = build_mesh_pp(data=1, pipe=4)

    def run(vocab_block):
        step, opt_init = build_lm_pp_train_step(
            model, mesh, optax.adam(1e-2), n_micro=4, attn="dense",
            vocab_block=vocab_block)
        params = shard_by_specs(mesh, lm_pp_specs(model),
                                model.init(seed=0))
        state = opt_init(params)
        batch = _pp_batch(mesh, rows)
        for _ in range(2):
            params, state, loss = step(params, state, *batch)
        return float(loss)

    np.testing.assert_allclose(run(32), run(None), rtol=1e-5)


def test_per_device_stage_shards():
    """Each pipe rank holds 1/pp of every block stack."""
    model = _model(n_layers=8)
    mesh = build_mesh_pp(data=1, pipe=8)
    params = shard_by_specs(mesh, lm_pp_specs(model), model.init(seed=0))
    wq = params["wq"]
    assert wq.shape == (8, 32, 32)
    for shard in wq.addressable_shards:
        assert shard.data.shape == (1, 32, 32)


def test_guards():
    moe = MoETransformerLM(vocab=32, d_model=16, n_heads=2, n_layers=2,
                           d_ff=32, max_len=8, n_experts=4)
    mesh = build_mesh_pp(data=1, pipe=2)
    with pytest.raises(NotImplementedError, match="MoE"):
        build_lm_pp_train_step(moe, mesh, optax.sgd(0.1), n_micro=2)
    with pytest.raises(ValueError, match="not divisible"):
        build_lm_pp_train_step(_model(n_layers=3), mesh, optax.sgd(0.1),
                               n_micro=2)
    with pytest.raises(ValueError, match="attn"):
        build_lm_pp_train_step(_model(), mesh, optax.sgd(0.1), n_micro=2,
                               attn="ring")


@pytest.mark.parametrize("dp,pp,n_micro,kw", [
    (1, 4, 4, {}),
    (2, 2, 4, {}),
    (1, 4, 8, dict(pos_encoding="rotary", norm="rmsnorm",
                   activation="swiglu", ffn_bias=False,
                   tie_embeddings=True)),
])
def test_1f1b_trajectory_matches_oracle(dp, pp, n_micro, kw):
    """Round 5: the hand-scheduled 1F1B loop (O(P)-microbatch stash,
    cond-gated embed/head) must reproduce the unpipelined trajectory."""
    model = _model(**kw)
    rows = _rows()
    want, o_losses = _oracle(model, optax.adam(1e-2), rows)

    mesh = build_mesh_pp(data=dp, pipe=pp)
    step, opt_init = build_lm_pp_train_step(
        model, mesh, optax.adam(1e-2), n_micro=n_micro, attn="dense",
        schedule="1f1b")
    params = shard_by_specs(mesh, lm_pp_specs(model), model.init(seed=0))
    state = opt_init(params)
    batch = _pp_batch(mesh, rows)
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=2e-4, atol=2e-5)
    got = {k: np.asarray(v) for k, v in params.items()}
    for k, v in want.items():
        np.testing.assert_allclose(got[k], v, rtol=2e-3, atol=2e-4,
                                   err_msg=k)


def test_gpipe_remat_trajectory_unchanged():
    """remat=True must change memory, never math."""
    model = _model()
    rows = _rows()
    mesh = build_mesh_pp(data=1, pipe=4)
    losses = {}
    for rm in (False, True):
        step, opt_init = build_lm_pp_train_step(
            model, mesh, optax.adam(1e-2), n_micro=4, attn="dense",
            remat=rm)
        params = shard_by_specs(mesh, lm_pp_specs(model),
                                model.init(seed=0))
        state = opt_init(params)
        batch = _pp_batch(mesh, rows)
        ls = []
        for _ in range(3):
            params, state, loss = step(params, state, *batch)
            ls.append(float(loss))
        losses[rm] = ls
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=1e-5, atol=1e-6)


def test_1f1b_vocab_block_matches_dense_head():
    """The chunked loss head streams inside the last rank's cond branch."""
    model = _model(pos_encoding="rotary")
    rows = _rows(seed=3)
    mesh = build_mesh_pp(data=1, pipe=2)
    losses = {}
    for vb in (None, 32):
        step, opt_init = build_lm_pp_train_step(
            model, mesh, optax.adam(1e-2), n_micro=4, attn="dense",
            schedule="1f1b", vocab_block=vb)
        params = shard_by_specs(mesh, lm_pp_specs(model),
                                model.init(seed=0))
        state = opt_init(params)
        batch = _pp_batch(mesh, rows)
        ls = []
        for _ in range(2):
            params, state, loss = step(params, state, *batch)
            ls.append(float(loss))
        losses[vb] = ls
    np.testing.assert_allclose(losses[32], losses[None],
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("kw", [
    {},
    dict(pos_encoding="rotary", tie_embeddings=True),
])
def test_1f1b_shard_edges_trajectory_and_storage(kw):
    """shard_edges: embedding/head storage splits over "pipe" (params +
    adam state ÷P at rest) with the trajectory unchanged."""
    model = _model(vocab=88, **kw)
    rows = _rows(vocab=88, seed=1)
    want, o_losses = _oracle(model, optax.adam(1e-2), rows)

    mesh = build_mesh_pp(data=1, pipe=4)
    step, opt_init = build_lm_pp_train_step(
        model, mesh, optax.adam(1e-2), n_micro=4, attn="dense",
        schedule="1f1b", shard_edges=True)
    params = shard_by_specs(mesh, lm_pp_specs(model, shard_edges=True),
                            model.init(seed=0))
    # per-device embedding shard is V/P rows (slice objects are only
    # hashable on py3.12+, so key the set on their endpoints)
    shard_shapes = {tuple((sl.start, sl.stop) for sl in s.index)
                    for s in params["tok"].addressable_shards}
    assert len(shard_shapes) == 4  # four distinct row blocks
    assert params["tok"].addressable_shards[0].data.shape[0] == 88 // 4
    state = opt_init(params)
    batch = _pp_batch(mesh, rows)
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, o_losses, rtol=2e-4, atol=2e-5)
    got = {k: np.asarray(v) for k, v in params.items()}
    for k, v in want.items():
        np.testing.assert_allclose(got[k], v, rtol=2e-3, atol=2e-4,
                                   err_msg=k)


def test_shard_edges_guards():
    model = _model()
    mesh = build_mesh_pp(data=1, pipe=4)
    with pytest.raises(ValueError, match="1f1b"):
        build_lm_pp_train_step(model, mesh, optax.sgd(0.1), n_micro=4,
                               shard_edges=True)
    bad = _model(vocab=90)  # 90 % 4 != 0
    with pytest.raises(ValueError, match="divisible"):
        build_lm_pp_train_step(bad, mesh, optax.sgd(0.1), n_micro=4,
                               schedule="1f1b", shard_edges=True)


@pytest.mark.parametrize("dp,pp,tp,n_micro,kw", [
    (1, 2, 4, 4, {}),
    (2, 2, 2, 2, dict(pos_encoding="rotary", norm="rmsnorm",
                      activation="swiglu", ffn_bias=False,
                      tie_embeddings=True)),
    (1, 4, 2, 4, dict(n_kv_heads=2, attn_bias=True)),
])
def test_pp_tp_trajectory_matches_oracle(dp, pp, tp, n_micro, kw):
    """Round 5: the REAL-LM 3-D composition — GPipe stages of
    Megatron-sharded blocks — must reproduce the unpipelined replicated
    trajectory."""
    from elephas_tpu.models.pipeline_lm import (
        build_lm_pp_tp_train_step, lm_pp_tp_specs)
    from elephas_tpu.parallel.composite import build_mesh_3d

    model = _model(**kw)
    rows = _rows()
    want, o_losses = _oracle(model, optax.adam(1e-2), rows)

    mesh = build_mesh_3d(data=dp, pipe=pp, model=tp)
    step, opt_init = build_lm_pp_tp_train_step(
        model, mesh, optax.adam(1e-2), n_micro=n_micro, attn="dense")
    params = shard_by_specs(mesh, lm_pp_tp_specs(model),
                            model.init(seed=0))
    # block stacks shard BOTH ways: per-device slice of wq is [L/pp, D, D/tp]
    sl = params["wq"].addressable_shards[0].data.shape
    assert sl == (4 // pp, 32, 32 // tp), sl
    state = opt_init(params)
    sh = NamedSharding(mesh, P("data"))
    tokens, positions, targets = make_lm_batches(rows)
    batch = tuple(jax.device_put(a, sh) for a in (tokens, positions, targets))
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=2e-4, atol=2e-5)
    got = {k: np.asarray(v) for k, v in params.items()}
    for k, v in want.items():
        if k == "bk":
            # bk's true gradient is mathematically ZERO (a uniform key
            # bias shifts every score in a query's row equally — softmax
            # is shift-invariant), so adam amplifies float noise into
            # ±lr steps; bound by the 3-step adam step size instead.
            assert np.max(np.abs(got[k] - v)) < 3.5 * 1e-2, "bk walk"
            continue
        np.testing.assert_allclose(got[k], v, rtol=2e-3, atol=2e-4,
                                   err_msg=k)


def test_pp_tp_guards():
    from elephas_tpu.models.pipeline_lm import build_lm_pp_tp_train_step
    from elephas_tpu.parallel.composite import build_mesh_3d

    mesh = build_mesh_3d(data=1, pipe=2, model=4)
    model = _model(n_layers=3)  # 3 % 2 != 0
    with pytest.raises(ValueError, match="divisible"):
        build_lm_pp_tp_train_step(model, mesh, optax.sgd(0.1), n_micro=2)
    bad_heads = _model(n_heads=2)  # 2 % 4 != 0
    with pytest.raises(ValueError, match="n_heads"):
        build_lm_pp_tp_train_step(bad_heads, mesh, optax.sgd(0.1),
                                  n_micro=2)
