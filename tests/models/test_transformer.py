"""Sequence-parallel transformer LM vs the dense single-device oracle.

Batch over "data", sequence over "seq", ring or Ulysses attention inside one
shard_map program — forward logits and training trajectories must match the
unsharded dense-attention model on the 8 virtual CPU devices (conftest).
"""

import numpy as np
import optax
import pytest

import jax

from elephas_tpu.compat import shard_map as compat_shard_map
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from elephas_tpu.models.transformer import (
    TransformerLM,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)


def _model():
    return TransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=2,
                         d_ff=32, max_len=32)


def _data(b=4, t=32, vocab=17, seed=0):
    rng = np.random.default_rng(seed)
    # learnable structure: next token = (token + 1) % vocab with noise-free
    # deterministic rows → the LM can drive loss toward zero
    start = rng.integers(0, vocab, size=(b, 1))
    rows = (start + np.arange(t + 1)) % vocab
    return make_lm_batches(rows)


@pytest.mark.parametrize("attn,dp,sp", [("ring", 2, 4), ("ulysses", 2, 4),
                                        ("ring", 1, 8), ("flash", 4, 1)])
def test_forward_matches_dense(attn, dp, sp):
    model = _model()
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    tokens, positions, targets = _data()

    want = np.asarray(model.apply(params, tokens, positions, attn="dense"))

    mesh = build_mesh_sp(data=dp, seq=sp)
    fwd = jax.jit(
        compat_shard_map(
            lambda p, tk, ps: model.apply(p, tk, ps, attn=attn),
            mesh=mesh,
            in_specs=(model.specs(), P("data", "seq"), P("data", "seq")),
            out_specs=P("data", "seq"),
            check_vma=False,
        )
    )
    sharding = NamedSharding(mesh, P("data", "seq"))
    got = np.asarray(fwd(model.shard_params(mesh, model.init(seed=1)),
                         jax.device_put(tokens, sharding),
                         jax.device_put(positions, sharding)))
    np.testing.assert_allclose(got, want, atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_train_step_matches_dense(attn):
    model = _model()
    optimizer = optax.adam(1e-2)
    tokens, positions, targets = _data()
    params0 = model.init(seed=2)

    # dense oracle
    o_params = {k: jnp.asarray(v) for k, v in params0.items()}
    o_state = optimizer.init(o_params)
    ntok = float(tokens.size)
    o_losses = []
    for _ in range(3):
        def loss_fn(p):
            return model.loss(p, tokens, positions, targets, attn="dense") / ntok
        loss, grads = jax.value_and_grad(loss_fn)(o_params)
        updates, o_state = optimizer.update(grads, o_state, o_params)
        o_params = jax.tree_util.tree_map(jnp.add, o_params, updates)
        o_losses.append(float(loss))

    mesh = build_mesh_sp(data=2, seq=4)
    step, opt_init = build_lm_train_step(model, mesh, optimizer, attn=attn)
    params = model.shard_params(mesh, params0)
    state = opt_init(params)
    td, pd, gd = shard_lm_batch(mesh, tokens, positions, targets)
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, td, pd, gd)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=2e-4, atol=2e-5)
    for k, v in o_params.items():
        np.testing.assert_allclose(
            np.asarray(params[k]), np.asarray(v), rtol=5e-4, atol=5e-5,
            err_msg=k,
        )


def test_flash_train_step_matches_dense():
    """attn='flash' (blockwise custom-VJP kernel, dp-only mesh) takes the
    same optimization trajectory as the dense oracle — gradients included."""
    model = _model()
    optimizer = optax.adam(1e-2)
    tokens, positions, targets = _data()
    params0 = model.init(seed=2)

    o_params = {k: jnp.asarray(v) for k, v in params0.items()}
    o_state = optimizer.init(o_params)
    ntok = float(tokens.size)
    o_losses = []
    for _ in range(3):
        def loss_fn(p):
            return model.loss(p, tokens, positions, targets, attn="dense") / ntok
        loss, grads = jax.value_and_grad(loss_fn)(o_params)
        updates, o_state = optimizer.update(grads, o_state, o_params)
        o_params = jax.tree_util.tree_map(jnp.add, o_params, updates)
        o_losses.append(float(loss))

    mesh = build_mesh_sp(data=4, seq=1)
    step, opt_init = build_lm_train_step(model, mesh, optimizer, attn="flash")
    params = model.shard_params(mesh, params0)
    state = opt_init(params)
    td, pd, gd = shard_lm_batch(mesh, tokens, positions, targets)
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, td, pd, gd)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=2e-4, atol=2e-5)
    for k, v in o_params.items():
        np.testing.assert_allclose(
            np.asarray(params[k]), np.asarray(v), rtol=5e-4, atol=5e-5,
            err_msg=k,
        )


def test_flash_rejected_under_seq_axis():
    mesh = build_mesh_sp(data=1, seq=8)
    model = TransformerLM(vocab=10, d_model=16, n_heads=4, n_layers=1,
                          d_ff=16, max_len=32)
    with pytest.raises(ValueError, match="whole-sequence-per-shard"):
        build_lm_train_step(model, mesh, optax.sgd(0.1), attn="flash")


def test_learns_synthetic_task():
    """Loss must fall substantially on the deterministic +1 sequence task."""
    model = _model()
    mesh = build_mesh_sp(data=2, seq=4)
    step, opt_init = build_lm_train_step(model, mesh, optax.adam(3e-3),
                                         attn="ring")
    params = model.shard_params(mesh, model.init(seed=0))
    state = opt_init(params)
    tokens, positions, targets = _data(b=8)
    td, pd, gd = shard_lm_batch(mesh, tokens, positions, targets)
    first = last = None
    for i in range(30):
        params, state, loss = step(params, state, td, pd, gd)
        if i == 0:
            first = float(loss)
        last = float(loss)
    assert last < first * 0.5, (first, last)


def test_rotary_forward_matches_dense_and_learns():
    """RoPE: sharded ring forward equals the dense oracle (absolute
    positions make rotation shard-invariant), params have no pos table,
    and the LM still learns the synthetic task."""
    model = TransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=2,
                          d_ff=32, max_len=32, pos_encoding="rotary")
    assert "pos" not in model.param_shapes()
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    tokens, positions, targets = _data()

    want = np.asarray(model.apply(params, tokens, positions, attn="dense"))
    mesh = build_mesh_sp(data=2, seq=4)
    fwd = jax.jit(
        compat_shard_map(
            lambda p, tk, ps: model.apply(p, tk, ps, attn="ring"),
            mesh=mesh,
            in_specs=(model.specs(), P("data", "seq"), P("data", "seq")),
            out_specs=P("data", "seq"),
            check_vma=False,
        )
    )
    sharding = NamedSharding(mesh, P("data", "seq"))
    got = np.asarray(fwd(model.shard_params(mesh, model.init(seed=1)),
                         jax.device_put(tokens, sharding),
                         jax.device_put(positions, sharding)))
    np.testing.assert_allclose(got, want, atol=5e-5, rtol=5e-5)

    step, opt_init = build_lm_train_step(model, mesh, optax.adam(3e-3),
                                         attn="ring")
    p = model.shard_params(mesh, model.init(seed=0))
    s = opt_init(p)
    td, pd, gd = shard_lm_batch(mesh, *_data(b=8))
    first = last = None
    for i in range(30):
        p, s, loss = step(p, s, td, pd, gd)
        first = float(loss) if i == 0 else first
        last = float(loss)
    assert last < first * 0.5, (first, last)


def test_tied_embeddings_and_eval_step():
    """tie_embeddings drops the head param and still trains/generates;
    build_lm_eval_step's sharded mean CE equals the dense computation."""
    from elephas_tpu.models.transformer import build_lm_eval_step

    model = TransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=2,
                          d_ff=32, max_len=32, tie_embeddings=True)
    assert "head" not in model.param_shapes()
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    tokens, positions, targets = _data()

    # dense mean CE oracle
    dense = float(model.loss(params, tokens, positions, targets,
                             attn="dense")) / tokens.size

    mesh = build_mesh_sp(data=2, seq=4)
    eval_fn = build_lm_eval_step(model, mesh, attn="ring")
    td, pd, gd = shard_lm_batch(mesh, tokens, positions, targets)
    got = float(eval_fn(model.shard_params(mesh, model.init(seed=1)),
                        td, pd, gd))
    np.testing.assert_allclose(got, dense, rtol=1e-5, atol=1e-6)

    # tied model trains and its loss falls
    step, opt_init = build_lm_train_step(model, mesh, optax.adam(3e-3),
                                         attn="ring")
    p = model.shard_params(mesh, model.init(seed=0))
    s = opt_init(p)
    td, pd, gd = shard_lm_batch(mesh, *_data(b=8))
    first = last = None
    for i in range(50):
        p, s, loss = step(p, s, td, pd, gd)
        first = float(loss) if i == 0 else first
        last = float(loss)
    assert last < first * 0.6, (first, last)

    # cached generation still equals the uncached rollout when tied
    hp = {k: jnp.asarray(np.asarray(v)) for k, v in p.items()}
    prompt = np.asarray(tokens[:2, :4])
    out = np.asarray(model.generate(hp, prompt, n_new=4))
    seq = prompt.copy()
    for _ in range(4):
        ps = np.broadcast_to(np.arange(seq.shape[1]), seq.shape)
        logits = model.apply(hp, jnp.asarray(seq), jnp.asarray(ps),
                             attn="dense")
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq)


@pytest.mark.parametrize("n_kv,pos_enc", [(2, "learned"), (1, "rotary")])
def test_gqa_matches_dense_and_shrinks_cache(n_kv, pos_enc):
    """Grouped-query attention: sharded ring forward equals the dense
    oracle, the KV cache carries only the KV heads, decode stays exact,
    and training still learns."""
    model = TransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=2,
                          d_ff=32, max_len=32, n_kv_heads=n_kv,
                          pos_encoding=pos_enc)
    assert model.param_shapes()["wk"].shape == (2, 16, 4 * n_kv)
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    tokens, positions, targets = _data()

    want = np.asarray(model.apply(params, tokens, positions, attn="dense"))
    mesh = build_mesh_sp(data=2, seq=4)
    fwd = jax.jit(
        compat_shard_map(
            lambda p, tk, ps: model.apply(p, tk, ps, attn="ring"),
            mesh=mesh,
            in_specs=(model.specs(), P("data", "seq"), P("data", "seq")),
            out_specs=P("data", "seq"),
            check_vma=False,
        )
    )
    sharding = NamedSharding(mesh, P("data", "seq"))
    got = np.asarray(fwd(model.shard_params(mesh, model.init(seed=1)),
                         jax.device_put(tokens, sharding),
                         jax.device_put(positions, sharding)))
    np.testing.assert_allclose(got, want, atol=5e-5, rtol=5e-5)

    # cache holds only the KV heads; cached decode still equals the full
    # forward's logits position-by-position
    cache = model.init_cache(batch=tokens.shape[0], length=12)
    # length rounds up to the flash-decode T-block (12 → 16); extra
    # positions are masked by pos
    assert cache["k"].shape == (2, tokens.shape[0], n_kv, 16, 4)
    toks12 = jnp.asarray(tokens[:, :12])
    full = np.asarray(model.apply(params, toks12, positions[:, :12],
                                  attn="dense"))
    step_logits = []
    for t in range(12):
        logits, cache = model.decode_step(params, toks12[:, t], t, cache)
        step_logits.append(np.asarray(logits))
    np.testing.assert_allclose(np.stack(step_logits, 1), full,
                               atol=3e-5, rtol=3e-5)

    step, opt_init = build_lm_train_step(model, mesh, optax.adam(3e-3),
                                         attn="ring")
    p = model.shard_params(mesh, model.init(seed=0))
    s = opt_init(p)
    td, pd, gd = shard_lm_batch(mesh, *_data(b=8))
    first = last = None
    for i in range(30):
        p, s, loss = step(p, s, td, pd, gd)
        first = float(loss) if i == 0 else first
        last = float(loss)
    assert last < first * 0.6, (first, last)


def test_gqa_validation():
    with pytest.raises(ValueError, match="n_kv_heads"):
        TransformerLM(vocab=10, d_model=16, n_heads=4, n_layers=1,
                      d_ff=16, max_len=8, n_kv_heads=3)


def test_pos_encoding_validation():
    with pytest.raises(ValueError, match="pos_encoding"):
        TransformerLM(vocab=10, d_model=16, n_heads=4, n_layers=1,
                      d_ff=16, max_len=8, pos_encoding="alibi")
    with pytest.raises(ValueError, match="even head dim"):
        TransformerLM(vocab=10, d_model=12, n_heads=4, n_layers=1,
                      d_ff=16, max_len=8, pos_encoding="rotary")


def test_bfloat16_compute():
    """bf16 activations: forward stays close to f32, training still learns,
    params/optimizer remain f32."""
    f32 = _model()
    bf16 = TransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=2,
                         d_ff=32, max_len=32, compute_dtype="bfloat16")
    params = {k: jnp.asarray(v) for k, v in f32.init(seed=1).items()}
    tokens, positions, targets = _data()
    a = np.asarray(f32.apply(params, tokens, positions, attn="dense"))
    b_raw = bf16.apply(params, tokens, positions, attn="dense")
    assert b_raw.dtype == jnp.float32  # logits come back f32, pre-cast
    np.testing.assert_allclose(a, np.asarray(b_raw), atol=0.15, rtol=0.1)

    mesh = build_mesh_sp(data=2, seq=4)
    step, opt_init = build_lm_train_step(bf16, mesh, optax.adam(3e-3),
                                         attn="ring")
    p = bf16.shard_params(mesh, bf16.init(seed=0))
    s = opt_init(p)
    td, pd, gd = shard_lm_batch(mesh, *_data(b=8))
    first = last = None
    for i in range(20):
        p, s, loss = step(p, s, td, pd, gd)
        first = float(loss) if i == 0 else first
        last = float(loss)
    assert p["wq"].dtype == jnp.float32  # master params stay f32
    assert last < first * 0.7, (first, last)


def test_head_divisibility_validation():
    with pytest.raises(ValueError, match="not divisible"):
        TransformerLM(vocab=10, d_model=15, n_heads=4, n_layers=1,
                      d_ff=16, max_len=8)


def test_build_and_call_validation():
    mesh = build_mesh_sp(data=1, seq=8)
    model = TransformerLM(vocab=10, d_model=16, n_heads=4, n_layers=1,
                          d_ff=16, max_len=32)
    # ulysses needs H % seq == 0 (4 % 8 != 0) — caught at build time
    with pytest.raises(ValueError, match="ulysses"):
        build_lm_train_step(model, mesh, optax.sgd(0.1), attn="ulysses")
    # over-long sequences must be rejected, not silently position-clamped
    step, opt_init = build_lm_train_step(model, mesh, optax.sgd(0.1),
                                         attn="ring")
    params = model.shard_params(mesh, model.init())
    state = opt_init(params)
    rows = np.tile(np.arange(41, dtype=np.int64) % 10, (2, 1))
    tokens, positions, targets = make_lm_batches(rows)  # T=40 > max_len=32
    with pytest.raises(ValueError, match="exceeds max_len"):
        step(params, state, *shard_lm_batch(mesh, tokens, positions, targets))
