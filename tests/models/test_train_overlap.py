"""Loss-curve parity for the LM train-step hot-path knobs.

``build_lm_train_step(overlap_grads=, fused_apply=, remat=)`` rebuilds the
step's backward-reduction, optimizer-apply, and rematerialization layers;
every variant must optimize the SAME objective as the baseline step. Pinned
here:

- **Bit-identity where the math is exactly associative**: at
  ``accum_steps=1`` with ``remat="none"``, overlapped reduction moves each
  psum to the program point its cotangent is produced WITHOUT changing its
  operand, and the fused apply replays the unfused op sequence leaf-fused —
  params after N steps are bit-identical, dense and MoE.
- **Loss-trajectory allclose elsewhere**: accumulation reassociates the
  per-microbatch cross-device sums (``Σ psum(g)`` vs ``psum(Σ g)``), the
  ring lowers the reduction through a different summation order, and remat
  recomputes the forward under different fusion — allclose on the loss
  trajectory over ≥20 steps, NOT on raw params: adam's ``m/√v`` normalizer
  amplifies float-noise-level gradient differences near small ``v``, so
  param-space divergence is expected while the optimization trajectory
  stays pinned (measured max relative loss drift ≤ 3e-4 over 25 steps on
  this backend; asserted at 2e-3).

The ≥20-step dense+MoE × accum ∈ {1,2} matrix required by the hot-path
acceptance runs in tier-1; the wider combined-knob sweeps are marked
``perf`` (+``slow``) — run them with ``make test-perf``.
"""

import numpy as np
import pytest

import optax

from elephas_tpu.models import (
    MoETransformerLM,
    TransformerLM,
    adam_compact,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)
from elephas_tpu.models import transformer as transformer_mod

perf = pytest.mark.perf
slow = pytest.mark.slow

LOSS_RTOL = 2e-3


def _build(kind, accum=1, overlap=False, fused=False, remat="none",
           optimizer=None):
    mesh = build_mesh_sp(data=2, seq=2)
    if kind == "moe":
        model = MoETransformerLM(vocab=13, d_model=8, n_heads=2, n_layers=2,
                                 d_ff=16, max_len=16, n_experts=2,
                                 aux_weight=0.01)
    else:
        model = TransformerLM(vocab=13, d_model=8, n_heads=2, n_layers=2,
                              d_ff=16, max_len=16)
    optimizer = adam_compact(1e-2) if optimizer is None else optimizer
    step, opt_init = build_lm_train_step(
        model, mesh, optimizer, attn="ring", accum_steps=accum,
        overlap_grads=overlap, fused_apply=fused, remat=remat,
    )
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 13, size=(8, 17))
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))
    params = model.shard_params(mesh, model.init(seed=0))
    return step, params, opt_init(params), batch


def _trajectory(step, params, state, batch, steps):
    losses = []
    for _ in range(steps):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))
    return np.asarray(losses), {k: np.asarray(v) for k, v in params.items()}


@pytest.mark.parametrize("kind", ["dense", "moe"])
def test_overlap_fused_bit_identical_accum1(kind):
    """accum=1, remat=none: the overlapped+fused step is EXACTLY the
    baseline step — psums move, operands don't; the fused apply replays
    the unfused op sequence. Params stay bit-identical over 5 steps."""
    losses_b, params_b = _trajectory(*_build(kind), steps=5)
    losses_o, params_o = _trajectory(
        *_build(kind, overlap=True, fused=True), steps=5)
    np.testing.assert_array_equal(losses_o, losses_b)
    for k in params_b:
        np.testing.assert_array_equal(params_o[k], params_b[k], err_msg=k)


@pytest.mark.parametrize("kind", ["dense", "moe"])
@pytest.mark.parametrize("accum", [1, 2])
def test_overlap_fused_loss_parity(kind, accum):
    """The required parity matrix: overlapped+fused matches the baseline
    loss trajectory over 20 steps, dense and MoE, accum_steps ∈ {1, 2},
    on the dp×sp mesh."""
    losses_b, _ = _trajectory(*_build(kind, accum=accum), steps=20)
    losses_o, _ = _trajectory(
        *_build(kind, accum=accum, overlap=True, fused=True), steps=20)
    np.testing.assert_allclose(losses_o, losses_b, rtol=LOSS_RTOL,
                               atol=1e-5)


def test_ring_reduction_loss_parity(monkeypatch):
    """overlap_grads='ring' with the size threshold forced to 0 pushes
    EVERY gradient leaf through the chunked ppermute ring; the summation
    order differs from psum, so parity is allclose, not bitwise."""
    monkeypatch.setattr(transformer_mod, "_RING_MIN_ELEMS", 1)
    losses_b, _ = _trajectory(*_build("dense"), steps=20)
    losses_r, _ = _trajectory(
        *_build("dense", overlap="ring", fused=True), steps=20)
    np.testing.assert_allclose(losses_r, losses_b, rtol=LOSS_RTOL,
                               atol=1e-5)


@pytest.mark.parametrize("remat", ["dots", "full"])
def test_remat_loss_parity(remat):
    """Remat recomputes the block forward (possibly under different XLA
    fusion), so the first step must agree tightly and the trajectory
    within the pinned tolerance."""
    losses_b, _ = _trajectory(*_build("dense"), steps=20)
    losses_r, _ = _trajectory(*_build("dense", remat=remat), steps=20)
    np.testing.assert_allclose(losses_r[0], losses_b[0], rtol=1e-5)
    np.testing.assert_allclose(losses_r, losses_b, rtol=5e-3, atol=1e-5)


def test_fused_apply_matches_unfused_chain():
    """fused_apply alone (no overlap) is bit-identical to update+apply —
    the optimizer-level contract, independent of the reduction layout."""
    losses_b, params_b = _trajectory(*_build("dense"), steps=5)
    losses_f, params_f = _trajectory(*_build("dense", fused=True), steps=5)
    np.testing.assert_array_equal(losses_f, losses_b)
    for k in params_b:
        np.testing.assert_array_equal(params_f[k], params_b[k], err_msg=k)


def test_knob_validation():
    mesh = build_mesh_sp(data=2, seq=2)
    model = TransformerLM(vocab=13, d_model=8, n_heads=2, n_layers=1,
                          d_ff=16, max_len=16)
    with pytest.raises(ValueError, match="fused_apply"):
        build_lm_train_step(model, mesh, optax.adam(1e-2), fused_apply=True)
    with pytest.raises(ValueError, match="remat"):
        build_lm_train_step(model, mesh, adam_compact(1e-2), remat="dotz")
    with pytest.raises(ValueError, match="overlap_grads"):
        build_lm_train_step(model, mesh, adam_compact(1e-2),
                            overlap_grads="rings")


@perf
@slow
@pytest.mark.parametrize("kind", ["dense", "moe"])
@pytest.mark.parametrize("accum", [1, 2])
def test_long_trajectory_combined_knobs(kind, accum, monkeypatch):
    """The full-stack long trajectory: ring reduction on every leaf +
    fused apply + remat='dots' vs the plain baseline, 40 steps.

    With all three reassociating knobs stacked, pointwise parity decays
    over long horizons — float-noise gradient differences compound
    through adam's normalizer (measured ~3% dense / ~5% MoE relative by
    step 40 while both curves track the same descent). Pinned: tight
    pointwise parity over the first 10 steps, a loose whole-trajectory
    envelope that still catches real divergence (blowup, stall), and
    matching net progress."""
    monkeypatch.setattr(transformer_mod, "_RING_MIN_ELEMS", 1)
    losses_b, _ = _trajectory(*_build(kind, accum=accum), steps=40)
    losses_o, _ = _trajectory(
        *_build(kind, accum=accum, overlap="ring", fused=True,
                remat="dots"), steps=40)
    np.testing.assert_allclose(losses_o[:10], losses_b[:10],
                               rtol=5e-3, atol=1e-5)
    np.testing.assert_allclose(losses_o, losses_b, rtol=0.15, atol=1e-5)
    assert losses_o[-1] < losses_o[0] - 0.5
    np.testing.assert_allclose(losses_o[0] - losses_o[-1],
                               losses_b[0] - losses_b[-1], rtol=0.15)
