"""LM ZeRO-3 (models/fsdp_lm.py) vs the replicated oracle.

Contracts pinned here:
- the chunked layout round-trips host params exactly;
- a 3-step FSDP trajectory equals the replicated ``build_lm_train_step``
  trajectory (same math, different storage layout);
- per-device resident params + optimizer state are bounded by
  ``total / P`` plus padding (the ZeRO-3 memory claim);
- gradient-accumulated and rematerialized steps change nothing;
- sharded-checkpoint save/restore resumes the exact trajectory.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from elephas_tpu.models.fsdp_lm import LMFsdpLayout, build_lm_fsdp_train_step
from elephas_tpu.models.transformer import (
    TransformerLM,
    MoETransformerLM,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)


def _model(**kw):
    cfg = dict(vocab=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
               max_len=32)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _rows(b=8, t=32, seed=0):
    return np.random.default_rng(seed).integers(0, 128, size=(b, t + 1))


def _oracle_params(model, optimizer, rows, steps=3, attn="dense"):
    mesh = build_mesh_sp(data=1, seq=1)
    step, opt_init = build_lm_train_step(model, mesh, optimizer, attn=attn)
    params = model.shard_params(mesh, model.init(seed=0))
    state = opt_init(params)
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))
    losses = []
    for _ in range(steps):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))
    return {k: np.asarray(v) for k, v in params.items()}, losses


def test_layout_roundtrip():
    model = _model(pos_encoding="rotary", norm="rmsnorm",
                   activation="swiglu", ffn_bias=False, attn_bias=True,
                   tie_embeddings=True)
    layout = LMFsdpLayout(model, n_shards=8)
    params = model.init(seed=3)
    back = layout.unchunk_host(layout.chunk_host(params))
    assert set(back) == set(params)
    for k in params:
        np.testing.assert_array_equal(back[k], params[k], err_msg=k)


def _moe_model(**kw):
    cfg = dict(vocab=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
               max_len=32, n_experts=8, k=2, capacity_factor=2.0,
               pos_encoding="rotary", norm="rmsnorm", activation="swiglu",
               ffn_bias=False)
    cfg.update(kw)
    return MoETransformerLM(**cfg)


def test_moe_layout_needs_mesh_split():
    moe = _moe_model()
    with pytest.raises(ValueError, match="data_shards"):
        LMFsdpLayout(moe, n_shards=8)


def test_moe_layout_roundtrip():
    moe = _moe_model()
    layout = LMFsdpLayout(moe, n_shards=8, data_shards=4, expert_shards=2)
    params = moe.init(seed=3)
    back = layout.unchunk_host(layout.chunk_host(params))
    assert set(back) == set(params)
    for k in params:
        np.testing.assert_array_equal(back[k], params[k], err_msg=k)


@pytest.mark.parametrize("dp,sp,attn", [(4, 1, "flash"), (2, 2, "ring")])
def test_moe_trajectory_matches_replicated(dp, sp, attn):
    """Round 5: ZeRO-3 for the MoE LM — trajectory must equal the
    replicated dp×sp step (experts over 'seq', rest replicated) on the
    SAME mesh, which is itself pinned to the dense-emulated oracle."""
    moe = _moe_model(ep_groups=sp)
    rows = _rows(seed=5)
    mesh = build_mesh_sp(data=dp, seq=sp)

    # replicated oracle on the same mesh/geometry
    o_step, o_init = build_lm_train_step(moe, mesh, optax.adam(1e-2),
                                         attn=attn)
    o_params = moe.shard_params(mesh, moe.init(seed=0))
    o_state = o_init(o_params)
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))
    o_losses = []
    for _ in range(3):
        o_params, o_state, loss = o_step(o_params, o_state, *batch)
        o_losses.append(float(loss))
    want = {k: np.asarray(v) for k, v in o_params.items()}

    step, opt_init, layout = build_lm_fsdp_train_step(
        moe, mesh, optax.adam(1e-2), attn=attn)
    chunks = layout.shard(mesh, layout.chunk_host(moe.init(seed=0)))
    state = opt_init(chunks)
    losses = []
    for _ in range(3):
        chunks, state, loss = step(chunks, state, *batch)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=2e-4, atol=2e-5)
    got = layout.unchunk_host({k: np.asarray(v) for k, v in chunks.items()})
    for k, v in want.items():
        np.testing.assert_allclose(got[k], v, rtol=1e-3, atol=1e-4,
                                   err_msg=k)


def test_moe_per_device_memory_bound():
    """Resident MoE params + opt state per device ≤ total/P + padding —
    the whole point: experts AND their adam state divide by dp·sp."""
    moe = _moe_model()
    mesh = build_mesh_sp(data=4, seq=2)
    step, opt_init, layout = build_lm_fsdp_train_step(
        moe, mesh, optax.adam(1e-2), attn="ring")
    chunks = layout.shard(mesh, layout.chunk_host(moe.init(seed=0)))
    state = opt_init(chunks)

    leaves = (jax.tree_util.tree_leaves(chunks)
              + jax.tree_util.tree_leaves(state))
    per_dev = {}
    for leaf in leaves:
        for shard in leaf.addressable_shards:
            per_dev[shard.device] = (
                per_dev.get(shard.device, 0) + shard.data.nbytes)
    L, E = layout.n_layers, layout.n_experts
    total_full = 3 * 4 * (layout.btotal * L + layout.ototal
                          + layout.etotal * E * L)
    p = 8
    pad_slack = 3 * 4 * (
        (layout.bpadded - layout.btotal) * L
        + (layout.opadded - layout.ototal)
        + (layout.epadded - layout.etotal) * E * L) // p
    bound = total_full // p + pad_slack + 64
    assert len(per_dev) == p
    for dev, nbytes in per_dev.items():
        assert nbytes <= bound, (dev, nbytes, bound)


def test_moe_sharded_checkpoint_resume(tmp_path):
    from elephas_tpu.utils.checkpoint import (
        load_sharded_pytree,
        save_sharded_pytree,
    )

    moe = _moe_model()
    rows = _rows(seed=7)
    mesh = build_mesh_sp(data=2, seq=2)
    step, opt_init, layout = build_lm_fsdp_train_step(
        moe, mesh, optax.adam(1e-2), attn="ring")
    chunks = layout.shard(mesh, layout.chunk_host(moe.init(seed=0)))
    state = opt_init(chunks)
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))

    chunks, state, _ = step(chunks, state, *batch)
    save_sharded_pytree(str(tmp_path / "ck"), {"p": chunks, "o": state})
    want_chunks, want_state, want_loss = step(chunks, state, *batch)
    restored = load_sharded_pytree(
        str(tmp_path / "ck"), template={"p": want_chunks, "o": want_state})
    got_chunks, got_state, got_loss = step(restored["p"], restored["o"],
                                           *batch)
    assert float(got_loss) == pytest.approx(float(want_loss), rel=1e-6)
    for k in want_chunks:
        np.testing.assert_allclose(
            np.asarray(got_chunks[k]), np.asarray(want_chunks[k]),
            rtol=1e-6, atol=1e-7, err_msg=k)


@pytest.mark.parametrize("dp,sp,attn", [(4, 1, "dense"), (2, 2, "ring")])
def test_trajectory_matches_replicated_oracle(dp, sp, attn):
    model = _model()
    rows = _rows()
    want, o_losses = _oracle_params(model, optax.adam(1e-2), rows)

    mesh = build_mesh_sp(data=dp, seq=sp)
    step, opt_init, layout = build_lm_fsdp_train_step(
        model, mesh, optax.adam(1e-2), attn=attn)
    chunks = layout.shard(mesh, layout.chunk_host(model.init(seed=0)))
    state = opt_init(chunks)
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))
    losses = []
    for _ in range(3):
        chunks, state, loss = step(chunks, state, *batch)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=2e-4, atol=2e-5)
    got = layout.unchunk_host({k: np.asarray(v) for k, v in chunks.items()})
    for k, v in want.items():
        np.testing.assert_allclose(got[k], v, rtol=5e-4, atol=5e-5,
                                   err_msg=k)


def test_per_device_memory_bound():
    """Resident params + opt state per device ≤ (total / P) + padding."""
    model = _model()
    mesh = build_mesh_sp(data=4, seq=2)
    optimizer = optax.adam(1e-2)
    step, opt_init, layout = build_lm_fsdp_train_step(model, mesh, optimizer,
                                                      attn="ring")
    chunks = layout.shard(mesh, layout.chunk_host(model.init(seed=0)))
    state = opt_init(chunks)

    leaves = jax.tree_util.tree_leaves(chunks) + jax.tree_util.tree_leaves(state)
    per_dev = {}
    for leaf in leaves:
        for shard in leaf.addressable_shards:
            per_dev[shard.device] = (
                per_dev.get(shard.device, 0) + shard.data.nbytes)
    # full f32 params + adam mu/nu = 3 copies of every param
    total_full = 3 * 4 * (layout.btotal * layout.n_layers + layout.ototal)
    p = 8
    pad_slack = 3 * 4 * (
        (layout.bpadded - layout.btotal) * layout.n_layers
        + (layout.opadded - layout.ototal)) // p
    bound = total_full // p + pad_slack + 64  # 64B: scalar step count etc.
    assert len(per_dev) == p
    for dev, nbytes in per_dev.items():
        assert nbytes <= bound, (dev, nbytes, bound)


def test_accum_steps_identical():
    model = _model()
    rows = _rows()
    mesh = build_mesh_sp(data=2, seq=1)

    def run(accum):
        step, opt_init, layout = build_lm_fsdp_train_step(
            model, mesh, optax.adam(1e-2), attn="dense",
            accum_steps=accum)
        chunks = layout.shard(mesh, layout.chunk_host(model.init(seed=0)))
        state = opt_init(chunks)
        batch = shard_lm_batch(mesh, *make_lm_batches(rows))
        for _ in range(2):
            chunks, state, loss = step(chunks, state, *batch)
        return layout.unchunk_host(
            {k: np.asarray(v) for k, v in chunks.items()}), float(loss)

    p1, l1 = run(1)
    p2, l2 = run(2)
    np.testing.assert_allclose(l2, l1, rtol=1e-5)
    for k in p1:
        np.testing.assert_allclose(p2[k], p1[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


def test_remat_identical():
    model = _model()
    rows = _rows()
    mesh = build_mesh_sp(data=4, seq=1)

    def run(remat):
        step, opt_init, layout = build_lm_fsdp_train_step(
            model, mesh, optax.adam(1e-2), attn="dense", remat=remat)
        chunks = layout.shard(mesh, layout.chunk_host(model.init(seed=0)))
        state = opt_init(chunks)
        batch = shard_lm_batch(mesh, *make_lm_batches(rows))
        for _ in range(2):
            chunks, state, loss = step(chunks, state, *batch)
        return float(loss)

    assert run(True) == pytest.approx(run(False), rel=1e-6)


def test_sharded_checkpoint_resume(tmp_path):
    """save_sharded_pytree / load_sharded_pytree round-trips the chunked
    state with no host gather and resumes the exact trajectory."""
    from elephas_tpu.utils.checkpoint import (
        load_sharded_pytree,
        save_sharded_pytree,
    )

    model = _model()
    rows = _rows()
    mesh = build_mesh_sp(data=4, seq=2)
    optimizer = optax.adam(1e-2)
    step, opt_init, layout = build_lm_fsdp_train_step(
        model, mesh, optimizer, attn="ring")
    chunks = layout.shard(mesh, layout.chunk_host(model.init(seed=0)))
    state = opt_init(chunks)
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))

    chunks, state, _ = step(chunks, state, *batch)
    save_sharded_pytree(str(tmp_path / "ck"), {"p": chunks, "o": state})
    # uninterrupted continuation
    want_chunks, want_state, want_loss = step(chunks, state, *batch)

    # chunks/state were donated into the continuation step; the template
    # only needs shardings, so use the (identically sharded) results.
    restored = load_sharded_pytree(
        str(tmp_path / "ck"), template={"p": want_chunks, "o": want_state})
    got_chunks, got_state, got_loss = step(restored["p"], restored["o"],
                                           *batch)
    assert float(got_loss) == pytest.approx(float(want_loss), rel=1e-6)
    for k in want_chunks:
        np.testing.assert_allclose(
            np.asarray(got_chunks[k]), np.asarray(want_chunks[k]),
            rtol=1e-6, atol=1e-7, err_msg=k)
