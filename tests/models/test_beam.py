"""Beam search vs greedy and vs an exhaustive oracle.

On tiny vocabularies the exact best fixed-length continuation can be found
by brute force — beam search with a wide enough beam must find it, and
``beam_size=1`` must reproduce greedy ``generate`` token-for-token.
"""

import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elephas_tpu.models import TransformerLM, generate_beam


def _model(**kw):
    cfg = dict(vocab=12, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=24)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=0):
    return jax.tree.map(jnp.asarray, model.init(seed))


def _seq_logprob(model, params, rows, t0):
    """Summed next-token log-prob of the generated span of ``rows``."""
    toks = rows[:, :-1]
    pos = np.broadcast_to(np.arange(toks.shape[1]), toks.shape)
    lp = jax.nn.log_softmax(
        np.asarray(model.apply(params, toks, pos)).astype(np.float32), -1)
    out = []
    for b in range(rows.shape[0]):
        s = sum(lp[b, j, rows[b, j + 1]] for j in range(t0 - 1,
                                                        rows.shape[1] - 1))
        out.append(float(s))
    return np.array(out)


def test_beam1_equals_greedy():
    model = _model()
    params = _params(model)
    prompt = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.int32)
    want = np.asarray(model.generate(params, prompt, 8))
    got, scores = generate_beam(model, params, prompt, 8, beam_size=1)
    np.testing.assert_array_equal(np.asarray(got), want)
    np.testing.assert_allclose(
        np.asarray(scores), _seq_logprob(model, params, want, 4), atol=1e-3)


def test_wide_beam_finds_exhaustive_optimum():
    # beam_size = vocab with n_new = 2 IS exhaustive: after the first step
    # every token is a beam, and the second step ranks all V^2 candidates
    model = _model(vocab=6, max_len=10)
    params = _params(model, seed=3)
    prompt = np.array([[1, 2, 3]], np.int32)
    n_new = 2
    best_s, best_rows = -np.inf, None
    for cont in itertools.product(range(6), repeat=n_new):
        rows = np.concatenate([prompt, np.array([cont], np.int32)], axis=1)
        s = _seq_logprob(model, params, rows, 3)[0]
        if s > best_s:
            best_s, best_rows = s, rows
    got, scores = generate_beam(model, params, prompt, n_new, beam_size=6)
    np.testing.assert_array_equal(np.asarray(got), best_rows)
    np.testing.assert_allclose(float(scores[0]), best_s, atol=1e-3)


def test_beam_score_at_least_greedy():
    model = _model()
    params = _params(model, seed=1)
    prompt = np.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], np.int32)
    greedy = np.asarray(model.generate(params, prompt, 9))
    g_score = _seq_logprob(model, params, greedy, 5)
    _, b_score = generate_beam(model, params, prompt, 9, beam_size=4)
    assert (np.asarray(b_score) >= g_score - 1e-4).all()


def test_eos_freezes_beams():
    model = _model()
    params = _params(model, seed=2)
    prompt = np.array([[1, 2, 3, 4]], np.int32)
    eos = 7
    got, _ = generate_beam(model, params, prompt, 12, beam_size=4,
                           eos_id=eos)
    row = np.asarray(got)[0, 4:]
    hits = np.nonzero(row == eos)[0]
    if hits.size:  # everything after the first eos must stay eos
        assert (row[hits[0]:] == eos).all()


def test_batch_rows_are_independent():
    model = _model()
    params = _params(model, seed=4)
    p1 = np.array([[1, 2, 3, 4]], np.int32)
    p2 = np.array([[8, 9, 10, 11]], np.int32)
    both = np.concatenate([p1, p2], axis=0)
    g_both, s_both = generate_beam(model, params, both, 6, beam_size=3)
    g1, s1 = generate_beam(model, params, p1, 6, beam_size=3)
    g2, s2 = generate_beam(model, params, p2, 6, beam_size=3)
    np.testing.assert_array_equal(np.asarray(g_both),
                                  np.concatenate([g1, g2], axis=0))
    np.testing.assert_allclose(np.asarray(s_both),
                               np.concatenate([s1, s2]), atol=1e-4)


def test_works_on_architecture_variants():
    for kw in (dict(activation="gelu", attn_bias=True, tie_embeddings=True),
               dict(activation="swiglu", norm="rmsnorm", ffn_bias=False,
                    pos_encoding="rotary", n_kv_heads=2, attn_window=5)):
        model = _model(**kw)
        params = _params(model)
        prompt = np.array([[1, 2, 3]], np.int32)
        want = np.asarray(model.generate(params, prompt, 6))
        got, _ = generate_beam(model, params, prompt, 6, beam_size=1)
        np.testing.assert_array_equal(np.asarray(got), want)
        wide, _ = generate_beam(model, params, prompt, 6, beam_size=4)
        assert np.asarray(wide).shape == (1, 9)


def test_validation():
    model = _model()
    params = _params(model)
    prompt = np.array([[1, 2]], np.int32)
    with pytest.raises(ValueError, match="beam_size"):
        generate_beam(model, params, prompt, 4, beam_size=0)
    with pytest.raises(ValueError, match="vocab"):
        generate_beam(model, params, prompt, 4, beam_size=200)
    with pytest.raises(ValueError, match="max_len"):
        generate_beam(model, params, prompt, 400)
