"""Keras↔JAX bridge tests: weight split/join, loss/optimizer mapping."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elephas_tpu.models import KerasModelAdapter, resolve_per_sample_loss, to_optax


def test_weights_state_round_trip(classifier_factory):
    model = classifier_factory()
    adapter = KerasModelAdapter(model)
    flat = model.get_weights()
    tv, ntv = adapter.weights_to_state(flat)
    assert len(tv) == len(model.trainable_variables)
    flat2 = adapter.state_to_weights(tv, ntv)
    for a, b in zip(flat, flat2):
        assert np.allclose(a, b)


def test_adapter_requires_built_model():
    import keras

    model = keras.Sequential([keras.layers.Dense(2)])
    with pytest.raises(ValueError):
        KerasModelAdapter(model, loss="mse")


def test_adapter_infers_accuracy(classifier_factory):
    adapter = KerasModelAdapter(classifier_factory())
    assert adapter.wants_accuracy


def test_train_step_reduces_loss(classifier_factory, toy_classification):
    x, y = toy_classification
    adapter = KerasModelAdapter(classifier_factory())
    opt = adapter.make_optimizer()
    step = adapter.build_train_step(opt)
    tv, ntv = adapter.state_values()
    opt_state = opt.init(tv)
    sw = np.ones((64,), "float32")
    first_loss = None
    for i in range(20):
        tv, ntv, opt_state, (loss_ws, _, wsum) = step(
            tv, ntv, opt_state, x[:64], y[:64], sw
        )
        if first_loss is None:
            first_loss = float(loss_ws / wsum)
    assert float(loss_ws / wsum) < first_loss


def test_all_padding_batch_is_noop(classifier_factory, toy_classification):
    """Zero sample-weight batches must not move params or optimizer state."""
    x, y = toy_classification
    adapter = KerasModelAdapter(classifier_factory())
    opt = adapter.make_optimizer()
    step = adapter.build_train_step(opt)
    tv, ntv = adapter.state_values()
    opt_state = opt.init(tv)
    sw = np.zeros((32,), "float32")
    tv2, ntv2, opt2, stats = step(tv, ntv, opt_state, x[:32], y[:32], sw)
    for a, b in zip(tv, tv2):
        assert np.allclose(a, b)


@pytest.mark.parametrize(
    "name", ["sgd", "adam", "rmsprop", "adagrad", "adamw", "nadam"]
)
def test_optimizer_mapping(name):
    tx = to_optax(name)
    params = [jnp.ones((3,))]
    state = tx.init(params)
    grads = [jnp.ones((3,))]
    updates, _ = tx.update(grads, state, params)
    assert updates[0].shape == (3,)


def test_optimizer_from_keras_object():
    import keras

    tx = to_optax(keras.optimizers.Adam(learning_rate=0.01))
    params = [jnp.zeros((2,))]
    updates, _ = tx.update([jnp.ones((2,))], tx.init(params), params)
    assert np.all(np.asarray(updates[0]) < 0)


@pytest.mark.parametrize(
    "loss,y_shape,out_shape",
    [
        ("mse", (8, 4), (8, 4)),
        ("mae", (8, 4), (8, 4)),
        ("categorical_crossentropy", (8, 5), (8, 5)),
        ("binary_crossentropy", (8, 1), (8, 1)),
        ("hinge", (8, 1), (8, 1)),
    ],
)
def test_per_sample_losses_shapes(loss, y_shape, out_shape):
    rng = np.random.default_rng(0)
    y = rng.uniform(0.1, 0.9, size=y_shape).astype("float32")
    p = rng.uniform(0.1, 0.9, size=out_shape).astype("float32")
    fn = resolve_per_sample_loss(loss)
    out = fn(y, p)
    assert out.shape == (8,)
    assert np.all(np.isfinite(np.asarray(out)))


def test_sparse_categorical_loss():
    fn = resolve_per_sample_loss("sparse_categorical_crossentropy")
    y = np.array([0, 2], dtype="int32")
    p = np.array([[0.8, 0.1, 0.1], [0.1, 0.1, 0.8]], dtype="float32")
    out = np.asarray(fn(y, p))
    assert out.shape == (2,)
    assert np.allclose(out, -np.log(0.8), atol=1e-5)


def test_loss_matches_keras_reference():
    import keras

    rng = np.random.default_rng(1)
    y = np.eye(4, dtype="float32")[rng.integers(0, 4, size=16)]
    p = rng.uniform(0.05, 0.95, size=(16, 4)).astype("float32")
    p = p / p.sum(axis=1, keepdims=True)
    ours = np.asarray(resolve_per_sample_loss("categorical_crossentropy")(y, p))
    theirs = np.asarray(keras.losses.categorical_crossentropy(y, p))
    assert np.allclose(ours, theirs, atol=1e-5)


def test_remat_flag_reaches_the_compiled_program(
    classifier_factory, toy_classification
):
    """SparkModel(remat=True) must actually change the compiled program —
    guard against the flag being silently dropped somewhere between the
    constructor and build_train_step (the resnet50 example relies on it)."""
    import jax

    x, y = toy_classification
    adapter = KerasModelAdapter(classifier_factory())
    opt = adapter.make_optimizer()
    tv, ntv = adapter.state_values()
    opt_state = opt.init(tv)
    sw = np.ones((64,), "float32")
    args = (tv, ntv, opt_state, x[:64], y[:64], sw)

    plain = str(jax.make_jaxpr(adapter.build_train_step(opt))(*args))
    remat = str(jax.make_jaxpr(adapter.build_train_step(opt, remat=True))(*args))
    assert "remat" not in plain
    assert "remat" in remat  # jax.checkpoint lowers to the remat primitive
