"""The architecture knobs (gelu/swiglu, rmsnorm, biases, rope_theta) work
through every code path: teacher-forced training, cached decode, and
seq-sharded generation.

models/hf_import.py resolves these knobs from HF configs; logits parity vs
torch lives in test_hf_import.py. Here the knob combinations themselves are
exercised against the framework's own oracles on the virtual CPU mesh.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from elephas_tpu.models import (
    TransformerLM,
    build_lm_generate,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)

GPT2ISH = dict(activation="gelu", norm="layernorm", attn_bias=True,
               ffn_bias=True, pos_encoding="learned", tie_embeddings=True)
LLAMAISH = dict(activation="swiglu", norm="rmsnorm", attn_bias=False,
                ffn_bias=False, pos_encoding="rotary", norm_eps=1e-6,
                rope_theta=500000.0, n_kv_heads=2)


def _model(**kw):
    cfg = dict(vocab=31, d_model=16, n_heads=4, n_layers=2, d_ff=32,
               max_len=32)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _rows(b=4, t=32, vocab=31, seed=0):
    rng = np.random.default_rng(seed)
    start = rng.integers(0, vocab, size=(b, 1))
    return (start + np.arange(t + 1)) % vocab


@pytest.mark.parametrize("arch", [GPT2ISH, LLAMAISH],
                         ids=["gpt2ish", "llamaish"])
def test_train_step_learns(arch):
    model = _model(**arch)
    mesh = build_mesh_sp(data=4, seq=2)
    step, opt_init = build_lm_train_step(model, mesh, optax.adam(1e-2),
                                         attn="ring")
    params = model.shard_params(mesh, model.init(0))
    opt = opt_init(params)
    tokens, positions, targets = make_lm_batches(_rows())
    batch = shard_lm_batch(mesh, tokens, positions, targets)
    first = None
    for _ in range(30):
        params, opt, loss = step(params, opt, *batch)
        first = float(loss) if first is None else first
    assert float(loss) < 0.5 * first


@pytest.mark.parametrize("arch", [GPT2ISH, LLAMAISH],
                         ids=["gpt2ish", "llamaish"])
def test_cached_generate_matches_teacher_forced(arch):
    model = _model(**arch)
    params = jax.tree.map(jnp.asarray, model.init(0))
    prompt = _rows(b=2, t=6)[:, :6].astype(np.int32)
    out = np.asarray(model.generate(params, prompt, 8))
    # every generated token must be the argmax of the teacher-forced
    # forward on its prefix (greedy self-consistency across cache paths)
    for j in range(6, 14):
        pos = np.broadcast_to(np.arange(j), (2, j))
        logits = np.asarray(model.apply(params, out[:, :j], pos))[:, -1]
        np.testing.assert_array_equal(out[:, j], logits.argmax(-1))


@pytest.mark.parametrize("arch", [GPT2ISH, LLAMAISH],
                         ids=["gpt2ish", "llamaish"])
def test_sharded_generate_matches_single_device(arch):
    model = _model(**arch)
    params = jax.tree.map(jnp.asarray, model.init(0))
    mesh = build_mesh_sp(data=2, seq=4)
    prompt = _rows(b=4, t=5)[:, :5].astype(np.int32)
    want = np.asarray(model.generate(params, prompt, 15))
    gen = build_lm_generate(model, mesh)
    got = np.asarray(gen(model.shard_params(mesh, params), prompt, 15))
    np.testing.assert_array_equal(got, want)


def test_bad_knobs_rejected():
    with pytest.raises(ValueError, match="activation"):
        _model(activation="swish")
    with pytest.raises(ValueError, match="norm"):
        _model(norm="batchnorm")


@pytest.mark.parametrize("arch", [GPT2ISH, LLAMAISH],
                         ids=["gpt2ish", "llamaish"])
def test_tp_forward_and_generate_match_replicated(arch):
    """Megatron TP now covers the hf_import architectures: same logits
    under the sharded train-path forward, and head-sharded generation
    token-for-token equal to the single-device rollout."""
    from elephas_tpu.models import (
        build_lm_tp_generate, build_lm_tp_train_step, build_mesh_tp,
        shard_tp_params,
    )

    model = _model(**arch)
    mesh = build_mesh_tp(data=4, model=2)  # n_kv_heads=2 bounds tp
    params = jax.tree.map(jnp.asarray, model.init(0))
    rows = _rows(b=4, t=16)

    # head-sharded generation == gathered rollout (before the train step:
    # the TP step donates its param buffers, which alias the replicated
    # leaves of `params`)
    prompt = rows[:4, :5].astype(np.int32)
    want = np.asarray(model.generate(params, prompt, 12))
    gen = build_lm_tp_generate(model, mesh, attn="dense")
    got = np.asarray(gen(shard_tp_params(mesh, model, params), prompt, 12))
    np.testing.assert_array_equal(got, want)

    # one TP train step runs and yields a finite loss
    tparams = shard_tp_params(mesh, model, params)
    step, opt_init = build_lm_tp_train_step(model, mesh, optax.sgd(0.1),
                                            attn="dense")
    tokens, positions, targets = make_lm_batches(rows)
    _, _, loss = step(tparams, opt_init(tparams), jnp.asarray(tokens),
                      jnp.asarray(positions), jnp.asarray(targets))
    assert np.isfinite(float(loss))


def test_tp_windowed_generate_matches_single_device():
    from elephas_tpu.models import build_lm_tp_generate, build_mesh_tp, \
        shard_tp_params

    model = _model(**{**MISTRALISH, "max_len": 64})
    mesh = build_mesh_tp(data=4, model=2)
    params = jax.tree.map(jnp.asarray, model.init(0))
    prompt = _rows(b=4, t=6)[:, :6].astype(np.int32)
    want = np.asarray(model.generate(params, prompt, 30))
    gen = build_lm_tp_generate(model, mesh, attn="dense")
    got = np.asarray(gen(shard_tp_params(mesh, model, params), prompt, 30))
    np.testing.assert_array_equal(got, want)


MISTRALISH = dict(activation="swiglu", norm="rmsnorm", ffn_bias=False,
                  pos_encoding="rotary", n_kv_heads=2, attn_window=6)


def test_windowed_train_step_learns():
    model = _model(**MISTRALISH)
    mesh = build_mesh_sp(data=8, seq=1)
    step, opt_init = build_lm_train_step(model, mesh, optax.adam(1e-2),
                                         attn="flash")
    params = model.shard_params(mesh, model.init(0))
    opt = opt_init(params)
    batch = shard_lm_batch(mesh, *make_lm_batches(_rows(b=8)))
    first = None
    for _ in range(30):
        params, opt, loss = step(params, opt, *batch)
        first = float(loss) if first is None else first
    assert float(loss) < 0.5 * first


def test_windowed_apply_matches_masked_oracle():
    # windowed teacher-forced forward == full model on inputs where only
    # the window differs: build the same logits via an explicitly masked
    # dense attention using the public attn_window knob vs window=None
    # on a sequence SHORTER than the window (must agree exactly)
    short = _model(**{**MISTRALISH, "attn_window": 32})  # window >= T
    full = _model(**{k: v for k, v in MISTRALISH.items()
                     if k != "attn_window"})
    p = jax.tree.map(jnp.asarray, full.init(0))
    toks = _rows(b=2, t=16)[:, :16].astype(np.int32)
    pos = np.broadcast_to(np.arange(16), toks.shape)
    np.testing.assert_allclose(
        np.asarray(short.apply(p, toks, pos)),
        np.asarray(full.apply(p, toks, pos)), rtol=1e-5, atol=1e-5)


def test_windowed_generate_consistent_and_window_matters():
    model = _model(**MISTRALISH)
    params = jax.tree.map(jnp.asarray, model.init(0))
    prompt = _rows(b=2, t=8)[:, :8].astype(np.int32)
    out = np.asarray(model.generate(params, prompt, 10))
    for j in range(8, 18):
        pos = np.broadcast_to(np.arange(j), (2, j))
        logits = np.asarray(model.apply(params, out[:, :j], pos))[:, -1]
        np.testing.assert_array_equal(out[:, j], logits.argmax(-1))
    # the window binds: the same weights WITHOUT a window disagree
    # somewhere on a longer teacher-forced pass
    full = _model(**{k: v for k, v in MISTRALISH.items()
                     if k != "attn_window"})
    toks = _rows(b=2, t=24)[:, :24].astype(np.int32)
    pos = np.broadcast_to(np.arange(24), toks.shape)
    a = np.asarray(model.apply(params, toks, pos))
    b = np.asarray(full.apply(params, toks, pos))
    assert np.abs(a - b).max() > 1e-3


def test_windowed_speculative_greedy_equals_rollout():
    model = _model(**MISTRALISH)
    draft = _model(**{**MISTRALISH, "d_ff": 16})
    params = jax.tree.map(jnp.asarray, model.init(0))
    dparams = jax.tree.map(jnp.asarray, draft.init(1))
    prompt = _rows(b=1, t=6)[:, :6].astype(np.int32)
    want = np.asarray(model.generate(params, prompt, 10))
    got = np.asarray(model.generate_speculative(
        params, prompt, 10, draft, dparams, spec_k=3))
    np.testing.assert_array_equal(got, want)


def test_window_guards():
    from elephas_tpu.models import build_lm_generate

    model = _model(**MISTRALISH)
    mesh = build_mesh_sp(data=4, seq=2)
    # uniform-window models ride every sp path: seq-sharded generation
    # (horizon-sharded cache masking on global window arithmetic; rollout
    # parity pinned in test_sharded_generate.py) and the ring/ulysses
    # trainers (the ring masks on absolute positions) — neither may raise
    assert callable(build_lm_generate(model, mesh))
    step, opt_init = build_lm_train_step(model, mesh, optax.adam(1e-2),
                                         attn="ring")
    params = model.shard_params(mesh, model.init(0))
    batch = shard_lm_batch(mesh, *make_lm_batches(_rows(b=4)))
    params, opt_state, loss = step(params, opt_init(params), *batch)
    assert np.isfinite(float(loss))
    with pytest.raises(ValueError, match="attn_window"):
        _model(**{**MISTRALISH, "attn_window": 0})


def test_ring_cache_memory_is_o_window():
    model = _model(**{**MISTRALISH, "max_len": 512})
    c = model.init_cache(2, 500)
    assert c["k"].shape[3] <= 2 * MISTRALISH["attn_window"] + 8
    # chunk margin grows the buffer, not the horizon
    c2 = model.init_cache(2, 500, chunk=5)
    assert c2["k"].shape[3] <= MISTRALISH["attn_window"] + 4 + 8


def test_ring_cache_long_rollout_matches_teacher_forced():
    model = _model(**{**MISTRALISH, "max_len": 128})
    params = jax.tree.map(jnp.asarray, model.init(0))
    prompt = _rows(b=2, t=9, vocab=31)[:, :9].astype(np.int32)
    out = np.asarray(model.generate(params, prompt, 40))
    for j in range(9, 49):
        pos = np.broadcast_to(np.arange(j), (2, j))
        lg = np.asarray(model.apply(params, out[:, :j], pos))[:, -1]
        np.testing.assert_array_equal(out[:, j], lg.argmax(-1))


def test_ring_cache_long_prompt_prefill():
    # prompt longer than the ring buffer: only its window-tail is kept
    model = _model(**{**MISTRALISH, "max_len": 128})
    params = jax.tree.map(jnp.asarray, model.init(0))
    prompt = _rows(b=2, t=30, vocab=31)[:, :30].astype(np.int32)
    out = np.asarray(model.generate(params, prompt, 12))
    for j in range(30, 42):
        pos = np.broadcast_to(np.arange(j), (2, j))
        lg = np.asarray(model.apply(params, out[:, :j], pos))[:, -1]
        np.testing.assert_array_equal(out[:, j], lg.argmax(-1))


def test_ring_cache_speculative_equals_rollout():
    model = _model(**{**MISTRALISH, "max_len": 128})
    draft = _model(**{**MISTRALISH, "max_len": 128, "d_ff": 16})
    params = jax.tree.map(jnp.asarray, model.init(0))
    dparams = jax.tree.map(jnp.asarray, draft.init(1))
    prompt = _rows(b=2, t=8, vocab=31)[:, :8].astype(np.int32)
    want = np.asarray(model.generate(params, prompt, 30))
    got = np.asarray(model.generate_speculative(params, prompt, 30, draft,
                                                dparams, spec_k=4))
    np.testing.assert_array_equal(got, want)


def test_ring_chunk_margin_guard():
    model = _model(**{**MISTRALISH, "max_len": 128})
    params = jax.tree.map(jnp.asarray, model.init(0))
    prompt = _rows(b=1, t=4, vocab=31)[:, :4].astype(np.int32)
    cache = model.init_cache(1, 64)  # no chunk margin
    _, cache = model.prefill(params, jnp.asarray(prompt), cache)
    with pytest.raises(ValueError, match="chunk"):
        model.decode_chunk(params, jnp.asarray(prompt), 4, cache)


def test_tp_windowed_long_prompt_prefill():
    # prompt longer than the rolling per-rank cache: exercises the
    # shared write_prompt_cache scatter branch under TP
    from elephas_tpu.models import build_lm_tp_generate, build_mesh_tp, \
        shard_tp_params

    model = _model(**{**MISTRALISH, "max_len": 64})
    mesh = build_mesh_tp(data=4, model=2)
    params = jax.tree.map(jnp.asarray, model.init(0))
    prompt = _rows(b=4, t=20)[:, :20].astype(np.int32)  # > Tc=8
    want = np.asarray(model.generate(params, prompt, 16))
    gen = build_lm_tp_generate(model, mesh, attn="dense")
    got = np.asarray(gen(shard_tp_params(mesh, model, params), prompt, 16))
    np.testing.assert_array_equal(got, want)
