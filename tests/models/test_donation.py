"""Donation regression guard for the LM train step.

``build_lm_train_step`` donates ``(params, opt_state)`` so XLA writes the
updated tree back into the incoming buffers — without it, a second copy of
params + both adam moments materializes every step (3× optimizer-path HBM,
the same trap the serving fast path hit with aliased k/v buffers). Donation
failures are SILENT: jax keeps the program correct and just falls back to
fresh allocations, emitting only a lowering-time warning ("Some donated
buffers were not usable"). This test turns that warning into a hard
failure so an edit that breaks the params→params aliasing (e.g. returning
a re-cast tree with a different dtype, or dropping an output leaf) can't
land quietly.

The warning fires at LOWERING, keyed on aval matching between donated
inputs and outputs — so ``.lower()`` is enough, no execution needed, and
the guard stays cheap across the knob matrix.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elephas_tpu.models import (
    MoETransformerLM,
    TransformerLM,
    adam_compact,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)

DONATION_WARNING = "donated buffer"


def _donation_warnings(fn):
    """Run fn under an always-on warning trap; return donation warnings."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        fn()
    return [w for w in caught if DONATION_WARNING in str(w.message)]


def test_canary_unusable_donation_does_warn():
    """Prove the trap works on this backend: a donated input with no
    aval-matching output MUST produce the warning this guard relies on.
    If jax stops warning (version bump, platform off the donation list),
    this fails first and tells us the guard below is blind."""

    # Scalar out: the donated [4,4] input has no aval-matching output.
    bad_jit = jax.jit(lambda x: jnp.sum(x), donate_argnums=(0,))
    caught = _donation_warnings(
        lambda: bad_jit.lower(jnp.zeros((4, 4), jnp.float32)))
    assert caught, (
        "jax no longer warns on unusable donations — the donation guard "
        "tests below cannot detect regressions on this backend")


@pytest.mark.parametrize("kind", ["dense", "moe"])
@pytest.mark.parametrize(
    "knobs",
    [
        dict(),
        dict(overlap_grads=True, fused_apply=True),
        dict(overlap_grads=True, fused_apply=True, remat="dots"),
    ],
    ids=["baseline", "overlap_fused", "overlap_fused_remat"],
)
def test_train_step_donation_holds(kind, knobs):
    """params + opt_state donation must survive every hot-path knob
    combination: lower the compiled step and fail on any 'donated buffer
    was not usable' warning."""
    mesh = build_mesh_sp(data=2, seq=2)
    if kind == "moe":
        model = MoETransformerLM(vocab=13, d_model=8, n_heads=2, n_layers=2,
                                 d_ff=16, max_len=16, n_experts=2,
                                 aux_weight=0.01)
    else:
        model = TransformerLM(vocab=13, d_model=8, n_heads=2, n_layers=2,
                              d_ff=16, max_len=16)
    step, opt_init = build_lm_train_step(
        model, mesh, adam_compact(1e-2), attn="ring", **knobs)
    params = model.shard_params(mesh, model.init(seed=0))
    opt_state = opt_init(params)
    rows = np.random.default_rng(0).integers(0, 13, size=(8, 17))
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))

    # .lower() is enough — the warning fires at lowering, and skipping
    # backend compilation keeps the 6-case matrix cheap in tier-1.
    caught = _donation_warnings(lambda: step.lower(params, opt_state, *batch))
    assert not caught, (
        "train step no longer donates params/opt_state cleanly: "
        + "; ".join(str(w.message) for w in caught))
