"""LoRA fine-tuning: exact start, frozen base, learned adapters, merge.

Load-bearing properties: (1) B=0 init means the adapted model starts
EXACTLY at the base model; (2) training moves ONLY the adapter factors —
the frozen base is bit-identical after any number of steps; (3) merging
bakes the adapters into plain arrays that reproduce the adapted model
exactly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from elephas_tpu.models import (
    LoRATensor,
    TransformerLM,
    apply_lora,
    build_lora_lm_train_step,
    build_mesh_sp,
    lora_mask,
    lora_trainable_count,
    make_lm_batches,
    merge_lora,
    quantize_lm_params,
    shard_lm_batch,
)


def _model(sp=2, **kw):
    cfg = dict(vocab=13, d_model=16, n_heads=sp, n_layers=2, d_ff=32,
               max_len=8 * sp)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=0):
    return {k: jnp.asarray(v) for k, v in model.init(seed=seed).items()}


def _batch(mesh, sp, rows=8, seed=0):
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 13, size=(rows, 8 * sp + 1))
    return shard_lm_batch(mesh, *make_lm_batches(data))


def test_adapted_model_starts_at_base():
    model = _model()
    base = _params(model, 1)
    lparams = apply_lora(base, rank=4)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 13, size=(2, 8)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(8), (2, 8))
    lb = np.asarray(model.apply(base, tokens, positions, attn="dense"))
    ll = np.asarray(model.apply(lparams, tokens, positions, attn="dense"))
    np.testing.assert_array_equal(lb, ll)
    trainable, total = lora_trainable_count(lparams)
    assert 0 < trainable < 0.2 * total


def test_training_moves_only_adapters_and_learns():
    sp = 2
    mesh = build_mesh_sp(data=2, seq=sp)
    model = _model(sp)
    lparams = apply_lora(_params(model, 1), rank=4)
    step, opt_init = build_lora_lm_train_step(
        model, mesh, optax.adam(5e-2), attn="ring"
    )
    state = opt_init(lparams)
    # masked optimizer: moment buffers exist ONLY for adapter factors —
    # no full-model state for frozen weights
    trainable, total = lora_trainable_count(lparams)
    state_elems = sum(
        np.size(x) for x in jax.tree_util.tree_leaves(state)
    )
    assert state_elems <= 2 * trainable + 16, (state_elems, trainable)
    batch = _batch(mesh, sp)
    w_before = {k: np.asarray(v.w) for k, v in lparams.items()
                if isinstance(v, LoRATensor)}
    frozen_before = {k: np.asarray(v) for k, v in lparams.items()
                     if not isinstance(v, LoRATensor)}
    losses = []
    for _ in range(8):
        lparams, state, loss = step(lparams, state, *batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    for k, w in w_before.items():
        np.testing.assert_array_equal(np.asarray(lparams[k].w), w)
        assert np.abs(np.asarray(lparams[k].b)).max() > 0  # adapters moved
    for k, v in frozen_before.items():
        np.testing.assert_array_equal(np.asarray(lparams[k]), v, err_msg=k)


def test_merge_reproduces_adapted_model_and_quantizes():
    sp = 2
    mesh = build_mesh_sp(data=2, seq=sp)
    model = _model(sp)
    lparams = apply_lora(_params(model, 2), rank=4)
    step, opt_init = build_lora_lm_train_step(
        model, mesh, optax.adam(5e-2), attn="ring"
    )
    state = opt_init(lparams)
    batch = _batch(mesh, sp, seed=3)
    for _ in range(3):
        lparams, state, _ = step(lparams, state, *batch)

    merged = merge_lora(lparams)
    assert not any(isinstance(v, LoRATensor) for v in merged.values())
    rng = np.random.default_rng(4)
    tokens = jnp.asarray(rng.integers(0, 13, size=(2, 10)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(10), (2, 10))
    la = np.asarray(model.apply(lparams, tokens, positions, attn="dense"))
    lm = np.asarray(model.apply(merged, tokens, positions, attn="dense"))
    np.testing.assert_allclose(la, lm, atol=1e-5, rtol=1e-5)
    # deployment composition: merged weights quantize like any others
    q = quantize_lm_params(merged)
    lq = np.asarray(model.apply(q, tokens, positions, attn="dense"))
    assert np.isfinite(lq).all()


def test_lora_mask_protects_base_from_weight_decay():
    model = _model()
    lparams = apply_lora(_params(model, 5), rank=2)
    mask = lora_mask(lparams)
    opt = optax.masked(optax.adamw(1e-2, weight_decay=0.5), mask)
    state = opt.init(lparams)
    zero_grads = jax.tree_util.tree_map(jnp.zeros_like, lparams)
    updates, _ = opt.update(zero_grads, state, lparams)
    flat_params = {k: v for k, v in lparams.items()}
    # frozen leaves (incl. each adapter's base) get EXACTLY zero update
    for k, v in flat_params.items():
        u = updates[k]
        if isinstance(v, LoRATensor):
            np.testing.assert_array_equal(np.asarray(u.w), 0)
        else:
            np.testing.assert_array_equal(np.asarray(u), 0)


def test_save_load_adapters_roundtrip(tmp_path):
    """Adapters persist alone (tiny file) and reattach to a fresh base,
    reproducing the adapted model exactly."""
    from elephas_tpu.models import load_lora, save_lora

    sp = 2
    mesh = build_mesh_sp(data=2, seq=sp)
    model = _model(sp)
    base_np = model.init(seed=9)
    lparams = apply_lora({k: jnp.asarray(v) for k, v in base_np.items()},
                         rank=4)
    step, opt_init = build_lora_lm_train_step(
        model, mesh, optax.adam(5e-2), attn="ring"
    )
    state = opt_init(lparams)
    batch = _batch(mesh, sp, seed=11)
    for _ in range(3):
        lparams, state, _ = step(lparams, state, *batch)

    path = str(tmp_path / "adapters.npz")
    save_lora(path, lparams)
    # tiny artifact: orders of magnitude under the full model
    import os

    full_bytes = sum(np.asarray(v).nbytes for v in base_np.values())
    assert os.path.getsize(path) < 0.35 * full_bytes
    # attach onto a FRESH copy of the base
    restored = load_lora(path, {k: jnp.asarray(v) for k, v in base_np.items()})
    rng = np.random.default_rng(12)
    tokens = jnp.asarray(rng.integers(0, 13, size=(2, 8)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(8), (2, 8))
    want = np.asarray(model.apply(lparams, tokens, positions, attn="dense"))
    got = np.asarray(model.apply(restored, tokens, positions, attn="dense"))
    np.testing.assert_array_equal(got, want)

    with pytest.raises(ValueError, match="no LoRA adapters"):
        save_lora(str(tmp_path / "x.npz"), base_np)
    bad_base = {k: v for k, v in base_np.items() if k != "wq"}
    with pytest.raises(ValueError, match="no base param"):
        load_lora(path, bad_base)


def test_full_state_checkpoint_of_adapted_params(tmp_path):
    """The generic pytree checkpoint round-trips LoRATensor nodes (full
    training state form, complementing the adapter-only save_lora)."""
    from elephas_tpu.utils import load_pytree, save_pytree

    model = _model()
    lparams = apply_lora(_params(model, 13), rank=2)
    path = str(tmp_path / "state")
    save_pytree(path, lparams)
    back = load_pytree(path)
    assert isinstance(back["wq"], LoRATensor)
    np.testing.assert_array_equal(np.asarray(back["wq"].w),
                                  np.asarray(lparams["wq"].w))
    np.testing.assert_array_equal(np.asarray(back["wq"].a),
                                  np.asarray(lparams["wq"].a))
    assert back["wq"].alpha == lparams["wq"].alpha


def test_generate_works_through_adapters():
    model = _model()
    lparams = apply_lora(_params(model, 6), rank=2)
    prompt = np.array([[1, 2, 3]], np.int32)
    base_out = np.asarray(model.generate(_params(model, 6), prompt, n_new=6))
    lora_out = np.asarray(model.generate(lparams, prompt, n_new=6))
    np.testing.assert_array_equal(base_out, lora_out)  # B=0 → identical


def test_validation():
    model = _model()
    params = _params(model)
    with pytest.raises(ValueError, match="not in params"):
        apply_lora(params, keys=("nope",))
    with pytest.raises(ValueError, match="non-matrix"):
        apply_lora(params, keys=("lnf_s",))
    # idempotent for a matching config; mismatched re-adaptation raises
    l1 = apply_lora(params, rank=2)
    l2 = apply_lora(l1, rank=2)
    assert l2["wq"] is l1["wq"]
    with pytest.raises(ValueError, match="already adapted"):
        apply_lora(l1, rank=8)
    from elephas_tpu.models.transformer import MoETransformerLM

    moe = MoETransformerLM(vocab=13, d_model=16, n_heads=2, n_layers=1,
                           d_ff=32, max_len=16, n_experts=2, k=1)
    mesh = build_mesh_sp(data=2, seq=2)
    with pytest.raises(NotImplementedError, match="dense"):
        build_lora_lm_train_step(moe, mesh, optax.adam(1e-2))