"""Vocab-chunked cross-entropy vs the dense loss head.

``chunked_summed_xent`` must equal ``_summed_xent(h @ w, targets)`` — value
AND gradients — for every block size, including non-divisors of V (padded
tail block), and must plug into ``build_lm_train_step`` /
``build_lora_lm_train_step`` without changing trajectories.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from elephas_tpu.models import chunked_summed_xent
from elephas_tpu.models.transformer import (
    TransformerLM,
    _summed_xent,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)


def _case(b=2, t=8, d=16, v=37, seed=0):
    rng = np.random.default_rng(seed)
    h = rng.normal(size=(b, t, d)).astype(np.float32)
    w = rng.normal(size=(d, v)).astype(np.float32)
    tg = rng.integers(0, v, size=(b, t)).astype(np.int32)
    return jnp.asarray(h), jnp.asarray(w), jnp.asarray(tg)


@pytest.mark.parametrize("v,block", [(37, 8), (37, 37), (64, 16), (64, 64),
                                     (64, 48), (8, 128)])
def test_value_matches_dense(v, block):
    h, w, tg = _case(v=v)
    want = float(_summed_xent(h @ w, tg))
    got = float(chunked_summed_xent(h, w, tg, block))
    np.testing.assert_allclose(got, want, rtol=1e-6)


@pytest.mark.parametrize("block", [8, 16, 37])
def test_gradients_match_dense(block):
    h, w, tg = _case(v=37)

    def dense(h, w):
        return _summed_xent(h @ w, tg)

    def chunked(h, w):
        return chunked_summed_xent(h, w, tg, block)

    # the exactness contract is stated at f32 matmul precision (the
    # chunked path pins f32 accumulation; pin the dense reference too so
    # the comparison is well-defined on backends whose default is bf16)
    with jax.default_matmul_precision("float32"):
        dh_want, dw_want = jax.grad(dense, argnums=(0, 1))(h, w)
        dh_got, dw_got = jax.grad(chunked, argnums=(0, 1))(h, w)
    # CPU (the CI mesh) is exact to float roundoff; TPU backends keep a
    # ~1e-3 residual from transcendental approximations and pass-count
    # differences between the two backward formulations — measured, not
    # a correctness gap (the VALUE is exact on both)
    rtol, atol = ((2e-5, 2e-6) if jax.default_backend() == "cpu"
                  else (3e-3, 3e-4))
    np.testing.assert_allclose(np.asarray(dh_got), np.asarray(dh_want),
                               rtol=rtol, atol=atol)
    np.testing.assert_allclose(np.asarray(dw_got), np.asarray(dw_want),
                               rtol=rtol, atol=atol)


def test_bf16_hidden_states():
    """bf16 activations (the TPU training dtype): same promotion as the
    dense head (logits accumulate f32), gradient dtype matches h."""
    h, w, tg = _case(v=64)
    hb = h.astype(jnp.bfloat16)
    want = float(_summed_xent((hb @ w).astype(jnp.float32), tg))
    got = float(chunked_summed_xent(hb, w, tg, 16))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    dh = jax.grad(lambda x: chunked_summed_xent(x, w, tg, 16))(hb)
    assert dh.dtype == jnp.bfloat16


def test_jit_under_scan():
    h, w, tg = _case(v=64)
    f = jax.jit(lambda h, w: chunked_summed_xent(h, w, tg, 16))
    np.testing.assert_allclose(float(f(h, w)),
                               float(_summed_xent(h @ w, tg)), rtol=1e-6)


@pytest.mark.parametrize("tie", [True, False])
def test_train_step_trajectory_unchanged(tie):
    """vocab_block must not change build_lm_train_step's trajectory."""
    model = TransformerLM(vocab=67, d_model=16, n_heads=2, n_layers=2,
                          d_ff=32, max_len=16, tie_embeddings=tie)
    rows = np.random.default_rng(3).integers(0, 67, size=(4, 17))
    mesh = build_mesh_sp(data=2, seq=1)

    def run(vocab_block):
        step, opt_init = build_lm_train_step(
            model, mesh, optax.adam(1e-2), attn="dense",
            vocab_block=vocab_block)
        params = model.shard_params(mesh, model.init(seed=0))
        state = opt_init(params)
        batch = shard_lm_batch(mesh, *make_lm_batches(rows))
        for _ in range(3):
            params, state, loss = step(params, state, *batch)
        return {k: np.asarray(v) for k, v in params.items()}, float(loss)

    p_dense, l_dense = run(None)
    p_chunk, l_chunk = run(16)
    np.testing.assert_allclose(l_chunk, l_dense, rtol=1e-5)
    for k in p_dense:
        np.testing.assert_allclose(p_chunk[k], p_dense[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def test_lora_vocab_block_trajectory_unchanged():
    from elephas_tpu.models import apply_lora, build_lora_lm_train_step

    model = TransformerLM(vocab=53, d_model=16, n_heads=2, n_layers=1,
                          d_ff=32, max_len=16, tie_embeddings=True)
    rows = np.random.default_rng(5).integers(0, 53, size=(4, 17))
    mesh = build_mesh_sp(data=2, seq=1)
    tokens, positions, targets = make_lm_batches(rows)

    def run(vocab_block):
        step, opt_init = build_lora_lm_train_step(
            model, mesh, optax.adam(1e-2), attn="dense",
            vocab_block=vocab_block)
        params = apply_lora(
            {k: jnp.asarray(v) for k, v in model.init(seed=0).items()},
            rank=2)
        state = opt_init(params)
        for _ in range(2):
            params, state, loss = step(
                params, state, jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(targets))
        leaves = jax.tree_util.tree_leaves(params)
        return [np.asarray(l) for l in leaves], float(loss)

    p_dense, l_dense = run(None)
    p_chunk, l_chunk = run(16)
    np.testing.assert_allclose(l_chunk, l_dense, rtol=1e-5)
    for a, b in zip(p_chunk, p_dense):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
