"""MoE transformer (dp×sp×ep in one program) vs the dense-emulated oracle.

Experts shard over the same "seq" axis the sequence rides; the dense path
emulates the per-shard dispatch groups (ep_groups = seq size), so sharded
and oracle runs compute identical routing, outputs, and aux losses.
"""

import numpy as np
import optax
import pytest

import jax

from elephas_tpu.compat import shard_map as compat_shard_map
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from elephas_tpu.models.transformer import (
    MoETransformerLM,
    build_lm_train_step,
    build_mesh_sp,
    make_lm_batches,
    shard_lm_batch,
)


def _model(sp=4):
    return MoETransformerLM(vocab=13, d_model=16, n_heads=4, n_layers=2,
                            d_ff=32, max_len=32, n_experts=8, k=2,
                            capacity_factor=2.0, aux_weight=1e-2,
                            ep_groups=sp)


def _data(b=4, t=32, vocab=13, seed=0):
    rng = np.random.default_rng(seed)
    start = rng.integers(0, vocab, size=(b, 1))
    rows = (start + np.arange(t + 1)) % vocab
    return make_lm_batches(rows)


@pytest.mark.parametrize("dp,sp", [(2, 4), (1, 8)])
def test_forward_matches_dense_oracle(dp, sp):
    model = _model(sp=sp)
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    tokens, positions, targets = _data()

    # oracle: per data group (contiguous batch rows), dense attention +
    # group-emulated MoE dispatch
    wants, auxes = [], []
    for tb, pb in zip(np.split(tokens, dp), np.split(positions, dp)):
        logits, aux = model.apply_with_aux(params, tb, pb, attn="dense")
        wants.append(np.asarray(logits))
        auxes.append(float(aux))
    want = np.concatenate(wants, axis=0)

    mesh = build_mesh_sp(data=dp, seq=sp)

    def impl(p, tk, ps):
        logits, aux = model.apply_with_aux(p, tk, ps, attn="ring")
        return logits, aux[None]

    fwd = jax.jit(
        compat_shard_map(
            impl, mesh=mesh,
            in_specs=(model.specs(), P("data", "seq"), P("data", "seq")),
            out_specs=(P("data", "seq"), P("data")),
            check_vma=False,
        )
    )
    sharding = NamedSharding(mesh, P("data", "seq"))
    got, aux_got = fwd(model.shard_params(mesh, model.init(seed=1)),
                       jax.device_put(tokens, sharding),
                       jax.device_put(positions, sharding))
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(aux_got), auxes, atol=1e-4,
                               rtol=1e-4)


def test_train_step_matches_dense_oracle():
    dp, sp = 2, 4
    model = _model(sp=sp)
    optimizer = optax.adam(1e-2)
    tokens, positions, targets = _data()
    params0 = model.init(seed=2)
    ntok = float(tokens.size)

    def oracle_loss(p):
        total = 0.0
        for tb, pb, gb in zip(np.split(tokens, dp), np.split(positions, dp),
                              np.split(targets, dp)):
            logits, aux = model.apply_with_aux(p, tb, pb, attn="dense")
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, jnp.asarray(gb)[..., None],
                                     axis=-1)[..., 0]
            total = total - jnp.sum(ll) / ntok + (
                model.aux_weight / dp
            ) * aux
        return total

    o_params = {k: jnp.asarray(v) for k, v in params0.items()}
    o_state = optimizer.init(o_params)
    o_losses = []
    for _ in range(3):
        loss, grads = jax.value_and_grad(oracle_loss)(o_params)
        updates, o_state = optimizer.update(grads, o_state, o_params)
        o_params = jax.tree_util.tree_map(jnp.add, o_params, updates)
        o_losses.append(float(loss))

    mesh = build_mesh_sp(data=dp, seq=sp)
    step, opt_init = build_lm_train_step(model, mesh, optimizer, attn="ring")
    params = model.shard_params(mesh, params0)
    state = opt_init(params)
    td, pd, gd = shard_lm_batch(mesh, tokens, positions, targets)
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, td, pd, gd)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=5e-4, atol=5e-5)
    for k, v in o_params.items():
        np.testing.assert_allclose(
            np.asarray(params[k]), np.asarray(v), rtol=2e-3, atol=2e-4,
            err_msg=k,
        )


def test_learns_and_validates():
    model = _model(sp=4)
    mesh = build_mesh_sp(data=2, seq=4)
    step, opt_init = build_lm_train_step(model, mesh, optax.adam(3e-3),
                                         attn="ring")
    params = model.shard_params(mesh, model.init(seed=0))
    state = opt_init(params)
    tokens, positions, targets = _data(b=8)
    td, pd, gd = shard_lm_batch(mesh, tokens, positions, targets)
    first = last = None
    for i in range(25):
        params, state, loss = step(params, state, td, pd, gd)
        if i == 0:
            first = float(loss)
        last = float(loss)
    assert last < first * 0.6, (first, last)

    # expert count must divide the seq axis
    bad = MoETransformerLM(vocab=13, d_model=16, n_heads=4, n_layers=1,
                           d_ff=32, max_len=32, n_experts=6)
    with pytest.raises(ValueError, match="n_experts"):
        build_lm_train_step(bad, build_mesh_sp(data=2, seq=4),
                            optax.sgd(0.1), attn="ring")


@pytest.mark.parametrize("dispatch", ["slots", "gmm", "ragged"])
def test_single_device_dispatch_matches_onehot(dispatch):
    """Every single-device executor must produce the onehot oracle's
    trajectory (identical routing; float-tolerance sums)."""
    import optax as _optax

    kw = dict(vocab=13, d_model=16, n_heads=4, n_layers=2, d_ff=32,
              max_len=32, n_experts=8, k=2, capacity_factor=1.25,
              ep_groups=1)
    tokens, positions, targets = _data()
    mesh = build_mesh_sp(data=1, seq=1)
    losses = {}
    for d in ("onehot", dispatch):
        model = MoETransformerLM(moe_dispatch=d, **kw)
        step, opt_init = build_lm_train_step(model, mesh,
                                             _optax.adam(1e-2),
                                             attn="flash")
        params = model.shard_params(mesh, model.init(seed=3))
        state = opt_init(params)
        td, pd, gd = shard_lm_batch(mesh, tokens, positions, targets)
        ls = []
        for _ in range(3):
            params, state, loss = step(params, state, td, pd, gd)
            ls.append(float(loss))
        losses[d] = ls
    np.testing.assert_allclose(losses[dispatch], losses["onehot"],
                               rtol=5e-4, atol=5e-5)


def test_bf16_param_storage_tracks_f32_trajectory():
    """param_dtype='bfloat16' stores the expert stacks compactly; the
    trajectory must track f32 storage closely (one bf16 rounding per
    update) and dtypes must stay stable through the step."""
    import optax as _optax

    kw = dict(vocab=13, d_model=16, n_heads=4, n_layers=2, d_ff=32,
              max_len=32, n_experts=8, k=2, capacity_factor=1.25,
              ep_groups=1, activation="swiglu", ffn_bias=False)
    tokens, positions, targets = _data()
    mesh = build_mesh_sp(data=1, seq=1)
    losses = {}
    for pd in ("float32", "bfloat16"):
        model = MoETransformerLM(param_dtype=pd, **kw)
        step, opt_init = build_lm_train_step(model, mesh,
                                             _optax.adam(1e-2),
                                             attn="flash")
        params = model.shard_params(mesh, model.init(seed=3))
        if pd == "bfloat16":
            assert params["w1"].dtype == jnp.bfloat16
            assert params["wg"].dtype == jnp.float32  # router stays f32
        state = opt_init(params)
        td, pd_, gd = shard_lm_batch(mesh, tokens, positions, targets)
        ls = []
        for _ in range(4):
            params, state, loss = step(params, state, td, pd_, gd)
            ls.append(float(loss))
        if pd == "bfloat16":
            assert params["w1"].dtype == jnp.bfloat16  # dtype-stable
        losses[pd] = ls
    # At toy scale (d16) with lr 1e-2 the per-update bf16 rounding is a
    # visible fraction of the update itself, so the contract here is
    # "tracks and learns", not bit-parity (at the bench scale — d1024,
    # lr 1e-3 — step-2 losses match f32 to 5 decimals; PERFORMANCE.md).
    np.testing.assert_allclose(losses["bfloat16"], losses["float32"],
                               rtol=1e-1)
    assert losses["bfloat16"][-1] < losses["bfloat16"][0]
