"""Pipelined stack vs the single-device dense oracle.

GPipe microbatching + ppermute hops are a pure re-scheduling of the same
math: forward outputs and training trajectories must match the unpipelined
reference bit-closely on the 8 virtual CPU devices (conftest).
"""

import numpy as np
import optax
import pytest

import jax

from elephas_tpu.compat import shard_map as compat_shard_map
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from elephas_tpu.parallel.pipeline import (
    PipelineDenseStack,
    build_mesh_pp,
    build_pp_train_step,
)


from tests._helpers import softmax_xent as _softmax_xent  # noqa: E402


@pytest.mark.parametrize("dp,pp,n_micro", [(1, 8, 4), (2, 4, 4), (4, 2, 2)])
def test_forward_matches_dense(dp, pp, n_micro):
    mesh = build_mesh_pp(data=dp, pipe=pp)
    model = PipelineDenseStack(
        d_in=12, hidden=16, d_out=6, n_stages=pp, layers_per_stage=2
    )
    params = model.init(seed=3)
    x = np.random.default_rng(0).normal(size=(16, 12)).astype(np.float32)

    want = np.asarray(model.apply_reference(params, x))

    sharded = model.shard_params(mesh, params)
    fwd = jax.jit(
        compat_shard_map(
            lambda p, xb: model.apply(p, xb, n_micro),
            mesh=mesh, in_specs=(model.specs(), P("data")),
            out_specs=P("data"), check_vma=False,
        )
    )
    xd = jax.device_put(x, NamedSharding(mesh, P("data")))
    got = np.asarray(fwd(sharded, xd))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("dp,pp,opt_name", [(2, 4, "adam"), (4, 2, "sgd")])
def test_train_step_matches_dense(dp, pp, opt_name):
    mesh = build_mesh_pp(data=dp, pipe=pp)
    model = PipelineDenseStack(
        d_in=10, hidden=16, d_out=4, n_stages=pp, layers_per_stage=1
    )
    optimizer = optax.adam(1e-2) if opt_name == "adam" else optax.sgd(0.1)
    params = model.init(seed=1)
    rng = np.random.default_rng(7)
    x = rng.normal(size=(32, 10)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=32)]

    def oracle_loss(p):
        return jnp.mean(_softmax_xent(y, model.apply_reference(p, x)))

    o_state = optimizer.init(params)
    o_params = params
    o_losses = []
    for _ in range(3):
        loss, grads = jax.value_and_grad(oracle_loss)(o_params)
        updates, o_state = optimizer.update(grads, o_state, o_params)
        o_params = jax.tree_util.tree_map(jnp.add, o_params, updates)
        o_losses.append(float(loss))

    step, opt_init = build_pp_train_step(
        model, mesh, optimizer, _softmax_xent, n_micro=4
    )
    sharded = model.shard_params(mesh, params)
    state = opt_init(sharded)
    xd = jax.device_put(x, NamedSharding(mesh, P("data")))
    yd = jax.device_put(y, NamedSharding(mesh, P("data")))
    losses = []
    for _ in range(3):
        sharded, state, loss = step(sharded, state, xd, yd)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=1e-4, atol=1e-5)
    got = model.gather_params(sharded)
    for k, v in o_params.items():
        np.testing.assert_allclose(
            got[k], np.asarray(v), rtol=2e-4, atol=2e-5, err_msg=k
        )


def test_validation():
    with pytest.raises(ValueError):
        PipelineDenseStack(4, 8, 2, n_stages=0)
    mesh = build_mesh_pp(data=2, pipe=4)
    model = PipelineDenseStack(4, 8, 2, n_stages=2)
    with pytest.raises(ValueError):
        build_pp_train_step(model, mesh, optax.sgd(0.1), _softmax_xent, 2)
