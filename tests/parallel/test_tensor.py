"""Tensor-parallel layers/trainer vs the single-device dense oracle.

The 8 virtual CPU devices (conftest) are folded into 2-D meshes; every
configuration must reproduce the math of the unsharded MLP bit-closely:
column/row sharding + psum is a pure re-layout of the same contractions.
"""

import numpy as np
import optax
import pytest

import jax

from elephas_tpu.compat import shard_map as compat_shard_map
import jax.numpy as jnp

from elephas_tpu.parallel.tensor import (
    TensorParallelMLP,
    build_mesh2d,
    build_tp_train_step,
    opt_state_specs,
)


from tests._helpers import softmax_xent as _softmax_xent  # noqa: E402


@pytest.mark.parametrize("dp,tp", [(1, 8), (2, 4), (4, 2), (8, 1)])
def test_forward_matches_dense(dp, tp):
    mesh = build_mesh2d(data=dp, model=tp)
    model = TensorParallelMLP([12, 32, 16, 24, 6], tp=tp)
    params = model.init(seed=3)
    x = np.random.default_rng(0).normal(size=(16, 12)).astype(np.float32)

    want = np.asarray(model.apply_reference(params, x))

    sharded = model.shard_params(mesh, params)
    from jax.sharding import NamedSharding, PartitionSpec as P

    fwd = jax.jit(
        compat_shard_map(
            model.apply, mesh=mesh,
            in_specs=(model.specs(), P("data")), out_specs=P("data"),
            check_vma=False,
        )
    )
    xd = jax.device_put(x, NamedSharding(mesh, P("data")))
    got = np.asarray(fwd(sharded, xd))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("dp,tp,opt_name", [(2, 4, "adam"), (4, 2, "sgd")])
def test_train_step_matches_dense(dp, tp, opt_name):
    mesh = build_mesh2d(data=dp, model=tp)
    model = TensorParallelMLP([10, 16, 8, 16, 4], tp=tp)
    optimizer = optax.adam(1e-2) if opt_name == "adam" else optax.sgd(0.1)
    params = model.init(seed=1)
    rng = np.random.default_rng(7)
    x = rng.normal(size=(32, 10)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=32)]

    # dense oracle: plain jax on full params
    def oracle_loss(p):
        return jnp.mean(_softmax_xent(y, model.apply_reference(p, x)))

    o_state = optimizer.init(params)
    o_params = params
    o_losses = []
    for _ in range(3):
        loss, grads = jax.value_and_grad(oracle_loss)(o_params)
        updates, o_state = optimizer.update(grads, o_state, o_params)
        o_params = jax.tree_util.tree_map(jnp.add, o_params, updates)
        o_losses.append(float(loss))

    # tp trainer
    step, opt_init = build_tp_train_step(model, mesh, optimizer, _softmax_xent)
    sharded = model.shard_params(mesh, params)
    state = opt_init(sharded)
    from jax.sharding import NamedSharding, PartitionSpec as P

    xd = jax.device_put(x, NamedSharding(mesh, P("data")))
    yd = jax.device_put(y, NamedSharding(mesh, P("data")))
    losses = []
    for _ in range(3):
        sharded, state, loss = step(sharded, state, xd, yd)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=1e-4, atol=1e-5)
    got = model.gather_params(sharded)
    for k, v in model.gather_params({k: v for k, v in o_params.items()}).items():
        np.testing.assert_allclose(got[k], v, rtol=2e-4, atol=2e-5)


def test_opt_state_specs_structure():
    from jax.sharding import PartitionSpec as P

    model = TensorParallelMLP([8, 16, 4], tp=2)
    specs = model.specs()
    params = model.init()
    tree = opt_state_specs(optax.adam(1e-3), params, specs)
    leaves = jax.tree_util.tree_leaves(
        tree, is_leaf=lambda s: isinstance(s, P)
    )
    # adam: count (replicated) + mu/nu mirroring the 4 params each
    assert sum(1 for s in leaves if s == P()) >= 1
    assert sum(1 for s in leaves if s == P(None, "model")) == 2  # w0 in mu,nu
    assert sum(1 for s in leaves if s == P("model", None)) == 2  # w1 in mu,nu


def test_dims_validation():
    with pytest.raises(ValueError):
        TensorParallelMLP([8, 16], tp=2)  # single layer (even dims len)
    with pytest.raises(ValueError):
        TensorParallelMLP([8, 15, 4], tp=2)  # hidden not divisible
