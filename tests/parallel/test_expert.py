"""Expert-parallel MoE vs the single-device routed oracle.

The all_to_all dispatch is a pure re-layout of the oracle's per-shard
routing: forward outputs, aux losses, and training trajectories must match
on the 8 virtual CPU devices (conftest).
"""

import numpy as np
import optax
import pytest

import jax

from elephas_tpu.compat import shard_map as compat_shard_map
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from elephas_tpu.parallel.expert import (
    MoEFeedForward,
    build_ep_train_step,
    build_mesh_ep,
)


def _mse(y, y_pred):
    return jnp.sum((y - y_pred) ** 2, axis=-1)


def _tokens(n=64, d=8, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, d)).astype(np.float32)


@pytest.mark.parametrize("dp,ep,k", [(1, 8, 1), (1, 8, 2), (2, 4, 2)])
def test_forward_matches_oracle(dp, ep, k):
    mesh = build_mesh_ep(data=dp, expert=ep)
    model = MoEFeedForward(d_model=8, d_ff=16, n_experts=8, k=k,
                           capacity_factor=1.5)
    params = model.init(seed=1)
    x = _tokens(n=64, d=8)

    # oracle: per data group, per-source-shard dispatch
    outs, auxes = [], []
    for blk in np.split(x, dp, axis=0):
        y, aux = model.apply_reference(params, jnp.asarray(blk), ep=ep)
        outs.append(np.asarray(y))
        auxes.append(float(aux))
    want = np.concatenate(outs, axis=0)

    sharded = model.shard_params(mesh, params)
    token_spec = P(("data", "expert"))

    def impl(p, xb):
        yb, aux = model.apply(p, xb)
        return yb, aux[None]  # aux replicated within each expert group

    fwd = jax.jit(
        compat_shard_map(
            impl, mesh=mesh,
            in_specs=(model.specs(), token_spec),
            out_specs=(token_spec, P("data")),
            check_vma=False,
        )
    )
    xd = jax.device_put(x, NamedSharding(mesh, token_spec))
    got, aux_got = fwd(sharded, xd)
    got = np.asarray(got)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)
    np.testing.assert_allclose(
        np.asarray(aux_got), auxes, rtol=3e-5, atol=3e-5
    )


def test_capacity_drops_tokens():
    """A tiny capacity factor must drop tokens (combine weight 0 ⇒ the MoE
    contribution vanishes) rather than corrupt neighbors."""
    model = MoEFeedForward(d_model=4, d_ff=8, n_experts=2, k=1,
                           capacity_factor=0.1)
    params = model.init(seed=0)
    x = jnp.asarray(_tokens(n=32, d=4, seed=3))
    y, _ = model.apply_reference(params, x)
    # capacity = ceil(0.1 * 1 * 32 / 2) = 2 slots/expert ⇒ ≤4 nonzero rows
    nonzero = np.sum(np.any(np.abs(np.asarray(y)) > 0, axis=-1))
    assert nonzero <= 4


@pytest.mark.parametrize("dp,ep,routing", [(2, 4, "token_choice"),
                                           (2, 4, "expert_choice")])
def test_train_step_matches_oracle(dp, ep, routing):
    mesh = build_mesh_ep(data=dp, expert=ep)
    model = MoEFeedForward(d_model=8, d_ff=16, n_experts=8, k=2,
                           capacity_factor=2.0, routing=routing)
    optimizer = optax.adam(1e-2)
    aux_w = 1e-2
    params = model.init(seed=2)
    rng = np.random.default_rng(5)
    x = _tokens(n=64, d=8, seed=5)
    y = rng.normal(size=(64, 8)).astype(np.float32)

    def oracle_loss(p):
        total, aux_sum = 0.0, 0.0
        for xb, yb in zip(np.split(x, dp), np.split(y, dp)):
            h, aux = model.apply_reference(p, jnp.asarray(xb), ep=ep)
            total = total + jnp.sum(_mse(jnp.asarray(yb), jnp.asarray(xb) + h))
            aux_sum = aux_sum + aux
        return total / x.shape[0] + aux_w * aux_sum / dp

    o_state = optimizer.init(params)
    o_params = {k: jnp.asarray(v) for k, v in params.items()}
    for _ in range(3):
        grads = jax.grad(oracle_loss)(o_params)
        updates, o_state = optimizer.update(grads, o_state, o_params)
        o_params = jax.tree_util.tree_map(jnp.add, o_params, updates)

    step, opt_init = build_ep_train_step(
        model, mesh, optimizer, _mse, aux_weight=aux_w
    )
    sharded = model.shard_params(mesh, params)
    state = opt_init(sharded)
    token_spec = P(("data", "expert"))
    xd = jax.device_put(x, NamedSharding(mesh, token_spec))
    yd = jax.device_put(y, NamedSharding(mesh, token_spec))
    for _ in range(3):
        sharded, state, loss = step(sharded, state, xd, yd)

    got = model.gather_params(sharded)
    for k, v in o_params.items():
        np.testing.assert_allclose(
            got[k], np.asarray(v), rtol=5e-4, atol=5e-5, err_msg=k
        )


@pytest.mark.parametrize("dp,ep", [(1, 8), (2, 4)])
def test_expert_choice_forward_matches_oracle(dp, ep):
    mesh = build_mesh_ep(data=dp, expert=ep)
    model = MoEFeedForward(d_model=8, d_ff=16, n_experts=8, k=2,
                           capacity_factor=1.0, routing="expert_choice")
    params = model.init(seed=1)
    x = _tokens(n=64, d=8)

    outs = []
    for blk in np.split(x, dp, axis=0):
        y, aux = model.apply_reference(params, jnp.asarray(blk), ep=ep)
        assert float(aux) == 0.0  # balanced by construction, no aux
        outs.append(np.asarray(y))
    want = np.concatenate(outs, axis=0)

    sharded = model.shard_params(mesh, params)
    token_spec = P(("data", "expert"))
    fwd = jax.jit(
        compat_shard_map(
            lambda p, xb: model.apply(p, xb)[0], mesh=mesh,
            in_specs=(model.specs(), token_spec), out_specs=token_spec,
            check_vma=False,
        )
    )
    xd = jax.device_put(x, NamedSharding(mesh, token_spec))
    got = np.asarray(fwd(sharded, xd))
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)


def test_expert_choice_trains():
    """Dropless routing must train through build_ep_train_step unchanged."""
    mesh = build_mesh_ep(data=2, expert=4)
    model = MoEFeedForward(d_model=8, d_ff=16, n_experts=8, k=2,
                           routing="expert_choice")
    step, opt_init = build_ep_train_step(model, mesh, optax.adam(1e-2), _mse)
    params = model.shard_params(mesh, model.init(seed=2))
    state = opt_init(params)
    rng = np.random.default_rng(5)
    x = _tokens(n=64, d=8, seed=5)
    y = rng.normal(size=(64, 8)).astype(np.float32)
    token_spec = P(("data", "expert"))
    xd = jax.device_put(x, NamedSharding(mesh, token_spec))
    yd = jax.device_put(y, NamedSharding(mesh, token_spec))
    losses = []
    for _ in range(8):
        params, state, loss = step(params, state, xd, yd)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


@pytest.mark.parametrize(
    "activation,bias,cf,k,ep",
    [
        ("relu", True, 1.5, 2, 1),
        ("gelu", True, 1.25, 1, 2),
        ("swiglu", False, 2.0, 2, 1),   # Mixtral expert shape
        ("swiglu", False, 0.25, 2, 1),  # capacity binds: drops must match
        ("relu", False, 1.0, 3, 4),     # multi-group per-shard quotas
    ],
)
def test_grouped_matches_onehot_oracle(activation, bias, cf, k, ep):
    """The sort + ragged-grouped-matmul executor must reproduce the one-hot
    dispatch oracle exactly (same routing, keeps, combine weights, aux) —
    only float summation order may differ."""
    model = MoEFeedForward(d_model=8, d_ff=16, n_experts=8, k=k,
                           capacity_factor=cf, activation=activation,
                           bias=bias)
    params = model.init(seed=4)
    x = jnp.asarray(_tokens(n=64, d=8, seed=7))
    want, aux_want = model.apply_reference(params, x, ep=ep)
    got, aux_got = model.apply_grouped(params, x, ep=ep)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux_got), float(aux_want), rtol=1e-5)


def test_grouped_gradients_match_onehot():
    """jax.grad through the grouped executor equals the one-hot oracle's
    gradients (routing is piecewise-constant; both paths stop gradients at
    the same argmax decisions)."""
    model = MoEFeedForward(d_model=8, d_ff=16, n_experts=4, k=2,
                           capacity_factor=1.5)
    params = {k: jnp.asarray(v) for k, v in model.init(seed=9).items()}
    x = jnp.asarray(_tokens(n=32, d=8, seed=11))
    y = jnp.asarray(_tokens(n=32, d=8, seed=12))

    def loss(p, fn):
        h, aux = fn(p, x)
        return jnp.mean(_mse(y, x + h)) + 1e-2 * aux

    g_ref = jax.grad(lambda p: loss(p, model.apply_reference))(params)
    g_grp = jax.grad(lambda p: loss(p, model.apply_grouped))(params)
    for k_ in params:
        np.testing.assert_allclose(
            np.asarray(g_grp[k_]), np.asarray(g_ref[k_]),
            rtol=2e-5, atol=2e-6, err_msg=k_)


@pytest.mark.parametrize(
    "activation,bias,cf,k,ep",
    [
        ("relu", True, 1.5, 2, 1),
        ("swiglu", False, 2.0, 2, 1),   # Mixtral expert shape
        ("swiglu", False, 0.25, 2, 1),  # capacity binds: drops must match
        ("gelu", True, 1.0, 3, 4),      # multi-group per-shard quotas
    ],
)
def test_slots_matches_onehot_oracle(activation, bias, cf, k, ep):
    """The index-form (gather) slot executor must reproduce the one-hot
    dispatch oracle exactly — same keeps, drops, combine weights, aux."""
    model = MoEFeedForward(d_model=8, d_ff=16, n_experts=8, k=k,
                           capacity_factor=cf, activation=activation,
                           bias=bias)
    params = model.init(seed=4)
    x = jnp.asarray(_tokens(n=64, d=8, seed=7))
    want, aux_want = model.apply_reference(params, x, ep=ep)
    got, aux_got = model.apply_slots(params, x, ep=ep)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux_got), float(aux_want), rtol=1e-5)


def test_slots_gradients_match_onehot():
    model = MoEFeedForward(d_model=8, d_ff=16, n_experts=4, k=2,
                           capacity_factor=1.25)
    params = {k: jnp.asarray(v) for k, v in model.init(seed=9).items()}
    x = jnp.asarray(_tokens(n=32, d=8, seed=11))
    y = jnp.asarray(_tokens(n=32, d=8, seed=12))

    def loss(p, fn):
        h, aux = fn(p, x)
        return jnp.mean(_mse(y, x + h)) + 1e-2 * aux

    g_ref = jax.grad(lambda p: loss(p, model.apply_reference))(params)
    g_slt = jax.grad(lambda p: loss(p, model.apply_slots))(params)
    for k_ in params:
        np.testing.assert_allclose(
            np.asarray(g_slt[k_]), np.asarray(g_ref[k_]),
            rtol=2e-5, atol=2e-6, err_msg=k_)


def test_grouped_rejects_expert_choice():
    model = MoEFeedForward(d_model=4, d_ff=8, n_experts=4,
                           routing="expert_choice")
    with pytest.raises(ValueError, match="token_choice"):
        model.apply_grouped(model.init(0), jnp.zeros((8, 4)))


def test_validation():
    with pytest.raises(ValueError):
        MoEFeedForward(d_model=4, d_ff=8, n_experts=1, k=2)
    with pytest.raises(ValueError, match="routing"):
        MoEFeedForward(d_model=4, d_ff=8, n_experts=4, routing="soft")
    mesh = build_mesh_ep(data=1, expert=8)
    model = MoEFeedForward(d_model=4, d_ff=8, n_experts=6, k=1)
    with pytest.raises(ValueError, match="not divisible"):
        build_ep_train_step(model, mesh, optax.sgd(0.1), _mse)
