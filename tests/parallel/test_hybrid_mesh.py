"""Hybrid DCN×ICI mesh: inner axis within a host, outer across hosts.

On this CPU test grid every "host" is virtual, but the layout contract is
identical: reshaping [n_devices] → [dcn, ici] with jax.devices() order
keeps each inner group contiguous-by-process. The trainers must run
unchanged on the hybrid mesh: tp's per-pair psum rides the inner axis,
dp's gradient mean the outer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from elephas_tpu.parallel import (
    TensorParallelMLP,
    build_tp_train_step,
    hybrid_mesh,
)


def xent(y, yp):
    return -jnp.sum(y * jax.nn.log_softmax(yp, -1), -1)


def test_layout_inner_axis_contiguous():
    mesh = hybrid_mesh(ici_size=4)
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (2, 4)
    flat = list(np.asarray(mesh.devices).ravel())
    assert flat == list(jax.devices())  # row-major: inner groups contiguous


def test_bad_ici_size_rejected():
    with pytest.raises(ValueError, match="divide"):
        hybrid_mesh(ici_size=3)


def test_tp_trains_on_hybrid_mesh():
    """dp over the (virtual) DCN axis × Megatron tp over the ICI axis."""
    mesh = hybrid_mesh(dcn_axis="data", ici_axis="model", ici_size=4)
    tp = mesh.devices.shape[1]
    model = TensorParallelMLP([8, 8 * tp, 8 * tp, 8 * tp, 4], tp=tp)
    step, opt_init = build_tp_train_step(model, mesh, optax.sgd(0.1), xent)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16 * mesh.devices.shape[0], 8)).astype("float32")
    y = np.eye(4, dtype="float32")[rng.integers(0, 4, size=x.shape[0])]
    params = model.shard_params(mesh, model.init())
    state = opt_init(params)
    losses = []
    for _ in range(3):
        xd = jax.device_put(x, NamedSharding(mesh, P("data")))
        yd = jax.device_put(y, NamedSharding(mesh, P("data")))
        params, state, loss = step(params, state, xd, yd)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_lm_dp_sp_on_hybrid_mesh():
    """The flagship layout: sequence sharding (ring attention's per-step
    ppermute traffic) on the ICI axis, data parallelism (one gradient mean
    per step) across the DCN axis."""
    from elephas_tpu.models import (
        TransformerLM,
        build_lm_train_step,
        make_lm_batches,
        shard_lm_batch,
    )

    mesh = hybrid_mesh(dcn_axis="data", ici_axis="seq", ici_size=4)
    sp = mesh.devices.shape[1]
    lm = TransformerLM(vocab=17, d_model=8, n_heads=sp, n_layers=1,
                       d_ff=16, max_len=8 * sp)
    step, opt_init = build_lm_train_step(lm, mesh, optax.sgd(0.1), attn="ring")
    rng = np.random.default_rng(2)
    rows = rng.integers(0, 17, size=(2 * mesh.devices.shape[0], 8 * sp + 1))
    batch = shard_lm_batch(mesh, *make_lm_batches(rows))
    params = lm.shard_params(mesh, lm.init())
    state = opt_init(params)
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, *batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
