"""ZeRO-3/FSDP step vs replicated single-device DP-SGD.

Chunked storage + all_gather/psum_scatter is a pure re-layout of the same
math: losses and parameter trajectories must match the dense oracle on the
8 virtual CPU devices (conftest), and the at-rest layout must actually be
sharded (each device holds 1/P rows).
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from elephas_tpu.parallel import build_mesh
from elephas_tpu.parallel.fsdp import FSDPParams, build_fsdp_train_step


def _mlp_shapes(d_in, h, d_out):
    return {"w0": (d_in, h), "b0": (h,), "w1": (h, d_out), "b1": (d_out,)}


def _mlp_apply(params, x):
    h = jax.nn.relu(jnp.dot(x, params["w0"]) + params["b0"])
    return jnp.dot(h, params["w1"]) + params["b1"]


def _mlp_init(shapes, seed=0):
    rng = np.random.default_rng(seed)
    return {
        k: (rng.normal(size=s) * 0.1).astype(np.float32) for k, s in shapes.items()
    }


from tests._helpers import softmax_xent as _softmax_xent  # noqa: E402


def test_chunk_roundtrip():
    shapes = _mlp_shapes(7, 13, 3)  # sizes deliberately indivisible by 8
    fsdp = FSDPParams(shapes, 8)
    params = _mlp_init(shapes)
    back = fsdp.unchunk_host(fsdp.chunk_host(params))
    for k in params:
        np.testing.assert_array_equal(back[k], params[k])


@pytest.mark.parametrize("opt_name,remat", [("adam", False), ("sgd", True)])
def test_train_step_matches_dense(opt_name, remat):
    mesh = build_mesh(8)
    shapes = _mlp_shapes(10, 17, 4)  # indivisible sizes exercise padding
    optimizer = optax.adam(1e-2) if opt_name == "adam" else optax.sgd(0.1)
    params = _mlp_init(shapes, seed=1)
    rng = np.random.default_rng(7)
    x = rng.normal(size=(64, 10)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=64)]

    def oracle_loss(p):
        return jnp.mean(_softmax_xent(y, _mlp_apply(p, x)))

    o_state = optimizer.init({k: jnp.asarray(v) for k, v in params.items()})
    o_params = {k: jnp.asarray(v) for k, v in params.items()}
    o_losses = []
    for _ in range(4):
        loss, grads = jax.value_and_grad(oracle_loss)(o_params)
        updates, o_state = optimizer.update(grads, o_state, o_params)
        o_params = jax.tree_util.tree_map(jnp.add, o_params, updates)
        o_losses.append(float(loss))

    step, opt_init, fsdp = build_fsdp_train_step(
        _mlp_apply, shapes, mesh, optimizer, _softmax_xent, remat=remat
    )
    chunks = fsdp.shard(mesh, fsdp.chunk_host(params))
    state = opt_init(chunks)
    xd = jax.device_put(x, NamedSharding(mesh, P("data")))
    yd = jax.device_put(y, NamedSharding(mesh, P("data")))
    losses = []
    for _ in range(4):
        chunks, state, loss = step(chunks, state, xd, yd)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=1e-4, atol=1e-5)
    got = fsdp.unchunk_host({k: np.asarray(v) for k, v in chunks.items()})
    for k, v in o_params.items():
        np.testing.assert_allclose(
            got[k], np.asarray(v), rtol=2e-4, atol=2e-5, err_msg=k
        )


def test_at_rest_layout_is_sharded():
    """Each device must hold exactly one [1, chunk] row of every param."""
    mesh = build_mesh(8)
    shapes = _mlp_shapes(10, 16, 4)
    fsdp = FSDPParams(shapes, 8)
    chunks = fsdp.shard(mesh, fsdp.chunk_host(_mlp_init(shapes)))
    for k, v in chunks.items():
        assert v.shape[0] == 8
        for shard in v.addressable_shards:
            assert shard.data.shape[0] == 1  # one row per device
