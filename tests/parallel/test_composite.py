"""3-D dp×pp×tp composite vs the single-device dense oracle.

The 8 virtual CPU devices fold into a (2, 2, 2) ("data", "pipe", "model")
mesh: GPipe microbatching over "pipe" with Megatron column→row pairs over
"model" inside each stage must reproduce the unsharded math exactly.
"""

import numpy as np
import optax
import pytest

import jax

from elephas_tpu.compat import shard_map as compat_shard_map
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from elephas_tpu.parallel.composite import (
    TensorPipelineStack,
    build_3d_train_step,
    build_mesh_3d,
)
from tests._helpers import softmax_xent as _softmax_xent


@pytest.mark.parametrize("dp,pp,tp", [(2, 2, 2), (1, 4, 2), (1, 2, 4)])
def test_forward_matches_dense(dp, pp, tp):
    mesh = build_mesh_3d(data=dp, pipe=pp, model=tp)
    model = TensorPipelineStack(d_in=12, hidden=16, d_out=6, n_stages=pp,
                                pairs_per_stage=2)
    params = model.init(seed=3)
    x = np.random.default_rng(0).normal(size=(16, 12)).astype(np.float32)

    want = np.asarray(model.apply_reference(params, x))

    fwd = jax.jit(
        compat_shard_map(
            lambda p, xb: model.apply(p, xb, n_micro=4),
            mesh=mesh, in_specs=(model.specs(), P("data")),
            out_specs=P("data"), check_vma=False,
        )
    )
    xd = jax.device_put(x, NamedSharding(mesh, P("data")))
    got = np.asarray(fwd(model.shard_params(mesh, params), xd))
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)


def test_train_step_matches_dense():
    dp, pp, tp = 2, 2, 2
    mesh = build_mesh_3d(data=dp, pipe=pp, model=tp)
    model = TensorPipelineStack(d_in=10, hidden=16, d_out=4, n_stages=pp)
    optimizer = optax.adam(1e-2)
    params = model.init(seed=1)
    rng = np.random.default_rng(7)
    x = rng.normal(size=(32, 10)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=32)]

    def oracle_loss(p):
        return jnp.mean(_softmax_xent(y, model.apply_reference(p, x)))

    o_state = optimizer.init(params)
    o_params = params
    o_losses = []
    for _ in range(3):
        loss, grads = jax.value_and_grad(oracle_loss)(o_params)
        updates, o_state = optimizer.update(grads, o_state, o_params)
        o_params = jax.tree_util.tree_map(jnp.add, o_params, updates)
        o_losses.append(float(loss))

    step, opt_init = build_3d_train_step(
        model, mesh, optimizer, _softmax_xent, n_micro=4
    )
    sharded = model.shard_params(mesh, params)
    state = opt_init(sharded)
    xd = jax.device_put(x, NamedSharding(mesh, P("data")))
    yd = jax.device_put(y, NamedSharding(mesh, P("data")))
    losses = []
    for _ in range(3):
        sharded, state, loss = step(sharded, state, xd, yd)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, o_losses, rtol=1e-4, atol=1e-5)
    got = model.gather_params(sharded)
    for k, v in o_params.items():
        np.testing.assert_allclose(
            got[k], np.asarray(v), rtol=3e-4, atol=3e-5, err_msg=k
        )


def test_validation():
    mesh = build_mesh_3d(data=2, pipe=2, model=2)
    with pytest.raises(ValueError, match="pipe axis"):
        build_3d_train_step(
            TensorPipelineStack(4, 8, 2, n_stages=4),
            mesh, optax.sgd(0.1), _softmax_xent, 2,
        )
    with pytest.raises(ValueError, match="not divisible"):
        build_3d_train_step(
            TensorPipelineStack(4, 9, 2, n_stages=2),
            mesh, optax.sgd(0.1), _softmax_xent, 2,
        )
    with pytest.raises(ValueError, match="needs"):
        build_mesh_3d(data=4, pipe=4, model=4)
