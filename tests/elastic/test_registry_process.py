"""HeartbeatRegistry driven by REAL processes (satellite coverage).

The registry's unit tests (tests/resilience/test_membership.py) drive it
with a fake clock and threads. Here the beats come from actual worker
processes over TCP via the elastic pool, and the properties under test are
the ones process-level chaos can break: snapshot JSON round-trips, epochs
strictly monotonic under concurrent join/expire, and ``fence()`` rejecting
a member whose lease expired between launch and commit.
"""

import json

import numpy as np
import pytest

from elephas_tpu.parallel.elastic import ElasticConfig, ElasticHostPool
from elephas_tpu.resilience.faults import FaultPlan
from elephas_tpu.resilience.membership import HeartbeatRegistry

pytestmark = pytest.mark.elastic


_MEMO = {}


def _chaos_pool():
    """A 3-host fleet (real processes) with one heartbeat partition: the
    registry sees joins from live processes, an expiry from a lease lapse,
    and a late (fenced) result from the zombie. Run once, inspected by
    several tests (the pool is closed; its state is what's under test)."""
    if "pool" in _MEMO:
        return _MEMO["pool"]
    rng = np.random.default_rng(2)
    x = rng.normal(size=(200, 3))
    y = x @ np.array([1.0, -2.0, 3.0])
    pool = ElasticHostPool(
        [np.zeros(3)],
        ElasticConfig(initial_hosts=3, rounds=3, lease_s=1.5,
                      beat_interval_s=0.1),
        task={"builtin": "sgd_task"},
        task_config={"lr": 0.5, "sleep_s": 0.1},
        fault_plan=FaultPlan(seed=3, partition_hosts={1: 2}),
    )
    pool.fit(x, y)
    _MEMO["pool"] = pool
    return pool


def test_snapshot_json_round_trips_from_process_run():
    pool = _chaos_pool()
    snap = pool.registry.snapshot()
    restored = json.loads(json.dumps(snap))
    assert restored == snap
    assert restored["membership"]["live"] == ["host-0", "host-1"]
    assert restored["counters"]["join"] == 3
    assert restored["counters"]["expire"] == 1
    assert restored["counters"]["late_reject"] == 1


def test_epochs_strictly_monotonic_under_process_churn():
    pool = _chaos_pool()
    events = pool.registry.snapshot()["events"]
    bumping = [e for e in events
               if e["kind"] in ("join", "rejoin", "leave", "expire")]
    epochs = [e["epoch"] for e in bumping]
    # every membership transition bumps: strictly increasing, no reuse
    assert epochs == sorted(epochs)
    assert len(set(epochs)) == len(epochs)
    # and the non-transition events never exceed the current epoch
    assert max(e["epoch"] for e in events) == pool.registry.epoch


def test_fence_rejects_lease_expired_between_launch_and_commit():
    """The exact zombie interleaving, against the real registry clock:
    work launched at epoch E, the member's lease expires (fence moves past
    E), the result shows up at commit time — fence() must reject it."""
    clock = {"now": 0.0}
    registry = HeartbeatRegistry(lease_s=1.0, clock=lambda: clock["now"])
    registry.join("host-0")
    registry.join("host-1")
    launch_epoch = registry.epoch
    # host-1 beats; host-0 goes silent past its lease
    clock["now"] = 1.5
    registry.heartbeat("host-1")
    expired = registry.sweep()
    assert expired == ["host-0"]
    # commit-time check: host-0's result was launched below its fence
    assert launch_epoch < registry.fence("host-0")
    assert not registry.is_live("host-0")
    # the survivor's results are NOT fenced
    assert launch_epoch >= registry.fence("host-1")
    # and the process-level pool enforces exactly this: the zombie's delta
    # ended in rejected_stale (see test_chaos_elastic for the full pin)


def test_pool_registry_fence_reflects_partition():
    pool = _chaos_pool()
    # launched at the pre-expiry epoch, fenced at the expiry epoch
    assert pool.registry.fence("host-2") > 0
    assert pool.ps.rejected_stale == 1
    snap = pool.registry.snapshot()
    fences = snap["membership"]["fences"]
    assert "host-2" in fences
    rejects = [e for e in snap["events"] if e["kind"] == "late_reject"]
    assert rejects and rejects[0]["detail"]["launch_epoch"] < fences["host-2"]
