"""ElasticHostPool basics over REAL worker processes (numpy sgd task).

Everything here crosses process boundaries for real: the hosts are
subprocesses speaking the sockets.py framing to the driver's control plane.
The sgd task keeps each host's boot under a second (no jax/keras import in
the worker), so a whole fleet costs a few seconds per test.
"""

import json

import numpy as np
import pytest

from elephas_tpu.parallel.elastic import ElasticConfig, ElasticHostPool

pytestmark = pytest.mark.elastic


def _lsq_problem(seed=0, n=300, d=3):
    rng = np.random.default_rng(seed)
    w_true = np.array([1.0, -2.0, 3.0])[:d]
    x = rng.normal(size=(n, d))
    return x, x @ w_true, w_true


def _run(cfg, plan=None, task_config=None, seed=0, n=300):
    x, y, w_true = _lsq_problem(seed=seed, n=n)
    pool = ElasticHostPool(
        [np.zeros(x.shape[1])], cfg, task={"builtin": "sgd_task"},
        task_config={"lr": 0.5, **(task_config or {})}, fault_plan=plan,
    )
    weights = pool.fit(x, y)
    return pool, weights, w_true


def test_static_pool_converges():
    cfg = ElasticConfig(initial_hosts=2, rounds=5, lease_s=2.0,
                        beat_interval_s=0.1)
    pool, weights, w_true = _run(cfg)
    losses = pool.history["loss"]
    assert len(losses) == 5
    assert losses[-1] < 0.1 * losses[0]
    assert np.allclose(weights[0], w_true, atol=0.5)
    # one commit per round, versions contiguous from 1
    assert [c["version"] for c in pool.commit_log] == [1, 2, 3, 4, 5]
    assert pool.stats["reformations"] == 0
    assert pool.membership_trace == [("join", "host-0"), ("join", "host-1")]


def test_scale_up_recuts_mesh():
    cfg = ElasticConfig(initial_hosts=2, rounds=4, lease_s=2.0,
                        beat_interval_s=0.1, scale_schedule={2: 4})
    pool, _, _ = _run(cfg)
    # mesh history records each distinct formation: 2 hosts then 4
    assert [m["num_hosts"] for m in pool.mesh_history] == [2, 4]
    assert [len(c["contributors"]) for c in pool.commit_log] == [2, 2, 4, 4]
    assert pool.membership_trace == [
        ("join", "host-0"), ("join", "host-1"),
        ("join", "host-2"), ("join", "host-3"),
    ]
    # epochs in the commit log are non-decreasing and bump at the scale-up
    epochs = [c["epoch"] for c in pool.commit_log]
    assert epochs == sorted(epochs) and epochs[2] > epochs[1]


def test_scale_down_retires_gracefully():
    cfg = ElasticConfig(initial_hosts=3, rounds=4, lease_s=2.0,
                        beat_interval_s=0.1, scale_schedule={2: 2})
    pool, _, _ = _run(cfg)
    assert [len(c["contributors"]) for c in pool.commit_log] == [3, 3, 2, 2]
    # graceful scale-down is a LEAVE (fenced), not an expiry
    assert ("leave", "host-2") in pool.membership_trace
    assert not any(kind == "expire" for kind, _ in pool.membership_trace)


def test_device_weighted_sharding():
    cfg = ElasticConfig(initial_hosts=2, rounds=2, lease_s=2.0,
                        beat_interval_s=0.1, devices_per_host=2)
    x, y, _ = _lsq_problem(n=200)
    pool = ElasticHostPool([np.zeros(3)], cfg, task={"builtin": "sgd_task"},
                           task_config={"lr": 0.5})
    pool.fit(x, y)
    assert pool.mesh_history[0]["total_devices"] == 4
    assert pool.mesh_history[0]["hosts"] == [(0, 2), (1, 2)]


def test_snapshot_json_round_trips():
    cfg = ElasticConfig(initial_hosts=2, rounds=2, lease_s=2.0,
                        beat_interval_s=0.1)
    pool, _, _ = _run(cfg)
    snap = json.loads(json.dumps(pool.snapshot()))
    assert snap["stats"]["rounds_committed"] == 2
    assert snap["parameter_server"]["version"] == 2
    assert [c["version"] for c in snap["commit_log"]] == [1, 2]
    assert snap["registry"]["membership"]["epoch"] >= 2
