"""Cluster bootstrap robustness + backend lifecycle.

`initialize_cluster` used to hand an unreachable coordinator straight to
``jax.distributed.initialize``, which blocks forever — a mistyped address
turned a pod bring-up into a silent hang. With ``timeout_s`` it probes the
endpoint with bounded backoff and raises a ``RuntimeError`` NAMING the
address (the refused-port pin below). The backend tests cover the process
half of the emulation harness (spawn/kill/reap — no orphan Popen) and the
real-pod geometry planner.
"""

import os
import socket
import subprocess
import time

import pytest

from elephas_tpu.parallel.distributed import initialize_cluster
from elephas_tpu.parallel.emulation import EmulationBackend, JaxPodBackend
from elephas_tpu.utils.sockets import connect_with_retry, parse_address

pytestmark = pytest.mark.elastic


def _refused_address() -> str:
    """An address guaranteed-refused RIGHT NOW: bind, read, close — nothing
    rebinds it within the sub-second probe window of these tests."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    return f"127.0.0.1:{port}"


def test_initialize_cluster_refused_port_raises_named_error():
    address = _refused_address()
    start = time.monotonic()
    with pytest.raises(RuntimeError) as err:
        initialize_cluster(coordinator_address=address, num_processes=2,
                           process_id=1, timeout_s=1.0)
    elapsed = time.monotonic() - start
    assert address in str(err.value)            # names the coordinator
    assert "could not join the cluster" in str(err.value)
    assert elapsed < 10.0                       # bounded, not a hang


def test_initialize_cluster_single_process_is_noop():
    # no coordinator, no env: must return immediately without touching
    # jax.distributed at all
    assert initialize_cluster(num_processes=1, timeout_s=0.1) is None


def test_connect_with_retry_backs_off_then_raises():
    address = _refused_address()
    sleeps = []
    fake_now = {"t": 0.0}

    def fake_sleep(s):
        sleeps.append(s)
        fake_now["t"] += s

    with pytest.raises(RuntimeError) as err:
        connect_with_retry(address, timeout_s=0.5, base_delay_s=0.05,
                           sleep=fake_sleep,
                           clock=lambda: fake_now["t"])
    assert address.split(":")[0] in str(err.value)
    # exponential: each delay doubles until the 1s cap
    assert sleeps[:3] == [0.05, 0.1, 0.2]


def test_connect_with_retry_reaches_live_listener():
    with socket.socket() as srv:
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        conn = connect_with_retry(f"127.0.0.1:{srv.getsockname()[1]}",
                                  timeout_s=5.0)
        conn.close()


def test_parse_address():
    assert parse_address("10.0.0.1:8476") == ("10.0.0.1", 8476)
    assert parse_address("10.0.0.1", default_port=4000) == ("10.0.0.1", 4000)


def test_emulation_backend_spawns_kills_and_reaps():
    backend = EmulationBackend(devices_per_host=1)
    with socket.socket() as srv:
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        backend.spawn(0, f"127.0.0.1:{srv.getsockname()[1]}")
        srv.settimeout(30)
        peer, _ = srv.accept()            # the worker process really dialed
        assert backend.alive(0)
        backend.kill(0)                   # real SIGKILL...
        assert not backend.alive(0)       # ...and already reaped
        assert backend.procs[0].returncode == -9
        peer.close()
    backend.stop_all()
    # no orphan Popen: every spawned process has a collected return code
    assert all(p.returncode is not None for p in backend.procs.values())


def test_emulation_backend_stop_all_reaps_stragglers():
    backend = EmulationBackend(devices_per_host=1)
    # never accepts: the worker sits in its connect-retry loop
    with socket.socket() as srv:
        srv.bind(("127.0.0.1", 0))
        backend.spawn(0, f"127.0.0.1:{srv.getsockname()[1]}")
        assert backend.alive(0)
        backend.stop_all(grace_s=0.2)     # grace expires -> SIGKILL + wait
    assert backend.procs[0].returncode is not None


def test_jax_pod_backend_reform_renumbers_densely():
    backend = JaxPodBackend("10.0.0.1:8476", timeout_s=30.0)
    plan = backend.reform([4, 0, 7])
    # jax.distributed needs process ids in [0, num_processes): survivors are
    # renumbered densely, lowest survivor hosts the restarted coordinator
    assert plan == {
        "coordinator_host": 0,
        "num_processes": 3,
        "process_ids": {0: 0, 4: 1, 7: 2},
    }
    boot = backend.bootstrap(host_id=4, num_processes=3)
    assert boot["coordinator_address"] == "10.0.0.1:8476"
    assert boot["process_id"] == 4 and boot["timeout_s"] == 30.0


def test_worker_script_runs_standalone_without_package_import():
    """The emulation worker must boot WITHOUT importing elephas_tpu (the
    package __init__ pulls in keras — seconds per host). Run it with the
    package unimportable and a driver that immediately closes: the worker
    must exit cleanly via its connection-lost path, not an ImportError."""
    import elephas_tpu.parallel.emulation as emulation

    with socket.socket() as srv:
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env["PYTHONPATH"] = "/nonexistent"
        proc = subprocess.Popen(
            ["python3", emulation.__file__,
             "--driver", f"127.0.0.1:{srv.getsockname()[1]}",
             "--host-id", "0"],
            env=env, stderr=subprocess.PIPE, text=True,
        )
        peer, _ = srv.accept()
        peer.close()                      # driver vanishes mid-handshake
        _, stderr = proc.communicate(timeout=60)
    assert proc.returncode == 1, stderr[-2000:]
    assert "ImportError" not in stderr and "ModuleNotFoundError" not in stderr
