"""THE pinned elastic chaos scenario (ROADMAP acceptance):

an elastic 2→4→3-host ``SparkModel.fit`` — real Keras replicas in real host
processes — that scales up mid-fit (one of the new hosts joining LATE),
loses a host to a real SIGKILL mid-round, re-forms the mesh each time, and
still converges; with the membership-event trace and the committed-version
log deterministic at the fixed seed and the committed-update monotonicity
asserted straight off the parameter store's version log.
"""

import numpy as np
import pytest

from elephas_tpu import SparkModel
from elephas_tpu.parallel.elastic import ElasticConfig
from elephas_tpu.resilience.faults import FaultPlan
from elephas_tpu.utils.rdd_utils import to_simple_rdd

from ..conftest import make_classifier

pytestmark = [pytest.mark.elastic, pytest.mark.chaos]

ROUNDS = 6

# The full expected membership-event sequence, as literals: hosts 0-1 boot
# the fit; the round-2 scale-up to 4 spawns hosts 2-3 but host 3's admission
# is delayed one boundary (late join); host 1 is SIGKILLed mid-round 4.
EXPECTED_TRACE = [
    ("join", "host-0"),
    ("join", "host-1"),
    ("join", "host-2"),
    ("join", "host-3"),
    ("expire", "host-1"),
]


@pytest.mark.timeout(280)
def test_elastic_2_4_3_spark_fit(spark_context, toy_classification):
    x, y = toy_classification
    rdd = to_simple_rdd(spark_context, x, y)
    model = make_classifier(hidden=8, optimizer="sgd")
    plan = FaultPlan(seed=1234, kill_hosts={4: 1}, join_delay_rounds={3: 1})
    sm = SparkModel(
        model, num_workers=4, batch_size=32,
        fault_plan=plan,
        elastic=ElasticConfig(
            initial_hosts=2, scale_schedule={2: 4}, min_hosts=1,
            lease_s=4.0, beat_interval_s=0.2, round_timeout_s=180.0,
        ),
    )
    sm.fit(rdd, epochs=ROUNDS, batch_size=32, validation_split=0.0)
    pool = sm._elastic_pool

    # -- convergence through the chaos -----------------------------------
    losses = pool.history["loss"]
    assert len(losses) == ROUNDS
    assert losses[-1] < losses[0], losses

    # -- membership-event trace: deterministic at the fixed seed ----------
    assert pool.membership_trace == EXPECTED_TRACE
    assert plan.fired.get("kill-host-1") == 4
    assert plan.fired.get("delay-join-host-3") == 1

    # -- the mesh re-formed 2 → 3 → 4 → 3 (host 3 joined a boundary after
    #    hosts 2; host 1 died) — device count changed mid-fit -------------
    assert [m["num_hosts"] for m in pool.mesh_history] == [2, 3, 4, 3]

    # -- committed-update monotonicity, straight off the PS version log --
    versions = [c["version"] for c in pool.commit_log]
    assert versions == list(range(1, ROUNDS + 1))      # no loss, no double
    assert pool.ps.version == ROUNDS
    epochs = [c["epoch"] for c in pool.commit_log]
    assert epochs == sorted(epochs)                    # epochs monotonic
    assert [tuple(c["contributors"]) for c in pool.commit_log] == [
        (0, 1), (0, 1), (0, 1, 2), (0, 1, 2, 3), (0, 2, 3), (0, 2, 3),
    ]

    # -- the killed issue consumed no version; its survivors' deltas were
    #    discarded at the pool, and nothing stale reached the weights ----
    assert pool.stats["reformations"] == 1
    assert pool.stats["discarded_reformation"] == 3   # one per survivor
    assert pool.ps.rejected_stale == 0

    # -- observability surfaces through SparkModel ------------------------
    snap = sm.membership_snapshot()
    assert snap["elastic"]["stats"]["rounds_committed"] == ROUNDS
    hist = sm.training_histories[-1]
    assert hist["mode"] == "elastic" and hist["reformations"] == 1
