"""Host-level chaos against the elastic control plane — pinned, not fuzzed.

Each scenario scripts its faults through the seeded FaultPlan's exact
round→host maps (`kill_hosts` / `partition_hosts` / `join_delay_rounds`),
so the membership-event trace, the commit log, and every fencing counter
are asserted as literals. The kills are real SIGKILLs of real processes;
the partitions cut a real heartbeat channel while the worker keeps
computing.

The sgd tasks carry a small ``sleep_s`` so a killed host is guaranteed to
die MID-compute (the signal always lands faster than the task finishes) —
that is what makes the re-formation path, not the lucky-commit path,
deterministic.
"""

import numpy as np
import pytest

from elephas_tpu.parallel.elastic import ElasticConfig, ElasticHostPool
from elephas_tpu.resilience.faults import FaultPlan

pytestmark = [pytest.mark.elastic, pytest.mark.chaos]


def _lsq_problem(seed=0, n=300, d=3):
    rng = np.random.default_rng(seed)
    w_true = np.array([1.0, -2.0, 3.0])[:d]
    x = rng.normal(size=(n, d))
    return x, x @ w_true, w_true


def _pool(cfg, plan, sleep_s=0.3):
    x, y, _ = _lsq_problem()
    pool = ElasticHostPool(
        [np.zeros(3)], cfg, task={"builtin": "sgd_task"},
        task_config={"lr": 0.5, "sleep_s": sleep_s}, fault_plan=plan,
    )
    return pool, pool.fit(x, y)


def test_kill_host_mid_round_reforms():
    cfg = ElasticConfig(initial_hosts=3, rounds=4, lease_s=2.0,
                        beat_interval_s=0.1)
    plan = FaultPlan(seed=11, kill_hosts={1: 2})
    pool, _ = _pool(cfg, plan)
    assert plan.fired.get("kill-host-2") == 1
    assert pool.stats["kills"] == 1
    assert pool.stats["reformations"] == 1
    assert pool.membership_trace == [
        ("join", "host-0"), ("join", "host-1"), ("join", "host-2"),
        ("expire", "host-2"),
    ]
    # round 1 re-forms over the survivors and still commits; versions never
    # skip or repeat — the killed issue consumed no version
    assert [(c["version"], c["round"], c["contributors"])
            for c in pool.commit_log] == [
        (1, 0, [0, 1, 2]), (2, 1, [0, 1]), (3, 2, [0, 1]), (4, 3, [0, 1]),
    ]
    # the survivors' pre-re-formation deltas were discarded at the pool,
    # never consuming a server version
    assert pool.stats["discarded_reformation"] == 2
    assert pool.ps.rejected_stale == 0


def test_zombie_partition_delta_rejected_stale():
    """Heartbeat-channel partition: the host stays alive and computes, the
    control plane stops hearing it. Its lease lapses, the round re-forms,
    and its delta — pushed through the REAL server fence — lands in
    ``rejected_stale``, not the weights."""
    cfg = ElasticConfig(initial_hosts=3, rounds=3, lease_s=1.5,
                        beat_interval_s=0.1)
    plan = FaultPlan(seed=3, partition_hosts={1: 2})
    pool, _ = _pool(cfg, plan, sleep_s=0.1)
    assert plan.fired.get("partition-host-2") == 1
    assert pool.stats["partitions"] == 1
    assert pool.membership_trace == [
        ("join", "host-0"), ("join", "host-1"), ("join", "host-2"),
        ("expire", "host-2"),
    ]
    # exactly one zombie delta, rejected BY THE SERVER (version untouched)
    assert pool.ps.rejected_stale == 1
    assert pool.stats["rejected_stale"] == 1
    assert pool.ps.version == len(pool.commit_log) == 3
    assert [c["version"] for c in pool.commit_log] == [1, 2, 3]
    events = pool.registry.snapshot()["events"]
    rejects = [e for e in events if e["kind"] == "late_reject"]
    assert len(rejects) == 1 and rejects[0]["member"] == "host-2"


def test_delayed_join_misses_boundaries_then_joins():
    cfg = ElasticConfig(initial_hosts=2, rounds=4, lease_s=2.0,
                        beat_interval_s=0.1, scale_schedule={1: 3})
    plan = FaultPlan(seed=5, join_delay_rounds={2: 2})
    pool, _ = _pool(cfg, plan, sleep_s=0.0)
    assert plan.fired.get("delay-join-host-2") == 2
    # spawned at round 1, admitted two boundaries later: contributes from
    # round 3 on
    assert [len(c["contributors"]) for c in pool.commit_log] == [2, 2, 2, 3]
    assert pool.membership_trace == [
        ("join", "host-0"), ("join", "host-1"), ("join", "host-2"),
    ]


def test_min_hosts_floor_is_enforced():
    cfg = ElasticConfig(initial_hosts=2, rounds=3, lease_s=1.5,
                        beat_interval_s=0.1, min_hosts=2)
    plan = FaultPlan(seed=9, kill_hosts={1: 0})
    x, y, _ = _lsq_problem()
    pool = ElasticHostPool(
        [np.zeros(3)], cfg, task={"builtin": "sgd_task"},
        task_config={"lr": 0.5, "sleep_s": 0.3}, fault_plan=plan,
    )
    with pytest.raises(RuntimeError, match="min_hosts"):
        pool.fit(x, y)


def test_trace_deterministic_across_runs():
    """Same seed, same faults → identical membership trace and commit shape,
    run twice for real (fresh processes both times)."""
    def run_once():
        cfg = ElasticConfig(initial_hosts=2, rounds=4, lease_s=2.0,
                            beat_interval_s=0.1, scale_schedule={1: 3})
        plan = FaultPlan(seed=21, kill_hosts={2: 1})
        pool, _ = _pool(cfg, plan)
        return (
            pool.membership_trace,
            [(c["version"], c["round"], tuple(c["contributors"]))
             for c in pool.commit_log],
        )

    assert run_once() == run_once()
