"""Retry policies and circuit breaking for parameter-server traffic.

Two composable pieces:

- :class:`RetryPolicy` — exponential backoff with deterministic jitter,
  attempt caps, and an overall deadline. Pure-ish: delays come from a
  seeded hash keyed by (seed, attempt), and both the clock and the sleep
  function are injectable, so tests pin exact schedules without waiting.
- :class:`CircuitBreaker` — classic closed → open → half-open state
  machine. After ``failure_threshold`` consecutive failures, calls
  fail-fast with :class:`CircuitOpenError` for ``reset_timeout_s``; then
  one probe call is admitted (half-open) and its outcome closes or
  re-opens the circuit. Fail-fast matters in hogwild mode: a dead server
  should cost a worker microseconds per step, not a 60s socket timeout
  per push.

:class:`ResilientClient` composes both around any
:class:`~elephas_tpu.parameter.client.BaseParameterClient`: every pull and
push routes through breaker → retry → transport. Only *transient* errors
(:func:`default_is_transient`: connection resets, timeouts, HTTP 5xx-ish
``URLError``/``OSError``) are retried; anything else — including an
injected :class:`~elephas_tpu.resilience.faults.InjectedWorkerCrash` — is
a crash and propagates immediately.
"""

from __future__ import annotations

import hashlib
import socket
import threading
import time
import urllib.error
from typing import Callable, Optional, TypeVar

from ..parameter.client import BaseParameterClient

T = TypeVar("T")


class RetryExhausted(RuntimeError):
    """All attempts failed (cap or deadline hit). ``__cause__`` is the
    last underlying error."""


class CircuitOpenError(ConnectionError):
    """Fail-fast rejection: the breaker is open, the call never went out."""


def default_is_transient(err: BaseException) -> bool:
    """Errors worth retrying: the network hiccupped, not the program.

    ``ConnectionError`` covers refused/reset/aborted plus injected
    :class:`~elephas_tpu.resilience.faults.TransientFault`; ``socket.timeout``
    and ``urllib.error.URLError`` are how the HTTP/socket clients surface
    slow or flapping servers; other ``OSError`` s (EPIPE, unreachable) round
    it out. ``CircuitOpenError`` is deliberately transient: a later attempt
    may find the breaker half-open.
    """
    if isinstance(err, (ConnectionError, socket.timeout, TimeoutError)):
        return True
    if isinstance(err, urllib.error.URLError):
        return True
    return isinstance(err, OSError)


def _jitter_unit(seed: int, attempt: int) -> float:
    digest = hashlib.blake2b(
        f"retry:{seed}:{attempt}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") / 2.0**64


class RetryPolicy:
    """Exponential backoff + jitter with attempt caps and deadlines.

    ``delay(attempt)`` for attempt k (0-based failure count) is
    ``min(base * mult**k, max_delay) * (1 - jitter * u)`` where ``u`` is a
    deterministic uniform draw from (seed, k) — full reproducibility with
    the decorrelation jitter buys in aggregate.
    """

    def __init__(self, *,
                 max_attempts: int = 5,
                 base_delay_s: float = 0.05,
                 multiplier: float = 2.0,
                 max_delay_s: float = 2.0,
                 jitter: float = 0.5,
                 deadline_s: Optional[float] = None,
                 seed: int = 0,
                 is_transient: Callable[[BaseException], bool] = default_is_transient,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.multiplier = float(multiplier)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.deadline_s = deadline_s
        self.seed = int(seed)
        self.is_transient = is_transient
        self.sleep = sleep
        self.clock = clock

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt + 1`` (attempt is the
        0-based count of failures so far)."""
        raw = min(
            self.base_delay_s * self.multiplier ** attempt, self.max_delay_s
        )
        return raw * (1.0 - self.jitter * _jitter_unit(self.seed, attempt))

    def call(self, fn: Callable[[], T], *, describe: str = "call") -> T:
        """Run ``fn``, retrying transient failures per the schedule.

        Raises :class:`RetryExhausted` when the attempt cap or deadline is
        hit; re-raises non-transient errors immediately.
        """
        start = self.clock()
        last_err: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except BaseException as err:  # noqa: BLE001 - filtered below
                if not self.is_transient(err):
                    raise
                last_err = err
            if attempt + 1 >= self.max_attempts:
                break
            pause = self.delay(attempt)
            if (self.deadline_s is not None
                    and self.clock() - start + pause > self.deadline_s):
                raise RetryExhausted(
                    f"{describe}: deadline {self.deadline_s}s exceeded "
                    f"after {attempt + 1} attempt(s)"
                ) from last_err
            if pause > 0.0:
                self.sleep(pause)
        raise RetryExhausted(
            f"{describe}: all {self.max_attempts} attempt(s) failed"
        ) from last_err


class CircuitBreaker:
    """Closed → open → half-open breaker, thread-safe.

    Hogwild workers share one breaker per client stack: the first worker
    to burn ``failure_threshold`` consecutive failures opens it for
    everyone, and every call during the open window costs only a lock and
    a clock read.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, *,
                 failure_threshold: int = 5,
                 reset_timeout_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        # caller holds the lock
        if (self._state == self.OPEN
                and self.clock() - self._opened_at >= self.reset_timeout_s):
            self._state = self.HALF_OPEN
            self._probing = False

    def allow(self) -> bool:
        """Admit one call? Half-open admits exactly one probe at a time."""
        with self._lock:
            self._maybe_half_open()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._trip()
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._trip()

    def _trip(self) -> None:
        # caller holds the lock
        self._state = self.OPEN
        self._failures = 0
        self._probing = False
        self._opened_at = self.clock()

    def call(self, fn: Callable[[], T]) -> T:
        if not self.allow():
            raise CircuitOpenError("circuit breaker is open")
        try:
            result = fn()
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return result


class FailoverClient(BaseParameterClient):
    """Multi-endpoint parameter client: primary + hot standbys.

    Wraps an ordered list of endpoint clients (endpoint 0 = primary) with a
    circuit breaker per endpoint. Every operation tries the active endpoint
    first; when it fails transiently (or its breaker is open), the call
    fails over to the next endpoint in order — transparently, within the
    same logical call. The caller never learns the primary died.

    Staleness bound on failover: the client tracks the highest weight
    *version* it has observed (parameter servers expose a monotonic update
    counter — :meth:`~elephas_tpu.parameter.client.BaseParameterClient.
    get_version`). When traffic moves to a standby, the client polls the
    standby's version until it has caught up to the last observed version
    (or ``staleness_wait_s`` elapses, since an abruptly killed primary may
    have applied updates that never left its replication queue). So reads
    after failover are bounded-stale, not arbitrarily stale.

    Failovers are observable: ``failovers`` counts them, and a
    :class:`~elephas_tpu.resilience.membership.HeartbeatRegistry` passed as
    ``registry`` receives an event per failover (surfaced in its JSON
    snapshot).

    Push semantics across failover are at-least-once, exactly like a plain
    retried push: a push that timed out on the dying primary may have been
    applied and replicated before the client re-sends it to the standby.
    Attempt-tagged pushes stay bounded by the server's rollback/fence
    machinery; untagged pushes inherit the reference's documented
    at-least-once contract.
    """

    def __init__(self, endpoints, *,
                 breakers=None,
                 failure_threshold: int = 2,
                 reset_timeout_s: float = 5.0,
                 registry=None,
                 staleness_wait_s: float = 2.0,
                 poll_s: float = 0.05,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 is_transient: Callable[[BaseException], bool] = default_is_transient):
        if not endpoints:
            raise ValueError("FailoverClient needs at least one endpoint")
        self.endpoints = list(endpoints)
        self.breakers = (
            list(breakers) if breakers is not None
            else [CircuitBreaker(failure_threshold=failure_threshold,
                                 reset_timeout_s=reset_timeout_s,
                                 clock=clock)
                  for _ in self.endpoints]
        )
        if len(self.breakers) != len(self.endpoints):
            raise ValueError("one breaker per endpoint")
        self.registry = registry
        self.staleness_wait_s = float(staleness_wait_s)
        self.poll_s = float(poll_s)
        self.sleep = sleep
        self.clock = clock
        self.is_transient = is_transient
        self._lock = threading.Lock()
        self._active = 0
        self._last_version = -1
        self.failovers = 0

    @property
    def active_endpoint(self) -> int:
        with self._lock:
            return self._active

    def _note_version(self, endpoint) -> None:
        seen = getattr(endpoint, "last_seen_version", -1)
        if seen is None:
            return
        with self._lock:
            if seen > self._last_version:
                self._last_version = int(seen)

    def _await_catchup(self, endpoint) -> None:
        """Bound read staleness: wait (briefly) for the standby's version
        counter to reach the last version this client observed."""
        with self._lock:
            target = self._last_version
        if target < 0 or self.staleness_wait_s <= 0:
            return
        deadline = self.clock() + self.staleness_wait_s
        while True:
            try:
                version = endpoint.get_version()
                if version < 0 or version >= target:
                    # <0 = backend exposes no version counter: staleness
                    # cannot be bounded, don't burn the wait budget on it
                    return
            except BaseException as err:  # noqa: BLE001 - transient probe
                if not self.is_transient(err):
                    raise
            if self.clock() >= deadline:
                return
            self.sleep(self.poll_s)

    def _failover_to(self, index: int) -> None:
        with self._lock:
            if self._active == index:
                return
            self._active = index
            self.failovers += 1
            version = self._last_version
        if self.registry is not None:
            self.registry.observe_failover(
                endpoint=index, version=None if version < 0 else version
            )

    def _run(self, op: Callable[[BaseParameterClient], T], describe: str) -> T:
        with self._lock:
            start = self._active
        last_err: Optional[BaseException] = None
        for k in range(len(self.endpoints)):
            i = (start + k) % len(self.endpoints)
            endpoint, breaker = self.endpoints[i], self.breakers[i]
            if not breaker.allow():
                last_err = CircuitOpenError(
                    f"{describe}: endpoint {i} breaker is open"
                )
                continue
            if i != start:
                self._await_catchup(endpoint)
            try:
                result = op(endpoint)
            except BaseException as err:  # noqa: BLE001 - filtered below
                breaker.record_failure()
                if not self.is_transient(err):
                    raise
                last_err = err
                continue
            breaker.record_success()
            if i != start:
                self._failover_to(i)
            self._note_version(endpoint)
            return result
        assert last_err is not None
        raise last_err

    def get_parameters(self):
        return self._run(lambda c: c.get_parameters(), "get_parameters")

    def get_version(self) -> int:
        return self._run(lambda c: c.get_version(), "get_version")

    def update_parameters(self, delta) -> None:
        self._run(lambda c: c.update_parameters(delta), "update_parameters")

    def update_parameters_tagged(self, task_id: str, delta,
                                 attempt=None) -> None:
        if attempt is None:
            self._run(lambda c: c.update_parameters_tagged(task_id, delta),
                      "update_parameters_tagged")
        else:
            self._run(
                lambda c: c.update_parameters_tagged(
                    task_id, delta, attempt=attempt
                ),
                "update_parameters_tagged",
            )

    def register_attempt(self, task_id: str, attempt: int) -> bool:
        return self._run(
            lambda c: c.register_attempt(task_id, attempt), "register_attempt"
        )

    def commit_attempt(self, task_id: str) -> None:
        self._run(lambda c: c.commit_attempt(task_id), "commit_attempt")

    def close(self) -> None:
        for endpoint in self.endpoints:
            try:
                endpoint.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass


class ResilientClient(BaseParameterClient):
    """Route a parameter client's traffic through breaker → retry.

    The breaker sits INSIDE the retry loop: an open circuit surfaces as a
    transient :class:`CircuitOpenError`, so the retry policy backs off
    across the breaker's reset window instead of giving up instantly —
    a worker rides out a brief server outage with a handful of cheap
    rejections, then resumes on the half-open probe.
    """

    def __init__(self, inner: BaseParameterClient,
                 policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.inner = inner
        self.policy = policy or RetryPolicy()
        self.breaker = breaker

    def _guarded(self, fn: Callable[[], T], describe: str) -> T:
        if self.breaker is None:
            return self.policy.call(fn, describe=describe)
        return self.policy.call(
            lambda: self.breaker.call(fn), describe=describe
        )

    def get_parameters(self):
        return self._guarded(self.inner.get_parameters, "get_parameters")

    def update_parameters(self, delta) -> None:
        self._guarded(
            lambda: self.inner.update_parameters(delta), "update_parameters"
        )

    def update_parameters_tagged(self, task_id: str, delta,
                                 attempt=None) -> None:
        # Forward the attempt tag only when set so plain two-arg inner
        # clients keep working unchanged.
        if attempt is None:
            self._guarded(
                lambda: self.inner.update_parameters_tagged(task_id, delta),
                "update_parameters_tagged",
            )
        else:
            self._guarded(
                lambda: self.inner.update_parameters_tagged(
                    task_id, delta, attempt=attempt
                ),
                "update_parameters_tagged",
            )

    def get_version(self) -> int:
        return self._guarded(self.inner.get_version, "get_version")

    def register_attempt(self, task_id: str, attempt: int) -> bool:
        return self._guarded(
            lambda: self.inner.register_attempt(task_id, attempt),
            "register_attempt",
        )

    def commit_attempt(self, task_id: str) -> None:
        self._guarded(
            lambda: self.inner.commit_attempt(task_id), "commit_attempt"
        )

    def close(self) -> None:
        self.inner.close()
