"""Training supervisor: checkpoint, crash, auto-resume.

The layering below the supervisor already absorbs *task-scoped* failure:
the facade RDD re-executes dead partitions (Spark-parity ``maxFailures``)
and the parameter server's stage-scoped task-id attempt machinery keeps
retried async pushes exactly-once. What nothing absorbs is *whole-fit*
death — driver OOM, preemption, an
:class:`~elephas_tpu.resilience.faults.InjectedWorkerCrash` escaping a fit
chunk. :class:`TrainingSupervisor` owns that layer: it wraps
``SparkModel.fit`` so the job checkpoints every ``checkpoint_frequency``
epochs, and on a crash restarts the fit resuming from the latest VALID
checkpoint (``has_checkpoint`` refuses partially written directories),
up to ``max_restarts`` times with backoff.

Two delegation modes, chosen by the model's comm path:

- ``comm='jax'`` — delegate to ``SparkModel.fit``'s native checkpointed
  path, which carries optimizer state AND (sync+epoch) the per-worker
  weight stacks across chunks, so a crash-resume run merges exactly like
  an uninterrupted one.
- host paths — the supervisor chunks epochs itself: fit ``chunk`` epochs,
  snapshot the master weights, repeat; resume restores weights and the
  epoch cursor. (Host-path optimizer state lives in throwaway per-worker
  replicas, so weights + epoch IS the whole resumable state.)

Every lifecycle transition is recorded as a :class:`SupervisorEvent`
(``events`` list + optional ``on_event`` callback) so tests and operators
can see exactly what the recovery did.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.checkpoint import has_checkpoint, load_checkpoint, save_checkpoint
from .policy import RetryPolicy


class SupervisorAborted(RuntimeError):
    """The restart budget is spent (or the error was not restartable);
    ``__cause__`` is the final crash."""


@dataclass
class SupervisorEvent:
    """One lifecycle transition: ``kind`` in ``{"start", "resume", "crash",
    "complete"}``, the restart count when it happened, and free-form
    detail (crash repr, resume epoch)."""

    kind: str
    restarts: int
    detail: str = ""
    info: Dict[str, Any] = field(default_factory=dict)


class TrainingSupervisor:
    """Run ``SparkModel.fit`` to completion across crashes.

    ``restart_policy`` is consulted only for pacing (``delay``/``sleep``)
    between restarts — the budget is ``max_restarts``, not the policy's
    attempt cap. By default restarts are immediate (tests shouldn't wait);
    production callers pass a backoff so a crash-looping job doesn't spin.

    ``should_restart`` filters crashes: anything it rejects aborts
    immediately. The default restarts every ``Exception`` —
    ``KeyboardInterrupt``/``SystemExit`` propagate regardless.
    """

    def __init__(self, model, checkpoint_dir: str, *,
                 checkpoint_frequency: int = 1,
                 max_restarts: int = 3,
                 restart_policy: Optional[RetryPolicy] = None,
                 should_restart: Callable[[BaseException], bool] = lambda e: True,
                 on_event: Optional[Callable[[SupervisorEvent], None]] = None):
        if checkpoint_frequency < 1:
            raise ValueError("checkpoint_frequency must be >= 1")
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        self.model = model
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_frequency = int(checkpoint_frequency)
        self.max_restarts = int(max_restarts)
        self.restart_policy = restart_policy or RetryPolicy(
            base_delay_s=0.0, jitter=0.0
        )
        self.should_restart = should_restart
        self.on_event = on_event
        self.restarts = 0
        self.events: List[SupervisorEvent] = []

    def _emit(self, kind: str, detail: str = "", **info) -> None:
        event = SupervisorEvent(kind, self.restarts, detail, dict(info))
        self.events.append(event)
        if self.on_event is not None:
            self.on_event(event)

    def fit(self, rdd, epochs: int = 10, **fit_kwargs) -> None:
        """Train to ``epochs`` total epochs, surviving up to
        ``max_restarts`` crashes. Raises :class:`SupervisorAborted` when
        the budget runs out."""
        self._supervise(
            lambda resume: self._run_fit(rdd, epochs, resume, fit_kwargs),
            {"epochs": epochs},
        )

    def fit_stream(self, batches, trainer, *, publisher=None,
                   checkpoint_every: Optional[int] = None) -> None:
        """Drain a finite micro-batch stream through ``trainer``
        (:class:`~elephas_tpu.streaming.trainer.StreamTrainer`), surviving
        crashes the same way :meth:`fit` does. The checkpoint carries the
        CURSOR (batches consumed) plus the publisher's JSON state and the
        current PS master weights; on resume, already-committed batches
        are skipped — exactly-once consumption — so the parameter server's
        version history (and therefore the publisher's publish/rollback
        history) replays deterministically at a fixed seed. The PS itself
        is assumed to outlive the driver-side crash (it holds the
        authoritative weights); the checkpointed weights exist for the
        cold-restart case where the PS must be reseeded too.

        ``checkpoint_every`` defaults to ``checkpoint_frequency``
        (commits, not epochs, in this mode)."""
        batches = list(batches)
        every = (self.checkpoint_frequency if checkpoint_every is None
                 else int(checkpoint_every))
        if every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        self._supervise(
            lambda resume: self._run_stream(batches, trainer, publisher,
                                            every, resume),
            {"batches": len(batches)},
        )

    def _supervise(self, attempt: Callable[[bool], None],
                   complete_info: Dict[str, Any]) -> None:
        while True:
            resume = has_checkpoint(self.checkpoint_dir)
            self._emit(
                "resume" if resume else "start",
                detail=self.checkpoint_dir if resume else "",
            )
            try:
                attempt(resume)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as err:
                if not self.should_restart(err):
                    raise SupervisorAborted(
                        f"crash not restartable: {err!r}"
                    ) from err
                if self.restarts >= self.max_restarts:
                    raise SupervisorAborted(
                        f"restart budget ({self.max_restarts}) exhausted; "
                        f"last crash: {err!r}"
                    ) from err
                self._emit("crash", detail=repr(err))
                pause = self.restart_policy.delay(self.restarts)
                self.restarts += 1
                if pause > 0.0:
                    self.restart_policy.sleep(pause)
                continue
            self._emit("complete", **complete_info)
            return

    # -- one attempt ------------------------------------------------------
    def _run_fit(self, rdd, epochs: int, resume: bool,
                 fit_kwargs: Dict[str, Any]) -> None:
        if getattr(self.model, "comm", None) == "jax":
            self.model.fit(
                rdd, epochs=epochs,
                checkpoint_dir=self.checkpoint_dir,
                checkpoint_frequency=self.checkpoint_frequency,
                resume=resume, **fit_kwargs,
            )
            return
        self._run_fit_host(rdd, epochs, resume, fit_kwargs)

    def _run_fit_host(self, rdd, epochs: int, resume: bool,
                      fit_kwargs: Dict[str, Any]) -> None:
        network = self.model.master_network
        start_epoch = 0
        if resume:
            weights, meta, _ = load_checkpoint(self.checkpoint_dir)
            network.set_weights(weights)
            start_epoch = int(meta.get("epoch", 0))
        epoch = start_epoch
        while epoch < epochs:
            chunk = min(self.checkpoint_frequency, epochs - epoch)
            self.model.fit(rdd, epochs=chunk, **fit_kwargs)
            epoch += chunk
            save_checkpoint(
                self.checkpoint_dir,
                [np.asarray(w) for w in network.get_weights()],
                {"epoch": epoch, "epochs": epochs, "mode": self.model.mode},
            )

    # -- one streaming attempt --------------------------------------------
    def _run_stream(self, batches, trainer, publisher, every: int,
                    resume: bool) -> None:
        start = 0
        if resume:
            _weights, meta, _ = load_checkpoint(self.checkpoint_dir)
            stream = meta.get("stream", {})
            start = int(stream.get("batches_done", 0))
            trainer.commits = int(stream.get("commits", trainer.commits))
            if publisher is not None and stream.get("publisher") is not None:
                publisher.load_state_dict(stream["publisher"],
                                          weights=_weights)
        done = start
        for i, batch in enumerate(batches):
            if i < start:
                continue  # committed before the crash: never re-applied
            commit = trainer.step(batch, index=i)
            if publisher is not None:
                publisher.offer(commit)
            done = i + 1
            if done % every == 0:
                self._checkpoint_stream(trainer, publisher, done)
        if done % every != 0 or done == start:
            self._checkpoint_stream(trainer, publisher, done)

    def _checkpoint_stream(self, trainer, publisher, done: int) -> None:
        weights = [np.asarray(w) for w in trainer.client.get_parameters()]
        meta: Dict[str, Any] = {
            "mode": "stream",
            "stream": {
                "batches_done": int(done),
                "commits": int(trainer.commits),
                "publisher": (None if publisher is None
                              else publisher.state_dict()),
            },
        }
        save_checkpoint(self.checkpoint_dir, weights, meta)
