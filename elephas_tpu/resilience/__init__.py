"""Resilience subsystem: failure as a first-class, testable input.

The paper's asynchronous/hogwild modes make worker failure and staleness a
*normal* operating condition (DeepSpark, arxiv 1602.08191, scales commodity
clusters only by tolerating stragglers and partial failure; SparkNet, arxiv
1511.06051, leans on iterative re-execution). This package makes that
condition injectable, policed, and recoverable across BOTH pipelines:

- :mod:`~elephas_tpu.resilience.faults` — ``FaultPlan``: a seeded,
  deterministic fault-injection layer. It wraps parameter clients
  (``FaultyClient``: dropped/duplicated pushes, delayed pulls, transient
  socket errors, crash-after-N-pushes), worker partitions
  (``maybe_crash_partition``: kill a worker mid-partition, once), compiled
  fit chunks (``tick``), parameter servers (server-side drop hooks), and
  serving steps (deterministic clock stalls that push requests past their
  deadlines). Same seed → same faults, so chaos scenarios are pinnable
  tests, not flakes.
- :mod:`~elephas_tpu.resilience.policy` — composable ``RetryPolicy``
  (exponential backoff + deterministic jitter, attempt caps, deadlines)
  and ``CircuitBreaker`` (closed → open → half-open), plus
  ``ResilientClient``, which routes any
  :class:`~elephas_tpu.parameter.client.BaseParameterClient`'s pulls and
  pushes through both.
- :mod:`~elephas_tpu.resilience.soak` — the randomized cross-stack chaos
  soak: each seeded schedule draws a random COMBINATION of fault rates
  (logical + wire-level under the checksummed socket framing) and applies
  it to a composed stack — sync/async/hogwild fit, streaming
  train-to-serve, the trace-driven fleet — with a global invariant check
  per run (``run_soak``; pinned in ``tests/resilience/test_soak.py``).
- :mod:`~elephas_tpu.resilience.supervisor` — ``TrainingSupervisor``:
  wraps ``SparkModel.fit`` with periodic checkpointing
  (:mod:`elephas_tpu.utils.checkpoint`) and auto-resume from the latest
  VALID checkpoint after a crash, bounded by ``max_restarts``. Task-level
  failures stay with the existing stage-scoped exactly-once machinery
  (``worker.py`` / ``parameter/client.py``); the supervisor handles the
  layer above it — whole-fit death.

Serving-side resilience (per-request deadlines, ``cancel(request_id)``,
O(1) slot reclamation on timeout, bounded result retention) lives in
:mod:`elephas_tpu.serving.engine`; the chaos scenarios for all of it are
pinned in ``tests/resilience/``.
"""

from .faults import (
    FaultPlan,
    FaultyClient,
    InjectedFault,
    InjectedWorkerCrash,
    TransientFault,
)
from .membership import (
    HeartbeatRegistry,
    MembershipEvent,
    QuorumLostError,
    QuorumRunner,
    member_id_for,
)
from .policy import (
    CircuitBreaker,
    CircuitOpenError,
    FailoverClient,
    ResilientClient,
    RetryExhausted,
    RetryPolicy,
    default_is_transient,
)
from .soak import (
    SCENARIOS,
    SoakInvariantViolation,
    draw_fault_kwargs,
    run_schedule,
    run_soak,
)
from .supervisor import SupervisorAborted, SupervisorEvent, TrainingSupervisor

__all__ = [
    "SCENARIOS",
    "SoakInvariantViolation",
    "draw_fault_kwargs",
    "run_schedule",
    "run_soak",
    "CircuitBreaker",
    "CircuitOpenError",
    "FailoverClient",
    "FaultPlan",
    "FaultyClient",
    "HeartbeatRegistry",
    "InjectedFault",
    "InjectedWorkerCrash",
    "MembershipEvent",
    "QuorumLostError",
    "QuorumRunner",
    "ResilientClient",
    "RetryExhausted",
    "RetryPolicy",
    "SupervisorAborted",
    "SupervisorEvent",
    "TrainingSupervisor",
    "default_is_transient",
    "member_id_for",
]
