"""Seeded, deterministic fault injection.

Every fault decision is a pure function of ``(seed, site, per-site call
index)`` via a keyed hash — NOT a shared RNG stream — so concurrent workers
cannot reorder each other's faults: whatever thread interleaving happens,
the Nth push on a given site sees the same verdict on every run. That is
what turns chaos scenarios into pinnable tests (``tests/resilience/``)
instead of flakes.

Injection points, one per layer the tentpole names:

- **parameter clients** — :class:`FaultyClient` wraps any
  :class:`~elephas_tpu.parameter.client.BaseParameterClient`: pushes can be
  dropped (delta lost in the network, at-most-once), duplicated
  (retransmit, at-least-once), or fail with a :class:`TransientFault`
  (a ``ConnectionError``, so retry policies treat it as real); pulls can be
  delayed or fail transiently; and a worker can be killed after its Nth
  push (``crash_partition``/``crash_after_pushes``) — the async
  "crash mid-partition" that exercises the server's attempt rollback.
- **worker partitions** — :meth:`FaultPlan.maybe_crash_partition` kills a
  synchronous worker mid-partition (work done, result lost), once, on
  attempt 0, driving the facade's Spark-parity task retry.
- **compiled fit chunks** — :meth:`FaultPlan.tick` raises at a configured
  per-site call index (e.g. ``{"fit_chunk": 2}`` kills the 3rd epoch chunk
  of a checkpointed ``_fit_jax``), once — the whole-fit death the
  :class:`~elephas_tpu.resilience.supervisor.TrainingSupervisor` recovers
  from.
- **parameter servers** — :meth:`FaultPlan.drop_server_push` /
  :meth:`FaultPlan.delay_server_pull`, consulted by
  ``BaseParameterServer`` when constructed with ``fault_plan=``.
- **serving steps** — :meth:`FaultPlan.serving_stall` injects deterministic
  wall-clock stalls by engine step index; the ``ServingEngine`` adds them
  to its clock reading, pushing slow requests past their deadlines.
- **host processes** — :meth:`FaultPlan.host_kill` /
  :meth:`FaultPlan.host_partition` / :meth:`FaultPlan.join_delay` drive the
  elastic control plane (``parallel/elastic.py``): a real ``SIGKILL`` of a
  host process mid-round, a one-sided heartbeat-channel partition (the
  zombie keeps computing; its delta must be fenced), and a deferred
  admission of a freshly spawned host (late join). All exact round→host
  maps, so membership-event traces pin at fixed seed.
- **the wire itself** — :meth:`FaultPlan.wrap_socket` returns a
  :class:`FaultySocket` shim that mutates outbound FRAMES (never 1-byte
  opcodes/acks or the 5-byte negotiation hello, so every injected
  corruption lands in checksummed frame bytes): ``wire_flip_bits`` XORs
  one deterministic bit, ``wire_garbage`` overwrites the frame head with
  junk, ``wire_truncate`` sends a prefix then closes, ``wire_duplicate``
  sends the frame twice, and ``wire_stall_s``/``wire_stall_prob`` sleep
  mid-frame (the slow-loris). Per-frame verdicts are seeded and
  per-opportunity like every other site; fires are counted in ``fired``
  and every typed catch the stack reports lands in ``wire_caught`` — the
  soak's "corruption is caught, never applied" ledger.

Faults fire AT MOST ONCE per crash site (``fired``/``crash_fired``
bookkeeping), so retries and supervisor restarts proceed — the injected
failure is a crash, not a curse.
"""

from __future__ import annotations

import hashlib
import socket as socket_mod
import threading
import time
from typing import Callable, Dict, Iterable, Optional, Tuple

from ..parameter.client import BaseParameterClient


class InjectedFault(Exception):
    """Base class for every injected failure (mixed into concrete types so
    ``except InjectedFault`` can tell chaos from genuine breakage)."""


class TransientFault(ConnectionError, InjectedFault):
    """An injected transient network error. Subclasses ``ConnectionError``
    so retry policies and generic handlers treat it like the real thing."""


class InjectedWorkerCrash(RuntimeError, InjectedFault):
    """An injected worker/partition death (task retry should absorb it)."""


def _unit(seed: int, site: str, n: int) -> float:
    """Deterministic uniform draw in [0, 1) keyed by (seed, site, n)."""
    digest = hashlib.blake2b(
        f"{seed}:{site}:{n}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") / 2.0**64


class FaultPlan:
    """One seeded plan of everything that will go wrong.

    Rates are probabilities per opportunity; crash sites are exact call
    indices. All counters are thread-safe, and every decision depends only
    on the plan's seed and the per-site opportunity index, never on global
    RNG state or other sites' traffic.
    """

    def __init__(self, seed: int = 0, *,
                 drop_push: float = 0.0,
                 dup_push: float = 0.0,
                 push_error_rate: float = 0.0,
                 pull_error_rate: float = 0.0,
                 pull_delay_s: float = 0.0,
                 pull_delay_prob: float = 0.0,
                 crash_partition: Optional[int] = None,
                 crash_after_pushes: int = 0,
                 crash_sites: Optional[Dict[str, int]] = None,
                 dead_partitions: Optional[Iterable[int]] = None,
                 straggler_stalls: Optional[Dict[int, float]] = None,
                 server_drop_push: float = 0.0,
                 server_pull_delay_s: float = 0.0,
                 serving_stalls: Optional[Dict[int, float]] = None,
                 kill_hosts: Optional[Dict[int, int]] = None,
                 partition_hosts: Optional[Dict[int, int]] = None,
                 join_delay_rounds: Optional[Dict[int, int]] = None,
                 wire_flip_bits: float = 0.0,
                 wire_truncate: float = 0.0,
                 wire_garbage: float = 0.0,
                 wire_duplicate: float = 0.0,
                 wire_stall_s: float = 0.0,
                 wire_stall_prob: float = 0.0,
                 sleep: Callable[[float], None] = time.sleep):
        self.seed = int(seed)
        self.drop_push = float(drop_push)
        self.dup_push = float(dup_push)
        self.push_error_rate = float(push_error_rate)
        self.pull_error_rate = float(pull_error_rate)
        self.pull_delay_s = float(pull_delay_s)
        self.pull_delay_prob = float(pull_delay_prob)
        self.crash_partition = crash_partition
        self.crash_after_pushes = int(crash_after_pushes)
        self.crash_sites = dict(crash_sites or {})
        # Partitions that die on EVERY attempt (machine gone, not task flake):
        # the elastic-quorum scenario — retries are futile and the membership
        # layer must expire these members and commit without them.
        self.dead_partitions = frozenset(
            int(p) for p in (dead_partitions or ())
        )
        # Deterministic straggler injection: partition -> seconds stalled at
        # the start of attempt 0 (backup attempts run at full speed, so
        # first-finish-wins has a winner).
        self.straggler_stalls = {
            int(p): float(s) for p, s in (straggler_stalls or {}).items()
        }
        self.server_drop_push = float(server_drop_push)
        self.server_pull_delay_s = float(server_pull_delay_s)
        self.serving_stalls = dict(serving_stalls or {})
        # Host-level crash sites for the elastic control plane
        # (parallel/elastic.py) — exact round→host maps, like crash_sites:
        # kill_hosts SIGKILLs a host PROCESS mid-round; partition_hosts cuts
        # a host's heartbeat channel (the worker stays alive and keeps
        # computing — a zombie whose delta must be fenced); join_delay_rounds
        # is host→rounds a spawned host's admission is deferred (late join).
        self.kill_hosts = {
            int(r): int(h) for r, h in (kill_hosts or {}).items()
        }
        self.partition_hosts = {
            int(r): int(h) for r, h in (partition_hosts or {}).items()
        }
        self.join_delay_rounds = {
            int(h): int(d) for h, d in (join_delay_rounds or {}).items()
        }
        # Wire-level sites (per outbound frame, through wrap_socket's shim).
        # Rates are per-frame probabilities; wire_stall_s is the injected
        # mid-frame sleep, gated by wire_stall_prob.
        self.wire_flip_bits = float(wire_flip_bits)
        self.wire_truncate = float(wire_truncate)
        self.wire_garbage = float(wire_garbage)
        self.wire_duplicate = float(wire_duplicate)
        self.wire_stall_s = float(wire_stall_s)
        self.wire_stall_prob = float(wire_stall_prob)
        self.sleep = sleep
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._push_counts: Dict[Tuple[int, int], int] = {}
        self.fired: Dict[str, int] = {}      # site -> call index it fired at
        # (wire sites record a FIRE COUNT per "<kind>:<site>" key instead of
        # a call index — a rate site can fire many times)
        # Catches reported back by the stack (client/server/elastic pool):
        # exception type name -> count. Together with `fired` this is the
        # soak's corruption ledger: injected corruption must show up HERE,
        # never in the applied weights.
        self.wire_caught: Dict[str, int] = {}

    # -- the decision primitive ------------------------------------------
    def decide(self, site: str, rate: float) -> bool:
        """Consume one opportunity at ``site``; True with probability
        ``rate``, deterministically in the site's opportunity index."""
        if rate <= 0.0:
            # still consume the index so enabling a rate later keeps other
            # sites' sequences unchanged? No: a zero rate must be free, or
            # composing plans changes unrelated decision streams.
            return False
        with self._lock:
            n = self._counters.get(site, 0)
            self._counters[site] = n + 1
        return _unit(self.seed, site, n) < rate

    # -- client-side faults ----------------------------------------------
    def pull_fault(self) -> None:
        """Apply the pull-side faults: optional delay, optional transient
        error (raised BEFORE the pull reaches the wire)."""
        if self.pull_delay_s > 0.0 and (
            self.pull_delay_prob >= 1.0
            or self.decide("pull_delay", self.pull_delay_prob)
        ):
            self.sleep(self.pull_delay_s)
        if self.decide("pull_error", self.pull_error_rate):
            raise TransientFault("injected transient pull failure")

    def push_fault(self) -> str:
        """Verdict for one push: ``"ok"`` | ``"drop"`` | ``"dup"``; raises
        :class:`TransientFault` for an injected wire error."""
        if self.decide("push_error", self.push_error_rate):
            raise TransientFault("injected transient push failure")
        if self.decide("drop_push", self.drop_push):
            return "drop"
        if self.decide("dup_push", self.dup_push):
            return "dup"
        return "ok"

    # -- worker crashes --------------------------------------------------
    def record_push(self, ctx) -> None:
        """Count one push for ``ctx``'s (partition, attempt); kill the
        worker once ``crash_after_pushes`` pushes have gone through
        (attempt 0 of ``crash_partition`` only, at most once)."""
        if ctx is None or self.crash_partition is None:
            return
        if ctx.partitionId() != self.crash_partition or ctx.attemptNumber():
            return
        with self._lock:
            key = (ctx.partitionId(), ctx.attemptNumber())
            n = self._push_counts.get(key, 0) + 1
            self._push_counts[key] = n
            site = f"crash-partition-{self.crash_partition}"
            if n <= self.crash_after_pushes or site in self.fired:
                return
            self.fired[site] = n
        raise InjectedWorkerCrash(
            f"injected crash of partition {ctx.partitionId()} after "
            f"{self.crash_after_pushes} push(es)"
        )

    def maybe_crash_partition(self, ctx) -> None:
        """Kill the worker for ``crash_partition`` mid-partition (attempt 0
        only, at most once) — the synchronous-path crash, placed by the
        worker AFTER local training so the computed delta is genuinely
        lost and must be recomputed by the retry.

        ``dead_partitions`` members die here too, on EVERY attempt — a
        permanently lost machine rather than a one-off task flake. Those
        crashes are what the quorum path must commit around.
        """
        if ctx is None:
            return
        if ctx.partitionId() in self.dead_partitions:
            with self._lock:
                site = f"dead-partition-{ctx.partitionId()}"
                self.fired[site] = self.fired.get(site, -1) + 1
            raise InjectedWorkerCrash(
                f"injected permanent death of partition {ctx.partitionId()} "
                f"(attempt {ctx.attemptNumber()})"
            )
        if self.crash_partition is None:
            return
        if ctx.partitionId() != self.crash_partition or ctx.attemptNumber():
            return
        site = f"crash-partition-{self.crash_partition}"
        with self._lock:
            if site in self.fired:
                return
            self.fired[site] = 0
        raise InjectedWorkerCrash(
            f"injected mid-partition crash of partition {ctx.partitionId()}"
        )

    def straggler_stall(self, ctx) -> None:
        """Stall the worker for a ``straggler_stalls`` partition at the start
        of attempt 0 — deterministic slow-node injection. Backup attempts
        (attempt > 0) are NOT stalled, so a launched backup clone finishes
        first and first-finish-wins has a deterministic winner."""
        if ctx is None or not self.straggler_stalls:
            return
        if ctx.attemptNumber():
            return
        stall = self.straggler_stalls.get(ctx.partitionId())
        if stall:
            with self._lock:
                self.fired[f"straggle-partition-{ctx.partitionId()}"] = 0
            self.sleep(stall)

    # -- coarse crash points (fit chunks, arbitrary sites) ---------------
    def tick(self, site: str) -> None:
        """Count one call to ``site``; raise :class:`InjectedWorkerCrash`
        at the call index configured in ``crash_sites`` (0-based), once."""
        with self._lock:
            n = self._counters.get(f"tick:{site}", 0)
            self._counters[f"tick:{site}"] = n + 1
            target = self.crash_sites.get(site)
            if target is None or n != target or site in self.fired:
                return
            self.fired[site] = n
        raise InjectedWorkerCrash(
            f"injected crash at {site!r} call {n}"
        )

    # -- host-level sites (elastic control plane) ------------------------
    def host_kill(self, round_index: int) -> Optional[int]:
        """Host id to SIGKILL at round ``round_index`` (at most once per
        host site), or None. The elastic pool consults this right after
        issuing the round — the death is mid-round by construction."""
        host = self.kill_hosts.get(int(round_index))
        if host is None:
            return None
        site = f"kill-host-{host}"
        with self._lock:
            if site in self.fired:
                return None
            self.fired[site] = int(round_index)
        return int(host)

    def host_partition(self, round_index: int) -> Optional[int]:
        """Host whose heartbeat channel is cut starting at ``round_index``
        (at most once per host site), or None. The partition is one-sided
        and permanent: the host keeps computing and sending, the control
        plane stops hearing it — lease expiry does the rest."""
        host = self.partition_hosts.get(int(round_index))
        if host is None:
            return None
        site = f"partition-host-{host}"
        with self._lock:
            if site in self.fired:
                return None
            self.fired[site] = int(round_index)
        return int(host)

    def join_delay(self, host_id: int) -> int:
        """Rounds to defer admission of a freshly spawned ``host_id``."""
        delay = int(self.join_delay_rounds.get(int(host_id), 0))
        if delay > 0:
            with self._lock:
                self.fired.setdefault(f"delay-join-host-{int(host_id)}",
                                      delay)
        return delay

    # -- server-side hooks -----------------------------------------------
    def drop_server_push(self) -> bool:
        """True = the server should silently discard this delta (the push
        'arrived' but its application is lost)."""
        return self.decide("server_drop_push", self.server_drop_push)

    def delay_server_pull(self) -> None:
        if self.server_pull_delay_s > 0.0:
            self.sleep(self.server_pull_delay_s)

    # -- serving ----------------------------------------------------------
    def serving_stall(self, step_index: int) -> float:
        """Seconds of injected wall-clock stall at engine step
        ``step_index`` (deterministic: an explicit step → seconds map)."""
        return float(self.serving_stalls.get(int(step_index), 0.0))

    # -- wire-level faults (byte-level, through wrap_socket) ---------------
    #: sendall payloads at or below this many bytes are control traffic
    #: (1-byte opcodes, the 5-byte negotiation hello, 1/4-byte acks) and
    #: pass through untouched — every injected corruption therefore lands
    #: inside a FRAME (v2 header 18B + payload, legacy header 20B), which
    #: is what makes "every flip is caught by the framing layer" provable.
    _WIRE_CONTROL_MAX = 16

    def has_wire_faults(self) -> bool:
        """True when any wire-level site could fire (wrap_socket is then
        worth the shim; otherwise it returns the socket unwrapped)."""
        return (self.wire_flip_bits > 0.0 or self.wire_truncate > 0.0
                or self.wire_garbage > 0.0 or self.wire_duplicate > 0.0
                or (self.wire_stall_s > 0.0 and self.wire_stall_prob > 0.0))

    def wrap_socket(self, sock, site: str):
        """Wrap ``sock`` so outbound frames pass through this plan's wire
        sites. Returns ``sock`` unchanged when no wire site is active."""
        if not self.has_wire_faults():
            return sock
        return FaultySocket(sock, self, str(site))

    def note_wire_caught(self, where: str, err: BaseException) -> None:
        """The stack caught a typed frame error: record it in the ledger
        (keyed ``where:ExceptionType``). Called by ``SocketClient``,
        ``SocketServer``, and the elastic pool's readers."""
        key = f"{where}:{type(err).__name__}"
        with self._lock:
            self.wire_caught[key] = self.wire_caught.get(key, 0) + 1

    def wire_caught_total(self) -> int:
        return sum(self.wire_caught.values())

    def wire_fired_total(self, kinds: Tuple[str, ...] = (
            "wire_flip_bits", "wire_garbage", "wire_truncate")) -> int:
        """Total fires across sites for the given wire kinds (default: the
        CORRUPTING kinds — duplicates and stalls don't damage a frame)."""
        with self._lock:
            return sum(count for site, count in self.fired.items()
                       if site.split(":", 1)[0] in kinds)

    def _record_wire_fire(self, kind: str, site: str) -> int:
        """Count one fire of ``kind`` at ``site``; returns the 0-based fire
        index (seeds the deterministic mutation position draws)."""
        key = f"{kind}:{site}"
        with self._lock:
            n = self.fired.get(key, 0)
            self.fired[key] = n + 1
        return n

    def wire_send(self, sock, data: bytes, site: str) -> None:
        """Send ``data`` through the wire-fault sites (the FaultySocket
        sendall path). Control-sized payloads pass through untouched; for
        frames, every active kind draws one seeded per-opportunity verdict
        (all streams advance every frame, so enabling one kind never
        re-orders another's), and the first destructive verdict wins."""
        if len(data) <= self._WIRE_CONTROL_MAX:
            sock.sendall(data)
            return
        stall = (self.wire_stall_s > 0.0
                 and self.decide(f"wire_stall:{site}", self.wire_stall_prob))
        verdict = None
        for kind, rate in (("wire_truncate", self.wire_truncate),
                           ("wire_garbage", self.wire_garbage),
                           ("wire_flip_bits", self.wire_flip_bits),
                           ("wire_duplicate", self.wire_duplicate)):
            if self.decide(f"{kind}:{site}", rate) and verdict is None:
                verdict = kind
        if stall:
            self._record_wire_fire("wire_stall", site)

        def emit(payload: bytes) -> None:
            if stall:
                cut = max(1, len(payload) // 2)
                sock.sendall(payload[:cut])
                self.sleep(self.wire_stall_s)
                sock.sendall(payload[cut:])
            else:
                sock.sendall(payload)

        if verdict is None:
            emit(data)
            return
        n = self._record_wire_fire(verdict, site)
        if verdict == "wire_truncate":
            # Prefix then hard close: the peer sees EOF mid-frame. The
            # caller believes the send succeeded (like a real network cut —
            # the sender learns on its NEXT operation) and reconnects then.
            cut = 1 + int(_unit(self.seed, f"wire_truncate_cut:{site}", n)
                          * (len(data) - 1))
            try:
                sock.sendall(data[:cut])
                sock.shutdown(socket_mod.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
            return
        mutated = bytearray(data)
        if verdict == "wire_garbage":
            # Overwrite the frame head with deterministic junk. Byte 0 is
            # forced to 0xFF — neither v2 magic nor an ASCII digit — so the
            # receiver types it immediately and quarantines the connection
            # (the rest of the mutated stream is never parsed).
            junk = hashlib.blake2b(
                f"{self.seed}:wire_garbage_bytes:{site}:{n}".encode(),
                digest_size=32,
            ).digest()
            span = min(len(junk), len(mutated))
            mutated[:span] = junk[:span]
            mutated[0] = 0xFF
        elif verdict == "wire_flip_bits":
            pos = int(_unit(self.seed, f"wire_flip_pos:{site}", n)
                      * len(mutated))
            bit = int(_unit(self.seed, f"wire_flip_bit:{site}", n) * 8)
            mutated[pos] ^= 1 << bit
        elif verdict == "wire_duplicate":
            emit(bytes(mutated))  # the original ...
        emit(bytes(mutated))      # ... and the (possibly mutated) frame


class FaultySocket:
    """A socket shim that routes outbound bytes through a
    :class:`FaultPlan`'s wire sites (:meth:`FaultPlan.wire_send`).

    Sits UNDER the framing layer: ``sendall`` is intercepted, everything
    else (``recv``, ``recv_into``, ``settimeout``, ``close``, …) delegates
    to the wrapped socket, so ``utils.sockets``' send/receive and the
    stall-deadline save/restore work unchanged. Wrapping only the sender
    side of each direction covers the whole wire: the client's shim
    corrupts client→server frames (caught by the server), the server's
    shim corrupts replies (caught by the client).
    """

    def __init__(self, sock, plan: FaultPlan, site: str):
        self._sock = sock
        self._plan = plan
        self._site = site

    def sendall(self, data) -> None:
        self._plan.wire_send(self._sock, bytes(data), self._site)

    def __getattr__(self, name):
        return getattr(self._sock, name)


class FaultyClient(BaseParameterClient):
    """Wrap a parameter client with a :class:`FaultPlan`.

    Sits at the transport layer: whatever stacks above it (compression,
    :class:`~elephas_tpu.resilience.policy.ResilientClient` retries) sees
    injected faults exactly as it would see real network ones. Dropped
    pushes report success to the caller — the delta is lost in flight, the
    worker never knows, which is precisely the failure mode async training
    must converge through.
    """

    def __init__(self, inner: BaseParameterClient, plan: FaultPlan):
        self.inner = inner
        self.plan = plan

    def _task_ctx(self):
        from ..data import TaskContext

        return TaskContext.get()

    def get_parameters(self):
        self.plan.pull_fault()
        return self.inner.get_parameters()

    def _push(self, do_push: Callable[[], None]) -> None:
        self.plan.record_push(self._task_ctx())
        verdict = self.plan.push_fault()
        if verdict == "drop":
            return
        do_push()
        if verdict == "dup":
            do_push()

    def update_parameters(self, delta) -> None:
        self._push(lambda: self.inner.update_parameters(delta))

    def update_parameters_tagged(self, task_id: str, delta,
                                 attempt=None) -> None:
        # Forward the attempt tag only when set: plain two-arg inner clients
        # (and pre-fencing fakes in tests) keep working unchanged.
        if attempt is None:
            self._push(
                lambda: self.inner.update_parameters_tagged(task_id, delta)
            )
        else:
            self._push(
                lambda: self.inner.update_parameters_tagged(
                    task_id, delta, attempt=attempt
                )
            )

    def get_version(self) -> int:
        return self.inner.get_version()

    def register_attempt(self, task_id: str, attempt: int) -> bool:
        return self.inner.register_attempt(task_id, attempt)

    def commit_attempt(self, task_id: str) -> None:
        self.inner.commit_attempt(task_id)

    def close(self) -> None:
        self.inner.close()
