"""Seeded, deterministic fault injection.

Every fault decision is a pure function of ``(seed, site, per-site call
index)`` via a keyed hash — NOT a shared RNG stream — so concurrent workers
cannot reorder each other's faults: whatever thread interleaving happens,
the Nth push on a given site sees the same verdict on every run. That is
what turns chaos scenarios into pinnable tests (``tests/resilience/``)
instead of flakes.

Injection points, one per layer the tentpole names:

- **parameter clients** — :class:`FaultyClient` wraps any
  :class:`~elephas_tpu.parameter.client.BaseParameterClient`: pushes can be
  dropped (delta lost in the network, at-most-once), duplicated
  (retransmit, at-least-once), or fail with a :class:`TransientFault`
  (a ``ConnectionError``, so retry policies treat it as real); pulls can be
  delayed or fail transiently; and a worker can be killed after its Nth
  push (``crash_partition``/``crash_after_pushes``) — the async
  "crash mid-partition" that exercises the server's attempt rollback.
- **worker partitions** — :meth:`FaultPlan.maybe_crash_partition` kills a
  synchronous worker mid-partition (work done, result lost), once, on
  attempt 0, driving the facade's Spark-parity task retry.
- **compiled fit chunks** — :meth:`FaultPlan.tick` raises at a configured
  per-site call index (e.g. ``{"fit_chunk": 2}`` kills the 3rd epoch chunk
  of a checkpointed ``_fit_jax``), once — the whole-fit death the
  :class:`~elephas_tpu.resilience.supervisor.TrainingSupervisor` recovers
  from.
- **parameter servers** — :meth:`FaultPlan.drop_server_push` /
  :meth:`FaultPlan.delay_server_pull`, consulted by
  ``BaseParameterServer`` when constructed with ``fault_plan=``.
- **serving steps** — :meth:`FaultPlan.serving_stall` injects deterministic
  wall-clock stalls by engine step index; the ``ServingEngine`` adds them
  to its clock reading, pushing slow requests past their deadlines.
- **host processes** — :meth:`FaultPlan.host_kill` /
  :meth:`FaultPlan.host_partition` / :meth:`FaultPlan.join_delay` drive the
  elastic control plane (``parallel/elastic.py``): a real ``SIGKILL`` of a
  host process mid-round, a one-sided heartbeat-channel partition (the
  zombie keeps computing; its delta must be fenced), and a deferred
  admission of a freshly spawned host (late join). All exact round→host
  maps, so membership-event traces pin at fixed seed.

Faults fire AT MOST ONCE per crash site (``fired``/``crash_fired``
bookkeeping), so retries and supervisor restarts proceed — the injected
failure is a crash, not a curse.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Callable, Dict, Iterable, Optional, Tuple

from ..parameter.client import BaseParameterClient


class InjectedFault(Exception):
    """Base class for every injected failure (mixed into concrete types so
    ``except InjectedFault`` can tell chaos from genuine breakage)."""


class TransientFault(ConnectionError, InjectedFault):
    """An injected transient network error. Subclasses ``ConnectionError``
    so retry policies and generic handlers treat it like the real thing."""


class InjectedWorkerCrash(RuntimeError, InjectedFault):
    """An injected worker/partition death (task retry should absorb it)."""


def _unit(seed: int, site: str, n: int) -> float:
    """Deterministic uniform draw in [0, 1) keyed by (seed, site, n)."""
    digest = hashlib.blake2b(
        f"{seed}:{site}:{n}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") / 2.0**64


class FaultPlan:
    """One seeded plan of everything that will go wrong.

    Rates are probabilities per opportunity; crash sites are exact call
    indices. All counters are thread-safe, and every decision depends only
    on the plan's seed and the per-site opportunity index, never on global
    RNG state or other sites' traffic.
    """

    def __init__(self, seed: int = 0, *,
                 drop_push: float = 0.0,
                 dup_push: float = 0.0,
                 push_error_rate: float = 0.0,
                 pull_error_rate: float = 0.0,
                 pull_delay_s: float = 0.0,
                 pull_delay_prob: float = 0.0,
                 crash_partition: Optional[int] = None,
                 crash_after_pushes: int = 0,
                 crash_sites: Optional[Dict[str, int]] = None,
                 dead_partitions: Optional[Iterable[int]] = None,
                 straggler_stalls: Optional[Dict[int, float]] = None,
                 server_drop_push: float = 0.0,
                 server_pull_delay_s: float = 0.0,
                 serving_stalls: Optional[Dict[int, float]] = None,
                 kill_hosts: Optional[Dict[int, int]] = None,
                 partition_hosts: Optional[Dict[int, int]] = None,
                 join_delay_rounds: Optional[Dict[int, int]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.seed = int(seed)
        self.drop_push = float(drop_push)
        self.dup_push = float(dup_push)
        self.push_error_rate = float(push_error_rate)
        self.pull_error_rate = float(pull_error_rate)
        self.pull_delay_s = float(pull_delay_s)
        self.pull_delay_prob = float(pull_delay_prob)
        self.crash_partition = crash_partition
        self.crash_after_pushes = int(crash_after_pushes)
        self.crash_sites = dict(crash_sites or {})
        # Partitions that die on EVERY attempt (machine gone, not task flake):
        # the elastic-quorum scenario — retries are futile and the membership
        # layer must expire these members and commit without them.
        self.dead_partitions = frozenset(
            int(p) for p in (dead_partitions or ())
        )
        # Deterministic straggler injection: partition -> seconds stalled at
        # the start of attempt 0 (backup attempts run at full speed, so
        # first-finish-wins has a winner).
        self.straggler_stalls = {
            int(p): float(s) for p, s in (straggler_stalls or {}).items()
        }
        self.server_drop_push = float(server_drop_push)
        self.server_pull_delay_s = float(server_pull_delay_s)
        self.serving_stalls = dict(serving_stalls or {})
        # Host-level crash sites for the elastic control plane
        # (parallel/elastic.py) — exact round→host maps, like crash_sites:
        # kill_hosts SIGKILLs a host PROCESS mid-round; partition_hosts cuts
        # a host's heartbeat channel (the worker stays alive and keeps
        # computing — a zombie whose delta must be fenced); join_delay_rounds
        # is host→rounds a spawned host's admission is deferred (late join).
        self.kill_hosts = {
            int(r): int(h) for r, h in (kill_hosts or {}).items()
        }
        self.partition_hosts = {
            int(r): int(h) for r, h in (partition_hosts or {}).items()
        }
        self.join_delay_rounds = {
            int(h): int(d) for h, d in (join_delay_rounds or {}).items()
        }
        self.sleep = sleep
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._push_counts: Dict[Tuple[int, int], int] = {}
        self.fired: Dict[str, int] = {}      # site -> call index it fired at

    # -- the decision primitive ------------------------------------------
    def decide(self, site: str, rate: float) -> bool:
        """Consume one opportunity at ``site``; True with probability
        ``rate``, deterministically in the site's opportunity index."""
        if rate <= 0.0:
            # still consume the index so enabling a rate later keeps other
            # sites' sequences unchanged? No: a zero rate must be free, or
            # composing plans changes unrelated decision streams.
            return False
        with self._lock:
            n = self._counters.get(site, 0)
            self._counters[site] = n + 1
        return _unit(self.seed, site, n) < rate

    # -- client-side faults ----------------------------------------------
    def pull_fault(self) -> None:
        """Apply the pull-side faults: optional delay, optional transient
        error (raised BEFORE the pull reaches the wire)."""
        if self.pull_delay_s > 0.0 and (
            self.pull_delay_prob >= 1.0
            or self.decide("pull_delay", self.pull_delay_prob)
        ):
            self.sleep(self.pull_delay_s)
        if self.decide("pull_error", self.pull_error_rate):
            raise TransientFault("injected transient pull failure")

    def push_fault(self) -> str:
        """Verdict for one push: ``"ok"`` | ``"drop"`` | ``"dup"``; raises
        :class:`TransientFault` for an injected wire error."""
        if self.decide("push_error", self.push_error_rate):
            raise TransientFault("injected transient push failure")
        if self.decide("drop_push", self.drop_push):
            return "drop"
        if self.decide("dup_push", self.dup_push):
            return "dup"
        return "ok"

    # -- worker crashes --------------------------------------------------
    def record_push(self, ctx) -> None:
        """Count one push for ``ctx``'s (partition, attempt); kill the
        worker once ``crash_after_pushes`` pushes have gone through
        (attempt 0 of ``crash_partition`` only, at most once)."""
        if ctx is None or self.crash_partition is None:
            return
        if ctx.partitionId() != self.crash_partition or ctx.attemptNumber():
            return
        with self._lock:
            key = (ctx.partitionId(), ctx.attemptNumber())
            n = self._push_counts.get(key, 0) + 1
            self._push_counts[key] = n
            site = f"crash-partition-{self.crash_partition}"
            if n <= self.crash_after_pushes or site in self.fired:
                return
            self.fired[site] = n
        raise InjectedWorkerCrash(
            f"injected crash of partition {ctx.partitionId()} after "
            f"{self.crash_after_pushes} push(es)"
        )

    def maybe_crash_partition(self, ctx) -> None:
        """Kill the worker for ``crash_partition`` mid-partition (attempt 0
        only, at most once) — the synchronous-path crash, placed by the
        worker AFTER local training so the computed delta is genuinely
        lost and must be recomputed by the retry.

        ``dead_partitions`` members die here too, on EVERY attempt — a
        permanently lost machine rather than a one-off task flake. Those
        crashes are what the quorum path must commit around.
        """
        if ctx is None:
            return
        if ctx.partitionId() in self.dead_partitions:
            with self._lock:
                site = f"dead-partition-{ctx.partitionId()}"
                self.fired[site] = self.fired.get(site, -1) + 1
            raise InjectedWorkerCrash(
                f"injected permanent death of partition {ctx.partitionId()} "
                f"(attempt {ctx.attemptNumber()})"
            )
        if self.crash_partition is None:
            return
        if ctx.partitionId() != self.crash_partition or ctx.attemptNumber():
            return
        site = f"crash-partition-{self.crash_partition}"
        with self._lock:
            if site in self.fired:
                return
            self.fired[site] = 0
        raise InjectedWorkerCrash(
            f"injected mid-partition crash of partition {ctx.partitionId()}"
        )

    def straggler_stall(self, ctx) -> None:
        """Stall the worker for a ``straggler_stalls`` partition at the start
        of attempt 0 — deterministic slow-node injection. Backup attempts
        (attempt > 0) are NOT stalled, so a launched backup clone finishes
        first and first-finish-wins has a deterministic winner."""
        if ctx is None or not self.straggler_stalls:
            return
        if ctx.attemptNumber():
            return
        stall = self.straggler_stalls.get(ctx.partitionId())
        if stall:
            with self._lock:
                self.fired[f"straggle-partition-{ctx.partitionId()}"] = 0
            self.sleep(stall)

    # -- coarse crash points (fit chunks, arbitrary sites) ---------------
    def tick(self, site: str) -> None:
        """Count one call to ``site``; raise :class:`InjectedWorkerCrash`
        at the call index configured in ``crash_sites`` (0-based), once."""
        with self._lock:
            n = self._counters.get(f"tick:{site}", 0)
            self._counters[f"tick:{site}"] = n + 1
            target = self.crash_sites.get(site)
            if target is None or n != target or site in self.fired:
                return
            self.fired[site] = n
        raise InjectedWorkerCrash(
            f"injected crash at {site!r} call {n}"
        )

    # -- host-level sites (elastic control plane) ------------------------
    def host_kill(self, round_index: int) -> Optional[int]:
        """Host id to SIGKILL at round ``round_index`` (at most once per
        host site), or None. The elastic pool consults this right after
        issuing the round — the death is mid-round by construction."""
        host = self.kill_hosts.get(int(round_index))
        if host is None:
            return None
        site = f"kill-host-{host}"
        with self._lock:
            if site in self.fired:
                return None
            self.fired[site] = int(round_index)
        return int(host)

    def host_partition(self, round_index: int) -> Optional[int]:
        """Host whose heartbeat channel is cut starting at ``round_index``
        (at most once per host site), or None. The partition is one-sided
        and permanent: the host keeps computing and sending, the control
        plane stops hearing it — lease expiry does the rest."""
        host = self.partition_hosts.get(int(round_index))
        if host is None:
            return None
        site = f"partition-host-{host}"
        with self._lock:
            if site in self.fired:
                return None
            self.fired[site] = int(round_index)
        return int(host)

    def join_delay(self, host_id: int) -> int:
        """Rounds to defer admission of a freshly spawned ``host_id``."""
        delay = int(self.join_delay_rounds.get(int(host_id), 0))
        if delay > 0:
            with self._lock:
                self.fired.setdefault(f"delay-join-host-{int(host_id)}",
                                      delay)
        return delay

    # -- server-side hooks -----------------------------------------------
    def drop_server_push(self) -> bool:
        """True = the server should silently discard this delta (the push
        'arrived' but its application is lost)."""
        return self.decide("server_drop_push", self.server_drop_push)

    def delay_server_pull(self) -> None:
        if self.server_pull_delay_s > 0.0:
            self.sleep(self.server_pull_delay_s)

    # -- serving ----------------------------------------------------------
    def serving_stall(self, step_index: int) -> float:
        """Seconds of injected wall-clock stall at engine step
        ``step_index`` (deterministic: an explicit step → seconds map)."""
        return float(self.serving_stalls.get(int(step_index), 0.0))


class FaultyClient(BaseParameterClient):
    """Wrap a parameter client with a :class:`FaultPlan`.

    Sits at the transport layer: whatever stacks above it (compression,
    :class:`~elephas_tpu.resilience.policy.ResilientClient` retries) sees
    injected faults exactly as it would see real network ones. Dropped
    pushes report success to the caller — the delta is lost in flight, the
    worker never knows, which is precisely the failure mode async training
    must converge through.
    """

    def __init__(self, inner: BaseParameterClient, plan: FaultPlan):
        self.inner = inner
        self.plan = plan

    def _task_ctx(self):
        from ..data import TaskContext

        return TaskContext.get()

    def get_parameters(self):
        self.plan.pull_fault()
        return self.inner.get_parameters()

    def _push(self, do_push: Callable[[], None]) -> None:
        self.plan.record_push(self._task_ctx())
        verdict = self.plan.push_fault()
        if verdict == "drop":
            return
        do_push()
        if verdict == "dup":
            do_push()

    def update_parameters(self, delta) -> None:
        self._push(lambda: self.inner.update_parameters(delta))

    def update_parameters_tagged(self, task_id: str, delta,
                                 attempt=None) -> None:
        # Forward the attempt tag only when set: plain two-arg inner clients
        # (and pre-fencing fakes in tests) keep working unchanged.
        if attempt is None:
            self._push(
                lambda: self.inner.update_parameters_tagged(task_id, delta)
            )
        else:
            self._push(
                lambda: self.inner.update_parameters_tagged(
                    task_id, delta, attempt=attempt
                )
            )

    def get_version(self) -> int:
        return self.inner.get_version()

    def register_attempt(self, task_id: str, attempt: int) -> bool:
        return self.inner.register_attempt(task_id, attempt)

    def commit_attempt(self, task_id: str) -> None:
        self.inner.commit_attempt(task_id)

    def close(self) -> None:
        self.inner.close()
