"""Randomized cross-stack chaos soak: seeded fault schedules over COMPOSED
stacks, with a global invariant check per run.

The unit chaos scenarios (``tests/resilience/``, ``tests/fleet/``) each pin
one fault against one layer. The soak is the complement: for each schedule
a seed draws a random *combination* of fault rates over every injection
site the :class:`~elephas_tpu.resilience.faults.FaultPlan` knows — logical
(dropped/duplicated pushes, transient errors, worker crashes) AND wire-level
(bit flips, garbage, truncation, duplication, mid-frame stalls under the
checksummed framing) — and applies it to a full training or serving stack.
Every decision is a pure function of the schedule seed, so a red schedule
replays exactly: ``run_schedule(name, seed)`` is the whole repro.

Schedules rotate through five stacks:

- ``sync-fit`` — host-path synchronous ``SparkModel.fit`` with a worker
  killed mid-partition: the task retry must make the final weights
  BIT-IDENTICAL to the fault-free run at the same seed (the sync path has
  no PS wire; recomputation is exact).
- ``async-fit`` / ``hogwild-fit`` — live socket parameter server with the
  full storm (logical + wire faults): training must finish (or die with a
  TYPED error), the weights must stay finite and bounded, and every
  destructive wire fire must be CAUGHT by the checksummed framing — never
  silently applied.
- ``fit-stream`` — streaming train-to-serve with live publication through
  a recording sink: exactly-once commits (every batch committed once, in
  order), monotone non-decreasing published versions, and — because the
  driver loop is single-threaded and every fault verdict is seeded — a
  same-seed replay must be bit-identical (weights, losses, publications).
- ``fleet-replay`` — the trace-driven serving fleet with a partition
  killed and a replacement joining mid-trace, on PAGED engines: every
  request terminal, token-identical to the undisturbed baseline run, and
  exact page accounting (``kv.check()``) at the end.

Honesty notes. Async/hogwild thread interleavings reorder PS applies, so
those stacks assert invariants (finiteness, typed failure, wire ledger),
not bit-identity — that guarantee belongs to the sync and stream stacks,
whose execution IS deterministic. And the wire ledger asserts
``fired > 0 ⇒ caught > 0`` rather than ``fired == caught``: once a
corrupt frame quarantines a connection, frames already in flight behind
it die with ordinary ``ConnectionError``s (counted as fired, caught as
generic resets), and a flipped LENGTH field surfaces as a stall rather
than a checksum mismatch. The per-fire 1:1 accounting lives in the wire
fuzz unit tests (``tests/utils/test_wire_fuzz.py``); the soak's job is
the end-to-end claim — no corrupted payload is ever APPLIED, because
every applied payload passed its CRC.

Wire-faulted stacks always set ``wire_stall_timeout_s``: a flipped length
field can otherwise park a receive forever (the reader waits for bytes
the sender never promised).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from ..utils import sockets as socket_utils
from .faults import FaultPlan, InjectedFault, _unit
from .policy import RetryExhausted, RetryPolicy


class SoakInvariantViolation(AssertionError):
    """A soak run broke a cross-stack invariant (this is a real bug, not
    an acceptable typed failure)."""


#: Failures a schedule may legitimately end with: the fault plan made the
#: run impossible, and the stack said so with a NAMED error instead of
#: corrupting state or hanging. Anything outside this tuple fails the soak.
TYPED_FAILURES = (
    InjectedFault,
    socket_utils.FrameError,
    RetryExhausted,
    ConnectionError,
    TimeoutError,
)


def _check(condition: bool, message: str) -> None:
    if not condition:
        raise SoakInvariantViolation(message)


# -- schedule drawing ------------------------------------------------------

def draw_fault_kwargs(seed: int, scenario: str) -> Dict[str, Any]:
    """Seeded random fault-rate combination for one schedule.

    Pure function of ``(seed, scenario)`` via the same keyed hash the plan
    itself uses, so the schedule — not just the per-site verdicts — is
    pinned. Rates are kept in a band where most schedules complete and
    the rest die typed (the acceptance bar), not where every run is a
    retry-exhaustion trivially.
    """
    def rate(name: str, hi: float) -> float:
        return round(hi * _unit(seed, f"soak:{scenario}:{name}", 0), 4)

    kwargs: Dict[str, Any] = {
        "drop_push": rate("drop_push", 0.15),
        "dup_push": rate("dup_push", 0.10),
        "push_error_rate": rate("push_error", 0.10),
        "pull_error_rate": rate("pull_error", 0.05),
        "wire_flip_bits": rate("wire_flip", 0.06),
        "wire_garbage": rate("wire_garbage", 0.06),
        "wire_truncate": rate("wire_truncate", 0.04),
        "wire_duplicate": rate("wire_duplicate", 0.05),
    }
    if _unit(seed, f"soak:{scenario}:stall?", 0) < 0.3:
        kwargs["wire_stall_s"] = 0.1
        kwargs["wire_stall_prob"] = rate("wire_stall", 0.05)
    if _unit(seed, f"soak:{scenario}:crash?", 0) < 0.4:
        kwargs["crash_partition"] = int(
            _unit(seed, f"soak:{scenario}:crash_pid", 0) * 2)
        kwargs["crash_after_pushes"] = 1
    return kwargs


def _wire_ledger_check(plan: FaultPlan) -> None:
    """fired destructive wire faults ⇒ the stack caught typed frame errors
    (zero silently-applied corruption; see module docstring for why this
    is ``> 0``, not ``==``)."""
    destructive = plan.wire_fired_total()
    if destructive > 0:
        _check(
            plan.wire_caught_total() > 0,
            f"{destructive} destructive wire fault(s) fired but the stack "
            f"caught no typed FrameError — corruption may have been "
            f"silently applied (fired={dict(plan.fired)})",
        )


# -- shared fixtures (tiny, deterministic) ---------------------------------

def _toy_data(seed: int, n: int = 96, d: int = 10, c: int = 3):
    rng = np.random.default_rng(1000 + seed)
    x = rng.normal(size=(n, d)).astype("float32")
    w = rng.normal(size=(d, c))
    y = np.eye(c, dtype="float32")[(x @ w).argmax(axis=1)]
    return x, y


def _classifier(seed: int, input_dim: int = 10, nb_classes: int = 3,
                hidden: int = 6):
    import keras

    keras.utils.set_random_seed(2000 + seed)  # deterministic init per seed
    model = keras.Sequential([
        keras.layers.Dense(hidden, activation="relu"),
        keras.layers.Dense(nb_classes, activation="softmax"),
    ])
    model.build((None, input_dim))
    model.compile(optimizer="sgd", loss="categorical_crossentropy")
    return model


def _spark_context(seed: int):
    from ..data.rdd import SparkContext

    return SparkContext(master="local[4]", appName=f"soak-{seed}")


def _retry_policy() -> RetryPolicy:
    return RetryPolicy(max_attempts=6, base_delay_s=0.01, max_delay_s=0.05)


def _check_weights_sane(weights: Iterable[np.ndarray]) -> None:
    for w in weights:
        w = np.asarray(w)
        _check(bool(np.all(np.isfinite(w))), "non-finite weight after soak")
        _check(float(np.abs(w).max(initial=0.0)) < 1e3,
               "runaway weight magnitude after soak (double-apply?)")


# -- scenario runners ------------------------------------------------------

def soak_sync_fit(seed: int) -> Dict[str, Any]:
    """Worker killed mid-partition on the synchronous host path: the task
    retry recomputes the SAME delta, so faulted == fault-free, bitwise."""
    from ..spark_model import SparkModel
    from ..utils import to_simple_rdd

    x, y = _toy_data(seed)
    init = _classifier(seed).get_weights()
    sc = _spark_context(seed)

    def fit_once(plan: Optional[FaultPlan]) -> List[np.ndarray]:
        model = _classifier(seed)
        model.set_weights(init)
        sm = SparkModel(model, mode="synchronous", num_workers=2,
                        comm="host", fault_plan=plan)
        sm.fit(to_simple_rdd(sc, x, y), epochs=1, batch_size=16, verbose=0,
               validation_split=0.0, shuffle=False)
        return model.get_weights()

    clean = fit_once(None)
    plan = FaultPlan(seed=seed, crash_partition=int(
        _unit(seed, "soak:sync:crash_pid", 0) * 2))
    faulted = fit_once(plan)
    _check(bool(plan.fired), "the scheduled worker crash never fired")
    for w_clean, w_faulted in zip(clean, faulted):
        _check(np.array_equal(np.asarray(w_clean), np.asarray(w_faulted)),
               "sync fit diverged from the fault-free run after task retry")
    return {"fired": dict(plan.fired)}


def _soak_async(seed: int, mode: str) -> Dict[str, Any]:
    """The full storm against a live socket PS: logical faults through
    ``FaultyClient``, wire faults through ``FaultySocket`` under the v2
    checksummed framing, retries on top."""
    from ..spark_model import SparkModel
    from ..utils import to_simple_rdd

    x, y = _toy_data(seed)
    plan = FaultPlan(seed=seed, **draw_fault_kwargs(seed, mode))
    model = _classifier(seed)
    sc = _spark_context(seed)
    # frequency="batch": one push/pull round-trip per micro-batch, so the
    # per-frame wire fault rates get real opportunity counts (per-epoch
    # pushing would give the whole fit ~4 frames)
    sm = SparkModel(model, mode=mode, frequency="batch", num_workers=2,
                    comm="host", parameter_server_mode="socket", port=0,
                    fault_plan=plan, retry_policy=_retry_policy(),
                    wire_stall_timeout_s=2.0, ps_timeout=10.0)
    sm.fit(to_simple_rdd(sc, x, y), epochs=2, batch_size=16, verbose=0,
           validation_split=0.0, shuffle=False)
    _check_weights_sane(model.get_weights())
    _wire_ledger_check(plan)
    return {"fired": dict(plan.fired), "wire_caught": dict(plan.wire_caught)}


def soak_async_fit(seed: int) -> Dict[str, Any]:
    return _soak_async(seed, "asynchronous")


def soak_hogwild_fit(seed: int) -> Dict[str, Any]:
    return _soak_async(seed, "hogwild")


def soak_fit_stream(seed: int) -> Dict[str, Any]:
    """Streaming train-to-serve under the storm, twice: the driver loop is
    single-threaded and every fault verdict is seeded, so the same seed
    must reproduce the run bit-for-bit — commits, publications, weights."""
    from ..spark_model import SparkModel

    kwargs = draw_fault_kwargs(seed, "stream")
    kwargs.pop("crash_partition", None)  # no partitions in the driver loop
    kwargs.pop("crash_after_pushes", None)
    batches = [round(0.05 * (1 + (i % 5)), 3) for i in range(10)]

    def train_fn(weights, batch):
        return [w + np.float32(batch) * 1e-3 for w in weights], float(batch)

    def run_once():
        plan = FaultPlan(seed=seed, **kwargs)
        model = _classifier(seed)
        sm = SparkModel(model, mode="asynchronous",
                        parameter_server_mode="socket", port=0,
                        fault_plan=plan, retry_policy=_retry_policy(),
                        wire_stall_timeout_s=2.0, ps_timeout=10.0)
        published: List[int] = []
        summary = sm.fit_stream(
            batches, train_fn,
            sink=lambda weights, version: published.append(int(version)),
            publish_every=3)
        return plan, summary, published, model.get_weights()

    plan, summary, published, weights = run_once()
    # exactly-once: every batch committed, once, in order
    _check(summary["commits"] == len(batches),
           f"{summary['commits']} commits for {len(batches)} batches")
    # committed-version monotonicity (non-decreasing: a dropped/corrupted
    # push legitimately leaves the version where it was)
    _check(published == sorted(published),
           f"published versions regressed: {published}")
    _check_weights_sane(weights)
    _wire_ledger_check(plan)

    _plan2, summary2, published2, weights2 = run_once()
    _check(published2 == published and summary2["commits"] == summary["commits"]
           and summary2["last_loss"] == summary["last_loss"],
           "same-seed stream replay diverged (commits/publications)")
    for w1, w2 in zip(weights, weights2):
        _check(np.array_equal(np.asarray(w1), np.asarray(w2)),
               "same-seed stream replay produced different weights")
    return {"fired": dict(plan.fired), "wire_caught": dict(plan.wire_caught),
            "published": published}


def soak_fleet_replay(seed: int) -> Dict[str, Any]:
    """Kill/join churn over a paged serving fleet mid-trace: nothing lost,
    tokens identical to the undisturbed run, page accounting exact."""
    import jax.numpy as jnp

    from ..fleet import (FleetPolicy, FleetRouter, SimClock, TrafficModel,
                         run_trace)
    from ..models.transformer import TransformerLM
    from ..serving import ServingEngine

    model = TransformerLM(vocab=17, d_model=16, n_heads=4, n_layers=2,
                          d_ff=32, max_len=48)
    params = {k: jnp.asarray(v) for k, v in model.init(seed=1).items()}
    trace = TrafficModel(seed=seed, base_rps=3.0, duration_s=5.0,
                         n_tenants=2, sampled_frac=0.5,
                         burst_amp=2.0).generate()
    kill_t = 0.5 + 2.0 * _unit(seed, "soak:fleet:kill_t", 0)
    chaos = [{"t": kill_t, "op": "kill", "pid": 0},
             {"t": kill_t + 0.5, "op": "join"}]

    def run(events):
        clock = SimClock()

        def factory(pid):
            return ServingEngine(model, params, n_slots=4, max_queue=8,
                                 paged=True, page_size=4, clock=clock,
                                 perf_clock=clock)

        router = FleetRouter(factory, 2, policy=FleetPolicy(), clock=clock,
                             lease_s=0.5)
        snap = run_trace(router, trace, clock=clock, step_dt=0.05,
                         chaos=events)
        for pid in router.partition_ids():
            router._engines[pid].kv.check()  # exact page accounting
        return router, snap

    base_router, _ = run(None)
    router, snap = run(chaos)
    fleet = snap["fleet"]
    _check(fleet["done"] == len(trace) and fleet["queued"] == 0,
           f"requests lost to the kill/join churn: {fleet}")
    chaos_results = router.results()
    for rid, st in base_router.results().items():
        _check(chaos_results[rid].tokens == st.tokens,
               f"stream {rid} diverged from the undisturbed run")
    return {"kill_t": round(kill_t, 3),
            "migrations": int(router.migrations),
            "requests": len(trace)}


SCENARIOS: Dict[str, Callable[[int], Dict[str, Any]]] = {
    "sync-fit": soak_sync_fit,
    "async-fit": soak_async_fit,
    "hogwild-fit": soak_hogwild_fit,
    "fit-stream": soak_fit_stream,
    "fleet-replay": soak_fleet_replay,
}


# -- the soak loop ---------------------------------------------------------

def run_schedule(scenario: str, seed: int) -> Dict[str, Any]:
    """Run ONE seeded schedule. Returns its report; a schedule that dies
    with a member of :data:`TYPED_FAILURES` is an acceptable outcome and
    reported as such. :class:`SoakInvariantViolation` (and any untyped
    exception) propagates — that is a soak failure."""
    runner = SCENARIOS[scenario]
    base = {"scenario": scenario, "seed": seed}
    try:
        detail = runner(seed)
    except SoakInvariantViolation:
        raise
    except TYPED_FAILURES as err:
        return {**base, "outcome": f"typed:{type(err).__name__}",
                "error": str(err)[:300]}
    return {**base, "outcome": "completed", **detail}


def run_soak(n_schedules: int = 20, base_seed: int = 0,
             scenarios: Optional[Iterable[str]] = None,
             verbose: bool = False) -> Dict[str, Any]:
    """Round-robin ``n_schedules`` seeded schedules across the scenario
    set. Never raises: invariant violations and untyped crashes land in
    ``report["failures"]`` (so one red seed does not hide the rest);
    callers assert ``not report["failures"]``."""
    names = list(scenarios) if scenarios is not None else list(SCENARIOS)
    runs: List[Dict[str, Any]] = []
    failures: List[Dict[str, Any]] = []
    for i in range(int(n_schedules)):
        scenario, seed = names[i % len(names)], base_seed + i
        try:
            run = run_schedule(scenario, seed)
            runs.append(run)
            if verbose:  # pragma: no cover - operator convenience
                print(f"[soak] {scenario} seed={seed}: {run['outcome']}")
        except Exception as err:  # noqa: BLE001 — soak collects, not dies
            failures.append({"scenario": scenario, "seed": seed,
                             "error": f"{type(err).__name__}: {err}"})
            if verbose:  # pragma: no cover
                print(f"[soak] {scenario} seed={seed}: FAILED {err}")
    return {
        "schedules": int(n_schedules),
        "completed": sum(r["outcome"] == "completed" for r in runs),
        "typed_failures": sum(
            r["outcome"].startswith("typed:") for r in runs),
        "runs": runs,
        "failures": failures,
    }
