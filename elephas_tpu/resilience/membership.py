"""Elastic membership: heartbeat leases, quorum rounds, straggler backups.

DeepSpark (arxiv 1602.08191) identifies the two cost-dominant failure modes
of synchronous data parallelism on commodity clusters: the whole round blocks
on the slowest worker, and a single lost worker stalls it forever. Its answer
is *partial* aggregation — commit a round once K of N workers report, reject
what arrives late. This module is that layer for the host training paths:

- :class:`HeartbeatRegistry` — per-worker leases with deadline-based
  liveness and a **monotonic membership epoch**. Every join/expire bumps the
  epoch; work launched under an older epoch than a member's fence is stale
  by definition and its result is rejected. The clock is injectable (and in
  chaos tests driven off the seeded :class:`~elephas_tpu.resilience.faults.
  FaultPlan` scheduling), so liveness decisions replay deterministically.
- :class:`QuorumRunner` — runs one round of partition tasks with
  K-of-N commit semantics: the round commits when every live member has
  reported, or when the round deadline passes with at least ``quorum``
  results in hand. Stragglers flagged by the registry get a **backup clone**
  of their task (same task id, next attempt number); first finish wins, and
  the parameter-server attempt machinery (``register_attempt`` rollback +
  server-side attempt fences) keeps the loser's deltas from double-applying.

Observability: the registry keeps a bounded event log (join / heartbeat
expiry / epoch bumps / backups / failovers / per-round shortfall) and
exposes it as a JSON-able :meth:`HeartbeatRegistry.snapshot`, same style as
``serving/metrics.py``.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import Counter, deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence


class QuorumLostError(RuntimeError):
    """Fewer live workers than the quorum requires: the round cannot commit."""


@dataclass
class MembershipEvent:
    """One membership transition, stamped with the registry clock + epoch."""

    kind: str            # join | expire | leave | rejoin | backup | failover
                         # | late_reject | round
    member: Optional[str]
    epoch: int
    at: float
    detail: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "member": self.member,
            "epoch": self.epoch,
            "at": round(float(self.at), 6),
            **({"detail": self.detail} if self.detail else {}),
        }


class HeartbeatRegistry:
    """Lease-based group membership with monotonic epochs.

    Every member holds a lease of ``lease_s`` seconds, renewed by
    :meth:`heartbeat`. :meth:`sweep` expires members whose lease lapsed —
    each expiry (and each join) bumps the monotonic membership ``epoch``.
    A member older than ``straggler_after_s`` since its last beat (but still
    inside its lease) is flagged a *straggler*: alive, but slow enough that a
    backup task is worth launching.

    Late-result fencing: :meth:`fence` records, per member, the epoch below
    which results are stale. Work launched before a member was expired (or
    re-joined) carries the old epoch; comparing launch epoch against the
    fence rejects it without any wall-clock reasoning.

    Thread-safe; the clock is injectable so chaos tests can drive liveness
    deterministically off a fake clock instead of real sleeps.
    """

    def __init__(self, *, lease_s: float = 10.0,
                 straggler_after_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_event: Optional[Callable[[MembershipEvent], None]] = None,
                 max_events: int = 256, max_rounds: int = 64):
        if lease_s <= 0:
            raise ValueError("lease_s must be > 0")
        if straggler_after_s is not None and straggler_after_s <= 0:
            raise ValueError("straggler_after_s must be > 0")
        self.lease_s = float(lease_s)
        self.straggler_after_s = (
            None if straggler_after_s is None else float(straggler_after_s)
        )
        self.clock = clock
        self.on_event = on_event
        self._lock = threading.Lock()
        self._epoch = 0
        self._last_beat: Dict[str, float] = {}
        self._fences: Dict[str, int] = {}
        self._events: deque = deque(maxlen=int(max_events))
        self._rounds: deque = deque(maxlen=int(max_rounds))
        self._counts: Counter = Counter()
        self._failovers = 0

    # -- membership transitions ------------------------------------------
    def _emit(self, kind: str, member: Optional[str],
              **detail: Any) -> MembershipEvent:
        # caller holds the lock
        ev = MembershipEvent(kind=kind, member=member, epoch=self._epoch,
                             at=self.clock(), detail=dict(detail))
        self._events.append(ev)
        self._counts[kind] += 1
        if self.on_event is not None:
            self.on_event(ev)
        return ev

    def join(self, member: str) -> int:
        """Admit (or re-admit) ``member``; returns the new epoch."""
        with self._lock:
            rejoin = member in self._fences and member not in self._last_beat
            self._last_beat[member] = self.clock()
            self._epoch += 1
            if rejoin:
                # results launched before the member died are still stale:
                # keep the fence at the rejoin epoch
                self._fences[member] = self._epoch
            self._emit("rejoin" if rejoin else "join", member)
            return self._epoch

    def heartbeat(self, member: str) -> None:
        """Renew ``member``'s lease (implicitly joining unknown members)."""
        with self._lock:
            if member not in self._last_beat:
                self._epoch += 1
                self._emit("join", member, implicit=True)
            self._last_beat[member] = self.clock()

    def leave(self, member: str) -> None:
        """Graceful departure: bump the epoch, fence the member's results."""
        with self._lock:
            if self._last_beat.pop(member, None) is None:
                return
            self._epoch += 1
            self._fences[member] = self._epoch
            self._emit("leave", member)

    def expire(self, member: str) -> None:
        """Force-expire ``member`` (e.g. the driver declared it dead after
        exhausted retries) — same epoch/fence semantics as a lease lapse."""
        with self._lock:
            if self._last_beat.pop(member, None) is None:
                return
            self._epoch += 1
            self._fences[member] = self._epoch
            self._emit("expire", member, forced=True)

    def sweep(self) -> List[str]:
        """Expire every member whose lease lapsed; returns who was expired."""
        now = self.clock()
        expired = []
        with self._lock:
            for member, beat in list(self._last_beat.items()):
                if now - beat >= self.lease_s:
                    del self._last_beat[member]
                    self._epoch += 1
                    self._fences[member] = self._epoch
                    self._emit("expire", member,
                               lease_age=round(now - beat, 6))
                    expired.append(member)
        return expired

    # -- queries ---------------------------------------------------------
    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def fence(self, member: str) -> int:
        """Results from work launched at an epoch < fence are stale."""
        with self._lock:
            return self._fences.get(member, 0)

    def is_live(self, member: str, default: bool = False) -> bool:
        """Live = holds an unexpired lease. ``default`` answers for members
        the registry has never seen (external callers may treat unknown as
        live when membership is opt-in)."""
        with self._lock:
            beat = self._last_beat.get(member)
            if beat is None:
                return default and member not in self._fences
            return self.clock() - beat < self.lease_s

    def live(self) -> List[str]:
        now = self.clock()
        with self._lock:
            return sorted(
                m for m, beat in self._last_beat.items()
                if now - beat < self.lease_s
            )

    def stragglers(self) -> List[str]:
        """Members inside their lease but silent past ``straggler_after_s``."""
        if self.straggler_after_s is None:
            return []
        now = self.clock()
        with self._lock:
            return sorted(
                m for m, beat in self._last_beat.items()
                if self.straggler_after_s <= now - beat < self.lease_s
            )

    # -- observability ----------------------------------------------------
    def observe_backup(self, member: str, attempt: int) -> None:
        with self._lock:
            self._emit("backup", member, attempt=int(attempt))

    def observe_failover(self, *, endpoint: int,
                         version: Optional[int] = None) -> None:
        with self._lock:
            self._failovers += 1
            self._emit("failover", None, endpoint=int(endpoint),
                       **({} if version is None else {"version": int(version)}))

    def observe_late_reject(self, member: str, *, launch_epoch: int) -> None:
        with self._lock:
            self._emit("late_reject", member, launch_epoch=int(launch_epoch))

    def observe_round(self, *, expected: int, received: int,
                      quorum: Optional[int] = None,
                      backups: int = 0, deadline_hit: bool = False) -> None:
        """Record one aggregation round's outcome (shortfall = how many
        expected results the commit went ahead without)."""
        with self._lock:
            entry = {
                "epoch": self._epoch,
                "expected": int(expected),
                "received": int(received),
                "shortfall": max(0, int(expected) - int(received)),
                "quorum": quorum if quorum is None else int(quorum),
                "backups": int(backups),
                "deadline_hit": bool(deadline_hit),
            }
            self._rounds.append(entry)
            self._emit("round", None, **entry)

    @property
    def failovers(self) -> int:
        with self._lock:
            return self._failovers

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able registry state, ``serving/metrics.py`` style."""
        now = self.clock()
        with self._lock:
            live = sorted(
                m for m, beat in self._last_beat.items()
                if now - beat < self.lease_s
            )
            return {
                "membership": {
                    "epoch": self._epoch,
                    "live": live,
                    "stragglers": sorted(
                        m for m, beat in self._last_beat.items()
                        if self.straggler_after_s is not None
                        and self.straggler_after_s <= now - beat < self.lease_s
                    ),
                    "fences": dict(self._fences),
                    "lease_s": self.lease_s,
                    "straggler_after_s": self.straggler_after_s,
                },
                "counters": {
                    **dict(self._counts),
                    "failovers": self._failovers,
                },
                "rounds": list(self._rounds),
                "events": [e.to_dict() for e in self._events],
            }


def member_id_for(partition: int) -> str:
    """Registry member id for a partition index (one worker per partition on
    the facade's thread-pool executor)."""
    return f"partition-{partition}"


class QuorumRunner:
    """One K-of-N round over partitions, with straggler backups.

    Replaces ``rdd.mapPartitions(...).collect()`` for elastic synchronous
    training: each partition's task runs on its own thread under a
    :class:`~elephas_tpu.data.rdd.TaskContext` (partition id, attempt
    number, stage id — identical to the facade RDD's contract, so workers
    and the ``FaultPlan`` can't tell the difference). The round:

    - commits as soon as every *live* member has reported;
    - commits the received subset once the round deadline passes with at
      least ``quorum`` results (DeepSpark partial aggregation);
    - relaunches crashed tasks up to ``max_failures`` attempts, then
      expires the member (permanent node loss);
    - launches a backup clone when the registry flags a straggler; first
      finish wins, the loser is rejected (per-partition, only one result
      commits) and its server-side deltas are fenced by attempt number;
    - rejects results whose launch epoch is below the member's fence
      (late deltas from expired members).

    Raises :class:`QuorumLostError` when fewer than ``quorum`` members can
    still possibly report.
    """

    def __init__(self, registry: HeartbeatRegistry, *,
                 quorum: Optional[int] = None,
                 round_deadline_s: Optional[float] = None,
                 backup_stragglers: bool = True,
                 max_failures: int = 4,
                 poll_s: float = 0.02):
        self.registry = registry
        self.quorum = quorum
        self.round_deadline_s = round_deadline_s
        self.backup_stragglers = bool(backup_stragglers)
        self.max_failures = int(max_failures)
        self.poll_s = float(poll_s)
        self.backups_launched = 0
        self.abandoned: List[int] = []   # pids uncommitted at quorum commit

    def run(self, partitions: Sequence[Sequence[Any]],
            task_fn: Callable[[Iterator[Any]], Iterator[Any]],
            *, stage_id: int = 0) -> Dict[int, List[Any]]:
        """Run ``task_fn`` over every partition; return {pid: results} for
        the committed subset (every value is the task's materialized output
        list, exactly what ``mapPartitions`` would have collected)."""
        from ..data.rdd import TaskContext

        n = len(partitions)
        if n == 0:
            return {}
        quorum = n if self.quorum is None else min(int(self.quorum), n)
        if quorum < 1:
            raise ValueError("quorum must be >= 1")
        registry = self.registry
        clock = registry.clock
        for pid in range(n):
            registry.join(member_id_for(pid))

        results: "queue.Queue" = queue.Queue()
        committed: Dict[int, List[Any]] = {}
        attempts = {pid: 0 for pid in range(n)}        # next attempt number
        failures = {pid: 0 for pid in range(n)}
        outstanding = {pid: 0 for pid in range(n)}     # in-flight attempts
        backed_up = set()
        dead = set()

        def _attempt(pid: int, attempt: int, launch_epoch: int) -> None:
            outer = TaskContext.get()
            TaskContext._set(TaskContext(pid, attempt, stage_id))
            member = member_id_for(pid)
            registry.heartbeat(member)
            try:
                out = list(task_fn(iter(partitions[pid])))
            except BaseException as err:  # noqa: BLE001 - reported to driver
                results.put((pid, attempt, launch_epoch, err, None))
            else:
                registry.heartbeat(member)
                results.put((pid, attempt, launch_epoch, None, out))
            finally:
                TaskContext._set(outer)

        executor = ThreadPoolExecutor(max_workers=max(2, 2 * n))

        def _launch(pid: int) -> None:
            attempt = attempts[pid]
            attempts[pid] = attempt + 1
            outstanding[pid] += 1
            executor.submit(_attempt, pid, attempt, registry.epoch)

        try:
            for pid in range(n):
                _launch(pid)
            deadline = (
                None if self.round_deadline_s is None
                else clock() + float(self.round_deadline_s)
            )
            while True:
                pending = [
                    pid for pid in range(n)
                    if pid not in committed and pid not in dead
                ]
                if not pending:
                    break
                if len(committed) + len(pending) < quorum:
                    raise QuorumLostError(
                        f"only {len(committed)} of {n} partitions can still "
                        f"report (quorum {quorum}); "
                        f"dead={sorted(dead)}"
                    )
                if (deadline is not None and clock() >= deadline
                        and len(committed) >= quorum):
                    # DeepSpark partial aggregation: the round goes ahead
                    # with the received subset; whoever is still running is
                    # expired so their eventual result (and, on the async
                    # path, their uncommitted server deltas) is fenced out.
                    for pid in pending:
                        registry.expire(member_id_for(pid))
                        self.abandoned.append(pid)
                    break
                if self.backup_stragglers:
                    for member in registry.stragglers():
                        pid = int(member.rsplit("-", 1)[1])
                        if (pid in committed or pid in dead
                                or pid in backed_up):
                            continue
                        backed_up.add(pid)
                        self.backups_launched += 1
                        registry.observe_backup(member, attempts[pid])
                        _launch(pid)
                try:
                    pid, attempt, launch_epoch, err, out = results.get(
                        timeout=self.poll_s
                    )
                except queue.Empty:
                    # Lease lapse == node loss: the member is fenced (its
                    # late result will be rejected) and its partition is
                    # written off for this round. lease_s must therefore
                    # exceed the expected task duration unless the worker
                    # heartbeats mid-task.
                    for member in registry.sweep():
                        pid = int(member.rsplit("-", 1)[1])
                        if pid not in committed:
                            dead.add(pid)
                    continue
                outstanding[pid] -= 1
                member = member_id_for(pid)
                if pid in committed or pid in dead:
                    # first-finish already won (or the member was declared
                    # dead): the loser's result must not double-commit.
                    registry.observe_late_reject(
                        member, launch_epoch=launch_epoch
                    )
                    continue
                if launch_epoch < registry.fence(member):
                    # launched before the member was expired/rejoined: stale
                    # by membership epoch, reject it.
                    registry.observe_late_reject(
                        member, launch_epoch=launch_epoch
                    )
                    continue
                if err is None:
                    committed[pid] = out
                    continue
                failures[pid] += 1
                if failures[pid] >= self.max_failures:
                    if outstanding[pid] == 0:
                        dead.add(pid)
                        registry.expire(member)
                elif outstanding[pid] == 0:
                    _launch(pid)
            received = len(committed)
            if received < quorum:
                raise QuorumLostError(
                    f"round ended with {received} of {n} partitions "
                    f"(quorum {quorum})"
                )
            registry.observe_round(
                expected=n, received=received, quorum=quorum,
                backups=self.backups_launched,
                deadline_hit=bool(self.abandoned),
            )
            return committed
        finally:
            # Never block the driver on abandoned attempts: zombie threads
            # finish on their own and their queued results are simply never
            # read. (Their server-side pushes are fenced separately.)
            executor.shutdown(wait=False)
