"""Distributed hyperparameter search: ``HyperParamModel``.

Rebuild of reference ``elephas/hyperparam.py:~1`` (``HyperParamModel.minimize``
/ ``compute_trials`` / ``best_models``, ``HyperasWorker._minimize``). The
reference templates the *source code* of user-supplied ``data()``/``model()``
functions hyperas-style — ``{{choice([...])}}`` markers inside the model
function — fans the templated source out over a dummy RDD, and runs an
independent hyperopt TPE search per partition with a partition-derived seed.

hyperas/hyperopt are not in this environment (SURVEY.md §7.0), so the search
core is self-contained but keeps the hyperas *user surface*:

- write ``{{choice([...])}}`` / ``{{uniform(a, b)}}`` etc. in the model
  function body (import the names from this module so the file parses);
- ``data()`` returns ``x_train, y_train, x_test, y_test`` and is called on
  every worker (the reference loads the dataset independently per worker —
  search is parallel, data is not; SURVEY.md §3.5);
- ``model(x_train, y_train, x_test, y_test)`` returns
  ``{'loss': ..., 'status': STATUS_OK, 'model': model}``.

Search strategy per worker: a self-contained Tree-structured Parzen
Estimator (Bergstra et al. 2011 — the same algorithm behind hyperopt's
``tpe.suggest``) over independent per-dimension Parzen models: after a
random startup phase, trials split at the γ loss quantile into good/bad
sets, each dimension fits kernel densities to both (Gaussians in the
transformed coordinate for continuous dims, smoothed categoricals for
``choice``), candidates are drawn from the good model, and the one
maximizing ``g(x)/b(x)`` is evaluated. hyperopt itself is absent from this
environment (SURVEY.md §7.0); matching its trial-for-trial draws is a
documented divergence, the algorithm family is not.
"""

from __future__ import annotations

import inspect
import math
import random as _random
import re
import textwrap
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .data.rdd import SparkContext

STATUS_OK = "ok"


# -- hyperas-style distribution markers --------------------------------------
# These exist so user files importing them parse; inside ``{{...}}`` they are
# re-parsed textually into Space objects at template time.


class _Space:
    def sample(self, rng: _random.Random):
        raise NotImplementedError


class _Choice(_Space):
    def __init__(self, options):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class _Uniform(_Space):
    def __init__(self, low, high):
        self.low, self.high = float(low), float(high)

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class _QUniform(_Space):
    def __init__(self, low, high, q):
        self.low, self.high, self.q = float(low), float(high), float(q)

    def sample(self, rng):
        return round(rng.uniform(self.low, self.high) / self.q) * self.q


class _LogUniform(_Space):
    def __init__(self, low, high):
        self.low, self.high = math.log(low), math.log(high)

    def sample(self, rng):
        return math.exp(rng.uniform(self.low, self.high))


def choice(options):  # noqa: D103 — hyperas-parity marker
    return _Choice(options)


def uniform(low, high):  # noqa: D103
    return _Uniform(low, high)


def quniform(low, high, q):  # noqa: D103
    return _QUniform(low, high, q)


def loguniform(low, high):  # noqa: D103
    return _LogUniform(low, high)


# -- TPE sampler --------------------------------------------------------------


class TPESampler:
    """Independent-dimension Tree-structured Parzen Estimator.

    For each dimension the observed values from the best γ-fraction of
    trials form the "good" density ``g`` and the rest the "bad" density
    ``b``; proposals are drawn from ``g`` and ranked by ``g(x)/b(x)``.
    Continuous dims use Parzen windows (equal-weight Gaussians at the
    observations, bandwidth from the neighbour spacing) in the TRANSFORMED
    coordinate — log-space for ``loguniform`` — mixed with the uniform prior
    so no region's density ever hits zero; ``choice``/``quniform`` dims use
    add-one-smoothed categoricals. Deterministic given the ``random.Random``
    passed in.
    """

    def __init__(self, spaces: List[_Space], gamma: float = 0.25,
                 n_candidates: int = 24, n_startup: int = 5):
        self.spaces = spaces
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.n_startup = n_startup

    # -- per-dimension transforms ---------------------------------------
    @staticmethod
    def _fwd(space: _Space, v):
        if isinstance(space, _LogUniform):
            return math.log(v)
        return float(v)

    @staticmethod
    def _bounds(space: _Space):
        if isinstance(space, _Uniform):  # quniform routes to the
            return space.low, space.high  # discrete branch, never here
        if isinstance(space, _LogUniform):
            return space.low, space.high  # already log-space
        return None

    def _parzen(self, space: _Space, obs: List[float]):
        """(means, sigmas, lo, hi) for a continuous dim's Parzen windows."""
        lo, hi = self._bounds(space)
        pts = sorted(self._fwd(space, v) for v in obs)
        span = max(hi - lo, 1e-12)
        sigmas = []
        for i, m in enumerate(pts):
            left = pts[i - 1] if i > 0 else lo
            right = pts[i + 1] if i + 1 < len(pts) else hi
            s = max(right - left, span / 100.0) / 2.0
            sigmas.append(min(s, span))
        return pts, sigmas, lo, hi

    @staticmethod
    def _parzen_pdf(x, means, sigmas, lo, hi, prior_w=0.1):
        span = max(hi - lo, 1e-12)
        p = prior_w / span
        if means:
            k = (1.0 - prior_w) / len(means)
            for m, s in zip(means, sigmas):
                p += k * math.exp(-0.5 * ((x - m) / s) ** 2) / (
                    s * math.sqrt(2 * math.pi)
                )
        return p

    def _dim_models(self, space: _Space, good: List, bad: List):
        """Return (sample_good(rng), score(value)) for one dimension."""
        if isinstance(space, (_Choice, _QUniform)):
            # discrete: smoothed categorical over the observed support
            def key(v):
                return repr(v)

            support: List = []
            seen = set()
            for v in good + bad:
                if key(v) not in seen:
                    seen.add(key(v))
                    support.append(v)
            if isinstance(space, _Choice):
                for v in space.options:
                    if key(v) not in seen:
                        seen.add(key(v))
                        support.append(v)

            def probs(obs):
                counts = {key(v): 1.0 for v in support}  # add-one smoothing
                for v in obs:
                    counts[key(v)] += 1.0
                tot = sum(counts.values()) + 1.0  # +1: unseen-value mass
                return {k_: c / tot for k_, c in counts.items()}, 1.0 / tot

            (pg, floor_g), (pb, floor_b) = probs(good), probs(bad)

            def sample_good(rng):
                if rng.random() < 0.1:  # keep the prior alive — quniform
                    return space.sample(rng)  # support lists only observed
                r = rng.random()
                acc = 0.0
                for v in support:
                    acc += pg[key(v)]
                    if r <= acc:
                        return v
                return support[-1]

            def score(v):
                return math.log(pg.get(key(v), floor_g)) - math.log(
                    pb.get(key(v), floor_b)
                )

            return sample_good, score

        g_m, g_s, lo, hi = self._parzen(space, good)
        b_m, b_s, _, _ = self._parzen(space, bad)

        def sample_good(rng):
            if not g_m or rng.random() < 0.1:  # keep the prior alive
                return space.sample(rng)
            i = rng.randrange(len(g_m))
            for _ in range(16):
                x = rng.gauss(g_m[i], g_s[i])
                if lo <= x <= hi:
                    break
            else:
                x = min(max(x, lo), hi)
            if isinstance(space, _LogUniform):
                return math.exp(x)
            return x

        def score(v):
            x = self._fwd(space, v)
            return math.log(self._parzen_pdf(x, g_m, g_s, lo, hi)) - math.log(
                self._parzen_pdf(x, b_m, b_s, lo, hi)
            )

        return sample_good, score

    def suggest(self, trials: List[Dict[str, Any]],
                rng: _random.Random) -> List[Any]:
        """Propose the next parameter vector given past ``trials`` (each
        with ``"loss"`` and ``"params"``)."""
        ok = [t for t in trials if t.get("status", STATUS_OK) == STATUS_OK]
        if len(ok) < self.n_startup:
            return [s.sample(rng) for s in self.spaces]
        ok = sorted(ok, key=lambda t: t["loss"])
        n_good = max(1, int(round(self.gamma * len(ok))))
        good, bad = ok[:n_good], ok[n_good:] or ok[:1]

        dims = [
            self._dim_models(s, [t["params"][d] for t in good],
                             [t["params"][d] for t in bad])
            for d, s in enumerate(self.spaces)
        ]
        best_cand, best_score = None, None
        for _ in range(self.n_candidates):
            cand = [sample(rng) for sample, _ in dims]
            sc = sum(score(v) for (_, score), v in zip(dims, cand))
            if best_score is None or sc > best_score:
                best_cand, best_score = cand, sc
        return best_cand


_MARKER = re.compile(r"\{\{(.+?)\}\}", re.DOTALL)


def get_hyperopt_model_string(model_fn: Callable) -> Dict[str, Any]:
    """Template the model function's source (reference: hyperas
    ``get_hyperopt_model_string``, ``hyperparam.py:~30``).

    Returns ``{'source', 'spaces', 'name'}`` where each ``{{...}}`` marker has
    been replaced by ``__hp__[i]`` and ``spaces[i]`` is the parsed Space.
    """
    src = textwrap.dedent(inspect.getsource(model_fn))
    # Drop decorators if any, keep the def.
    spaces: List[_Space] = []

    def repl(match):
        expr = match.group(1)
        space = eval(  # noqa: S307 — expression comes from the user's own file
            expr,
            {"choice": choice, "uniform": uniform, "quniform": quniform,
             "loguniform": loguniform},
        )
        if not isinstance(space, _Space):
            raise ValueError(f"{{{{{expr}}}}} is not a search-space expression")
        spaces.append(space)
        return f"__hp__[{len(spaces) - 1}]"

    templated = _MARKER.sub(repl, src)
    return {"source": templated, "spaces": spaces, "name": model_fn.__name__,
            "globals": model_fn.__globals__}


class HyperasWorker:
    """Per-partition search worker (reference ``HyperasWorker._minimize``).

    ``keep_weights_top`` bounds driver memory: only each worker's best-k
    trials ship their full weight lists back; the rest carry
    ``weights=None`` (loss/params always recorded).
    """

    def __init__(self, model_spec: Dict[str, Any], data_fn: Callable,
                 max_evals: int, keep_weights_top: Optional[int] = None):
        self.model_spec = model_spec
        self.data_fn = data_fn
        self.max_evals = int(max_evals)
        self.keep_weights_top = keep_weights_top

    def _minimize(self, data_iterator):
        """Run ``max_evals`` evaluations seeded from the partition contents.

        TPU-first fan-out (SURVEY §7.1.5 "fanned out across mesh slices"):
        each search worker pins its trials to its OWN device from the
        visible set (``devices[partitionId % n]`` via ``jax.default_device``,
        a thread-local setting). The reference's workers are separate Spark
        executors with separate GPUs; without pinning, this facade's
        thread-workers all dispatch to device 0 and serialize on it. With
        pinning, concurrent trials run on disjoint chips — on real
        multi-chip hardware the host thread only orchestrates, so
        ``num_workers``-way concurrency is real. (On the single-core CI box
        the virtual CPU devices share one core, so wall-clock parity there
        is expected — the placement, not the timing, is what tests pin.)
        """
        import contextlib

        import jax

        from .data import TaskContext

        ctx = TaskContext.get()
        devices = jax.devices()
        if ctx is not None and len(devices) > 1:
            pin = jax.default_device(devices[ctx.partitionId() % len(devices)])
        else:
            pin = contextlib.nullcontext()
        with pin:
            yield self._run_trials(data_iterator)

    def _run_trials(self, data_iterator):
        elements = list(data_iterator)
        seed = int(elements[0]) if elements else 0
        rng = _random.Random(seed)
        data = self.data_fn()

        spaces = self.model_spec["spaces"]
        exec_globals = dict(self.model_spec["globals"])
        exec_globals["STATUS_OK"] = STATUS_OK
        local_ns: Dict[str, Any] = {}
        exec(compile(self.model_spec["source"], "<hyperparam-template>", "exec"),
             exec_globals, local_ns)
        fn = local_ns[self.model_spec["name"]]

        import jax.numpy as jnp

        # where this worker's computation actually lands (the pinned slice)
        device = str(next(iter(jnp.zeros(()).devices())))

        sampler = TPESampler(spaces)
        trials: List[Dict[str, Any]] = []
        for i in range(self.max_evals):
            params = sampler.suggest(trials, rng)
            exec_globals["__hp__"] = params
            result = fn(*data)
            model = result["model"]
            trial = {
                "loss": float(result["loss"]),
                "status": result.get("status", STATUS_OK),
                "params": params,
                "model_json": model.to_json(),
                "weights": model.get_weights(),
                "device": device,
            }
            trials.append(trial)
        if self.keep_weights_top is not None:
            ok = sorted(
                (t for t in trials if t["status"] == STATUS_OK),
                key=lambda t: t["loss"],
            )
            keep = {id(t) for t in ok[: self.keep_weights_top]}
            for t in trials:
                if id(t) not in keep:
                    t["weights"] = None
        return trials


class HyperParamModel:
    """Driver-side distributed search (reference ``HyperParamModel``)."""

    def __init__(self, sc: SparkContext, num_workers: int = 4):
        self.spark_context = sc
        self.num_workers = int(num_workers)

    def compute_trials(self, model: Callable, data: Callable, max_evals: int,
                       keep_weights_top: Optional[int] = None
                       ) -> List[Dict[str, Any]]:
        """All trials from all workers (reference ``compute_trials``)."""
        model_spec = get_hyperopt_model_string(model)
        worker = HyperasWorker(model_spec, data, max_evals, keep_weights_top)
        # Dummy RDD fan-out: partition contents only seed the per-worker RNG
        # (reference ``hyperparam.py:~40``).
        dummy_rdd = self.spark_context.parallelize(range(1, 1000), 50)
        dummy_rdd = dummy_rdd.repartition(self.num_workers)
        trial_lists = dummy_rdd.mapPartitions(worker._minimize).collect()
        return [t for trials in trial_lists for t in trials]

    def minimize(self, model: Callable, data: Callable, max_evals: int = 5):
        """Best Keras model across the distributed search
        (reference ``minimize``)."""
        import keras

        trials = self.compute_trials(model, data, max_evals, keep_weights_top=1)
        ok = [t for t in trials if t["status"] == STATUS_OK and t["weights"]]
        if not ok:
            raise ValueError("Search produced no successful trials")
        best = min(ok, key=lambda t: t["loss"])
        best_model = keras.models.model_from_json(best["model_json"])
        best_model.set_weights(best["weights"])
        return best_model

    def best_models(self, nb_models: int, model: Callable, data: Callable,
                    max_evals: int) -> "VotingModel":
        """Top-k ensemble (reference ``best_models`` → hyperas VotingModel)."""
        import keras

        trials = self.compute_trials(
            model, data, max_evals, keep_weights_top=nb_models
        )
        ok = sorted(
            (t for t in trials if t["status"] == STATUS_OK and t["weights"]),
            key=lambda t: t["loss"],
        )
        members = []
        for t in ok[:nb_models]:
            m = keras.models.model_from_json(t["model_json"])
            m.set_weights(t["weights"])
            members.append(m)
        if not members:
            raise ValueError("Search produced no successful trials")
        return VotingModel(members)


class VotingModel:
    """Prediction-averaging ensemble (hyperas ``VotingModel`` parity)."""

    def __init__(self, models: List):
        self.models = list(models)

    def predict(self, x, **kwargs):
        preds = [m.predict(x, verbose=0) for m in self.models]
        return np.mean(np.stack(preds), axis=0)

    def predict_classes(self, x, **kwargs):
        return self.predict(x).argmax(axis=-1)
