"""Version-portability shims for jax APIs this repo straddles.

The codebase targets the modern ``jax.shard_map`` entry point (top-level,
``check_vma=`` keyword); older runtimes (jax 0.4.x) ship the same
transform as ``jax.experimental.shard_map.shard_map`` with the
replication-check keyword spelled ``check_rep=``. Every shard_map call in
the repo goes through :func:`shard_map` below so the whole sharded stack
(training engines, tensor/expert/pipeline parallel layers, sharded
generate, the serving engine) runs unmodified on either line.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):                     # jax >= 0.6
    _shard_map = jax.shard_map
    _CHECK_KW = "check_vma"
else:                                             # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the replication-check flag normalized to the
    modern ``check_vma`` spelling (mapped to ``check_rep`` on 0.4.x)."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: check_vma})


if hasattr(jax.lax, "axis_size"):                 # jax >= 0.5
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        """Static size of a mapped axis. ``psum`` of a Python constant is
        constant-folded to ``size * value`` at trace time — the idiom
        ``jax.lax.axis_size`` replaced — so this stays a concrete int
        usable in trace-time ``if``s."""
        return jax.lax.psum(1, axis_name)
