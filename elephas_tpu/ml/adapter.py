"""DataFrame → simple RDD conversion.

Rebuild of reference ``elephas/ml/adapter.py:~1``
(``df_to_simple_rdd(df, categorical, nb_classes, features_col, label_col)``):
selects the feature/label columns, densifies MLlib vectors, one-hot encodes
categorical labels, and yields an RDD of ``(x, y)`` pairs for
``SparkModel.fit``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..data.dataframe import DataFrame
from ..data.rdd import RDD
from ..mllib.linalg import DenseVector
from ..utils.rdd_utils import encode_label


def _to_array(features) -> np.ndarray:
    if isinstance(features, DenseVector):
        return features.toArray().astype("float32")
    return np.asarray(features, dtype="float32")


def df_to_simple_rdd(df: DataFrame, categorical: bool = False,
                     nb_classes: Optional[int] = None,
                     features_col: str = "features",
                     label_col: str = "label") -> RDD:
    """DataFrame rows → RDD of ``(features ndarray, label)`` pairs."""
    if categorical and nb_classes is None:
        nb_classes = (
            int(max(float(r[label_col]) for r in df.select(label_col).collect())) + 1
        )

    selected = df.select(features_col, label_col)

    def convert(row):
        x = _to_array(row[features_col])
        label = float(row[label_col])
        if categorical:
            y = encode_label(label, nb_classes)
        else:
            y = np.float32(label)
        return (x, y)

    return selected.rdd.map(convert)
