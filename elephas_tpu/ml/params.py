"""Typed parameter mixins for the ML-pipeline skin.

Rebuild of reference ``elephas/ml/params.py:~1``: one ``Has<X>`` mixin per
knob, each contributing a ``Param`` descriptor plus getter/setter, composed by
``ElephasEstimator``. The reference builds these on ``pyspark.ml.param.Params``;
there is no JVM/pyspark here, so a minimal ``Params`` base reproduces the
observable behavior: named params with docs, defaults, ``set``/``get``,
keyword construction, ``explainParams``, and dict round-trip for persistence.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class Param:
    """A named, documented parameter attached to a Params instance."""

    def __init__(self, parent: "Params", name: str, doc: str):
        self.parent = parent
        self.name = name
        self.doc = doc

    def __repr__(self):
        return f"Param({self.name})"


class Params:
    """Mini ``pyspark.ml.param.Params``: a registry of Param + values."""

    def __init__(self):
        self._params: Dict[str, Param] = {}
        self._paramMap: Dict[str, Any] = {}
        self._defaultParamMap: Dict[str, Any] = {}
        # Continue the cooperative chain so Has* mixins after Params in the
        # MRO declare their params once the registries exist.
        super().__init__()

    def _declare(self, name: str, doc: str, default: Any = None) -> Param:
        p = Param(self, name, doc)
        self._params[name] = p
        self._defaultParamMap[name] = default
        return p

    # -- pyspark-shaped accessors ---------------------------------------
    @property
    def params(self) -> List[Param]:
        return list(self._params.values())

    def hasParam(self, name: str) -> bool:
        return name in self._params

    def getOrDefault(self, name: str) -> Any:
        if name in self._paramMap:
            return self._paramMap[name]
        return self._defaultParamMap.get(name)

    def _set(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            if k not in self._params:
                raise ValueError(f"Unknown param: {k}")
            self._paramMap[k] = v
        return self

    def setParams(self, **kwargs) -> "Params":
        return self._set(**kwargs)

    def copy(self, extra: Dict[str, Any] = None) -> "Params":
        """Shallow copy with ``extra`` params overlaid — pyspark's
        ``fit(df, params)`` semantics apply params to a copy, leaving the
        original untouched."""
        import copy as _copy

        new = _copy.copy(self)
        new._paramMap = dict(self._paramMap)
        if extra:
            new._set(**extra)
        return new

    def explainParams(self) -> str:
        lines = []
        for name, p in sorted(self._params.items()):
            lines.append(f"{name}: {p.doc} (current: {self.getOrDefault(name)})")
        return "\n".join(lines)

    def param_values(self) -> Dict[str, Any]:
        """All effective values (defaults overlaid with set values)."""
        out = dict(self._defaultParamMap)
        out.update(self._paramMap)
        return out


def _mixin(name: str, doc: str, default: Any = None, snake: Optional[str] = None):
    """Build a ``Has<X>`` mixin class with get_/set_ accessors.

    The reference's mixins expose ``set_<snake>`` / ``get_<snake>`` methods
    (e.g. ``set_keras_model_config``); generated here from a template.
    """
    snake = snake or name

    class Mixin:
        def __init__(self):
            setattr(self, snake, self._declare(snake, doc, default))
            super().__init__()

    def setter(self, value):
        self._set(**{snake: value})
        return self

    def getter(self):
        return self.getOrDefault(snake)

    setattr(Mixin, f"set_{snake}", setter)
    setattr(Mixin, f"get_{snake}", getter)
    Mixin.__name__ = f"Has{''.join(w.capitalize() for w in snake.split('_'))}"
    return Mixin


HasKerasModelConfig = _mixin(
    "keras_model_config", "Serialized Keras model architecture (JSON)", None
)
HasOptimizerConfig = _mixin(
    "optimizer_config", "Serialized Keras optimizer config", None
)
HasMode = _mixin("mode", "Training mode: synchronous|asynchronous|hogwild",
                 "asynchronous")
HasFrequency = _mixin("frequency", "Merge frequency: epoch|batch", "epoch")
HasParameterServerMode = _mixin(
    "parameter_server_mode", "Weight transport: jax|http|socket", "http"
)
HasNumberOfClasses = _mixin("nb_classes", "Number of output classes", 10)
HasNumberOfWorkers = _mixin("num_workers", "Number of data-parallel workers", None)
HasEpochs = _mixin("epochs", "Training epochs", 10)
HasBatchSize = _mixin("batch_size", "Per-worker batch size", 32)
HasVerbosity = _mixin("verbose", "Verbosity level", 0)
HasValidationSplit = _mixin(
    "validation_split", "Fraction of each worker's data held out", 0.1
)
HasCategoricalLabels = _mixin(
    "categorical", "Whether labels are categorical (one-hot encoded)", True
)
HasLoss = _mixin("loss", "Keras loss identifier", None)
HasMetrics = _mixin("metrics", "Keras metric identifiers", None)
HasFeaturesCol = _mixin("features_col", "Features column name", "features")
HasLabelCol = _mixin("label_col", "Label column name", "label")
HasOutputCol = _mixin("output_col", "Prediction output column name", "prediction")
HasCustomObjects = _mixin(
    "custom_objects", "Custom Keras objects for deserialization", None
)
