from .adapter import df_to_simple_rdd
from .pipeline import Pipeline, PipelineModel, StandardScaler, StringIndexer

__all__ = [
    "df_to_simple_rdd",
    "Pipeline",
    "PipelineModel",
    "StandardScaler",
    "StringIndexer",
]
