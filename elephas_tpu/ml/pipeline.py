"""Minimal ML ``Pipeline`` facade.

The reference's pipeline examples compose ``ElephasEstimator`` inside a
``pyspark.ml.Pipeline`` (SURVEY.md §3.3). This module provides the Pipeline /
PipelineModel shape plus the two feature stages the reference's examples lean
on (``StringIndexer``, ``StandardScaler``), so those scripts run against the
local facade unchanged in structure.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..data.dataframe import DataFrame
from ..mllib.linalg import DenseVector


class Pipeline:
    """Ordered stages; estimators are fit, transformers pass through."""

    def __init__(self, stages: Sequence):
        self.stages = list(stages)

    def fit(self, df: DataFrame) -> "PipelineModel":
        fitted = []
        current = df
        for stage in self.stages:
            if hasattr(stage, "fit"):
                model = stage.fit(current)
                fitted.append(model)
                current = model.transform(current)
            else:
                fitted.append(stage)
                current = stage.transform(current)
        return PipelineModel(fitted)


class PipelineModel:
    def __init__(self, stages: List):
        self.stages = stages

    def transform(self, df: DataFrame) -> DataFrame:
        current = df
        for stage in self.stages:
            current = stage.transform(current)
        return current


class _ColumnStage:
    def _replace_column(self, df: DataFrame, col: str, fn) -> DataFrame:
        return df.withColumn(col, fn)


class StringIndexer(_ColumnStage):
    """Label → index by descending frequency (pyspark semantics)."""

    def __init__(self, inputCol: str, outputCol: str):
        self.inputCol = inputCol
        self.outputCol = outputCol

    def fit(self, df: DataFrame) -> "StringIndexerModel":
        values = [r[self.inputCol] for r in df.collect()]
        uniq, counts = np.unique(np.asarray(values, dtype=object), return_counts=True)
        order = sorted(zip(uniq, counts), key=lambda t: (-t[1], str(t[0])))
        mapping = {v: float(i) for i, (v, _) in enumerate(order)}
        return StringIndexerModel(self.inputCol, self.outputCol, mapping)


class StringIndexerModel(_ColumnStage):
    def __init__(self, inputCol: str, outputCol: str, mapping: dict):
        self.inputCol = inputCol
        self.outputCol = outputCol
        self.labels = mapping

    def transform(self, df: DataFrame) -> DataFrame:
        return self._replace_column(
            df, self.outputCol, lambda r: self.labels[r[self.inputCol]]
        )


class StandardScaler(_ColumnStage):
    """Feature standardization over a vector column (pyspark semantics)."""

    def __init__(self, inputCol: str, outputCol: str, withMean: bool = True,
                 withStd: bool = True):
        self.inputCol = inputCol
        self.outputCol = outputCol
        self.withMean = withMean
        self.withStd = withStd

    def fit(self, df: DataFrame) -> "StandardScalerModel":
        from .adapter import _to_array

        feats = np.stack([_to_array(r[self.inputCol]) for r in df.collect()])
        mean = feats.mean(axis=0) if self.withMean else np.zeros(feats.shape[1])
        std = feats.std(axis=0, ddof=1) if self.withStd else np.ones(feats.shape[1])
        std = np.where(std == 0, 1.0, std)
        return StandardScalerModel(self.inputCol, self.outputCol, mean, std)


class StandardScalerModel(_ColumnStage):
    def __init__(self, inputCol, outputCol, mean, std):
        self.inputCol = inputCol
        self.outputCol = outputCol
        self.mean = mean
        self.std = std

    def transform(self, df: DataFrame) -> DataFrame:
        from .adapter import _to_array

        return self._replace_column(
            df, self.outputCol,
            lambda r: DenseVector((_to_array(r[self.inputCol]) - self.mean) / self.std),
        )
